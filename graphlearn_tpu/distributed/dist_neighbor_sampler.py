"""Distributed multi-hop neighbor sampling over a mesh-sharded graph.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_neighbor_sampler.py.
The reference's engine is an asyncio event loop per worker: per hop it splits
the frontier by partition book, samples the local part on its GPU, RPCs the
remote parts to their owners, and stitches results (dist_neighbor_sampler.py:
585-648), hiding RPC latency with concurrent seed batches.

Here the entire multi-hop sample is ONE jitted shard_map program over the
mesh axis 'g' (one graph partition per chip). Per hop, per shard:

  1. dest = node_pb[frontier]                       (replicated PB lookup)
  2. pack frontier into [P, C] buckets              (ops.route_slots/scatter)
  3. lax.all_to_all                                 (requests ride ICI)
  4. local fanout sample over the shard's CSR       (ops.uniform_sample_local)
  5. lax.all_to_all back                            (responses)
  6. unpermute into frontier order                  (ops.gather_from_buckets)
  7. dedup/relabel into the shard's batch           (ops.induce_next)

No asyncio, no RPC, no stitch kernels: the collectives are compiled into the
step and XLA overlaps them with compute. Every shard builds its own batch
from its own seed block — the SPMD equivalent of the reference's
one-batch-per-worker model.
"""
from typing import List, Optional

import numpy as np

from .. import ops
from ..sampler import NodeSamplerInput, SamplerOutput
from .dist_feature import DistFeature
from .dist_graph import DistGraph


class DistNeighborSampler:
  """Reference: dist_neighbor_sampler.py:95-744 (homogeneous path).

  Args:
    dist_graph: DistGraph (stacked sharded partitions + node_pb).
    num_neighbors: per-hop fanouts.
    mesh: jax Mesh with axis 'g' of size num_partitions.
    dist_feature: optional DistFeature for fused feature collection.
    with_edge: emit global edge ids.
    seed: PRNG seed.
  """

  def __init__(self, dist_graph: DistGraph, num_neighbors: List[int],
               mesh, dist_feature: Optional[DistFeature] = None,
               with_edge: bool = False, seed: Optional[int] = None,
               node_budget: Optional[int] = None,
               collect_features: bool = False):
    import jax
    self.graph = dist_graph
    self.num_neighbors = list(num_neighbors)
    self.mesh = mesh
    self.dist_feature = dist_feature
    self.with_edge = with_edge
    self.collect_features = collect_features and dist_feature is not None
    self.node_budget = node_budget
    self._key = jax.random.PRNGKey(0 if seed is None else seed)
    self._dev = dist_graph.device_arrays(mesh)
    self._fns = {}

  def _next_keys(self):
    import jax
    self._key, sub = jax.random.split(self._key)
    return jax.random.split(sub, self.graph.num_partitions)

  def _capacities(self, b: int):
    caps = [b]
    for k in self.num_neighbors:
      nxt = caps[-1] * k
      if self.node_budget is not None:
        nxt = min(nxt, self.node_budget)
      caps.append(nxt)
    return caps

  # ------------------------------------------------------------- build fn

  def _build_fn(self, b: int):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    nparts = self.graph.num_partitions
    fanouts = tuple(self.num_neighbors)
    caps = self._capacities(b)
    node_cap = sum(caps)
    with_edge = self.with_edge

    def exchange_hop(gdev, frontier, fmask, k, key):
      """One hop: route -> local sample -> route back. All [.] per-shard."""
      bf = frontier.shape[0]
      pb = gdev['node_pb']
      safe = jnp.maximum(frontier, 0)
      dest = jnp.where(fmask, pb[safe], nparts)
      slot, ok = ops.route_slots(dest, fmask, capacity=bf)
      send = ops.scatter_to_buckets(frontier, dest, slot, ok, nparts, bf)
      req = jax.lax.all_to_all(send, 'g', 0, 0)
      flat = req.reshape(-1)
      fm = flat >= 0
      nbrs, epos, m = ops.uniform_sample_local(
          gdev['row_ids'], gdev['indptr'], gdev['indices'], flat, fm, k,
          key)
      resp_n = jax.lax.all_to_all(nbrs.reshape(nparts, bf, k), 'g', 0, 0)
      resp_m = jax.lax.all_to_all(m.reshape(nparts, bf, k), 'g', 0, 0)
      back_n = ops.gather_from_buckets(resp_n, dest, slot, ok)
      back_m = ops.gather_from_buckets(resp_m, dest, slot, ok,
                                       fill=False) & ok[:, None]
      back_e = None
      if with_edge:
        e = jnp.where(m, gdev['eids'][jnp.where(m, epos, 0)], -1)
        resp_e = jax.lax.all_to_all(e.reshape(nparts, bf, k), 'g', 0, 0)
        back_e = ops.gather_from_buckets(resp_e, dest, slot, ok)
      return back_n, back_m, back_e

    def body(row_ids, indptr, indices, eids, pb, seeds, smask, keys):
      gdev = dict(row_ids=row_ids[0], indptr=indptr[0],
                  indices=indices[0], eids=eids[0], node_pb=pb)
      seeds, smask, key = seeds[0], smask[0], keys[0]
      hop_keys = jax.random.split(key, len(fanouts))
      state, uniq, umask, inv = ops.init_node(seeds, smask,
                                              capacity=node_cap)
      frontier, fidx, fmask = uniq, jnp.arange(b, dtype=jnp.int32), umask
      rows, cols, edges, emasks = [], [], [], []
      nodes_per_hop = [state.num_nodes]
      edges_per_hop = []
      for i, k in enumerate(fanouts):
        nbrs, m, e = exchange_hop(gdev, frontier, fmask, k, hop_keys[i])
        state, out = ops.induce_next(state, fidx, nbrs, m)
        rows.append(out['cols'])   # message direction: neighbor -> seed
        cols.append(out['rows'])
        emasks.append(out['edge_mask'])
        if with_edge:
          edges.append(jnp.where(out['edge_mask'], e.reshape(-1), -1))
        nodes_per_hop.append(out['num_new'])
        edges_per_hop.append(out['edge_mask'].sum())
        nxt = caps[i + 1]
        frontier = out['frontier'][:nxt]
        fidx = out['frontier_idx'][:nxt]
        fmask = out['frontier_mask'][:nxt]
      res = dict(
          node=state.nodes[None], num_nodes=state.num_nodes[None],
          row=jnp.concatenate(rows)[None],
          col=jnp.concatenate(cols)[None],
          edge_mask=jnp.concatenate(emasks)[None],
          seed_inverse=inv[None],
          num_sampled_nodes=jnp.stack(nodes_per_hop)[None],
          num_sampled_edges=jnp.stack(edges_per_hop)[None])
      if with_edge:
        res['edge'] = jnp.concatenate(edges)[None]
      return res

    out_specs = dict(node=P('g'), num_nodes=P('g'), row=P('g'),
                     col=P('g'), edge_mask=P('g'), seed_inverse=P('g'),
                     num_sampled_nodes=P('g'), num_sampled_edges=P('g'))
    if with_edge:
      out_specs['edge'] = P('g')
    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P('g'), P('g'), P('g'), P('g'), P(), P('g'), P('g'),
                  P('g')),
        out_specs=out_specs)
    jfn = jax.jit(fn)
    d = self._dev

    def run(seeds, smask, keys):
      return jfn(d['row_ids'], d['indptr'], d['indices'], d['eids'],
                 d['node_pb'], seeds, smask, keys)

    return run

  # ------------------------------------------------------------ public API

  def sample_from_nodes(self, inputs, seed_mask=None,
                        **kwargs) -> SamplerOutput:
    """Sample per-shard batches: seeds [P, B] (or [P*B] flat, split evenly).

    Returns a SamplerOutput whose arrays carry a leading partition axis
    [P, ...] — shard p is the batch built from seed block p, ready to feed
    a data-parallel train step on the same mesh. ``seed_mask`` (same shape
    as seeds) marks padding seeds False — they produce no nodes/edges and
    are excluded from num_nodes (used by DistLoader's final short batch).
    """
    import jax.numpy as jnp
    seeds = np.asarray(inputs.node if isinstance(inputs, NodeSamplerInput)
                       else inputs)
    p = self.graph.num_partitions
    if seeds.ndim == 1:
      assert seeds.shape[0] % p == 0, 'flat seeds must split evenly'
      seeds = seeds.reshape(p, -1)
    b = seeds.shape[1]
    smask = (np.ones_like(seeds, bool) if seed_mask is None
             else np.asarray(seed_mask).reshape(seeds.shape))
    if b not in self._fns:
      self._fns[b] = self._build_fn(b)
    res = self._fns[b](jnp.asarray(seeds, jnp.int32), jnp.asarray(smask),
                       self._next_keys())
    return SamplerOutput(
        node=res['node'], num_nodes=res['num_nodes'], row=res['row'],
        col=res['col'], edge=res.get('edge'), edge_mask=res['edge_mask'],
        batch=jnp.asarray(seeds), batch_size=b,
        num_sampled_nodes=res['num_sampled_nodes'],
        num_sampled_edges=res['num_sampled_edges'],
        metadata={'seed_inverse': res['seed_inverse'],
                  'seed_mask': jnp.asarray(smask)})

  def collate(self, out: SamplerOutput, node_labels=None):
    """Attach features (sharded all_to_all gather) and labels.

    Reference: _colloate_fn (dist_neighbor_sampler.py:650-744).
    """
    import jax.numpy as jnp
    x = None
    if self.collect_features:
      x = self.dist_feature.get(out.node)
    y = None
    if node_labels is not None:
      labels = jnp.asarray(node_labels)
      y = labels[jnp.maximum(out.node, 0)]
    return x, y
