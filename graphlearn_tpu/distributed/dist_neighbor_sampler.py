"""Distributed sampling over a mesh-sharded graph: node, link, subgraph.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_neighbor_sampler.py.
The reference's engine is an asyncio event loop per worker: per hop it splits
the frontier by partition book, samples the local part on its GPU, RPCs the
remote parts to their owners, and stitches results (dist_neighbor_sampler.py:
585-648), hiding RPC latency with concurrent seed batches.

Here the entire multi-hop sample is ONE jitted shard_map program over the
graph mesh — flat axis 'g' (one partition per chip) or the 2-axis
('slice', 'chip') multi-slice layout from init_multihost(mesh_shape=...).
Per hop, per shard:

  1. dest = node_pb[frontier]                       (replicated PB lookup)
  2. pack frontier into [P, C] buckets              (ops.route_slots/scatter)
  3. lax.all_to_all                                 (requests ride ICI)
  4. local fanout sample over the shard's CSR       (ops.uniform_sample_local
                                                     or weighted_sample_local)
  5. lax.all_to_all back                            (responses)
  6. unpermute into frontier order                  (ops.gather_from_buckets)
  7. dedup/relabel into the shard's batch           (ops.induce_next)

Exchange volume (round 3): buckets default to bucket_frac=2.0 x the mean
per-destination load instead of the full frontier width, with a psum'd
overflow count driving a replicated lax.cond fallback to the full-width
exchange — loss-free on every input, ~P/2 x fewer bytes on typical ones
(_exchange_hop). On a 2-axis mesh the exchange is HIERARCHICAL: a
full-width transpose along 'chip' (ICI) aggregates cross-slice traffic,
then a fractional transpose along 'slice' carries it over DCN
(_exchange_hop_hier) — S buckets of aggregated ids instead of P-C
full-width ones.

No asyncio, no RPC, no stitch kernels: the collectives are compiled into the
step and XLA overlaps them with compute. Every shard builds its own batch
from its own seed block — the SPMD equivalent of the reference's
one-batch-per-worker model.

Link sampling (reference _sample_from_edges, dist_neighbor_sampler.py:369-496)
and subgraph sampling (reference _subgraph, :499-559) are additional program
builders over the same hop engine: negatives are drawn shard-locally inside
the program (default non-strict like the reference's local-only distributed
negative sampling, :380-383; ``neg_strict=True`` upgrades validity to
guaranteed non-edges using the engine's edges-live-with-their-source
invariant), and the induced-subgraph edge extraction is an
all_gather of the node set + per-shard local extraction + all_to_all of the
results — the collective analog of the reference's subgraph RPC fan-out.
"""
from typing import Dict, List, Optional, Union

import numpy as np

from .. import ops
from ..sampler import (EdgeSamplerInput, HeteroSamplerOutput,
                       NodeSamplerInput, SamplerOutput)
from ..typing import reverse_edge_type
from .dist_feature import DistFeature
from .dist_graph import DistGraph, DistHeteroGraph


# canonical home is ops.route (shared with the feature-store miss
# exchange); re-exported here because benchmarks/tests import them from
# this module
from ..ops.route import exchange_capacity, round8 as _round8  # noqa: E402,F401


def _local_sample(garr, flat, fm, k, key, weighted: bool):
  """Shared shard-local fanout sample over this shard's stacked CSR."""
  if weighted:
    return ops.weighted_sample_local(
        garr['row_ids'], garr['indptr'], garr['indices'], garr['wcum'],
        flat, fm, k, key)
  return ops.uniform_sample_local(
      garr['row_ids'], garr['indptr'], garr['indices'], flat, fm, k, key)


def _exchange_hop_hier(garr, pb, frontier, fmask, k, key, sizes,
                       with_edge: bool, weighted: bool, bucket_frac,
                       axes):
  """Hierarchical 2-stage exchange for a (slice, chip) mesh.

  Stage 1 transposes along 'chip' at FULL frontier width — intra-slice
  traffic rides ICI, where the loss-free full-width posture is cheap.
  Stage 2 buckets the aggregated per-chip-column ids by destination
  slice — the DCN hop carries S aggregated buckets instead of (P-C)
  per-chip-pair ones. Stage-2 capacity is sized on the MEAN VALID load,
  not the slot count: after stage 1 each chip holds C peers' buckets of
  ~bf/C valid ids each, i.e. ~bf valid ids spread over C*bf slots, so a
  per-slice bucket needs ~bf/S slots (x bucket_frac slack). Sizing on
  slots (the round-3 posture, C*bf*frac/S) shipped C x more DCN bytes
  than the valid load requires. Overflow (psum over both axes,
  replicated) falls back to the flat full-width exchange — loss-free on
  every input. Responses retrace both transposes.
  """
  import jax
  import jax.numpy as jnp
  s_ax, c_ax = axes
  s_sz, c_sz = sizes
  nparts = s_sz * c_sz
  bf = frontier.shape[0]
  safe = jnp.maximum(frontier, 0)
  dest = jnp.where(fmask, pb[safe], nparts)
  c_dst = jnp.where(fmask, dest % c_sz, c_sz)
  slot1, ok1 = ops.route_slots(c_dst, fmask, capacity=bf)
  send1 = ops.scatter_to_buckets(frontier, c_dst, slot1, ok1, c_sz, bf)
  req1 = jax.lax.all_to_all(send1, c_ax, 0, 0)       # [C, bf] via ICI
  mid = req1.reshape(-1)
  mid_mask = mid >= 0
  mdest = jnp.where(mid_mask, pb[jnp.maximum(mid, 0)] // c_sz, s_sz)
  slot2, ok2f = ops.route_slots(mdest, mid_mask, capacity=c_sz * bf)
  if bucket_frac is None or s_sz <= 1:
    cap2 = c_sz * bf
  else:
    # graftlint: allow[host-sync] trace-time shape arithmetic — bf is a static Python int (frontier.shape[0]), never a traced value
    cap2 = min(c_sz * bf, _round8(int(bucket_frac * bf / s_sz)))

  def hier_path(_):
    ok2 = ok2f & (slot2 < cap2)
    send2 = ops.scatter_to_buckets(mid, mdest, slot2, ok2, s_sz, cap2)
    req2 = jax.lax.all_to_all(send2, s_ax, 0, 0)     # [S, cap2] via DCN
    flat = req2.reshape(-1)
    nbrs, epos, m = _local_sample(garr, flat, flat >= 0, k, key,
                                  weighted)
    def back(vals, fill, dtype=None):
      r2 = jax.lax.all_to_all(vals.reshape(s_sz, cap2, k), s_ax, 0, 0)
      b2 = ops.gather_from_buckets(r2, mdest, slot2, ok2, fill=fill)
      r1 = jax.lax.all_to_all(b2.reshape(c_sz, bf, k), c_ax, 0, 0)
      return ops.gather_from_buckets(r1, c_dst, slot1, ok1, fill=fill)
    back_n = back(nbrs, ops.FILL)
    back_m = back(m, False) & ok1[:, None]
    if with_edge:
      e = jnp.where(m, garr['eids'][jnp.where(m, epos, 0)], -1)
      back_e = back(e, ops.FILL)
    else:
      back_e = jnp.zeros((bf, k), jnp.int32)
    return back_n, back_m, back_e

  def flat_path(_):
    slotp, okp = ops.route_slots(dest, fmask, capacity=bf)
    send = ops.scatter_to_buckets(frontier, dest, slotp, okp, nparts, bf)
    req = jax.lax.all_to_all(send, axes, 0, 0)
    flat = req.reshape(-1)
    nbrs, epos, m = _local_sample(garr, flat, flat >= 0, k, key,
                                  weighted)
    resp_n = jax.lax.all_to_all(nbrs.reshape(nparts, bf, k), axes, 0, 0)
    resp_m = jax.lax.all_to_all(m.reshape(nparts, bf, k), axes, 0, 0)
    back_n = ops.gather_from_buckets(resp_n, dest, slotp, okp)
    back_m = ops.gather_from_buckets(resp_m, dest, slotp, okp,
                                     fill=False) & okp[:, None]
    if with_edge:
      e = jnp.where(m, garr['eids'][jnp.where(m, epos, 0)], -1)
      resp_e = jax.lax.all_to_all(e.reshape(nparts, bf, k), axes, 0, 0)
      back_e = ops.gather_from_buckets(resp_e, dest, slotp, okp)
    else:
      back_e = jnp.zeros((bf, k), jnp.int32)
    return back_n, back_m, back_e

  if cap2 >= c_sz * bf:
    back_n, back_m, back_e = hier_path(None)
  else:
    ovf = jnp.sum(mid_mask & (slot2 >= cap2)).astype(jnp.int32)
    total_ovf = jax.lax.psum(ovf, axes)
    back_n, back_m, back_e = jax.lax.cond(total_ovf == 0, hier_path,
                                          flat_path, None)
  if not with_edge:
    back_e = None
  return back_n, back_m, back_e


def _exchange_hop(garr, pb, frontier, fmask, k, key, nparts: int,
                  with_edge: bool, weighted: bool = False,
                  bucket_frac=2.0, axes=('g',), axis_sizes=None):
  """One cross-shard hop, shared by the homo and hetero engines:
  route frontier ids by partition book -> all_to_all request ->
  local fanout sample over this shard's CSR -> all_to_all response ->
  unpermute into frontier order.

  Runs inside shard_map; all values are per-shard. ``garr`` holds the
  shard's stacked local CSR (row_ids/indptr/indices/eids, plus wcum when
  ``weighted``).

  Bucket capacity: with ``bucket_frac=None`` every bucket is sized to
  the full frontier width, so routing can NEVER overflow (loss-free by
  construction, at nparts x the necessary all_to_all bytes — the round-2
  posture). With a fraction ``alpha`` (default 2.0 = 2x the mean load),
  buckets are ``alpha * frontier / nparts`` wide and the hop ships
  ~alpha x the necessary bytes; a psum'd overflow count drives a
  REPLICATED lax.cond that falls back to the full-width exchange on the
  rare batch whose per-destination skew exceeds the slack — still
  loss-free on every input, sub-linear volume growth in nparts on
  typical ones (reference parity: exact split, never drops,
  dist_neighbor_sampler.py:585-648).
  """
  import jax
  import jax.numpy as jnp
  if len(axes) == 2:
    assert axis_sizes is not None and len(axis_sizes) == 2
    return _exchange_hop_hier(garr, pb, frontier, fmask, k, key,
                              axis_sizes, with_edge, weighted,
                              bucket_frac, axes)
  bf = frontier.shape[0]
  safe = jnp.maximum(frontier, 0)
  dest = jnp.where(fmask, pb[safe], nparts)
  slot, ok = ops.route_slots(dest, fmask, capacity=bf)

  def _do(cap: int):
    okc = ok & (slot < cap)
    send = ops.scatter_to_buckets(frontier, dest, slot, okc, nparts, cap)
    req = jax.lax.all_to_all(send, axes, 0, 0)
    flat = req.reshape(-1)
    fm = flat >= 0
    if weighted:
      nbrs, epos, m = ops.weighted_sample_local(
          garr['row_ids'], garr['indptr'], garr['indices'], garr['wcum'],
          flat, fm, k, key)
    else:
      nbrs, epos, m = ops.uniform_sample_local(
          garr['row_ids'], garr['indptr'], garr['indices'], flat, fm, k,
          key)
    resp_n = jax.lax.all_to_all(nbrs.reshape(nparts, cap, k), axes, 0, 0)
    resp_m = jax.lax.all_to_all(m.reshape(nparts, cap, k), axes, 0, 0)
    back_n = ops.gather_from_buckets(resp_n, dest, slot, okc)
    back_m = ops.gather_from_buckets(resp_m, dest, slot, okc,
                                     fill=False) & okc[:, None]
    if with_edge:
      e = jnp.where(m, garr['eids'][jnp.where(m, epos, 0)], -1)
      resp_e = jax.lax.all_to_all(e.reshape(nparts, cap, k), axes, 0, 0)
      back_e = ops.gather_from_buckets(resp_e, dest, slot, okc)
    else:
      back_e = jnp.zeros((bf, k), jnp.int32)   # uniform cond signature
    return back_n, back_m, back_e

  cap_small = exchange_capacity(bf, nparts, bucket_frac)
  if cap_small >= bf:
    back_n, back_m, back_e = _do(bf)
  else:
    # replicated decision: every shard sees the SAME total overflow, so
    # the collectives inside each branch stay uniform across the mesh
    ovf = jnp.sum(fmask & (slot >= cap_small)).astype(jnp.int32)
    total_ovf = jax.lax.psum(ovf, axes)
    back_n, back_m, back_e = jax.lax.cond(
        total_ovf == 0, lambda _: _do(cap_small), lambda _: _do(bf),
        None)
  if not with_edge:
    back_e = None
  return back_n, back_m, back_e


def _homo_hop_loop(gdev, pb, seeds, smask, key, fanouts, caps,
                   node_cap: int, nparts: int, with_edge: bool,
                   weighted: bool, dedup: str = 'sort',
                   bucket_frac=2.0, axes=('g',), axis_sizes=None):
  """Multi-hop homo engine body (traced inside shard_map): dedup seeds,
  expand hop by hop via _exchange_hop + the chosen inducer. Returns the
  per-shard result dict (no leading axis).

  ``dedup='tree'`` uses the positional computation-tree inducer
  (ops/induce_tree.py) — zero random access, ~4x device speedup over the
  exact-dedup inducers at products scale (PERF.md); 'sort' keeps exact
  dedup (the shard-local analog of the reference's inducer).
  """
  import jax
  import jax.numpy as jnp
  b = seeds.shape[0]
  hop_keys = jax.random.split(key, max(1, len(fanouts)))
  from ..sampler.neighbor_sampler import _inducer_for
  init_seed, _, induce = _inducer_for(dedup)
  state, uniq, umask, inv = init_seed(seeds, smask, capacity=node_cap)
  frontier, fidx, fmask = uniq, jnp.arange(b, dtype=jnp.int32), umask
  rows, cols, edges, emasks = [], [], [], []
  nodes_per_hop = [state.num_nodes]
  edges_per_hop = []
  # on-device truncation flag for clamped exact plans (calibrated
  # frontier_caps): psum'd below so every shard reports the SAME verdict
  overflow = jnp.zeros((), bool)
  from ..sampler.neighbor_sampler import (merge_layout_from_caps,
                                          tree_layout_from_caps)
  if dedup == 'tree':
    node_offs, _ = tree_layout_from_caps(caps, fanouts)
  else:
    # merge engine: clamped occupancy bound (see _fused_homo_fn)
    node_offs, _ = merge_layout_from_caps(caps, fanouts)
  for i, k in enumerate(fanouts):
    nbrs, m, e = _exchange_hop(gdev, pb, frontier, fmask, k,
                               hop_keys[i], nparts, with_edge, weighted,
                               bucket_frac=bucket_frac, axes=axes,
                               axis_sizes=axis_sizes)
    state, out = induce(state, fidx, nbrs, m, node_offs[i],
                        final=(i + 1 == len(fanouts)),
                        max_new=caps[i + 1])
    rows.append(out['cols'])   # message direction: neighbor -> seed
    cols.append(out['rows'])
    emasks.append(out['edge_mask'])
    if with_edge:
      edges.append(jnp.where(out['edge_mask'], e.reshape(-1), -1))
    nodes_per_hop.append(out['num_new'])
    edges_per_hop.append(out['edge_mask'].sum())
    if dedup == 'merge' and caps[i + 1] < caps[i] * k:
      overflow = overflow | (out['num_new'] > caps[i + 1])
    nxt = caps[i + 1]
    frontier = out['frontier'][:nxt]
    fidx = out['frontier_idx'][:nxt]
    fmask = out['frontier_mask'][:nxt]
  if any(dedup == 'merge' and caps[i + 1] < caps[i] * k
         for i, k in enumerate(fanouts)):
    # replicated verdict: ANY shard's truncation taints the step
    overflow = jax.lax.psum(overflow.astype(jnp.int32), axes) > 0
  if not fanouts:
    rows = [jnp.zeros((0,), jnp.int32)]
    cols = [jnp.zeros((0,), jnp.int32)]
    emasks = [jnp.zeros((0,), bool)]
    edges_per_hop = [jnp.asarray(0, jnp.int32)]
    if with_edge:
      edges = [jnp.zeros((0,), jnp.int64)]
  res = dict(
      node=state.nodes, num_nodes=state.num_nodes,
      row=jnp.concatenate(rows),
      col=jnp.concatenate(cols),
      edge_mask=jnp.concatenate(emasks),
      seed_inverse=inv,
      num_sampled_nodes=jnp.stack(nodes_per_hop),
      num_sampled_edges=jnp.stack(edges_per_hop),
      overflow=overflow)
  if with_edge:
    res['edge'] = jnp.concatenate(edges)
  return res


def _lift(res):
  """Add the per-shard leading axis shard_map's P('g') out_specs expect."""
  import jax
  return jax.tree.map(lambda x: x[None], res)


class DistNeighborSampler:
  """Reference: dist_neighbor_sampler.py:95-744.

  Args:
    dist_graph: DistGraph (stacked sharded partitions + node_pb).
    num_neighbors: per-hop fanouts (None for pure induced subgraphs).
    mesh: jax Mesh with axis 'g' of size num_partitions.
    dist_feature: optional DistFeature for fused feature collection.
    with_edge: emit global edge ids.
    with_weight: edge-weight-biased sampling (works in the sharded engine;
      the reference GPU path falls back to uniform here,
      sampler/neighbor_sampler.py:86-91).
    seed: PRNG seed.
  """

  def __init__(self, dist_graph: Union[DistGraph, DistHeteroGraph],
               num_neighbors, mesh,
               dist_feature: Optional[DistFeature] = None,
               with_edge: bool = False, seed: Optional[int] = None,
               node_budget: Optional[int] = None,
               collect_features: bool = False,
               with_weight: bool = False, dedup: str = 'sort',
               bucket_frac=2.0, neg_strict: bool = False,
               frontier_caps=None):
    import jax
    self.graph = dist_graph
    self.is_hetero = dist_graph.is_hetero
    if num_neighbors is None:
      self.num_neighbors = []
    else:
      self.num_neighbors = (dict(num_neighbors)
                            if isinstance(num_neighbors, dict)
                            else list(num_neighbors))
    self.mesh = mesh
    self.dist_feature = dist_feature
    self.with_edge = with_edge
    self.with_weight = with_weight
    self.collect_features = collect_features and dist_feature is not None
    self.node_budget = node_budget
    # per-hop exchange bucket capacity = bucket_frac * frontier / nparts
    # with a replicated full-width fallback on overflow (see
    # _exchange_hop); None = always full width (round-2 posture)
    self.bucket_frac = bucket_frac
    # neg_strict=True: distributed negatives whose validity GUARANTEES
    # non-edge pairs (the engine's edges-live-with-their-source
    # invariant makes the shard-local membership check complete —
    # ops.random_negative_sample_local); False = reference parity
    # (always-full output, rare slip-through).
    self.neg_strict = neg_strict
    # 'sort'/'map'/'merge' = exact dedup (all run the merge-sort engine,
    # ops/induce_merge.py — batch-sized memory, so it shards cleanly);
    # 'tree' ('none' aliases it) = positional computation-tree batches
    # with a zero-random-access inducer (PERF.md).
    dedup = 'tree' if dedup == 'none' else dedup
    if dedup in ('sort', 'map', 'merge'):
      dedup = 'merge'
    elif dedup != 'tree':
      raise ValueError(f'unknown dedup mode {dedup!r}; the distributed '
                       "engine supports 'sort'/'map'/'merge' (exact) and "
                       "'tree'")
    self.dedup = dedup
    # frontier_caps: per-hop post-dedup frontier capacity clamps — the
    # calibrated-capacity mechanism, now on the distributed engine too.
    # Every per-shard buffer (exchange frontier, inducer append block,
    # node buffer, collate gather) shrinks from the worst-case
    # ``caps[i]*k`` to the calibrated bound; overflow is tracked
    # ON DEVICE per batch (psum'd, replicated) and surfaced through
    # metadata['overflow'] so DistLoader's overflow_policy can raise or
    # replay at full capacities (see sampler/calibrate.py; reference
    # parity target: exact semantics at sub-worst-case cost, the
    # dynamic-shape posture of dist_neighbor_sampler.py:585-648).
    if frontier_caps is not None:
      if isinstance(frontier_caps, str):
        raise ValueError(
            f'frontier_caps={frontier_caps!r}: the distributed engine '
            'takes explicit caps — calibrate on the host CSR with '
            'sampler.calibrate.estimate_frontier_caps (homo list; '
            'batch_size = the PER-SHARD seed width) or '
            'estimate_hetero_frontier_caps (hetero dict); '
            "'auto' exists on the local loaders only")
      if self.dedup == 'tree':
        raise ValueError('frontier_caps requires an exact-dedup mode '
                         "('sort'/'map'/'merge'); tree frontiers are "
                         'positional, use node_budget there')
    if frontier_caps is None:
      self.frontier_caps = None
    elif self.is_hetero:
      from ..sampler.calibrate import normalize_hetero_frontier_caps
      self.frontier_caps = normalize_hetero_frontier_caps(
          frontier_caps, dist_graph.etypes)
    else:
      if isinstance(frontier_caps, dict):
        raise ValueError('dict-form frontier_caps is hetero-only; pass '
                         'a per-hop list on homogeneous graphs')
      self.frontier_caps = tuple(frontier_caps)
    self._key = jax.random.PRNGKey(0 if seed is None else seed)
    # host-side PRNG stream position: step keys are
    # split(fold_in(self._key, count), P) with count starting at 1
    # (see _keys_for) — replayable by counter, matching the local
    # sampler's discipline so scanned epochs can fold the counter into
    # the scan carry
    self._call_count = 0
    # every-axis collectives: ('g',) on the flat mesh, or
    # ('slice', 'chip') on a 2-axis multi-slice mesh (init_multihost
    # mesh_shape) — specs/collectives below use the tuple uniformly
    self._axes = tuple(mesh.axis_names)
    self._axis_sizes = tuple(mesh.shape[a] for a in self._axes)
    self._dev = dist_graph.device_arrays(mesh)
    if with_weight:
      self._attach_wcum()
    self._fns = {}

  def _attach_wcum(self):
    """Upload the per-shard weighted-sampling CDF tables."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
    if self.is_hetero:
      for et, g in self.graph.sub.items():
        if g.weights is not None:
          self._dev[et]['wcum'] = jax.device_put(g.row_cumsum_stacked(),
                                                 shard)
    else:
      self._dev['wcum'] = jax.device_put(self.graph.row_cumsum_stacked(),
                                         shard)

  def _weighted_for(self, etype=None) -> bool:
    if not self.with_weight:
      return False
    if self.is_hetero:
      return 'wcum' in self._dev[etype]
    return 'wcum' in self._dev

  def _sorted_loc_dev(self, etype=None):
    """Lazily uploaded [P, E] segment-sorted local indices (negative
    sampling membership table)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    key = ('#sorted', etype)
    if key not in self._dev:
      g = self.graph.sub[etype] if etype is not None else self.graph
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      self._dev[key] = jax.device_put(g.sorted_local_indices(), shard)
    return self._dev[key]

  def _keys_for(self, count):
    """Per-shard keys for PRNG-stream position ``count``:
    split(fold_in(base_key, count), P). Counter-addressed (not
    split-and-carry) so the scanned-epoch program (loader/scan_epoch.py
    DistScanTrainer) can replay the exact per-step stream from a carried
    step counter — count may be a host int or a traced scalar."""
    import jax
    sub = jax.random.fold_in(self._key, count)
    return jax.random.split(sub, self.graph.num_partitions)

  def _next_keys(self):
    self._call_count += 1
    return self._keys_for(self._call_count)

  def state_dict(self):
    """fold_in counter PRNG: base key + stream position."""
    return {'key': np.asarray(self._key).tolist(),
            'call_count': self._call_count}

  def load_state_dict(self, state):
    import jax.numpy as jnp
    if 'key' not in state:
      raise ValueError(
          f'checkpoint sampler state {sorted(state)} was written by a '
          'different sampler type; resuming would diverge')
    self._key = jnp.asarray(np.asarray(state['key'], np.uint32))
    # pre-fold_in checkpoints carry no counter; resume at stream start
    self._call_count = int(state.get('call_count', 0))

  def _capacities(self, b: int, with_frontier_caps: bool = True):
    """Per-hop frontier capacity plan (single-chip capacity_plan with the
    node_budget and calibrated frontier_caps clamps). The subgraph
    builder passes ``with_frontier_caps=False``: its legacy inducer has
    no clean-truncation contract, so calibration must not clamp it."""
    from ..sampler.neighbor_sampler import capacity_plan
    return capacity_plan(
        b, list(self.num_neighbors), self.node_budget,
        self.frontier_caps if with_frontier_caps else None)

  def hop_caps(self, batch_cap: int) -> List[int]:
    """Resolved per-hop frontier capacities (per shard) — the
    distributed counterpart of NeighborSampler.hop_caps, consumed by
    calibrate.check_no_overflow."""
    return self._capacities(batch_cap)

  @property
  def clamped_exact(self) -> bool:
    """True when the engine runs exact dedup under calibrated
    frontier_caps — results then carry a replicated on-device
    metadata['overflow'] flag (see DistLoader overflow_policy)."""
    return self.frontier_caps is not None and self.dedup == 'merge'

  def uncapped_clone(self) -> 'DistNeighborSampler':
    """Sampler sharing this one's device arrays / mesh / PRNG base but
    with NO frontier_caps — the full-capacity replay target for
    overflow recovery."""
    import copy
    clone = copy.copy(self)
    clone.frontier_caps = None
    clone._fns = {}
    return clone

  def _node_cap(self, caps) -> int:
    if self.dedup == 'tree':
      from ..sampler.neighbor_sampler import tree_layout_from_caps
      return tree_layout_from_caps(caps, self.num_neighbors)[0][-1]
    return sum(caps)

  # ----------------------------------------------------- hetero static plan

  def _etype_fanouts(self, et) -> List[int]:
    nn = self.num_neighbors
    return list(nn[et]) if isinstance(nn, dict) else list(nn)

  def _hetero_plan(self, seed_widths: Dict):
    """Static per-hop capacity schedule (mirror of the single-machine
    sampler's plan, sampler/neighbor_sampler.py hetero path), generalized
    to multi-type seed sets (link sampling seeds both endpoint types).
    Dict-form frontier_caps clamp each (hop, etype)'s new-node
    contribution exactly like the local plan's etype_caps — hop entries
    become ``(fcap, k, cap)`` with cap == fcap*k when unclamped."""
    g = self.graph
    etype_caps = self.frontier_caps if self.is_hetero else None
    # canonical intra-hop order (see hetero_capacity_plan): the layout
    # helpers sort, so the engine's plan must sort identically
    etypes = sorted(tuple(et) for et in g.etypes)
    edge_dir = g.edge_dir
    num_hops = max(len(self._etype_fanouts(et)) for et in etypes)
    ntypes = g.ntypes
    frontier_cap = {t: 0 for t in ntypes}
    for t, w in seed_widths.items():
      frontier_cap[t] = w
    node_caps = dict(frontier_cap)
    hop_caps = []
    for hop in range(num_hops):
      adds = {t: 0 for t in ntypes}
      per_et = {}
      for et in etypes:
        fo = self._etype_fanouts(et)
        if hop >= len(fo) or fo[hop] == 0:
          continue
        key_t = et[0] if edge_dir == 'out' else et[2]
        res_t = et[2] if edge_dir == 'out' else et[0]
        fcap = frontier_cap.get(key_t, 0)
        if fcap == 0:
          continue
        if self.node_budget is not None:
          fcap = min(fcap, self.node_budget)
        from ..sampler.calibrate import clamp_etype_cap
        cap = clamp_etype_cap(etype_caps, et, hop, fcap * fo[hop])
        per_et[et] = (fcap, fo[hop], cap)
        adds[res_t] += cap
      hop_caps.append(per_et)
      for t in ntypes:
        frontier_cap[t] = adds[t]
        node_caps[t] += adds[t]
    return num_hops, hop_caps, node_caps

  # ------------------------------------------------------------- build fn

  def _build_fn(self, b: int):
    import jax
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    nparts = self.graph.num_partitions
    fanouts = tuple(self.num_neighbors)
    caps = self._capacities(b)
    node_cap = self._node_cap(caps)
    dedup = self.dedup
    with_edge = self.with_edge
    weighted = self._weighted_for()
    bucket_frac = self.bucket_frac
    ax = self._axes
    sizes = self._axis_sizes

    def body(row_ids, indptr, indices, eids, wcum, pb, seeds, smask, keys):
      gdev = dict(row_ids=row_ids[0], indptr=indptr[0],
                  indices=indices[0], eids=eids[0])
      if weighted:
        gdev['wcum'] = wcum[0]
      res = _homo_hop_loop(gdev, pb, seeds[0], smask[0], keys[0], fanouts,
                           caps, node_cap, nparts, with_edge, weighted,
                           dedup=dedup, bucket_frac=bucket_frac, axes=ax,
                           axis_sizes=sizes)
      return _lift(res)

    out_specs = dict(node=P(ax), num_nodes=P(ax), row=P(ax),
                     col=P(ax), edge_mask=P(ax), seed_inverse=P(ax),
                     num_sampled_nodes=P(ax), num_sampled_edges=P(ax),
                     overflow=P(ax))
    if with_edge:
      out_specs['edge'] = P(ax)
    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P(ax), P(ax), P(ax), P(ax), P(ax), P(), P(ax),
                  P(ax), P(ax)),
        out_specs=out_specs)
    jfn = jax.jit(fn)
    d = self._dev

    def run(seeds, smask, keys):
      return jfn(d['row_ids'], d['indptr'], d['indices'], d['eids'],
                 d.get('wcum', d['eids']), d['node_pb'], seeds, smask,
                 keys)

    return run

  # ----------------------------------------------------------- link build

  def _build_link_fn(self, b: int, num_neg: int, mode: str):
    """Distributed sample_from_edges program (reference:
    dist_neighbor_sampler.py:369-496 homo branch): shard-local negatives
    + seed union + multi-hop engine + label-index metadata, all inside
    one SPMD program."""
    import jax
    import jax.numpy as jnp
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    nparts = self.graph.num_partitions
    fanouts = tuple(self.num_neighbors)
    with_edge = self.with_edge
    weighted = self._weighted_for()
    edge_dir = self.graph.edge_dir
    num_nodes = self.graph.num_nodes
    bucket_frac = self.bucket_frac
    neg_strict = self.neg_strict
    ax = self._axes
    sizes = self._axis_sizes
    if mode == 'none':
      width = 2 * b
    elif mode == 'binary':
      width = 2 * b + 2 * num_neg
    else:  # triplet
      width = 2 * b + num_neg
    caps = self._capacities(width)
    node_cap = self._node_cap(caps)
    dedup = self.dedup

    def body(row_ids, indptr, indices, eids, wcum, sorted_loc, pb,
             rows, cols, smask, keys):
      gdev = dict(row_ids=row_ids[0], indptr=indptr[0],
                  indices=indices[0], eids=eids[0])
      if weighted:
        gdev['wcum'] = wcum[0]
      rows_, cols_, sm, key = rows[0], cols[0], smask[0], keys[0]
      kneg, kloop = jax.random.split(key)
      if mode == 'none':
        seeds = jnp.concatenate([rows_, cols_])
        seed_mask = jnp.concatenate([sm, sm])
      else:
        nr, nc, nvalid = ops.random_negative_sample_local(
            gdev['row_ids'], gdev['indptr'], sorted_loc[0], num_nodes,
            num_neg, kneg, strict=neg_strict)
        # CSR key side vs user-facing (src, dst): flip for CSC ('in')
        neg_src, neg_dst = (nr, nc) if edge_dir == 'out' else (nc, nr)
        if mode == 'binary':
          seeds = jnp.concatenate([rows_, cols_, neg_src, neg_dst])
          seed_mask = jnp.concatenate([sm, sm, nvalid, nvalid])
        else:
          seeds = jnp.concatenate([rows_, cols_, neg_dst])
          seed_mask = jnp.concatenate([sm, sm, nvalid])
      res = _homo_hop_loop(gdev, pb, seeds, seed_mask, kloop, fanouts,
                           caps, node_cap, nparts, with_edge, weighted,
                           dedup=dedup, bucket_frac=bucket_frac,
                           axes=ax, axis_sizes=sizes)
      inv = res['seed_inverse']
      if mode == 'none':
        res['edge_label_index'] = jnp.stack([inv[:b], inv[b:2 * b]])
      elif mode == 'binary':
        src = jnp.concatenate([inv[:b], inv[2 * b:2 * b + num_neg]])
        dst = jnp.concatenate([inv[b:2 * b],
                               inv[2 * b + num_neg:2 * b + 2 * num_neg]])
        res['edge_label_index'] = jnp.stack([src, dst])
      else:
        res['src_index'] = inv[:b]
        res['dst_pos_index'] = inv[b:2 * b]
        res['dst_neg_index'] = inv[2 * b:2 * b + num_neg]
      return _lift(res)

    out_keys = ['node', 'num_nodes', 'row', 'col', 'edge_mask',
                'seed_inverse', 'num_sampled_nodes', 'num_sampled_edges',
                'overflow']
    if with_edge:
      out_keys.append('edge')
    if mode in ('none', 'binary'):
      out_keys.append('edge_label_index')
    else:
      out_keys += ['src_index', 'dst_pos_index', 'dst_neg_index']
    out_specs = {k: P(ax) for k in out_keys}
    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P(ax),) * 6 + (P(),) + (P(ax),) * 4,
        out_specs=out_specs)
    jfn = jax.jit(fn)
    d = self._dev

    def run(rows, cols, smask, keys):
      sorted_loc = (self._sorted_loc_dev() if mode != 'none'
                    else d['eids'])
      return jfn(d['row_ids'], d['indptr'], d['indices'], d['eids'],
                 d.get('wcum', d['eids']), sorted_loc, d['node_pb'],
                 rows, cols, smask, keys)

    return run

  # ------------------------------------------------------- subgraph build

  def _build_subgraph_fn(self, b: int, max_degree: int):
    """Distributed induced-subgraph program (reference: _subgraph,
    dist_neighbor_sampler.py:499-559): optional hop expansion, then
    all_gather the node set, extract local induced edges per shard, and
    all_to_all the relabeled results back to the owning shard."""
    import jax
    import jax.numpy as jnp
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    nparts = self.graph.num_partitions
    fanouts = tuple(self.num_neighbors)
    ax = self._axes
    # legacy inducer: no clean-truncation contract — never clamp it
    # with calibrated caps
    caps = self._capacities(b, with_frontier_caps=False)
    node_cap = sum(caps)
    with_edge = self.with_edge
    weighted = self._weighted_for()

    def body(row_ids, indptr, indices, eids, pb, seeds, smask, keys):
      gdev = dict(row_ids=row_ids[0], indptr=indptr[0],
                  indices=indices[0], eids=eids[0])
      seeds_, sm, key = seeds[0], smask[0], keys[0]
      node_buf, nvalid = seeds_, sm
      if fanouts:
        hop_keys = jax.random.split(key, len(fanouts))
        state, uniq, umask, _ = ops.init_node(seeds_, sm,
                                              capacity=node_cap)
        frontier = uniq
        fidx = jnp.arange(b, dtype=jnp.int32)
        fmask = umask
        for i, k in enumerate(fanouts):
          nbrs, m, _ = _exchange_hop(gdev, pb, frontier, fmask, k,
                                     hop_keys[i], nparts, False, weighted,
                                     bucket_frac=self.bucket_frac,
                                     axes=ax,
                                     axis_sizes=self._axis_sizes)
          state, out = ops.induce_next(state, fidx, nbrs, m)
          nxt = caps[i + 1]
          frontier = out['frontier'][:nxt]
          fidx = out['frontier_idx'][:nxt]
          fmask = out['frontier_mask'][:nxt]
        node_buf = state.nodes
        nvalid = jnp.arange(node_cap) < state.num_nodes
      nodes, num_nodes, _ = ops.masked_unique(node_buf, nvalid,
                                              size=node_cap)
      big = jnp.iinfo(nodes.dtype).max
      nkeys = jnp.where(jnp.arange(node_cap) < num_nodes, nodes, big)
      all_keys = jax.lax.all_gather(nkeys, ax)            # [P, cap]
      sub = jax.vmap(lambda nk: ops.node_subgraph_local(
          gdev['row_ids'], gdev['indptr'], gdev['indices'], nk,
          max_degree))(all_keys)
      r = jax.lax.all_to_all(sub['rows'], ax, 0, 0).reshape(-1)
      c = jax.lax.all_to_all(sub['cols'], ax, 0, 0).reshape(-1)
      em = jax.lax.all_to_all(sub['edge_mask'], ax, 0, 0).reshape(-1)
      res = dict(node=nodes, num_nodes=num_nodes, row=r, col=c,
                 edge_mask=em,
                 num_edges=em.sum().astype(jnp.int32))
      if with_edge:
        e = jnp.where(sub['edge_mask'],
                      gdev['eids'][sub['epos']], -1)
        res['edge'] = jax.lax.all_to_all(e, ax, 0, 0).reshape(-1)
      # seed positions in the deduped node set
      spos = jnp.clip(jnp.searchsorted(nkeys, seeds_), 0, node_cap - 1)
      res['mapping'] = jnp.where(sm & (nkeys[spos] == seeds_),
                                 spos.astype(jnp.int32), -1)
      return _lift(res)

    out_keys = ['node', 'num_nodes', 'row', 'col', 'edge_mask',
                'num_edges', 'mapping']
    if with_edge:
      out_keys.append('edge')
    out_specs = {k: P(ax) for k in out_keys}
    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P(ax),) * 4 + (P(),) + (P(ax),) * 3,
        out_specs=out_specs)
    jfn = jax.jit(fn)
    d = self._dev

    def run(seeds, smask, keys):
      return jfn(d['row_ids'], d['indptr'], d['indices'], d['eids'],
                 d['node_pb'], seeds, smask, keys)

    return run

  # ------------------------------------------------------- hetero engine

  def _hetero_engine(self, garr, pbs, seed_arrays, key, plan):
    """Typed multi-hop engine body (traced inside shard_map): per-hop,
    per-edge-type route -> all_to_all -> local sample -> all_to_all back
    -> per-node-type induce.

    Reference: dist_neighbor_sampler.py:287-319 (hetero hop fan-out via
    asyncio tasks per etype + RPC); here each etype's exchange is a pair
    of collectives inside ONE jitted SPMD program.

    Args:
      seed_arrays: ordered {ntype: (seeds [w], mask [w])} traced arrays.
      plan: (num_hops, hop_caps, node_caps) from _hetero_plan.

    Returns (res dict — per-shard, unwrapped — and inv_dict per seed
    ntype).
    """
    import jax
    import jax.numpy as jnp
    g = self.graph
    nparts = g.num_partitions
    etypes = list(g.etypes)
    ntypes = list(g.ntypes)
    edge_dir = g.edge_dir
    with_edge = self.with_edge
    num_hops, hop_caps, node_caps = plan
    out_et_of = {et: (reverse_edge_type(et) if edge_dir == 'out' else et)
                 for et in etypes}

    from ..sampler.neighbor_sampler import _inducer_for
    init_seed, init_empty, induce = _inducer_for(self.dedup)
    offsets = {t: (seed_arrays[t][0].shape[0] if t in seed_arrays else 0)
               for t in ntypes}   # positional layout (tree mode)
    states, frontier, inv_dict = {}, {}, {}
    for t in ntypes:
      if node_caps[t] == 0:
        continue
      if t in seed_arrays:
        s, m = seed_arrays[t]
        states[t], uniq, umask, inv_dict[t] = init_seed(
            s, m, capacity=node_caps[t])
        frontier[t] = (uniq, jnp.arange(s.shape[0], dtype=jnp.int32),
                       umask)
      else:
        states[t] = init_empty(node_caps[t])

    rows, cols, edges, emasks = {}, {}, {}, {}
    nodes_per_hop = {t: [states[t].num_nodes if t in states
                         else jnp.asarray(0, jnp.int32)] for t in ntypes}
    edges_per_hop = {}
    keys = jax.random.split(key, max(1, num_hops * max(1, len(etypes))))
    ki = 0
    # calibrated dict caps (hetero clamps): overflow is tracked on
    # device and psum'd below so every shard reports the SAME verdict
    clamped = self.is_hetero and self.frontier_caps is not None
    overflow = jnp.zeros((), bool)
    for hop in range(num_hops):
      new_parts = {t: [] for t in ntypes}
      items = list(hop_caps[hop].items())
      from ..sampler.neighbor_sampler import _final_touch_map
      last_touch = (_final_touch_map(items, edge_dir)
                    if hop + 1 == num_hops else {})
      for j, (et, (fcap, k, ecap)) in enumerate(items):
        key_t = et[0] if edge_dir == 'out' else et[2]
        res_t = et[2] if edge_dir == 'out' else et[0]
        out_et = out_et_of[et]
        f, fidx, fmask = frontier[key_t]
        f, fidx, fmask = f[:fcap], fidx[:fcap], fmask[:fcap]
        nbrs, m, e = _exchange_hop(garr[et], pbs[key_t], f, fmask, k,
                                   keys[ki], nparts, with_edge,
                                   self._weighted_for(et),
                                   bucket_frac=self.bucket_frac,
                                   axes=self._axes,
                                   axis_sizes=self._axis_sizes)
        ki += 1
        states[res_t], iout = induce(states[res_t], fidx, nbrs, m,
                                     offsets[res_t],
                                     final=last_touch.get(res_t) == j,
                                     max_new=ecap if clamped else None)
        # occupancy bound advances by the CLAMPED contribution
        offsets[res_t] += ecap
        rows.setdefault(out_et, []).append(iout['cols'])
        cols.setdefault(out_et, []).append(iout['rows'])
        emasks.setdefault(out_et, []).append(iout['edge_mask'])
        if with_edge:
          edges.setdefault(out_et, []).append(
              jnp.where(iout['edge_mask'], e.reshape(-1), -1))
        edges_per_hop.setdefault(out_et, []).append(
            iout['edge_mask'].sum())
        if clamped and ecap < fcap * k:
          overflow = overflow | (iout['num_new'] > ecap)
        new_parts[res_t].append((iout['frontier'][:ecap],
                                 iout['frontier_idx'][:ecap],
                                 iout['frontier_mask'][:ecap]))
      for t in ntypes:
        parts = new_parts[t]
        if not parts:
          frontier[t] = (jnp.zeros((0,), jnp.int32),
                         jnp.zeros((0,), jnp.int32),
                         jnp.zeros((0,), bool))
          nodes_per_hop[t].append(jnp.asarray(0, jnp.int32))
          continue
        fr = jnp.concatenate([p[0] for p in parts])
        fi = jnp.concatenate([p[1] for p in parts])
        fm = jnp.concatenate([p[2] for p in parts])
        if self.dedup == 'merge' and len(parts) > 1:
          # cross-part compaction, as the local typed engine: restores
          # the arithmetic frontier_idx prefix under clamps
          order = jnp.argsort(~fm, stable=True)
          fr, fi, fm = fr[order], fi[order], fm[order]
        frontier[t] = (fr, fi, fm)
        nodes_per_hop[t].append(fm.sum().astype(jnp.int32))

    # replicated verdict: every shard must agree (uniform collectives)
    overflow = jax.lax.psum(overflow.astype(jnp.int32), self._axes) > 0
    res = dict(
        overflow=overflow,
        node={t: s.nodes for t, s in states.items()},
        num_nodes={t: s.num_nodes for t, s in states.items()},
        row={et: jnp.concatenate(v) for et, v in rows.items()},
        col={et: jnp.concatenate(v) for et, v in cols.items()},
        edge_mask={et: jnp.concatenate(v) for et, v in emasks.items()},
        num_sampled_nodes={t: jnp.stack(v)
                           for t, v in nodes_per_hop.items()},
        num_sampled_edges={et: jnp.stack(v)
                           for et, v in edges_per_hop.items()})
    if with_edge:
      res['edge'] = {et: jnp.concatenate(v) for et, v in edges.items()}
    return res, inv_dict

  def _hetero_out_specs(self, seed_widths, with_extra=()):
    """out_specs pytree mirroring _hetero_engine's result dict."""
    from jax.sharding import PartitionSpec as P
    ax = self._axes
    g = self.graph
    _, hop_caps, node_caps = self._hetero_plan(seed_widths)
    edge_dir = g.edge_dir
    out_et_of = {et: (reverse_edge_type(et) if edge_dir == 'out' else et)
                 for et in g.etypes}
    touched = []
    for hop in hop_caps:
      for et in hop:
        if out_et_of[et] not in touched:
          touched.append(out_et_of[et])
    out_specs = dict(
        node={t: P(ax) for t in g.ntypes if node_caps[t] > 0},
        num_nodes={t: P(ax) for t in g.ntypes if node_caps[t] > 0},
        row={}, col={}, edge_mask={}, num_sampled_nodes={},
        num_sampled_edges={}, overflow=P(ax))
    for oet in touched:
      for k in ('row', 'col', 'edge_mask', 'num_sampled_edges'):
        out_specs[k][oet] = P(ax)
    out_specs['num_sampled_nodes'] = {t: P(ax) for t in g.ntypes}
    if self.with_edge:
      out_specs['edge'] = {oet: P(ax) for oet in touched}
    for k in with_extra:
      out_specs[k] = P(ax)
    return out_specs

  def _hetero_graph_args(self):
    """(flat device args, unflatten) for the per-etype CSRs + per-ntype
    partition books feeding a hetero shard_map program."""
    d = self._dev
    etypes = list(self.graph.etypes)
    ntypes = list(self.graph.ntypes)
    args = []
    for et in etypes:
      ga = d[et]
      args.extend([ga['row_ids'], ga['indptr'], ga['indices'],
                   ga['eids'],
                   ga.get('wcum', ga['eids'])])
    for nt in ntypes:
      args.append(d['#pb'][nt])
    return args

  def _unpack_hetero_graph(self, flat_args):
    etypes = list(self.graph.etypes)
    ntypes = list(self.graph.ntypes)
    i = 0
    garr = {}
    for et in etypes:
      garr[et] = dict(row_ids=flat_args[i][0], indptr=flat_args[i + 1][0],
                      indices=flat_args[i + 2][0],
                      eids=flat_args[i + 3][0])
      if self._weighted_for(et):
        garr[et]['wcum'] = flat_args[i + 4][0]
      i += 5
    pbs = {}
    for nt in ntypes:
      pbs[nt] = flat_args[i]
      i += 1
    return garr, pbs, i

  def _hetero_in_specs(self, n_tail: int):
    from jax.sharding import PartitionSpec as P
    n_et = len(self.graph.etypes)
    n_nt = len(self.graph.ntypes)
    ax = tuple(self.mesh.axis_names)
    return tuple([P(ax)] * (5 * n_et) + [P()] * n_nt +
                 [P(ax)] * n_tail)

  # ------------------------------------------------------- hetero build fn

  def _build_hetero_fn(self, b: int, input_ntype):
    import jax
    from ..utils.compat import shard_map

    plan = self._hetero_plan({input_ntype: b})

    def body(*flat_args):
      garr, pbs, i = self._unpack_hetero_graph(flat_args)
      seeds, smask, key = (flat_args[i][0], flat_args[i + 1][0],
                           flat_args[i + 2][0])
      res, inv_dict = self._hetero_engine(
          garr, pbs, {input_ntype: (seeds, smask)}, key, plan)
      res['seed_inverse'] = inv_dict[input_ntype]
      return _lift(res)

    out_specs = self._hetero_out_specs({input_ntype: b},
                                       with_extra=('seed_inverse',))
    fn = shard_map(body, mesh=self.mesh,
                   in_specs=self._hetero_in_specs(3),
                   out_specs=out_specs)
    jfn = jax.jit(fn)

    def run(seeds, smask, keys):
      return jfn(*self._hetero_graph_args(), seeds, smask, keys)

    return run

  # -------------------------------------------------- hetero link build fn

  def _build_hetero_link_fn(self, b: int, num_neg: int, mode: str, etype):
    """Distributed hetero sample_from_edges (reference:
    dist_neighbor_sampler.py:424-474): typed seed sets for both endpoint
    types (+ shard-local negatives against the seed edge type's CSR),
    multi-type engine, per-type label-index metadata."""
    import jax
    import jax.numpy as jnp
    from ..utils.compat import shard_map

    g = self.graph
    src_t, _, dst_t = etype
    edge_dir = g.edge_dir
    # the candidate ids drawn against the CSR's column side belong to the
    # NON-key endpoint type: dst for CSR ('out'), src for CSC ('in') —
    # parity with the single-machine num_other derivation
    # (sampler/neighbor_sampler.py:570-574)
    num_other = g.num_nodes(dst_t if edge_dir == 'out' else src_t)
    # seed widths per endpoint type
    if mode == 'binary':
      ws, wd = b + num_neg, b + num_neg
    elif mode == 'triplet':
      ws, wd = b, b + num_neg
    else:
      ws, wd = b, b
    if src_t == dst_t:
      seed_widths = {src_t: ws + wd}
    else:
      seed_widths = {src_t: ws, dst_t: wd}
    plan = self._hetero_plan(seed_widths)

    def body(*flat_args):
      garr, pbs, i = self._unpack_hetero_graph(flat_args)
      sorted_loc = flat_args[i][0]
      rows_, cols_, sm, key = (flat_args[i + 1][0], flat_args[i + 2][0],
                               flat_args[i + 3][0], flat_args[i + 4][0])
      kneg, kloop = jax.random.split(key)
      if mode == 'none':
        src_seeds, src_m = rows_, sm
        dst_seeds, dst_m = cols_, sm
      else:
        gd = garr[etype]
        nr, nc, nvalid = ops.random_negative_sample_local(
            gd['row_ids'], gd['indptr'], sorted_loc, num_other, num_neg,
            kneg, strict=self.neg_strict)
        neg_src, neg_dst = (nr, nc) if edge_dir == 'out' else (nc, nr)
        if mode == 'binary':
          src_seeds = jnp.concatenate([rows_, neg_src])
          src_m = jnp.concatenate([sm, nvalid])
          dst_seeds = jnp.concatenate([cols_, neg_dst])
          dst_m = jnp.concatenate([sm, nvalid])
        else:
          src_seeds, src_m = rows_, sm
          dst_seeds = jnp.concatenate([cols_, neg_dst])
          dst_m = jnp.concatenate([sm, nvalid])
      if src_t == dst_t:
        seed_arrays = {src_t: (jnp.concatenate([src_seeds, dst_seeds]),
                               jnp.concatenate([src_m, dst_m]))}
      else:
        seed_arrays = {src_t: (src_seeds, src_m),
                       dst_t: (dst_seeds, dst_m)}
      res, inv_dict = self._hetero_engine(garr, pbs, seed_arrays, kloop,
                                          plan)
      if src_t == dst_t:
        inv = inv_dict[src_t]
        inv_src, inv_dst = inv[:ws], inv[ws:ws + wd]
      else:
        inv_src, inv_dst = inv_dict[src_t], inv_dict[dst_t]
      if mode in ('none', 'binary'):
        res['edge_label_index'] = jnp.stack(
            [jnp.concatenate([inv_src[:b], inv_src[b:b + num_neg]])
             if mode == 'binary' else inv_src[:b],
             jnp.concatenate([inv_dst[:b], inv_dst[b:b + num_neg]])
             if mode == 'binary' else inv_dst[:b]])
      else:
        res['src_index'] = inv_src[:b]
        res['dst_pos_index'] = inv_dst[:b]
        res['dst_neg_index'] = inv_dst[b:b + num_neg]
      return _lift(res)

    extra = (('edge_label_index',) if mode in ('none', 'binary')
             else ('src_index', 'dst_pos_index', 'dst_neg_index'))
    out_specs = self._hetero_out_specs(seed_widths, with_extra=extra)
    fn = shard_map(body, mesh=self.mesh,
                   in_specs=self._hetero_in_specs(5),
                   out_specs=out_specs)
    jfn = jax.jit(fn)

    def run(rows, cols, smask, keys):
      sorted_loc = (self._sorted_loc_dev(etype) if mode != 'none'
                    else self._dev[etype]['eids'])
      return jfn(*self._hetero_graph_args(), sorted_loc, rows, cols,
                 smask, keys)

    return run

  def _hetero_sample_from_nodes(self, input_ntype, seeds, smask):
    import jax.numpy as jnp
    b = seeds.shape[1]
    sig = ('het', b, input_ntype)
    if sig not in self._fns:
      self._fns[sig] = self._build_hetero_fn(b, input_ntype)
    from ..utils.trace import record_dispatch
    record_dispatch('dist_sample')
    res = self._fns[sig](jnp.asarray(seeds, jnp.int32),
                         jnp.asarray(smask), self._next_keys())
    return HeteroSamplerOutput(
        node=res['node'], num_nodes=res['num_nodes'], row=res['row'],
        col=res['col'], edge=res.get('edge'), edge_mask=res['edge_mask'],
        batch={input_ntype: jnp.asarray(seeds)}, batch_size=b,
        num_sampled_nodes=res['num_sampled_nodes'],
        num_sampled_edges=res['num_sampled_edges'],
        input_type=input_ntype,
        metadata={'seed_inverse': res['seed_inverse'],
                  'seed_mask': jnp.asarray(smask),
                  'overflow': res['overflow']})

  # ------------------------------------------------------------ public API

  def sample_from_nodes(self, inputs, seed_mask=None, keys=None,
                        **kwargs) -> SamplerOutput:
    """Sample per-shard batches: seeds [P, B] (or [P*B] flat, split evenly).

    Returns a SamplerOutput whose arrays carry a leading partition axis
    [P, ...] — shard p is the batch built from seed block p, ready to feed
    a data-parallel train step on the same mesh. ``seed_mask`` (same shape
    as seeds) marks padding seeds False — they produce no nodes/edges and
    are excluded from num_nodes (used by DistLoader's final short batch).
    ``keys``: explicit per-shard PRNG keys (default: the carried stream)
    — loaders replay overflowed calibrated batches at full capacities
    with the SAME keys, yielding the untruncated version of the
    identical draw.
    """
    import jax.numpy as jnp
    input_ntype = None
    if isinstance(inputs, NodeSamplerInput):
      input_ntype, raw = inputs.input_type, inputs.node
    elif isinstance(inputs, tuple) and len(inputs) == 2 and \
        isinstance(inputs[0], str):
      input_ntype, raw = inputs
    else:
      raw = inputs
    seeds = np.asarray(raw)
    p = self.graph.num_partitions
    if seeds.ndim == 1:
      assert seeds.shape[0] % p == 0, 'flat seeds must split evenly'
      seeds = seeds.reshape(p, -1)
    b = seeds.shape[1]
    smask = (np.ones_like(seeds, bool) if seed_mask is None
             else np.asarray(seed_mask).reshape(seeds.shape))
    if self.is_hetero:
      assert input_ntype is not None, \
          'hetero distributed sampling requires an input node type'
      if input_ntype not in self.graph.ntypes:
        raise ValueError(f'unknown input node type {input_ntype!r}; '
                         f'graph has {self.graph.ntypes}')
      return self._hetero_sample_from_nodes(input_ntype, seeds, smask)
    if b not in self._fns:
      self._fns[b] = self._build_fn(b)
    from ..utils.trace import record_dispatch
    record_dispatch('dist_sample')
    res = self._fns[b](jnp.asarray(seeds, jnp.int32), jnp.asarray(smask),
                       keys if keys is not None else self._next_keys())
    return SamplerOutput(
        node=res['node'], num_nodes=res['num_nodes'], row=res['row'],
        col=res['col'], edge=res.get('edge'), edge_mask=res['edge_mask'],
        batch=jnp.asarray(seeds), batch_size=b,
        num_sampled_nodes=res['num_sampled_nodes'],
        num_sampled_edges=res['num_sampled_edges'],
        metadata={'seed_inverse': res['seed_inverse'],
                  'seed_mask': jnp.asarray(smask),
                  'overflow': res['overflow']})

  def sample_from_edges(self, inputs: EdgeSamplerInput, seed_mask=None,
                        keys=None, **kwargs):
    """Distributed link sampling: seed edges [P, B] per shard (reference:
    _sample_from_edges, dist_neighbor_sampler.py:369-496).

    Negatives are shard-local (non-strict — the reference's distributed
    negative sampling likewise cannot see remote positives, :380-383).
    Metadata carries edge_label_index/edge_label (binary) or
    src/dst_pos/dst_neg indices (triplet), per shard.
    """
    import jax.numpy as jnp
    etype = inputs.input_type
    rows = np.asarray(inputs.row)
    cols = np.asarray(inputs.col)
    p = self.graph.num_partitions
    if rows.ndim == 1:
      assert rows.shape[0] % p == 0, 'flat seed edges must split evenly'
      rows = rows.reshape(p, -1)
      cols = cols.reshape(p, -1)
    b = rows.shape[1]
    smask = (np.ones_like(rows, bool) if seed_mask is None
             else np.asarray(seed_mask).reshape(rows.shape))
    neg = inputs.neg_sampling
    mode = 'none' if neg is None else neg.mode
    num_neg = 0 if neg is None else neg.num_negatives(b)
    from ..utils.trace import record_dispatch
    record_dispatch('dist_sample')

    if self.is_hetero:
      assert etype is not None, 'hetero link sampling requires input_type'
      sig = ('hlink', b, num_neg, mode, etype)
      if sig not in self._fns:
        self._fns[sig] = self._build_hetero_link_fn(b, num_neg, mode,
                                                    etype)
      res = self._fns[sig](jnp.asarray(rows, jnp.int32),
                           jnp.asarray(cols, jnp.int32),
                           jnp.asarray(smask), self._next_keys())
      out = HeteroSamplerOutput(
          node=res['node'], num_nodes=res['num_nodes'], row=res['row'],
          col=res['col'], edge=res.get('edge'),
          edge_mask=res['edge_mask'],
          batch=None, batch_size=b,
          num_sampled_nodes=res['num_sampled_nodes'],
          num_sampled_edges=res['num_sampled_edges'],
          input_type=etype,
          metadata={'seed_mask': jnp.asarray(smask),
                    'overflow': res['overflow']})
    else:
      sig = ('link', b, num_neg, mode)
      if sig not in self._fns:
        self._fns[sig] = self._build_link_fn(b, num_neg, mode)
      res = self._fns[sig](jnp.asarray(rows, jnp.int32),
                           jnp.asarray(cols, jnp.int32),
                           jnp.asarray(smask),
                           keys if keys is not None else self._next_keys())
      out = SamplerOutput(
          node=res['node'], num_nodes=res['num_nodes'], row=res['row'],
          col=res['col'], edge=res.get('edge'),
          edge_mask=res['edge_mask'],
          batch=jnp.stack([jnp.asarray(rows), jnp.asarray(cols)], axis=1),
          batch_size=b,
          num_sampled_nodes=res['num_sampled_nodes'],
          num_sampled_edges=res['num_sampled_edges'],
          metadata={'seed_inverse': res['seed_inverse'],
                    'seed_mask': jnp.asarray(smask),
                    'overflow': res['overflow']})

    if mode in ('none', 'binary'):
      label = (jnp.asarray(np.asarray(inputs.label).reshape(p, b))
               if inputs.label is not None
               else jnp.ones((p, b), jnp.int32))
      if mode == 'binary':
        label = jnp.concatenate(
            [label, jnp.zeros((p, num_neg), label.dtype)], axis=1)
      out.metadata['edge_label'] = label
      out.metadata['edge_label_index'] = res['edge_label_index']
    else:
      out.metadata['src_index'] = res['src_index']
      out.metadata['dst_pos_index'] = res['dst_pos_index']
      out.metadata['dst_neg_index'] = res['dst_neg_index']
    return out

  def subgraph(self, inputs, seed_mask=None,
               max_degree: Optional[int] = None, **kwargs):
    """Distributed induced subgraph over per-shard seed blocks [P, B]
    (reference: _subgraph, dist_neighbor_sampler.py:499-559; hetero
    unsupported there too — :505 raises NotImplementedError).
    """
    import jax.numpy as jnp
    if self.is_hetero:
      # reference-parity boundary: the upstream engine raises
      # NotImplementedError here too — a feature neither side has
      # graftlint: allow[hetero-gate] reference-parity, not unmigrated
      raise NotImplementedError(
          'hetero distributed subgraph sampling (reference parity: '
          'dist_neighbor_sampler.py:505 raises NotImplementedError)')
    if isinstance(inputs, NodeSamplerInput):
      raw = inputs.node
    else:
      raw = inputs
    seeds = np.asarray(raw)
    p = self.graph.num_partitions
    if seeds.ndim == 1:
      assert seeds.shape[0] % p == 0, 'flat seeds must split evenly'
      seeds = seeds.reshape(p, -1)
    b = seeds.shape[1]
    smask = (np.ones_like(seeds, bool) if seed_mask is None
             else np.asarray(seed_mask).reshape(seeds.shape))
    if max_degree is None:
      max_degree = self._global_max_degree()
    node_cap = sum(self._capacities(b, with_frontier_caps=False))
    buf_elems = self.graph.num_partitions * node_cap * max_degree
    if buf_elems > (1 << 25):
      import warnings
      warnings.warn(
          f'distributed subgraph buffers are [P={self.graph.num_partitions}'
          f' x node_cap={node_cap} x max_degree={max_degree}] = '
          f'{buf_elems / 1e6:.0f}M elements per shard; on power-law '
          'graphs pass an explicit max_degree cap (edges beyond the cap '
          'per row are dropped) to bound HBM',
          stacklevel=2)
    sig = ('sub', b, max_degree)
    if sig not in self._fns:
      self._fns[sig] = self._build_subgraph_fn(b, max_degree)
    from ..utils.trace import record_dispatch
    record_dispatch('dist_sample')
    res = self._fns[sig](jnp.asarray(seeds, jnp.int32),
                         jnp.asarray(smask), self._next_keys())
    return SamplerOutput(
        node=res['node'], num_nodes=res['num_nodes'], row=res['row'],
        col=res['col'], edge=res.get('edge'), edge_mask=res['edge_mask'],
        batch=jnp.asarray(seeds), batch_size=b,
        num_sampled_nodes=None, num_sampled_edges=None,
        metadata={'mapping': res['mapping'],
                  'seed_mask': jnp.asarray(smask)})

  def _global_max_degree(self) -> int:
    if not hasattr(self, '_max_deg'):
      self._max_deg = max(
          1, int(np.max(np.diff(self.graph.indptr, axis=1))))
    return self._max_deg

  def collate(self, out, node_labels=None, label_cap=None):
    """Attach features (sharded all_to_all gather) and labels.

    Reference: _colloate_fn (dist_neighbor_sampler.py:650-744). Labels
    are PARTITIONED like features — each shard holds only its owned
    nodes' labels as a 1-wide sharded table and the gather rides the same
    all_to_all path — not replicated per device (which at papers100M
    scale would put the full [N] array on every chip).

    ``label_cap``: gather labels only for the first ``label_cap`` node
    slots per shard (the seed block leads each shard's buffer); for
    hetero, only the seed (input) type carries labels then.
    """
    if isinstance(out, HeteroSamplerOutput):
      x = y = None
      if self.collect_features and self.dist_feature is not None:
        x = {t: self.dist_feature[t].get(out.node[t])
             for t in out.node if t in self.dist_feature}
      if node_labels is not None:
        y = {}
        for t in out.node:
          if t not in node_labels:
            continue
          if label_cap is not None and t != out.input_type:
            continue
          buf = (out.node[t] if label_cap is None
                 else out.node[t][:, :label_cap])
          y[t] = self._label_dist(node_labels[t], t).get(buf)[..., 0]
      return x, y
    x = None
    if self.collect_features:
      x = self.dist_feature.get(out.node)
    y = None
    if node_labels is not None:
      buf = (out.node if label_cap is None
             else out.node[:, :label_cap])
      y = self._label_dist(node_labels).get(buf)[..., 0]
    return x, y

  def label_stores(self):
    """The sharded label DistFeatures built by _label_dist — their
    on-device [P, 4] stats accumulators carry the same int32 wrap
    hazard as the dataset's feature stores, so the loaders drain them
    per epoch alongside data.feature_stores()."""
    if hasattr(self, '_labels_cache'):
      for _, store in self._labels_cache.values():
        yield store

  def _label_dist(self, labels, key=None):
    """Sharded label store, built once per distinct label array (keyed by
    identity, so swapping in different labels is picked up while repeated
    batches reuse the shards)."""
    from .dist_feature import DistFeature
    if not hasattr(self, '_labels_cache'):
      self._labels_cache = {}  # key -> (id(labels), DistFeature)
    hit = self._labels_cache.get(key)
    if hit is None or hit[0] != id(labels):
      lab = np.asarray(labels).reshape(-1)
      if lab.dtype == np.int64:     # TPU-native widths
        lab = lab.astype(np.int32)
      elif lab.dtype == np.float64:
        lab = lab.astype(np.float32)
      pb = (self.graph.node_pb[key] if self.is_hetero
            else self.graph.node_pb)
      blocks = []
      for p in range(self.graph.num_partitions):
        ids = np.nonzero(pb == p)[0].astype(np.int64)
        blocks.append((ids, lab[ids][:, None]))
      hit = (id(labels), DistFeature(self.graph.num_partitions, blocks,
                                     pb, mesh=self.mesh,
                                     dtype=lab.dtype))
      self._labels_cache[key] = hit
    return hit[1]
