"""Resilience primitives for the distributed sampling path.

Two building blocks shared by rpc / loader / producer code:

* :class:`RetryPolicy` — exponential backoff with deterministic jitter
  and two deadline budgets (per attempt, total). Replaces the bare
  immediate-retry loop that used to live in ``rpc.RpcClient
  .request_sync``. Retries are only ever applied to calls the caller
  has declared idempotent — re-sending a non-idempotent RPC after a
  lost response duplicates its side effect.

* :class:`Heartbeat` — a liveness tracker. Sampling servers answer a
  ``heartbeat`` RPC (DistServer.heartbeat); the remote loaders poll it
  from a background thread per server so a dead or partitioned server
  is declared dead after ``miss_threshold`` consecutive missed probes
  (seconds) instead of surfacing as a 180 s socket timeout deep inside
  a fetch.

Degradation events are reported through utils/trace.py counters
(``resilience.retry``, ``resilience.server_dead``, ...) so a degraded
epoch is observable without log scraping.
"""
import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional

from ..utils import trace
from ..utils.faults import fault_point

logger = logging.getLogger('graphlearn_tpu.resilience')

# shared jitter source for policies without an explicit seed (process-
# seeded, so independent clients spread their retries apart)
_jitter = random.Random()


def env_float(name: str, default: float,
              minimum: Optional[float] = None) -> float:
  """A float tuning knob from the environment, HARDENED: a malformed
  or out-of-range value warns and falls back to the default — a typo'd
  production override must never crash a worker's import or wedge its
  liveness loop (the GLT_SPAN_BUFFER discipline, metrics/spans.py)."""
  raw = os.environ.get(name)
  if raw in (None, ''):
    return default
  try:
    val = float(raw)
    if val != val or (minimum is not None and val < minimum):
      raise ValueError('out of range')
  except (TypeError, ValueError):
    logger.warning('%s=%r is not a usable number — using the default '
                   '%s', name, raw, default)
    return default
  return val


def env_int(name: str, default: int, minimum: Optional[int] = None) -> int:
  """Integer counterpart of :func:`env_float` (same fallback rules)."""
  raw = os.environ.get(name)
  if raw in (None, ''):
    return default
  try:
    val = int(raw)
    if minimum is not None and val < minimum:
      raise ValueError('out of range')
  except (TypeError, ValueError):
    logger.warning('%s=%r is not a usable integer — using the default '
                   '%s', name, raw, default)
    return default
  return val


#: Launch-wide heartbeat tuning (docs/failure_model.md): probe period
#: and miss threshold for Heartbeat instances constructed without
#: explicit values. Malformed values fall back (env_float/env_int).
ENV_HEARTBEAT_INTERVAL = 'GLT_HEARTBEAT_INTERVAL'
ENV_HEARTBEAT_MISS = 'GLT_HEARTBEAT_MISS'


class DeadlineExceeded(TimeoutError):
  """A RetryPolicy exhausted its attempt or total-deadline budget."""


class ServerDeadError(ConnectionError):
  """A sampling server was declared dead (liveness or hard RPC failure).

  Carries the rank so failover code can redistribute its work."""

  def __init__(self, rank: int, cause: str = ''):
    super().__init__(f'sampling server rank {rank} declared dead'
                     + (f': {cause}' if cause else ''))
    self.rank = rank
    self.cause = cause


@dataclass(frozen=True)
class RetryPolicy:
  """Exponential backoff + jitter + deadline budgets.

  ``base_delay * multiplier**k`` capped at ``max_delay``, each delay
  scaled by ``1 - jitter .. 1``. With ``seed`` set the jitter stream is
  deterministic per delays() call (tests replay the exact schedule);
  with the default ``seed=None`` it draws from a process RNG so
  concurrent retriers desynchronize instead of hammering a recovering
  server in lockstep. ``max_attempts`` bounds tries; ``total_deadline``
  bounds wall time across attempts (checked before sleeping — the
  policy never sleeps past its budget); ``per_attempt_timeout`` is
  advisory for callers that can bound a single attempt (RpcClient maps
  it onto the socket timeout, including connection establishment).
  """
  max_attempts: int = 4
  base_delay: float = 0.05
  max_delay: float = 2.0
  multiplier: float = 2.0
  jitter: float = 0.25
  per_attempt_timeout: Optional[float] = None
  total_deadline: Optional[float] = 30.0
  seed: Optional[int] = None

  def delays(self) -> Iterable[float]:
    """The backoff schedule: one delay per retry (attempts - 1)."""
    rng = random.Random(self.seed) if self.seed is not None else _jitter
    for k in range(self.max_attempts - 1):
      d = min(self.base_delay * (self.multiplier ** k), self.max_delay)
      yield d * (1.0 - self.jitter * rng.random())

  def run(self, fn: Callable, *args,
          retry_on=(ConnectionError, TimeoutError, OSError, EOFError),
          on_retry: Optional[Callable] = None, describe: str = '',
          **kwargs):
    """Call ``fn`` under this policy. Retries on ``retry_on``; any other
    exception propagates immediately. ``on_retry(attempt, exc)`` runs
    before each backoff sleep (loaders use it to refresh connections).
    Raises :class:`DeadlineExceeded` (with the last error chained) when
    the budget is exhausted.
    """
    start = time.monotonic()
    last_err: Optional[BaseException] = None
    attempts_made = 0
    delays = list(self.delays())
    for attempt in range(self.max_attempts):
      if self.total_deadline is not None and \
          time.monotonic() - start > self.total_deadline:
        break
      try:
        attempts_made += 1
        return fn(*args, **kwargs)
      except retry_on as e:  # noqa: PERF203 - retry loop
        last_err = e
        if attempt >= self.max_attempts - 1:
          break
        delay = delays[attempt]
        if self.total_deadline is not None and \
            (time.monotonic() - start) + delay > self.total_deadline:
          break
        trace.counter_inc('resilience.retry')
        if on_retry is not None:
          on_retry(attempt, e)
        logger.warning('%s failed (%s); retrying in %.3fs (attempt %d/%d)',
                       describe or getattr(fn, '__name__', 'call'), e,
                       delay, attempt + 1, self.max_attempts)
        time.sleep(delay)
    if attempts_made <= 1 and last_err is not None:
      # nothing was retried (NO_RETRY or immediate budget exhaustion):
      # surface the ORIGINAL exception type — re-typing a single
      # ConnectionRefusedError as a TimeoutError would steer callers
      # that branch on the class into the wrong recovery path
      raise last_err
    raise DeadlineExceeded(
        f'{describe or getattr(fn, "__name__", "call")} failed after '
        f'{attempts_made} attempt(s) / '
        f'{time.monotonic() - start:.1f}s: {last_err}') from last_err


#: Conservative default used for idempotent control-plane RPCs. The
#: finite per-attempt timeout matters: without it a hung (not dead)
#: server would hold one attempt for the full 180 s socket timeout and
#: the total_deadline would expire after a single try, never retrying.
DEFAULT_RETRY_POLICY = RetryPolicy(per_attempt_timeout=7.0)

#: No retries at all — single attempt, surface the first error.
NO_RETRY = RetryPolicy(max_attempts=1, total_deadline=None)


class Heartbeat:
  """Background liveness probes against a set of server ranks.

  One daemon thread per rank calls ``probe_fn(rank)`` every
  ``interval`` seconds with a bounded per-probe timeout; after
  ``miss_threshold`` consecutive failures the rank is declared dead:
  ``on_dead(rank, cause)`` fires once, ``dead_ranks()`` reports it, and
  probing of that rank stops (death is sticky — a flapping server must
  be re-added explicitly). Detection latency is therefore about
  ``interval * miss_threshold`` seconds, versus the 180 s socket
  timeout on the data path.
  """

  def __init__(self, ranks: Iterable[int], probe_fn: Callable[[int], None],
               interval: Optional[float] = None,
               miss_threshold: Optional[int] = None,
               on_dead: Optional[Callable[[int, str], None]] = None):
    self._ranks: List[int] = list(ranks)
    self._probe = probe_fn
    # None = the launch-wide env defaults (hardened parse: a malformed
    # GLT_HEARTBEAT_* value warns and uses the built-in default)
    if interval is None:
      interval = env_float(ENV_HEARTBEAT_INTERVAL, 1.0, minimum=1e-3)
    if miss_threshold is None:
      miss_threshold = env_int(ENV_HEARTBEAT_MISS, 3, minimum=1)
    self.interval = interval
    self.miss_threshold = max(1, miss_threshold)
    self._on_dead = on_dead
    # liveness state shared between per-rank probe threads and caller
    # threads (is_dead/dead_ranks/mark_dead) — every access holds _lock
    # graftlint: shared[_lock]
    self._dead: Dict[int, str] = {}
    # graftlint: shared[_lock]
    self._misses: Dict[int, int] = {r: 0 for r in self._ranks}
    # graftlint: shared[_lock]
    self._last_ok: Dict[int, float] = {}
    self._stop = threading.Event()
    self._lock = threading.Lock()
    self._threads: List[threading.Thread] = []

  def start(self):
    if self._threads:
      return
    self._stop.clear()
    for rank in self._ranks:
      t = threading.Thread(target=self._loop, args=(rank,), daemon=True,
                           name=f'glt-heartbeat-{rank}')
      self._threads.append(t)
      t.start()

  def stop(self):
    self._stop.set()
    for t in self._threads:
      t.join(timeout=self.interval + 5)
    self._threads = []

  def _loop(self, rank: int):
    while not self._stop.wait(self.interval):
      with self._lock:
        if rank in self._dead:
          return
      try:
        fault_point('heartbeat.probe')
        self._probe(rank)
      except Exception as e:  # noqa: BLE001 - any failure is a miss
        dead = False
        with self._lock:
          self._misses[rank] += 1
          if self._misses[rank] >= self.miss_threshold and \
              rank not in self._dead:
            self._dead[rank] = repr(e)
            dead = True
        if dead:
          trace.counter_inc('resilience.server_dead')
          logger.warning('server rank %d declared dead after %d missed '
                         'heartbeats: %s', rank, self.miss_threshold, e)
          if self._on_dead is not None:
            try:
              self._on_dead(rank, repr(e))
            except Exception:  # noqa: BLE001 - callback must not kill probe
              logger.exception('heartbeat on_dead callback failed')
          return
      else:
        with self._lock:
          self._misses[rank] = 0
          self._last_ok[rank] = time.monotonic()

  def is_dead(self, rank: int) -> bool:
    with self._lock:
      return rank in self._dead

  def dead_ranks(self) -> Dict[int, str]:
    """{rank: cause} for every rank declared dead so far."""
    with self._lock:
      return dict(self._dead)

  def mark_dead(self, rank: int, cause: str):
    """Externally declare a rank dead (e.g. a hard RPC failure on the
    data path — no need to wait out the probe threshold)."""
    first = False
    with self._lock:
      if rank not in self._dead:
        self._dead[rank] = cause
        first = True
    if first:
      trace.counter_inc('resilience.server_dead')
