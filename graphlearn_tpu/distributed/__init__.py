from .dist_context import (DistContext, DistRole, get_context,
                           init_worker_group)
from .dist_dataset import DistDataset
from .dist_feature import DistFeature
from .dist_graph import DistGraph, build_local_csr
from .dist_loader import DistLoader, DistNeighborLoader
from .dist_neighbor_sampler import DistNeighborSampler
