from .dist_context import (DistContext, DistRole, get_context,
                           init_multihost, init_worker_group)
from .dist_dataset import DistDataset
from .dist_random_partitioner import DistRandomPartitioner, shared_node_pb
from .dist_table_dataset import DistTableDataset
from .dist_feature import DistFeature
from .dist_graph import DistGraph, DistHeteroGraph, build_local_csr
from .dist_loader import (DistLinkNeighborLoader, DistLoader,
                          DistNeighborLoader, DistSubGraphLoader,
                          MpDistLinkNeighborLoader, MpDistNeighborLoader,
                          RemoteDistLinkNeighborLoader,
                          RemoteDistNeighborLoader)
from .dist_neighbor_sampler import DistNeighborSampler
from .dist_options import (CollocatedDistSamplingWorkerOptions,
                           MpDistSamplingWorkerOptions,
                           RemoteDistSamplingWorkerOptions)
from .dist_sampling_producer import (DistCollocatedSamplingProducer,
                                     DistMpSamplingProducer)
from .block_producer import (BlockSampleProducer, block_mb_per_chunk,
                             stack_block_frames)
from .remote_scan import RemoteBlockStager, RemoteScanTrainer
from .dist_server import (DistServer, get_server, init_server,
                          wait_and_shutdown_server)
from .dist_client import (async_request_server, init_client,
                          request_server, shutdown_client)
from .event_loop import ConcurrentEventLoop
from .message import message_to_data, output_to_message
from .resilience import (DEFAULT_RETRY_POLICY, NO_RETRY, DeadlineExceeded,
                         Heartbeat, RetryPolicy, ServerDeadError)
from .rpc import (Barrier, RpcCalleeBase, RpcClient,
                  RpcDataPartitionRouter, RpcServer, get_free_port)
from .tenancy import (PRIORITY_CLASSES, AdmissionController, TenancyConfig,
                      TenantQuotaExceeded, TenantRejection, TenantSpec,
                      TenantStarvedError, TenantThrottled,
                      WeightedFairScheduler, with_backpressure)
