"""SamplerOutput <-> flat SampleMessage conversion.

Counterpart of the reference's SampleMessage dict convention
(/root/reference/graphlearn_torch/python/distributed/dist_neighbor_sampler.py:650-744:
flat Dict[str, Tensor] with '#' control keys) used across channels and the
server-client wire.
"""
from typing import Optional

import numpy as np

from ..loader import Data
from ..sampler import SamplerOutput

META_PREFIX = '#META.'


def output_to_message(out: SamplerOutput, x=None, y=None) -> dict:
  """Flatten a (homogeneous) SamplerOutput + optional collected features."""
  msg = {
      'node': np.asarray(out.node),
      'num_nodes': np.asarray(out.num_nodes),
      'row': np.asarray(out.row),
      'col': np.asarray(out.col),
      'edge_mask': np.asarray(out.edge_mask),
  }
  if out.edge is not None:
    msg['edge'] = np.asarray(out.edge)
  if out.batch is not None:
    msg['batch'] = np.asarray(out.batch)
  if out.batch_size is not None:
    msg['#META.batch_size'] = np.asarray(out.batch_size)
  if out.num_sampled_nodes is not None:
    msg['num_sampled_nodes'] = np.asarray(
        [np.asarray(v) for v in out.num_sampled_nodes])
  if out.num_sampled_edges is not None:
    msg['num_sampled_edges'] = np.asarray(
        [np.asarray(v) for v in out.num_sampled_edges])
  if x is not None:
    msg['x'] = np.asarray(x)
  if y is not None:
    msg['y'] = np.asarray(y)
  for k, v in out.metadata.items():
    try:
      msg[META_PREFIX + k] = np.asarray(v)
    except Exception:
      pass
  return msg


def message_to_data(msg: dict, to_device: bool = True) -> Data:
  """SampleMessage -> loader.Data (reference: DistLoader._collate_fn,
  dist_loader.py:331-441). Arrays stay padded; device transfer is one
  device_put per array when `to_device`."""
  import jax.numpy as jnp
  conv = (lambda a: jnp.asarray(a)) if to_device else (lambda a: a)
  node = conv(msg['node'])
  row, col = conv(msg['row']), conv(msg['col'])
  ei = jnp.stack([row, col]) if to_device else np.stack([row, col])
  num_nodes = msg.get('num_nodes')
  node_mask = None
  if num_nodes is not None:
    num_nodes = int(np.asarray(num_nodes).reshape(-1)[0])
    rng = jnp.arange(node.shape[0]) if to_device else \
        np.arange(node.shape[0])
    node_mask = rng < num_nodes
  metadata = {k[len(META_PREFIX):]: v for k, v in msg.items()
              if k.startswith(META_PREFIX) and k != '#META.batch_size'}
  return Data(
      node=node, num_nodes=num_nodes, node_mask=node_mask, edge_index=ei,
      edge_mask=conv(msg['edge_mask']),
      x=conv(msg['x']) if 'x' in msg else None,
      y=conv(msg['y']) if 'y' in msg else None,
      edge_ids=conv(msg['edge']) if 'edge' in msg else None,
      batch=conv(msg['batch']) if 'batch' in msg else None,
      batch_size=(int(np.asarray(msg['#META.batch_size']).reshape(-1)[0])
                  if '#META.batch_size' in msg else None),
      num_sampled_nodes=msg.get('num_sampled_nodes'),
      num_sampled_edges=msg.get('num_sampled_edges'),
      metadata=metadata)
