"""SamplerOutput <-> flat SampleMessage conversion.

Counterpart of the reference's SampleMessage dict convention
(/root/reference/graphlearn_torch/python/distributed/dist_neighbor_sampler.py:650-744:
flat Dict[str, Tensor] with '#' control keys) used across channels and the
server-client wire.
"""
import numpy as np

from ..loader import Data
from ..sampler import SamplerOutput

META_PREFIX = '#META.'


def output_to_message(out: SamplerOutput, x=None, y=None) -> dict:
  """Flatten a (homogeneous) SamplerOutput + optional collected features."""
  msg = {
      'node': np.asarray(out.node),
      'num_nodes': np.asarray(out.num_nodes),
      'row': np.asarray(out.row),
      'col': np.asarray(out.col),
      'edge_mask': np.asarray(out.edge_mask),
  }
  if out.edge is not None:
    msg['edge'] = np.asarray(out.edge)
  if out.batch is not None:
    msg['batch'] = np.asarray(out.batch)
  if out.batch_size is not None:
    msg['#META.batch_size'] = np.asarray(out.batch_size)
  if out.num_sampled_nodes is not None:
    msg['num_sampled_nodes'] = np.asarray(
        [np.asarray(v) for v in out.num_sampled_nodes])
  if out.num_sampled_edges is not None:
    msg['num_sampled_edges'] = np.asarray(
        [np.asarray(v) for v in out.num_sampled_edges])
  if x is not None:
    msg['x'] = np.asarray(x)
  if y is not None:
    msg['y'] = np.asarray(y)
  for k, v in out.metadata.items():
    try:
      arr = np.asarray(v)
    except Exception:
      continue
    if arr.dtype == object:    # nested containers can't ride the channel
      continue
    msg[META_PREFIX + k] = arr
  return msg


def _et_key(et) -> str:
  from ..typing import as_str
  return as_str(tuple(et))


def hetero_output_to_message(out, x_dict=None, y_dict=None) -> dict:
  """Flatten a HeteroSamplerOutput + optional typed features/labels.

  Typed payloads use dotted keys (``node.paper``,
  ``row.paper__cites__paper``) mirroring the reference's hetero
  SampleMessage convention (dist_neighbor_sampler.py:650-744 '#'-keyed
  dicts); ``#META.hetero`` marks the message so message_to_data
  rebuilds a HeteroData. Node/edge type names must not contain '.' or
  '__' (the framework-wide etype-name convention)."""
  msg = {'#META.hetero': np.asarray(1)}
  for t, v in out.node.items():
    msg[f'node.{t}'] = np.asarray(v)
    msg[f'num_nodes.{t}'] = np.asarray(out.num_nodes[t])
  for et, v in out.row.items():
    k = _et_key(et)
    msg[f'row.{k}'] = np.asarray(v)
    msg[f'col.{k}'] = np.asarray(out.col[et])
    msg[f'edge_mask.{k}'] = np.asarray(out.edge_mask[et])
    if out.edge is not None and et in out.edge:
      msg[f'edge.{k}'] = np.asarray(out.edge[et])
    if out.num_sampled_edges is not None and et in out.num_sampled_edges:
      msg[f'num_sampled_edges.{k}'] = np.asarray(
          [np.asarray(c) for c in out.num_sampled_edges[et]])
  if out.batch is not None:
    for t, v in out.batch.items():
      msg[f'batch.{t}'] = np.asarray(v)
  if out.num_sampled_nodes is not None:
    for t, v in out.num_sampled_nodes.items():
      msg[f'num_sampled_nodes.{t}'] = np.asarray(
          [np.asarray(c) for c in v])
  if out.batch_size is not None:
    msg['#META.batch_size'] = np.asarray(out.batch_size)
  if out.input_type is not None:
    from ..typing import as_str
    msg['#META.input_type'] = np.frombuffer(
        as_str(out.input_type).encode(), dtype=np.uint8).copy()
  for t, v in (x_dict or {}).items():
    msg[f'x.{t}'] = np.asarray(v)
  for t, v in (y_dict or {}).items():
    msg[f'y.{t}'] = np.asarray(v)
  for k, v in out.metadata.items():
    try:
      arr = np.asarray(v)
    except Exception:
      continue
    if arr.dtype == object:    # nested dicts (e.g. seed_inverse_dict)
      continue                 # don't serialize; channel is flat arrays
    msg[META_PREFIX + k] = arr
  return msg


def _hetero_message_to_data(msg: dict, to_device: bool):
  """SampleMessage -> loader.HeteroData (typed counterpart of
  message_to_data; keys per hetero_output_to_message)."""
  import jax.numpy as jnp

  from ..loader.transform import HeteroData
  from ..typing import to_edge_type
  conv = (lambda a: jnp.asarray(a)) if to_device else (lambda a: a)

  def group(prefix, et_keyed=False):
    d = {}
    for k, v in msg.items():
      if not k.startswith(prefix + '.'):
        continue
      name = k[len(prefix) + 1:]
      d[to_edge_type(name) if et_keyed else name] = v
    return d

  node = {t: conv(v) for t, v in group('node').items()}
  num_nodes = {t: int(np.asarray(v).reshape(-1)[0])
               for t, v in group('num_nodes').items() if '__' not in t}
  rows = group('row', et_keyed=True)
  cols = group('col', et_keyed=True)
  ei = {}
  for et, r in rows.items():
    r2, c2 = conv(r), conv(cols[et])
    ei[et] = jnp.stack([r2, c2]) if to_device else np.stack([r2, c2])
  em = {et: conv(v) for et, v in group('edge_mask', True).items()}
  eids = {et: conv(v) for et, v in group('edge', True).items()} or None
  x = {t: conv(v) for t, v in group('x').items()} or None
  y = {t: conv(v) for t, v in group('y').items()} or None
  batch = {t: conv(v) for t, v in group('batch').items()} or None
  nsn = {t: v for t, v in group('num_sampled_nodes').items()
         if '__' not in t} or None
  nse = group('num_sampled_edges', et_keyed=True) or None
  metadata = {k[len(META_PREFIX):]: v for k, v in msg.items()
              if k.startswith(META_PREFIX) and
              k not in ('#META.batch_size', '#META.hetero',
                        '#META.input_type')}
  if '#META.input_type' in msg:
    metadata['input_type'] = bytes(
        np.asarray(msg['#META.input_type'])).decode()
  return HeteroData(
      node=node, num_nodes=num_nodes, edge_index=ei, edge_mask=em,
      x=x, y=y, edge_ids=eids, batch=batch,
      batch_size=(int(np.asarray(msg['#META.batch_size']).reshape(-1)[0])
                  if '#META.batch_size' in msg else None),
      num_sampled_nodes=nsn, num_sampled_edges=nse, metadata=metadata)


def message_to_data(msg: dict, to_device: bool = True) -> Data:
  """SampleMessage -> loader.Data (reference: DistLoader._collate_fn,
  dist_loader.py:331-441). Arrays stay padded; device transfer is one
  device_put per array when `to_device`. Messages flagged
  ``#META.hetero`` rebuild a HeteroData instead."""
  import jax.numpy as jnp
  if '#META.hetero' in msg:
    return _hetero_message_to_data(msg, to_device)
  conv = (lambda a: jnp.asarray(a)) if to_device else (lambda a: a)
  node = conv(msg['node'])
  row, col = conv(msg['row']), conv(msg['col'])
  ei = jnp.stack([row, col]) if to_device else np.stack([row, col])
  num_nodes = msg.get('num_nodes')
  node_mask = None
  if num_nodes is not None:
    num_nodes = int(np.asarray(num_nodes).reshape(-1)[0])
    rng = jnp.arange(node.shape[0]) if to_device else \
        np.arange(node.shape[0])
    node_mask = rng < num_nodes
  metadata = {k[len(META_PREFIX):]: v for k, v in msg.items()
              if k.startswith(META_PREFIX) and k != '#META.batch_size'}
  return Data(
      node=node, num_nodes=num_nodes, node_mask=node_mask, edge_index=ei,
      edge_mask=conv(msg['edge_mask']),
      x=conv(msg['x']) if 'x' in msg else None,
      y=conv(msg['y']) if 'y' in msg else None,
      edge_ids=conv(msg['edge']) if 'edge' in msg else None,
      batch=conv(msg['batch']) if 'batch' in msg else None,
      batch_size=(int(np.asarray(msg['#META.batch_size']).reshape(-1)[0])
                  if '#META.batch_size' in msg else None),
      num_sampled_nodes=msg.get('num_sampled_nodes'),
      num_sampled_edges=msg.get('num_sampled_edges'),
      metadata=metadata)
