"""Concurrent event loop: daemon-thread asyncio with bounded concurrency.

TPU-native port of
/root/reference/graphlearn_torch/python/distributed/event_loop.py. In the
mesh path concurrency dissolves into the compiled step, but the
server-client topology still overlaps batch production with streaming; this
loop drives that, same contract as the reference (add_task async w/
callback, run_task sync, semaphore cap).
"""
import asyncio
import threading
from typing import Callable, Optional


class ConcurrentEventLoop:
  """Reference: event_loop.py:39-99."""

  def __init__(self, concurrency: int = 4):
    self._loop = asyncio.new_event_loop()
    self._sem = None
    self._concurrency = concurrency
    self._thread = threading.Thread(target=self._run, daemon=True)

  def _run(self):
    asyncio.set_event_loop(self._loop)
    self._sem = asyncio.BoundedSemaphore(self._concurrency)
    self._loop.run_forever()

  def start_loop(self):
    if not self._thread.is_alive():
      self._thread.start()
      while self._sem is None:
        pass  # tiny spin until loop-owned state exists

  def shutdown_loop(self):
    if self._thread.is_alive():
      self._loop.call_soon_threadsafe(self._loop.stop)
      self._thread.join(timeout=5)

  def add_task(self, coro, callback: Optional[Callable] = None):
    """Schedule `coro` under the concurrency cap; `callback(result)` fires
    on completion (reference: event_loop.py:60-78)."""

    async def guarded():
      async with self._sem:
        return await coro

    fut = asyncio.run_coroutine_threadsafe(guarded(), self._loop)
    if callback is not None:
      fut.add_done_callback(lambda f: callback(f.result()))
    return fut

  def run_task(self, coro):
    """Run `coro` to completion synchronously (reference: 80-90)."""
    return asyncio.run_coroutine_threadsafe(coro, self._loop).result()

  async def wrap_future(self, fut):
    """concurrent.futures.Future -> awaitable (reference wrap_torch_future,
    event_loop.py:92-99)."""
    return await asyncio.wrap_future(fut, loop=self._loop)
