"""Lightweight TCP RPC for the control plane and server-client streaming.

TPU-native replacement for the reference's torch.distributed.rpc stack
(/root/reference/graphlearn_torch/python/distributed/rpc.py, TensorPipe/uv):
on TPU the *data plane* between training chips is XLA collectives over
ICI/DCN (see dist_neighbor_sampler.py), so RPC survives only where the
reference used it for the server-client topology — sampling servers
streaming batches to training clients — and for control-plane
barrier/gather. That needs no torch: a threaded socket server with
length-prefixed pickled frames (numpy arrays ride pickle protocol 5
zero-copy buffers).

API parity: rpc_register / rpc_request_async / rpc_request_sync /
RpcCalleeBase (reference rpc.py:371-473), barrier/all_gather
(rpc.py:109-233).

TRUST MODEL: frames are deserialized with pickle, so anyone who can
connect can execute arbitrary code — the reference's torch-RPC posture
(TensorPipe performs no authentication either). This stack removes the
sharpest edge with a shared-secret MUTUAL HMAC handshake: set
``GLT_RPC_SECRET`` in the environment (or pass ``secret=``) and every
accepted connection must answer an HMAC-SHA256 challenge before any
frame is processed, and the server must in turn answer the CLIENT's
challenge before the client deserializes a single response frame (a
spoofed/MITM server that does not know the secret is dropped before
its first pickle reaches the client). The handshake is REQUIRED for
non-loopback binds (a routable server without a secret refuses to
start unless ``insecure=True``); loopback binds may omit it for parity
with local multiprocess use. Residual risk: the handshake authenticates
peers but does not encrypt or MAC the frames that follow, so an
attacker who can rewrite established TCP streams (not just connect) can
still inject pickles — the network boundary (VPC / firewall / TLS
tunnel) remains the outer wall against that class.
"""
import hashlib
import hmac
import logging
import os
import pickle
import secrets as _secrets
import socket
import socketserver
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger('graphlearn_tpu.rpc')

_HDR = struct.Struct('<Q')
_SECRET_ENV = 'GLT_RPC_SECRET'

# Typed wire errors (distributed/tenancy.py registers its retryable
# rejections here): a server-side exception whose class carries a
# WIRE_TYPE registered in this table ships as a STRUCTURED
# ``(etype, payload-dict)`` pair in the error frame — plain primitives,
# never a pickled exception object — and the client reconstructs the
# typed exception instead of a generic RuntimeError. Anything
# unregistered keeps the legacy string-only error path.
_WIRE_ERRORS: Dict[str, Callable[[dict], BaseException]] = {}


def register_wire_error(etype: str, factory: Callable[[dict],
                                                      BaseException]):
  """Register a typed error for structured RPC propagation. The
  factory receives the server's payload dict and returns the exception
  instance to raise client-side."""
  _WIRE_ERRORS[etype] = factory


def _env_secret() -> Optional[bytes]:
  s = os.environ.get(_SECRET_ENV)
  return s.encode() if s else None


def _hmac_of(secret: bytes, nonce: bytes,
             role: bytes = b'client') -> bytes:
  # role domain-separates the two handshake directions: without it a
  # MITM could replay one client's answer as a 'server proof' to
  # another client (reflection), never knowing the secret
  return hmac.new(secret, role + nonce, hashlib.sha256).digest()


def _send_frame(sock: socket.socket, obj: Any):
  payload = pickle.dumps(obj, protocol=5)
  sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
  chunks = []
  while n:
    b = sock.recv(min(n, 1 << 20))
    if not b:
      raise ConnectionError('peer closed')
    chunks.append(b)
    n -= len(b)
  return b''.join(chunks)


def _recv_frame(sock: socket.socket) -> Any:
  (size,) = _HDR.unpack(_recv_exact(sock, 8))
  return pickle.loads(_recv_exact(sock, size))


class RpcCalleeBase:
  """Stateful remote-callable object (reference: rpc.py:371-385)."""

  def call(self, *args, **kwargs):
    raise NotImplementedError


class RpcServer:
  """Threaded socket server dispatching registered callees."""

  def __init__(self, host: str = '127.0.0.1', port: int = 0,
               handlers: Optional[Dict[str, Callable]] = None,
               secret: Optional[bytes] = None, insecure: bool = False):
    # handlers passed here are registered BEFORE the server starts
    # accepting — register() after construction races incoming requests
    self._handlers: Dict[str, Callable] = dict(handlers) if handlers \
        else {}
    self._secret = secret if secret is not None else _env_secret()
    loopback = host in ('127.0.0.1', 'localhost', '::1')
    if self._secret is None and not loopback and not insecure:
      raise ValueError(
          f'RpcServer binding routable address {host!r} without a '
          f'shared secret: set {_SECRET_ENV} (or pass secret=) so peers '
          'must pass the HMAC handshake, or pass insecure=True to '
          'accept unauthenticated pickle RPC on this network')
    outer = self

    class Handler(socketserver.BaseRequestHandler):
      def handle(self):
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
          if outer._secret is not None:
            # mutual challenge-response BEFORE any pickle leaves the
            # wire: an unauthenticated peer never reaches the
            # deserializer, and the client hears our proof before it
            # deserializes our first response frame
            nonce = _secrets.token_bytes(32)
            sock.sendall(nonce)
            # verify the 32-byte answer BEFORE reading the client's
            # nonce: a secret-less client's first (pickle) frame can be
            # shorter than 64 bytes, and blocking on all 64 would
            # deadlock both sides instead of rejecting promptly
            answer = _recv_exact(sock, 32)
            if not hmac.compare_digest(
                answer, _hmac_of(outer._secret, nonce)):
              logger.warning('rejected RPC connection from %s: bad '
                             'HMAC handshake', self.client_address)
              return
            client_nonce = _recv_exact(sock, 32)
            sock.sendall(_hmac_of(outer._secret, client_nonce,
                                  role=b'server'))
          from ..metrics import spans
          from ..utils.faults import fault_point
          while True:
            req = _recv_frame(sock)
            # armed 'delay' simulates a hung server (liveness-test
            # territory); 'raise' tears the connection down mid-stream
            fault_point('rpc.server.dispatch')
            # adopt the caller's span context for the handler: spans it
            # opens (and anything it propagates onward — mp producer
            # commands, serving submits) join the caller's trace, so
            # one request id recovers the whole cross-process tree
            ctx = req.get('ctx')
            try:
              with spans.adopt(ctx), \
                  spans.span('rpc.server.handle', func=req['func']):
                fn = outer._handlers[req['func']]
                result = fn(*req.get('args', ()),
                            **req.get('kwargs', {}))
              _send_frame(sock, {'ok': True, 'result': result})
            except Exception as e:  # noqa: BLE001 - errors cross the wire
              reply = {'ok': False,
                       'error': f'{type(e).__name__}: {e}'}
              # typed rejections (tenancy throttles/quotas) ship a
              # structured payload so the client reconstructs the
              # exact exception — see register_wire_error
              etype = getattr(type(e), 'WIRE_TYPE', None)
              if etype in _WIRE_ERRORS:
                to_wire = getattr(e, 'to_wire', None)
                reply['etype'] = etype
                reply['payload'] = to_wire() if to_wire else {}
              _send_frame(sock, reply)
        except (ConnectionError, EOFError, OSError):
          pass

    class Server(socketserver.ThreadingTCPServer):
      daemon_threads = True
      allow_reuse_address = True

    self._server = Server((host, port), Handler)
    self.host, self.port = self._server.server_address
    self._thread = threading.Thread(target=self._server.serve_forever,
                                    daemon=True)
    self._thread.start()

  def register(self, name: str, fn: Callable):
    """reference: rpc_register (rpc.py:401-417)"""
    if name in self._handlers:
      raise ValueError(f'handler {name!r} already registered')
    self._handlers[name] = fn

  def register_callee(self, name: str, callee: RpcCalleeBase):
    self.register(name, callee.call)

  def shutdown(self):
    self._server.shutdown()
    self._server.server_close()


class RpcClient:
  """Per-target connection pool + sync/async requests."""

  def __init__(self, max_workers: int = 8,
               secret: Optional[bytes] = None):
    self._pool = ThreadPoolExecutor(max_workers=max_workers)
    self._local = threading.local()
    self._addrs: Dict[int, Tuple[str, int]] = {}
    self._secret = secret if secret is not None else _env_secret()

  def add_target(self, rank: int, host: str, port: int):
    self._addrs[rank] = (host, port)

  @property
  def targets(self) -> List[int]:
    return sorted(self._addrs)

  def _conn(self, rank: int,
            connect_timeout: Optional[float] = None) -> socket.socket:
    conns = getattr(self._local, 'conns', None)
    if conns is None:
      conns = self._local.conns = {}
    if rank not in conns:
      # the caller's per-request timeout must bound the CONNECT too: a
      # blackholed peer (partition, no RST) would otherwise stall every
      # reconnecting probe for the full 180 s default, defeating the
      # heartbeat's seconds-scale detection promise
      s = socket.create_connection(self._addrs[rank],
                                   timeout=connect_timeout or 180)
      s.settimeout(180)   # per-request timeouts are applied in _attempt
      s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
      if self._secret is not None:
        # answer the server's HMAC challenge, then verify the server's
        # answer to OURS before any response frame is unpickled (see
        # module trust model). Short timeout on the nonce read: a
        # secret-less server sends no challenge, and without this the
        # config mismatch would hang for the full 180 s socket timeout
        # with a generic error. The caller's connect budget bounds the
        # handshake too — a heartbeat probe must not wait 10 s on a
        # wedged-but-accepting peer.
        s.settimeout(min(10, connect_timeout) if connect_timeout
                     else 10)
        try:
          nonce = _recv_exact(s, 32)
          my_nonce = _secrets.token_bytes(32)
          s.sendall(_hmac_of(self._secret, nonce) + my_nonce)
          proof = _recv_exact(s, 32)
        except socket.timeout:
          s.close()
          raise ConnectionError(
              'server did not complete the mutual HMAC handshake '
              'within 10s — secret configured on this client (via '
              f'{_SECRET_ENV} or secret=) but probably not on the '
              'server') from None
        except (ConnectionError, OSError):
          # e.g. the server rejected OUR answer (secret mismatch) and
          # closed mid-handshake; don't leak the half-open socket
          s.close()
          raise
        if not hmac.compare_digest(
            proof, _hmac_of(self._secret, my_nonce, role=b'server')):
          s.close()
          raise ConnectionError(
              'server failed the mutual HMAC handshake: it does not '
              'know the shared secret — refusing to deserialize its '
              'responses')
        s.settimeout(180)
      conns[rank] = s
    return conns[rank]

  def _drop_conn(self, rank: int):
    conns = getattr(self._local, 'conns', None)
    if conns and rank in conns:
      try:
        conns.pop(rank).close()
      except OSError:
        pass

  def _attempt(self, rank: int, func: str, args, kwargs,
               timeout: Optional[float]):
    """One request/response round trip on the pooled connection."""
    import time as _time

    from ..metrics import spans
    from ..utils.faults import fault_point
    t0 = _time.perf_counter()
    # one client span per round trip, carrying the current trace (or
    # this process's run_id) over the wire in the frame's ctx field —
    # the server adopts it for the handler, so client and server spans
    # of one request join on the same id
    sp = spans.begin('rpc.client.request', rank=rank, func=func)
    # the span closes in ONE place (the finally) so no raise — not even
    # from _drop_conn or a malformed response frame — can leak it;
    # each path records its outcome by rebinding end_kw first
    end_kw = {'ok': False, 'error': 'client'}
    try:
      try:
        fault_point('rpc.client.request')
        sock = self._conn(rank, connect_timeout=timeout)
        if timeout is not None:
          sock.settimeout(timeout)
        _send_frame(sock, {'func': func, 'args': args, 'kwargs': kwargs,
                           'ctx': {'trace': sp.trace,
                                   'span': sp.span_id}})
        resp = _recv_frame(sock)
        fault_point('rpc.client.response')
        if timeout is not None:
          sock.settimeout(180)
      except socket.timeout as e:
        # normalize to TimeoutError so retry_on and callers see one type
        end_kw = {'ok': False, 'error': 'timeout'}
        self._drop_conn(rank)
        raise TimeoutError(
            f'rpc to rank {rank} func {func!r} timed out after '
            f'{timeout}s') from e
      except BaseException as e:
        end_kw = {'ok': False, 'error': type(e).__name__}
        # a broken pooled connection must not poison the next attempt
        if isinstance(e, (ConnectionError, EOFError, OSError)):
          self._drop_conn(rank)
        raise
      if not resp['ok']:
        end_kw = {'ok': False, 'error': 'remote'}
        factory = _WIRE_ERRORS.get(resp.get('etype'))
        if factory is not None:
          # typed rejection: reconstruct it so callers can distinguish
          # 'back off and retry' (tenancy throttle) from a remote fault.
          # NOT in request_sync's retry_on — visible-backpressure layers
          # (tenancy.with_backpressure) own the wait
          end_kw = {'ok': False, 'error': str(resp.get('etype'))}
          raise factory(resp.get('payload') or {})
        raise RuntimeError(
            f'remote error from rank {rank}: {resp["error"]}')
      end_kw = {'ok': True}
      # SUCCESSFUL round trips feed the control/stream-plane latency
      # histogram — the p50/p99 every remote-batch consumer actually
      # pays per RPC. Failures (including ok=False remote errors, often
      # fast-failing) surface through resilience.* counters instead of
      # dragging the latency distribution down
      from .. import metrics
      metrics.observe('rpc.client.request_ms',
                      (_time.perf_counter() - t0) * 1e3)
      return resp['result']
    finally:
      spans.end(sp, **end_kw)

  def request_sync(self, rank: int, func: str, *args,
                   timeout: Optional[float] = None,
                   idempotent: bool = False,
                   retry_policy=None, **kwargs):
    """reference: rpc_request / _rpc_call sync path (rpc.py:422-447).

    ``timeout`` bounds each attempt (socket-level, seconds; the reference
    wraps every RPC in rpc_timeout, rpc.py:92-117). Failed attempts are
    retried — with exponential backoff + jitter under ``retry_policy``
    (default resilience.DEFAULT_RETRY_POLICY) — ONLY when the caller
    declares the callee ``idempotent=True``: a retry after a lost
    response re-executes the remote side effect, so non-idempotent
    calls get exactly one attempt and surface the first error.
    """
    from .resilience import DEFAULT_RETRY_POLICY, NO_RETRY
    if retry_policy is not None and not idempotent:
      raise ValueError(
          f'retry_policy passed for rpc {func!r} without idempotent=True '
          '— retrying a non-idempotent call can duplicate its side '
          'effect; declare the callee idempotent to opt into retry')
    policy = (retry_policy or DEFAULT_RETRY_POLICY) if idempotent \
        else NO_RETRY
    if timeout is None:
      timeout = policy.per_attempt_timeout
    return policy.run(
        self._attempt, rank, func, args, kwargs, timeout,
        retry_on=(ConnectionError, TimeoutError, OSError, EOFError),
        describe=f'rpc to rank {rank} func {func!r}')

  def request_async(self, rank: int, func: str, *args, **kwargs) -> Future:
    """reference: rpc_request_async (rpc.py:422-447)"""
    return self._pool.submit(self.request_sync, rank, func, *args,
                             **kwargs)

  def close(self):
    self._pool.shutdown(wait=False)
    conns = getattr(self._local, 'conns', {})
    for s in conns.values():
      try:
        s.close()
      except OSError:
        pass


class RpcDataPartitionRouter:
  """Round-robin workers serving each data partition
  (reference: rpc.py:316-334)."""

  def __init__(self, partition_to_workers: Dict[int, List[int]]):
    self._p2w = partition_to_workers
    self._next = {p: 0 for p in partition_to_workers}

  def get_to_worker(self, partition: int) -> int:
    workers = self._p2w[partition]
    i = self._next[partition]
    self._next[partition] = (i + 1) % len(workers)
    return workers[i]


class Barrier:
  """Server-hosted counting barrier (control-plane parity with the
  reference's role-scoped barrier, rpc.py:171-233)."""

  def __init__(self, world_size: int):
    self._world = world_size
    self._count = 0
    self._gen = 0
    self._cv = threading.Condition()
    self._values: Dict[int, Any] = {}
    self._arrived = set()

  def arrive(self, rank: int, value: Any = None, timeout: float = 180.0,
             phase: Optional[int] = None):
    """``phase`` (optional, monotonically increasing per caller) makes
    retries fully idempotent: a retry of an ALREADY-RELEASED phase
    returns immediately instead of being miscounted into the next
    generation (a retry can arrive late when only the response was
    lost)."""
    with self._cv:
      gen = self._gen
      if phase is not None and phase < gen:
        return dict(self._values)   # stale retry of a released phase
      if rank in self._arrived:
        # duplicate arrival within a generation (client retried after a
        # lost response): wait for the release, don't double-count
        if not self._cv.wait_for(lambda: self._gen > gen,
                                 timeout=timeout):
          raise TimeoutError('barrier timeout')
        return dict(self._values)
      self._arrived.add(rank)
      self._values[rank] = value
      self._count += 1
      if self._count == self._world:
        self._count = 0
        self._arrived.clear()
        self._gen += 1
        self._cv.notify_all()
      else:
        if not self._cv.wait_for(lambda: self._gen > gen,
                                 timeout=timeout):
          raise TimeoutError('barrier timeout')
      return dict(self._values)


def get_free_port(host: str = '127.0.0.1') -> int:
  with socket.socket() as s:
    s.bind((host, 0))
    return s.getsockname()[1]
