"""Distributed loaders: per-shard batches over the mesh.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_loader.py +
dist_neighbor_loader.py. The reference dispatches between collocated /
multiprocess / remote sampling workers feeding a channel; on TPU the
sampling step IS a compiled SPMD program on the same mesh as training, so
the default loader is the collocated equivalent: every iteration draws
P seed blocks (one per shard), runs the jitted distributed sample, and
yields a stacked `Data` whose leading axis is the partition ('g'/data)
axis. Mp/remote modes (host-process producers + channels) live in
dist_server/dist_client.
"""
from typing import List, Optional

import numpy as np

from ..loader import Data
from ..loader.node_loader import OverflowGuardMixin
from ..sampler import NodeSamplerInput
from .dist_dataset import DistDataset
from .dist_neighbor_sampler import DistNeighborSampler
from .tenancy import with_backpressure


def _split_input_type(input_nodes):
  """The framework-wide seed convention: ``('ntype', ids)`` for typed
  seeds, a bare array otherwise. ONE implementation for every loader
  front-end (collocated / mp / remote)."""
  if isinstance(input_nodes, tuple) and len(input_nodes) == 2 and \
      isinstance(input_nodes[0], str):
    return input_nodes[0], input_nodes[1]
  return None, input_nodes


def _norm_num_neighbors(num_neighbors):
  """Picklable copy: per-etype dict fanouts or a shared list."""
  return (dict(num_neighbors) if isinstance(num_neighbors, dict)
          else list(num_neighbors))


from ..typing import split_edge_type_seeds as _split_edge_type  # noqa: E402


class DistLoader(OverflowGuardMixin):
  """Reference: dist_loader.py:128-441 (collocated branch)."""

  def __init__(self, data: DistDataset, sampler: DistNeighborSampler,
               input_nodes, batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, collect_features: bool = True,
               seed: Optional[int] = None,
               seed_labels_only: bool = False,
               overflow_policy: str = 'raise'):
    self.data = data
    self.sampler = sampler
    self._init_overflow_policy(overflow_policy)
    # seed_labels_only: gather y for the per-shard seed block only
    # (supervision reads seed slots; skips a full-capacity sharded
    # label gather — the same knob as the local loaders)
    self.seed_labels_only = seed_labels_only
    self.input_type, input_nodes = _split_input_type(input_nodes)
    self.input_seeds = np.asarray(input_nodes).reshape(-1)
    self.batch_size = batch_size  # per shard
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.collect_features = collect_features
    self.seed = seed   # kept: DistScanTrainer derives its perm key here
    self._rng = np.random.default_rng(seed)
    self.num_partitions = data.num_partitions
    self._flight_epochs = 0   # epochs RECORDED (metrics/flight.py)

  def __len__(self):
    g = self.num_partitions * self.batch_size
    n = self._num_seeds()
    return n // g if self.drop_last else (n + g - 1) // g

  def _num_seeds(self):
    return self.input_seeds.shape[0]

  def state_dict(self):
    """Resumable iteration state (epoch-boundary granularity): the seed
    shuffle stream + the SPMD sampler's PRNG state (delegated)."""
    return {'rng_state': self._rng.bit_generator.state,
            'sampler': self.sampler.state_dict()}

  def load_state_dict(self, state):
    self._rng.bit_generator.state = state['rng_state']
    if 'sampler' in state:
      self.sampler.load_state_dict(state['sampler'])

  def _index_blocks(self):
    """Yield ([P, B] seed-index blocks, validity mask or None) per step.

    The final short block is padded by repeating indices (cyclically, so
    it works even with fewer total seeds than one global batch) but
    carries a validity mask: pad seeds produce no nodes/edges in the
    sampler and consumers can exclude them (no silent double-counting;
    the reference emits a short batch instead, dist_loader.py:284-295).
    """
    n = self._num_seeds()
    order = self._rng.permutation(n) if self.shuffle else np.arange(n)
    g = self.num_partitions * self.batch_size
    shape = (self.num_partitions, self.batch_size)
    for s in range(len(self)):
      idx = order[s * g:(s + 1) * g]
      n_valid = idx.shape[0]
      mask = None
      if n_valid < g:
        idx = np.concatenate([idx, np.resize(order, g - n_valid)])
        mask = (np.arange(g) < n_valid).reshape(shape)
      yield idx.reshape(shape), mask

  # -- epoch flight records (metrics/flight.py; docs/observability.md):
  # every per-step loader epoch appends ONE JSONL record to GLT_RUN_LOG
  # — steps yielded, wall, dispatch/feature/resilience counter deltas.
  # Pure host bookkeeping around the existing loop (the feature fields
  # come from the publish_stats fetch the epoch already pays).

  def _flight_begin(self):
    from ..metrics import flight, spans
    # one epoch.run span per epoch alongside the flight record: both
    # carry the process run_id, so a flight line, a scrape and the
    # epoch's span tree join on one id (docs/observability.md)
    return (flight.epoch_begin(),
            spans.begin('epoch.run', emitter=type(self).__name__))

  def _flight_end(self, tok, steps: int, completed: bool):
    from ..metrics import flight, spans
    flight_tok, span_tok = tok
    spans.end(span_tok, steps=steps, completed=completed)
    flight.end_for(self, flight_tok, steps=steps, completed=completed,
                   config=self._flight_config())

  def _flight_config(self) -> dict:
    """Static epoch configuration (fingerprinted in flight records)."""
    return dict(loader=type(self).__name__, batch_size=self.batch_size,
                shuffle=self.shuffle, drop_last=self.drop_last,
                num_partitions=self.num_partitions, seed=self.seed,
                num_neighbors=getattr(self.sampler, 'num_neighbors',
                                      None))

  def __iter__(self):
    from ..utils import step_annotation
    # overflow-policy state resolves BEFORE the span/flight bracket: a
    # raise from it must not leak the attached epoch.run span (which
    # would mis-parent every later span on this thread)
    guarded, recompute = self._overflow_epoch_start()
    tok = self._flight_begin()
    steps, completed = 0, False
    try:
      for i, (idx, mask) in enumerate(self._index_blocks()):
        with step_annotation('glt_dist_batch', i):
          inp = NodeSamplerInput(self.input_seeds[idx], self.input_type)
          if recompute:
            keys = self.sampler._next_keys()
            out = self.sampler.sample_from_nodes(inp, seed_mask=mask,
                                                 keys=keys)
            if self._batch_overflowed(out):
              self.overflow_recomputes += 1
              out = self._replay_sampler().sample_from_nodes(
                  inp, seed_mask=mask, keys=keys)
          else:
            out = self.sampler.sample_from_nodes(inp, seed_mask=mask)
            if guarded:
              self._accumulate_overflow(out)
          yield self._collate_fn(out)
          steps += 1
      completed = True
      if guarded and not recompute:
        self._finish_epoch_overflow()
    finally:
      # also on early break/close: the on-device int32 accumulator must
      # be drained per epoch or it eventually wraps. The publish is a
      # device fetch that can raise — the span/flight close must
      # survive it (inner finally), or the attached epoch span leaks
      try:
        self._publish_feature_stats()
      finally:
        self._flight_end(tok, steps, completed)

  def _publish_feature_stats(self):
    """Surface the feature-store hit/miss counters into utils.trace at
    EPOCH granularity — the counters accumulate on device across the
    epoch's batches (DistFeature threads them through its one dispatch),
    so this is the only device->host stats fetch of the feature path.
    Edge-feature stores publish too: their accumulators thread through
    every edge_attr gather and must be drained each epoch (an unread
    int32 accumulator would eventually wrap). The sampler's sharded
    LABEL stores are DistFeatures with the same accumulator and the
    same wrap hazard — they drain here too, under 'dist_label' so the
    headline dist_feature.* parity (per-step vs scanned, which skips
    label-stat accumulation by design) is untouched."""
    for f in self.data.feature_stores():
      f.publish_stats()
    for f in self.sampler.label_stores():
      f.publish_stats(prefix='dist_label')

  def _collate_fn(self, out):
    """SamplerOutput [P, ...] -> stacked Data/HeteroData (reference:
    dist_loader.py:331-441 parses the channel SampleMessage; here arrays
    are already device-resident and sharded)."""
    from .. import ops
    from ..utils.trace import record_dispatch
    # the collate's own program launches (edge_index stack; the feature
    # and label gathers count separately under 'dist_feature.get') —
    # together with 'dist_sample' this makes the per-step distributed
    # loop's >= 2 dispatches/step an assertable budget, not arithmetic
    record_dispatch('dist_collate')
    from ..loader import HeteroData
    from ..sampler import HeteroSamplerOutput
    x, y = self.sampler.collate(
        out, self.data.node_labels,
        label_cap=(self.batch_size if self.seed_labels_only else None))
    if isinstance(out, HeteroSamplerOutput):
      ei = {et: ops.stack2_batched(out.row[et], out.col[et])
            for et in out.row}
      edge_attr = None
      efs = getattr(self.data, 'edge_features', None)
      if out.edge is not None and efs:
        # batches key edges by the message-direction (reversed) type; the
        # ids belong to the ORIGINAL edge type's id space
        from ..typing import reverse_edge_type
        edge_attr = {}
        for et in out.edge:
          src_et = (reverse_edge_type(et) if self.data.edge_dir == 'out'
                    else et)
          if src_et in efs:
            edge_attr[et] = efs[src_et].get(out.edge[et])
        edge_attr = edge_attr or None
      return HeteroData(node=out.node, num_nodes=out.num_nodes,
                        edge_index=ei, edge_mask=out.edge_mask, x=x, y=y,
                        edge_ids=out.edge, edge_attr=edge_attr,
                        batch=out.batch,
                        batch_size=out.batch_size,
                        num_sampled_nodes=out.num_sampled_nodes,
                        num_sampled_edges=out.num_sampled_edges,
                        metadata=dict(out.metadata))
    edge_attr = None
    if out.edge is not None and \
        getattr(self.data, 'edge_features', None) is not None:
      edge_attr = self.data.edge_features.get(out.edge)
    ei = ops.stack2_batched(out.row, out.col)  # [P, 2, E]
    return Data(node=out.node, num_nodes=out.num_nodes,
                edge_index=ei, edge_mask=out.edge_mask, x=x, y=y,
                edge_ids=out.edge, edge_attr=edge_attr, batch=out.batch,
                batch_size=out.batch_size,
                num_sampled_nodes=out.num_sampled_nodes,
                num_sampled_edges=out.num_sampled_edges,
                metadata=dict(out.metadata))


class MpDistNeighborLoader:
  """Mp worker mode: sampling subprocesses feed a native shm channel, the
  loader drains it (reference: dist_loader.py:226-302 mp branch). Use when
  host-side seed prep/feature IO should overlap device training; the
  collocated mesh loader (DistNeighborLoader) is the device-fast path."""

  def __init__(self, data, num_neighbors, input_nodes,
               batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, num_workers: int = 2,
               channel_size: int = 1 << 26, seed: Optional[int] = None,
               max_worker_restarts: int = 2):
    from ..sampler import SamplingConfig, SamplingType
    # hetero seeds: ('paper', ids) — workers sample the typed engine and
    # stream HeteroData messages (message.hetero_output_to_message)
    input_type, input_nodes = _split_input_type(input_nodes)
    config = SamplingConfig(
        SamplingType.NODE, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        False, False, data.edge_dir, seed)
    self._setup(data,
                NodeSamplerInput(np.asarray(input_nodes).reshape(-1),
                                 input_type=input_type),
                config, channel_size, num_workers, seed,
                max_worker_restarts=max_worker_restarts)

  def _setup(self, data, sampler_input, config, channel_size, num_workers,
             seed, max_worker_restarts: int = 2):
    """Shared producer/channel wiring for the mp loader family."""
    from ..channel import QueueTimeoutError, ShmChannel
    from .dist_sampling_producer import DistMpSamplingProducer
    from .message import message_to_data
    self._message_to_data = message_to_data
    self._timeout_error = QueueTimeoutError
    self.channel = ShmChannel(shm_size=channel_size)
    self.producer = DistMpSamplingProducer(
        data, sampler_input, config, self.channel,
        num_workers=num_workers, seed=seed,
        max_worker_restarts=max_worker_restarts)
    self.producer.init()
    self._expected = self.producer.num_expected()
    # recv window between producer health checks: short enough that a
    # crashed worker is detected (and restarted) promptly, long enough
    # that the checks stay off the hot path
    self.health_check_interval_ms = 5000

  def __len__(self):
    return self._expected

  def __iter__(self):
    from ..metrics import flight, spans
    cfg = self.producer.config
    tok = flight.epoch_begin()
    # the epoch span is CURRENT while produce_all ships the epoch
    # commands, so worker spans (producer.epoch/batch) parent under it;
    # produce_all runs INSIDE the try — a raise there must still end
    # the attached span (and now also records the failed epoch)
    sp = spans.begin('epoch.run', emitter=type(self).__name__)
    received = 0
    try:
      self.producer.produce_all()
      while received < self._expected:
        try:
          msg = self.channel.recv(
              timeout_ms=self.health_check_interval_ms)
        except self._timeout_error:
          # crashed worker -> restart + bit-identical replay (raises
          # only once the producer's restart budget is exhausted),
          # rather than spinning on an empty channel forever
          self.producer.check_worker_health()
          if self.producer.is_all_sampling_completed() and \
              self.channel.empty():
            break
          continue
        received += 1
        yield self._message_to_data(msg)
    finally:
      spans.end(sp, steps=received,
                completed=received >= self._expected)
      flight.end_for(
          self, tok, steps=received,
          completed=received >= self._expected,
          config=dict(loader=type(self).__name__,
                      batch_size=cfg.batch_size, shuffle=cfg.shuffle,
                      num_neighbors=cfg.num_neighbors,
                      num_workers=self.producer.num_workers))

  def worker_metrics(self):
    """Merged metric snapshot across this loader's mp sampling workers
    (see DistMpSamplingProducer.worker_metrics); None before the first
    epoch-end publish."""
    return self.producer.worker_metrics()

  def shutdown(self):
    self.producer.shutdown()
    self.channel.close()


class MpDistLinkNeighborLoader(MpDistNeighborLoader):
  """Mp worker mode for LINK sampling: subprocesses run
  sample_from_edges (positives + negatives) and stream batches with
  edge_label_index/edge_label metadata over the shm channel (reference:
  the link branch of the sampling producers,
  dist_sampling_producer.py:106-140)."""

  def __init__(self, data, num_neighbors: List[int], edge_label_index,
               edge_label=None, neg_sampling=None, batch_size: int = 64,
               shuffle: bool = False, drop_last: bool = False,
               with_edge: bool = False, collect_features: bool = True,
               num_workers: int = 2, channel_size: int = 1 << 26,
               seed: Optional[int] = None):
    from ..sampler import (EdgeSamplerInput, SamplingConfig, SamplingType)
    # hetero seed edges: ((src_t, rel, dst_t), [2, E]) — the LinkLoader
    # tuple convention; workers run the typed link engine
    edge_type, edge_label_index = _split_edge_type(edge_label_index)
    ei = np.asarray(edge_label_index)
    config = SamplingConfig(
        SamplingType.LINK, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        neg_sampling is not None, False, data.edge_dir, seed)
    self._setup(data,
                EdgeSamplerInput(ei[0], ei[1], label=edge_label,
                                 input_type=edge_type,
                                 neg_sampling=neg_sampling),
                config, channel_size, num_workers, seed)


class _RemoteLoaderBase:
  """Shared remote (server-client) machinery: create one producer per
  server from a per-server sampler-input split, pull batches through
  the RemoteReceivingChannel, restart producers per epoch (reference:
  dist_loader.py:155-195 + dist_neighbor_loader.py remote branch).

  Resilience (docs/failure_model.md): a Heartbeat thread per server
  detects death in ~heartbeat_interval * heartbeat_miss seconds; a dead
  server's UNACKED seeds — its seed share minus the seeds of batches
  this loader already received (each batch message carries its seed ids
  in 'batch') — are redistributed across the surviving servers as fresh
  producers, so the epoch completes with every seed delivered exactly
  once. The server's worker_key idempotent-producer mechanism makes the
  re-requests safe. Degradations are counted in utils/trace.py
  ('resilience.failover', 'resilience.server_dead').

  This family is the PER-BATCH remote path (>= 2 RPC dispatches + host
  Python per step). For supervised homogeneous node classification the
  chunk-staged ``distributed.RemoteScanTrainer`` (docs/remote_scan.md)
  runs the same server-client topology at scanned speed — K-batch
  blocks, ceil(steps/K)+2 client dispatches, chunk-granular
  ack/failover — and is bit-identical to this path at shuffle=False.
  """

  #: Node loaders ack received seeds from each batch's 'batch' ids and
  #: can therefore fail over; link batches carry only local indices, so
  #: the link loader degrades to a hard error on server death.
  supports_failover = True

  def _tenant_kwargs(self) -> dict:
    """create_sampling_producer kwargs registering this loader's
    producers under its tenant — empty (wire-compatible with
    pre-tenancy servers) when no tenant is configured."""
    if getattr(self, '_tenant', None) is None:
      return {}
    return dict(tenant=self._tenant, priority=self._tenant_priority,
                weight=self._tenant_weight)

  def _note_throttle(self, rej):
    # remembered so an eventual idle-budget QueueTimeoutError names the
    # quota this tenant was last bouncing off (docs/multi_tenancy.md)
    self._last_throttle = rej

  def _setup_remote(self, config, per_server_inputs, worker_options):
    import dataclasses

    from ..channel import RemoteReceivingChannel
    from . import dist_client
    from .message import message_to_data
    from .resilience import Heartbeat
    self._message_to_data = message_to_data
    opts = worker_options
    self._opts = opts
    self._config = config
    self._tenant = getattr(opts, 'tenant', None) if opts else None
    self._tenant_priority = getattr(opts, 'tenant_priority', None) \
        if opts else None
    self._tenant_weight = getattr(opts, 'tenant_weight', None) \
        if opts else None
    self._bp_budget = getattr(opts, 'backpressure_budget', 120.0) \
        if opts else 120.0
    self._last_throttle = None   # last TenantRejection, for timeout context
    self.producer_ids = []
    self._expected = 0
    for i, (rank, part) in enumerate(zip(self.server_ranks,
                                         per_server_inputs)):
      # fold the SERVER index into the seed: same-ranked mp workers on
      # different servers would otherwise derive identical worker
      # seeds and draw identical negative edges per batch index
      # (negatives depend only on the graph + key)
      cfg_i = dataclasses.replace(
          config, seed=(config.seed or 0) * 7919 + i)
      pid = with_backpressure(
          lambda rank=rank, part=part, cfg_i=cfg_i:
          dist_client.request_server(
              rank, 'create_sampling_producer', part, cfg_i,
              opts.num_workers if opts else 1,
              worker_key=(opts.worker_key if opts else None),
              **self._tenant_kwargs()),
          describe=f'create_sampling_producer rank {rank}',
          budget_s=self._bp_budget, tenant=self._tenant,
          on_reject=self._note_throttle)
      self.producer_ids.append(pid)
      # the producer's own count: its mp workers split the seed share and
      # each rounds up, so ceil(n/batch_size) would undercount here
      exp = dist_client.request_server(
          rank, 'producer_num_expected', pid, idempotent=True)
      self._pair_expected = getattr(self, '_pair_expected', {})
      self._pair_expected[(rank, pid)] = exp
      self._expected += exp
    self.channel = RemoteReceivingChannel(
        self.server_ranks, self.producer_ids,
        prefetch_size=(opts.prefetch_size if opts else 4))
    self._dist_client = dist_client
    # -- resilience state ---------------------------------------------------
    # per-(rank, pid) seed shares for failover accounting (None when the
    # input carries no ackable seeds, e.g. link mode)
    self._pair_parts = {}
    for rank, pid, part in zip(self.server_ranks, self.producer_ids,
                               per_server_inputs):
      seeds = getattr(part, 'node', part if not hasattr(part, 'row')
                      else None)
      self._pair_parts[(rank, pid)] = (
          np.asarray(seeds).reshape(-1) if seeds is not None else None)
    self._dead_ranks = {}        # rank -> cause, sticky across epochs
    self._pair_batches = {}      # (rank, pid) -> batches received
    self._live_pairs = set()     # this epoch's pulling (rank, pid)s
    self._fo_producers = []      # this epoch's replacement (rank, pid)s
    self._fo_seq = 0
    self._epoch = 0
    self._heartbeat_miss = opts.heartbeat_miss if opts else 3
    self._heartbeat_interval = opts.heartbeat_interval if opts else 1.0
    self._failover_enabled = (opts.failover if opts else True) and \
        self.supports_failover
    self._idle_budget = opts.rpc_timeout if opts else 180.0
    probe_timeout = max(self._heartbeat_interval, 2.0)

    def probe(rank):
      from .resilience import NO_RETRY
      dist_client.request_server(rank, 'heartbeat',
                                 timeout=probe_timeout,
                                 idempotent=True, retry_policy=NO_RETRY)

    self._heartbeat = Heartbeat(
        self.server_ranks, probe, interval=self._heartbeat_interval,
        miss_threshold=self._heartbeat_miss)

  def _resolve_ranks(self, worker_options):
    opts = worker_options
    ranks = opts.server_rank if opts and opts.server_rank is not None \
        else [0]
    if isinstance(ranks, int):
      ranks = [ranks]
    self.server_ranks = list(ranks)

  def __len__(self):
    return self._expected

  # -- failover machinery ---------------------------------------------------

  def _ack(self, rank, pid, msg):
    """Record which seeds a received batch covered (homo: 'batch' ids;
    hetero: 'batch.<input_type>'). Unackable messages are ignored —
    failover then treats their seeds as undelivered (safe: duplicates
    are impossible, the pair's producer is abandoned before replay)."""
    self._pair_batches[(rank, pid)] = \
        self._pair_batches.get((rank, pid), 0) + 1
    acked = self._acked.get((rank, pid))
    if acked is None:
      acked = self._acked[(rank, pid)] = set()
    bs = msg.get('#META.batch_size')
    ids = msg.get('batch')
    if ids is None and '#META.input_type' in msg:
      t = bytes(np.asarray(msg['#META.input_type'])).decode()
      ids = msg.get(f'batch.{t}')
    if ids is None:
      return
    ids = np.asarray(ids).reshape(-1)
    if bs is not None:
      ids = ids[:int(np.asarray(bs).reshape(-1)[0])]
    acked.update(int(i) for i in ids)

  def _handle_dead_pair(self, rank, pid, cause):
    """Declare (rank, pid) dead and redistribute its unacked seeds to
    surviving servers. Returns buffered messages that were drained while
    abandoning the pair (already acked; caller yields them). Idempotent
    per pair per epoch."""
    if (rank, pid) in self._handled_pairs:
      return []
    # feasibility FIRST, before any state mutation: when this loader
    # cannot fail over, the rank must not be marked sticky-dead (a
    # transient blip would then poison every later epoch) and buffered
    # batches must not be drained onto the raise path
    part = self._pair_parts.get((rank, pid))
    if not self.supports_failover or part is None:
      raise RuntimeError(
          f'sampling server rank {rank} died mid-epoch ({cause}) and '
          'this loader cannot fail over: its batches carry no seed '
          'provenance to ack (link mode) — restart the epoch')
    if not self._failover_enabled:
      raise RuntimeError(
          f'sampling server rank {rank} died mid-epoch ({cause}) and '
          'failover is disabled (RemoteDistSamplingWorkerOptions'
          '.failover=False)')
    self._handled_pairs.add((rank, pid))
    # the failover span is the epoch tree's resilience annotation: the
    # degraded chunk of work — dead rank, cause, redistributed seed
    # count — hangs off this epoch's epoch.run span, and the replacement
    # producers' RPCs (and their workers' spans) parent under it
    from ..metrics import spans
    fo_span = spans.begin('loader.failover', rank=rank,
                          cause=str(cause)[:200])
    try:
      return self._handle_dead_pair_spanned(rank, pid, cause, part,
                                            fo_span)
    except BaseException as e:
      fo_span.attrs['error'] = f'{type(e).__name__}: {e}'
      raise
    finally:
      spans.end(fo_span)

  def _handle_dead_pair_spanned(self, rank, pid, cause, part, fo_span):
    from ..utils import trace
    self._live_pairs.discard((rank, pid))
    self._dead_ranks[rank] = cause
    self._heartbeat.mark_dead(rank, cause)
    self.channel.abandon(rank, pid)
    # ack everything already buffered from ANY pair before computing the
    # unacked set — in-flight batches of the dying server must not be
    # re-requested (they were delivered, just not consumed yet)
    buffered = self.channel.drain_now()
    for r2, p2, m in buffered:
      self._ack(r2, p2, m)
    acked = self._acked.get((rank, pid), set())
    unacked = part[~np.isin(part, np.fromiter(acked, dtype=part.dtype,
                                              count=len(acked)))] \
        if len(acked) else part
    survivors = [r for r in self.server_ranks
                 if r not in self._dead_ranks]
    if not survivors:
      raise RuntimeError(
          f'all sampling servers dead (last: rank {rank}: {cause}) — '
          'cannot complete the epoch')
    trace.counter_inc('resilience.failover')
    trace.counter_inc('resilience.failover_seeds', int(unacked.shape[0]))
    fo_span.attrs.update(seeds=int(unacked.shape[0]),
                         survivors=list(survivors))
    import logging
    logging.getLogger('graphlearn_tpu.loader').warning(
        'server rank %d dead (%s): redistributing %d unacked seeds '
        'across surviving servers %s', rank, cause, unacked.shape[0],
        survivors)
    if unacked.shape[0] == 0:
      return buffered
    import dataclasses
    from ..sampler import NodeSamplerInput as NSI
    new_expected = 0
    splits = np.array_split(unacked, len(survivors))
    for r2, sub in zip(survivors, splits):
      if sub.shape[0] == 0:
        continue
      self._fo_seq += 1
      base_key = (self._opts.worker_key
                  if self._opts and self._opts.worker_key else 'fo')
      key = (f'{base_key}/fo/e{self._epoch}/'
             f'd{rank}/s{r2}/{self._fo_seq}')
      part2 = (NSI(sub, self.input_type)
               if getattr(self, 'input_type', None) is not None else sub)
      cfg2 = dataclasses.replace(
          self._config,
          seed=(self._config.seed or 0) * 7919 + 104729 + self._fo_seq)
      # worker_key makes the create re-request-safe, so it may retry —
      # a transient hiccup on the SURVIVOR must not abort the very
      # failover meant to save the epoch. start_new_epoch_sampling has
      # no such dedup (a retried start double-produces), so it stays
      # single-attempt.
      pid2 = with_backpressure(
          lambda r2=r2, part2=part2, cfg2=cfg2, key=key:
          self._dist_client.request_server(
              r2, 'create_sampling_producer', part2, cfg2,
              self._opts.num_workers if self._opts else 1, worker_key=key,
              idempotent=True, **self._tenant_kwargs()),
          describe=f'failover producer rank {r2}',
          budget_s=self._bp_budget, tenant=self._tenant,
          on_reject=self._note_throttle)
      repl_expected = self._dist_client.request_server(
          r2, 'producer_num_expected', pid2, idempotent=True)
      self._dist_client.request_server(r2, 'start_new_epoch_sampling',
                                       pid2)
      self._pair_parts[(r2, pid2)] = sub
      self._pair_expected[(r2, pid2)] = repl_expected
      self._fo_producers.append((r2, pid2))
      self._live_pairs.add((r2, pid2))
      self.channel.add_producer(r2, pid2)
      new_expected += repl_expected
    # keep len(self) truthful mid-epoch: this epoch now delivers the
    # dead pair's already-received batches + the replacements' counts
    # instead of the dead pair's original expectation (re-chunking can
    # shift partial-batch counts when bs does not divide the shares)
    dead_expected = self._pair_expected.get((rank, pid))
    if dead_expected is not None:
      delivered = self._pair_batches.get((rank, pid), 0)
      self._expected += new_expected - (dead_expected - delivered)
    return buffered

  def __iter__(self):
    from ..metrics import flight, spans
    # Ordering matters: kill any previous epoch's pullers BEFORE
    # restarting the server producers (a stale puller would consume
    # new-epoch messages into its dead queue), and only then start the
    # new pullers.
    self.channel.stop(join=True)
    self._epoch += 1
    cfg = self._config
    tok = flight.epoch_begin()
    # the epoch span stays current across _epoch_messages, so the
    # start_new_epoch_sampling RPCs (and through them the servers'
    # producer workers) and any failover spans parent under it — one
    # joinable tree per epoch across client, server and producers
    sp = spans.begin('epoch.run', emitter=type(self).__name__,
                     epoch=self._epoch)
    received, completed = 0, False
    try:
      for data in self._epoch_messages():
        yield data
        received += 1
      completed = True
    finally:
      spans.end(sp, steps=received, completed=completed,
                dead_ranks=len(self._dead_ranks))
      # the flight record is the postmortem trail for THIS epoch:
      # failover/retry counter deltas, batches delivered, wall — one
      # JSONL line (docs/observability.md), nothing on the hot path
      # (cfg resolved before the brackets opened: nothing between the
      # span close above and the record below may raise)
      flight.end_for(
          self, tok, epoch=self._epoch, steps=received,
          completed=completed,
          config=dict(loader=type(self).__name__,
                      batch_size=cfg.batch_size, shuffle=cfg.shuffle,
                      num_neighbors=cfg.num_neighbors,
                      servers=list(self.server_ranks)),
          extra={'expected': self._expected,
                 'dead_ranks': {str(r): c for r, c in
                                self._dead_ranks.items()}})

  def _epoch_messages(self):
    import time as _time

    from ..channel import QueueTimeoutError
    from ..channel.remote_channel import PeerDeadError
    self._acked = {}
    self._pair_batches = {}
    self._handled_pairs = set()
    # failover producers are per-epoch: release last epoch's now (and
    # drop their seed-share records — a stale share must never be
    # redistributed into a later epoch)
    for rank, pid in self._fo_producers:
      self._pair_parts.pop((rank, pid), None)
      self._pair_expected.pop((rank, pid), None)
      try:
        self._dist_client.request_server(rank,
                                         'destroy_sampling_producer', pid)
      except (RuntimeError, ConnectionError, OSError):
        pass
    self._fo_producers = []
    # restore the undegraded expectation; this epoch's failovers (if
    # any) re-adjust it as they happen
    self._expected = sum(
        self._pair_expected.get(p, 0)
        for p in zip(self.server_ranks, self.producer_ids))
    started, start_dead = [], []
    for rank, pid in zip(self.server_ranks, self.producer_ids):
      if rank in self._dead_ranks:
        start_dead.append((rank, pid))
        continue
      try:
        self._dist_client.request_server(rank, 'start_new_epoch_sampling',
                                         pid)
        started.append((rank, pid))
      except (ConnectionError, TimeoutError, OSError) as e:
        if not (self._failover_enabled and self.supports_failover):
          # no recovery path: surface the failure without sticky-marking
          # the rank, so a recovered server works on the next attempt
          raise
        start_dead.append((rank, pid))
        self._dead_ranks[rank] = repr(e)
    if not started:
      raise RuntimeError('no live sampling server to start the epoch: '
                         f'dead={self._dead_ranks}')
    self._live_pairs = set(started)
    self.channel.start_pairs(started)
    self._heartbeat.start()
    # ranks that died in an earlier epoch (or refused the epoch start):
    # their whole seed share is unacked — fail it over immediately
    for rank, pid in start_dead:
      for r2, p2, m in self._handle_dead_pair(
          rank, pid, self._dead_ranks.get(rank, 'dead at epoch start')):
        yield self._message_to_data(m)
    idle_since = _time.monotonic()
    while True:
      try:
        rank, pid, msg = self.channel.recv_with_meta(timeout_ms=5000)
      except StopIteration:
        return
      except PeerDeadError as e:
        for r2, p2, m in self._handle_dead_pair(e.rank, e.producer_id,
                                                e.cause):
          yield self._message_to_data(m)
        continue
      except QueueTimeoutError as qte:
        # quiet window: consult liveness before waiting further — a
        # partitioned/hung server never RSTs, the heartbeat is the only
        # signal (detection in seconds vs the 180 s socket timeout)
        handled = False
        for rank, cause in self._heartbeat.dead_ranks().items():
          for (r2, p2) in [pr for pr in list(self._live_pairs)
                           if pr[0] == rank and
                           pr not in self._handled_pairs]:
            for r3, p3, m in self._handle_dead_pair(r2, p2, cause):
              yield self._message_to_data(m)
            handled = True
        if handled:
          idle_since = _time.monotonic()
          continue
        if _time.monotonic() - idle_since > self._idle_budget:
          # a starved tenant's stall must name WHO hit WHAT limit, not
          # read as an anonymous timeout (docs/multi_tenancy.md)
          last = getattr(self, '_last_throttle', None)
          qte.with_context(tenant=getattr(self, '_tenant', None),
                           quota=getattr(last, 'quota', None))
          raise
        continue
      idle_since = _time.monotonic()
      self._ack(rank, pid, msg)
      yield self._message_to_data(msg)

  def shutdown(self):
    self._heartbeat.stop()
    self.channel.stop()
    for rank, pid in (list(zip(self.server_ranks, self.producer_ids)) +
                      list(self._fo_producers)):
      if rank in self._dead_ranks:
        continue
      try:
        self._dist_client.request_server(rank,
                                         'destroy_sampling_producer', pid)
      except (RuntimeError, ConnectionError, OSError):
        pass


class RemoteDistNeighborLoader(_RemoteLoaderBase):
  """Remote (server-client) NODE loading: producers run on sampling
  servers, batches stream back over RPC; hetero seeds as
  ('ntype', ids)."""

  def __init__(self, num_neighbors, input_nodes,
               batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, worker_options=None,
               seed: Optional[int] = None):
    from ..sampler import NodeSamplerInput as NSI
    from ..sampler import SamplingConfig, SamplingType
    self._resolve_ranks(worker_options)
    # hetero seeds: ('paper', ids) — the server's mp workers run the
    # typed engine and stream HeteroData messages back (round 5); ship
    # typed NodeSamplerInputs so the tuple convention (type FIRST)
    # never hits CastMixin's positional cast
    input_type, input_nodes = _split_input_type(input_nodes)
    # stored for failover: replacement producers must re-ship TYPED
    # seeds, or the server-side producer rejects them for hetero graphs
    self.input_type = input_type
    config = SamplingConfig(
        SamplingType.NODE, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        False, False, 'out', seed)
    seeds = np.asarray(input_nodes).reshape(-1)
    # split seeds across servers; each server samples its share
    splits = np.array_split(seeds, len(self.server_ranks))
    parts = [NSI(p, input_type) if input_type is not None else p
             for p in splits]
    self._setup_remote(config, parts, worker_options)


class RemoteDistLinkNeighborLoader(_RemoteLoaderBase):
  """Remote (server-client) LINK loading: seed edges split across the
  sampling servers, whose mp workers draw negatives + run the (typed)
  link engine; batches stream back with edge_label metadata. Hetero
  seed edges as ((src_t, rel, dst_t), [2, E])."""

  # link batches expose only batch-local seed indices — no global edge
  # ids to ack — so a dead server is a hard error here, not a failover
  supports_failover = False

  def __init__(self, num_neighbors, edge_label_index, edge_label=None,
               neg_sampling=None, batch_size: int = 64,
               shuffle: bool = False, drop_last: bool = False,
               with_edge: bool = False, collect_features: bool = True,
               worker_options=None, seed: Optional[int] = None):
    from ..sampler import (EdgeSamplerInput, NegativeSampling,
                           SamplingConfig, SamplingType)
    self._resolve_ranks(worker_options)
    edge_type, edge_label_index = _split_edge_type(edge_label_index)
    ei = np.asarray(edge_label_index)
    label = (np.asarray(edge_label).reshape(-1)
             if edge_label is not None else None)
    ns = (NegativeSampling.cast(neg_sampling)
          if neg_sampling is not None else None)
    config = SamplingConfig(
        SamplingType.LINK, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        ns is not None, False, 'out', seed)
    nsrv = len(self.server_ranks)
    row_s = np.array_split(ei[0].reshape(-1), nsrv)
    col_s = np.array_split(ei[1].reshape(-1), nsrv)
    lab_s = (np.array_split(label, nsrv) if label is not None
             else [None] * nsrv)
    parts = [EdgeSamplerInput(r, c, label=lb, input_type=edge_type,
                              neg_sampling=ns)
             for r, c, lb in zip(row_s, col_s, lab_s)]
    self._setup_remote(config, parts, worker_options)


class DistLinkNeighborLoader(DistLoader):
  """Distributed link-prediction loader: per-shard seed-edge blocks ->
  one SPMD link-sampling program (reference:
  distributed/dist_link_neighbor_loader.py:1-158; the sampling itself is
  dist_neighbor_sampler.py:369-496).

  Args:
    edge_label_index: [2, E] seed edges, or (edge_type, [2, E]) for
      hetero.
    edge_label: optional [E] labels for the positives.
    neg_sampling: optional NegativeSampling ('binary'/'triplet').
  """

  def __init__(self, data: DistDataset, num_neighbors, edge_label_index,
               edge_label=None, batch_size: int = 64,
               shuffle: bool = False, drop_last: bool = True,
               neg_sampling=None, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               node_budget: Optional[int] = None, mesh=None,
               with_weight: bool = False, dedup: str = 'sort',
               bucket_frac=2.0, neg_strict: bool = False,
               frontier_caps=None, overflow_policy: str = 'raise'):
    if mesh is None:
      from .dist_context import get_context
      ctx = get_context()
      mesh = ctx.mesh if ctx else None
    input_type, edge_label_index = _split_edge_type(edge_label_index)
    ei = np.asarray(edge_label_index)
    self.seed_rows = ei[0].reshape(-1)
    self.seed_cols = ei[1].reshape(-1)
    self.edge_label = (np.asarray(edge_label).reshape(-1)
                       if edge_label is not None else None)
    self.neg_sampling = neg_sampling
    # frontier_caps: calibrate against the effective PER-SHARD seed
    # width — the engine derives it internally from batch_size and
    # neg_sampling (calibrate.link_seed_width); pass caps estimated at
    # that width
    sampler = DistNeighborSampler(
        data.graph, num_neighbors, mesh,
        dist_feature=data.node_features, with_edge=with_edge, seed=seed,
        node_budget=node_budget, collect_features=collect_features,
        with_weight=with_weight, dedup=dedup, bucket_frac=bucket_frac,
        neg_strict=neg_strict, frontier_caps=frontier_caps)
    super().__init__(data, sampler, np.zeros(0, np.int64), batch_size,
                     shuffle, drop_last, collect_features, seed,
                     overflow_policy=overflow_policy)
    self.input_type = input_type  # EdgeType for hetero link sampling

  def _num_seeds(self):
    return self.seed_rows.shape[0]

  def __iter__(self):
    from ..sampler import EdgeSamplerInput
    # overflow-policy prologue BEFORE the span/flight bracket: a raise
    # from it must not leak the attached epoch.run span (same ordering
    # as DistLoader.__iter__)
    guarded, recompute = self._overflow_epoch_start()
    tok = self._flight_begin()
    steps, completed = 0, False
    try:
      for idx, mask in self._index_blocks():
        inputs = EdgeSamplerInput(
            self.seed_rows[idx], self.seed_cols[idx],
            label=(self.edge_label[idx]
                   if self.edge_label is not None else None),
            input_type=self.input_type,
            neg_sampling=self.neg_sampling)
        if recompute:
          keys = self.sampler._next_keys()
          out = self.sampler.sample_from_edges(inputs, seed_mask=mask,
                                               keys=keys)
          if self._batch_overflowed(out):
            self.overflow_recomputes += 1
            out = self._replay_sampler().sample_from_edges(
                inputs, seed_mask=mask, keys=keys)
        else:
          out = self.sampler.sample_from_edges(inputs, seed_mask=mask)
          if guarded:
            self._accumulate_overflow(out)
        yield self._collate_fn(out)
        steps += 1
      completed = True
      if guarded and not recompute:
        self._finish_epoch_overflow()
    finally:
      # device-fetch publish can raise: close span + flight regardless
      try:
        self._publish_feature_stats()
      finally:
        self._flight_end(tok, steps, completed)


class DistSubGraphLoader(DistLoader):
  """Distributed induced-subgraph loader (reference:
  distributed/dist_subgraph_loader.py:1-93; sampling is
  dist_neighbor_sampler.py:499-559). ``num_neighbors=None`` induces over
  the seed set alone; otherwise seeds are hop-expanded first."""

  def __init__(self, data: DistDataset, num_neighbors, input_nodes,
               batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               max_degree: Optional[int] = None, mesh=None,
               bucket_frac=2.0):
    if mesh is None:
      from .dist_context import get_context
      ctx = get_context()
      mesh = ctx.mesh if ctx else None
    sampler = DistNeighborSampler(
        data.graph, num_neighbors, mesh,
        dist_feature=data.node_features, with_edge=with_edge, seed=seed,
        collect_features=collect_features, bucket_frac=bucket_frac)
    super().__init__(data, sampler, input_nodes, batch_size, shuffle,
                     drop_last, collect_features, seed)
    self.max_degree = max_degree

  def __iter__(self):
    tok = self._flight_begin()
    steps, completed = 0, False
    try:
      for idx, mask in self._index_blocks():
        out = self.sampler.subgraph(self.input_seeds[idx],
                                    seed_mask=mask,
                                    max_degree=self.max_degree)
        yield self._collate_fn(out)
        steps += 1
      completed = True
    finally:
      # device-fetch publish can raise: close span + flight regardless
      try:
        self._publish_feature_stats()
      finally:
        self._flight_end(tok, steps, completed)


class DistNeighborLoader(DistLoader):
  """Reference: dist_neighbor_loader.py:104-112."""

  def __init__(self, data: DistDataset, num_neighbors: List[int],
               input_nodes, batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               node_budget: Optional[int] = None, mesh=None,
               with_weight: bool = False, dedup: str = 'sort',
               seed_labels_only: bool = False, bucket_frac=2.0,
               frontier_caps=None, overflow_policy: str = 'raise'):
    if mesh is None:
      from .dist_context import get_context
      ctx = get_context()
      mesh = ctx.mesh if ctx else None
    sampler = DistNeighborSampler(
        data.graph, num_neighbors, mesh,
        dist_feature=data.node_features, with_edge=with_edge, seed=seed,
        node_budget=node_budget, collect_features=collect_features,
        with_weight=with_weight, dedup=dedup, bucket_frac=bucket_frac,
        frontier_caps=frontier_caps)
    super().__init__(data, sampler, input_nodes, batch_size, shuffle,
                     drop_last, collect_features, seed,
                     seed_labels_only=seed_labels_only,
                     overflow_policy=overflow_policy)
