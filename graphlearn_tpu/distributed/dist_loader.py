"""Distributed loaders: per-shard batches over the mesh.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_loader.py +
dist_neighbor_loader.py. The reference dispatches between collocated /
multiprocess / remote sampling workers feeding a channel; on TPU the
sampling step IS a compiled SPMD program on the same mesh as training, so
the default loader is the collocated equivalent: every iteration draws
P seed blocks (one per shard), runs the jitted distributed sample, and
yields a stacked `Data` whose leading axis is the partition ('g'/data)
axis. Mp/remote modes (host-process producers + channels) live in
dist_server/dist_client.
"""
from typing import List, Optional

import numpy as np

from ..loader import Data
from ..sampler import NodeSamplerInput
from .dist_dataset import DistDataset
from .dist_neighbor_sampler import DistNeighborSampler


class DistLoader:
  """Reference: dist_loader.py:128-441 (collocated branch)."""

  def __init__(self, data: DistDataset, sampler: DistNeighborSampler,
               input_nodes, batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, collect_features: bool = True,
               seed: Optional[int] = None):
    self.data = data
    self.sampler = sampler
    self.input_seeds = np.asarray(input_nodes).reshape(-1)
    self.batch_size = batch_size  # per shard
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.collect_features = collect_features
    self._rng = np.random.default_rng(seed)
    self.num_partitions = data.num_partitions

  def __len__(self):
    g = self.num_partitions * self.batch_size
    n = self.input_seeds.shape[0]
    return n // g if self.drop_last else (n + g - 1) // g

  def __iter__(self):
    order = (self._rng.permutation(self.input_seeds.shape[0])
             if self.shuffle else np.arange(self.input_seeds.shape[0]))
    g = self.num_partitions * self.batch_size
    n_steps = len(self)
    for s in range(n_steps):
      idx = order[s * g:(s + 1) * g]
      if idx.shape[0] < g:  # pad the final global batch (repeat seeds)
        idx = np.concatenate([idx, order[:g - idx.shape[0]]])
      seeds = self.input_seeds[idx].reshape(self.num_partitions,
                                            self.batch_size)
      out = self.sampler.sample_from_nodes(NodeSamplerInput(seeds))
      yield self._collate_fn(out)

  def _collate_fn(self, out) -> Data:
    """SamplerOutput [P, ...] -> stacked Data (reference: dist_loader.py:
    331-441 parses the channel SampleMessage; here arrays are already
    device-resident and sharded)."""
    import jax.numpy as jnp
    x, y = self.sampler.collate(
        out, self.data.node_labels if self.data.node_labels is not None
        else None)
    ei = jnp.stack([out.row, out.col], axis=1)  # [P, 2, E]
    return Data(node=out.node, num_nodes=out.num_nodes,
                edge_index=ei, edge_mask=out.edge_mask, x=x, y=y,
                edge_ids=out.edge, batch=out.batch,
                batch_size=out.batch_size,
                num_sampled_nodes=out.num_sampled_nodes,
                num_sampled_edges=out.num_sampled_edges,
                metadata=dict(out.metadata))


class DistNeighborLoader(DistLoader):
  """Reference: dist_neighbor_loader.py:104-112."""

  def __init__(self, data: DistDataset, num_neighbors: List[int],
               input_nodes, batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               node_budget: Optional[int] = None, mesh=None):
    if mesh is None:
      from .dist_context import get_context
      ctx = get_context()
      mesh = ctx.mesh if ctx else None
    sampler = DistNeighborSampler(
        data.graph, num_neighbors, mesh,
        dist_feature=data.node_features, with_edge=with_edge, seed=seed,
        node_budget=node_budget, collect_features=collect_features)
    super().__init__(data, sampler, input_nodes, batch_size, shuffle,
                     drop_last, collect_features, seed)
