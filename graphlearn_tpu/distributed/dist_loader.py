"""Distributed loaders: per-shard batches over the mesh.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_loader.py +
dist_neighbor_loader.py. The reference dispatches between collocated /
multiprocess / remote sampling workers feeding a channel; on TPU the
sampling step IS a compiled SPMD program on the same mesh as training, so
the default loader is the collocated equivalent: every iteration draws
P seed blocks (one per shard), runs the jitted distributed sample, and
yields a stacked `Data` whose leading axis is the partition ('g'/data)
axis. Mp/remote modes (host-process producers + channels) live in
dist_server/dist_client.
"""
from typing import List, Optional

import numpy as np

from ..loader import Data
from ..loader.node_loader import OverflowGuardMixin
from ..sampler import NodeSamplerInput
from .dist_dataset import DistDataset
from .dist_neighbor_sampler import DistNeighborSampler


def _split_input_type(input_nodes):
  """The framework-wide seed convention: ``('ntype', ids)`` for typed
  seeds, a bare array otherwise. ONE implementation for every loader
  front-end (collocated / mp / remote)."""
  if isinstance(input_nodes, tuple) and len(input_nodes) == 2 and \
      isinstance(input_nodes[0], str):
    return input_nodes[0], input_nodes[1]
  return None, input_nodes


def _norm_num_neighbors(num_neighbors):
  """Picklable copy: per-etype dict fanouts or a shared list."""
  return (dict(num_neighbors) if isinstance(num_neighbors, dict)
          else list(num_neighbors))


from ..typing import split_edge_type_seeds as _split_edge_type  # noqa: E402


class DistLoader(OverflowGuardMixin):
  """Reference: dist_loader.py:128-441 (collocated branch)."""

  def __init__(self, data: DistDataset, sampler: DistNeighborSampler,
               input_nodes, batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, collect_features: bool = True,
               seed: Optional[int] = None,
               seed_labels_only: bool = False,
               overflow_policy: str = 'raise'):
    self.data = data
    self.sampler = sampler
    self._init_overflow_policy(overflow_policy)
    # seed_labels_only: gather y for the per-shard seed block only
    # (supervision reads seed slots; skips a full-capacity sharded
    # label gather — the same knob as the local loaders)
    self.seed_labels_only = seed_labels_only
    self.input_type, input_nodes = _split_input_type(input_nodes)
    self.input_seeds = np.asarray(input_nodes).reshape(-1)
    self.batch_size = batch_size  # per shard
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.collect_features = collect_features
    self._rng = np.random.default_rng(seed)
    self.num_partitions = data.num_partitions

  def __len__(self):
    g = self.num_partitions * self.batch_size
    n = self._num_seeds()
    return n // g if self.drop_last else (n + g - 1) // g

  def _num_seeds(self):
    return self.input_seeds.shape[0]

  def state_dict(self):
    """Resumable iteration state (epoch-boundary granularity): the seed
    shuffle stream + the SPMD sampler's PRNG state (delegated)."""
    return {'rng_state': self._rng.bit_generator.state,
            'sampler': self.sampler.state_dict()}

  def load_state_dict(self, state):
    self._rng.bit_generator.state = state['rng_state']
    if 'sampler' in state:
      self.sampler.load_state_dict(state['sampler'])

  def _index_blocks(self):
    """Yield ([P, B] seed-index blocks, validity mask or None) per step.

    The final short block is padded by repeating indices (cyclically, so
    it works even with fewer total seeds than one global batch) but
    carries a validity mask: pad seeds produce no nodes/edges in the
    sampler and consumers can exclude them (no silent double-counting;
    the reference emits a short batch instead, dist_loader.py:284-295).
    """
    n = self._num_seeds()
    order = self._rng.permutation(n) if self.shuffle else np.arange(n)
    g = self.num_partitions * self.batch_size
    shape = (self.num_partitions, self.batch_size)
    for s in range(len(self)):
      idx = order[s * g:(s + 1) * g]
      n_valid = idx.shape[0]
      mask = None
      if n_valid < g:
        idx = np.concatenate([idx, np.resize(order, g - n_valid)])
        mask = (np.arange(g) < n_valid).reshape(shape)
      yield idx.reshape(shape), mask

  def __iter__(self):
    from ..utils import step_annotation
    guarded, recompute = self._overflow_epoch_start()
    for i, (idx, mask) in enumerate(self._index_blocks()):
      with step_annotation('glt_dist_batch', i):
        inp = NodeSamplerInput(self.input_seeds[idx], self.input_type)
        if recompute:
          keys = self.sampler._next_keys()
          out = self.sampler.sample_from_nodes(inp, seed_mask=mask,
                                               keys=keys)
          if self._batch_overflowed(out):
            self.overflow_recomputes += 1
            out = self._replay_sampler().sample_from_nodes(
                inp, seed_mask=mask, keys=keys)
        else:
          out = self.sampler.sample_from_nodes(inp, seed_mask=mask)
          if guarded:
            self._accumulate_overflow(out)
        yield self._collate_fn(out)
    if guarded and not recompute:
      self._finish_epoch_overflow()

  def _collate_fn(self, out):
    """SamplerOutput [P, ...] -> stacked Data/HeteroData (reference:
    dist_loader.py:331-441 parses the channel SampleMessage; here arrays
    are already device-resident and sharded)."""
    from .. import ops
    from ..loader import HeteroData
    from ..sampler import HeteroSamplerOutput
    x, y = self.sampler.collate(
        out, self.data.node_labels,
        label_cap=(self.batch_size if self.seed_labels_only else None))
    if isinstance(out, HeteroSamplerOutput):
      ei = {et: ops.stack2_batched(out.row[et], out.col[et])
            for et in out.row}
      edge_attr = None
      efs = getattr(self.data, 'edge_features', None)
      if out.edge is not None and efs:
        # batches key edges by the message-direction (reversed) type; the
        # ids belong to the ORIGINAL edge type's id space
        from ..typing import reverse_edge_type
        edge_attr = {}
        for et in out.edge:
          src_et = (reverse_edge_type(et) if self.data.edge_dir == 'out'
                    else et)
          if src_et in efs:
            edge_attr[et] = efs[src_et].get(out.edge[et])
        edge_attr = edge_attr or None
      return HeteroData(node=out.node, num_nodes=out.num_nodes,
                        edge_index=ei, edge_mask=out.edge_mask, x=x, y=y,
                        edge_ids=out.edge, edge_attr=edge_attr,
                        batch=out.batch,
                        batch_size=out.batch_size,
                        num_sampled_nodes=out.num_sampled_nodes,
                        num_sampled_edges=out.num_sampled_edges,
                        metadata=dict(out.metadata))
    edge_attr = None
    if out.edge is not None and \
        getattr(self.data, 'edge_features', None) is not None:
      edge_attr = self.data.edge_features.get(out.edge)
    ei = ops.stack2_batched(out.row, out.col)  # [P, 2, E]
    return Data(node=out.node, num_nodes=out.num_nodes,
                edge_index=ei, edge_mask=out.edge_mask, x=x, y=y,
                edge_ids=out.edge, edge_attr=edge_attr, batch=out.batch,
                batch_size=out.batch_size,
                num_sampled_nodes=out.num_sampled_nodes,
                num_sampled_edges=out.num_sampled_edges,
                metadata=dict(out.metadata))


class MpDistNeighborLoader:
  """Mp worker mode: sampling subprocesses feed a native shm channel, the
  loader drains it (reference: dist_loader.py:226-302 mp branch). Use when
  host-side seed prep/feature IO should overlap device training; the
  collocated mesh loader (DistNeighborLoader) is the device-fast path."""

  def __init__(self, data, num_neighbors, input_nodes,
               batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, num_workers: int = 2,
               channel_size: int = 1 << 26, seed: Optional[int] = None):
    from ..sampler import SamplingConfig, SamplingType
    # hetero seeds: ('paper', ids) — workers sample the typed engine and
    # stream HeteroData messages (message.hetero_output_to_message)
    input_type, input_nodes = _split_input_type(input_nodes)
    config = SamplingConfig(
        SamplingType.NODE, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        False, False, data.edge_dir, seed)
    self._setup(data,
                NodeSamplerInput(np.asarray(input_nodes).reshape(-1),
                                 input_type=input_type),
                config, channel_size, num_workers, seed)

  def _setup(self, data, sampler_input, config, channel_size, num_workers,
             seed):
    """Shared producer/channel wiring for the mp loader family."""
    from ..channel import QueueTimeoutError, ShmChannel
    from .dist_sampling_producer import DistMpSamplingProducer
    from .message import message_to_data
    self._message_to_data = message_to_data
    self._timeout_error = QueueTimeoutError
    self.channel = ShmChannel(shm_size=channel_size)
    self.producer = DistMpSamplingProducer(
        data, sampler_input, config, self.channel,
        num_workers=num_workers, seed=seed)
    self.producer.init()
    self._expected = self.producer.num_expected()

  def __len__(self):
    return self._expected

  def __iter__(self):
    self.producer.produce_all()
    received = 0
    while received < self._expected:
      try:
        msg = self.channel.recv(timeout_ms=60000)
      except self._timeout_error:
        self.producer.check_worker_health()   # crashed worker -> raise,
        # don't spin on an empty channel forever
        if self.producer.is_all_sampling_completed() and \
            self.channel.empty():
          break
        continue
      received += 1
      yield self._message_to_data(msg)

  def shutdown(self):
    self.producer.shutdown()
    self.channel.close()


class MpDistLinkNeighborLoader(MpDistNeighborLoader):
  """Mp worker mode for LINK sampling: subprocesses run
  sample_from_edges (positives + negatives) and stream batches with
  edge_label_index/edge_label metadata over the shm channel (reference:
  the link branch of the sampling producers,
  dist_sampling_producer.py:106-140)."""

  def __init__(self, data, num_neighbors: List[int], edge_label_index,
               edge_label=None, neg_sampling=None, batch_size: int = 64,
               shuffle: bool = False, drop_last: bool = False,
               with_edge: bool = False, collect_features: bool = True,
               num_workers: int = 2, channel_size: int = 1 << 26,
               seed: Optional[int] = None):
    from ..sampler import (EdgeSamplerInput, SamplingConfig, SamplingType)
    # hetero seed edges: ((src_t, rel, dst_t), [2, E]) — the LinkLoader
    # tuple convention; workers run the typed link engine
    edge_type, edge_label_index = _split_edge_type(edge_label_index)
    ei = np.asarray(edge_label_index)
    config = SamplingConfig(
        SamplingType.LINK, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        neg_sampling is not None, False, data.edge_dir, seed)
    self._setup(data,
                EdgeSamplerInput(ei[0], ei[1], label=edge_label,
                                 input_type=edge_type,
                                 neg_sampling=neg_sampling),
                config, channel_size, num_workers, seed)


class _RemoteLoaderBase:
  """Shared remote (server-client) machinery: create one producer per
  server from a per-server sampler-input split, pull batches through
  the RemoteReceivingChannel, restart producers per epoch (reference:
  dist_loader.py:155-195 + dist_neighbor_loader.py remote branch)."""

  def _setup_remote(self, config, per_server_inputs, worker_options):
    import dataclasses

    from ..channel import RemoteReceivingChannel
    from . import dist_client
    from .message import message_to_data
    self._message_to_data = message_to_data
    opts = worker_options
    self.producer_ids = []
    self._expected = 0
    for i, (rank, part) in enumerate(zip(self.server_ranks,
                                         per_server_inputs)):
      # fold the SERVER index into the seed: same-ranked mp workers on
      # different servers would otherwise derive identical worker
      # seeds and draw identical negative edges per batch index
      # (negatives depend only on the graph + key)
      cfg_i = dataclasses.replace(
          config, seed=(config.seed or 0) * 7919 + i)
      pid = dist_client.request_server(
          rank, 'create_sampling_producer', part, cfg_i,
          opts.num_workers if opts else 1,
          worker_key=(opts.worker_key if opts else None))
      self.producer_ids.append(pid)
      # the producer's own count: its mp workers split the seed share and
      # each rounds up, so ceil(n/batch_size) would undercount here
      self._expected += dist_client.request_server(
          rank, 'producer_num_expected', pid)
    self.channel = RemoteReceivingChannel(
        self.server_ranks, self.producer_ids,
        prefetch_size=(opts.prefetch_size if opts else 4))
    self._dist_client = dist_client

  def _resolve_ranks(self, worker_options):
    opts = worker_options
    ranks = opts.server_rank if opts and opts.server_rank is not None \
        else [0]
    if isinstance(ranks, int):
      ranks = [ranks]
    self.server_ranks = list(ranks)

  def __len__(self):
    return self._expected

  def __iter__(self):
    # Ordering matters: kill any previous epoch's pullers BEFORE
    # restarting the server producers (a stale puller would consume
    # new-epoch messages into its dead queue), and only then start the
    # new pullers.
    self.channel.stop(join=True)
    for rank, pid in zip(self.server_ranks, self.producer_ids):
      self._dist_client.request_server(rank, 'start_new_epoch_sampling',
                                       pid)
    self.channel.start()
    while True:
      try:
        msg = self.channel.recv(timeout_ms=60000)
      except StopIteration:
        return
      yield self._message_to_data(msg)

  def shutdown(self):
    self.channel.stop()
    for rank, pid in zip(self.server_ranks, self.producer_ids):
      try:
        self._dist_client.request_server(rank,
                                         'destroy_sampling_producer', pid)
      except (RuntimeError, ConnectionError, OSError):
        pass


class RemoteDistNeighborLoader(_RemoteLoaderBase):
  """Remote (server-client) NODE loading: producers run on sampling
  servers, batches stream back over RPC; hetero seeds as
  ('ntype', ids)."""

  def __init__(self, num_neighbors, input_nodes,
               batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, worker_options=None,
               seed: Optional[int] = None):
    from ..sampler import NodeSamplerInput as NSI
    from ..sampler import SamplingConfig, SamplingType
    self._resolve_ranks(worker_options)
    # hetero seeds: ('paper', ids) — the server's mp workers run the
    # typed engine and stream HeteroData messages back (round 5); ship
    # typed NodeSamplerInputs so the tuple convention (type FIRST)
    # never hits CastMixin's positional cast
    input_type, input_nodes = _split_input_type(input_nodes)
    config = SamplingConfig(
        SamplingType.NODE, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        False, False, 'out', seed)
    seeds = np.asarray(input_nodes).reshape(-1)
    # split seeds across servers; each server samples its share
    splits = np.array_split(seeds, len(self.server_ranks))
    parts = [NSI(p, input_type) if input_type is not None else p
             for p in splits]
    self._setup_remote(config, parts, worker_options)


class RemoteDistLinkNeighborLoader(_RemoteLoaderBase):
  """Remote (server-client) LINK loading: seed edges split across the
  sampling servers, whose mp workers draw negatives + run the (typed)
  link engine; batches stream back with edge_label metadata. Hetero
  seed edges as ((src_t, rel, dst_t), [2, E])."""

  def __init__(self, num_neighbors, edge_label_index, edge_label=None,
               neg_sampling=None, batch_size: int = 64,
               shuffle: bool = False, drop_last: bool = False,
               with_edge: bool = False, collect_features: bool = True,
               worker_options=None, seed: Optional[int] = None):
    from ..sampler import (EdgeSamplerInput, NegativeSampling,
                           SamplingConfig, SamplingType)
    self._resolve_ranks(worker_options)
    edge_type, edge_label_index = _split_edge_type(edge_label_index)
    ei = np.asarray(edge_label_index)
    label = (np.asarray(edge_label).reshape(-1)
             if edge_label is not None else None)
    ns = (NegativeSampling.cast(neg_sampling)
          if neg_sampling is not None else None)
    config = SamplingConfig(
        SamplingType.LINK, _norm_num_neighbors(num_neighbors),
        batch_size, shuffle, drop_last, with_edge, collect_features,
        ns is not None, False, 'out', seed)
    nsrv = len(self.server_ranks)
    row_s = np.array_split(ei[0].reshape(-1), nsrv)
    col_s = np.array_split(ei[1].reshape(-1), nsrv)
    lab_s = (np.array_split(label, nsrv) if label is not None
             else [None] * nsrv)
    parts = [EdgeSamplerInput(r, c, label=lb, input_type=edge_type,
                              neg_sampling=ns)
             for r, c, lb in zip(row_s, col_s, lab_s)]
    self._setup_remote(config, parts, worker_options)


class DistLinkNeighborLoader(DistLoader):
  """Distributed link-prediction loader: per-shard seed-edge blocks ->
  one SPMD link-sampling program (reference:
  distributed/dist_link_neighbor_loader.py:1-158; the sampling itself is
  dist_neighbor_sampler.py:369-496).

  Args:
    edge_label_index: [2, E] seed edges, or (edge_type, [2, E]) for
      hetero.
    edge_label: optional [E] labels for the positives.
    neg_sampling: optional NegativeSampling ('binary'/'triplet').
  """

  def __init__(self, data: DistDataset, num_neighbors, edge_label_index,
               edge_label=None, batch_size: int = 64,
               shuffle: bool = False, drop_last: bool = True,
               neg_sampling=None, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               node_budget: Optional[int] = None, mesh=None,
               with_weight: bool = False, dedup: str = 'sort',
               bucket_frac=2.0, neg_strict: bool = False,
               frontier_caps=None, overflow_policy: str = 'raise'):
    if mesh is None:
      from .dist_context import get_context
      ctx = get_context()
      mesh = ctx.mesh if ctx else None
    input_type, edge_label_index = _split_edge_type(edge_label_index)
    ei = np.asarray(edge_label_index)
    self.seed_rows = ei[0].reshape(-1)
    self.seed_cols = ei[1].reshape(-1)
    self.edge_label = (np.asarray(edge_label).reshape(-1)
                       if edge_label is not None else None)
    self.neg_sampling = neg_sampling
    # frontier_caps: calibrate against the effective PER-SHARD seed
    # width — the engine derives it internally from batch_size and
    # neg_sampling (calibrate.link_seed_width); pass caps estimated at
    # that width
    sampler = DistNeighborSampler(
        data.graph, num_neighbors, mesh,
        dist_feature=data.node_features, with_edge=with_edge, seed=seed,
        node_budget=node_budget, collect_features=collect_features,
        with_weight=with_weight, dedup=dedup, bucket_frac=bucket_frac,
        neg_strict=neg_strict, frontier_caps=frontier_caps)
    super().__init__(data, sampler, np.zeros(0, np.int64), batch_size,
                     shuffle, drop_last, collect_features, seed,
                     overflow_policy=overflow_policy)
    self.input_type = input_type  # EdgeType for hetero link sampling

  def _num_seeds(self):
    return self.seed_rows.shape[0]

  def __iter__(self):
    from ..sampler import EdgeSamplerInput
    guarded, recompute = self._overflow_epoch_start()
    for idx, mask in self._index_blocks():
      inputs = EdgeSamplerInput(
          self.seed_rows[idx], self.seed_cols[idx],
          label=(self.edge_label[idx]
                 if self.edge_label is not None else None),
          input_type=self.input_type,
          neg_sampling=self.neg_sampling)
      if recompute:
        keys = self.sampler._next_keys()
        out = self.sampler.sample_from_edges(inputs, seed_mask=mask,
                                             keys=keys)
        if self._batch_overflowed(out):
          self.overflow_recomputes += 1
          out = self._replay_sampler().sample_from_edges(
              inputs, seed_mask=mask, keys=keys)
      else:
        out = self.sampler.sample_from_edges(inputs, seed_mask=mask)
        if guarded:
          self._accumulate_overflow(out)
      yield self._collate_fn(out)
    if guarded and not recompute:
      self._finish_epoch_overflow()


class DistSubGraphLoader(DistLoader):
  """Distributed induced-subgraph loader (reference:
  distributed/dist_subgraph_loader.py:1-93; sampling is
  dist_neighbor_sampler.py:499-559). ``num_neighbors=None`` induces over
  the seed set alone; otherwise seeds are hop-expanded first."""

  def __init__(self, data: DistDataset, num_neighbors, input_nodes,
               batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               max_degree: Optional[int] = None, mesh=None,
               bucket_frac=2.0):
    if mesh is None:
      from .dist_context import get_context
      ctx = get_context()
      mesh = ctx.mesh if ctx else None
    sampler = DistNeighborSampler(
        data.graph, num_neighbors, mesh,
        dist_feature=data.node_features, with_edge=with_edge, seed=seed,
        collect_features=collect_features, bucket_frac=bucket_frac)
    super().__init__(data, sampler, input_nodes, batch_size, shuffle,
                     drop_last, collect_features, seed)
    self.max_degree = max_degree

  def __iter__(self):
    for idx, mask in self._index_blocks():
      out = self.sampler.subgraph(self.input_seeds[idx], seed_mask=mask,
                                  max_degree=self.max_degree)
      yield self._collate_fn(out)


class DistNeighborLoader(DistLoader):
  """Reference: dist_neighbor_loader.py:104-112."""

  def __init__(self, data: DistDataset, num_neighbors: List[int],
               input_nodes, batch_size: int = 64, shuffle: bool = False,
               drop_last: bool = True, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               node_budget: Optional[int] = None, mesh=None,
               with_weight: bool = False, dedup: str = 'sort',
               seed_labels_only: bool = False, bucket_frac=2.0,
               frontier_caps=None, overflow_policy: str = 'raise'):
    if mesh is None:
      from .dist_context import get_context
      ctx = get_context()
      mesh = ctx.mesh if ctx else None
    sampler = DistNeighborSampler(
        data.graph, num_neighbors, mesh,
        dist_feature=data.node_features, with_edge=with_edge, seed=seed,
        node_budget=node_budget, collect_features=collect_features,
        with_weight=with_weight, dedup=dedup, bucket_frac=bucket_frac,
        frontier_caps=frontier_caps)
    super().__init__(data, sampler, input_nodes, batch_size, shuffle,
                     drop_last, collect_features, seed,
                     seed_labels_only=seed_labels_only,
                     overflow_policy=overflow_policy)
