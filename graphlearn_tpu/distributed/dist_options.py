"""Sampling-worker option bundles.

TPU-native port of
/root/reference/graphlearn_torch/python/distributed/dist_options.py. The
three deployment shapes survive: collocated (sampling compiled into the
training step's mesh program — the default and fastest on TPU), mp
(sampling in subprocesses feeding a shm channel; useful when host-side
seed prep/IO is the bottleneck), and remote (sampling on server processes,
batches streamed to clients over DCN).
"""
from dataclasses import dataclass
from typing import List, Optional, Union


@dataclass
class _BasicDistSamplingWorkerOptions:
  """Reference: dist_options.py:24-116."""
  num_workers: int = 1
  worker_concurrency: int = 4
  master_addr: Optional[str] = None
  master_port: Optional[Union[str, int]] = None
  channel_size: Optional[Union[int, str]] = None
  pin_memory: bool = False
  rpc_timeout: float = 180.0


@dataclass
class CollocatedDistSamplingWorkerOptions(_BasicDistSamplingWorkerOptions):
  """Sampling runs in-process on the training mesh
  (reference: dist_options.py:145-166)."""
  use_all2all: bool = True


@dataclass
class MpDistSamplingWorkerOptions(_BasicDistSamplingWorkerOptions):
  """Sampling subprocesses + shm channel
  (reference: dist_options.py:169-199)."""
  channel_capacity: int = 128


@dataclass
class RemoteDistSamplingWorkerOptions(_BasicDistSamplingWorkerOptions):
  """Server-side producers streaming to this client
  (reference: dist_options.py:202-260).

  Resilience tunables (docs/failure_model.md): the loader heartbeats
  every server at ``heartbeat_interval`` seconds and declares one dead
  after ``heartbeat_miss`` consecutive missed probes (detection latency
  ~ interval * miss, vs the 180 s socket timeout). With ``failover``
  on, a dead server's unacknowledged seeds are redistributed across the
  surviving servers so the epoch still completes (node loaders only —
  link batches carry no seed provenance to ack). ``rpc_timeout`` doubles
  as the total-idle budget: an epoch that receives nothing for that
  long fails with a contextual QueueTimeoutError.

  Chunk-staged scan tunables (``distributed.RemoteScanTrainer``,
  docs/remote_scan.md): ``block_wire_dtype='bf16'`` ships block
  feature payloads at half width (f32 upcast happens inside the chunk
  program after device upload — ~2x fewer block bytes, a precision
  delta only); ``block_ahead`` is the client prefetch depth (2 = the
  classic double buffer: block c+1 stages while chunk c trains);
  ``block_timeout`` bounds how long a chunk boundary waits for its
  staged block before degrading to a synchronous fetch of the same
  block. With ``failover`` on, a dead server's unfetched BLOCKS are
  re-replayed by survivors from the same counter stream
  (shuffle=False only).

  Tenancy tunables (docs/multi_tenancy.md): when the servers run with a
  ``TenancyConfig``, ``tenant`` names the quota/fair-share bucket this
  client's producers are admitted under; ``tenant_priority`` is one of
  ``interactive``/``training``/``bulk`` (strict priority between
  classes); ``tenant_weight`` is the deficit-round-robin share within
  the class. ``backpressure_budget`` bounds the total seconds a loader
  will spend in throttle-retry backoff (``tenant.backpressure_ms``)
  before failing loudly with the tenant's quota snapshot.
  """
  server_rank: Optional[Union[int, List[int]]] = None
  buffer_size: Optional[Union[int, str]] = None
  prefetch_size: int = 4
  worker_key: Optional[str] = None
  epochs: int = 1
  heartbeat_interval: float = 1.0
  heartbeat_miss: int = 3
  failover: bool = True
  block_wire_dtype: Optional[str] = None
  block_ahead: int = 2
  block_timeout: float = 30.0
  tenant: Optional[str] = None
  tenant_priority: Optional[str] = None
  tenant_weight: Optional[float] = None
  backpressure_budget: float = 120.0


AllDistSamplingWorkerOptions = Union[
    CollocatedDistSamplingWorkerOptions,
    MpDistSamplingWorkerOptions,
    RemoteDistSamplingWorkerOptions,
]
