"""Sampling server: holds graph data, produces batches for remote clients.

TPU-native port of
/root/reference/graphlearn_torch/python/distributed/dist_server.py. The
server process owns a Dataset (its graph partition + features), registers a
producer per client request, and streams serialized SampleMessages on
demand over the TCP RPC (replacing torch-RPC). `fetch_one_sampled_message`
keeps the reference's poll contract: (message|None, end_of_epoch_flag) with
a bounded wait (dist_server.py:149-166).
"""
import logging
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..channel import QueueTimeoutError, ShmChannel
from ..sampler import NodeSamplerInput, SamplingConfig
from ..utils.faults import fault_point
from .dist_context import _set_server_context
from .dist_sampling_producer import DistMpSamplingProducer
from .rpc import Barrier, RpcServer


class DistServer:
  """Reference: dist_server.py:38-176.

  ``producer_ttl``: seconds of producer inactivity (no fetch / epoch
  start) after which a background reaper destroys the producer and
  releases its ShmChannel. This is the backstop against clients that
  disconnect mid-stream without calling destroy_sampling_producer — a
  leaked producer would otherwise hold its shm ring (and worker
  subprocesses) until server exit. None disables reaping.

  ``tenancy`` (tenancy.TenancyConfig, docs/multi_tenancy.md) turns on
  the multi-tenant service plane: per-tenant admission quotas at
  producer creation and the block handlers (typed retryable
  rejections), the weighted-fair block scheduling lane, per-tenant
  ``producer_ttl`` overrides (one vanished client reaps only its own
  streams), and per-tenant quota state in get_metrics. None (the
  default) keeps the single-tenant behavior bit-for-bit.
  """

  def __init__(self, dataset, producer_ttl: Optional[float] = None,
               tenancy=None):
    from .tenancy import AdmissionController, WeightedFairScheduler
    self.dataset = dataset
    self._producers: Dict[int, DistMpSamplingProducer] = {}
    # chunk-staged block streams (distributed/block_producer.py,
    # docs/remote_scan.md): pure counter-addressed replays — no
    # subprocesses, no shm ring, so their lifecycle is just this dict
    self._block_producers: Dict[int, object] = {}
    self._block_key_to_id: Dict[str, int] = {}
    self._buffers: Dict[int, ShmChannel] = {}
    # per-producer fetch locks: destroy (client call OR idle reaper)
    # must not close a shm ring while a fetch thread is blocked inside
    # its native recv — that is a use-after-free on the ring
    self._fetch_locks: Dict[int, threading.Lock] = {}
    self._expected: Dict[int, int] = {}
    self._received: Dict[int, int] = {}
    self._last_active: Dict[int, float] = {}
    self._next_id = 0
    self._worker_key_to_id: Dict[str, int] = {}
    self._lock = threading.RLock()
    self._exit = threading.Event()
    self.producer_ttl = producer_ttl
    self._admission = AdmissionController(tenancy) \
        if tenancy is not None else None
    self._scheduler = WeightedFairScheduler(
        self._admission, quantum=tenancy.quantum,
        timeout=tenancy.sched_timeout) if tenancy is not None else None
    self._reaper: Optional[threading.Thread] = None
    if self._min_ttl() is not None:
      self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
      self._reaper.start()

  def _min_ttl(self) -> Optional[float]:
    """Smallest armed reap threshold (server-wide or any tenant's) —
    the reaper runs when any ttl is armed and polls at the tightest
    one's cadence."""
    if self._admission is not None:
      return self._admission.min_ttl(self.producer_ttl)
    return self.producer_ttl

  def _pid_ttl(self, pid: int) -> Optional[float]:
    if self._admission is not None:
      return self._admission.ttl_for_pid(pid, self.producer_ttl)
    return self.producer_ttl

  def _pid_context(self, pid: int) -> str:
    """Tenant + quota context for stale-handle errors ('' without
    tenancy) — the operator-actionable half of a reaped-pid failure."""
    if self._admission is None:
      return ''
    return self._admission.describe_pid(pid)

  def _touch(self, producer_id: int):
    self._last_active[producer_id] = time.monotonic()

  def _reap_loop(self):
    interval = min(max((self._min_ttl() or 1.0) / 4.0, 0.05), 30.0)
    while not self._exit.wait(interval):
      try:
        self.reap_idle_producers()
      except Exception:   # noqa: BLE001 - an armed tenant.reap chaos
        pass              # raise must not kill the reaper thread

  def reap_idle_producers(self) -> int:
    """Destroy producers idle for longer than their reap threshold
    (the tenant's ``producer_ttl`` when tenancy is on, else the
    server-wide one — one vanished client reaps only its own streams);
    returns the number reaped (also callable directly from tests).
    Each reap counts under ``tenant.reaped.<tenant>``."""
    now = time.monotonic()
    with self._lock:
      stale = []
      for pid, ts in self._last_active.items():
        ttl = self._pid_ttl(pid)
        if ttl is not None and now - ts > ttl:
          stale.append(pid)
      stale_blocks = {pid for pid in stale
                      if pid in self._block_producers}
    from .. import metrics
    from ..utils import trace
    for pid in stale:
      fault_point('tenant.reap')
      tenant = (self._admission.tenant_of(pid)
                if self._admission is not None else 'default')
      trace.counter_inc('resilience.producer_reaped')
      metrics.inc(f'tenant.reaped.{tenant}')
      logging.getLogger('graphlearn_tpu.server').info(
          'reaping idle producer %d of tenant %r (ttl=%s)', pid,
          tenant, self._pid_ttl(pid))
      if self._admission is not None:
        self._admission.release_producer(pid, reaped=True)
      if pid in stale_blocks:
        self.destroy_block_producer(pid)
      else:
        self.destroy_sampling_producer(pid)
    return len(stale)

  # -- producer lifecycle (reference: dist_server.py:104-147) --------------

  def create_sampling_producer(self, seeds, sampling_config: SamplingConfig,
                               num_workers: int = 1,
                               buffer_size: int = 1 << 26,
                               worker_key: Optional[str] = None,
                               tenant: Optional[str] = None,
                               priority: Optional[str] = None,
                               weight: Optional[float] = None) -> int:
    fault_point('server.create_producer')
    with self._lock:
      if worker_key is not None and worker_key in self._worker_key_to_id:
        pid = self._worker_key_to_id[worker_key]
        self._touch(pid)
        return pid
      pid = self._next_id
      self._next_id += 1
      if self._admission is not None:
        # admission BEFORE any resource allocation: an over-quota
        # tenant's rejection (typed, retryable) must not leak a ring
        self._admission.admit_producer(
            tenant or 'default', pid, ring_bytes=int(buffer_size),
            priority=priority, weight=weight)
      try:
        buf = ShmChannel(shm_size=buffer_size)
        import dataclasses

        from ..sampler import EdgeSamplerInput, SamplingType
        # the server's dataset is the authority on edge orientation —
        # remote clients can't know it and default to 'out'
        sampling_config = dataclasses.replace(
            sampling_config, edge_dir=self.dataset.edge_dir)
        if sampling_config.sampling_type == SamplingType.LINK:
          # seeds arrive as [2, E] (or an EdgeSamplerInput); negatives
          # are requested through config.with_neg (binary, amount 1 —
          # pass an EdgeSamplerInput for other modes)
          if not isinstance(seeds, EdgeSamplerInput):
            from ..sampler import NegativeSampling
            ei = np.asarray(seeds)
            seeds = EdgeSamplerInput(
                ei[0], ei[1],
                neg_sampling=(NegativeSampling('binary', 1)
                              if sampling_config.with_neg else None))
          sampler_input = seeds
        else:
          sampler_input = NodeSamplerInput.cast(seeds)
        producer = DistMpSamplingProducer(
            self.dataset, sampler_input, sampling_config, buf,
            num_workers=num_workers)
        producer.init()
      except BaseException:
        # a failed create must not hold the tenant's admission slot
        if self._admission is not None:
          self._admission.release_producer(pid)
        raise
      self._producers[pid] = producer
      self._buffers[pid] = buf
      self._fetch_locks[pid] = threading.Lock()
      self._expected[pid] = producer.num_expected()
      self._received[pid] = 0
      self._touch(pid)
      if worker_key is not None:
        self._worker_key_to_id[worker_key] = pid
      return pid

  def _live_producer(self, producer_id: int):
    """Producer + buffer for an id, or a diagnosable error: after an
    idle-reap or double-destroy the bare KeyError would reach the
    client as an inscrutable remote failure. With tenancy on, the
    error carries the pid's tenant + quota snapshot."""
    producer = self._producers.get(producer_id)
    buf = self._buffers.get(producer_id)
    if producer is None or buf is None:
      raise RuntimeError(
          f'producer {producer_id} unknown on this server — it was '
          'destroyed or idle-reaped (producer_ttl); recreate the remote '
          f'loader to register a fresh producer'
          f'{self._pid_context(producer_id)}')
    return producer, buf

  def producer_num_expected(self, producer_id: int) -> int:
    """Exact number of batches this producer emits per epoch (its mp
    workers each round their seed share up, so the client cannot derive
    this from ceil(n/batch_size) — see DistMpSamplingProducer
    .num_expected)."""
    with self._lock:
      self._live_producer(producer_id)
      return self._expected[producer_id]

  def start_new_epoch_sampling(self, producer_id: int):
    with self._lock:
      producer, buf = self._live_producer(producer_id)
      self._touch(producer_id)
      # Drain messages left over from an abandoned previous epoch so they
      # are not served as (and counted against) the new epoch's batches.
      # A still-producing abandoned epoch keeps writing until its seeds
      # are exhausted; wait it out first (bounded by production time).
      if 0 < self._received.get(producer_id, 0) < \
          self._expected.get(producer_id, 0):
        deadline = time.time() + 120.0
        while not producer.is_all_sampling_completed():
          if time.time() > deadline:
            break
          time.sleep(0.05)
      while not buf.empty():
        try:
          buf.recv(timeout_ms=10)
        except (QueueTimeoutError, StopIteration):
          break
      self._received[producer_id] = 0
    producer.produce_all()

  def fetch_one_sampled_message(self, producer_id: int,
                                timeout_ms: int = 500
                                ) -> Tuple[Optional[dict], bool]:
    """(message|None, end_of_epoch). Reference: dist_server.py:149-166."""
    fault_point('server.fetch')
    t0 = time.perf_counter()
    # one atomic preamble: existence check, touch, count check, and the
    # fetch-lock lookup must see a consistent producer state — a racing
    # destroy between them would otherwise KeyError (opaque remote
    # error) or resurrect the reaped pid's _last_active entry
    with self._lock:
      producer, buf = self._live_producer(producer_id)
      fetch_lock = self._fetch_locks[producer_id]
      self._touch(producer_id)
      if self._received[producer_id] >= self._expected[producer_id]:
        return None, True
    try:
      with fetch_lock:
        if producer_id not in self._buffers:   # destroyed while waiting
          return None, True
        msg = buf.recv(timeout_ms=timeout_ms)
    except QueueTimeoutError:
      # nothing buffered: either the epoch is done, or a producer worker
      # crashed mid-epoch — self-heal (restart + replay, bounded by the
      # producer's restart budget) so the client's stream resumes
      # instead of polling an empty ring forever
      producer.check_worker_health()
      done = (producer.is_all_sampling_completed() and buf.empty())
      return None, done
    except StopIteration:
      return None, True
    with self._lock:
      self._received[producer_id] += 1
      end = self._received[producer_id] >= self._expected[producer_id]
    # delivered-fetch latency distribution: the serving-tier p50/p99
    # substrate (ROADMAP item 1); empty polls/timeouts are excluded so
    # the histogram measures delivery, not the poll cadence
    from .. import metrics
    metrics.observe('server.fetch_ms', (time.perf_counter() - t0) * 1e3)
    return msg, end

  def destroy_sampling_producer(self, producer_id: int):
    """Idempotent: destroying an unknown / already-destroyed producer is
    a no-op (a client may retry destroy after a lost response, and the
    idle reaper may have won the race). Always releases the producer's
    ShmChannel — the shm ring must not outlive the producer, or
    create/destroy churn across epochs leaks shared memory."""
    if self._admission is not None:
      self._admission.release_producer(producer_id)
    with self._lock:
      producer = self._producers.pop(producer_id, None)
      buf = self._buffers.pop(producer_id, None)
      self._expected.pop(producer_id, None)
      self._received.pop(producer_id, None)
      self._last_active.pop(producer_id, None)
      fetch_lock = self._fetch_locks.pop(producer_id, None)
      for k, v in list(self._worker_key_to_id.items()):
        if v == producer_id:
          del self._worker_key_to_id[k]
    if producer:
      producer.shutdown()
    if buf:
      if fetch_lock is not None:
        # wait out any fetch blocked in the ring's native recv (bounded
        # by the fetch poll timeout) before freeing the shared memory
        with fetch_lock:
          buf.close()
      else:
        buf.close()
    return True

  # -- chunk-staged block streams (distributed/block_producer.py;
  # docs/remote_scan.md). Blocks are pure functions of (share, config,
  # epoch, batch range), so every handler here is idempotent by
  # construction and the client calls them with retry under the fault
  # registry (docs/failure_model.md). ----------------------------------

  def create_block_producer(self, seeds, sampling_config,
                            wire_dtype: Optional[str] = None,
                            worker_key: Optional[str] = None,
                            tenant: Optional[str] = None,
                            priority: Optional[str] = None,
                            weight: Optional[float] = None) -> int:
    """Register a block stream over a seed share. ``worker_key`` dedups
    re-creates (client retries, failover replay producers on
    survivors) exactly like the sampling producers' key. ``tenant`` /
    ``priority`` / ``weight`` register the stream with the admission
    controller (docs/multi_tenancy.md); its staged frame bytes then
    count against the tenant's in-flight quota and its builds drain
    through the weighted-fair lane."""
    import dataclasses

    from .block_producer import BlockSampleProducer
    with self._lock:
      if worker_key is not None and worker_key in self._block_key_to_id:
        pid = self._block_key_to_id[worker_key]
        self._touch(pid)
        return pid
      pid = self._next_id
      self._next_id += 1
      if self._admission is not None:
        self._admission.admit_producer(
            tenant or 'default', pid, ring_bytes=0,
            priority=priority, weight=weight)
      # the server's dataset is the authority on edge orientation —
      # same replace as create_sampling_producer
      cfg = dataclasses.replace(sampling_config,
                                edge_dir=self.dataset.edge_dir)
      try:
        producer = BlockSampleProducer(
            self.dataset, seeds, cfg, wire_dtype=wire_dtype)
      except BaseException:
        if self._admission is not None:
          self._admission.release_producer(pid)
        raise
      if self._admission is not None:
        # in-flight byte accounting: frames charged as they stage into
        # the producer cache, released as the client fetches them
        adm, t = self._admission, (tenant or 'default')
        producer.on_stage = lambda n: adm.charge_inflight(t, n)
        producer.on_fetch = lambda n: adm.release_inflight(t, n)
      self._block_producers[pid] = producer
      self._touch(pid)
      if worker_key is not None:
        self._block_key_to_id[worker_key] = pid
      return pid

  def _live_block_producer(self, producer_id: int):
    producer = self._block_producers.get(producer_id)
    if producer is None:
      raise RuntimeError(
          f'block producer {producer_id} unknown on this server — it '
          'was destroyed or idle-reaped (producer_ttl); recreate the '
          f'remote scan trainer to register a fresh stream'
          f'{self._pid_context(producer_id)}')
    return producer

  def _block_lane(self, producer_id: int, k: int, fn):
    """Run a block build/fetch through the weighted-fair lane (strict
    priority + DWRR — docs/multi_tenancy.md); a direct call without
    tenancy. Cost is the batch count: a tail block is cheaper than a
    full one."""
    if self._scheduler is None:
      return fn()
    tenant = self._admission.tenant_of(producer_id)
    return self._scheduler.run(tenant, float(k), fn)

  def block_producer_num_batches(self, producer_id: int) -> int:
    """Exact batches per epoch of this block stream (single stream —
    the per-batch producers' num_expected analog)."""
    with self._lock:
      producer = self._live_block_producer(producer_id)
      self._touch(producer_id)
    return producer.num_batches()

  def block_produce(self, producer_id: int, epoch: int, start: int,
                    k: int) -> bool:
    """Stage block (epoch, [start, start+k)) into the frame cache —
    the produce half of the client's produce-c+1-while-fetching-c
    pipelining. With tenancy on, a tenant at its in-flight byte quota
    gets a retryable TenantThrottled (produce-ahead is optional work —
    fetching the staged frames drains the quota), and the build drains
    through the weighted-fair lane."""
    with self._lock:
      producer = self._live_block_producer(producer_id)
      self._touch(producer_id)
    if self._admission is not None:
      self._admission.check_inflight(self._admission.tenant_of(producer_id))
    return self._block_lane(
        producer_id, k, lambda: producer.produce(epoch, start, k))

  def block_fetch(self, producer_id: int, epoch: int, start: int,
                  k: int) -> dict:
    """The block frame (cache pop, or built on demand) — pure, so a
    retried fetch after a lost response rebuilds identical bytes.
    Routed through the weighted-fair lane: under contention an
    interactive tenant's fetch jumps a bulk tenant's queued builds.
    Never blocked by the in-flight quota — fetching DRAINS it."""
    with self._lock:
      producer = self._live_block_producer(producer_id)
      self._touch(producer_id)
    return self._block_lane(
        producer_id, k, lambda: producer.fetch(epoch, start, k))

  def destroy_block_producer(self, producer_id: int) -> bool:
    """Idempotent, like destroy_sampling_producer. Releases the
    tenant's admission slot and any still-staged frame bytes (zero
    leaked quota after a reap — the chaos tests pin this)."""
    with self._lock:
      producer = self._block_producers.pop(producer_id, None)
      self._last_active.pop(producer_id, None)
      for key, pid in list(self._block_key_to_id.items()):
        if pid == producer_id:
          del self._block_key_to_id[key]
    if self._admission is not None:
      tenant = self._admission.tenant_of(producer_id)
      leftover = getattr(producer, 'cached_bytes', lambda: 0)() \
          if producer is not None else 0
      if leftover:
        self._admission.release_inflight(tenant, leftover)
      self._admission.release_producer(producer_id)
    return True

  def update_tenant(self, tenant: str, priority: Optional[str] = None,
                    weight: Optional[float] = None) -> dict:
    """Re-register a tenant's priority/weight mid-flight (the elastic
    autoscale driver — docs/multi_tenancy.md) and return its quota
    snapshot. Idempotent by construction."""
    if self._admission is None:
      raise RuntimeError('tenancy is not enabled on this server '
                         '(DistServer(tenancy=TenancyConfig(...)))')
    self._admission.register(tenant, priority=priority, weight=weight)
    return self._admission.snapshot(tenant)

  def heartbeat(self) -> dict:
    """Cheap liveness probe (resilience.Heartbeat polls this): answers
    while the RPC loop is alive. Deliberately LOCK-FREE — self._lock is
    held across slow operations (producer.init subprocess spawning,
    epoch-start ring drains), and a probe blocked behind one of those
    would make a busy-but-healthy server miss its liveness deadline and
    get failed over for no reason. len() is atomic under the GIL."""
    return dict(ok=True, time=time.time(),
                n_producers=len(self._producers))

  def get_metrics(self) -> dict:
    """Scrape endpoint (metrics.scrape_all): this server PROCESS's
    metric snapshot plus each live producer's merged mp-worker
    snapshot, keyed by producer id. READ-ONLY and side-effect-free —
    idempotent by construction, so clients scrape it with retry under
    the fault-injection registry. Like heartbeat, the snapshot itself
    takes no self._lock (the registry has its own); only the producer
    table copy does."""
    from ..metrics import snapshot, spans
    srv = snapshot()
    # run_id + the span ring ride the snapshot (extra keys, ignored by
    # merge_snapshots): the scraping client recovers this server's
    # spans — and the producers' worker spans below — by id alone
    srv['run_id'] = spans.run_id()
    srv['spans'] = spans.export(limit=spans.SCRAPE_EXPORT_LIMIT)
    out = {'server': srv, 'producers': {}}
    if self._admission is not None:
      # per-tenant quota/usage state rides the scrape (and through it
      # the flight record): visible backpressure, not a silent stall
      out['tenants'] = self._admission.snapshot_all()
      if self._scheduler is not None:
        out['tenant_served'] = dict(self._scheduler.served)
    with self._lock:
      producers = dict(self._producers)
    for pid, producer in producers.items():
      workers = getattr(producer, 'worker_metrics', lambda: None)()
      if workers:
        out['producers'][pid] = workers
    return out

  # -- misc (reference: dist_server.py:60-102) -----------------------------

  def register_serving_engine(self, engine):
    """Attach an online embedding endpoint (serving.ServingEngine) so
    remote clients can look embeddings up through the ``serve`` RPC —
    the server-client topology's inference plane (docs/serving.md)."""
    self._serving = engine

  def serve(self, ids):
    """Embedding lookup RPC: ids -> [n, F] numpy rows. Routed through
    the engine's admission queue, so remote traffic batches with local
    traffic into the same calibrated bucket programs. READ-ONLY and
    idempotent by construction (like get_metrics) — clients call it
    with ``idempotent=True`` and it retries safely under the fault
    registry (docs/failure_model.md)."""
    engine = getattr(self, '_serving', None)
    if engine is None:
      raise RuntimeError('no serving engine registered on this server '
                         '(DistServer.register_serving_engine)')
    return engine.serve_numpy(np.asarray(ids, np.int64))

  def get_dataset_meta(self):
    g = self.dataset.graph
    if isinstance(g, dict):     # hetero: per-etype counts
      return dict(
          num_nodes={et: gr.num_nodes for et, gr in g.items()},
          num_edges={et: gr.num_edges for et, gr in g.items()},
          edge_types=sorted(tuple(et) for et in g),
          edge_dir=self.dataset.edge_dir)
    return dict(num_nodes=g.num_nodes, num_edges=g.num_edges,
                edge_dir=self.dataset.edge_dir)

  def exit(self):
    """Idempotent shutdown: destroys every producer (releasing all shm)
    and signals wait_for_exit; a second exit (client retry, multi-client
    fan-out) is a no-op."""
    for pid in list(self._producers):
      self.destroy_sampling_producer(pid)
    for pid in list(self._block_producers):
      self.destroy_block_producer(pid)
    if self._scheduler is not None:
      self._scheduler.close()
    self._exit.set()
    return True

  def wait_for_exit(self, timeout: Optional[float] = None) -> bool:
    return self._exit.wait(timeout)


_server: Optional[DistServer] = None
_rpc_server: Optional[RpcServer] = None


def get_server() -> Optional[DistServer]:
  return _server


def init_server(num_servers: int, num_clients: int, server_rank: int,
                dataset, master_addr: str = '127.0.0.1',
                server_client_master_port: int = 0,
                producer_ttl: Optional[float] = None,
                tenancy=None) -> Tuple[str, int]:
  """Start this server's RPC endpoint (reference: dist_server.py:180-212).
  Returns (host, port) — hand these to clients (the reference's tensorpipe
  rendezvous becomes explicit address exchange). ``producer_ttl`` bounds
  how long a producer abandoned by a disconnected client holds its shm
  ring (docs/failure_model.md). Off by default: a live client that
  pauses between epochs (eval, checkpointing) longer than the ttl would
  otherwise lose its producer; arm it when clients are expected to
  vanish without calling destroy, and keep it far above the longest
  legitimate between-epoch pause. ``tenancy``
  (tenancy.TenancyConfig) arms the multi-tenant service plane —
  admission quotas, weighted-fair block scheduling, per-tenant ttls
  (docs/multi_tenancy.md)."""
  global _server, _rpc_server
  _set_server_context(num_servers, num_clients, server_rank)
  _server = DistServer(dataset, producer_ttl=producer_ttl,
                       tenancy=tenancy)
  s = _server
  barrier = Barrier(num_clients)
  # handlers registered at construction: the server accepts connections
  # the moment it binds, and a fast client must not see a half-registered
  # callee table (see RpcServer docstring)
  _rpc_server = RpcServer(
      master_addr, server_client_master_port,
      handlers={
          'create_sampling_producer': s.create_sampling_producer,
          'producer_num_expected': s.producer_num_expected,
          'start_new_epoch_sampling': s.start_new_epoch_sampling,
          'fetch_one_sampled_message': s.fetch_one_sampled_message,
          'destroy_sampling_producer': s.destroy_sampling_producer,
          'create_block_producer': s.create_block_producer,
          'block_producer_num_batches': s.block_producer_num_batches,
          'block_produce': s.block_produce,
          'block_fetch': s.block_fetch,
          'destroy_block_producer': s.destroy_block_producer,
          'update_tenant': s.update_tenant,
          'get_dataset_meta': s.get_dataset_meta,
          'heartbeat': s.heartbeat,
          'get_metrics': s.get_metrics,
          'serve': s.serve,
          'exit': s.exit,
          'client_barrier': barrier.arrive,
      })
  return _rpc_server.host, _rpc_server.port


def wait_and_shutdown_server(timeout: Optional[float] = None):
  """Block until a client calls exit (reference: dist_server.py:215-233)."""
  global _server, _rpc_server
  if _server is not None:
    _server.wait_for_exit(timeout)
    time.sleep(0.1)  # let the exit RPC response flush
  if _rpc_server is not None:
    _rpc_server.shutdown()
  _server = None
  _rpc_server = None
