"""Sampling server: holds graph data, produces batches for remote clients.

TPU-native port of
/root/reference/graphlearn_torch/python/distributed/dist_server.py. The
server process owns a Dataset (its graph partition + features), registers a
producer per client request, and streams serialized SampleMessages on
demand over the TCP RPC (replacing torch-RPC). `fetch_one_sampled_message`
keeps the reference's poll contract: (message|None, end_of_epoch_flag) with
a bounded wait (dist_server.py:149-166).
"""
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..channel import QueueTimeoutError, ShmChannel
from ..sampler import NodeSamplerInput, SamplingConfig
from .dist_context import _set_server_context, get_context
from .dist_sampling_producer import DistMpSamplingProducer
from .rpc import Barrier, RpcServer


class DistServer:
  """Reference: dist_server.py:38-176."""

  def __init__(self, dataset):
    self.dataset = dataset
    self._producers: Dict[int, DistMpSamplingProducer] = {}
    self._buffers: Dict[int, ShmChannel] = {}
    self._expected: Dict[int, int] = {}
    self._received: Dict[int, int] = {}
    self._next_id = 0
    self._worker_key_to_id: Dict[str, int] = {}
    self._lock = threading.RLock()
    self._exit = threading.Event()

  # -- producer lifecycle (reference: dist_server.py:104-147) --------------

  def create_sampling_producer(self, seeds, sampling_config: SamplingConfig,
                               num_workers: int = 1,
                               buffer_size: int = 1 << 26,
                               worker_key: Optional[str] = None) -> int:
    with self._lock:
      if worker_key is not None and worker_key in self._worker_key_to_id:
        return self._worker_key_to_id[worker_key]
      pid = self._next_id
      self._next_id += 1
      buf = ShmChannel(shm_size=buffer_size)
      import dataclasses

      from ..sampler import EdgeSamplerInput, SamplingType
      # the server's dataset is the authority on edge orientation —
      # remote clients can't know it and default to 'out'
      sampling_config = dataclasses.replace(
          sampling_config, edge_dir=self.dataset.edge_dir)
      if sampling_config.sampling_type == SamplingType.LINK:
        # seeds arrive as [2, E] (or an EdgeSamplerInput); negatives are
        # requested through config.with_neg (binary, amount 1 — pass an
        # EdgeSamplerInput for other modes)
        if not isinstance(seeds, EdgeSamplerInput):
          from ..sampler import NegativeSampling
          ei = np.asarray(seeds)
          seeds = EdgeSamplerInput(
              ei[0], ei[1],
              neg_sampling=(NegativeSampling('binary', 1)
                            if sampling_config.with_neg else None))
        sampler_input = seeds
      else:
        sampler_input = NodeSamplerInput.cast(seeds)
      producer = DistMpSamplingProducer(
          self.dataset, sampler_input, sampling_config, buf,
          num_workers=num_workers)
      producer.init()
      self._producers[pid] = producer
      self._buffers[pid] = buf
      self._expected[pid] = producer.num_expected()
      self._received[pid] = 0
      if worker_key is not None:
        self._worker_key_to_id[worker_key] = pid
      return pid

  def producer_num_expected(self, producer_id: int) -> int:
    """Exact number of batches this producer emits per epoch (its mp
    workers each round their seed share up, so the client cannot derive
    this from ceil(n/batch_size) — see DistMpSamplingProducer
    .num_expected)."""
    with self._lock:
      return self._expected[producer_id]

  def start_new_epoch_sampling(self, producer_id: int):
    buf = self._buffers[producer_id]
    producer = self._producers[producer_id]
    with self._lock:
      # Drain messages left over from an abandoned previous epoch so they
      # are not served as (and counted against) the new epoch's batches.
      # A still-producing abandoned epoch keeps writing until its seeds
      # are exhausted; wait it out first (bounded by production time).
      if 0 < self._received.get(producer_id, 0) < \
          self._expected.get(producer_id, 0):
        deadline = time.time() + 120.0
        while not producer.is_all_sampling_completed():
          if time.time() > deadline:
            break
          time.sleep(0.05)
      while not buf.empty():
        try:
          buf.recv(timeout_ms=10)
        except (QueueTimeoutError, StopIteration):
          break
      self._received[producer_id] = 0
    producer.produce_all()

  def fetch_one_sampled_message(self, producer_id: int,
                                timeout_ms: int = 500
                                ) -> Tuple[Optional[dict], bool]:
    """(message|None, end_of_epoch). Reference: dist_server.py:149-166."""
    producer = self._producers[producer_id]
    buf = self._buffers[producer_id]
    with self._lock:
      if self._received[producer_id] >= self._expected[producer_id]:
        return None, True
    try:
      msg = buf.recv(timeout_ms=timeout_ms)
    except QueueTimeoutError:
      done = (producer.is_all_sampling_completed() and buf.empty())
      return None, done
    except StopIteration:
      return None, True
    with self._lock:
      self._received[producer_id] += 1
      end = self._received[producer_id] >= self._expected[producer_id]
    return msg, end

  def destroy_sampling_producer(self, producer_id: int):
    with self._lock:
      producer = self._producers.pop(producer_id, None)
      buf = self._buffers.pop(producer_id, None)
      self._expected.pop(producer_id, None)
      self._received.pop(producer_id, None)
      for k, v in list(self._worker_key_to_id.items()):
        if v == producer_id:
          del self._worker_key_to_id[k]
    if producer:
      producer.shutdown()
    if buf:
      buf.close()

  # -- misc (reference: dist_server.py:60-102) -----------------------------

  def get_dataset_meta(self):
    g = self.dataset.graph
    if isinstance(g, dict):     # hetero: per-etype counts
      return dict(
          num_nodes={et: gr.num_nodes for et, gr in g.items()},
          num_edges={et: gr.num_edges for et, gr in g.items()},
          edge_types=sorted(tuple(et) for et in g),
          edge_dir=self.dataset.edge_dir)
    return dict(num_nodes=g.num_nodes, num_edges=g.num_edges,
                edge_dir=self.dataset.edge_dir)

  def exit(self):
    for pid in list(self._producers):
      self.destroy_sampling_producer(pid)
    self._exit.set()
    return True

  def wait_for_exit(self, timeout: Optional[float] = None) -> bool:
    return self._exit.wait(timeout)


_server: Optional[DistServer] = None
_rpc_server: Optional[RpcServer] = None


def get_server() -> Optional[DistServer]:
  return _server


def init_server(num_servers: int, num_clients: int, server_rank: int,
                dataset, master_addr: str = '127.0.0.1',
                server_client_master_port: int = 0) -> Tuple[str, int]:
  """Start this server's RPC endpoint (reference: dist_server.py:180-212).
  Returns (host, port) — hand these to clients (the reference's tensorpipe
  rendezvous becomes explicit address exchange)."""
  global _server, _rpc_server
  _set_server_context(num_servers, num_clients, server_rank)
  _server = DistServer(dataset)
  s = _server
  barrier = Barrier(num_clients)
  # handlers registered at construction: the server accepts connections
  # the moment it binds, and a fast client must not see a half-registered
  # callee table (see RpcServer docstring)
  _rpc_server = RpcServer(
      master_addr, server_client_master_port,
      handlers={
          'create_sampling_producer': s.create_sampling_producer,
          'producer_num_expected': s.producer_num_expected,
          'start_new_epoch_sampling': s.start_new_epoch_sampling,
          'fetch_one_sampled_message': s.fetch_one_sampled_message,
          'destroy_sampling_producer': s.destroy_sampling_producer,
          'get_dataset_meta': s.get_dataset_meta,
          'exit': s.exit,
          'client_barrier': barrier.arrive,
      })
  return _rpc_server.host, _rpc_server.port


def wait_and_shutdown_server(timeout: Optional[float] = None):
  """Block until a client calls exit (reference: dist_server.py:215-233)."""
  global _server, _rpc_server
  if _server is not None:
    _server.wait_for_exit(timeout)
    time.sleep(0.1)  # let the exit RPC response flush
  if _rpc_server is not None:
    _rpc_server.shutdown()
  _server = None
  _rpc_server = None
