"""Multi-tenant service plane for the sampling/feature cluster.

At production scale the sampling cluster IS a shared service: several
trainers, the embedding materializer and online serving refresh all
contend for the same DistServers (the reference's server-client
topology has no notion of tenancy — PAPER.md L5). This module is the
governance layer that turns contention from an outage mode into a
bounded, observable condition (docs/multi_tenancy.md):

* **Tenant model.** Every producer/block-stream registration carries a
  tenant id plus a priority class (``interactive`` > ``training`` >
  ``bulk``) and a fair-share weight. Unknown tenants auto-register
  under the config's default spec, so single-tenant deployments run
  unchanged.
* **Admission control** (:class:`AdmissionController`): per-tenant
  quotas bound concurrent producers, shm ring bytes and in-flight
  block bytes, enforced at producer creation and the ``block_*`` RPC
  handlers (dist_server.py). Over-quota requests raise a TYPED,
  RETRYABLE rejection — :class:`TenantQuotaExceeded` /
  :class:`TenantThrottled` — that crosses the RPC wire as a structured
  payload (rpc.register_wire_error) and reconstructs client-side,
  never as an opaque timeout.
* **Weighted-fair scheduling** (:class:`WeightedFairScheduler`): the
  server-side block build/fetch lane drains by deficit-weighted
  round-robin over tenants with STRICT priority preemption — an
  interactive serving-refresh block jumps a bulk trainer's backlog —
  so throughput under contention splits by configured weight rather
  than arrival order.
* **Visible backpressure** (:func:`with_backpressure`): clients wrap
  throttle-prone RPCs in a bounded exponential backoff that emits
  ``tenant.backpressure_ms`` + a ``tenant.throttle`` span under the
  epoch root; when the RetryPolicy-style budget runs out, a
  permanently-starved tenant fails LOUDLY with its quota state in the
  error (:class:`TenantStarvedError`) instead of stalling.

The scheduler + admission state is deliberately host-only Python (no
jax): it runs on the RPC dispatch threads of the sampling servers.
"""
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .. import metrics
from ..metrics import spans
from ..utils.faults import fault_point
from .rpc import register_wire_error

#: strict preemption order: an interactive tenant's queued work is
#: always granted before any training work, which preempts bulk
PRIORITY_CLASSES = ('interactive', 'training', 'bulk')

_DEFAULT_TENANT = 'default'


def _priority_index(priority: str) -> int:
  try:
    return PRIORITY_CLASSES.index(priority)
  except ValueError:
    raise ValueError(
        f'unknown priority class {priority!r}; expected one of '
        f'{PRIORITY_CLASSES}') from None


@dataclass(frozen=True)
class TenantSpec:
  """One tenant's contract with the cluster. ``None`` quota fields are
  unlimited; ``producer_ttl`` overrides the server-wide ttl for this
  tenant's producers (one vanished client reaps only its own
  streams)."""
  tenant: str = _DEFAULT_TENANT
  priority: str = 'training'
  weight: float = 1.0
  max_producers: Optional[int] = None
  max_ring_bytes: Optional[int] = None
  max_inflight_bytes: Optional[int] = None
  producer_ttl: Optional[float] = None

  def __post_init__(self):
    _priority_index(self.priority)
    if not self.weight > 0:
      raise ValueError(f'tenant {self.tenant!r}: weight must be > 0, '
                       f'got {self.weight}')


@dataclass
class TenancyConfig:
  """Server-side tenancy configuration (DistServer(tenancy=...)).

  ``specs`` seeds the known tenants; unknown tenants auto-register
  from ``default_spec`` (so turning tenancy on never hard-rejects a
  legacy client). ``sched_timeout`` bounds how long a queued build
  waits for its fair-share grant before the server answers with a
  retryable :class:`TenantThrottled` — the scheduler's backpressure
  valve. ``quantum`` is the DWRR deficit refill per visit, in cost
  units (batches)."""
  specs: List[TenantSpec] = field(default_factory=list)
  default_spec: TenantSpec = field(default_factory=TenantSpec)
  sched_timeout: float = 30.0
  quantum: float = 4.0


class TenantRejection(RuntimeError):
  """Base of the typed, RETRYABLE tenancy rejections. Crosses the RPC
  wire as ``(etype, payload)`` (rpc.py) and reconstructs client-side,
  so loaders can distinguish 'back off and retry' from genuine remote
  failures. Deliberately NOT a ConnectionError/TimeoutError/OSError:
  request_sync's blind retry_on and the remote-scan dead-server
  classifier (_DEAD_EXCS) must both ignore it — backoff happens at the
  tenancy-aware layer (:func:`with_backpressure`), visibly."""

  WIRE_TYPE = 'TenantRejection'
  retryable = True

  def __init__(self, tenant: str, resource: str, message: str,
               quota: Optional[dict] = None,
               retry_after: Optional[float] = None):
    super().__init__(
        f'tenant {tenant!r} {message} (resource={resource}, '
        f'quota={quota})')
    self.tenant = tenant
    self.resource = resource
    self.message = message
    self.quota = dict(quota or {})
    self.retry_after = retry_after

  def to_wire(self) -> dict:
    return dict(tenant=self.tenant, resource=self.resource,
                message=self.message, quota=self.quota,
                retry_after=self.retry_after)

  @classmethod
  def from_wire(cls, payload: dict) -> 'TenantRejection':
    return cls(payload.get('tenant', _DEFAULT_TENANT),
               payload.get('resource', '?'),
               payload.get('message', 'rejected'),
               quota=payload.get('quota'),
               retry_after=payload.get('retry_after'))


class TenantQuotaExceeded(TenantRejection):
  """Admission rejection: a hard per-tenant quota (concurrent
  producers, ring bytes) is full. Retryable — the quota frees when the
  tenant destroys (or the reaper reaps) a producer."""

  WIRE_TYPE = 'TenantQuotaExceeded'


class TenantThrottled(TenantRejection):
  """Flow-control rejection: in-flight block bytes over quota, or the
  fair-share grant did not arrive within ``sched_timeout``. Retryable
  by design — this is the visible form of backpressure."""

  WIRE_TYPE = 'TenantThrottled'


class TenantStarvedError(RuntimeError):
  """Raised CLIENT-side when a tenant's backpressure budget is
  exhausted: the loud failure mode for a permanently-starved tenant,
  carrying the last quota snapshot the server reported (the
  issue-the-operator-can-act-on contract — never a silent stall or an
  opaque QueueTimeoutError)."""

  def __init__(self, describe: str, last: TenantRejection,
               waited_s: float):
    super().__init__(
        f'{describe}: tenant {last.tenant!r} starved — backpressure '
        f'budget exhausted after {waited_s:.1f}s of throttle waits; '
        f'last rejection: {last.message} (resource={last.resource}, '
        f'quota={last.quota})')
    self.tenant = last.tenant
    self.quota = dict(last.quota)
    self.waited_s = waited_s


for _cls in (TenantRejection, TenantQuotaExceeded, TenantThrottled):
  register_wire_error(_cls.WIRE_TYPE, _cls.from_wire)


# --------------------------------------------------------------- admission


class AdmissionController:
  """Per-tenant quota accounting + the pid→tenant map (dist_server
  wiring). All methods are thread-safe; raises are typed/retryable."""

  def __init__(self, config: Optional[TenancyConfig] = None):
    self.config = config or TenancyConfig()
    self._lock = threading.Lock()
    self._specs: Dict[str, TenantSpec] = {
        s.tenant: s for s in self.config.specs}
    self._pid_tenant: Dict[int, str] = {}
    self._pid_ring: Dict[int, int] = {}
    self._inflight: Dict[str, int] = {}
    self._reaped_pids: Dict[int, str] = {}   # tombstones for diagnostics

  # ------------------------------------------------------------- specs

  def register(self, tenant: str, priority: Optional[str] = None,
               weight: Optional[float] = None) -> TenantSpec:
    """Fetch-or-create the tenant's spec, applying any explicit
    priority/weight override (the ``update_tenant`` RPC and the
    create-time registration both land here)."""
    import dataclasses
    with self._lock:
      spec = self._specs.get(tenant)
      if spec is None:
        spec = dataclasses.replace(self.config.default_spec,
                                   tenant=tenant)
      changes = {}
      if priority is not None and priority != spec.priority:
        changes['priority'] = priority
      if weight is not None and weight != spec.weight:
        changes['weight'] = weight
      if changes:
        spec = dataclasses.replace(spec, **changes)
      self._specs[tenant] = spec
      return spec

  def spec(self, tenant: str) -> TenantSpec:
    with self._lock:
      s = self._specs.get(tenant)
    return s if s is not None else self.register(tenant)

  def tenant_of(self, pid: int) -> str:
    with self._lock:
      return self._pid_tenant.get(
          pid, self._reaped_pids.get(pid, _DEFAULT_TENANT))

  def ttl_for_pid(self, pid: int,
                  default: Optional[float]) -> Optional[float]:
    """The reap threshold for this producer: its tenant's
    ``producer_ttl`` when set, else the server-wide default."""
    spec = self.spec(self.tenant_of(pid))
    return spec.producer_ttl if spec.producer_ttl is not None \
        else default

  def min_ttl(self, default: Optional[float]) -> Optional[float]:
    """The smallest armed ttl (reaper poll cadence); None when no ttl
    is armed anywhere."""
    with self._lock:
      ttls = [s.producer_ttl for s in self._specs.values()
              if s.producer_ttl is not None]
    if self.config.default_spec.producer_ttl is not None:
      ttls.append(self.config.default_spec.producer_ttl)
    if default is not None:
      ttls.append(default)
    return min(ttls) if ttls else None

  # --------------------------------------------------------- admission

  def snapshot(self, tenant: str) -> dict:
    """This tenant's quota state — rides every rejection and the
    stale-handle/starvation errors (the operator-actionable context)."""
    spec = self.spec(tenant)
    with self._lock:
      pids = [p for p, t in self._pid_tenant.items() if t == tenant]
      ring = sum(self._pid_ring.get(p, 0) for p in pids)
      inflight = self._inflight.get(tenant, 0)
    return dict(tenant=tenant, priority=spec.priority,
                weight=spec.weight, producers=len(pids),
                max_producers=spec.max_producers, ring_bytes=ring,
                max_ring_bytes=spec.max_ring_bytes,
                inflight_bytes=inflight,
                max_inflight_bytes=spec.max_inflight_bytes,
                producer_ttl=spec.producer_ttl)

  def snapshot_all(self) -> Dict[str, dict]:
    with self._lock:
      tenants = set(self._specs) | set(self._pid_tenant.values())
    return {t: self.snapshot(t) for t in sorted(tenants)}

  def describe_pid(self, pid: int) -> str:
    """Context suffix for stale-handle errors: tenant + quota snapshot
    (satellite: never a bare 'producer unknown')."""
    tenant = self.tenant_of(pid)
    reaped = pid in self._reaped_pids
    return (f' [tenant={tenant!r}'
            f'{" (idle-reaped)" if reaped else ""}, '
            f'quota={self.snapshot(tenant)}]')

  def admit_producer(self, tenant: str, pid: int, ring_bytes: int = 0,
                     priority: Optional[str] = None,
                     weight: Optional[float] = None):
    """Admission gate for producer creation (sampling AND block): the
    ``tenant.admit`` fault site lives here; over-quota raises the
    typed, retryable :class:`TenantQuotaExceeded` with the quota
    snapshot aboard."""
    fault_point('tenant.admit')
    spec = self.register(tenant, priority=priority, weight=weight)
    snap = self.snapshot(tenant)
    if spec.max_producers is not None and \
        snap['producers'] >= spec.max_producers:
      metrics.inc('tenant.admit_rejections')
      raise TenantQuotaExceeded(
          tenant, 'producers',
          f'at its concurrent-producer quota '
          f'({snap["producers"]}/{spec.max_producers})', quota=snap)
    if spec.max_ring_bytes is not None and \
        snap['ring_bytes'] + ring_bytes > spec.max_ring_bytes:
      metrics.inc('tenant.admit_rejections')
      raise TenantQuotaExceeded(
          tenant, 'ring_bytes',
          f'would exceed its shm ring quota '
          f'({snap["ring_bytes"]} + {ring_bytes} > '
          f'{spec.max_ring_bytes})', quota=snap)
    with self._lock:
      self._pid_tenant[pid] = tenant
      if ring_bytes:
        self._pid_ring[pid] = int(ring_bytes)

  def release_producer(self, pid: int, reaped: bool = False):
    with self._lock:
      tenant = self._pid_tenant.pop(pid, None)
      self._pid_ring.pop(pid, None)
      if tenant is not None and reaped:
        self._reaped_pids[pid] = tenant
    return tenant

  # ------------------------------------------------- in-flight bytes

  def check_inflight(self, tenant: str):
    """The produce-ahead throttle: a tenant whose staged-but-unfetched
    block bytes are at quota gets a retryable TenantThrottled (the
    client's fetch of the resident frame is never blocked — fetching
    DRAINS the quota)."""
    spec = self.spec(tenant)
    if spec.max_inflight_bytes is None:
      return
    with self._lock:
      used = self._inflight.get(tenant, 0)
    if used >= spec.max_inflight_bytes:
      metrics.inc('tenant.throttled')
      raise TenantThrottled(
          tenant, 'inflight_bytes',
          f'throttled: {used} staged block bytes >= quota '
          f'{spec.max_inflight_bytes} — fetch staged blocks (or wait) '
          'before producing ahead', quota=self.snapshot(tenant),
          retry_after=0.05)

  def charge_inflight(self, tenant: str, nbytes: int):
    with self._lock:
      self._inflight[tenant] = self._inflight.get(tenant, 0) \
          + int(nbytes)

  def release_inflight(self, tenant: str, nbytes: int):
    with self._lock:
      self._inflight[tenant] = max(
          0, self._inflight.get(tenant, 0) - int(nbytes))


# --------------------------------------------------------------- scheduling


class _Ticket:
  __slots__ = ('cost', 'granted', 'done')

  def __init__(self, cost: float):
    self.cost = float(cost)
    self.granted = threading.Event()
    self.done = threading.Event()


class WeightedFairScheduler:
  """Deficit-weighted round-robin over tenants with strict priority
  preemption — the server-side block work lane (docs/multi_tenancy.md).

  Callers enqueue a ticket and block until the drain thread grants it;
  exactly one grant is outstanding at a time, so the granted caller
  owns the build lane and signals ``done`` when its work finishes.
  Grant order: the highest priority class with queued work always
  wins (an interactive ticket enqueued behind a bulk backlog is
  granted next — strict preemption of the BACKLOG; a running build is
  never interrupted); within a class, classic DRR — each visited
  tenant's deficit grows by ``quantum * weight`` and its head ticket
  is granted once the deficit covers its cost, so long-run throughput
  splits by weight.

  A ticket not granted within ``timeout`` raises the retryable
  :class:`TenantThrottled` — scheduler wait IS backpressure, and the
  client's bounded backoff (:func:`with_backpressure`) makes it
  visible instead of letting the RPC hang."""

  def __init__(self, admission: AdmissionController,
               quantum: float = 4.0, timeout: float = 30.0):
    self._admission = admission
    self.quantum = float(quantum)
    self.timeout = float(timeout)
    self._lock = threading.Lock()
    self._wake = threading.Condition(self._lock)
    # DRR state shared between caller threads (run/close) and the
    # grant thread (_drain/_pick) — guarded by _lock; _wake is a
    # Condition WRAPPING _lock, so waiting on it holds the same lock
    # per priority class: tenant -> deque of tickets (FIFO per tenant)
    # graftlint: shared[_lock]
    self._queues: Dict[int, Dict[str, List[_Ticket]]] = {
        i: {} for i in range(len(PRIORITY_CLASSES))}
    # graftlint: shared[_lock]
    self._deficit: Dict[str, float] = {}
    # graftlint: shared[_lock]
    self._rr: Dict[int, int] = {i: 0 for i in range(len(PRIORITY_CLASSES))}
    self.served: Dict[str, float] = {}   # granted cost per tenant
    self._stop = False
    self._thread = threading.Thread(target=self._drain, daemon=True,
                                    name='glt-tenant-sched')
    self._thread.start()

  def close(self):
    with self._lock:
      self._stop = True
      self._wake.notify_all()
    self._thread.join(timeout=5.0)

  def run(self, tenant: str, cost: float, fn: Callable,
          timeout: Optional[float] = None):
    """Run ``fn`` under this tenant's fair-share grant. Blocks until
    granted (bounded), runs ``fn`` on the CALLING thread (results and
    errors propagate naturally), then releases the lane."""
    spec = self._admission.spec(tenant)
    prio = _priority_index(spec.priority)
    ticket = _Ticket(cost)
    t0 = time.perf_counter()
    with self._lock:
      self._queues[prio].setdefault(tenant, []).append(ticket)
      self._wake.notify_all()
    if not ticket.granted.wait(self.timeout if timeout is None
                               else timeout):
      with self._lock:
        q = self._queues[prio].get(tenant)
        if q is not None and ticket in q:
          q.remove(ticket)
      # the grant may have raced the timeout: _pick pops under the
      # lock but sets `granted` after releasing it, so give a ticket
      # that is no longer queued a beat to show its grant — if it DID
      # arrive, the lane is ours and must be released normally
      if not ticket.granted.wait(0.1):
        metrics.inc('tenant.throttled')
        raise TenantThrottled(
            tenant, 'schedule',
            f'throttled: no fair-share grant within '
            f'{timeout if timeout is not None else self.timeout}s '
            '(higher-priority/weight tenants hold the block lane)',
            quota=self._admission.snapshot(tenant), retry_after=0.1)
    metrics.observe('tenant.sched_wait_ms',
                    (time.perf_counter() - t0) * 1e3)
    try:
      return fn()
    finally:
      ticket.done.set()
      with self._lock:
        self.served[tenant] = self.served.get(tenant, 0.0) + ticket.cost
        self._wake.notify_all()

  def set_weight(self, tenant: str, weight: float):
    self._admission.register(tenant, weight=weight)

  # ------------------------------------------------------------ drain

  # graftlint: locked[_lock]
  def _pick(self) -> Optional[_Ticket]:
    """Next ticket under the lock, or None when nothing is runnable.
    Strict priority first; DRR within the class."""
    for prio in range(len(PRIORITY_CLASSES)):
      tenants = sorted(t for t, q in self._queues[prio].items() if q)
      if not tenants:
        continue
      # Classic DRR, one grant per call: the cursor tenant keeps the
      # lane while its deficit covers its head ticket; an unaffordable
      # head refills ONCE (quantum * weight) and passes the cursor on.
      # Refilling only on the unaffordable visit is load-bearing —
      # topping up every visited tenant before the affordability check
      # makes any quantum >= cost degenerate to plain round-robin,
      # with the weights ignored.
      start = self._rr[prio]
      n = len(tenants)
      for visit in itertools.count():
        t = tenants[(start + visit) % n]
        head = self._queues[prio][t][0]
        if self._deficit.get(t, 0.0) >= head.cost or visit >= 64 * n:
          # past the defensive cap (huge cost vs tiny weights), grant
          # the current head regardless so the lane cannot wedge
          self._deficit[t] = self._deficit.get(t, 0.0) - head.cost
          self._queues[prio][t].pop(0)
          if not self._queues[prio][t]:
            del self._queues[prio][t]
            # an emptied tenant forfeits its leftover deficit: an idle
            # tenant must not hoard service credit into its next burst
            self._deficit.pop(t, None)
          self._rr[prio] = (start + visit) % n
          return head
        w = max(self._admission.spec(t).weight, 1e-3)
        self._deficit[t] = self._deficit.get(t, 0.0) + self.quantum * w
    return None

  def _drain(self):
    while True:
      with self._lock:
        while not self._stop:
          ticket = self._pick()
          if ticket is not None:
            break
          self._wake.wait(timeout=0.5)
        if self._stop:
          return
      ticket.granted.set()
      # one grant outstanding: wait for the caller to finish its build
      # (or vanish — the done wait is bounded so a killed client
      # thread cannot wedge every other tenant's lane forever)
      ticket.done.wait(timeout=120.0)


# ------------------------------------------------------------ backpressure


def with_backpressure(fn: Callable, describe: str = '',
                      budget_s: float = 120.0,
                      base_delay: float = 0.05,
                      max_delay: float = 2.0,
                      tenant: Optional[str] = None,
                      on_reject: Optional[Callable] = None):
  """Run ``fn()``, absorbing typed tenancy rejections with a bounded
  exponential backoff — the client half of the backpressure contract.

  Every throttle episode emits ``tenant.backpressure_ms`` (the wait)
  plus a ``tenant.throttle`` span carrying the tenant and rejected
  resource, parented under whatever span is current (the epoch root on
  the dispatch thread; the stager worker adopts the epoch context).
  When the cumulative wait exceeds ``budget_s`` the tenant fails
  LOUDLY: :class:`TenantStarvedError` with the server's last quota
  snapshot aboard — never a silent stall, never an opaque
  QueueTimeoutError (docs/multi_tenancy.md)."""
  waited = 0.0
  attempt = 0
  while True:
    try:
      return fn()
    except TenantRejection as e:
      fault_point('tenant.throttle')
      if on_reject is not None:
        on_reject(e)
      delay = e.retry_after if e.retry_after is not None \
          else base_delay * (2 ** attempt)
      delay = min(max(delay, base_delay), max_delay)
      if waited + delay > budget_s:
        metrics.inc('tenant.starved')
        raise TenantStarvedError(describe or 'backpressured call',
                                 e, waited) from e
      t0 = time.perf_counter()
      with spans.span('tenant.throttle',
                      tenant=str(tenant or e.tenant),
                      resource=e.resource, attempt=attempt):
        time.sleep(delay)
      wait_ms = (time.perf_counter() - t0) * 1e3
      metrics.observe('tenant.backpressure_ms', wait_ms)
      waited += delay
      attempt += 1
