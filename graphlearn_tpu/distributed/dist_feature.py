"""Sharded distributed feature store: hot-vertex cache + miss-only exchange.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_feature.py. The
reference splits a lookup into a local UVA gather plus per-remote-partition
async RPCs and stitches futures (dist_feature.py:134-269). Here the whole
lookup is ONE jitted SPMD function; the asyncio machinery dissolves.

Byte posture (this file owns the largest per-batch wire volume in the
system — feature rows are ~F x wider than sampler id traffic, PERF.md
"Feature path"):

  1. **Replicated hot cache** (GLT's UnifiedTensor split, SURVEY
     §UnifiedTensor; reference data/feature.py split_ratio + hotness
     reorder): the globally hottest ``cache_rows`` rows live replicated on
     every shard next to its owned partition. Requested ids are split
     hit/miss INSIDE the program by a searchsorted over the sorted cached
     id set; hits gather locally and never touch the interconnect.
  2. **Miss-only bucketed exchange**: only cache misses — deduped within
     the batch (one request per unique id, response scattered back to all
     its slots) — enter the all_to_all, packed into per-destination
     buckets of capacity ``bucket_frac x mean miss load`` with the
     psum-replicated ``lax.cond`` full-width fallback (exactly the
     sampler-exchange contract: loss-free on EVERY input,
     dist_neighbor_sampler._exchange_hop). On a 2-axis ('slice', 'chip')
     mesh the transposes go hierarchical: full-width along 'chip' (ICI),
     fractional along 'slice' (DCN), retraced for the response.
  3. **Wire dtype**: ``wire_dtype=jnp.bfloat16`` ships response rows at
     half width and upcasts to the storage dtype after
     ``gather_from_buckets`` — independent of hit rate.

On-device hit/miss/overflow counters ride the same program (a [P, 4]
accumulator threaded through every ``get``), so hit rates are observable
with ZERO per-batch host syncs: fetch with :meth:`stats` /
:meth:`publish_stats` once per epoch.
"""
from typing import Optional

import numpy as np

from .. import ops
from ..ops.route import exchange_capacity

INT32_MAX = np.iinfo(np.int32).max

# stats accumulator layout (per shard, int32)
STAT_HITS, STAT_MISSES, STAT_UNIQUE, STAT_OVERFLOW = range(4)


def miss_capacity(request_width: int, nparts: int, bucket_frac,
                  hit_rate: float = 0.0) -> int:
  """Static per-destination bucket capacity for a miss-only feature
  exchange over ``request_width`` request slots: ``bucket_frac x`` the
  mean per-destination MISS load (the expected unique-miss width is
  ``request_width * (1 - hit_rate)``), rounded to lanes and clamped to
  the loss-free full width. ``bucket_frac=None`` keeps the full-width
  posture (every bucket ``request_width`` wide, can never overflow).
  Thin front of the shared capacity policy in ops.route —
  the sampler's exchange resolves through the same function."""
  return exchange_capacity(request_width, nparts, bucket_frac, hit_rate)


def feature_exchange_mb(request_width: int, nparts: int, feat_dim: int,
                        bucket_frac=2.0, wire_bytes: int = 4,
                        id_bytes: int = 4, hit_rate: float = 0.0) -> float:
  """Analytic all_to_all MB/shard/batch of one distributed feature
  lookup: [P, cap] id requests + [P, cap, F] row responses. The
  full-width posture (the pre-cache baseline) is ``bucket_frac=None,
  wire_bytes=4, hit_rate=0``. Benchmarks report this next to measured
  volumes so byte regressions are visible without a trace."""
  cap = miss_capacity(request_width, nparts, bucket_frac, hit_rate)
  return nparts * cap * (id_bytes + feat_dim * wire_bytes) / 1e6


class DistFeature:
  """Reference: dist_feature.py:51-269.

  Args:
    num_partitions: partitions == product of the mesh axis sizes.
    feat_parts: list of (ids [n_p], feats [n_p, F]) per partition (the
      FeaturePartitionData payload, cache already merged via
      cat_feature_cache).
    feature_pb: [N] id -> owning partition (the *feature* partition book —
      may differ from the graph node_pb once caches move entries).
    mesh: the graph mesh ('g',) flat or ('slice', 'chip') hierarchical.
    dtype: optional storage dtype (bf16 halves HBM + ICI bytes).
    split_ratio: fraction of the N globally hottest rows replicated
      per shard (0 = no cache, 1 = fully replicated), mirroring the
      local ``data.Feature`` API.
    cache_rows: absolute row count for the hot cache (overrides
      ``split_ratio``).
    hotness: [N] per-id hotness score (higher = hotter) selecting the
      cached set — in-degrees (``data.reorder.in_degree_hotness``) or a
      presampling frequency count (``data.reorder.frequency_hotness``).
      None assumes ids are already hot-ordered (row 0 hottest), the
      layout ``data.reorder.sort_by_in_degree`` produces.
    wire_dtype: optional dtype for response rows ON THE WIRE (e.g.
      jnp.bfloat16); storage and results stay ``dtype``.
    bucket_frac: miss-exchange bucket slack over the mean miss load
      (None = full-width loss-free posture, the pre-cache baseline).
    dedup: dedup misses within the batch before the exchange (one
      request per unique id; the response fans back to every slot).
  """

  def __init__(self, num_partitions: int, feat_parts, feature_pb,
               mesh=None, dtype=None, split_ratio: float = 0.0,
               cache_rows: Optional[int] = None, hotness=None,
               wire_dtype=None, bucket_frac=2.0, dedup: bool = True):
    self.num_partitions = num_partitions
    self.feature_pb = np.asarray(feature_pb)
    self.mesh = mesh
    self._init_storage(feat_parts, dtype)
    self.split_ratio = float(split_ratio)
    self.wire_dtype = wire_dtype
    self.bucket_frac = bucket_frac
    self.dedup = dedup
    n_total = int(self.feature_pb.shape[0])
    h = int(cache_rows) if cache_rows is not None \
        else int(n_total * self.split_ratio)
    h = max(0, min(h, n_total))
    self.cache_rows = h
    # hit-rate floor used to size the miss buckets: uniform requests hit
    # at exactly H/N; skewed-to-hot requests (the point of the cache)
    # hit more, so capacities sized on (1 - H/N) only gain slack
    self._cache_frac = h / n_total if n_total else 0.0
    if h > 0:
      if hotness is None:
        hot_ids = np.arange(h, dtype=np.int64)
      else:
        hotness = np.asarray(hotness).reshape(-1)
        assert hotness.shape[0] == n_total, (
            f'hotness covers {hotness.shape[0]} ids, feature_pb has '
            f'{n_total}')
        hot_ids = np.argsort(-hotness, kind='stable')[:h]
      self.cache_ids = np.sort(hot_ids).astype(np.int32)
      self.cache_feats = self.cpu_get(self.cache_ids)
    else:
      self.cache_ids = None
      self.cache_feats = None
    self._dev = None
    self._stats = None
    self._fns = {}

  def _init_storage(self, feat_parts, dtype):
    """Pack the per-partition (ids, rows) blocks into the sorted
    [P, n_max] id table + the [P, n_max, F] row store. The row store
    is HOST-RAM-resident here; storage.TieredDistFeature overrides
    this to keep rows in memory-mapped disk tiers (the out-of-core
    shard layout, docs/storage.md) while the id table — the small
    routing structure — stays resident."""
    n_max = max(ids.shape[0] for ids, _ in feat_parts)
    f = feat_parts[0][1].shape[1]
    p = len(feat_parts)
    dt = np.dtype(dtype or feat_parts[0][1].dtype)
    self.n_max = n_max
    self._fdim = int(f)
    self.storage_dtype = dt
    self.feat_ids = np.full((p, n_max), INT32_MAX, np.int32)
    self.feats = np.zeros((p, n_max, f), dt)
    for i, (ids, fe) in enumerate(feat_parts):
      order = np.argsort(ids)
      self.feat_ids[i, :ids.shape[0]] = ids[order]
      self.feats[i, :ids.shape[0]] = fe[order]

  @property
  def feature_dim(self) -> int:
    return self._fdim

  def device_arrays(self):
    if self._dev is None:
      from jax.sharding import NamedSharding, PartitionSpec as P
      from ..utils import global_device_put
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      repl = NamedSharding(self.mesh, P())
      h = self.cache_rows
      cache_ids = (self.cache_ids if h else
                   np.full((1,), INT32_MAX, np.int32))
      cache_feats = (self.cache_feats if h else
                     np.zeros((1, self.feature_dim), self.storage_dtype))
      self._dev = dict(
          feat_ids=global_device_put(self.feat_ids, shard),
          feats=global_device_put(self.feats, shard),
          feature_pb=global_device_put(self.feature_pb.astype(np.int32),
                                       repl),
          cache_ids=global_device_put(cache_ids, repl),
          cache_feats=global_device_put(cache_feats, repl))
    return self._dev

  # ------------------------------------------------------------ stats
  def _stats_dev(self):
    if self._stats is None:
      import jax
      from jax.sharding import NamedSharding, PartitionSpec as P
      from ..utils import global_device_put
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      self._stats = global_device_put(
          np.zeros((self.num_partitions, 4), np.int32), shard)
    return self._stats

  def stats(self) -> dict:
    """Host snapshot of the on-device counters, summed over shards.

    This is the ONE device->host fetch of the feature path — call it per
    epoch (loaders do), never per batch. On a multi-host mesh only this
    process's shard rows are fetched (a global np.asarray would span
    non-addressable devices and raise) — counters are per-shard disjoint
    rows of the [P, 4] accumulator, so the result is the process-local
    view; aggregate across hosts out of band if needed."""
    if self._stats is None:
      tot = np.zeros((4,), np.int64)
    elif getattr(self._stats, 'is_fully_addressable', True):
      tot = np.asarray(self._stats).sum(axis=0).astype(np.int64)
    else:
      tot = sum(np.asarray(s.data).reshape(-1, 4).sum(axis=0)
                for s in self._stats.addressable_shards).astype(np.int64)
    lookups = int(tot[STAT_HITS] + tot[STAT_MISSES])
    return dict(hits=int(tot[STAT_HITS]), misses=int(tot[STAT_MISSES]),
                unique_misses=int(tot[STAT_UNIQUE]),
                overflow=int(tot[STAT_OVERFLOW]), lookups=lookups,
                hit_rate=(int(tot[STAT_HITS]) / lookups if lookups
                          else 0.0))

  def reset_stats(self):
    self._stats = None

  def publish_stats(self, prefix: str = 'dist_feature'):
    """Fetch + reset the on-device counters into utils.trace named
    counters ('<prefix>.hits' etc.) — the per-epoch surfacing hook."""
    from ..utils import trace
    s = self.stats()
    for k in ('hits', 'misses', 'unique_misses', 'overflow', 'lookups'):
      if s[k]:
        # graftlint: allow[metric-registry] caller-chosen prefix; both families (dist_feature.*/dist_label.*) are registered wildcards
        trace.counter_inc(f'{prefix}.{k}', s[k])
    self.reset_stats()
    return s

  # ---------------------------------------------------------- program
  def _shard_body(self, b: int, slab: bool = False):
    """Per-shard lookup body over UNWRAPPED per-shard views — the core
    of the one-dispatch program, exposed so outer shard_map programs
    (DistScanTrainer's scanned epoch) can inline the exact same
    cache-split -> miss-dedup -> bucketed-exchange -> merge computation
    and thread the [4] stats row through their own carry.

    Returns ``body(feat_ids [n], feats [n, F], pb, cache_ids,
    cache_feats, stats_row [4], ids [b], mask [b]) ->
    (rows [b, F], new_stats_row [4])``. Must be traced on this store's
    mesh (the exchange collectives run over every mesh axis).

    ``slab=True`` is the SLAB-BACKED lookup path (device
    oversubscription through the shard exchange — storage/dist_scan.py,
    docs/storage.md): ``feats`` is then the pytree ``(hot [H, F],
    slab_pos [cap], slab_rows [cap, F])`` instead of the full
    ``[n, F]`` partition — a remote request resolves its position in
    this shard's sorted id table exactly as before, but the ROW comes
    from the HBM hot prefix (position < H) or the chunk's staged slab
    (searchsorted over the staged position list, INT32_MAX pads never
    match). Under an exact miss-exchange program every requested
    position >= H is in the slab by construction, so the exchanged
    bytes are identical to the all-HBM path."""
    import jax
    import jax.numpy as jnp

    nparts = self.num_partitions
    fdim = self.feature_dim
    fdtype = self.storage_dtype
    wdtype = self.wire_dtype or fdtype
    h = self.cache_rows
    dedup = self.dedup
    bucket_frac = self.bucket_frac
    hit_est = self._cache_frac
    # collectives/specs over every mesh axis: works identically on the
    # flat ('g',) mesh and a 2-axis ('slice', 'chip') mesh
    ax = tuple(self.mesh.axis_names)
    sizes = tuple(self.mesh.shape[a] for a in ax)
    hier = len(ax) == 2

    if slab:
      def lookup_local(feat_ids, feats, flat):
        """Slab-backed rows for a flat request vector: position from
        the sorted owned-id table as usual, payload from the hot
        prefix or the staged slab (zeros where absent/padded — an
        impossible case for planned rows under an exact program)."""
        hot, slab_pos, slab_rows = feats
        pos = jnp.clip(jnp.searchsorted(feat_ids, flat), 0,
                       feat_ids.shape[0] - 1)
        found = feat_ids[pos] == flat
        hp = hot.shape[0]
        hot_rows = hot[jnp.clip(pos, 0, hp - 1)]
        sp = jnp.clip(jnp.searchsorted(slab_pos, pos.astype(jnp.int32)),
                      0, slab_pos.shape[0] - 1)
        in_slab = slab_pos[sp] == pos.astype(jnp.int32)
        rows = jnp.where((pos < hp)[:, None], hot_rows,
                         jnp.where(in_slab[:, None], slab_rows[sp], 0))
        return jnp.where(found[:, None], rows, 0)
    else:
      def lookup_local(feat_ids, feats, flat):
        """Rows for a flat request vector over this shard's sorted owned
        ids (zeros where absent/padded)."""
        pos = jnp.clip(jnp.searchsorted(feat_ids, flat), 0,
                       feat_ids.shape[0] - 1)
        found = feat_ids[pos] == flat
        return jnp.where(found[:, None], feats[pos], 0)

    def exchange_flat(feat_ids, feats, pb, req, rmask):
      """Fractional bucketed all_to_all with replicated full-width
      fallback (sampler _exchange_hop parity). Returns rows [b, F]
      (storage dtype) in request order + the overflow count."""
      dest = jnp.where(rmask, pb[jnp.maximum(req, 0)], nparts)
      slot, ok = ops.route_slots(dest, rmask, capacity=b)

      def do(cap: int):
        okc = ok & (slot < cap)
        send = ops.scatter_to_buckets(req, dest, slot, okc, nparts, cap)
        r = jax.lax.all_to_all(send, ax, 0, 0)          # [P, cap] reqs
        rows = lookup_local(feat_ids, feats, r.reshape(-1))
        rows = rows.astype(wdtype).reshape(nparts, cap, fdim)
        resp = jax.lax.all_to_all(rows, ax, 0, 0)       # [P, cap, F]
        back = ops.gather_from_buckets(resp, dest, slot, okc, fill=0)
        return back.astype(fdtype)

      cap_small = miss_capacity(b, nparts, bucket_frac, hit_est)
      if cap_small >= b:
        return do(b), jnp.int32(0)
      ovf = jnp.sum(rmask & (slot >= cap_small)).astype(jnp.int32)
      total_ovf = jax.lax.psum(ovf, ax)
      rows = jax.lax.cond(total_ovf == 0, lambda _: do(cap_small),
                          lambda _: do(b), None)
      return rows, ovf

    def exchange_hier(feat_ids, feats, pb, req, rmask):
      """2-stage exchange for a (slice, chip) mesh: full-width along
      'chip' (ICI), fractional along 'slice' (DCN), retraced for the
      response — the feature-row counterpart of
      dist_neighbor_sampler._exchange_hop_hier. Stage-2 capacity is
      sized on the mean VALID miss load (~miss width over S), not the
      C*b slot count."""
      s_ax, c_ax = ax
      s_sz, c_sz = sizes
      dest = jnp.where(rmask, pb[jnp.maximum(req, 0)], nparts)
      c_dst = jnp.where(rmask, dest % c_sz, c_sz)
      slot1, ok1 = ops.route_slots(c_dst, rmask, capacity=b)
      send1 = ops.scatter_to_buckets(req, c_dst, slot1, ok1, c_sz, b)
      req1 = jax.lax.all_to_all(send1, c_ax, 0, 0)      # [C, b] via ICI
      mid = req1.reshape(-1)
      mid_mask = mid >= 0
      mdest = jnp.where(mid_mask, pb[jnp.maximum(mid, 0)] // c_sz, s_sz)
      slot2, ok2f = ops.route_slots(mdest, mid_mask, capacity=c_sz * b)
      cap2 = (c_sz * b if bucket_frac is None or s_sz <= 1 else
              min(c_sz * b,
                  miss_capacity(b, s_sz, bucket_frac, hit_est)))

      def hier_path(_):
        ok2 = ok2f & (slot2 < cap2)
        send2 = ops.scatter_to_buckets(mid, mdest, slot2, ok2, s_sz,
                                       cap2)
        req2 = jax.lax.all_to_all(send2, s_ax, 0, 0)    # [S, cap2] DCN
        rows = lookup_local(feat_ids, feats, req2.reshape(-1))
        rows = rows.astype(wdtype).reshape(s_sz, cap2, fdim)
        r2 = jax.lax.all_to_all(rows, s_ax, 0, 0)
        b2 = ops.gather_from_buckets(r2, mdest, slot2, ok2, fill=0)
        r1 = jax.lax.all_to_all(b2.reshape(c_sz, b, fdim), c_ax, 0, 0)
        back = ops.gather_from_buckets(r1, c_dst, slot1, ok1, fill=0)
        return back.astype(fdtype)

      def flat_path(_):
        slotp, okp = ops.route_slots(dest, rmask, capacity=b)
        send = ops.scatter_to_buckets(req, dest, slotp, okp, nparts, b)
        r = jax.lax.all_to_all(send, ax, 0, 0)
        rows = lookup_local(feat_ids, feats, r.reshape(-1))
        rows = rows.astype(wdtype).reshape(nparts, b, fdim)
        resp = jax.lax.all_to_all(rows, ax, 0, 0)
        back = ops.gather_from_buckets(resp, dest, slotp, okp, fill=0)
        return back.astype(fdtype)

      if cap2 >= c_sz * b:
        return hier_path(None), jnp.int32(0)
      ovf = jnp.sum(mid_mask & (slot2 >= cap2)).astype(jnp.int32)
      total_ovf = jax.lax.psum(ovf, ax)
      rows = jax.lax.cond(total_ovf == 0, hier_path, flat_path, None)
      return rows, ovf

    def body(feat_ids, feats, pb, cache_ids, cache_feats, stats, ids,
             mask):
      safe = jnp.maximum(ids, 0)
      if h > 0:
        cpos = jnp.clip(jnp.searchsorted(cache_ids, safe), 0,
                        cache_ids.shape[0] - 1)
        is_hit = mask & (cache_ids[cpos] == safe)
        out_hit = jnp.where(is_hit[:, None], cache_feats[cpos], 0)
        miss = mask & ~is_hit
      else:
        is_hit = jnp.zeros_like(mask)
        out_hit = jnp.zeros((b, fdim), fdtype)
        miss = mask
      if dedup:
        # one request per unique missed id; `inverse` fans the response
        # row back to every batch slot that asked for it
        req, ucnt, inverse = ops.masked_unique(ids, miss, size=b)
        rmask = req != ops.FILL
      else:
        req, rmask = ids, miss
        inverse = jnp.where(miss, jnp.arange(b, dtype=jnp.int32), -1)
        ucnt = jnp.sum(miss)
      exchange = exchange_hier if hier else exchange_flat
      rows, ovf = exchange(feat_ids, feats, pb, req, rmask)
      out_miss = rows[jnp.maximum(inverse, 0)]
      out = jnp.where(is_hit[:, None], out_hit.astype(fdtype),
                      jnp.where(miss[:, None], out_miss, 0))
      batch_stats = jnp.stack([
          jnp.sum(is_hit), jnp.sum(miss), ucnt, ovf]).astype(jnp.int32)
      return out, stats + batch_stats

    return body

  def _build_fn(self, b: int):
    """Jitted shard_map lookup for per-shard request blocks of size b:
    cache split -> miss dedup -> bucketed (or hierarchical) miss-only
    exchange -> fan-out + merge, ONE dispatch, no host syncs."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    dev = self.device_arrays()
    ax = tuple(self.mesh.axis_names)
    core = self._shard_body(b)

    def body(feat_ids, feats, pb, cache_ids, cache_feats, stats, ids,
             mask):
      # per-shard views: feat_ids [1, n], feats [1, n, F], ids [1, b]
      out, new_stats = core(feat_ids[0], feats[0], pb, cache_ids,
                            cache_feats, stats[0], ids[0], mask[0])
      return out[None], new_stats[None]

    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P(ax), P(ax), P(), P(), P(), P(ax), P(ax), P(ax)),
        out_specs=(P(ax), P(ax)))
    jfn = jax.jit(fn)

    def run(ids, mask):
      out, self._stats = jfn(dev['feat_ids'], dev['feats'],
                             dev['feature_pb'], dev['cache_ids'],
                             dev['cache_feats'], self._stats_dev(),
                             ids, mask)
      return out

    return run

  def get(self, ids, mask=None):
    """Sharded lookup: ids [P, B] (per-shard request blocks) -> [P, B, F].

    ONE program dispatch, zero host syncs (the hit/miss counters stay on
    device — see :meth:`stats`). Reference: DistFeature.async_get /
    __getitem__ (dist_feature.py:122-153).
    """
    import jax.numpy as jnp

    from ..utils import trace
    ids = jnp.asarray(ids)
    assert ids.ndim == 2 and ids.shape[0] == self.num_partitions
    if mask is None:
      mask = ids >= 0
    b = ids.shape[1]
    if b not in self._fns:
      from ..metrics import programs
      self._fns[b] = programs.instrument(self._build_fn(b),
                                         'dist_feature.get')
    trace.record_dispatch('dist_feature.get')
    return self._fns[b](ids, mask)

  def cpu_get(self, ids) -> np.ndarray:
    """Host-side exact gather (server-side remote serving path)."""
    ids = np.asarray(ids)
    out = np.zeros((ids.shape[0], self.feature_dim), self.storage_dtype)
    for p in range(self.num_partitions):
      m = self.feature_pb[np.clip(ids, 0, None)] == p
      if not m.any():
        continue
      pos = np.searchsorted(self.feat_ids[p], ids[m])
      pos = np.clip(pos, 0, self.feat_ids.shape[1] - 1)
      out[m] = self.feats[p][pos]
    return out
