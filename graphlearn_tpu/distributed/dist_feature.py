"""Sharded distributed feature store with all_to_all gather.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_feature.py. The
reference splits a lookup into a local UVA gather plus per-remote-partition
async RPCs and stitches futures (dist_feature.py:134-269). Here the whole
lookup is ONE jitted SPMD function: route requested ids to their owning
shard (fixed-capacity all_to_all), gather rows locally (searchsorted over
the shard's sorted owned ids), route rows back, unpermute. XLA overlaps the
collective with compute — the asyncio machinery dissolves.
"""
import functools
from typing import Optional

import numpy as np

from .. import ops

INT32_MAX = np.iinfo(np.int32).max


class DistFeature:
  """Reference: dist_feature.py:51-269.

  Args:
    num_partitions: partitions == mesh 'g' axis size.
    feat_parts: list of (ids [n_p], feats [n_p, F]) per partition (the
      FeaturePartitionData payload, cache already merged via
      cat_feature_cache).
    feature_pb: [N] id -> owning partition (the *feature* partition book —
      may differ from the graph node_pb once caches move entries).
    mesh: the graph mesh.
    dtype: optional storage dtype (bf16 halves HBM + ICI bytes).
  """

  def __init__(self, num_partitions: int, feat_parts, feature_pb,
               mesh=None, dtype=None):
    self.num_partitions = num_partitions
    self.feature_pb = np.asarray(feature_pb)
    self.mesh = mesh
    n_max = max(ids.shape[0] for ids, _ in feat_parts)
    f = feat_parts[0][1].shape[1]
    p = len(feat_parts)
    dt = dtype or feat_parts[0][1].dtype
    self.feat_ids = np.full((p, n_max), INT32_MAX, np.int32)
    self.feats = np.zeros((p, n_max, f), dt)
    for i, (ids, fe) in enumerate(feat_parts):
      order = np.argsort(ids)
      self.feat_ids[i, :ids.shape[0]] = ids[order]
      self.feats[i, :ids.shape[0]] = fe[order]
    self._dev = None
    self._fns = {}

  @property
  def feature_dim(self) -> int:
    return self.feats.shape[-1]

  def device_arrays(self):
    if self._dev is None:
      from jax.sharding import NamedSharding, PartitionSpec as P
      from ..utils import global_device_put
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      repl = NamedSharding(self.mesh, P())
      self._dev = dict(
          feat_ids=global_device_put(self.feat_ids, shard),
          feats=global_device_put(self.feats, shard),
          feature_pb=global_device_put(self.feature_pb.astype(np.int32),
                                       repl))
    return self._dev

  def _build_fn(self, b: int):
    """Jitted shard_map lookup for per-shard request blocks of size b."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    nparts = self.num_partitions
    dev = self.device_arrays()
    fdim = self.feature_dim
    fdtype = self.feats.dtype
    # collectives/specs over every mesh axis: works identically on the
    # flat ('g',) mesh and a 2-axis ('slice', 'chip') mesh
    ax = tuple(self.mesh.axis_names)

    def body(feat_ids, feats, pb, ids, mask):
      # per-shard views: feat_ids [1, n], feats [1, n, F], ids [1, b]
      feat_ids, feats = feat_ids[0], feats[0]
      ids, mask = ids[0], mask[0]
      dest = jnp.where(mask, pb[jnp.maximum(ids, 0)], nparts)
      slot, ok = ops.route_slots(dest, mask, capacity=b)
      send = ops.scatter_to_buckets(ids, dest, slot, ok, nparts, b)
      req = jax.lax.all_to_all(send, ax, 0, 0)            # [P, b] requests
      flat = req.reshape(-1)
      pos = jnp.clip(jnp.searchsorted(feat_ids, flat), 0,
                     feat_ids.shape[0] - 1)
      found = feat_ids[pos] == flat
      rows = jnp.where(found[:, None], feats[pos], 0)
      rows = rows.reshape(nparts, b, fdim)
      resp = jax.lax.all_to_all(rows, ax, 0, 0)           # [P, b] responses
      out = ops.gather_from_buckets(resp, dest, slot, ok, fill=0)
      return out.astype(fdtype)[None]

    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P(ax), P(ax), P(), P(ax), P(ax)),
        out_specs=P(ax))
    jfn = jax.jit(fn)
    return lambda ids, mask: jfn(dev['feat_ids'], dev['feats'],
                                 dev['feature_pb'], ids, mask)

  def get(self, ids, mask=None):
    """Sharded lookup: ids [P, B] (per-shard request blocks) -> [P, B, F].

    Reference: DistFeature.async_get / __getitem__
    (dist_feature.py:122-153).
    """
    import jax.numpy as jnp
    ids = jnp.asarray(ids)
    assert ids.ndim == 2 and ids.shape[0] == self.num_partitions
    if mask is None:
      mask = ids >= 0
    b = ids.shape[1]
    if b not in self._fns:
      self._fns[b] = self._build_fn(b)
    return self._fns[b](ids, mask)

  def cpu_get(self, ids) -> np.ndarray:
    """Host-side exact gather (server-side remote serving path)."""
    ids = np.asarray(ids)
    out = np.zeros((ids.shape[0], self.feature_dim), self.feats.dtype)
    for p in range(self.num_partitions):
      m = self.feature_pb[np.clip(ids, 0, None)] == p
      if not m.any():
        continue
      pos = np.searchsorted(self.feat_ids[p], ids[m])
      pos = np.clip(pos, 0, self.feat_ids.shape[1] - 1)
      out[m] = self.feats[p][pos]
    return out
