"""RemoteScanTrainer: the chunk-staged hybrid — server-client epochs at
scanned speed.

The per-batch remote path (``RemoteDistNeighborLoader`` + a per-batch
jitted train step) pays >= 2 RPC dispatches plus host Python per
optimizer step, while the collocated ``DistScanTrainer`` runs
``ceil(steps/K) + 2`` dispatches per epoch. This module closes that gap
for the decoupled sampling-server/trainer topology (the reference's
flagship production deployment — PAPER.md "storage cluster != training
cluster"):

* **Servers produce K-batch blocks.** Each sampling server replays the
  SAME counter-addressed stream the per-batch mp-worker path draws
  (``distributed/block_producer.py``) and stacks K consecutive batches
  into one fixed-shape frame — the landing zone PyTorch-Direct (arXiv
  2101.07956) argues for: fixed-shape staging buffers for irregular
  remote payloads.
* **The client double-buffers blocks over RPC.** A bounded
  :class:`RemoteBlockStager` worker (the ``storage/staging.py``
  ChunkStager pattern) fetches block ``c+1`` while chunk ``c`` trains,
  and pipelines a ``block_produce`` for ``c+1`` ahead of the
  ``block_fetch`` of ``c`` so the server builds the next frame while
  this one's bytes are on the wire (the overlap posture of
  GPU-initiated direct storage access, arXiv 2306.16384).
* **One upload, one program per chunk.** The frame is device_put once
  (explicit — the epoch region runs under ``strict_guards``) and the
  chunk executes as ONE jitted ``lax.scan`` of the shared train step
  over the block's ``[k, ...]`` batches — one executable per (k, block
  shape) under GLT_STRICT; with ``wire_dtype='bf16'`` the f32 upcast
  happens inside the program (zero extra dispatches). Client dispatch
  budget: ``ceil(steps/K) + 2`` (begin + chunks + metrics concat),
  asserted by tests/test_remote_scan.py.
* **Acks and failover move to CHUNK granularity.** The PR 2 per-batch
  seed-ack protocol and the PR 10 FailoverRunner rollback contract
  unify here: a block is acked when its chunk is dispatched; a dead
  server's UNFETCHED blocks are re-replayed by survivors from the same
  counter stream, bit-identically (blocks are pure functions of the
  share + config + epoch + batch range). ``shuffle=True`` epochs fail
  over just as exactly: the server-side epoch permutation is
  EPOCH-ADDRESSED — a pure function of (stream seed, epoch), not a
  stateful host rng — so a survivor's replay producer draws the
  identical order (the constraint the per-batch loaders still carry;
  lifted here in round 15, chaos-tested for exact coverage under a
  mid-epoch kill). Frames already fetched client-side survive the
  death: a killed server loses at most the in-flight block.
* **Degrade-to-sync, never corruption.** A failed/slow stager worker
  falls back to a synchronous fetch of the SAME block on the dispatch
  thread (``remote.prefetch_miss``) — identical bytes, just slower,
  chaos-tested with the ``remote.block_fetch`` fault armed.

With ``shuffle=False`` and ``wire_dtype=None`` the losses and final
params are BIT-IDENTICAL to the per-batch remote path (single server,
``num_workers=1``) — including ragged tail batches, tail chunks and
epoch-2 stream continuation (tests/test_remote_scan.py pins all
three). The ``stage_hook``/``ack_hook`` chunk-boundary seams carry the
same contract as the other scanned trainers, so
``recovery.ChunkCheckpointer`` attaches unchanged and a crash resumes
at a block boundary (docs/remote_scan.md, docs/recovery.md).

Usage::

    glt.distributed.init_client(...)
    opts = glt.distributed.RemoteDistSamplingWorkerOptions(
        server_rank=[0, 1])
    trainer = glt.distributed.RemoteScanTrainer(
        [15, 10], seeds, model, tx, num_classes, batch_size=1024,
        chunk_size=32, worker_options=opts, seed=0)
    state, losses, accs = trainer.run_epoch(state)
"""
import dataclasses
import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import metrics
from ..metrics import flight, programs, spans
from ..utils.faults import fault_point
from ..utils.strict import strict_guards
from ..utils.trace import record_dispatch
from .dist_loader import _norm_num_neighbors, _split_input_type
from .resilience import NO_RETRY, DeadlineExceeded, ServerDeadError
from .tenancy import with_backpressure

#: exception classes a block fetch may die with when its server is gone
#: (TCP reset, probe timeout, exhausted idempotent-retry deadline) —
#: anything else is a genuine remote error and must surface, not
#: trigger a bogus failover
_DEAD_EXCS = (ConnectionError, TimeoutError, OSError, DeadlineExceeded,
              ServerDeadError)


class _Slab:
  __slots__ = ('frame', 'ready', 'error', 't_done')

  def __init__(self):
    self.frame = None
    self.ready = threading.Event()
    self.error: Optional[BaseException] = None
    self.t_done: Optional[float] = None


class RemoteBlockStager:
  """One background worker prefetching block frames ahead of the chunk
  dispatch loop — the remote twin of ``storage.staging.ChunkStager``
  (same double-buffer shape, same degrade-to-sync failure semantics).

  ``fetch_fn(chunk_index)`` performs the actual RPC; it re-reads the
  trainer's schedule at call time, so a failover that re-points a
  chunk's descriptor at a survivor is picked up by both the worker and
  the synchronous fallback without re-priming the ring.

  Deliberately a SEPARATE class from ChunkStager rather than a shared
  parameterized base: the storage stager owns plan arrays, the tier
  gather + pad_slab, its own fault sites (storage.stage/promote) and
  the storage.* metric family, while this one owns RPC failure
  classes, schedule re-pointing and the remote.* family — the shared
  part is the lifecycle shape, and coupling the two hot paths would
  make every storage-side change a remote-side risk."""

  def __init__(self, fetch_fn: Callable[[int], dict], max_ahead: int = 2,
               timeout_s: float = 30.0):
    if max_ahead < 1:
      raise ValueError('max_ahead must be >= 1')
    self.fetch_fn = fetch_fn
    self.max_ahead = int(max_ahead)
    self.timeout_s = float(timeout_s)
    self._num_chunks = 0
    self._slabs: Dict[int, _Slab] = {}
    self._lock = threading.Lock()
    self._q: 'queue.Queue' = queue.Queue()
    self._worker: Optional[threading.Thread] = None
    self._stop = False
    self._next_submit = 0
    self.degraded = False   # a worker fetch failed this epoch
    self._ctx = None        # epoch-root span context adopted by _loop

  # ------------------------------------------------------------ lifecycle

  def begin_epoch(self, num_chunks: int, start_chunk: int = 0):
    """Install this epoch's chunk count and prime the first
    ``max_ahead`` fetches. A mid-epoch resume passes ``start_chunk``;
    consumed chunks are never fetched again."""
    if not 0 <= start_chunk <= num_chunks:
      raise ValueError(f'start_chunk={start_chunk} outside the '
                       f'{num_chunks}-chunk epoch')
    with self._lock:
      self._num_chunks = int(num_chunks)
      self._slabs = {}
      self._next_submit = int(start_chunk)
      self.degraded = False
      # capture the caller's (epoch-root) span context so worker-thread
      # fetch spans — remote.block_fetch, tenant.throttle — parent under
      # the epoch tree instead of floating as orphans
      self._ctx = spans.wire_context()
    self._ensure_worker()
    for _ in range(min(self.max_ahead, num_chunks - int(start_chunk))):
      self._submit_next()

  def close(self):
    self._stop = True
    self._q.put(None)
    w = self._worker
    if w is not None:
      w.join(timeout=5.0)
    self._worker = None
    self._stop = False
    # drain leftovers so a stale None can't kill the next epoch's
    # fresh worker on its first pop (the ChunkStager close contract)
    try:
      while True:
        self._q.get_nowait()
    except queue.Empty:
      pass

  def _ensure_worker(self):
    if self._worker is not None and self._worker.is_alive():
      return
    self._worker = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-remote-block-stager')
    self._worker.start()

  def _submit_next(self):
    with self._lock:
      c = self._next_submit
      if c >= self._num_chunks:
        return
      self._next_submit = c + 1
      self._slabs[c] = _Slab()
    self._q.put(c)

  # --------------------------------------------------------------- worker

  def _loop(self):
    while True:
      c = self._q.get()
      if c is None or self._stop:
        return
      with self._lock:
        slab = self._slabs.get(c)
        ctx = self._ctx
      if slab is None or slab.ready.is_set():
        continue   # epoch moved on, or failover already failed it
      try:
        t0 = time.perf_counter()
        fault_point('remote.block_fetch')
        with spans.adopt(ctx):
          slab.frame = self.fetch_fn(c)
        metrics.observe('remote.block_stage_ms',
                        (time.perf_counter() - t0) * 1e3)
      except BaseException as e:   # a chaos raise must not kill later blocks
        slab.error = e
        self.degraded = True
      finally:
        slab.t_done = time.perf_counter()
        slab.ready.set()

  # ------------------------------------------------------------- consumer

  def has_frame(self, c: int) -> bool:
    """True when chunk ``c``'s frame is already staged client-side —
    such frames survive the death of the server that produced them."""
    with self._lock:
      slab = self._slabs.get(c)
    return (slab is not None and slab.ready.is_set() and
            slab.error is None and slab.frame is not None)

  def fail_pending(self, chunks: List[int], exc: BaseException):
    """Failover support: mark not-yet-staged slabs errored so
    :meth:`take` falls through to the synchronous path (with the
    re-pointed descriptor) immediately instead of waiting out the
    timeout against a dead server."""
    with self._lock:
      slabs = [self._slabs.get(c) for c in chunks]
    for slab in slabs:
      if slab is not None and not slab.ready.is_set():
        slab.error = exc
        slab.ready.set()

  def take(self, c: int) -> dict:
    """Frame for chunk ``c``. Blocks up to ``timeout_s`` for the
    worker, then degrades to a synchronous fetch of the SAME block
    (``remote.prefetch_miss``) — identical bytes either way. The
    synchronous fetch may raise (dead server); the trainer's failover
    handles that and calls take() again — the ring advances only in
    :meth:`ack` (once per consumed chunk), so failover retries can
    never over-deepen the prefetch pipeline."""
    with self._lock:
      slab = self._slabs.get(c)
    ok = slab is not None and slab.ready.wait(self.timeout_s)
    if ok and slab.error is None and slab.frame is not None:
      return slab.frame
    self.degraded = True
    metrics.inc('remote.prefetch_miss')
    return self.fetch_fn(c)

  def ack(self, c: int):
    """Chunk ``c``'s program consumed its frame (the device_put
    copied it): free the ring slot and pull the next chunk forward so
    the pipeline stays ``max_ahead`` deep."""
    with self._lock:
      self._slabs.pop(c, None)
    self._submit_next()


def _norm_fans(f):
  """Canonical comparison form of a fanout spec: per-etype dict
  fanouts (hetero plans) normalize to sorted string keys — tuned
  artifacts round-trip etype keys through JSON as strings
  (docs/capacity_plans.md)."""
  if isinstance(f, dict):
    from ..typing import as_str
    return {as_str(tuple(et)) if isinstance(et, (tuple, list))
            else str(et): [int(k) for k in v]
            for et, v in sorted(f.items(), key=lambda kv: str(kv[0]))}
  return [int(k) for k in f]


def _group_frame(frame: dict, prefix: str, et_keyed: bool = False):
  """Host-side regroup of a typed block frame's dotted keys
  (``x.paper`` / ``row.paper__cites__paper`` — docs/capacity_plans.md)
  into a per-type dict for one device_put."""
  from ..typing import to_edge_type
  p = prefix + '.'
  return {(to_edge_type(kk[len(p):]) if et_keyed else kk[len(p):]):
          np.asarray(v) for kk, v in frame.items()
          if kk.startswith(p)}


def _resolve_remote_config(name: str, config, fanouts,
                           batch_size: int) -> dict:
  """Validate a tune-artifact ``config=`` against the remote scenario
  and return its tuned block knobs (empty when no config). Topology
  must be 'remote' or a generic 'local' artifact (chunk K + kernels
  transfer; block knobs are remote-only), and the artifact's
  fanouts/batch_size must match the stream this trainer creates — a
  block-stream assignment tuned at different frame shapes is a
  different program population (docs/tuning.md 'Topology
  candidates')."""
  if config is None:
    return {}
  art_topo = getattr(config, 'topology', 'local') or 'local'
  if art_topo not in ('local', 'remote'):
    raise ValueError(
        f'{name}: tune artifact was tuned for topology {art_topo!r}, '
        "but this trainer runs the 'remote' scenario — per-topology "
        'knobs do not transfer; re-run graphlearn_tpu.tune('
        "topology='remote') (docs/tuning.md)")
  choices = getattr(config, 'choices', None) or {}
  tuned_fans = choices.get('fanouts')
  if tuned_fans is not None and \
      _norm_fans(tuned_fans) != _norm_fans(fanouts):
    raise ValueError(
        f'{name}: tune artifact pins fanouts {tuned_fans} but '
        f'this trainer streams at {_norm_fans(fanouts)} — the '
        'block frames were sized for a different sampling shape '
        '(docs/tuning.md)')
  tuned_bs = choices.get('batch_size')
  if tuned_bs is not None and int(tuned_bs) != int(batch_size):
    raise ValueError(
        f'{name}: tune artifact pins batch_size={int(tuned_bs)} but '
        f'this trainer streams at batch_size={int(batch_size)} '
        '(docs/tuning.md)')
  if getattr(config, 'dataset', None) is not None:
    import warnings
    warnings.warn(
        f'{name}: the remote client holds no dataset to recompute '
        'the artifact fingerprint against — tuned config accepted on '
        'the tune-side validation only', RuntimeWarning, stacklevel=3)
  if art_topo == 'remote' and hasattr(config, 'topology_kwargs'):
    kw = config.topology_kwargs()
    return {k: kw[k] for k in ('block_ahead', 'block_wire_dtype')
            if k in kw}
  return {}


class RemoteScanTrainer:
  """Scanned epochs over sampling-server block streams (module
  docstring). Scope: supervised node classification with collected
  features and labels, homogeneous or heterogeneous — typed seeds
  select typed block streams whose closed shapes come from the
  stream's CapacityPlan (docs/capacity_plans.md); the homo path is the
  single-ntype degenerate plan of the same machinery.

  Args:
    num_neighbors: fanouts (list, or per-etype dict for hetero).
    input_nodes: seed ids — untyped array, or ``('ntype', ids)`` for
      hetero graphs (split across the servers in rank order — the
      per-batch remote loaders' share convention).
    model, tx, num_classes: the supervised training triple
    batch_size: per optimizer step.
    chunk_size: K, batches per block/chunk (the tail block compiles
      once more at its own length).
    shuffle: epoch-addressed server-side shuffle — a pure function of
      (stream seed, epoch), so shuffled epochs keep exact chunk
      failover and resume. ``False`` additionally holds the
      bit-identity-to-the-per-batch-path contract (the per-batch
      loaders' host rng is stateful; docs/remote_scan.md).
    drop_last: drop the ragged tail batch.
    worker_options: RemoteDistSamplingWorkerOptions — server_rank,
      heartbeat/failover tunables, ``block_wire_dtype`` /
      ``block_ahead`` / ``block_timeout``.
    seed: sampling seed; folded per server exactly like the per-batch
      remote loaders (``seed * 7919 + i``).
    config: a tune artifact (``graphlearn_tpu.tune(topology='remote')``,
      docs/tuning.md): supplies the tuned chunk K when ``chunk_size``
      is not given and the tuned ``block_ahead``/``block_wire_dtype``
      (overriding the worker_options defaults — the artifact is the
      signed assignment); refuses a mismatched topology, fanouts, or
      batch size.
  """

  _NAME = 'RemoteScanTrainer'

  # chunk-boundary hooks — the same host-side seam as the other scanned
  # trainers (docs/storage.md, docs/recovery.md): ``stage_hook(c,
  # start, k)`` before each chunk dispatch, ``ack_hook(c, start, k)``
  # right after (with ``self._chunk_carry`` exposing the boundary
  # state for the ChunkCheckpointer's explicit device_get)
  stage_hook = None
  ack_hook = None

  def __init__(self, num_neighbors, input_nodes, model, tx,
               num_classes: int, batch_size: int = 64,
               chunk_size: Optional[int] = None, shuffle: bool = False,
               drop_last: bool = False, collect_features: bool = True,
               worker_options=None, seed: Optional[int] = None,
               config=None):
    import jax

    from ..models import train as train_lib
    from ..sampler import SamplingConfig, SamplingType
    from . import dist_client
    from .resilience import Heartbeat
    # config= takes a tune artifact (graphlearn_tpu.tune(topology=
    # 'remote'), docs/tuning.md): topology-checked, structurally
    # validated against the fanouts/batch this trainer streams at, and
    # the source of the tuned chunk K + block knobs below. The client
    # holds no dataset, so the dataset fingerprint cannot be
    # recomputed here — it was validated on the tune side
    tuned_block = _resolve_remote_config(
        self._NAME, config, _norm_num_neighbors(num_neighbors),
        batch_size)
    if chunk_size is None:
      chunk_size = int(config.trainer_kwargs()['chunk_size']) \
          if config is not None else 32
    if chunk_size < 1:
      raise ValueError(f'chunk_size must be >= 1, got {chunk_size}')
    input_type, input_nodes = _split_input_type(input_nodes)
    # typed seeds select the hetero block streams: the server derives
    # the stream's CapacityPlan (docs/capacity_plans.md) from the typed
    # share and the chunk program scans typed frames — the homo path is
    # the single-ntype degenerate case of the same machinery
    self._input_type = input_type
    if not collect_features:
      raise ValueError(f'{self._NAME} needs collect_features=True — '
                       'the chunk program trains on the block frames\' '
                       'feature payload')
    self.model = model
    self.tx = tx
    self.num_classes = num_classes
    self.chunk_size = int(chunk_size)
    self.batch_size = int(batch_size)
    self.input_seeds = np.asarray(input_nodes).reshape(-1)
    self.seed = seed
    self._shuffle = bool(shuffle)
    self._drop_last = bool(drop_last)
    opts = worker_options
    self._opts = opts
    self._dist_client = dist_client
    ranks = opts.server_rank if opts and opts.server_rank is not None \
        else [0]
    if isinstance(ranks, int):
      ranks = [ranks]
    self.server_ranks = list(ranks)
    self._wire_dtype = getattr(opts, 'block_wire_dtype', None) \
        if opts else None
    self._max_ahead = getattr(opts, 'block_ahead', 2) if opts else 2
    self._fetch_timeout = getattr(opts, 'block_timeout', 30.0) \
        if opts else 30.0
    # the artifact's tuned block knobs are the signed, evidence-backed
    # assignment: a non-None tuned value overrides the worker_options
    # default (hand-pick by passing options WITHOUT config=)
    if 'block_wire_dtype' in tuned_block:
      self._wire_dtype = tuned_block['block_wire_dtype']
    if 'block_ahead' in tuned_block:
      self._max_ahead = int(tuned_block['block_ahead'])
    self._failover_enabled = (opts.failover if opts else True)
    self._tenant = getattr(opts, 'tenant', None) if opts else None
    self._tenant_priority = getattr(opts, 'tenant_priority', None) \
        if opts else None
    self._tenant_weight = getattr(opts, 'tenant_weight', None) \
        if opts else None
    self._base_weight = float(self._tenant_weight or 1.0)
    self._bp_budget = getattr(opts, 'backpressure_budget', 120.0) \
        if opts else 120.0
    self._config = SamplingConfig(
        SamplingType.NODE, _norm_num_neighbors(num_neighbors),
        self.batch_size, self._shuffle, self._drop_last, False,
        collect_features, False, False, 'out', seed)
    base_key = (opts.worker_key if opts and opts.worker_key
                else f'rscan{os.getpid()}-{id(self):x}')
    self._worker_key = base_key
    # one block stream per server, shares + seed folding exactly as the
    # per-batch remote loaders split them (dist_loader.py) — with one
    # server and num_workers=1 the streams are bit-identical
    splits = np.array_split(self.input_seeds, len(self.server_ranks))
    self._streams = []
    for i, (rank, split) in enumerate(zip(self.server_ranks, splits)):
      from ..sampler import NodeSamplerInput
      share = (NodeSamplerInput(split, input_type=self._input_type)
               if self._input_type is not None else split)
      cfg_i = dataclasses.replace(self._config,
                                  seed=(seed or 0) * 7919 + i)
      pid = with_backpressure(
          lambda rank=rank, share=share, cfg_i=cfg_i, i=i:
          dist_client.request_server(
              rank, 'create_block_producer', share, cfg_i,
              self._wire_dtype, worker_key=f'{base_key}/blk/{i}',
              idempotent=True, **self._tenant_kwargs()),
          describe=f'create_block_producer stream {i} rank {rank}',
          budget_s=self._bp_budget, tenant=self._tenant)
      nb = dist_client.request_server(
          rank, 'block_producer_num_batches', pid, idempotent=True)
      self._streams.append(dict(rank=rank, pid=pid, seeds=share,
                                cfg=cfg_i, num_batches=int(nb)))
    self._active_ranks = list(self.server_ranks)
    self._current_chunk = -1
    self._dead_ranks: Dict[int, str] = {}
    self._replay_pids: Dict[tuple, int] = {}
    self._epochs = 0
    self._schedule: List[dict] = []
    self._stager = RemoteBlockStager(self._fetch_block,
                                     max_ahead=self._max_ahead,
                                     timeout_s=self._fetch_timeout)
    hb_interval = opts.heartbeat_interval if opts else 1.0
    hb_miss = opts.heartbeat_miss if opts else 3

    def probe(rank):
      dist_client.request_server(rank, 'heartbeat',
                                 timeout=max(hb_interval, 2.0),
                                 idempotent=True, retry_policy=NO_RETRY)

    self._heartbeat = Heartbeat(self.server_ranks, probe,
                                interval=hb_interval,
                                miss_threshold=hb_miss)
    self._hb_started = False
    self._train_step, _ = train_lib.make_train_step(model, tx,
                                                    num_classes)
    self._begin_fn = programs.instrument(self._build_begin_fn(),
                                         'remote_epoch_begin')
    self._chunk_fn = programs.instrument(self._build_chunk_fn(),
                                         'remote_scan_chunk')
    self._concat_fn = programs.instrument(self._build_concat_fn(),
                                          'remote_metrics_concat')
    self.last_overflow = None       # [bool] device scalar, per epoch
    self.last_epoch_seed_ids = None  # host ack record, per epoch

  # ------------------------------------------------------------- programs

  def _build_begin_fn(self):
    """ONE prologue program committing the epoch carry (train state +
    overflow flag) into the canonical device layout the chunk
    executable expects — a host-built or restored state then presents
    the same signature as a donated chunk output, so no epoch's first
    chunk retraces."""
    import jax

    def remote_epoch_begin(state, ovf):
      return state, ovf

    return jax.jit(remote_epoch_begin)

  def _build_chunk_fn(self):
    """The scanned K-step block program: ``lax.scan`` of the shared
    train step over the uploaded block's per-step batches. The wire
    upcast (bf16 -> f32) happens INSIDE the program, and every block
    buffer is donated — HBM stays flat at one state + one in-flight
    block."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    train_step = self._train_step   # jit-of-jit: inlined into the scan
    upcast = self._wire_dtype is not None

    def remote_scan_chunk(state, ovf, x, row, col, edge_mask, y, nseed,
                          ovf_steps):
      def body(carry, xs):
        state, ovf = carry
        x_s, r_s, c_s, em_s, y_s, ns_s, o_s = xs
        up = (lambda a: a.astype(jnp.float32)) if upcast else (lambda a: a)
        if isinstance(x_s, dict):
          # typed block frame (docs/capacity_plans.md): per-ntype
          # feature dicts, per-etype edge dicts — the same batch dict
          # the collocated hetero collate builds (loader/pipeline.py)
          batch = dict(x={t: up(v) for t, v in x_s.items()},
                       edge_index={et: jnp.stack([r_s[et], c_s[et]])
                                   for et in r_s},
                       edge_mask=em_s, y=y_s, num_seed_nodes=ns_s)
        else:
          batch = dict(x=up(x_s), edge_index=jnp.stack([r_s, c_s]),
                       edge_mask=em_s, y=y_s, num_seed_nodes=ns_s)
        state, loss, acc = train_step(state, batch)
        return (state, ovf | o_s), (loss, acc)

      (state, ovf), (losses, accs) = lax.scan(
          body, (state, ovf),
          (x, row, col, edge_mask, y, nseed, ovf_steps))
      return state, ovf, losses, accs

    # donate the carry only: the block buffers have no same-shaped
    # outputs to alias into (XLA would warn and copy), and they free
    # naturally when the chunk's Python references drop
    return jax.jit(remote_scan_chunk, donate_argnums=(0, 1))

  def _build_concat_fn(self):
    """One program concatenating the per-chunk [k] loss/acc outputs."""
    import jax
    import jax.numpy as jnp

    def remote_metrics_concat(losses, accs):
      return jnp.concatenate(losses), jnp.concatenate(accs)

    return jax.jit(remote_metrics_concat)

  # ------------------------------------------------------------- schedule

  def __len__(self) -> int:
    return sum(st['num_batches'] for st in self._streams)

  def _block_boundaries(self) -> List[int]:
    """Global step indices where blocks begin — the only valid
    ``start_step`` resume points (with one server: multiples of K)."""
    bounds, step0 = [], 0
    for st in self._streams:
      nb = st['num_batches']
      bounds.extend(step0 + b for b in range(0, nb, self.chunk_size))
      step0 += nb
    return bounds

  def _block_schedule(self, steps: int, epoch: int) -> List[dict]:
    """Chunk descriptors in epoch order: stream shares back to back
    (concatenated shares == the full seed sequence for shuffle=False),
    each stream cut into K-batch blocks plus a tail. A rank already
    known dead is re-pointed at survivors up front (the epoch-start
    failover path)."""
    descs, step0 = [], 0
    for i, st in enumerate(self._streams):
      nb = st['num_batches']
      for b0 in range(0, nb, self.chunk_size):
        gstep = step0 + b0
        if gstep >= steps:
          break
        k = min(self.chunk_size, nb - b0, steps - gstep)
        descs.append(dict(stream=i, rank=st['rank'], pid=st['pid'],
                          epoch=epoch, start=b0, k=k, step0=gstep))
      step0 += nb
    if self._dead_ranks:
      survivors = [r for r in self._active_ranks
                   if r not in self._dead_ranks] or \
                  [r for r in self.server_ranks
                   if r not in self._dead_ranks]
      if not survivors:
        raise RuntimeError('no live sampling server to start the '
                           f'epoch: dead={self._dead_ranks}')
      moved = 0
      for d in descs:
        if d['rank'] in self._dead_ranks:
          self._require_failover()
          surv = survivors[moved % len(survivors)]
          d['pid'] = self._replay_pid(surv, d['stream'])
          d['rank'] = surv
          moved += 1
    # policy shrink (set_block_ranks / set_tenant_weight): home ranks
    # outside the active set hand their blocks to replay producers on
    # active ranks — the same counter-addressed contract as failover,
    # driven by policy instead of death
    inactive = [r for r in self.server_ranks
                if r not in self._active_ranks and
                r not in self._dead_ranks]
    if inactive:
      targets = [r for r in self._active_ranks
                 if r not in self._dead_ranks]
      moved = 0
      for d in descs:
        if d['rank'] in inactive:
          tgt = targets[moved % len(targets)]
          d['pid'] = self._replay_pid(tgt, d['stream'])
          d['rank'] = tgt
          moved += 1
      if moved:
        metrics.inc('tenant.rebalanced_blocks', moved)
    return descs

  # -------------------------------------------------------- block fetch

  def _fetch_block(self, c: int) -> dict:
    """Fetch chunk ``c``'s frame (reading the schedule AT CALL TIME so
    failover re-pointing is honored), pipelining a produce of the next
    pending chunk so the server builds c+1 while c's bytes are on the
    wire. Called from the stager worker AND from its synchronous
    degrade path."""
    desc = self._schedule[c]
    nxt = c + 1
    if nxt < len(self._schedule) and not self._stager.has_frame(nxt):
      nd = self._schedule[nxt]
      try:
        fut = self._dist_client.async_request_server(
            nd['rank'], 'block_produce', nd['pid'], nd['epoch'],
            nd['start'], nd['k'])
        fut.add_done_callback(lambda f: f.exception())  # swallow
      except Exception:   # produce-ahead is an optimization only
        pass
    t0 = time.perf_counter()
    with spans.span('remote.block_fetch', chunk=int(c),
                    rank=int(desc['rank']), start=int(desc['start'])):
      frame = with_backpressure(
          lambda: self._dist_client.request_server(
              desc['rank'], 'block_fetch', desc['pid'], desc['epoch'],
              desc['start'], desc['k'], idempotent=True),
          describe=f'block_fetch chunk {c} rank {desc["rank"]}',
          budget_s=self._bp_budget, tenant=self._tenant)
    metrics.observe('remote.block_fetch_ms',
                    (time.perf_counter() - t0) * 1e3)
    nbytes = sum(int(np.asarray(v).nbytes) for v in frame.values())
    metrics.inc('remote.blocks')
    metrics.inc('remote.block_bytes', nbytes)
    metrics.observe('remote.block_mb_per_chunk', nbytes / 1e6)
    return frame

  # ----------------------------------------------------------- failover

  def _require_failover(self):
    # shuffle=True is failover-safe on THIS path (unlike the per-batch
    # remote loaders): the server permutation is epoch-addressed — a
    # pure function of (stream seed, epoch), block_producer._epoch_order
    # — so a survivor's replay producer draws the identical order and
    # re-replays the dead rank's blocks bit-identically
    # (tests/test_remote_scan.py pins exact coverage after a mid-epoch
    # kill with shuffle=True)
    if not self._failover_enabled:
      raise RuntimeError(
          'sampling server died and failover is disabled '
          '(RemoteDistSamplingWorkerOptions.failover=False)')

  def _replay_pid(self, survivor: int, stream_i: int) -> int:
    """A block producer for stream ``stream_i``'s share ON the
    survivor — same share, same folded config, so its blocks are
    bit-identical to the dead server's. worker_key makes the create
    retry-safe."""
    key = (survivor, stream_i)
    pid = self._replay_pids.get(key)
    if pid is not None:
      return pid
    st = self._streams[stream_i]
    pid = with_backpressure(
        lambda: self._dist_client.request_server(
            survivor, 'create_block_producer', st['seeds'], st['cfg'],
            self._wire_dtype,
            worker_key=f'{self._worker_key}/bfo/s{stream_i}/r{survivor}',
            idempotent=True, **self._tenant_kwargs()),
        describe=f'replay producer stream {stream_i} rank {survivor}',
        budget_s=self._bp_budget, tenant=self._tenant)
    self._replay_pids[key] = pid
    return pid

  # ------------------------------------------------- tenancy / elasticity

  def _tenant_kwargs(self) -> dict:
    """create_block_producer kwargs registering this trainer's streams
    under its tenant — empty (and wire-compatible with pre-tenancy
    servers) when no tenant is configured."""
    if self._tenant is None:
      return {}
    return dict(tenant=self._tenant, priority=self._tenant_priority,
                weight=self._tenant_weight)

  def set_block_ranks(self, ranks: List[int]):
    """Elastic resize: restrict block production to ``ranks`` (grow by
    passing a superset again). Mid-epoch, pending not-yet-staged chunks
    whose home rank left the active set are re-pointed at replay
    producers on active ranks — the PR 11 counter-addressed contract
    makes the re-produced blocks bit-identical, so this is failover
    machinery driven by policy instead of death."""
    live = [r for r in dict.fromkeys(ranks) if r not in self._dead_ranks]
    unknown = [r for r in live if r not in self.server_ranks]
    if unknown:
      raise ValueError(f'unknown server ranks {unknown}; trainer knows '
                       f'{self.server_ranks}')
    if not live:
      raise ValueError('set_block_ranks needs at least one live rank '
                       f'(dead={self._dead_ranks})')
    self._active_ranks = live
    if not self._schedule:
      return
    moved = 0
    for j in range(self._current_chunk + 1, len(self._schedule)):
      d = self._schedule[j]
      if (d['rank'] in self._active_ranks or
          d['rank'] in self._dead_ranks or self._stager.has_frame(j)):
        continue
      tgt = self._active_ranks[moved % len(self._active_ranks)]
      d['pid'] = self._replay_pid(tgt, d['stream'])
      d['rank'] = tgt
      moved += 1
    if moved:
      metrics.inc('tenant.rebalanced_blocks', moved)

  def set_tenant_weight(self, weight: float):
    """Autoscale on a weight change: push the new fair-share weight to
    every live server, then grow/shrink the active producer rank set
    proportionally (weight halved -> half the ranks produce for this
    tenant; blocks stay bit-identical under the re-point)."""
    if weight <= 0:
      raise ValueError(f'tenant weight must be > 0, got {weight}')
    if self._tenant is not None:
      for r in self.server_ranks:
        if r in self._dead_ranks:
          continue
        try:
          self._dist_client.request_server(
              r, 'update_tenant', self._tenant, weight=float(weight),
              idempotent=True)
        except _DEAD_EXCS:
          pass   # heartbeat will declare it; re-point happens there
    self._tenant_weight = float(weight)
    live = [r for r in self.server_ranks if r not in self._dead_ranks]
    frac = min(1.0, float(weight) / max(self._base_weight, 1e-9))
    target = max(1, int(np.ceil(frac * len(live))))
    self.set_block_ranks(live[:target])

  def _handle_dead_rank(self, rank: int, cause: str, ci: int):
    """Declare ``rank`` dead and re-point its pending (unfetched)
    blocks at survivors — frames already staged client-side are kept
    (the data outlives its producer), so a killed server costs at most
    the in-flight block. Idempotent per rank."""
    if rank in self._dead_ranks:
      return
    from ..utils import trace
    pending = [j for j in range(ci, len(self._schedule))
               if self._schedule[j]['rank'] == rank and
               not self._stager.has_frame(j)]
    if pending:
      # feasibility FIRST: when this epoch cannot fail over, the rank
      # must not be marked sticky-dead (the per-batch loaders' rule)
      self._require_failover()
    self._dead_ranks[rank] = str(cause)
    self._heartbeat.mark_dead(rank, cause)
    if not pending:
      return
    survivors = [r for r in self.server_ranks
                 if r not in self._dead_ranks]
    if not survivors:
      raise RuntimeError(
          f'all sampling servers dead (last: rank {rank}: {cause}) — '
          'cannot complete the epoch')
    fo_span = spans.begin('loader.failover', rank=rank,
                          cause=str(cause)[:200], blocks=len(pending),
                          detected_chunk=int(ci),
                          survivors=list(survivors))
    try:
      for n, j in enumerate(pending):
        surv = survivors[n % len(survivors)]
        d = self._schedule[j]
        d['pid'] = self._replay_pid(surv, d['stream'])
        d['rank'] = surv
      trace.counter_inc('resilience.failover')
      metrics.inc('remote.failover_blocks', len(pending))
      import logging
      logging.getLogger('graphlearn_tpu.loader').warning(
          'sampling server rank %d dead (%s): re-replaying %d pending '
          'blocks on survivors %s', rank, cause, len(pending),
          survivors)
    except BaseException as e:
      fo_span.attrs['error'] = f'{type(e).__name__}: {e}'
      raise
    finally:
      spans.end(fo_span)
    self._stager.fail_pending(
        pending, ConnectionError(f'rank {rank} dead: {cause}'))

  def _poll_liveness(self, ci: int):
    for rank, cause in self._heartbeat.dead_ranks().items():
      if rank not in self._dead_ranks:
        self._handle_dead_rank(rank, cause, ci)

  def _take_with_failover(self, ci: int) -> dict:
    """take() with dead-server recovery: each failure declares the
    current owner dead and re-points the chunk at a survivor; bounded
    by the server count."""
    for _ in range(len(self.server_ranks) + 1):
      try:
        return self._stager.take(ci)
      except _DEAD_EXCS as e:
        self._handle_dead_rank(self._schedule[ci]['rank'], repr(e), ci)
    raise RuntimeError(f'chunk {ci}: no server could deliver its '
                       f'block (dead={self._dead_ranks})')

  # ----------------------------------------------------------------- epoch

  def run_epoch(self, state, max_steps: Optional[int] = None,
                start_step: int = 0, resume_overflow: bool = False):
    """One chunk-staged remote epoch. Returns ``(state, losses,
    accs)`` with losses/accs [steps]-shaped device arrays — fetch
    once, after the epoch. The input state is DONATED to the first
    chunk; train on the returned state. ``start_step`` (a block
    boundary) resumes THIS epoch mid-flight — go through
    ``recovery.ChunkCheckpointer.resume_epoch``."""
    import jax.numpy as jnp
    if not self._hb_started:
      self._heartbeat.start()
      self._hb_started = True
    epoch_no = self._epochs
    full_steps = len(self)
    steps = full_steps
    truncated = False
    if max_steps is not None and max_steps < steps:
      steps, truncated = max_steps, True
    if start_step:
      if start_step not in set(self._block_boundaries()):
        raise ValueError(f'start_step={start_step} is not a block '
                         f'boundary (chunk_size={self.chunk_size}) — '
                         'resume only at the boundaries checkpoints '
                         'are taken at')
      if not 0 <= start_step < steps:
        raise ValueError(f'start_step={start_step} outside this '
                         f"epoch's {steps} steps")
    # both brackets open after the step arithmetic (and the zero-step
    # path's empty-result device work): a prologue raise must not
    # leave a permanently-open flight record or a leaked attached span
    # — see ScanTrainer.run_epoch
    if steps <= 0:
      empty = jnp.zeros((0,), jnp.float32)
      flight_tok = flight.epoch_begin()
      epoch_span = spans.begin('epoch.run', emitter=self._NAME,
                               epoch=epoch_no)
      spans.end(epoch_span, steps=0, completed=True)
      flight.epoch_end(flight_tok, emitter=self._NAME, epoch=epoch_no,
                       steps=0, config=self._flight_config(),
                       extra={'chunk_size': self.chunk_size,
                              'truncated': truncated})
      return state, empty, empty

    flight_tok = flight.epoch_begin()
    epoch_span = spans.begin('epoch.run', emitter=self._NAME,
                             epoch=epoch_no)
    completed = False
    self._steps_dispatched = start_step
    try:
      state, losses, accs, ovf = self._run_epoch_body(
          state, steps, full_steps, start_step=start_step,
          resume_overflow=resume_overflow)
      completed = True
      self.last_overflow = ovf
    finally:
      spans.end(epoch_span,
                steps=(steps if completed else
                       getattr(self, '_steps_dispatched', 0)),
                completed=completed)
      flight.epoch_end(flight_tok, emitter=self._NAME, epoch=epoch_no,
                       steps=(steps if completed else
                              getattr(self, '_steps_dispatched', 0)),
                       completed=completed,
                       config=self._flight_config(),
                       extra={'chunk_size': self.chunk_size,
                              'truncated': truncated,
                              'start_step': start_step,
                              'dead_ranks': {str(r): c for r, c in
                                             self._dead_ranks.items()}})
    return state, losses, accs

  def _run_epoch_body(self, state, steps, full_steps, start_step=0,
                      resume_overflow=False):
    import jax
    epoch = self._epochs
    self._schedule = self._block_schedule(steps, epoch)
    start_idx = 0
    if start_step:
      start_idx = next(i for i, d in enumerate(self._schedule)
                       if d['step0'] == start_step)
    self._current_chunk = start_idx - 1
    self._seen_ids: List[np.ndarray] = []
    self._stager.begin_epoch(len(self._schedule), start_chunk=start_idx)
    losses, accs = [], []
    with strict_guards():
      record_dispatch('remote_epoch_begin')
      state, ovf = self._begin_fn(
          jax.device_put(state),
          jax.device_put(np.asarray(bool(resume_overflow))))
      for ci in range(start_idx, len(self._schedule)):
        self._current_chunk = ci   # elastic re-points only chunks > ci
        desc = self._schedule[ci]
        if self.stage_hook is not None:
          self.stage_hook(ci, desc['step0'], desc['k'])
        self._poll_liveness(ci)
        frame = self._take_with_failover(ci)
        blk = self._upload(frame)
        record_dispatch('remote_scan_chunk')
        with spans.span('epoch.chunk', start=desc['step0'],
                        k=desc['k']):
          state, ovf, loss_k, acc_k = self._chunk_fn(state, ovf, *blk)
        # the device_put copied the frame: free the ring slot and keep
        # the host-side seed ack (the chunk-granular ack protocol)
        self._stager.ack(ci)
        self._ack_frame(frame)
        losses.append(loss_k)
        accs.append(acc_k)
        self._steps_dispatched = desc['step0'] + desc['k']
        if self.ack_hook is not None:
          # boundary carry for the recovery seam — valid only inside
          # the hook call (the next chunk dispatch donates state/ovf)
          self._chunk_carry = dict(state=state, ovf=ovf, losses=losses,
                                   accs=accs, steps=steps,
                                   full_steps=full_steps,
                                   start_step=start_step)
          self.ack_hook(ci, desc['step0'], desc['k'])
      if len(losses) > 1:
        record_dispatch('remote_metrics_concat')
        losses, accs = self._concat_fn(losses, accs)
      else:
        losses, accs = losses[0], accs[0]
    self.last_epoch_seed_ids = (
        np.concatenate(self._seen_ids) if self._seen_ids
        else np.zeros((0,), np.int64))
    self._epochs += 1
    return state, losses, accs, ovf

  def _upload(self, frame: dict):
    """One explicit device upload of the block's training payload —
    the epoch region runs under strict_guards, so nothing may arrive
    implicitly. Typed frames (dotted keys, docs/capacity_plans.md)
    upload as per-ntype / per-etype dicts in one device_put."""
    import jax
    if self._input_type is not None:
      t_in = self._input_type
      x = _group_frame(frame, 'x')
      row = _group_frame(frame, 'row', True)
      col = _group_frame(frame, 'col', True)
      em = _group_frame(frame, 'edge_mask', True)
      y = np.asarray(frame[f'y.{t_in}'])
      nseed = np.asarray(
          frame[f'num_sampled_nodes.{t_in}'])[:, 0].astype(np.int32)
      k = int(y.shape[0])
      ovf_steps = np.asarray(frame.get(
          '#META.overflow', np.zeros((k,), bool))).astype(bool)
      return jax.device_put((x, row, col, em, y, nseed, ovf_steps))
    k = int(np.asarray(frame['row']).shape[0])
    ovf_steps = np.asarray(frame.get('#META.overflow',
                                     np.zeros((k,), bool))).astype(bool)
    nseed = np.asarray(frame['num_sampled_nodes'])[:, 0].astype(np.int32)
    return jax.device_put((
        np.asarray(frame['x']), np.asarray(frame['row']),
        np.asarray(frame['col']), np.asarray(frame['edge_mask']),
        np.asarray(frame['y']), nseed, ovf_steps))

  def _ack_frame(self, frame: dict):
    """Host-side seed ack at CHUNK granularity: record the seed ids
    this block delivered (the per-batch ack protocol's provenance,
    lifted to the block) — chaos tests assert exact coverage from
    this. Typed frames ack from the seed type's 'batch.<t>' key."""
    ids = frame.get('batch')
    if ids is None and self._input_type is not None:
      ids = frame.get(f'batch.{self._input_type}')
    if ids is None:
      return
    ids = np.asarray(ids)
    bs = frame.get('#META.batch_size')
    if bs is not None:
      bs = np.asarray(bs).reshape(-1)
      rows = [ids[j][:int(bs[j])] for j in range(ids.shape[0])]
      ids = np.concatenate(rows) if rows else ids.reshape(-1)
    else:
      ids = ids.reshape(-1)
    self._seen_ids.append(np.asarray(ids, np.int64).reshape(-1))

  # -------------------------------------------------------------- config

  def _flight_config(self) -> dict:
    fans = self._config.num_neighbors
    return dict(trainer=self._NAME, batch_size=self.batch_size,
                chunk_size=self.chunk_size,
                input_type=self._input_type,
                fanouts=(dict(fans) if isinstance(fans, dict)
                         else list(fans)),
                shuffle=self._shuffle, drop_last=self._drop_last,
                num_classes=self.num_classes, seed=self.seed,
                servers=list(self.server_ranks),
                wire_dtype=self._wire_dtype,
                tenant=self._tenant,
                tenant_priority=self._tenant_priority,
                tenant_weight=self._tenant_weight,
                active_ranks=list(self._active_ranks))

  # -------------------------------------------------- recovery protocol
  # (recovery/checkpoint.py ChunkCheckpointer — docs/recovery.md). The
  # client carries NO sampler: the server streams are counter-addressed
  # by (epoch, batch index) alone, so a snapshot needs only the epoch
  # index beyond the train state — the resumed epoch re-fetches its
  # remaining blocks from the same pure stream.

  def _recovery_config(self) -> dict:
    import hashlib
    cfg = self._flight_config()
    # elastic tenancy state changes mid-run by design; it must not
    # invalidate the snapshot fingerprint
    cfg.pop('active_ranks', None)
    cfg.pop('tenant_weight', None)
    cfg.update(
        collect_features=self._config.collect_features,
        seeds_sha=hashlib.sha1(
            np.ascontiguousarray(
                self.input_seeds.astype(np.int64)).tobytes())
        .hexdigest()[:16])
    return cfg

  def _recovery_capture(self, carry):
    del carry
    return {}, {}

  def _recovery_load(self, meta, arrays):
    del arrays
    self._epochs = int(meta['epoch'])

  def _recovery_advance(self, meta):
    self._epochs = int(meta['epoch']) + 1

  # ------------------------------------------------------------ teardown

  def shutdown(self):
    """Stop the stager/heartbeat and destroy the server-side block
    producers (dead ranks skipped; destroys are idempotent)."""
    self._stager.close()
    self._heartbeat.stop()
    targets = [(st['rank'], st['pid']) for st in self._streams]
    targets += [(rank, pid)
                for (rank, _), pid in self._replay_pids.items()]
    for rank, pid in targets:
      if rank in self._dead_ranks:
        continue
      try:
        self._dist_client.request_server(rank, 'destroy_block_producer',
                                         pid)
      except (RuntimeError, ConnectionError, OSError):
        pass
