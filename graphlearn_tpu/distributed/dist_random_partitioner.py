"""Parallel (multi-rank) random partitioning.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_random_partitioner.py:
there, each rank owns a slice of edges/features and a DistPartitionManager
syncs partition chunks + books over torch-RPC callees (:60-126). Here the
design leans on what a TPU pod actually has: (a) the node partition book is
derived DETERMINISTICALLY from a shared seed, so no communication is needed
to agree on it; (b) partition payload exchange goes through the shared
filesystem (each rank writes its chunks into the target partition's spool
dir — the reference's on-disk layout already assumes a shared/collected
view); (c) a light TCP barrier (distributed/rpc.py) sequences the phases.

Output layout matches partition/base.py exactly, so DistDataset.load reads
it unchanged.
"""
import json
import os
import shutil
from typing import Optional

import numpy as np

from ..partition.base import _type_str
from .rpc import Barrier, RpcClient, RpcServer


def shared_node_pb(num_nodes: int, num_parts: int, seed: int) -> np.ndarray:
  """Deterministic shuffled round-robin book — every rank computes the
  same array from the seed (replaces the reference's PB broadcast)."""
  rng = np.random.default_rng(seed)
  perm = rng.permutation(num_nodes)
  pb = np.empty(num_nodes, dtype=np.int32)
  share = (num_nodes + num_parts - 1) // num_parts
  for p in range(num_parts):
    pb[perm[p * share:(p + 1) * share]] = p
  return pb


class DistRandomPartitioner:
  """Reference: dist_random_partitioner.py:129-538 (homogeneous path).

  Args:
    output_dir: shared filesystem target.
    num_nodes: global node count.
    edge_index / edge_ids / node_feat / node_feat_ids: THIS RANK's slice.
    num_parts: partition count (defaults to world_size).
    rank / world_size: this rank's coordinates.
    master_addr/master_port: rank-0 barrier endpoint (None => single rank).
  """

  def __init__(self, output_dir: str, num_nodes: int, edge_index,
               edge_ids=None, node_feat=None, node_feat_ids=None,
               num_parts: Optional[int] = None, rank: int = 0,
               world_size: int = 1, master_addr: str = '127.0.0.1',
               master_port: Optional[int] = None, seed: int = 0,
               edge_assign_strategy: str = 'by_src'):
    self.output_dir = output_dir
    self.num_nodes = num_nodes
    self.edge_index = np.asarray(edge_index)
    self.edge_ids = (np.asarray(edge_ids) if edge_ids is not None
                     else None)
    self.node_feat = node_feat
    self.node_feat_ids = (np.asarray(node_feat_ids)
                          if node_feat_ids is not None else None)
    self.num_parts = num_parts or world_size
    self.rank = rank
    self.world_size = world_size
    self.master_addr = master_addr
    self.master_port = master_port
    self.seed = seed
    self.edge_assign_strategy = edge_assign_strategy
    self._server = None
    self._client = None

  # -- barrier plumbing ----------------------------------------------------

  def _init_comm(self):
    if self.world_size <= 1:
      return
    if self.rank == 0:
      self._server = RpcServer(self.master_addr, self.master_port or 0)
      barrier = Barrier(self.world_size)
      self._server.register('partition_barrier', barrier.arrive)
      self.master_port = self._server.port
    self._client = RpcClient()
    self._client.add_target(0, self.master_addr, self.master_port)

  def _barrier(self):
    if self._client is not None:
      self._client.request_sync(0, 'partition_barrier', self.rank)

  # -- partitioning --------------------------------------------------------

  def partition(self) -> str:
    self._init_comm()
    node_pb = shared_node_pb(self.num_nodes, self.num_parts, self.seed)

    # phase 1: every rank spools its slice's chunks into target partitions
    rows, cols = self.edge_index[0], self.edge_index[1]
    eids = (self.edge_ids if self.edge_ids is not None
            else np.arange(rows.shape[0], dtype=np.int64))
    key = rows if self.edge_assign_strategy == 'by_src' else cols
    edge_owner = node_pb[key]
    for p in range(self.num_parts):
      spool = os.path.join(self.output_dir, f'part{p}', '_spool')
      os.makedirs(spool, exist_ok=True)
      m = edge_owner == p
      np.savez(os.path.join(spool, f'graph_rank{self.rank}.npz'),
               rows=rows[m], cols=cols[m], eids=eids[m])
      if self.node_feat is not None:
        fids = (self.node_feat_ids if self.node_feat_ids is not None
                else np.arange(np.asarray(self.node_feat).shape[0]))
        fm = node_pb[fids] == p
        np.savez(os.path.join(spool, f'feat_rank{self.rank}.npz'),
                 feats=np.asarray(self.node_feat)[fm], ids=fids[fm])
    self._barrier()

    # phase 2: each rank merges the partitions it owns (round-robin)
    for p in range(self.rank, self.num_parts, self.world_size):
      part_dir = os.path.join(self.output_dir, f'part{p}')
      spool = os.path.join(part_dir, '_spool')
      g_chunks = sorted(f for f in os.listdir(spool)
                        if f.startswith('graph_rank'))
      rows_l, cols_l, eids_l = [], [], []
      for f in g_chunks:
        with np.load(os.path.join(spool, f)) as z:
          rows_l.append(z['rows'])
          cols_l.append(z['cols'])
          eids_l.append(z['eids'])
      np.savez(os.path.join(part_dir, 'graph.npz'),
               rows=np.concatenate(rows_l), cols=np.concatenate(cols_l),
               eids=np.concatenate(eids_l))
      f_chunks = sorted(f for f in os.listdir(spool)
                        if f.startswith('feat_rank'))
      if f_chunks:
        feats_l, ids_l = [], []
        for f in f_chunks:
          with np.load(os.path.join(spool, f)) as z:
            feats_l.append(z['feats'])
            ids_l.append(z['ids'])
        ids = np.concatenate(ids_l)
        order = np.argsort(ids)
        np.savez(os.path.join(part_dir, 'node_feat.npz'),
                 feats=np.concatenate(feats_l)[order], ids=ids[order])
      shutil.rmtree(spool)

    if self.rank == 0:
      np.save(os.path.join(self.output_dir, 'node_pb.npy'), node_pb)
      # edge book: derived per-rank slices are merged implicitly; rebuild
      # from the merged graphs for exactness
      total_edges = 0
      for p in range(self.num_parts):
        with np.load(os.path.join(self.output_dir, f'part{p}',
                                  'graph.npz')) as z:
          total_edges = max(total_edges,
                            int(z['eids'].max()) + 1 if z['eids'].size
                            else 0)
      edge_pb = np.zeros(total_edges, dtype=np.int32)
      for p in range(self.num_parts):
        with np.load(os.path.join(self.output_dir, f'part{p}',
                                  'graph.npz')) as z:
          edge_pb[z['eids']] = p
      np.save(os.path.join(self.output_dir, 'edge_pb.npy'), edge_pb)
      with open(os.path.join(self.output_dir, 'META.json'), 'w') as f:
        json.dump(dict(num_parts=self.num_parts, hetero=False), f)
    self._barrier()
    if self._server is not None:
      self._server.shutdown()
    return self.output_dir
