"""Parallel (multi-rank) random partitioning, homo + hetero.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_random_partitioner.py:
there, each rank owns a slice of edges/features and a DistPartitionManager
syncs partition chunks + books over torch-RPC callees (:60-126). Here the
design leans on what a TPU pod actually has: (a) the node partition book is
derived DETERMINISTICALLY from a shared seed, so no communication is needed
to agree on it; (b) partition payload exchange goes through the shared
filesystem (each rank writes its chunks into the target partition's spool
dir — the reference's on-disk layout already assumes a shared/collected
view); (c) a light TCP barrier (distributed/rpc.py) sequences the phases.

Heterogeneous inputs (dicts keyed by node/edge type, reference
:299-538) produce the hetero layout of partition/base.py: per-type books
under node_pb/ + edge_pb/, per-type payloads under part{p}/graph/ etc.

Output layout matches partition/base.py exactly, so DistDataset.load reads
it unchanged.
"""
import json
import os
import shutil
from typing import Dict, Optional, Union

import numpy as np

from ..partition.base import _type_str
from .rpc import Barrier, RpcClient, RpcServer


def shared_node_pb(num_nodes: int, num_parts: int, seed: int) -> np.ndarray:
  """Deterministic shuffled round-robin book — every rank computes the
  same array from the seed (replaces the reference's PB broadcast)."""
  rng = np.random.default_rng(seed)
  perm = rng.permutation(num_nodes)
  pb = np.empty(num_nodes, dtype=np.int32)
  share = (num_nodes + num_parts - 1) // num_parts
  for p in range(num_parts):
    pb[perm[p * share:(p + 1) * share]] = p
  return pb


class DistRandomPartitioner:
  """Reference: dist_random_partitioner.py:129-538.

  Args:
    output_dir: shared filesystem target.
    num_nodes: global node count (dict per ntype for hetero).
    edge_index / edge_ids / node_feat / node_feat_ids: THIS RANK's slice
      (dicts keyed by edge/node type for hetero).
    num_parts: partition count (defaults to world_size).
    rank / world_size: this rank's coordinates.
    master_addr/master_port: rank-0 barrier endpoint (None => single rank).
  """

  def __init__(self, output_dir: str,
               num_nodes: Union[int, Dict], edge_index,
               edge_ids=None, node_feat=None, node_feat_ids=None,
               num_parts: Optional[int] = None, rank: int = 0,
               world_size: int = 1, master_addr: str = '127.0.0.1',
               master_port: Optional[int] = None, seed: int = 0,
               edge_assign_strategy: str = 'by_src'):
    self.output_dir = output_dir
    self.is_hetero = isinstance(edge_index, dict)
    self.num_nodes = num_nodes
    self.edge_index = edge_index
    self.edge_ids = edge_ids
    self.node_feat = node_feat
    self.node_feat_ids = node_feat_ids
    self.num_parts = num_parts or world_size
    self.rank = rank
    self.world_size = world_size
    self.master_addr = master_addr
    self.master_port = master_port
    self.seed = seed
    self.edge_assign_strategy = edge_assign_strategy
    self._server = None
    self._client = None
    self._phase = 0

  # -- barrier plumbing ----------------------------------------------------

  def _init_comm(self):
    if self.world_size <= 1:
      return
    if self.rank == 0:
      barrier = Barrier(self.world_size)
      self._server = RpcServer(
          self.master_addr, self.master_port or 0,
          handlers={'partition_barrier': barrier.arrive})
      self.master_port = self._server.port
    self._client = RpcClient()
    self._client.add_target(0, self.master_addr, self.master_port)

  def _barrier(self):
    if self._client is None:
      return
    # rank 0 binds its barrier server concurrently with the other ranks'
    # first arrival — retry refused connections instead of dying (which
    # would strand rank 0 in a 180 s barrier timeout). The phase counter
    # makes retries idempotent across generations (rpc.Barrier.arrive),
    # so the arrival can ride the standard backoff policy.
    from .resilience import RetryPolicy
    phase = self._phase
    self._phase += 1
    # flat 0.2s polls: the attempt budget must outlast the 60s deadline
    # (exponential growth would exhaust the backoff sum long before the
    # window rank 0 historically got to bind its barrier server)
    policy = RetryPolicy(max_attempts=400, base_delay=0.2, max_delay=0.2,
                         multiplier=1.0, jitter=0.0, total_deadline=60.0)
    self._client.request_sync(0, 'partition_barrier', self.rank,
                              phase=phase, idempotent=True,
                              retry_policy=policy)

  # -- typed views ---------------------------------------------------------

  def _ntypes(self):
    if not self.is_hetero:
      return [None]
    types = {t for et in self.edge_index for t in (et[0], et[2])}
    if isinstance(self.num_nodes, dict):
      types |= set(self.num_nodes)      # featured-but-edgeless node types
    if isinstance(self.node_feat, dict):
      types |= set(self.node_feat)
    return sorted(types)

  def _etypes(self):
    return list(self.edge_index) if self.is_hetero else [None]

  def _node_pb_for(self, ntype):
    """Every rank derives the same per-type book from (seed, type)."""
    n = (self.num_nodes[ntype] if self.is_hetero else self.num_nodes)
    off = 0 if ntype is None else self._ntypes().index(ntype)
    return shared_node_pb(n, self.num_parts, self.seed + off)

  def _sel(self, maybe_dict, key):
    if maybe_dict is None:
      return None
    return maybe_dict.get(key) if isinstance(maybe_dict, dict) \
        else (maybe_dict if key is None else None)

  def _tag(self, type_):
    return '' if type_ is None else '_' + _type_str(type_).replace(
        os.sep, '-')

  # -- partitioning --------------------------------------------------------

  def partition(self) -> str:
    self._init_comm()
    ntypes, etypes = self._ntypes(), self._etypes()
    node_pbs = {nt: self._node_pb_for(nt) for nt in ntypes}

    # phase 1: every rank spools its slice's chunks into target partitions
    for et in etypes:
      ei = np.asarray(self.edge_index[et] if self.is_hetero
                      else self.edge_index)
      rows, cols = ei[0].reshape(-1), ei[1].reshape(-1)
      eids = self._sel(self.edge_ids, et)
      if eids is None and self.world_size > 1:
        raise ValueError(
            'multi-rank partitioning requires explicit global edge_ids '
            'per slice — a per-rank arange default would produce '
            'duplicate edge ids across ranks')
      eids = (np.asarray(eids) if eids is not None
              else np.arange(rows.shape[0], dtype=np.int64))
      if self.is_hetero:
        key_pb = node_pbs[et[0] if self.edge_assign_strategy == 'by_src'
                          else et[2]]
      else:
        key_pb = node_pbs[None]
      key = rows if self.edge_assign_strategy == 'by_src' else cols
      edge_owner = key_pb[key]
      for p in range(self.num_parts):
        spool = os.path.join(self.output_dir, f'part{p}', '_spool')
        os.makedirs(spool, exist_ok=True)
        m = edge_owner == p
        np.savez(os.path.join(
            spool, f'graph{self._tag(et)}_rank{self.rank}.npz'),
            rows=rows[m], cols=cols[m], eids=eids[m])
    for nt in ntypes:
      feat = self._sel(self.node_feat, nt)
      if feat is None:
        continue
      feat = np.asarray(feat)
      fids = self._sel(self.node_feat_ids, nt)
      fids = (np.asarray(fids) if fids is not None
              else np.arange(feat.shape[0]))
      pb = node_pbs[nt]
      for p in range(self.num_parts):
        spool = os.path.join(self.output_dir, f'part{p}', '_spool')
        os.makedirs(spool, exist_ok=True)
        fm = pb[fids] == p
        np.savez(os.path.join(
            spool, f'feat{self._tag(nt)}_rank{self.rank}.npz'),
            feats=feat[fm], ids=fids[fm])
    self._barrier()

    # phase 2: each rank merges the partitions it owns (round-robin)
    for p in range(self.rank, self.num_parts, self.world_size):
      part_dir = os.path.join(self.output_dir, f'part{p}')
      spool = os.path.join(part_dir, '_spool')
      for et in etypes:
        pre = f'graph{self._tag(et)}_rank'
        chunks = sorted(f for f in os.listdir(spool) if f.startswith(pre))
        rows_l, cols_l, eids_l = [], [], []
        for f in chunks:
          with np.load(os.path.join(spool, f)) as z:
            rows_l.append(z['rows'])
            cols_l.append(z['cols'])
            eids_l.append(z['eids'])
        payload = dict(rows=np.concatenate(rows_l),
                       cols=np.concatenate(cols_l),
                       eids=np.concatenate(eids_l))
        if et is None:
          np.savez(os.path.join(part_dir, 'graph.npz'), **payload)
        else:
          d = os.path.join(part_dir, 'graph')
          os.makedirs(d, exist_ok=True)
          np.savez(os.path.join(d, f'{_type_str(et)}.npz'), **payload)
      for nt in ntypes:
        pre = f'feat{self._tag(nt)}_rank'
        chunks = sorted(f for f in os.listdir(spool) if f.startswith(pre))
        if not chunks:
          continue
        feats_l, ids_l = [], []
        for f in chunks:
          with np.load(os.path.join(spool, f)) as z:
            feats_l.append(z['feats'])
            ids_l.append(z['ids'])
        ids = np.concatenate(ids_l)
        order = np.argsort(ids)
        payload = dict(feats=np.concatenate(feats_l)[order],
                       ids=ids[order])
        if nt is None:
          np.savez(os.path.join(part_dir, 'node_feat.npz'), **payload)
        else:
          d = os.path.join(part_dir, 'node_feat')
          os.makedirs(d, exist_ok=True)
          np.savez(os.path.join(d, f'{nt}.npz'), **payload)
      shutil.rmtree(spool)
    # all ranks must finish merging before rank 0 reads the merged graphs
    # to rebuild the edge books
    self._barrier()

    if self.rank == 0:
      self._write_books_and_meta(node_pbs, ntypes, etypes)
    self._barrier()
    if self._server is not None:
      self._server.shutdown()
    return self.output_dir

  def _edge_pb_from_merged(self, et) -> np.ndarray:
    """Rebuild the edge book from the merged per-partition graphs."""
    total = 0
    loads = []
    for p in range(self.num_parts):
      path = (os.path.join(self.output_dir, f'part{p}', 'graph.npz')
              if et is None else
              os.path.join(self.output_dir, f'part{p}', 'graph',
                           f'{_type_str(et)}.npz'))
      with np.load(path) as z:
        eids = z['eids']
      loads.append(eids)
      total = max(total, int(eids.max()) + 1 if eids.size else 0)
    pb = np.zeros(total, dtype=np.int32)
    for p, eids in enumerate(loads):
      pb[eids] = p
    return pb

  def _write_books_and_meta(self, node_pbs, ntypes, etypes):
    if self.is_hetero:
      nd = os.path.join(self.output_dir, 'node_pb')
      os.makedirs(nd, exist_ok=True)
      for nt in ntypes:
        np.save(os.path.join(nd, f'{nt}.npy'), node_pbs[nt])
      ed = os.path.join(self.output_dir, 'edge_pb')
      os.makedirs(ed, exist_ok=True)
      for et in etypes:
        np.save(os.path.join(ed, f'{_type_str(et)}.npy'),
                self._edge_pb_from_merged(et))
      meta = dict(num_parts=self.num_parts, hetero=True,
                  node_types=ntypes,
                  edge_types=[list(et) for et in etypes])
    else:
      np.save(os.path.join(self.output_dir, 'node_pb.npy'),
              node_pbs[None])
      np.save(os.path.join(self.output_dir, 'edge_pb.npy'),
              self._edge_pb_from_merged(None))
      meta = dict(num_parts=self.num_parts, hetero=False)
    with open(os.path.join(self.output_dir, 'META.json'), 'w') as f:
      json.dump(meta, f)
