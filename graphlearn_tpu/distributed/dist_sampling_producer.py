"""Sampling producers: collocated (in-process) and mp (subprocess) batch
production into a channel.

TPU-native port of
/root/reference/graphlearn_torch/python/distributed/dist_sampling_producer.py.
The mp producer spawns worker subprocesses that run the sampler over a
static split of the seed range and push serialized SampleMessages into the
shared shm channel (reference _sampling_worker_loop, :53-151). Worker
subprocesses force the CPU jax backend — the TPU chips belong to the
training process (single-controller model), so host-side producers sample
on CPU; the fast path for device sampling is the collocated mesh program.
"""
import multiprocessing as mp
from enum import Enum
from typing import Optional

import numpy as np

from ..channel import ChannelBase
from ..sampler import NodeSamplerInput, SamplingConfig, SamplingType
from .message import hetero_output_to_message, output_to_message


class MpCommand(Enum):
  """Reference: dist_sampling_producer.py MpCommand."""
  SAMPLE_ALL = 0
  STOP = 1


def _sampling_worker_loop(rank, dataset_handle, sampling_config, seeds,
                          task_queue, channel, done_counter):
  """Subprocess body (reference: dist_sampling_producer.py:53-151)."""
  import jax
  try:
    jax.config.update('jax_platforms', 'cpu')
  except RuntimeError:
    pass
  import graphlearn_tpu as glt

  # rebuild from host-side ipc handles; device state stays on CPU here
  gipc = dataset_handle['graph_ipc']
  hetero = isinstance(gipc, dict)
  if hetero:
    graph = {tuple(et): glt.data.Graph(h[0], 'CPU')
             for et, h in gipc.items()}
  else:
    topo, _ = gipc
    graph = glt.data.Graph(topo, 'CPU')
  fipc = dataset_handle['feature_ipc']
  feature = None
  if fipc is not None:
    def _rebuild(h):
      f = glt.data.Feature.from_ipc_handle(h)
      f.with_device = False
      return f
    feature = ({t: _rebuild(h) for t, h in fipc.items()}
               if isinstance(fipc, dict) else _rebuild(fipc))
  dataset = glt.data.Dataset(graph, feature, None,
                             dataset_handle['node_labels'],
                             dataset_handle['edge_dir'])
  input_type = dataset_handle.get('input_type')
  cfg: SamplingConfig = sampling_config
  # fold the worker rank into the seed: same-seeded workers would draw
  # IDENTICAL negative edges per batch index (negatives depend only on
  # the graph + key, not the positives), collapsing negative diversity
  worker_seed = (0 if cfg.seed is None else cfg.seed) * 1000003 + rank
  sampler = glt.sampler.NeighborSampler(
      dataset.graph, cfg.num_neighbors, with_edge=cfg.with_edge,
      with_weight=cfg.with_weight, edge_dir=cfg.edge_dir,
      seed=worker_seed)
  from graphlearn_tpu.sampler import (EdgeSamplerInput, NegativeSampling,
                                      SamplingType)
  is_link = cfg.sampling_type == SamplingType.LINK
  if is_link:
    # seeds is a dict payload for link sampling (reference producers
    # branch on the config's sampling type the same way,
    # dist_sampling_producer.py:106-140)
    rows_, cols_ = seeds['rows'], seeds['cols']
    label_ = seeds.get('label')
    neg = (NegativeSampling(seeds['neg_mode'], seeds['neg_amount'])
           if seeds.get('neg_mode') else None)
    n_seeds = rows_.shape[0]
  else:
    n_seeds = seeds.shape[0]
  while True:
    cmd, payload = task_queue.get()
    if cmd == MpCommand.STOP:
      break
    epoch_seed_order = payload
    n = n_seeds
    bs = cfg.batch_size
    for i in range(0, n - (n % bs if cfg.drop_last else 0), bs):
      idx = epoch_seed_order[i:i + bs]
      if idx.shape[0] == 0:
        continue
      if is_link:
        if idx.shape[0] < bs:
          # pad the final short batch cyclically so every batch keeps the
          # compiled shape (a fresh length would retrace the whole chain
          # per epoch); the few duplicated positives are slightly
          # over-weighted in that one batch
          idx = np.resize(idx, bs)
        out = sampler.sample_from_edges(EdgeSamplerInput(
            rows_[idx], cols_[idx],
            label=(label_[idx] if label_ is not None else None),
            input_type=input_type,
            neg_sampling=neg))
      else:
        out = sampler.sample_from_nodes(
            NodeSamplerInput(seeds[idx], input_type=input_type),
            batch_cap=bs)
      if hetero:
        x_d = y_d = None
        if cfg.collect_features and \
            isinstance(dataset.node_features, dict):
          x_d = {t: dataset.node_features[t].cpu_get(
              np.maximum(np.asarray(out.node[t]), 0))
              for t in out.node if t in dataset.node_features}
        if isinstance(dataset.node_labels, dict):
          y_d = {}
          for t, lab in dataset.node_labels.items():
            if t not in out.node:
              continue
            lab = np.asarray(lab)
            y_d[t] = lab[np.clip(np.asarray(out.node[t]), 0,
                                 len(lab) - 1)]
        channel.send(hetero_output_to_message(out, x_d, y_d))
        continue
      x = y = None
      if cfg.collect_features and dataset.node_features is not None:
        x = dataset.node_features.cpu_get(
            np.maximum(np.asarray(out.node), 0))
      if dataset.node_labels is not None:
        labels = np.asarray(dataset.node_labels)
        y = labels[np.clip(np.asarray(out.node), 0, len(labels) - 1)]
      channel.send(output_to_message(out, x, y))
    with done_counter.get_lock():
      done_counter.value += 1


class DistMpSamplingProducer:
  """Spawn N sampling subprocesses feeding `channel`
  (reference: dist_sampling_producer.py:154-280)."""

  def __init__(self, dataset, sampler_input,
               sampling_config: SamplingConfig, channel: ChannelBase,
               num_workers: int = 1, seed: Optional[int] = None):
    self.dataset = dataset
    self.config = sampling_config
    if hasattr(sampler_input, 'row'):     # EdgeSamplerInput (link mode)
      neg = sampler_input.neg_sampling
      self._link_input = dict(
          rows=np.asarray(sampler_input.row).reshape(-1),
          cols=np.asarray(sampler_input.col).reshape(-1),
          label=(np.asarray(sampler_input.label).reshape(-1)
                 if sampler_input.label is not None else None),
          neg_mode=(neg.mode if neg is not None else None),
          neg_amount=(neg.amount if neg is not None else 1))
      # one channel for the typed-seed tag: the shared dataset handle
      # (input_type below), not per-worker seed payloads
      self._input_type = getattr(sampler_input, 'input_type', None)
      n = self._link_input['rows'].shape[0]
      self.seeds = None
    else:
      self._link_input = None
      self.seeds = np.asarray(sampler_input.node).reshape(-1)
      self._input_type = getattr(sampler_input, 'input_type', None)
      n = self.seeds.shape[0]
    # typed-graph contract, validated HERE so every mp consumer (node
    # loader, link loader, server producers) fails fast instead of a
    # worker assert surfacing as a 60s channel timeout
    if isinstance(dataset.graph, dict) and self._input_type is None:
      raise ValueError(
          'hetero sampling requires typed seeds — pass '
          "('ntype', ids) node seeds (or a NodeSamplerInput with "
          'input_type), or ((src, rel, dst), edge_label_index) link '
          'seeds (EdgeSamplerInput with input_type)')
    self._num_seeds = n
    self.channel = channel
    self.num_workers = num_workers
    self._rng = np.random.default_rng(seed)
    self._procs = []
    self._queues = []
    self._done = None
    self._splits = np.array_split(np.arange(n), num_workers)

  def init(self):
    ctx = mp.get_context('spawn')
    self._done = ctx.Value('i', 0)
    g = self.dataset.graph
    nf = self.dataset.node_features
    handle = dict(
        graph_ipc=({et: gr.share_ipc() for et, gr in g.items()}
                   if isinstance(g, dict) else g.share_ipc()),
        feature_ipc=(None if nf is None else
                     {t: f.share_ipc() for t, f in nf.items()}
                     if isinstance(nf, dict) else nf.share_ipc()),
        node_labels=self.dataset.node_labels,
        edge_dir=self.dataset.edge_dir,
        input_type=getattr(self, '_input_type', None))
    # ship host containers; subprocesses rebuild on the CPU backend
    for w in range(self.num_workers):
      q = ctx.Queue()
      if self._link_input is not None:
        sl = self._splits[w]
        li = self._link_input
        wseeds = dict(rows=li['rows'][sl], cols=li['cols'][sl],
                      label=(li['label'][sl] if li['label'] is not None
                             else None),
                      neg_mode=li['neg_mode'],
                      neg_amount=li['neg_amount'])
      else:
        wseeds = self.seeds[self._splits[w]]
      p = ctx.Process(
          target=_sampling_worker_loop,
          args=(w, handle, self.config, wseeds, q,
                self.channel, self._done),
          daemon=True)
      p.start()
      self._procs.append(p)
      self._queues.append(q)

  def produce_all(self):
    """Kick one epoch of sampling on all workers
    (reference: :227-240)."""
    with self._done.get_lock():
      self._done.value = 0
    if hasattr(self.channel, 'reset'):
      self.channel.reset()
    for w in range(self.num_workers):
      n = self._splits[w].shape[0]
      order = (self._rng.permutation(n) if self.config.shuffle
               else np.arange(n))
      self._queues[w].put((MpCommand.SAMPLE_ALL, order))

  def is_all_sampling_completed(self) -> bool:
    with self._done.get_lock():
      return self._done.value == self.num_workers

  def check_worker_health(self):
    """Raise if a sampling subprocess died abnormally (failure detection —
    the reference's mp workers likewise surface nonzero exits,
    dist_sampling_producer.py worker join handling)."""
    for p in self._procs:
      if p.exitcode is not None and p.exitcode != 0:
        raise RuntimeError(
            f'sampling worker pid={p.pid} died with exit code '
            f'{p.exitcode}')

  def num_expected(self) -> int:
    bs = self.config.batch_size
    total = 0
    for s in self._splits:
      n = s.shape[0]
      total += n // bs if self.config.drop_last else -(-n // bs)
    return total

  def shutdown(self):
    for q in self._queues:
      try:
        q.put((MpCommand.STOP, None))
      except Exception:
        pass
    for p in self._procs:
      p.join(timeout=5)
      if p.is_alive():
        import logging
        logging.getLogger('graphlearn_tpu.producer').warning(
            'sampling worker %s did not exit within 5s; terminating',
            p.pid)
        p.terminate()


class DistCollocatedSamplingProducer:
  """In-process synchronous producer (reference: :283-349)."""

  def __init__(self, dataset, sampler_input: NodeSamplerInput,
               sampling_config: SamplingConfig,
               seed: Optional[int] = None):
    import graphlearn_tpu as glt
    self.dataset = dataset
    self.seeds = np.asarray(sampler_input.node).reshape(-1)
    self.config = sampling_config
    cfg = sampling_config
    self.sampler = glt.sampler.NeighborSampler(
        dataset.graph, cfg.num_neighbors, with_edge=cfg.with_edge,
        with_weight=cfg.with_weight, edge_dir=cfg.edge_dir, seed=cfg.seed)
    self._rng = np.random.default_rng(seed)
    self._order = None
    self._pos = 0

  def reset(self):
    self._order = (self._rng.permutation(self.seeds.shape[0])
                   if self.config.shuffle
                   else np.arange(self.seeds.shape[0]))
    self._pos = 0

  def sample(self):
    """Produce the next batch's message, or None at epoch end."""
    if self._order is None:
      self.reset()
    bs = self.config.batch_size
    n = self.seeds.shape[0]
    if self._pos >= n or (self.config.drop_last and
                          self._pos + bs > n):
      return None
    idx = self._order[self._pos:self._pos + bs]
    self._pos += bs
    out = self.sampler.sample_from_nodes(NodeSamplerInput(self.seeds[idx]),
                                         batch_cap=bs)
    x = y = None
    if self.config.collect_features and \
        self.dataset.node_features is not None:
      x = self.dataset.node_features.cpu_get(
          np.maximum(np.asarray(out.node), 0))
    if self.dataset.node_labels is not None:
      labels = np.asarray(self.dataset.node_labels)
      y = labels[np.clip(np.asarray(out.node), 0, len(labels) - 1)]
    return output_to_message(out, x, y)
