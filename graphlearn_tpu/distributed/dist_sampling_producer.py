"""Sampling producers: collocated (in-process) and mp (subprocess) batch
production into a channel.

TPU-native port of
/root/reference/graphlearn_torch/python/distributed/dist_sampling_producer.py.
The mp producer spawns worker subprocesses that run the sampler over a
static split of the seed range and push serialized SampleMessages into the
shared shm channel (reference _sampling_worker_loop, :53-151). Worker
subprocesses force the CPU jax backend — the TPU chips belong to the
training process (single-controller model), so host-side producers sample
on CPU; the fast path for device sampling is the collocated mesh program.
"""
import multiprocessing as mp
import threading
from enum import Enum
from typing import Optional

import numpy as np

from ..channel import ChannelBase
from ..sampler import NodeSamplerInput, SamplingConfig, SamplingType
from .message import hetero_output_to_message, output_to_message


class MpCommand(Enum):
  """Reference: dist_sampling_producer.py MpCommand."""
  SAMPLE_ALL = 0
  STOP = 1


def _sampling_worker_loop(rank, dataset_handle, sampling_config, seeds,
                          task_queue, channel, done_counter,
                          progress=None, resume_calls: int = 0,
                          metrics_q=None):
  """Subprocess body (reference: dist_sampling_producer.py:53-151).

  Self-healing contract: after every batch lands in the channel the
  worker publishes (batches sent this epoch, sampler call_count) into
  the shared ``progress`` arrays. A crashed worker is respawned with
  ``resume_calls`` = its last published call_count and replays its
  epoch order from the first unsent batch — the sampler's fold_in
  per-call key stream makes the replayed batches bit-identical to what
  the dead worker would have produced (batch i's key depends only on
  (worker seed, call index), never on history).
  """
  import jax
  try:
    jax.config.update('jax_platforms', 'cpu')
  except RuntimeError:
    pass
  import graphlearn_tpu as glt

  # rebuild from host-side ipc handles; device state stays on CPU here
  gipc = dataset_handle['graph_ipc']
  hetero = isinstance(gipc, dict)
  if hetero:
    graph = {tuple(et): glt.data.Graph(h[0], 'CPU')
             for et, h in gipc.items()}
  else:
    topo, _ = gipc
    graph = glt.data.Graph(topo, 'CPU')
  fipc = dataset_handle['feature_ipc']
  feature = None
  if fipc is not None:
    def _rebuild(h):
      f = glt.data.Feature.from_ipc_handle(h)
      f.with_device = False
      return f
    feature = ({t: _rebuild(h) for t, h in fipc.items()}
               if isinstance(fipc, dict) else _rebuild(fipc))
  dataset = glt.data.Dataset(graph, feature, None,
                             dataset_handle['node_labels'],
                             dataset_handle['edge_dir'])
  input_type = dataset_handle.get('input_type')
  cfg: SamplingConfig = sampling_config
  # fold the worker rank into the seed: same-seeded workers would draw
  # IDENTICAL negative edges per batch index (negatives depend only on
  # the graph + key, not the positives), collapsing negative diversity
  worker_seed = (0 if cfg.seed is None else cfg.seed) * 1000003 + rank
  sampler = glt.sampler.NeighborSampler(
      dataset.graph, cfg.num_neighbors, with_edge=cfg.with_edge,
      with_weight=cfg.with_weight, edge_dir=cfg.edge_dir,
      seed=worker_seed)
  # restart path: fast-forward the PRNG stream to where the dead worker
  # left it, so replayed batches reuse the exact per-call keys
  if resume_calls:
    sampler._call_count = resume_calls
  from graphlearn_tpu.sampler import (EdgeSamplerInput, NegativeSampling,
                                      SamplingType)
  is_link = cfg.sampling_type == SamplingType.LINK
  if is_link:
    # seeds is a dict payload for link sampling (reference producers
    # branch on the config's sampling type the same way,
    # dist_sampling_producer.py:106-140)
    rows_, cols_ = seeds['rows'], seeds['cols']
    label_ = seeds.get('label')
    neg = (NegativeSampling(seeds['neg_mode'], seeds['neg_amount'])
           if seeds.get('neg_mode') else None)
    n_seeds = rows_.shape[0]
  else:
    n_seeds = seeds.shape[0]
  from graphlearn_tpu import metrics
  from graphlearn_tpu.utils.faults import fault_point
  import os as _os
  import queue as _queue
  import time as _time
  parent = _os.getppid()
  while True:
    try:
      cmd, payload = task_queue.get(timeout=5)
    except _queue.Empty:
      # orphan guard: a SIGKILL'd producer process cannot STOP its
      # workers; when the parent is gone (reparented to init) exit
      # instead of idling forever as a leaked process
      if _os.getppid() != parent:
        return
      continue
    if cmd == MpCommand.STOP:
      break
    # payload: (epoch order, replay start batch, span wire-context) —
    # the ctx joins this worker's spans to the driving client's trace
    # (a replayed command after a respawn carries the SAME ctx, so the
    # replacement incarnation's spans land in the same tree, orphan-
    # free). Two-tuple payloads from older callers still work.
    epoch_seed_order, start_batch = payload[0], payload[1]
    span_ctx = payload[2] if len(payload) > 2 else None
    from graphlearn_tpu.metrics import spans
    n = n_seeds
    bs = cfg.batch_size
    batch_no = 0
    epoch_ctx = spans.adopt(span_ctx)
    epoch_ctx.__enter__()
    epoch_span = spans.begin('producer.epoch', worker=rank,
                             start_batch=start_batch)
    try:
      for i in range(0, n - (n % bs if cfg.drop_last else 0), bs):
        idx = epoch_seed_order[i:i + bs]
        if idx.shape[0] == 0:
          continue
        if batch_no < start_batch:
          # replay fast-forward: these batches already landed in the
          # channel before the previous incarnation died; the PRNG keys
          # they consumed are covered by resume_calls, so skipping them
          # does not shift the remaining batches' key stream
          batch_no += 1
          continue
        # chaos harness site: armed 'exit' here (before the sample/send)
        # kills the worker at an exact batch index with nothing in flight
        fault_point('producer.worker.batch')
        batch_span = spans.begin('producer.batch', batch=batch_no)
        try:
          t_batch = _time.perf_counter()
          if is_link:
            if idx.shape[0] < bs:
              # pad the final short batch cyclically so every batch keeps
              # the compiled shape (a fresh length would retrace the whole
              # chain per epoch); the few duplicated positives are slightly
              # over-weighted in that one batch
              idx = np.resize(idx, bs)
            out = sampler.sample_from_edges(EdgeSamplerInput(
                rows_[idx], cols_[idx],
                label=(label_[idx] if label_ is not None else None),
                input_type=input_type,
                neg_sampling=neg))
          else:
            out = sampler.sample_from_nodes(
                NodeSamplerInput(seeds[idx], input_type=input_type),
                batch_cap=bs)
          if hetero:
            x_d = y_d = None
            if cfg.collect_features and \
                isinstance(dataset.node_features, dict):
              x_d = {t: dataset.node_features[t].cpu_get(
                  np.maximum(np.asarray(out.node[t]), 0))
                  for t in out.node if t in dataset.node_features}
            if isinstance(dataset.node_labels, dict):
              y_d = {}
              for t, lab in dataset.node_labels.items():
                if t not in out.node:
                  continue
                lab = np.asarray(lab)
                y_d[t] = lab[np.clip(np.asarray(out.node[t]), 0,
                                     len(lab) - 1)]
            msg = hetero_output_to_message(out, x_d, y_d)
          else:
            x = y = None
            if cfg.collect_features and dataset.node_features is not None:
              x = dataset.node_features.cpu_get(
                  np.maximum(np.asarray(out.node), 0))
            if dataset.node_labels is not None:
              labels = np.asarray(dataset.node_labels)
              y = labels[np.clip(np.asarray(out.node), 0,
                                 len(labels) - 1)]
            msg = output_to_message(out, x, y)
          channel.send(msg)
          # worker-local observability: this subprocess's own registry; it
          # reaches the trainer through the metrics_q snapshot below (and
          # DistServer.get_metrics / metrics.scrape_all from there)
          metrics.inc('producer.batches')
          metrics.observe('producer.sample_ms',
                          (_time.perf_counter() - t_batch) * 1e3)
        finally:
          # a raising sample/send must not strand the batch span on this
          # worker's context stack — later batches would parent under it
          spans.end(batch_span)
        batch_no += 1
        if progress is not None:
          # published AFTER the send. Tradeoff for an UNCONTROLLED crash
          # landing exactly between send and publish: the replay re-emits
          # that one batch (a duplicate, which consumers counting toward
          # expected will take in place of the true final batch) —
          # publishing first would instead lose the batch outright.
          # Exact replay is guaranteed when the crash point is before the
          # send, which is where the chaos harness injects kills
          # (docs/failure_model.md 'Limits').
          sent_arr, calls_arr = progress
          with sent_arr.get_lock():
            sent_arr[rank] = batch_no
            calls_arr[rank] = sampler._call_count
    finally:
      # the epoch span and adopted trace context close even when a
      # batch raises out of the loop — the respawned incarnation's
      # replay re-adopts the same ctx and must not nest under a stale
      # leaked span
      spans.end(epoch_span, batches=batch_no)
      epoch_ctx.__exit__(None, None, None)
    with done_counter.get_lock():
      done_counter.value += 1
    if metrics_q is not None:
      # publish the CUMULATIVE worker snapshot at epoch end over the
      # producer's queue plumbing — latest-wins per rank on the other
      # side, so a lost/duplicated frame costs nothing. The snapshot
      # carries this worker's span ring + the epoch's trace id as
      # extra keys: DistServer.get_metrics (and worker_metrics) expose
      # them so a scrape recovers producer spans by id alone
      try:
        snap = metrics.snapshot()
        snap['spans'] = spans.export(limit=spans.SCRAPE_EXPORT_LIMIT)
        snap['run_id'] = (span_ctx or {}).get('trace') or spans.run_id()
        metrics_q.put_nowait((rank, snap))
      except Exception:  # noqa: BLE001 - observability must not kill work
        pass


class DistMpSamplingProducer:
  """Spawn N sampling subprocesses feeding `channel`
  (reference: dist_sampling_producer.py:154-280)."""

  def __init__(self, dataset, sampler_input,
               sampling_config: SamplingConfig, channel: ChannelBase,
               num_workers: int = 1, seed: Optional[int] = None,
               max_worker_restarts: int = 2):
    self.dataset = dataset
    self.config = sampling_config
    # self-healing budget: check_worker_health respawns a crashed worker
    # (replaying its unfinished seed blocks bit-identically) at most
    # this many times per producer before giving up
    self.max_worker_restarts = max_worker_restarts
    self._restarts_used = 0
    # serializes crash detection + respawn: the server calls
    # check_worker_health from concurrent RPC handler threads (one per
    # puller connection), and a double-respawn of the same worker would
    # replay its seed tail twice
    self._health_lock = threading.Lock()
    if hasattr(sampler_input, 'row'):     # EdgeSamplerInput (link mode)
      neg = sampler_input.neg_sampling
      self._link_input = dict(
          rows=np.asarray(sampler_input.row).reshape(-1),
          cols=np.asarray(sampler_input.col).reshape(-1),
          label=(np.asarray(sampler_input.label).reshape(-1)
                 if sampler_input.label is not None else None),
          neg_mode=(neg.mode if neg is not None else None),
          neg_amount=(neg.amount if neg is not None else 1))
      # one channel for the typed-seed tag: the shared dataset handle
      # (input_type below), not per-worker seed payloads
      self._input_type = getattr(sampler_input, 'input_type', None)
      n = self._link_input['rows'].shape[0]
      self.seeds = None
    else:
      self._link_input = None
      self.seeds = np.asarray(sampler_input.node).reshape(-1)
      self._input_type = getattr(sampler_input, 'input_type', None)
      n = self.seeds.shape[0]
    # typed-graph contract, validated HERE so every mp consumer (node
    # loader, link loader, server producers) fails fast instead of a
    # worker assert surfacing as a 60s channel timeout
    if isinstance(dataset.graph, dict) and self._input_type is None:
      raise ValueError(
          'hetero sampling requires typed seeds — pass '
          "('ntype', ids) node seeds (or a NodeSamplerInput with "
          'input_type), or ((src, rel, dst), edge_label_index) link '
          'seeds (EdgeSamplerInput with input_type)')
    self._num_seeds = n
    self.channel = channel
    self.num_workers = num_workers
    self._rng = np.random.default_rng(seed)
    self._procs = []
    self._queues = []
    self._done = None
    self._splits = np.array_split(np.arange(n), num_workers)

  def _worker_seeds(self, w: int):
    if self._link_input is not None:
      sl = self._splits[w]
      li = self._link_input
      return dict(rows=li['rows'][sl], cols=li['cols'][sl],
                  label=(li['label'][sl] if li['label'] is not None
                         else None),
                  neg_mode=li['neg_mode'],
                  neg_amount=li['neg_amount'])
    return self.seeds[self._splits[w]]

  def _spawn_worker(self, w: int, resume_calls: int = 0):
    q = self._ctx.Queue()
    p = self._ctx.Process(
        target=_sampling_worker_loop,
        args=(w, self._handle, self.config, self._worker_seeds(w), q,
              self.channel, self._done, (self._sent, self._calls),
              resume_calls, self._metrics_q),
        daemon=True)
    p.start()
    self._procs[w] = p
    self._queues[w] = q

  def init(self):
    ctx = self._ctx = mp.get_context('spawn')
    self._done = ctx.Value('i', 0)
    # per-worker progress, shared with the subprocesses: batches sent in
    # the current epoch + the sampler's call_count — everything the
    # restart path needs to replay a dead worker exactly
    self._sent = ctx.Array('q', self.num_workers)
    self._calls = ctx.Array('q', self.num_workers)
    # worker metric snapshots ride their own small queue (epoch-end
    # cadence, latest-wins) — NEVER the data channel, whose message
    # count is the epoch-completion contract
    self._metrics_q = ctx.Queue()
    self._worker_snaps = {}
    self._metrics_drain_lock = threading.Lock()
    self._last_orders = [None] * self.num_workers
    self._last_ctx = [None] * self.num_workers
    g = self.dataset.graph
    nf = self.dataset.node_features
    self._handle = dict(
        graph_ipc=({et: gr.share_ipc() for et, gr in g.items()}
                   if isinstance(g, dict) else g.share_ipc()),
        feature_ipc=(None if nf is None else
                     {t: f.share_ipc() for t, f in nf.items()}
                     if isinstance(nf, dict) else nf.share_ipc()),
        node_labels=self.dataset.node_labels,
        edge_dir=self.dataset.edge_dir,
        input_type=getattr(self, '_input_type', None))
    # ship host containers; subprocesses rebuild on the CPU backend
    self._procs = [None] * self.num_workers
    self._queues = [None] * self.num_workers
    for w in range(self.num_workers):
      self._spawn_worker(w)

  def produce_all(self):
    """Kick one epoch of sampling on all workers
    (reference: :227-240)."""
    from ..metrics import spans
    with self._done.get_lock():
      self._done.value = 0
    with self._sent.get_lock():
      for w in range(self.num_workers):
        self._sent[w] = 0
    if hasattr(self.channel, 'reset'):
      self.channel.reset()
    # the epoch command carries the CALLER's span context (the client's
    # epoch span when produce_all was reached through an RPC whose
    # handler adopted it) so worker spans join the driving trace; kept
    # per worker for replay — a respawned incarnation must land its
    # spans in the SAME tree
    ctx = spans.wire_context()
    for w in range(self.num_workers):
      n = self._splits[w].shape[0]
      order = (self._rng.permutation(n) if self.config.shuffle
               else np.arange(n))
      self._last_orders[w] = order
      self._last_ctx[w] = ctx
      self._queues[w].put((MpCommand.SAMPLE_ALL, (order, 0, ctx)))

  def is_all_sampling_completed(self) -> bool:
    with self._done.get_lock():
      return self._done.value == self.num_workers

  def _expected_for_worker(self, w: int) -> int:
    n = self._splits[w].shape[0]
    bs = self.config.batch_size
    return n // bs if self.config.drop_last else -(-n // bs)

  def check_worker_health(self):
    """Detect crashed sampling subprocesses and self-heal.

    A worker with a nonzero exit code is respawned with the sampler
    PRNG stream fast-forwarded to its last published call_count, and
    its current epoch order is replayed from the first unsent batch —
    bit-identical to what the dead worker would have produced (see
    _sampling_worker_loop). After ``max_worker_restarts`` respawns the
    producer gives up and raises, so a deterministically-crashing
    worker cannot restart-loop forever. Thread-safe: concurrent callers
    (the server's per-connection RPC threads) serialize on a lock, and
    the post-lock re-read of self._procs sees a sibling's respawn as a
    healthy worker instead of restarting it twice.
    """
    with self._health_lock:
      self._check_worker_health_locked()

  def _check_worker_health_locked(self):
    for w in range(len(self._procs)):
      p = self._procs[w]
      if p is None or p.exitcode is None or p.exitcode == 0:
        continue
      if self._restarts_used >= self.max_worker_restarts:
        raise RuntimeError(
            f'sampling worker {w} (pid={p.pid}) died with exit code '
            f'{p.exitcode} and the restart budget '
            f'({self.max_worker_restarts}) is exhausted — giving up')
      self._restarts_used += 1
      with self._sent.get_lock():
        sent = int(self._sent[w])
        calls = int(self._calls[w])
      from ..utils import trace
      trace.counter_inc('resilience.worker_restart')
      import logging
      logging.getLogger('graphlearn_tpu.producer').warning(
          'sampling worker %d (pid=%s) died with exit code %s after %d '
          'batches; respawning (restart %d/%d) and replaying from batch '
          '%d', w, p.pid, p.exitcode, sent, self._restarts_used,
          self.max_worker_restarts, sent)
      self._spawn_worker(w, resume_calls=calls)
      order = self._last_orders[w]
      if order is not None and sent < self._expected_for_worker(w):
        # mid-epoch death: replay the unfinished tail of its seed order
        # under the SAME span context — the respawned incarnation's
        # spans join the original epoch's tree (no orphans)
        self._queues[w].put((MpCommand.SAMPLE_ALL,
                             (order, sent, self._last_ctx[w])))

  def worker_metrics(self):
    """Merged metric snapshot across this producer's mp workers, or
    None before any worker has published (workers push cumulative
    snapshots at epoch end over ``_metrics_q``; latest-wins per rank —
    a respawned worker's fresh registry simply restarts its series).
    The drain is serialized under a lock: concurrent callers (the
    owning loader + DistServer.get_metrics RPC-handler threads) racing
    get_nowait against the per-rank dict write could otherwise land an
    OLDER frame over a newer one and make the cumulative series step
    backwards until the next epoch-end publish."""
    import queue as _queue
    q = getattr(self, '_metrics_q', None)
    if q is None:
      return None
    with self._metrics_drain_lock:
      while True:
        try:
          rank, snap = q.get_nowait()
        except (_queue.Empty, OSError, ValueError):
          break
        self._worker_snaps[rank] = snap
      if not self._worker_snaps:
        return None
      snaps = list(self._worker_snaps.values())
    from ..metrics import merge_snapshots
    merged = merge_snapshots(snaps)
    # span rings don't merge — concatenate them (and carry a run_id)
    # so get_metrics / scrape_all expose producer spans per role
    span_rows = [s for snap in snaps for s in snap.get('spans', ())]
    if span_rows:
      merged['spans'] = span_rows
    for snap in snaps:
      if snap.get('run_id'):
        merged['run_id'] = snap['run_id']
        break
    return merged

  def num_expected(self) -> int:
    bs = self.config.batch_size
    total = 0
    for s in self._splits:
      n = s.shape[0]
      total += n // bs if self.config.drop_last else -(-n // bs)
    return total

  def shutdown(self):
    """Idempotent: a second shutdown (epoch teardown racing server exit)
    is a no-op."""
    if getattr(self, '_shutdown_done', False):
      return
    self._shutdown_done = True
    for q in self._queues:
      try:
        q.put((MpCommand.STOP, None))
      except Exception:
        pass
    for p in self._procs:
      if p is None:
        continue
      p.join(timeout=5)
      if p.is_alive():
        import logging
        logging.getLogger('graphlearn_tpu.producer').warning(
            'sampling worker %s did not exit within 5s; terminating',
            p.pid)
        p.terminate()


class DistCollocatedSamplingProducer:
  """In-process synchronous producer (reference: :283-349)."""

  def __init__(self, dataset, sampler_input: NodeSamplerInput,
               sampling_config: SamplingConfig,
               seed: Optional[int] = None):
    import graphlearn_tpu as glt
    self.dataset = dataset
    self.seeds = np.asarray(sampler_input.node).reshape(-1)
    self.config = sampling_config
    cfg = sampling_config
    self.sampler = glt.sampler.NeighborSampler(
        dataset.graph, cfg.num_neighbors, with_edge=cfg.with_edge,
        with_weight=cfg.with_weight, edge_dir=cfg.edge_dir, seed=cfg.seed)
    self._rng = np.random.default_rng(seed)
    self._order = None
    self._pos = 0

  def reset(self):
    self._order = (self._rng.permutation(self.seeds.shape[0])
                   if self.config.shuffle
                   else np.arange(self.seeds.shape[0]))
    self._pos = 0

  def sample(self):
    """Produce the next batch's message, or None at epoch end."""
    if self._order is None:
      self.reset()
    bs = self.config.batch_size
    n = self.seeds.shape[0]
    if self._pos >= n or (self.config.drop_last and
                          self._pos + bs > n):
      return None
    idx = self._order[self._pos:self._pos + bs]
    self._pos += bs
    out = self.sampler.sample_from_nodes(NodeSamplerInput(self.seeds[idx]),
                                         batch_cap=bs)
    x = y = None
    if self.config.collect_features and \
        self.dataset.node_features is not None:
      x = self.dataset.node_features.cpu_get(
          np.maximum(np.asarray(out.node), 0))
    if self.dataset.node_labels is not None:
      labels = np.asarray(self.dataset.node_labels)
      y = labels[np.clip(np.asarray(out.node), 0, len(labels) - 1)]
    return output_to_message(out, x, y)
