"""Distributed context: mesh + role bookkeeping.

TPU-native re-design of
/root/reference/graphlearn_torch/python/distributed/dist_context.py. The
reference tracks (role, world_size, rank, group_name) per *process* in an
RPC mesh. On TPU a single host process drives all local chips and the
scale-out unit is the `jax.sharding.Mesh`; the context therefore carries the
mesh (graph-partition axis 'g') plus the same role/rank fields for
multi-host and server/client topologies (jax.process_index serves as the
node rank).
"""
import enum
from typing import Optional

import numpy as np


class DistRole(enum.Enum):
  """Reference: dist_context.py:23-25."""
  WORKER = 1
  SERVER = 2
  CLIENT = 3


class DistContext:
  """Reference: dist_context.py:100-134 (worker_name, rank arithmetic)."""

  def __init__(self, world_size: int, rank: int,
               role: DistRole = DistRole.WORKER,
               group_name: str = 'worker', num_partitions: int = 1,
               mesh=None):
    self.role = role
    self.world_size = world_size
    self.rank = rank
    self.group_name = group_name
    self.num_partitions = num_partitions
    self.mesh = mesh

  @property
  def worker_name(self) -> str:
    return f'{self.group_name}-{self.rank}'

  def is_worker(self) -> bool:
    return self.role == DistRole.WORKER

  def is_server(self) -> bool:
    return self.role == DistRole.SERVER

  def is_client(self) -> bool:
    return self.role == DistRole.CLIENT


_dist_context: Optional[DistContext] = None


def get_context() -> Optional[DistContext]:
  return _dist_context


def _build_mesh(devs, nparts: int, mesh_shape=None):
  """Flat ('g',) mesh, or a 2-axis ('slice', 'chip') mesh when
  ``mesh_shape=(S, C)`` is given (S*C == nparts). Device order is kept,
  so consecutive groups of C devices form one slice — on a pod that is
  one ICI domain, and the 'chip' axis collectives ride ICI while
  'slice' crosses DCN. The samplers run unchanged on either layout
  (collectives/specs use the full axis tuple)."""
  from jax.sharding import Mesh
  if mesh_shape is None:
    return Mesh(np.array(devs[:nparts]), ('g',))
  s, c = mesh_shape
  if s * c != nparts:
    raise ValueError(f'mesh_shape {mesh_shape} != num_partitions '
                     f'{nparts}')
  return Mesh(np.array(devs[:nparts]).reshape(s, c), ('slice', 'chip'))


def init_worker_group(world_size: int = 1, rank: int = 0,
                      group_name: str = 'worker',
                      num_partitions: Optional[int] = None,
                      devices=None, mesh_shape=None):
  """Create the worker context + graph mesh
  (reference: dist_context.py:169-183).

  ``num_partitions`` defaults to the device count: one graph partition per
  chip, the TPU analog of one partition per worker process.
  ``mesh_shape=(slices, chips)`` builds the 2-axis multi-slice mesh
  instead of the flat 'g' axis (see _build_mesh).
  """
  global _dist_context
  import jax
  devs = list(devices) if devices is not None else jax.devices()
  nparts = num_partitions or len(devs)
  mesh = _build_mesh(devs, nparts, mesh_shape)
  _dist_context = DistContext(world_size, rank, DistRole.WORKER,
                              group_name, nparts, mesh)
  return _dist_context


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   group_name: str = 'worker',
                   num_partitions: Optional[int] = None,
                   mesh_shape=None):
  """Multi-host worker context: initialize the JAX distributed runtime and
  build ONE GLOBAL mesh spanning every process's devices.

  The TPU replacement for the reference's cross-machine RPC worker mesh
  (distributed/rpc.py:238-311 + launch.py env wiring): after this call the
  same shard_map sampling programs run unchanged over the pod — XLA routes
  the all_to_all hops over ICI within a slice and DCN across hosts. Each
  process calls with its own ``process_id``; on Cloud TPU the three args
  can be omitted (auto-detected from the TPU environment). Device arrays
  built through utils.global_device_put place only this process's
  addressable shards.

  CPU harness (tests/test_multihost.py): set ``jax_num_cpu_devices`` per
  process and point every process at the same coordinator — collectives
  run over gloo, validating the multi-process path without a pod.
  """
  global _dist_context
  import jax
  jax.distributed.initialize(coordinator_address, num_processes,
                             process_id)
  from jax.sharding import Mesh
  devs = jax.devices()   # global: all processes' devices
  nparts = num_partitions or len(devs)
  mesh_devs = devs[:nparts]
  # every process must address at least one mesh device, or its
  # global_device_put/shard_map calls have nothing local to run on
  procs_in_mesh = {d.process_index for d in mesh_devs}
  if len(procs_in_mesh) < jax.process_count():
    raise ValueError(
        f'num_partitions={nparts} selects devices from only '
        f'{len(procs_in_mesh)}/{jax.process_count()} processes; use a '
        'multiple of the per-process device count (or omit it) so every '
        'host participates in the mesh')
  # default multi-slice layout: one slice per process (jax.devices()
  # orders by process, so each process's devices form one 'chip' row —
  # the ICI domain on a pod, the per-process group on the CPU harness)
  if mesh_shape == 'per_process':
    mesh_shape = (jax.process_count(), nparts // jax.process_count())
  mesh = _build_mesh(devs, nparts, mesh_shape)
  _dist_context = DistContext(jax.process_count(), jax.process_index(),
                              DistRole.WORKER, group_name, nparts, mesh)
  return _dist_context


def _set_server_context(num_servers, num_clients, server_rank,
                        group_name='server', num_partitions=1, mesh=None):
  """Reference: dist_context.py:135-151."""
  global _dist_context
  _dist_context = DistContext(num_servers, server_rank, DistRole.SERVER,
                              group_name, num_partitions, mesh)
  return _dist_context


def _set_client_context(num_servers, num_clients, client_rank,
                        group_name='client'):
  """Reference: dist_context.py:152-167."""
  global _dist_context
  _dist_context = DistContext(num_clients, client_rank, DistRole.CLIENT,
                              group_name)
  return _dist_context
