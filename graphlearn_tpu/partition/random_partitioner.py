"""Random (round-robin over shuffled chunks) node partitioning.

TPU-native port of
/root/reference/graphlearn_torch/python/partition/random_partitioner.py:
node ids are split into chunks, chunks shuffled, and dealt round-robin so
each partition gets a near-equal share.
"""
from typing import Optional

import numpy as np

from ..typing import NodeType
from .base import PartitionerBase


class RandomPartitioner(PartitionerBase):
  """Reference: random_partitioner.py:28-86."""

  def __init__(self, output_dir, num_parts, num_nodes, edge_index,
               node_feat=None, edge_feat=None, edge_weights=None,
               edge_assign_strategy='by_src', chunk_size=10000,
               seed: Optional[int] = None):
    super().__init__(output_dir, num_parts, num_nodes, edge_index,
                     node_feat, edge_feat, edge_weights,
                     edge_assign_strategy, chunk_size)
    self._rng = np.random.default_rng(seed)

  def _partition_node(self, ntype: Optional[NodeType]) -> np.ndarray:
    n = (self.num_nodes[ntype] if isinstance(self.num_nodes, dict)
         else self.num_nodes)
    perm = self._rng.permutation(n)
    pb = np.empty(n, dtype=np.int32)
    # shuffled ids dealt round-robin in equal contiguous shares
    share = (n + self.num_parts - 1) // self.num_parts
    for p in range(self.num_parts):
      pb[perm[p * share:(p + 1) * share]] = p
    return pb
