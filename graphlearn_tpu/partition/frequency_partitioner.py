"""Hotness-aware (access-frequency) partitioning with per-partition caches.

TPU-native port of
/root/reference/graphlearn_torch/python/partition/frequency_partitioner.py:
given per-partition access-probability vectors (from pre-sampling,
NeighborSampler.sample_prob), node chunks are greedily assigned to the
partition where they are hottest (subject to balance), and each partition
hot-caches its top remotely-owned nodes under a cache budget. On TPU the
cache feeds the HBM-resident hot prefix of the Feature store, which is the
main lever against host-fetch latency (no UVA).
"""
from typing import List, Optional

import numpy as np

from ..typing import NodeType
from .base import PartitionerBase


class FrequencyPartitioner(PartitionerBase):
  """Reference: frequency_partitioner.py:26-205.

  Args:
    probs: per-partition access-probability vectors — list of [num_nodes]
      arrays, one per target partition (homo), or dict ntype -> list.
    cache_ratio: fraction of a partition's nodes to hot-cache.
  """

  def __init__(self, output_dir, num_parts, num_nodes, edge_index,
               probs: List[np.ndarray], node_feat=None, edge_feat=None,
               edge_weights=None, edge_assign_strategy='by_src',
               chunk_size=10000, cache_ratio: float = 0.0,
               seed: Optional[int] = None):
    super().__init__(output_dir, num_parts, num_nodes, edge_index,
                     node_feat, edge_feat, edge_weights,
                     edge_assign_strategy, chunk_size)
    self.probs = probs
    self.cache_ratio = cache_ratio
    self._node_pb = {}
    del seed

  def _get_probs(self, ntype):
    return self.probs[ntype] if isinstance(self.probs, dict) else self.probs

  def _partition_node(self, ntype: Optional[NodeType]) -> np.ndarray:
    """Greedy chunk assignment maximizing local hotness under balance
    (reference: frequency_partitioner.py:103-171)."""
    n = (self.num_nodes[ntype] if isinstance(self.num_nodes, dict)
         else self.num_nodes)
    probs = [np.asarray(p) for p in self._get_probs(ntype)]
    assert len(probs) == self.num_parts
    chunk = self.chunk_size
    num_chunks = (n + chunk - 1) // chunk
    # score[c, p] = how hot chunk c is for partition p
    score = np.zeros((num_chunks, self.num_parts))
    for p in range(self.num_parts):
      padded = np.zeros(num_chunks * chunk)
      padded[:n] = probs[p][:n]
      score[:, p] = padded.reshape(num_chunks, chunk).sum(1)
    cap = (num_chunks + self.num_parts - 1) // self.num_parts
    counts = np.zeros(self.num_parts, dtype=np.int64)
    pb = np.empty(n, dtype=np.int32)
    # hottest chunks pick first (stable greedy, like the reference's
    # per-chunk argmax with capacity)
    order = np.argsort(-score.max(axis=1))
    for c in order:
      for p in np.argsort(-score[c]):
        if counts[p] < cap:
          lo, hi = c * chunk, min((c + 1) * chunk, n)
          pb[lo:hi] = p
          counts[p] += 1
          break
    self._node_pb[ntype] = pb
    return pb

  def _cache_node(self, ntype: Optional[NodeType],
                  part: int) -> Optional[np.ndarray]:
    """Top-hot nodes for `part` under the cache budget
    (reference: frequency_partitioner.py:173-205)."""
    if self.cache_ratio <= 0:
      return None
    n = (self.num_nodes[ntype] if isinstance(self.num_nodes, dict)
         else self.num_nodes)
    budget = int(n * self.cache_ratio / self.num_parts)
    if budget <= 0:
      return None
    prob = np.asarray(self._get_probs(ntype)[part])[:n]
    pb = self._node_pb[ntype]
    # cache only remotely-owned hot nodes (local ones are already local)
    remote_hot = np.where((pb != part) & (prob > 0))[0]
    if remote_hot.size == 0:
      return None
    top = remote_hot[np.argsort(-prob[remote_hot])][:budget]
    return np.sort(top)
