"""Graph/feature partitioning with a durable on-disk layout.

TPU-native port of /root/reference/graphlearn_torch/python/partition/base.py.
The pipeline (node -> node-feature -> graph -> edge-feature partitioning) and
the directory layout (base.py:397-475) are kept; tensors are .npz instead of
.pt and META is JSON:

  <root>/
    META.json                      {num_parts, hetero, node/edge types, ...}
    node_pb.npy | node_pb/<ntype>.npy
    edge_pb.npy | edge_pb/<etype-str>.npy
    part<i>/
      graph.npz | graph/<etype-str>.npz      rows, cols, eids[, weights]
      node_feat.npz | node_feat/<ntype>.npz  feats, ids[, cache_feats, cache_ids]
      edge_feat.npz | edge_feat/<etype-str>.npz

Partition books (node_pb/edge_pb) map global id -> owning partition
(reference typing.py:78-82); they double as the shard maps the distributed
layer bakes into its pjit shardings.
"""
import json
import os
from typing import Dict, Optional, Tuple, Union

import numpy as np

from ..typing import (EdgeType, FeaturePartitionData, GraphPartitionData,
                      NodeType, as_str)


class PartitionerBase:
  """Drives partitioning and persistence (reference: base.py:154-553).

  Subclasses implement `_partition_node(ntype) -> node_pb` and optionally
  `_cache_node(ntype, part) -> cached global ids`.

  Homo inputs are plain arrays; hetero inputs are dicts keyed by
  NodeType/EdgeType.
  """

  def __init__(self, output_dir: str, num_parts: int,
               num_nodes: Union[int, Dict[NodeType, int]],
               edge_index: Union[np.ndarray, Dict[EdgeType, np.ndarray]],
               node_feat=None, edge_feat=None, edge_weights=None,
               edge_assign_strategy: str = 'by_src',
               chunk_size: int = 10000):
    self.output_dir = output_dir
    self.num_parts = num_parts
    self.num_nodes = num_nodes
    self.edge_index = edge_index
    self.node_feat = node_feat
    self.edge_feat = edge_feat
    self.edge_weights = edge_weights
    self.edge_assign_strategy = edge_assign_strategy.lower()
    assert self.edge_assign_strategy in ('by_src', 'by_dst')
    self.chunk_size = chunk_size
    self.is_hetero = isinstance(edge_index, dict)

  # ------------------------------------------------------------ public API

  def partition(self):
    """Run the full pipeline and persist (reference: base.py:397-475)."""
    os.makedirs(self.output_dir, exist_ok=True)
    if self.is_hetero:
      ntypes = sorted({t for et in self.edge_index for t in (et[0], et[2])})
      etypes = list(self.edge_index.keys())
      node_pbs = {}
      for nt in ntypes:
        node_pbs[nt] = self._partition_node(nt)
        self._save_node_pb(node_pbs[nt], nt)
        self._partition_and_save_node_feat(node_pbs[nt], nt)
      for et in etypes:
        edge_pb = self._partition_and_save_graph(node_pbs, et)
        self._save_edge_pb(edge_pb, et)
        self._partition_and_save_edge_feat(edge_pb, et)
      meta = dict(num_parts=self.num_parts, hetero=True,
                  node_types=ntypes,
                  edge_types=[list(et) for et in etypes])
    else:
      node_pb = self._partition_node(None)
      self._save_node_pb(node_pb, None)
      self._partition_and_save_node_feat(node_pb, None)
      edge_pb = self._partition_and_save_graph(node_pb, None)
      self._save_edge_pb(edge_pb, None)
      self._partition_and_save_edge_feat(edge_pb, None)
      meta = dict(num_parts=self.num_parts, hetero=False)
    with open(os.path.join(self.output_dir, 'META.json'), 'w') as f:
      json.dump(meta, f)
    return self.output_dir

  # ---------------------------------------------------------- partitioning

  def _partition_node(self, ntype: Optional[NodeType]) -> np.ndarray:
    raise NotImplementedError

  def _cache_node(self, ntype: Optional[NodeType],
                  part: int) -> Optional[np.ndarray]:
    """Global ids to hot-cache on `part` (FrequencyPartitioner only)."""
    return None

  def _get_edge_index(self, etype):
    ei = self.edge_index[etype] if etype is not None else self.edge_index
    ei = np.asarray(ei)
    return ei[0].reshape(-1), ei[1].reshape(-1)

  def _partition_and_save_graph(self, node_pb, etype) -> np.ndarray:
    """Assign each edge to the partition owning its src (or dst) endpoint,
    chunked to bound peak memory (reference: base.py:254-334)."""
    rows, cols = self._get_edge_index(etype)
    e = rows.shape[0]
    eids = np.arange(e, dtype=np.int64)
    if self.is_hetero:
      src_pb = node_pb[etype[0]] if self.edge_assign_strategy == 'by_src' \
          else node_pb[etype[2]]
    else:
      src_pb = node_pb
    key = rows if self.edge_assign_strategy == 'by_src' else cols
    edge_pb = np.empty(e, dtype=np.int32)
    for start in range(0, e, self.chunk_size * 64):
      end = min(e, start + self.chunk_size * 64)
      edge_pb[start:end] = src_pb[key[start:end]]
    weights = (np.asarray(self.edge_weights[etype]) if
               (self.is_hetero and isinstance(self.edge_weights, dict))
               else (np.asarray(self.edge_weights)
                     if self.edge_weights is not None and not self.is_hetero
                     else None))
    for p in range(self.num_parts):
      m = edge_pb == p
      payload = dict(rows=rows[m], cols=cols[m], eids=eids[m])
      if weights is not None:
        payload['weights'] = weights[m]
      self._save_npz(payload, f'part{p}', 'graph', etype)
    return edge_pb

  def _partition_and_save_node_feat(self, node_pb, ntype):
    feat = (self.node_feat.get(ntype) if isinstance(self.node_feat, dict)
            else (self.node_feat if ntype is None else None))
    if feat is None:
      return
    feat = np.asarray(feat)
    for p in range(self.num_parts):
      ids = np.nonzero(node_pb == p)[0].astype(np.int64)
      payload = dict(feats=feat[ids], ids=ids)
      cache_ids = self._cache_node(ntype, p)
      if cache_ids is not None and cache_ids.size:
        payload['cache_feats'] = feat[cache_ids]
        payload['cache_ids'] = cache_ids.astype(np.int64)
      self._save_npz(payload, f'part{p}', 'node_feat', ntype)

  def _partition_and_save_edge_feat(self, edge_pb, etype):
    feat = (self.edge_feat.get(etype) if isinstance(self.edge_feat, dict)
            else (self.edge_feat if etype is None else None))
    if feat is None:
      return
    feat = np.asarray(feat)
    for p in range(self.num_parts):
      ids = np.nonzero(edge_pb == p)[0].astype(np.int64)
      self._save_npz(dict(feats=feat[ids], ids=ids), f'part{p}',
                     'edge_feat', etype)

  # -------------------------------------------------------------- persist

  def _save_npz(self, payload, part_dir, name, type_=None):
    d = os.path.join(self.output_dir, part_dir)
    if type_ is not None:
      d = os.path.join(d, name)
      os.makedirs(d, exist_ok=True)
      path = os.path.join(d, f'{_type_str(type_)}.npz')
    else:
      os.makedirs(d, exist_ok=True)
      path = os.path.join(d, f'{name}.npz')
    np.savez(path, **payload)

  def _save_node_pb(self, pb, ntype):
    if ntype is None:
      np.save(os.path.join(self.output_dir, 'node_pb.npy'), pb)
    else:
      d = os.path.join(self.output_dir, 'node_pb')
      os.makedirs(d, exist_ok=True)
      np.save(os.path.join(d, f'{ntype}.npy'), pb)

  def _save_edge_pb(self, pb, etype):
    if etype is None:
      np.save(os.path.join(self.output_dir, 'edge_pb.npy'), pb)
    else:
      d = os.path.join(self.output_dir, 'edge_pb')
      os.makedirs(d, exist_ok=True)
      np.save(os.path.join(d, f'{as_str(etype)}.npy'), pb)


def _type_str(t):
  return as_str(t) if isinstance(t, (tuple, list)) else str(t)


# ---------------------------------------------------------------- loading

def _load_npz(path) -> Optional[Dict[str, np.ndarray]]:
  if not os.path.exists(path):
    return None
  with np.load(path) as z:
    return {k: z[k] for k in z.files}


def load_partition(root_dir: str, partition_idx: int):
  """Load one partition (reference: base.py:555-656).

  Returns (num_parts, graph_data, node_feat_data, edge_feat_data,
  node_pb, edge_pb); each is a dict for hetero layouts.
  """
  with open(os.path.join(root_dir, 'META.json')) as f:
    meta = json.load(f)
  part = os.path.join(root_dir, f'part{partition_idx}')

  def graph_from(z):
    return GraphPartitionData(
        edge_index=np.stack([z['rows'], z['cols']]), eids=z['eids'],
        weights=z.get('weights'))

  def feat_from(z):
    if z is None:
      return None
    return FeaturePartitionData(
        feats=z.get('feats'), ids=z.get('ids'),
        cache_feats=z.get('cache_feats'), cache_ids=z.get('cache_ids'))

  if meta.get('hetero'):
    graph, nfeat, efeat, node_pb, edge_pb = {}, {}, {}, {}, {}
    for et_l in meta['edge_types']:
      et = tuple(et_l)
      z = _load_npz(os.path.join(part, 'graph', f'{as_str(et)}.npz'))
      if z is not None:
        graph[et] = graph_from(z)
      f_ = feat_from(_load_npz(os.path.join(part, 'edge_feat',
                                            f'{as_str(et)}.npz')))
      if f_ is not None:
        efeat[et] = f_
      p = os.path.join(root_dir, 'edge_pb', f'{as_str(et)}.npy')
      if os.path.exists(p):
        edge_pb[et] = np.load(p)
    for nt in meta['node_types']:
      f_ = feat_from(_load_npz(os.path.join(part, 'node_feat',
                                            f'{nt}.npz')))
      if f_ is not None:
        nfeat[nt] = f_
      p = os.path.join(root_dir, 'node_pb', f'{nt}.npy')
      if os.path.exists(p):
        node_pb[nt] = np.load(p)
    return (meta['num_parts'], graph, nfeat or None, efeat or None,
            node_pb, edge_pb)

  graph = graph_from(_load_npz(os.path.join(part, 'graph.npz')))
  nfeat = feat_from(_load_npz(os.path.join(part, 'node_feat.npz')))
  efeat = feat_from(_load_npz(os.path.join(part, 'edge_feat.npz')))
  node_pb = np.load(os.path.join(root_dir, 'node_pb.npy'))
  edge_pb = np.load(os.path.join(root_dir, 'edge_pb.npy'))
  return meta['num_parts'], graph, nfeat, efeat, node_pb, edge_pb


def cat_feature_cache(part_idx: int, feat_data: FeaturePartitionData,
                      feat_pb: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """Merge the hot cache into the local feature block
  (reference: base.py:659-700).

  Cached rows are prepended (hot-first, matching the HBM-prefix layout of
  the Feature store) and the feature partition book is rewritten so cached
  ids resolve locally. Returns (feats, ids, new_feat_pb).
  """
  if feat_data.cache_feats is None or feat_data.cache_feats.size == 0:
    return feat_data.feats, feat_data.ids, feat_pb
  cache_ids = feat_data.cache_ids
  # local rows that duplicate cached rows are dropped
  local_mask = ~np.isin(feat_data.ids, cache_ids)
  feats = np.concatenate([feat_data.cache_feats,
                          feat_data.feats[local_mask]])
  ids = np.concatenate([cache_ids, feat_data.ids[local_mask]])
  new_pb = feat_pb.copy()
  new_pb[cache_ids] = part_idx
  return feats, ids, new_pb
