from .base import (PartitionerBase, cat_feature_cache, load_partition)
from .frequency_partitioner import FrequencyPartitioner
from .random_partitioner import RandomPartitioner
