"""Core type aliases for graphlearn_tpu.

TPU-native re-design of the reference's typing module
(/root/reference/graphlearn_torch/python/typing.py). Node/edge typing and
partition-book semantics are kept API-compatible; tensors are numpy (host) or
jax.Array (device) instead of torch.Tensor.
"""
from typing import Dict, List, NamedTuple, Optional, Tuple, Union

import numpy as np

# A node type in a heterogeneous graph, e.g. 'paper'.
NodeType = str

# An edge type triplet (src_node_type, relation, dst_node_type).
EdgeType = Tuple[str, str, str]

# Prefix marking the reverse direction of an edge type
# (reference: typing.py:39-46).
REVERSE_PREFIX = 'rev_'

# String join token for edge types (reference: typing.py:32).
EDGE_TYPE_STR_SPLIT = '__'


def as_str(type_: Union[NodeType, EdgeType]) -> str:
  """Canonical string form of a node or edge type."""
  if isinstance(type_, NodeType):
    return type_
  if isinstance(type_, (list, tuple)) and len(type_) == 3:
    return EDGE_TYPE_STR_SPLIT.join(type_)
  return ''


def to_edge_type(type_str: str) -> EdgeType:
  parts = type_str.split(EDGE_TYPE_STR_SPLIT)
  if len(parts) != 3:
    raise ValueError(f'invalid edge type string: {type_str!r}')
  return tuple(parts)


def split_edge_type_seeds(edge_label_index):
  """The framework-wide typed seed-edge convention:
  ``((src, rel, dst), [2, E])`` -> ``(etype, edges)``; anything else ->
  ``(None, edges)``. ONE implementation for every link front-end
  (local / mp / remote loaders). The all-strings check keeps a
  homogeneous ``(rows, cols)`` pair with exactly 3 edges from being
  misread as a typed tuple."""
  if isinstance(edge_label_index, tuple) and \
      len(edge_label_index) == 2 and \
      isinstance(edge_label_index[0], (tuple, list)) and \
      len(edge_label_index[0]) == 3 and \
      all(isinstance(s, str) for s in edge_label_index[0]):
    return tuple(edge_label_index[0]), edge_label_index[1]
  return None, edge_label_index


def reverse_edge_type(etype: EdgeType) -> EdgeType:
  """Reverse of an edge type: flips endpoints and toggles the 'rev_' prefix."""
  src, rel, dst = etype
  if src != dst:
    if rel.startswith(REVERSE_PREFIX):
      rel = rel[len(REVERSE_PREFIX):]
    else:
      rel = REVERSE_PREFIX + rel
  return (dst, rel, src)


# A partition book maps a global node/edge id to the partition index that owns
# it (reference: typing.py:78-82). Host-side it is a numpy int array; on device
# it may be a jax.Array.
PartitionBook = np.ndarray
HeteroNodePartitionDict = Dict[NodeType, PartitionBook]
HeteroEdgePartitionDict = Dict[EdgeType, PartitionBook]


class GraphPartitionData(NamedTuple):
  """Edge-index data of a single graph partition (reference: typing.py:53-58)."""
  edge_index: np.ndarray          # [2, E_local] (row, col) in global ids
  eids: np.ndarray                # [E_local] global edge ids
  weights: Optional[np.ndarray] = None  # [E_local] edge weights


class FeaturePartitionData(NamedTuple):
  """Feature data of a single partition (reference: typing.py:60-68)."""
  feats: Optional[np.ndarray]        # [n_local, F]
  ids: Optional[np.ndarray]          # [n_local] global ids
  cache_feats: Optional[np.ndarray]  # [n_cache, F] hot-cache rows
  cache_ids: Optional[np.ndarray]    # [n_cache] global ids of cached rows


HeteroGraphPartitionDict = Dict[EdgeType, GraphPartitionData]
HeteroFeaturePartitionDict = Dict[Union[NodeType, EdgeType], FeaturePartitionData]

# Seeds / fanout aliases (reference: typing.py:84-91).
InputNodes = Union[np.ndarray, Tuple[NodeType, np.ndarray]]
InputEdges = Union[np.ndarray, Tuple[EdgeType, np.ndarray]]
NumNeighbors = Union[List[int], Dict[EdgeType, List[int]]]
