"""Low-latency online serving endpoint: admission batching into
calibrated static-shape buckets.

"Millions of users" means many small concurrent lookups, while the
accelerator wants few large fixed-shape dispatches — the classic
serving impedance mismatch. ``ServingEngine`` resolves it the TPU way
(GNNSampler, arxiv 2108.11571: hardware-matched static shapes):

  * **Admission batching.** Concurrent requests enqueue; the dispatcher
    drains them into one flat id vector, waiting at most
    ``max_wait_ms`` past the first request for the batch to fill — the
    standard latency/throughput knob.
  * **Calibrated padded buckets.** The batch pads to the SMALLEST
    capacity from a closed ``buckets`` set, so one persistent jitted
    program per bucket serves all traffic — no per-request compiles,
    ever. Oversized batches split across the largest bucket.
  * **Hot-embedding cache.** The store side (serving/store.py) answers
    from the materialized table — single-replica HBM, or the
    DistFeature-backed sharded store whose replicated hot split is the
    hot-embedding cache (docs/feature_cache.md machinery, reused).
  * **Staleness + final-layer refresh.** ``mark_stale(ids)`` flags
    nodes whose inputs changed; before a stale node is served, the
    engine recomputes ONLY its last layer from the penultimate store
    (``EmbeddingMaterializer.refresh_rows`` — the same training forward
    slice) and writes the rows back. Everything else keeps serving from
    the table.

Instrumented end to end through the PR 6 registry: per-request
``serving.queue_wait_ms`` / ``serving.total_ms`` histograms (the
p50/p99 the bench gate tracks), per-batch ``serving.batch_fill`` /
``serving.compute_ms``, and ``serving.requests`` / ``serving.batches``
/ ``serving.refreshed`` counters. The remote entry point
(``DistServer.serve``) is read-only and idempotent, so clients retry it
under the fault registry exactly like ``get_metrics`` —
chaos-hardening comes from the PR 2 machinery, not new code.
"""
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

import numpy as np

from .. import metrics
from ..metrics import spans

DEFAULT_BUCKETS = (16, 64, 256)


class _Request:
  __slots__ = ('ids', 'future', 't0', 'span')

  def __init__(self, ids: np.ndarray):
    self.ids = ids
    self.future: Future = Future()
    self.t0 = time.perf_counter()
    # the request span opens on the SUBMITTING thread (so it inherits
    # the caller's trace — e.g. the serve-RPC handler's adopted client
    # context) but is closed by the dispatcher at respond time:
    # attach=False keeps it off the submitter's context stack
    self.span = spans.begin('serving.request', attach=False,
                            n=int(ids.size))


class ServingEngine:
  """Admission-batched embedding lookup endpoint over an embedding
  store.

  Args:
    store: ``EmbeddingStore`` or ``DistEmbeddingStore``.
    buckets: ascending padded capacities (each a multiple of the
      store's ``granularity``); the closed static-shape set.
    max_wait_ms: admission window past the first queued request.
    refresh_fn: ``ids -> [n, F] rows`` final-layer recompute
      (``EmbeddingMaterializer.refresh_rows``) for stale nodes;
      requires a store with ``update_rows`` (the single-replica store).
    config: a tune artifact (``graphlearn_tpu.tune()``,
      docs/tuning.md): supplies the calibrated bucket ladder when
      ``buckets`` is not given explicitly, and refuses a store whose
      node count drifted from the tuned dataset's.
  """

  def __init__(self, store, buckets: Optional[Sequence[int]] = None,
               max_wait_ms: float = 2.0,
               refresh_fn: Optional[Callable] = None, config=None):
    if config is not None:
      tuned_n = (config.dataset or {}).get('num_nodes')
      store_n = getattr(store, 'num_nodes', None)
      if tuned_n is not None and store_n is not None and \
          int(tuned_n) != int(store_n):
        raise ValueError(
            f'ServingEngine config= artifact was tuned for '
            f'{tuned_n} nodes but the store serves {store_n} — '
            'dataset drifted; re-run graphlearn_tpu.tune() '
            f'(artifact fingerprint {config.fingerprint}, '
            'docs/tuning.md)')
      if buckets is None:
        buckets = config.serving_kwargs()['buckets']
      if hasattr(config, 'apply_kernel_routing'):
        # the tuned gather-kernel choice reaches the engine's store
        # (EmbeddingStore.set_kernel_routing); stores without the
        # surface (dist/tiered) simply don't accept it
        config.apply_kernel_routing(store)
    if buckets is None:
      buckets = DEFAULT_BUCKETS
    buckets = tuple(sorted(int(b) for b in set(buckets)))
    if not buckets:
      raise ValueError('at least one bucket capacity is required')
    g = getattr(store, 'granularity', 1)
    for b in buckets:
      if b <= 0 or b % g:
        raise ValueError(
            f'bucket capacity {b} must be a positive multiple of the '
            f'store granularity {g}')
    if refresh_fn is not None and \
        getattr(store, 'update_rows', None) is None:
      raise ValueError('refresh_fn needs a store with update_rows '
                       '(single-replica EmbeddingStore)')
    if refresh_fn is not None:
      try:
        store.update_rows(np.zeros((0,), np.int64),
                          np.zeros((0, store.feature_dim), np.float32))
      except NotImplementedError:
        raise ValueError(
            'refresh_fn is unsupported on immutable stores — refresh '
            'on the materializing replica and rebuild (docs/serving.md)')
    self.store = store
    self.buckets = buckets
    self.max_wait_s = float(max_wait_ms) / 1e3
    self._refresh_fn = refresh_fn
    self._q: 'queue.Queue[_Request]' = queue.Queue()
    # stale-id set shared between caller threads (mark_stale) and the
    # serving thread (_refresh_stale) — every access holds _stale_lock
    # graftlint: shared[_stale_lock]
    self._stale: set = set()
    self._stale_lock = threading.Lock()
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None

  # ------------------------------------------------------------ lifecycle

  def start(self):
    if self._thread is not None and self._thread.is_alive():
      return self
    self._stop.clear()
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-serving-dispatcher')
    self._thread.start()
    return self

  def stop(self):
    """Drain-free stop: pending requests get a RuntimeError (callers
    hold Futures, nothing blocks forever)."""
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=10)
      self._thread = None
    while True:
      try:
        r = self._q.get_nowait()
      except queue.Empty:
        break
      if not r.future.done():
        r.future.set_exception(RuntimeError('serving engine stopped'))
        spans.end(r.span, error='stopped')

  def __enter__(self):
    return self.start()

  def __exit__(self, *exc):
    self.stop()

  # -------------------------------------------------------------- intake

  def submit(self, ids) -> Future:
    """Enqueue one lookup request (any length). Returns a Future whose
    result is the [len(ids), F] numpy row block, in request order."""
    if self._thread is None or not self._thread.is_alive():
      raise RuntimeError('serving engine is not started')
    ids = np.asarray(ids, np.int64).reshape(-1)
    if ids.size == 0:
      f = Future()
      f.set_result(np.zeros((0, self.store.feature_dim), np.float32))
      return f
    if ids.min() < 0 or ids.max() >= self.store.num_nodes:
      raise ValueError(
          f'ids must be in [0, {self.store.num_nodes}); padding is an '
          'engine-internal concept and never crosses the API')
    req = _Request(ids)
    self._q.put(req)
    if self._stop.is_set() and not req.future.done():
      # stop() may have drained the queue between the alive check and
      # our put — fail fast instead of leaving the Future to hang
      req.future.set_exception(RuntimeError('serving engine stopped'))
      spans.end(req.span, error='stopped')
    return req.future

  def lookup(self, ids, timeout: Optional[float] = 30.0) -> np.ndarray:
    """Synchronous convenience: submit + wait."""
    return self.submit(ids).result(timeout)

  def mark_stale(self, ids):
    """Flag nodes whose features/neighborhood changed: their next
    lookup pays one final-layer refresh, then serves fresh rows.
    Requires a ``refresh_fn`` — without one a mark could never be
    honored, so accepting it would silently serve stale rows forever
    (rematerialize + rebuild instead, docs/serving.md)."""
    if self._refresh_fn is None:
      raise ValueError(
          'mark_stale needs a refresh_fn (ServingEngine(..., '
          'refresh_fn=materializer.refresh_rows)); without one stale '
          'marks would be accepted but never honored — rematerialize '
          'and rebuild the store instead (docs/serving.md)')
    ids = np.asarray(ids, np.int64).reshape(-1)
    with self._stale_lock:
      self._stale.update(int(i) for i in ids)

  def stale_count(self) -> int:
    with self._stale_lock:
      return len(self._stale)

  # ----------------------------------------------------------- dispatcher

  def _bucket_for(self, n: int) -> int:
    for b in self.buckets:
      if n <= b:
        return b
    return self.buckets[-1]

  def _loop(self):
    while not self._stop.is_set():
      try:
        first = self._q.get(timeout=0.05)
      except queue.Empty:
        continue
      batch = [first]
      fill = first.ids.size
      deadline = first.t0 + self.max_wait_s
      while fill < self.buckets[-1]:
        remaining = deadline - time.perf_counter()
        if remaining <= 0:
          # window closed: still DRAIN whatever already queued (free —
          # zero extra wait). Under sustained load the first popped
          # request is often older than the window (it queued while
          # the previous batch computed); without this drain every
          # batch would degenerate to size 1 exactly when batching
          # matters most.
          try:
            r = self._q.get_nowait()
          except queue.Empty:
            break
        else:
          try:
            r = self._q.get(timeout=remaining)
          except queue.Empty:
            break
        batch.append(r)
        fill += r.ids.size
      try:
        self._serve_batch(batch)
      except BaseException as e:  # noqa: BLE001 — futures carry it
        for r in batch:
          if not r.future.done():
            r.future.set_exception(e)
          spans.end(r.span, error=f'{type(e).__name__}: {e}')

  def _refresh_stale(self, flat: np.ndarray):
    if self._refresh_fn is None:
      return
    with self._stale_lock:
      if not self._stale:
        return
      stale_now = sorted(set(int(i) for i in flat) & self._stale)
      if not stale_now:
        return
      # claimed under the lock: concurrent batches each refresh a node
      # at most once
      self._stale.difference_update(stale_now)
    try:
      ids = np.asarray(stale_now, np.int64)
      rows = self._refresh_fn(ids)
      self.store.update_rows(ids, rows)
    except BaseException:
      # a failed refresh must NOT un-mark the nodes: the caller's retry
      # would otherwise be served the old stale rows as if fresh
      with self._stale_lock:
        self._stale.update(stale_now)
      raise
    metrics.inc('serving.refreshed', len(stale_now))

  def _serve_batch(self, batch):
    # drop requests already failed elsewhere (a submit that lost the
    # stop() race leaves its request enqueued; replaying it after a
    # restart would dispatch compute and over-count serving.requests
    # for a request nobody is waiting on)
    batch = [r for r in batch if not r.future.done()]
    if not batch:
      return
    t_batch = time.perf_counter()
    t_batch_unix = time.time()
    for r in batch:
      wait = t_batch - r.t0
      metrics.observe('serving.queue_wait_ms', wait * 1e3)
      # retroactive queue span: measured as plain timestamps at pickup
      spans.emit('serving.queue', trace=r.span.trace,
                 parent=r.span.span_id, t0_unix=t_batch_unix - wait,
                 dur_ms=wait * 1e3)
    # one batch span per admission batch. A batch is many-to-one with
    # requests, so it parents under the FIRST request's span (reachable
    # from that request's tree); the other requests link to it via the
    # batch attr stamped on their request spans at respond time.
    flat = np.concatenate([r.ids for r in batch])
    batch_span = spans.begin('serving.batch', attach=False,
                             trace=batch[0].span.trace,
                             parent=batch[0].span.span_id,
                             requests=len(batch))
    try:
      self._refresh_stale(flat)
      outs = []
      pos = 0
      while pos < flat.size:
        take = min(flat.size - pos, self.buckets[-1])
        cap = self._bucket_for(take)
        padded = np.full((cap,), -1, np.int32)
        padded[:take] = flat[pos:pos + take]
        mask = padded >= 0
        metrics.observe('serving.batch_fill', take / cap)
        rows = self.store.fetch(self.store.lookup(padded, mask))
        outs.append(rows[:take])
        metrics.inc('serving.batches')
        pos += take
      rows_all = outs[0] if len(outs) == 1 else np.concatenate(outs)
      compute_s = time.perf_counter() - t_batch
      metrics.observe('serving.compute_ms', compute_s * 1e3)
      spans.emit('serving.compute', trace=batch_span.trace,
                 parent=batch_span.span_id, t0_unix=t_batch_unix,
                 dur_ms=compute_s * 1e3, ids=int(flat.size))
    finally:
      # a raising refresh/fetch must not strand the batch span open —
      # it would simply never be emitted (attach=False), hiding the
      # failed batch from the trace it belongs to
      spans.end(batch_span, fill=int(flat.size))
    o = 0
    for r in batch:
      res = rows_all[o:o + r.ids.size]
      o += r.ids.size
      # metrics BEFORE set_result: a caller reading counters right
      # after .result() returns must see its own request counted
      metrics.inc('serving.requests')
      metrics.observe('serving.total_ms',
                      (time.perf_counter() - r.t0) * 1e3)
      t_resp = time.perf_counter()
      if not r.future.done():   # lost a stop() race: already failed
        r.future.set_result(res)
      spans.emit('serving.respond', trace=r.span.trace,
                 parent=r.span.span_id,
                 dur_ms=(time.perf_counter() - t_resp) * 1e3)
      # close the request span: its duration IS the request's
      # enqueue->rows latency (span-derived p50/p99 agrees with the
      # serving.total_ms histogram — tested within one bucket ratio)
      spans.end(r.span, batch=batch_span.span_id)

  # ------------------------------------------------------------- remote

  def serve_numpy(self, ids) -> np.ndarray:
    """Synchronous host entry for the ``serve`` RPC
    (DistServer.serve): submit through the same admission queue so
    remote traffic batches with local traffic, block for the rows.
    Read-only w.r.t. the caller — idempotent by construction, retried
    safely under the fault registry."""
    return self.lookup(np.asarray(ids, np.int64))
