"""Embedding stores the online endpoint answers from.

Two backings behind one ``lookup(ids, mask) -> rows`` surface:

* :class:`EmbeddingStore` — a single-replica device-resident
  ``[N, F]`` table with one persistent jitted gather per padded bucket
  capacity, plus a donated scatter for the final-layer refresh
  write-back.
* :class:`DistEmbeddingStore` — the sharded variant: the materialized
  table is row-partitioned into a ``DistFeature`` over the serving
  mesh, REUSING the feature path's hot-vertex split/hotness machinery
  wholesale (distributed/dist_feature.py): the globally hottest
  embedding rows are replicated per shard (the hot-EMBEDDING cache —
  DCI's workload-aware cache, arxiv 2503.01281, in GLT terms), misses
  dedup into the bucketed miss-only exchange, and the ``[P, 4]``
  hit/miss stats ride on device until ``publish_stats``.

Both stores keep every lookup ONE program dispatch over a closed set of
static shapes (GNNSampler, arxiv 2108.11571): the engine pads request
batches to calibrated bucket capacities, so each capacity compiles once
and serves all traffic.
"""
from typing import Optional

import numpy as np

from ..utils.trace import record_dispatch


def pow2_cap(n: int, floor: int = 8) -> int:
  """The padded power-of-two bucket capacity for ``n`` items — ONE
  formula shared by the refresh compute buckets
  (EmbeddingMaterializer.refresh_rows) and the write-back scatter
  buckets (EmbeddingStore.update_rows), so the two closed program sets
  stay in lockstep."""
  return max(floor, 1 << int(n - 1).bit_length()) if n > 1 else floor


class EmbeddingStore:
  """Single-replica device-resident embedding table.

  ``embeddings``: [N_pad, F] rows. ``num_nodes``: the REAL node count —
  REQUIRED knowledge for materializer tables, whose rows past
  ``num_nodes`` are block padding: defaulting to the table height would
  let the engine's id validation serve pad rows as real nodes. Prefer
  ``EmbeddingMaterializer.embedding_store()``, which passes it for you.
  ``granularity`` is the bucket divisibility the engine must respect
  (1: any capacity compiles).

  The store TAKES OWNERSHIP of the table: :meth:`update_rows` donates
  the buffer (the table is replaced in place, HBM stays flat), so after
  the first refresh write-back the array handed in here is dead — read
  embeddings through the store, not through a kept reference.
  """

  granularity = 1

  #: tuned kernel routing (tune/artifact.py apply_kernel_routing):
  #: route the bucket gather through the run-segmented DMA kernel
  #: (ops.gather_rows_hbm2) at the tuned grid point — the same gate as
  #: UnifiedTensor: inert off-TPU or on non-128-lane-aligned widths
  use_pallas_v2 = False
  pallas_v2_block_rows = 256
  pallas_v2_run_span = 8

  def __init__(self, embeddings, num_nodes: Optional[int] = None):
    import jax
    self._emb = jax.device_put(np.asarray(embeddings)) \
        if isinstance(embeddings, np.ndarray) else embeddings
    self.num_nodes = int(num_nodes if num_nodes is not None
                         else self._emb.shape[0])
    # ONE jitted gather/scatter each: jax.jit's own cache already
    # specializes per capacity, so the program set stays exactly
    # one-executable-per-bucket without per-cap bookkeeping here
    self._gather = None
    self._scatter = None
    self._kernel_routed = False

  def set_kernel_routing(self, use_pallas_v2: bool = False,
                         block_rows: int = 256, run_span: int = 8):
    """Apply a tuned-artifact kernel choice to the lookup gather.
    Rebuilds the gather program on the next lookup; the bucket set and
    semantics are unchanged (the kernel is bit-identical to the XLA
    gather — ops/gather_pallas.py)."""
    self.use_pallas_v2 = bool(use_pallas_v2)
    self.pallas_v2_block_rows = int(block_rows)
    self.pallas_v2_run_span = int(run_span)
    self._gather = None

  @property
  def feature_dim(self) -> int:
    return int(self._emb.shape[1])

  def _gather_fn(self):
    if self._gather is None:
      import jax
      import jax.numpy as jnp
      self._kernel_routed = (
          self.use_pallas_v2 and jax.default_backend() == 'tpu' and
          self._emb.shape[1] % 128 == 0)
      if self._kernel_routed:
        from ..ops.gather_pallas import _gather_rows_hbm2_impl
        br, rs = self.pallas_v2_block_rows, self.pallas_v2_run_span

        def gather(emb, ids, mask):
          rows = _gather_rows_hbm2_impl(
              emb, jnp.maximum(ids, 0).astype(jnp.int32), br, rs,
              False, False)
          return jnp.where(mask[:, None], rows, 0)
      else:

        def gather(emb, ids, mask):
          rows = emb[jnp.maximum(ids, 0)]
          return jnp.where(mask[:, None], rows, 0)

      from ..metrics import programs
      self._gather = programs.instrument(jax.jit(gather),
                                         'serve_lookup')
    return self._gather

  def lookup(self, ids, mask):
    """[cap] padded ids (-1 pads, mask False) -> [cap, F] device rows.
    One dispatch; the capacity's program persists across requests."""
    import jax.numpy as jnp
    ids = jnp.asarray(ids)
    fn = self._gather_fn()
    if self._kernel_routed:
      from .. import metrics
      metrics.inc('ops.gather_runs')
    record_dispatch('serve_lookup')
    return fn(self._emb, ids, jnp.asarray(mask))

  def fetch(self, rows) -> np.ndarray:
    """Device rows -> host (the engine's single fetch per batch)."""
    return np.asarray(rows)

  def update_rows(self, ids, rows):
    """Refresh write-back: scatter ``rows`` into the table at ``ids``
    (donated update — the table is replaced, not copied). Padded to
    power-of-two capacities like the refresh compute, so the write-back
    program set stays CLOSED under varying stale counts (pad slots
    scatter out of bounds and are dropped)."""
    import jax
    import jax.numpy as jnp
    ids = np.asarray(ids, np.int64).reshape(-1)
    if ids.size == 0:
      return
    rows = np.asarray(rows)
    cap = pow2_cap(ids.size)
    n_pad = int(self._emb.shape[0])
    idx = np.full((cap,), n_pad, np.int64)     # OOB: dropped by 'drop'
    idx[:ids.size] = ids
    vals = np.zeros((cap, rows.shape[1]), rows.dtype)
    vals[:ids.size] = rows
    if self._scatter is None:

      def scatter(emb, idx, vals):
        return emb.at[idx].set(vals.astype(emb.dtype), mode='drop')

      from ..metrics import programs
      self._scatter = programs.instrument(
          jax.jit(scatter, donate_argnums=(0,)), 'serve_store_update')
    record_dispatch('serve_store_update')
    self._emb = self._scatter(self._emb, jnp.asarray(idx),
                              jnp.asarray(vals))


class TieredEmbeddingStore:
  """Beyond-HBM embedding store: the materialized table lives in a
  ``storage.TieredFeature`` (HBM hot prefix -> host RAM -> disk), so an
  O(N·F) embedding table larger than device memory still serves — hot
  rows at HBM gather speed, cold rows through the tiered mixed gather
  (pow2 cold blocks, promoted-row warming). The natural pairing is
  ``EmbeddingMaterializer(..., spill_dir=...)`` +
  ``materializer.tiered_embedding_store(...)``.

  Immutable, like DistEmbeddingStore: stale rows are refreshed by
  rematerializing and rotating the spill (docs/serving.md), not by
  in-place scatter — the hot tier is device-resident while warm/disk
  rows are host-resident, and a write-through across tiers would race
  the staging pipeline.
  """

  granularity = 1

  def __init__(self, tiered_feature, num_nodes: Optional[int] = None):
    self.tf = tiered_feature
    self.num_nodes = int(num_nodes if num_nodes is not None
                         else tiered_feature.size)
    self._mask_fn = None

  @property
  def feature_dim(self) -> int:
    return int(self.tf.shape[1])

  def lookup(self, ids, mask):
    """[cap] padded host ids (-1 pads) -> [cap, F] device rows. The
    tiered gather ships only the non-hot rows (UnifiedTensor mixed
    path); one extra jitted where() zeroes the pad slots like
    EmbeddingStore.lookup."""
    import jax
    import jax.numpy as jnp
    rows = self.tf[np.asarray(ids)]
    if self._mask_fn is None:
      from ..metrics import programs
      self._mask_fn = programs.instrument(
          jax.jit(lambda r, m: jnp.where(m[:, None], r, 0)),
          'serve_lookup')
    record_dispatch('serve_lookup')
    return self._mask_fn(rows, jnp.asarray(mask))

  def fetch(self, rows) -> np.ndarray:
    return np.asarray(rows)

  def update_rows(self, ids, rows):
    raise NotImplementedError(
        'TieredEmbeddingStore rows are immutable — rematerialize with '
        'EmbeddingMaterializer(..., spill_dir=...) and rotate the '
        'spill (docs/storage.md, docs/serving.md)')


class DistEmbeddingStore:
  """Sharded embedding store over a mesh: a ``DistFeature`` whose rows
  are the materialized embeddings — the hot-embedding cache IS the
  DistFeature replicated hot split (``split_ratio``/``cache_rows`` +
  ``hotness``), and every lookup is its one-dispatch cached miss-only
  exchange. Bucket capacities must be multiples of the partition count
  (``granularity``): the engine spreads each padded bucket
  ``[cap] -> [P, cap/P]`` so the lookup itself load-balances over the
  serving shards."""

  def __init__(self, dist_feature):
    self.df = dist_feature
    self.granularity = int(dist_feature.num_partitions)
    self.num_nodes = int(dist_feature.feature_pb.shape[0])

  @classmethod
  def build(cls, embeddings, mesh, *, split_ratio: float = 0.0,
            cache_rows: Optional[int] = None, hotness=None,
            wire_dtype=None, bucket_frac=2.0,
            num_nodes: Optional[int] = None):
    """Partition a materialized [N(_pad), F] table into a DistFeature
    over ``mesh`` (contiguous row blocks). PASS ``num_nodes`` for
    materializer tables — it trims the block-padding rows, which would
    otherwise count as servable node ids past the real graph (the same
    footgun ``EmbeddingMaterializer.embedding_store`` closes on the
    single-replica path; prefer its ``dist_embedding_store``).
    ``split_ratio``/``cache_rows``/``hotness`` select the replicated
    hot-embedding cache exactly as the training-time feature cache
    does (docs/feature_cache.md)."""
    from ..distributed.dist_feature import DistFeature
    emb = np.asarray(embeddings)
    if num_nodes is not None:
      emb = emb[:num_nodes]
    n = emb.shape[0]
    p = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    pb = np.minimum((np.arange(n, dtype=np.int64) * p) // max(n, 1),
                    p - 1).astype(np.int32)
    parts = []
    for i in range(p):
      ids = np.where(pb == i)[0].astype(np.int64)
      if ids.size == 0:   # more shards than rows: keep a dummy row
        ids = np.zeros((1,), np.int64)
      parts.append((ids, emb[ids]))
    df = DistFeature(p, parts, pb, mesh=mesh, split_ratio=split_ratio,
                     cache_rows=cache_rows, hotness=hotness,
                     wire_dtype=wire_dtype, bucket_frac=bucket_frac)
    return cls(df)

  @property
  def feature_dim(self) -> int:
    return int(self.df.feature_dim)

  def lookup(self, ids, mask):
    """[cap] padded ids -> [P, cap/P, F] sharded device rows (reshaped
    back to [cap, F] by :meth:`fetch`). DistFeature.get is the one
    dispatch and records it."""
    import jax.numpy as jnp
    ids = jnp.asarray(ids, jnp.int32)
    cap = int(ids.shape[0])
    p = self.granularity
    assert cap % p == 0, (
        f'bucket capacity {cap} must be a multiple of the partition '
        f'count {p} (engine bucket calibration)')
    return self.df.get(ids.reshape(p, cap // p),
                       jnp.asarray(mask).reshape(p, cap // p))

  def fetch(self, rows) -> np.ndarray:
    out = np.asarray(rows)
    return out.reshape(-1, out.shape[-1])

  def publish_stats(self):
    """Per-interval hot-embedding cache hit/miss surfacing — the same
    once-per-epoch fetch discipline as the training feature cache."""
    return self.df.publish_stats()

  def update_rows(self, ids, rows):
    raise NotImplementedError(
        'DistEmbeddingStore rows are immutable — stale nodes are '
        'refreshed on the materializing replica and the sharded store '
        'is rebuilt on rotation/failover (docs/serving.md)')
