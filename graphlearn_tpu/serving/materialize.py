"""Offline layer-wise full-graph embedding materialization.

The serving tier's offline half (ROADMAP item 1; DCI, arxiv 2503.01281,
is the workload-aware inference exemplar): compute EVERY node's layer-l
embedding layer by layer, so the online endpoint answers lookups from a
precomputed table instead of running a sampled multi-hop forward per
request. GNNSampler (arxiv 2108.11571) argues inference hot paths should
run on hardware-matched static shapes — here the whole pass is a closed
set of fixed-shape programs:

  * **No sampling.** Each node aggregates over its FULL neighbor list,
    padded to a static width ``W`` (the max stored degree, or an
    explicit ``neighbor_cap`` for approximate serving) — the
    ``padded_neighbors`` table, built once per graph.
  * **Contiguous row blocks.** A layer pass walks the node table in
    ``block_size`` blocks; each block's forward consumes
    ``[B + B*W, F]`` rows sliced/gathered from the PREVIOUS layer's
    store and writes ``[B, F_out]`` rows into the next store — the
    ScanTrainer chunk pattern verbatim: a ``lax.scan`` over K blocks
    per dispatch, chunk position entering as a device scalar so every
    full chunk reuses one executable.
  * **Donated buffers, O(N·F) memory.** The output store rides the
    scan carry and is donated across chunk dispatches; layer l's output
    BECOMES layer l+1's feature store, so peak HBM is two stores (the
    one being read and the one being written), never O(N·F·L).
  * **Dispatch budget**: one store-init program + ceil(blocks/K) chunk
    programs per layer — within the ``ceil(chunks) + 2``-per-layer
    budget tests assert under ``GLT_STRICT`` (utils/strict.py), where
    the whole pass runs under ``jax.transfer_guard('disallow')``.

The per-layer forward is NOT a re-implementation: it calls
``models.train.make_layer_slice_fn`` — a slice of the exact forward
definition training optimizes (``make_forward_fn``), so trained and
served models cannot drift. Heterogeneous graphs (RGNN) materialize
per-type stores with per-edge-type padded adjacencies; the per-type
embed projection and the final ``lin_out`` head run as their own
row-local passes.

Each layer pass appends one flight record (``metrics.flight``) when
``GLT_RUN_LOG`` is set — materialization epochs diff like training
epochs.
"""
from typing import Any, Dict, Optional

import numpy as np

from ..models import train as train_lib
from ..typing import reverse_edge_type
from ..utils.strict import strict_guards
from ..utils.trace import record_dispatch


def padded_neighbors(topo, neighbor_cap: Optional[int] = None):
  """[N, W] int32 padded full-neighbor table from a stored Topology.

  Row ``v`` holds v's stored neighbor list (the same grouping the
  samplers draw from: out-edges for ``edge_dir='out'``, in-edges for
  ``'in'``), padded with -1 to ``W = max degree`` (or ``neighbor_cap``,
  which TRUNCATES heavier nodes — approximate serving for degree-skewed
  graphs; exact parity requires the full width). Built once per graph
  on the host; the device copy is the materializer's only O(N·W) input.
  """
  indptr = np.asarray(topo.indptr, np.int64)
  indices = np.asarray(topo.indices, np.int64)
  n = indptr.shape[0] - 1
  deg = np.diff(indptr)
  w = int(deg.max()) if neighbor_cap is None else int(neighbor_cap)
  w = max(w, 1)
  nbr = np.full((n, w), -1, np.int32)
  if indices.size:
    key = np.repeat(np.arange(n), deg)
    off = np.arange(indices.shape[0]) - np.repeat(indptr[:-1], deg)
    keep = off < w
    nbr[key[keep], off[keep]] = indices[keep]
  return nbr


def _block_edges(b: int, w: int) -> np.ndarray:
  """The constant [2, b*w] block-graph COO: each of the block's ``b``
  target slots (node rows [0, b)) receives from its ``w`` neighbor
  slots (node rows [b, b + b*w), in row-major order) — the layout every
  chunk shares, uploaded once."""
  row = b + np.arange(b * w, dtype=np.int32)
  col = np.repeat(np.arange(b, dtype=np.int32), w)
  return np.stack([row, col])


class EmbeddingMaterializer:
  """Layer-wise full-graph embedding program over a Dataset + trained
  params.

  Args:
    dataset: the (homogeneous or heterogeneous) ``data.Dataset`` whose
      graph/features to materialize over.
    model: the TRAINED model (GraphSAGE/GAT homo, RGNN hetero) — built
      WITHOUT hop offsets / dense flags (layer slices run the plain
      segment forward; the layered forms are sampled-batch layout
      optimizations). GCN is rejected: its symmetric degree norm is a
      function of the edge_index the conv sees, which block subgraphs
      cannot reproduce.
    params: the trained flax params.
    block_size: B, rows per block (the static self-row width of the
      block forward).
    chunk_size: K, blocks per scanned dispatch.
    neighbor_cap: optional per-node neighbor truncation (approximate
      serving; None = exact full-neighbor width).

  ``materialize()`` returns the final-layer output table; per-type /
  penultimate stores stay on the instance for the online refresh path
  (:meth:`refresh_rows`).
  """

  _NAME = 'EmbeddingMaterializer'

  def __init__(self, dataset, model, params, *, block_size: int = 128,
               chunk_size: int = 8, neighbor_cap: Optional[int] = None,
               spill_dir: Optional[str] = None):
    if block_size < 1 or chunk_size < 1:
      raise ValueError('block_size and chunk_size must be >= 1')
    self.model = model
    self.params = params
    self.block_size = int(block_size)
    self.chunk_size = int(chunk_size)
    self.neighbor_cap = neighbor_cap
    # spill-to-tier (storage/, docs/storage.md): every completed layer
    # pass also lands on disk as a memory-mapped tier, so O(N·F)
    # stores beyond HBM still materialize (the superseded device store
    # is donated away as before; the disk copy is the durable one) and
    # the finished table can serve through a TieredEmbeddingStore
    self.spill_dir = spill_dir
    self.spilled = {}     # pass label -> storage.DiskTier
    self.is_hetero = bool(dataset.is_hetero)
    self.num_layers = int(model.num_layers)
    self._chunk_fns: Dict[Any, Any] = {}
    self._init_fns: Dict[Any, Any] = {}
    self._refresh_fns: Dict[int, Any] = {}
    self._embeddings = None
    self._penultimate = None
    if self.is_hetero:
      self._init_hetero(dataset)
    else:
      self._init_homo(dataset)

  # ------------------------------------------------------------- setup

  def _pad_rows(self, arr: np.ndarray) -> np.ndarray:
    """Pad a [N, ...] host table up to the block multiple (pad rows are
    never read back: neighbor ids always reference real rows < N)."""
    n = arr.shape[0]
    n_pad = -(-n // self.block_size) * self.block_size
    if n_pad == n:
      return arr
    out = np.zeros((n_pad,) + arr.shape[1:], arr.dtype)
    out[:n] = arr
    return out

  def _feat_rows(self, feature) -> np.ndarray:
    """Id-ordered [N, F] float rows from a Feature store (cpu_get
    resolves any hotness reorder, so row i is node i)."""
    return np.asarray(
        feature.cpu_get(np.arange(feature.size, dtype=np.int64)),
        np.float32)

  def _init_homo(self, dataset):
    from ..models.models import GCN
    if isinstance(self.model, GCN):
      # GCNConv derives its symmetric degree norm FROM the edge_index it
      # is given; in a block subgraph every neighbor slot has local
      # out-degree 1, so the norm would silently diverge from the
      # full-graph forward (1/sqrt(2) vs 1/sqrt(deg_out+1)) — no
      # local-block program can reproduce it without global degree
      # tables the conv does not accept
      raise ValueError(
          'GCN materialization is unsupported: GCNConv normalizes by '
          'degrees of the edge_index it sees, which a block subgraph '
          'cannot reproduce — serve GraphSAGE/GAT (homo) or RGNN '
          '(hetero) models')
    if dataset.node_features is None:
      raise ValueError('materialization needs node features')
    topo = dataset.graph.topo
    self.num_nodes = int(topo.num_nodes)
    nbr = padded_neighbors(topo, self.neighbor_cap)
    self._w = nbr.shape[1]
    self._nbr_np = self._pad_rows(nbr)
    # pad rows keep -1 everywhere already (np.zeros would alias node 0)
    self._nbr_np[self.num_nodes:] = -1
    self._x0_np = self._pad_rows(self._feat_rows(dataset.node_features))
    self._ei_np = _block_edges(self.block_size, self._w)
    self._dev = None   # uploaded lazily in materialize()

  def _init_hetero(self, dataset):
    from ..models.models import RGNN
    if not isinstance(self.model, RGNN):
      raise ValueError('hetero materialization covers RGNN models')
    feats = dataset.node_features
    if not isinstance(feats, dict) or not feats:
      raise ValueError('hetero materialization needs per-type features')
    self.edge_dir = dataset.edge_dir
    self._etypes = list(dataset.graph.keys())
    # stored etype (u, r, v): edge_dir='out' groups by src u (key/target
    # type of the aggregation) expanding to v neighbors, and batches key
    # the message-flow edges by reverse_edge_type — exactly the
    # sampler's convention (sampler/neighbor_sampler.py
    # _hetero_sample_from_nodes docstring)
    self._key_t = {et: (et[0] if self.edge_dir == 'out' else et[2])
                   for et in self._etypes}
    self._res_t = {et: (et[2] if self.edge_dir == 'out' else et[0])
                   for et in self._etypes}
    self._out_et = {et: (reverse_edge_type(et) if self.edge_dir == 'out'
                         else et)
                    for et in self._etypes}
    self.num_nodes = {t: int(f.size) for t, f in feats.items()}
    self._x0_np = {t: self._pad_rows(self._feat_rows(f))
                   for t, f in feats.items()}
    self._nbr_np, self._w = {}, {}
    for et in self._etypes:
      nbr = padded_neighbors(dataset.graph[et].topo, self.neighbor_cap)
      kt = self._key_t[et]
      n_t = self.num_nodes.get(kt)
      if n_t is None:
        raise ValueError(f'etype {et}: key type {kt!r} has no features')
      if nbr.shape[0] < n_t:   # isolated tail nodes the topo never saw
        nbr = np.concatenate(
            [nbr, np.full((n_t - nbr.shape[0], nbr.shape[1]), -1,
                          np.int32)])
      nbr = self._pad_rows(nbr[:n_t])
      nbr[n_t:] = -1
      self._nbr_np[et] = nbr
      self._w[et] = nbr.shape[1]
    # types that ever receive messages; others keep their embed output
    # but never advance (mirrors HeteroConv dropping non-target types)
    self._targets = {self._key_t[et] for et in self._etypes}
    self._dev = None

  # ---------------------------------------------------------- programs

  def _upload(self):
    """One-time explicit device upload of the static tables — everything
    the chunk programs consume enters as an all-device argument, so the
    strict_guards region (transfer_guard('disallow')) stays clean."""
    import jax
    if self._dev is not None:
      return self._dev
    if self.is_hetero:
      self._dev = dict(
          nbr={et: jax.device_put(v) for et, v in self._nbr_np.items()},
          x0={t: jax.device_put(v) for t, v in self._x0_np.items()})
    else:
      self._dev = dict(nbr=jax.device_put(self._nbr_np),
                       x0=jax.device_put(self._x0_np),
                       ei=jax.device_put(self._ei_np))
    return self._dev

  def _homo_slice(self, layer: int):
    return train_lib.make_layer_slice_fn(self.model, layer, layer + 1)

  def _init_fn(self, key, shape, dtype):
    """Jitted zero-store builder (ONE dispatch per layer pass)."""
    if key not in self._init_fns:
      import jax
      import jax.numpy as jnp
      from ..metrics import programs
      self._init_fns[key] = programs.instrument(
          jax.jit(lambda: jnp.zeros(shape, dtype)), 'embed_store_init')
    return self._init_fns[key]

  def _out_spec(self, slice_fn, in_specs):
    """(rows-dtype, feature-dim) of a layer slice via eval_shape — no
    model-specific width arithmetic to drift."""
    import jax
    out = jax.eval_shape(slice_fn, self.params, in_specs)
    return out

  def _homo_chunk_fn(self, layer: int, k: int):
    """The scanned K-block program of homo layer ``layer``: slice self
    rows + gather full neighbor rows from the previous store, run the
    layer slice of the training forward, write the block into the
    donated output store."""
    key = ('homo', layer, k)
    if key in self._chunk_fns:
      return self._chunk_fns[key]
    import jax
    import jax.numpy as jnp
    from jax import lax
    b, w = self.block_size, self._w
    slice_fn = self._homo_slice(layer)

    def chunk(params, prev, out, nbr, ei, start):
      def body(out, g):
        base = g * b
        self_rows = lax.dynamic_slice_in_dim(prev, base, b)
        nbr_blk = lax.dynamic_slice_in_dim(nbr, base, b)
        em = (nbr_blk >= 0).reshape(-1)
        nbr_rows = prev[jnp.maximum(nbr_blk.reshape(-1), 0)]
        batch = dict(x=jnp.concatenate([self_rows, nbr_rows]),
                     edge_index=ei, edge_mask=em)
        h = slice_fn(params, batch)
        return lax.dynamic_update_slice(out, h[:b].astype(out.dtype),
                                        (base, 0)), None
      out, _ = lax.scan(body, out, start + lax.iota(jnp.int32, k))
      return out

    from ..metrics import programs
    fn = programs.instrument(jax.jit(chunk, donate_argnums=(2,)),
                             'embed_chunk')
    self._chunk_fns[key] = fn
    return fn

  def _run_layer_pass(self, pass_key, n_pad, out_shape, out_dtype,
                      dispatch_chunk, layer_label):
    """Shared pass driver: store init + scanned chunks under
    strict_guards, flight-recorded like a training epoch. The dispatch
    budget is 1 + ceil(blocks/K) — within the asserted
    ceil(chunks) + 2 per layer."""
    import jax
    from ..metrics import flight
    nblocks = n_pad // self.block_size
    tok = flight.epoch_begin()
    completed = False
    chunks = 0
    try:
      with strict_guards():
        record_dispatch('embed_store_init')
        out = self._init_fn((pass_key, 'init'), out_shape, out_dtype)()
        start = 0
        while start < nblocks:
          k = min(self.chunk_size, nblocks - start)
          record_dispatch('embed_chunk')
          out = dispatch_chunk(out, k,
                               jax.device_put(np.int32(start)))
          start += k
          chunks += 1
      completed = True
    finally:
      flight.end_for(
          self, tok, emitter=self._NAME, steps=nblocks,
          completed=completed, config=self._flight_config(),
          extra={'pass': str(layer_label), 'chunks': chunks})
    if self.spill_dir is not None:
      self._spill_pass(str(layer_label), out)
    return out

  def _spill_pass(self, label: str, out):
    """Write a completed pass's output store to its disk tier (outside
    the strict region — the fetch is the spill's whole point)."""
    import os
    from ..storage.disk import spill_array
    safe = label.replace('/', '_').replace(' ', '_')
    self.spilled[label] = spill_array(
        os.path.join(self.spill_dir, f'pass_{safe}'), np.asarray(out))

  def _flight_config(self) -> dict:
    return dict(emitter=self._NAME, block_size=self.block_size,
                chunk_size=self.chunk_size, hetero=self.is_hetero,
                num_layers=self.num_layers,
                neighbor_cap=self.neighbor_cap)

  # ------------------------------------------------------------- homo

  def _materialize_homo(self):
    import jax
    dev = self._upload()
    prev = dev['x0']
    n_pad = prev.shape[0]
    b = self.block_size
    for layer in range(self.num_layers):
      slice_fn = self._homo_slice(layer)
      spec = self._out_spec(slice_fn, dict(
          x=jax.ShapeDtypeStruct((b + b * self._w, prev.shape[1]),
                                 prev.dtype),
          edge_index=jax.ShapeDtypeStruct((2, b * self._w), np.int32),
          edge_mask=jax.ShapeDtypeStruct((b * self._w,), bool)))

      def dispatch(out, k, start, _layer=layer):
        return self._homo_chunk_fn(_layer, k)(
            self.params, prev, out, dev['nbr'], dev['ei'], start)

      if layer == self.num_layers - 1:
        self._penultimate = prev
      out = self._run_layer_pass(('homo', layer), n_pad,
                                 (n_pad, spec.shape[-1]), spec.dtype,
                                 dispatch, layer)
      prev = out
    self._embeddings = prev
    return prev

  # ------------------------------------------------------------ hetero

  def _hetero_layout(self, t, live_ets, b: Optional[int] = None):
    """Static per-(target type, live etypes) block layout: the order
    and offsets of each result type's buffer segments, plus the
    constant per-out-etype edge arrays. Self rows of type ``t`` lead
    t's buffer; each etype's ``B*W`` neighbor rows append to its result
    type's buffer in etype order. ``b`` defaults to the materializer
    block size; the refresh buckets pass their padded capacity (the
    SAME layout at refresh-bucket scale)."""
    b = self.block_size if b is None else int(b)
    widths = {t: b}
    offsets = {}
    for et in live_ets:
      r = self._res_t[et]
      offsets[et] = widths.get(r, 0)
      widths[r] = offsets[et] + b * self._w[et]
    ei = {}
    for et in live_ets:
      w = self._w[et]
      row = offsets[et] + np.arange(b * w, dtype=np.int32)
      col = np.repeat(np.arange(b, dtype=np.int32), w)
      ei[self._out_et[et]] = np.stack([row, col])
    return offsets, ei

  def _hetero_chunk_fn(self, t, layer, live_ets, k):
    """Scanned K-block program of hetero conv layer ``layer`` for
    target type ``t``: per-etype neighbor gathers from the per-type
    stores, one RGNN layer slice (embed=False, head=False), block
    write into t's donated output store."""
    key = ('het', t, layer, tuple(live_ets), k)
    if key in self._chunk_fns:
      return self._chunk_fns[key]
    import jax
    import jax.numpy as jnp
    from jax import lax
    b = self.block_size
    _, ei_np = self._hetero_layout(t, live_ets)
    ei_dev = {oet: jax.device_put(v) for oet, v in ei_np.items()}
    slice_fn = train_lib.make_layer_slice_fn(
        self.model, layer, layer + 1, embed=False, head=False)
    res_order = []            # segment order per result-type buffer
    for et in live_ets:
      res_order.append((et, self._res_t[et]))

    def chunk(params, stores, out, nbrs, start):
      def body(out, g):
        base = g * b
        parts = {t: [lax.dynamic_slice_in_dim(stores[t], base, b)]}
        masks = {}
        for et, r in res_order:
          blk = lax.dynamic_slice_in_dim(nbrs[et], base, b)
          masks[self._out_et[et]] = (blk >= 0).reshape(-1)
          rows = stores[r][jnp.maximum(blk.reshape(-1), 0)]
          parts.setdefault(r, []).append(rows)
        x = {r: (jnp.concatenate(v) if len(v) > 1 else v[0])
             for r, v in parts.items()}
        batch = dict(x=x, edge_index=ei_dev, edge_mask=masks)
        h = slice_fn(params, batch)[t]
        return lax.dynamic_update_slice(out, h[:b].astype(out.dtype),
                                        (base, 0)), None
      out, _ = lax.scan(body, out, start + lax.iota(jnp.int32, k))
      return out

    from ..metrics import programs
    fn = programs.instrument(jax.jit(chunk, donate_argnums=(2,)),
                             'embed_chunk')
    self._chunk_fns[key] = fn
    return fn

  def _hetero_rowlocal_fn(self, t, tag, slice_fn, k):
    """Scanned K-block program of a row-local pass (the per-type embed
    projection, the final lin_out head): no neighbors, one Dense per
    block."""
    key = ('hetrow', t, tag, k)
    if key in self._chunk_fns:
      return self._chunk_fns[key]
    import jax
    import jax.numpy as jnp
    from jax import lax
    b = self.block_size

    def chunk(params, src, out, start):
      def body(out, g):
        base = g * b
        rows = lax.dynamic_slice_in_dim(src, base, b)
        h = slice_fn(params, dict(x={t: rows}, edge_index={},
                                  edge_mask={}))
        if isinstance(h, dict):
          h = h[t]
        return lax.dynamic_update_slice(out, h.astype(out.dtype),
                                        (base, 0)), None
      out, _ = lax.scan(body, out, start + lax.iota(jnp.int32, k))
      return out

    from ..metrics import programs
    fn = programs.instrument(jax.jit(chunk, donate_argnums=(2,)),
                             'embed_chunk')
    self._chunk_fns[key] = fn
    return fn

  def _materialize_hetero(self):
    import jax
    dev = self._upload()
    b = self.block_size
    embed_fn = train_lib.make_layer_slice_fn(self.model, 0, 0,
                                             embed=True, head=False)
    stores = {}
    # pass 0: per-type embed projection (row-local)
    for t, x0 in dev['x0'].items():
      spec = self._out_spec(
          lambda p, bt: embed_fn(p, bt)[t],
          dict(x={t: jax.ShapeDtypeStruct((b, x0.shape[1]), x0.dtype)},
               edge_index={}, edge_mask={}))

      def dispatch(out, k, start, _t=t, _x0=x0):
        return self._hetero_rowlocal_fn(
            _t, 'embed', embed_fn, k)(self.params, _x0, out, start)

      stores[t] = self._run_layer_pass(
          ('embed', t), x0.shape[0], (x0.shape[0], spec.shape[-1]),
          spec.dtype, dispatch, f'embed/{t}')
    # conv layers: per target type, over the etypes whose result type
    # is still live (mirrors HeteroConv's type dropping)
    for layer in range(self.num_layers):
      new_stores = {}
      for t in sorted(self._targets):
        if t not in stores:
          continue
        live = tuple(et for et in self._etypes
                     if self._key_t[et] == t and self._res_t[et] in stores)
        if not live:
          continue
        slice_fn = train_lib.make_layer_slice_fn(
            self.model, layer, layer + 1, embed=False, head=False)
        _, ei_np = self._hetero_layout(t, live)
        widths = {t: b}
        for et in live:
          r = self._res_t[et]
          widths[r] = widths.get(r, b if r == t else 0) + b * self._w[et]
        spec = self._out_spec(
            lambda p, bt: slice_fn(p, bt)[t],
            dict(x={r: jax.ShapeDtypeStruct((widths[r],
                                             stores[r].shape[1]),
                                            stores[r].dtype)
                    for r in widths if r in stores},
                 edge_index={oet: jax.ShapeDtypeStruct(v.shape, np.int32)
                             for oet, v in ei_np.items()},
                 edge_mask={oet: jax.ShapeDtypeStruct((v.shape[1],),
                                                      bool)
                            for oet, v in ei_np.items()}))
        n_pad = stores[t].shape[0]

        def dispatch(out, k, start, _t=t, _layer=layer, _live=live,
                     _stores=stores):
          return self._hetero_chunk_fn(_t, _layer, _live, k)(
              self.params, _stores, out, dev['nbr'], start)

        new_stores[t] = self._run_layer_pass(
            ('het', t, layer), n_pad, (n_pad, spec.shape[-1]),
            spec.dtype, dispatch, f'{layer}/{t}')
      if layer == self.num_layers - 1:
        self._penultimate = stores
      stores = new_stores
    self.stores = stores
    # head: lin_out over the output type (row-local), when the model
    # has one — otherwise the per-type stores ARE the result
    out_t = getattr(self.model, 'out_ntype', None)
    if out_t is None:
      self._embeddings = stores
      return stores
    if out_t not in stores:
      raise ValueError(f'out_ntype {out_t!r} received no messages')
    head_fn = train_lib.make_layer_slice_fn(
        self.model, self.num_layers, self.num_layers, embed=False,
        head=True)
    src = stores[out_t]
    spec = self._out_spec(
        head_fn, dict(x={out_t: jax.ShapeDtypeStruct((b, src.shape[1]),
                                                     src.dtype)},
                      edge_index={}, edge_mask={}))

    def dispatch(out, k, start):
      return self._hetero_rowlocal_fn(
          out_t, 'head', head_fn, k)(self.params, src, out, start)

    self._embeddings = self._run_layer_pass(
        ('head', out_t), src.shape[0], (src.shape[0], spec.shape[-1]),
        spec.dtype, dispatch, f'head/{out_t}')
    return self._embeddings

  # -------------------------------------------------------------- API

  def materialize(self):
    """Run the full layer-by-layer pass. Returns the final output table
    (homo: [N_pad, out_dim] device array; hetero: the ``lin_out`` table
    of ``out_ntype``, or the per-type store dict when the model has no
    head). Rows past ``num_nodes`` are block padding — never read."""
    if self.is_hetero:
      return self._materialize_hetero()
    return self._materialize_homo()

  @property
  def embeddings(self):
    if self._embeddings is None:
      raise RuntimeError('call materialize() first')
    return self._embeddings

  def embedding_store(self):
    """The materialized table wrapped as a serving ``EmbeddingStore``
    with the REAL node count — use this (not a bare
    ``EmbeddingStore(table)``) so the table's block-padding rows stay
    behind the engine's id validation instead of being servable as
    node ids (homo only; hetero stores are per type)."""
    from .store import EmbeddingStore
    if self.is_hetero:
      # per-type outputs by design: the caller picks WHICH type's
      # table to serve; no plan input is missing
      # graftlint: allow[hetero-gate] per-type outputs by design
      raise ValueError('hetero materialization produces per-type '
                       'stores — wrap the one you serve explicitly: '
                       'EmbeddingStore(table, num_nodes=N_type)')
    if self._embeddings is None:
      raise RuntimeError('call materialize() first')
    return EmbeddingStore(self._embeddings, num_nodes=self.num_nodes)

  def tiered_embedding_store(self, hot_rows: int = 0, warm_rows: int = 0,
                             **kwargs):
    """The spilled final-layer table as a beyond-HBM
    ``TieredEmbeddingStore``: hot_rows stay in HBM, warm_rows in host
    RAM, the rest serves from the memory-mapped spill (homo only;
    requires ``spill_dir``). The real node count rides along so block
    padding stays behind the engine's id validation."""
    from ..storage.tiered import TieredFeature
    from .store import TieredEmbeddingStore
    if self.is_hetero:
      # per-type outputs by design: the caller picks WHICH type's
      # table to serve; no plan input is missing
      # graftlint: allow[hetero-gate] per-type outputs by design
      raise ValueError('hetero materialization produces per-type '
                       'stores — build TieredEmbeddingStore over the '
                       'spilled pass tier you serve explicitly')
    if self.spill_dir is None:
      raise ValueError('tiered_embedding_store needs '
                       'EmbeddingMaterializer(..., spill_dir=...)')
    if self._embeddings is None:
      raise RuntimeError('call materialize() first')
    tier = self.spilled[str(self.num_layers - 1)]
    tf = TieredFeature(tier, hot_rows=hot_rows, warm_rows=warm_rows,
                       **kwargs)
    return TieredEmbeddingStore(tf, num_nodes=self.num_nodes)

  def dist_embedding_store(self, mesh, **kwargs):
    """The materialized table as a sharded ``DistEmbeddingStore`` over
    ``mesh``, with the real node count passed for you (block-pad rows
    must not become servable ids — see :meth:`embedding_store`).
    ``kwargs`` forward to ``DistEmbeddingStore.build`` (split_ratio /
    cache_rows / hotness / wire_dtype / bucket_frac)."""
    from .store import DistEmbeddingStore
    if self.is_hetero:
      # per-type outputs by design: the caller picks WHICH type's
      # table to serve; no plan input is missing
      # graftlint: allow[hetero-gate] per-type outputs by design
      raise ValueError('hetero materialization produces per-type '
                       'stores — build the one you serve explicitly '
                       'with DistEmbeddingStore.build(table, mesh, '
                       'num_nodes=N_type, ...)')
    if self._embeddings is None:
      raise RuntimeError('call materialize() first')
    return DistEmbeddingStore.build(self._embeddings, mesh,
                                    num_nodes=self.num_nodes, **kwargs)

  # ------------------------------------------------------------ refresh

  def _refresh_fn_for(self, cap: int):
    """Jitted final-layer-only recompute for a [cap] id bucket: gather
    the stale nodes' penultimate rows + their full neighbor rows, run
    the LAST layer slice of the training forward. Homo only (the
    hetero head/type bookkeeping lives server-side for now)."""
    if cap in self._refresh_fns:
      return self._refresh_fns[cap]
    import jax
    import jax.numpy as jnp
    w = self._w
    last = self.num_layers - 1
    slice_fn = self._homo_slice(last)
    ei = jax.device_put(_block_edges(cap, w))

    def refresh(params, prev, nbr, ids, mask):
      safe = jnp.maximum(ids, 0)
      self_rows = prev[safe]
      nbr_blk = jnp.where(mask[:, None], nbr[safe], -1)
      em = (nbr_blk >= 0).reshape(-1)
      nbr_rows = prev[jnp.maximum(nbr_blk.reshape(-1), 0)]
      batch = dict(x=jnp.concatenate([self_rows, nbr_rows]),
                   edge_index=ei, edge_mask=em)
      return slice_fn(params, batch)[:cap]

    from ..metrics import programs
    fn = programs.instrument(jax.jit(refresh), 'serve_refresh')
    self._refresh_fns[cap] = fn
    return fn

  def _hetero_live_for(self, t):
    """The etypes feeding target type ``t`` in the LAST conv layer —
    the same liveness rule _materialize_hetero applies, computed
    against the penultimate store set."""
    return tuple(et for et in self._etypes
                 if self._key_t[et] == t
                 and self._res_t[et] in self._penultimate)

  def _hetero_refresh_fn_for(self, t, cap: int):
    """Typed final-layer refresh for a [cap] id bucket of type ``t``:
    gather the stale nodes' penultimate rows + their per-etype full
    neighbor rows (the SAME `_hetero_layout` the chunk programs use, at
    refresh-bucket scale), run the LAST conv layer slice of the
    training forward for ``t`` — plus the ``lin_out`` head when ``t``
    is the model's output type, so refreshed rows land in the same
    space the served table holds."""
    key = ('het', t, cap)
    if key in self._refresh_fns:
      return self._refresh_fns[key]
    import jax
    import jax.numpy as jnp
    live = self._hetero_live_for(t)
    if not live:
      raise ValueError(f'type {t!r} receives no messages in the last '
                       'layer — nothing to refresh')
    last = self.num_layers - 1
    slice_fn = train_lib.make_layer_slice_fn(
        self.model, last, last + 1, embed=False, head=False)
    head_fn = None
    if getattr(self.model, 'out_ntype', None) == t:
      head_fn = train_lib.make_layer_slice_fn(
          self.model, self.num_layers, self.num_layers, embed=False,
          head=True)
    _, ei_np = self._hetero_layout(t, live, b=cap)
    ei_dev = {oet: jax.device_put(v) for oet, v in ei_np.items()}
    res_order = [(et, self._res_t[et]) for et in live]

    def refresh(params, stores, nbrs, ids, mask):
      safe = jnp.maximum(ids, 0)
      parts = {t: [stores[t][safe]]}
      masks = {}
      for et, r in res_order:
        blk = jnp.where(mask[:, None], nbrs[et][safe], -1)
        masks[self._out_et[et]] = (blk >= 0).reshape(-1)
        parts.setdefault(r, []).append(
            stores[r][jnp.maximum(blk.reshape(-1), 0)])
      x = {r: (jnp.concatenate(v) if len(v) > 1 else v[0])
           for r, v in parts.items()}
      h = slice_fn(params, dict(x=x, edge_index=ei_dev,
                                edge_mask=masks))[t]
      if head_fn is not None:
        h2 = head_fn(params, dict(x={t: h}, edge_index={},
                                  edge_mask={}))
        h = h2[t] if isinstance(h2, dict) else h2
      return h[:cap]

    from ..metrics import programs
    fn = programs.instrument(jax.jit(refresh), 'serve_refresh')
    self._refresh_fns[key] = fn
    return fn

  def _refresh_out_dim(self, ntype=None) -> int:
    if not self.is_hetero:
      return int(self.model.out_dim)
    if getattr(self.model, 'out_ntype', None) == ntype:
      return int(self.model.out_dim)
    return int(self.stores[ntype].shape[1])

  def refresh_rows(self, ids, ntype=None) -> np.ndarray:
    """Final-layer-only refresh: recompute the CURRENT last-layer
    embedding rows for ``ids`` from the penultimate store (one bucket
    program per padded capacity — the online engine's stale-node hook).
    Returns [len(ids), F_out] host rows.

    Hetero (RGNN): pass ``ntype`` — rows refresh through the per-type
    last-layer slice (plus the head when ``ntype`` is the output type),
    against the SAME per-etype full-neighbor tables the offline pass
    aggregated over; wire into an engine as
    ``refresh_fn=lambda ids: mat.refresh_rows(ids, ntype='paper')``."""
    if self._penultimate is None:
      raise RuntimeError('call materialize() first')
    import jax.numpy as jnp
    from .store import pow2_cap
    if self.is_hetero:
      if ntype is None:
        raise ValueError(
            'hetero refresh needs the node type: '
            "refresh_rows(ids, ntype='paper') (per-type stores, "
            'docs/serving.md)')
      if ntype not in getattr(self, 'stores', {}):
        raise ValueError(f'{ntype!r} has no final-layer store '
                         f'(have: {sorted(self.stores)})')
    ids = np.asarray(ids, np.int64).reshape(-1)
    if ids.size == 0:
      # never touch _embeddings here: the caller may have handed that
      # table to an EmbeddingStore whose refresh write-back DONATED it
      return np.zeros((0, self._refresh_out_dim(ntype)), np.float32)
    cap = pow2_cap(ids.size)
    padded = np.full((cap,), -1, np.int32)
    padded[:ids.size] = ids
    mask = padded >= 0
    record_dispatch('serve_refresh')
    if self.is_hetero:
      rows = self._hetero_refresh_fn_for(ntype, cap)(
          self.params, self._penultimate, self._upload()['nbr'],
          jnp.asarray(padded), jnp.asarray(mask))
    else:
      rows = self._refresh_fn_for(cap)(
          self.params, self._penultimate, self._upload()['nbr'],
          jnp.asarray(padded), jnp.asarray(mask))
    return np.asarray(rows)[:ids.size]


def warm_embedding_store(spill_dir: str, num_nodes: int, *,
                         hot_rows: Optional[int] = None,
                         warm_rows: int = 0,
                         pass_label: Optional[str] = None):
  """Engine RESTART path: rebuild a serving store from the spilled
  (checkpointed) final-layer tier on disk, WITHOUT rematerializing.

  ``EmbeddingMaterializer(..., spill_dir=...)`` writes every completed
  layer pass as an immutable memory-mapped disk tier — a durable
  checkpoint of the store version that was serving. After an engine
  crash or rolling restart, this reopens that version and serves it
  immediately (seconds, not a full O(L) rematerialization); the next
  scheduled rematerialize-and-rotate then replaces it as usual
  (docs/recovery.md, docs/serving.md).

  Args:
    spill_dir: the materializer's spill directory.
    num_nodes: REAL node count (the spilled table carries block-pad
      rows; they must stay behind the engine's id validation — the
      same footgun :meth:`EmbeddingMaterializer.embedding_store`
      guards).
    hot_rows: None -> load the whole table to HBM (a plain
      ``EmbeddingStore``); otherwise serve beyond-HBM through a
      ``TieredEmbeddingStore`` with this hot prefix (+ ``warm_rows``
      in host RAM).
    pass_label: which spilled pass to serve (default: the
      highest-numbered ``pass_<n>`` — the final layer).
  """
  import os
  import re

  from ..storage.disk import DiskTier
  from ..storage.tiered import TieredFeature
  from .store import EmbeddingStore, TieredEmbeddingStore
  if pass_label is None:
    passes = sorted(
        (int(m.group(1)) for m in
         (re.match(r'^pass_(\d+)$', d) for d in os.listdir(spill_dir))
         if m))
    if not passes:
      spilled = sorted(d for d in os.listdir(spill_dir)
                       if d.startswith('pass_'))
      raise FileNotFoundError(
          f'no numeric pass_<n> tier under {spill_dir!r} '
          f'(found: {spilled or "nothing"}) — either the materializer '
          'ran without spill_dir, or this is a HETERO spill (per-type '
          "labels like 'head/paper'): pass the pass_label of the store "
          'you serve explicitly')
    pass_label = str(passes[-1])
  # the materializer sanitizes labels on spill ('/'/' ' -> '_',
  # _spill_pass) — apply the same mapping so hetero labels round-trip
  safe = str(pass_label).replace('/', '_').replace(' ', '_')
  tier = DiskTier(os.path.join(spill_dir, f'pass_{safe}'))
  if num_nodes > tier.shape[0]:
    raise ValueError(f'num_nodes={num_nodes} exceeds the spilled '
                     f'table height {tier.shape[0]}')
  if hot_rows is None:
    table = tier.gather(np.arange(tier.shape[0], dtype=np.int64))
    return EmbeddingStore(table, num_nodes=num_nodes)
  tf = TieredFeature(tier, hot_rows=hot_rows, warm_rows=warm_rows)
  return TieredEmbeddingStore(tf, num_nodes=num_nodes)
