"""Zero-downtime rotating sharded serving stores.

The single-replica serving stores (serving/store.py) refresh by
REBUILD: a new embedding version displaces the old one in place, and
until PR 13 the only multi-version story was "stop serving, swap,
restart". This module is the production rotation half of ROADMAP
item 2: a multi-shard store whose next version MATERIALIZES onto
per-shard disk tiers while the current version keeps serving, then
swaps in atomically — under live traffic, with degrade-to-previous-
version when a shard swap fails.

Version lifecycle (docs/serving.md):

  1. **Build (minutes, concurrent with serving).** ``rotate(build_fn)``
     produces the next version's [N, F] table (typically
     ``EmbeddingMaterializer.materialize()`` — layer by layer, the
     offline pass) and spills it as per-shard memory-mapped disk tiers
     under ``<root>/v<NNNN>/shard_<SS>`` (storage/disk.py). Version v
     serves throughout; nothing the build does is visible to readers.
  2. **Swap (milliseconds).** Each shard's new payload is installed in
     a per-shard pass (the ``serving.rotate`` fault site fires per
     shard), then ONE atomic pointer flip publishes the full version:
     a lookup snapshots the shard tuple exactly once, so every request
     is answered from a SINGLE consistent version — no torn reads
     across the swap, ever. The critical section's duration is the
     ``serving.rotation_swap_ms`` histogram (``rotation_swap_ms_p99``
     in bench.py).
  3. **Degrade.** A failed shard swap (or build) discards the partial
     version and KEEPS the previous version serving — in-flight and
     subsequent requests see v, none fail. Disk retention is ONE
     rotation deep: after a successful flip to v, spilled version dirs
     older than v-1 are pruned (unbounded per-rotation table copies
     would otherwise fill the disk). Requests that snapshotted an
     older version mid-swap still finish cleanly — the reader's
     snapshot holds the shard tuple (and its open mmaps) alive by
     reference, and POSIX keeps unlinked mmap pages valid until the
     handles drop.

The per-shard payload is a warm-prefix + mmap-tier gather (the CPU
replica of the serving shard — each shard keeps its first
``warm_rows`` rows in host RAM and serves the rest straight from its
disk tier); the engine-facing surface is the standard store contract
(``lookup``/``fetch``/``num_nodes``/``granularity``), so a
``ServingEngine`` batches over it unchanged.
"""
import os
import threading
import time
from typing import Callable, Optional, Tuple

import numpy as np

from .. import metrics
from ..metrics import spans
from ..storage.disk import spill_array
from ..utils.faults import fault_point
from ..utils.trace import record_dispatch


class _VersionShard:
  """One shard of one store version: rows [lo, hi) of the version's
  table, a warm RAM prefix + the spilled mmap tier."""

  __slots__ = ('lo', 'hi', 'tier', 'warm')

  def __init__(self, lo: int, hi: int, tier, warm_rows: int):
    self.lo, self.hi = int(lo), int(hi)
    self.tier = tier
    w = max(0, min(int(warm_rows), tier.rows))
    self.warm = (tier.gather(np.arange(w, dtype=np.int64)) if w
                 else None)

  def gather(self, local_ids: np.ndarray) -> np.ndarray:
    out = np.zeros((local_ids.shape[0], self.tier.dim), self.tier.dtype)
    w = 0 if self.warm is None else self.warm.shape[0]
    is_warm = local_ids < w
    if is_warm.any():
      out[is_warm] = self.warm[local_ids[is_warm]]
    cold = ~is_warm
    if cold.any():
      out[cold] = self.tier.gather(local_ids[cold])
    return out


class RotatingShardedStore:
  """Sharded, versioned embedding store with zero-downtime rotation.

  Args:
    root_dir: where per-version per-shard tiers are spilled
      (``<root>/v<NNNN>/shard_<SS>``).
    num_shards: contiguous row shards per version.
    initial_table: version 0's [N(, _pad), F] table (np array or
      device array; rows past ``num_nodes`` are trimmed).
    num_nodes: REAL node count (materializer tables carry block-pad
      rows; they must stay behind the engine's id validation — the
      ``EmbeddingMaterializer.embedding_store`` footgun).
    warm_rows: per-shard host-RAM prefix; the rest of each shard
      serves from its memory-mapped tier.
    rows_per_chunk: DiskTier layout knob for the spills.
  """

  granularity = 1

  def __init__(self, root_dir: str, num_shards: int, initial_table,
               num_nodes: Optional[int] = None, warm_rows: int = 0,
               rows_per_chunk: int = 65536):
    if num_shards < 1:
      raise ValueError('num_shards must be >= 1')
    table = np.asarray(initial_table)
    self.root_dir = str(root_dir)
    self.num_shards = int(num_shards)
    self.num_nodes = int(num_nodes if num_nodes is not None
                         else table.shape[0])
    if self.num_nodes > table.shape[0]:
      raise ValueError(f'num_nodes={self.num_nodes} exceeds the table '
                       f'height {table.shape[0]}')
    self._fdim = int(table.shape[1])
    self.warm_rows = int(warm_rows)
    self.rows_per_chunk = int(rows_per_chunk)
    # shard s covers rows [bounds[s], bounds[s+1])
    self._bounds = (np.arange(self.num_shards + 1, dtype=np.int64)
                    * self.num_nodes) // self.num_shards
    self._version = -1
    self._shards: Optional[Tuple[_VersionShard, ...]] = None
    self._rotate_lock = threading.Lock()   # one rotation at a time
    self._mask_fn = None
    self.install_version(table)

  # ------------------------------------------------------------ rotation

  @property
  def version(self) -> int:
    """The currently served version index."""
    return self._version

  def install_version(self, table) -> int:
    """Build the next version from ``table`` and swap it in (module
    docstring: build concurrent with serving, one atomic flip, degrade
    to the previous version on any failure). Returns the new version
    index; raises the build/swap failure AFTER guaranteeing the
    previous version still serves."""
    table = np.asarray(table)
    if table.shape[0] < self.num_nodes or table.shape[1] != self._fdim:
      raise ValueError(
          f'version table must be [>= {self.num_nodes}, {self._fdim}], '
          f'got {table.shape}')
    with self._rotate_lock:
      v = self._version + 1
      # BUILD: per-shard disk tiers — invisible to readers until the
      # flip below, so a failure here leaves the serving version
      # untouched by construction
      built = []
      for s in range(self.num_shards):
        lo, hi = int(self._bounds[s]), int(self._bounds[s + 1])
        tier = spill_array(
            os.path.join(self.root_dir, f'v{v:04d}', f'shard_{s:02d}'),
            table[lo:hi], rows_per_chunk=self.rows_per_chunk)
        built.append((lo, hi, tier))
      # SWAP: the per-shard install pass + one atomic pointer flip.
      # A fault mid-pass abandons the staged list — the previous
      # version keeps serving, zero failed requests (chaos-tested)
      t0 = time.perf_counter()
      with spans.span('serving.rotate', version=v,
                      shards=self.num_shards):
        staged = []
        for s, (lo, hi, tier) in enumerate(built):
          fault_point('serving.rotate')
          staged.append(_VersionShard(lo, hi, tier, self.warm_rows))
        # the one flip readers snapshot: a tuple assignment is atomic,
        # and every lookup reads self._shards exactly once. No
        # previous-version bookkeeping is needed — a reader's snapshot
        # keeps its shard tuple (and mmaps) alive by reference
        self._shards = tuple(staged)
        self._version = v
      metrics.inc('serving.rotations')
      metrics.observe('serving.rotation_swap_ms',
                      (time.perf_counter() - t0) * 1e3)
      self._prune_versions(v - 1)
      return v

  def _prune_versions(self, keep_from: int):
    """Delete spilled version dirs older than ``keep_from`` — the
    one-rotation-deep disk retention (a long-running rotation loop
    writes a full table copy per version; without pruning the root
    dir grows without bound). Readers mid-request are safe: their
    snapshot's mmap handles keep unlinked pages valid until dropped.
    Best-effort — a prune failure must never fail a completed swap."""
    import re
    import shutil
    try:
      names = os.listdir(self.root_dir)
    except OSError:
      return
    for d in names:
      m = re.match(r'^v(\d+)$', d)
      if m and int(m.group(1)) < keep_from:
        shutil.rmtree(os.path.join(self.root_dir, d),
                      ignore_errors=True)

  def rotate(self, build_fn: Callable[[], np.ndarray]) -> int:
    """One full rotation: materialize the next version while the
    current serves (``build_fn()`` — e.g. ``lambda:
    np.asarray(EmbeddingMaterializer(...).materialize())``), then
    install it. Returns the new version index."""
    return self.install_version(build_fn())

  # ------------------------------------------------------- store surface

  @property
  def feature_dim(self) -> int:
    return self._fdim

  def lookup(self, ids, mask):
    """[cap] padded ids (-1 pads, mask False) -> [cap, F] device rows.
    The shard tuple is snapshotted ONCE, so the whole request answers
    from a single version even while a rotation swaps underneath."""
    import jax
    import jax.numpy as jnp
    shards = self._shards          # the one consistent-version snapshot
    ids_np = np.asarray(ids, np.int64).reshape(-1)
    mask_np = np.asarray(mask).reshape(-1)
    rows = np.zeros((ids_np.shape[0], self._fdim),
                    shards[0].tier.dtype)
    safe = np.clip(ids_np, 0, self.num_nodes - 1)
    for sh in shards:
      m = mask_np & (safe >= sh.lo) & (safe < sh.hi)
      if m.any():
        rows[m] = sh.gather(safe[m] - sh.lo)
    if self._mask_fn is None:
      from ..metrics import programs
      self._mask_fn = programs.instrument(
          jax.jit(lambda r, m: jnp.where(m[:, None], r, 0)),
          'serve_lookup')
    record_dispatch('serve_lookup')
    return self._mask_fn(jnp.asarray(rows), jnp.asarray(mask_np))

  def fetch(self, rows) -> np.ndarray:
    return np.asarray(rows)

  def update_rows(self, ids, rows):
    raise NotImplementedError(
        'RotatingShardedStore rows are immutable within a version — '
        'refresh by rotating in the next materialized version '
        '(rotate(), docs/serving.md)')


class RotationScheduler:
  """Drives ``RotatingShardedStore.rotate`` on a schedule — the
  materializer loop that turns the zero-downtime swap primitive into a
  PRODUCTION refresh cadence (ROADMAP 2d; docs/serving.md 'Scheduled
  rotation').

  A daemon thread polls every ``poll_s`` seconds and triggers one full
  rotation (``build_fn`` -> ``install_version``) when EITHER fires:

  * **interval**: ``interval_s`` seconds elapsed since the last
    successful rotation (wall-clock freshness floor), or
  * **staleness**: ``staleness_fn()`` returned truthy — the
    workload-aware trigger (typical: a closure over the engine's
    stale set or an ingestion watermark; the scheduler imposes no
    schema on it).

  Failure semantics match the store's: a failed BUILD or SWAP keeps
  the previous version serving (``serving.rotation_errors`` counts it,
  the next poll retries — chaos-tested with the ``serving.rotate``
  fault armed in tests/test_rotation.py). A ``staleness_fn`` that
  raises counts as not-stale: observability hooks must never take the
  serving path down.

  ``stop()`` is join-semantics: the thread exits its current poll (or
  finishes an in-flight rotation — rotations are never interrupted
  mid-swap) and joins within ``stop(timeout)``.
  """

  def __init__(self, store, build_fn: Callable[[], np.ndarray],
               interval_s: Optional[float] = None,
               staleness_fn: Optional[Callable[[], bool]] = None,
               poll_s: float = 0.5):
    if interval_s is None and staleness_fn is None:
      raise ValueError('RotationScheduler needs a trigger: interval_s '
                       'and/or staleness_fn')
    if interval_s is not None and interval_s <= 0:
      raise ValueError(f'interval_s must be > 0, got {interval_s}')
    self.store = store
    self.build_fn = build_fn
    self.interval_s = None if interval_s is None else float(interval_s)
    self.staleness_fn = staleness_fn
    self.poll_s = float(poll_s)
    self.rotations = 0         # successful rotations this scheduler ran
    self.failures = 0          # failed attempts (previous version kept)
    self.last_error: Optional[str] = None
    self._last_rotate = time.monotonic()
    self._stop = threading.Event()
    self._wake = threading.Event()   # stop/rotate_now interrupt a poll
    self._thread: Optional[threading.Thread] = None

  # ------------------------------------------------------------ lifecycle

  def start(self) -> 'RotationScheduler':
    if self._thread is not None and self._thread.is_alive():
      return self
    self._stop.clear()
    # the interval clock runs from START, not construction — a
    # scheduler built during process setup and started after warmup
    # must not fire a full build+swap on its first poll
    self._last_rotate = time.monotonic()
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-rotation-scheduler')
    self._thread.start()
    return self

  def stop(self, timeout: float = 30.0):
    """Signal the loop to exit and join it. An in-flight rotation
    completes first — the swap critical section is never abandoned
    half-installed (the store's atomicity contract)."""
    self._stop.set()
    self._wake.set()
    t = self._thread
    if t is not None:
      t.join(timeout=timeout)
      if t.is_alive():
        raise TimeoutError(
            f'rotation scheduler did not stop within {timeout}s (a '
            'rotation build is still running; it will finish on the '
            'daemon thread)')
    self._thread = None

  def rotate_now(self):
    """Force the next poll to rotate regardless of triggers."""
    self._force = True
    self._wake.set()

  _force = False

  # ----------------------------------------------------------------- loop

  def _due(self) -> bool:
    if self._force:
      return True
    if self.interval_s is not None and \
        time.monotonic() - self._last_rotate >= self.interval_s:
      return True
    if self.staleness_fn is not None:
      try:
        return bool(self.staleness_fn())
      except Exception:  # noqa: BLE001 - a broken probe must not kill serving
        metrics.inc('serving.rotation_errors')
        import logging
        logging.getLogger('graphlearn_tpu.serving').exception(
            'rotation staleness_fn raised — treating as not-stale')
    return False

  def _loop(self):
    while not self._stop.is_set():
      if self._due():
        try:
          self.store.rotate(self.build_fn)
          # a forced request is consumed only by a SUCCESSFUL rotation
          # — a failed build keeps the force armed so the next poll
          # retries it (the docstring's retry contract holds even for
          # staleness-only schedulers whose probe reads False)
          self._force = False
          self.rotations += 1
          self.last_error = None
          # interval restarts from the SUCCESS; a failure below keeps
          # the old deadline so the next poll retries immediately
          self._last_rotate = time.monotonic()
        except Exception as e:  # noqa: BLE001 - degrade, keep serving
          self.failures += 1
          self.last_error = f'{type(e).__name__}: {e}'
          metrics.inc('serving.rotation_errors')
          import logging
          logging.getLogger('graphlearn_tpu.serving').warning(
              'scheduled rotation failed (%s) — previous version '
              'keeps serving; retrying next poll', self.last_error)
      self._wake.wait(self.poll_s)
      self._wake.clear()
