"""graphlearn_tpu.serving: the inference tier (docs/serving.md).

Two halves (ROADMAP item 1):

* **Offline**: :class:`EmbeddingMaterializer` — layer-wise full-graph
  embedding materialization as a closed set of scanned fixed-shape
  programs (the ScanTrainer chunk pattern, no sampling), each layer's
  output becoming the next layer's feature store (O(N·F) memory).
* **Online**: :class:`ServingEngine` — admission batching into
  calibrated padded buckets over an :class:`EmbeddingStore` (single
  replica) or :class:`DistEmbeddingStore` (DistFeature-backed sharded
  store with the replicated hot-embedding cache), with final-layer-only
  refresh for stale nodes and ``serving.*`` latency histograms.

Both halves resolve the model forward through
``models.train.make_forward_fn`` / ``make_layer_slice_fn`` — the same
definition training optimizes, so trained and served models cannot
drift.
"""
from .engine import DEFAULT_BUCKETS, ServingEngine
from .materialize import (EmbeddingMaterializer, padded_neighbors,
                          warm_embedding_store)
from .rotation import RotatingShardedStore, RotationScheduler
from .store import DistEmbeddingStore, EmbeddingStore

__all__ = [
    'DEFAULT_BUCKETS', 'DistEmbeddingStore', 'EmbeddingMaterializer',
    'EmbeddingStore', 'RotatingShardedStore', 'RotationScheduler',
    'ServingEngine', 'padded_neighbors', 'warm_embedding_store',
]
