"""NeighborLoader: fanout-sampling node loader.

TPU-native port of
/root/reference/graphlearn_torch/python/loader/neighbor_loader.py: builds a
NeighborSampler from the Dataset and drives NodeLoader with it.
"""
from typing import Optional

from ..data import Dataset
from ..sampler import NeighborSampler
from .node_loader import NodeLoader


class NeighborLoader(NodeLoader):
  """Reference: loader/neighbor_loader.py:27-113."""

  def __init__(self, data: Dataset, num_neighbors, input_nodes,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               with_weight: bool = False, strategy: str = 'random',
               collect_features: bool = True, to_device=None,
               seed: Optional[int] = None,
               node_budget: Optional[int] = None, dedup: str = 'auto',
               padded_window: Optional[int] = None,
               seed_labels_only: bool = False,
               frontier_caps=None, overflow_policy: str = 'raise',
               use_fused_hop=False, fused_hop_window: int = 512):
    # frontier_caps='auto': calibrate in-loader against THIS loader's
    # seed pool and batch size (sampler.calibrate), so no caller ever
    # hand-computes calibration widths
    if isinstance(frontier_caps, str):
      if frontier_caps != 'auto':
        raise ValueError(f'frontier_caps={frontier_caps!r}: pass a list '
                         "of per-hop caps or 'auto'")
      if isinstance(data.graph, dict):
        # raised here so 'auto' on a hetero dataset fails clearly, not
        # with an AttributeError inside estimate_frontier_caps
        raise ValueError(
            "frontier_caps='auto' is homogeneous-only; on hetero "
            'datasets pass the {edge_type: [per-hop caps]} dict from '
            'calibrate.estimate_hetero_frontier_caps')
      from ..sampler.calibrate import estimate_frontier_caps
      pool = (input_nodes[1] if isinstance(input_nodes, tuple)
              else input_nodes)
      frontier_caps = estimate_frontier_caps(
          data.graph, list(num_neighbors), batch_size, input_nodes=pool,
          seed=seed or 0)
    sampler = NeighborSampler(
        data.graph, num_neighbors, device=to_device, with_edge=with_edge,
        with_weight=with_weight, strategy=strategy, edge_dir=data.edge_dir,
        seed=seed, node_budget=node_budget, dedup=dedup,
        padded_window=padded_window, frontier_caps=frontier_caps,
        use_fused_hop=use_fused_hop, fused_hop_window=fused_hop_window)
    super().__init__(data, sampler, input_nodes, batch_size, shuffle,
                     drop_last, with_edge, collect_features, to_device,
                     seed, seed_labels_only=seed_labels_only,
                     overflow_policy=overflow_policy)
