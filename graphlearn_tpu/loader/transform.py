"""Batch containers + SamplerOutput -> batch transforms.

TPU-native port of /root/reference/graphlearn_torch/python/loader/transform.py
(to_data / to_hetero_data). The reference emits torch_geometric
``Data``/``HeteroData``; this framework is torch-free on the hot path, so
`Data`/`HeteroData` here are light pytree-friendly containers holding jax (or
numpy) arrays, **kept at their padded static shapes** with validity masks so a
jitted train step compiles once. ``to_pyg()`` bridges to torch_geometric when
torch is wanted (reference parity for examples).
"""
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

from ..sampler import HeteroSamplerOutput, SamplerOutput
from ..typing import EdgeType, NodeType


@dataclass
class Data:
  """A sampled mini-batch subgraph (PyG-Data-shaped, fixed-shape + masks).

  node: [cap_n] global node ids (FILL-padded); local index == position.
  node_mask / num_nodes: validity of `node`.
  edge_index: [2, cap_e] relabeled (row=message source, col=target).
  edge_mask: [cap_e] validity.
  x / y: optional features [cap_n, F] / labels [cap_n].
  edge_ids / edge_attr: optional per-edge payloads.
  batch: [B] seed node ids; batch_size: number of real seeds.
  """
  node: Any
  num_nodes: Any = None
  node_mask: Any = None
  edge_index: Any = None
  edge_mask: Any = None
  x: Any = None
  y: Any = None
  edge_ids: Any = None
  edge_attr: Any = None
  batch: Any = None
  batch_size: Optional[int] = None
  num_sampled_nodes: Any = None
  num_sampled_edges: Any = None
  metadata: Dict[str, Any] = field(default_factory=dict)

  # pytree-ish convenience
  def __getattr__(self, item):
    md = object.__getattribute__(self, 'metadata')
    if item in md:
      return md[item]
    raise AttributeError(item)

  def to_pyg(self):
    """Exact-size torch_geometric.data.Data (drops padding). Optional torch
    bridge — reference emits these natively (transform.py:26-57)."""
    import torch
    from torch_geometric.data import Data as PygData
    node = np.asarray(self.node)
    n = int(self.num_nodes) if self.num_nodes is not None else node.shape[0]
    emask = np.asarray(self.edge_mask) if self.edge_mask is not None else None
    ei = np.asarray(self.edge_index)
    if emask is not None:
      ei = ei[:, emask]
    data = PygData(edge_index=torch.as_tensor(np.ascontiguousarray(ei)))
    data.node = torch.as_tensor(node[:n])
    if self.x is not None:
      data.x = torch.as_tensor(np.asarray(self.x)[:n])
    if self.y is not None:
      data.y = torch.as_tensor(np.asarray(self.y)[:n])
    if self.edge_ids is not None:
      e = np.asarray(self.edge_ids)
      data.edge_ids = torch.as_tensor(e[emask] if emask is not None else e)
    if self.batch is not None:
      data.batch = torch.as_tensor(np.asarray(self.batch))
    data.batch_size = self.batch_size
    for k, v in self.metadata.items():
      try:
        data[k] = torch.as_tensor(np.asarray(v))
      except Exception:
        pass
    return data


@dataclass
class HeteroData:
  """Hetero mini-batch: per-type dicts of the same padded payloads."""
  node: Dict[NodeType, Any]
  num_nodes: Dict[NodeType, Any] = None
  edge_index: Dict[EdgeType, Any] = None
  edge_mask: Dict[EdgeType, Any] = None
  x: Dict[NodeType, Any] = None
  y: Dict[NodeType, Any] = None
  edge_ids: Dict[EdgeType, Any] = None
  edge_attr: Dict[EdgeType, Any] = None
  batch: Dict[NodeType, Any] = None
  batch_size: Optional[int] = None
  num_sampled_nodes: Any = None
  num_sampled_edges: Any = None
  metadata: Dict[str, Any] = field(default_factory=dict)

  def __getattr__(self, item):
    md = object.__getattribute__(self, 'metadata')
    if item in md:
      return md[item]
    raise AttributeError(item)


def to_data(out: SamplerOutput, node_feats=None, node_labels=None,
            edge_feats=None, node_mask=None, edge_index=None) -> Data:
  """SamplerOutput -> Data (reference: transform.py:26-57). Keeps padding.

  ``node_mask``/``edge_index`` may be passed precomputed (loaders derive
  them inside the jitted ops.collate_batch so no eager op touches pending
  sampler outputs); when absent they are derived here.
  """
  from .. import ops
  node = out.node
  if node_mask is None and out.num_nodes is not None:
    node_mask = ops.valid_mask(node, out.num_nodes)
  ei = edge_index
  if ei is None and out.row is not None:
    ei = ops.stack2(out.row, out.col)
  return Data(
      node=node, num_nodes=out.num_nodes, node_mask=node_mask,
      edge_index=ei, edge_mask=out.edge_mask, x=node_feats, y=node_labels,
      edge_ids=out.edge, edge_attr=edge_feats, batch=out.batch,
      batch_size=out.batch_size, num_sampled_nodes=out.num_sampled_nodes,
      num_sampled_edges=out.num_sampled_edges, metadata=dict(out.metadata))


def to_hetero_data(out: HeteroSamplerOutput, node_feats=None,
                   node_labels=None, edge_feats=None) -> HeteroData:
  """HeteroSamplerOutput -> HeteroData (reference: transform.py:60-136)."""
  from .. import ops
  ei = None
  if out.row is not None:
    # jitted per-etype stack: no eager op on pending sampler outputs
    ei = {et: ops.stack2(r, out.col[et]) for et, r in out.row.items()}
  return HeteroData(
      node=out.node, num_nodes=out.num_nodes, edge_index=ei,
      edge_mask=out.edge_mask, x=node_feats, y=node_labels,
      edge_ids=out.edge, batch=out.batch, batch_size=out.batch_size,
      num_sampled_nodes=out.num_sampled_nodes,
      num_sampled_edges=out.num_sampled_edges, metadata=dict(out.metadata))
