"""Epoch-as-a-program: scanned K-step sample -> collate -> train execution.

PERF.md establishes that on this rig the per-step DISPATCH is the dominant
wall-clock tax: device trace and wall clock diverge by 100-1000x once any
fetch lands, which is why `OverlappedTrainer` already collapsed 3
dispatches/step to 1. But an epoch is still ~steps dispatches plus
per-step host numpy (seed padding). The reference hides sampling latency
with producer processes/streams (dist_sampling_producer.py); on TPU the
native answer is to put the LOOP ITSELF on device: `ScanTrainer` executes
an epoch as ~ceil(steps/K) dispatches — a `lax.scan` over a static chunk
of K steps whose body is the existing fused sample+collate+train program
(`pipeline.FusedEpochTrainer` plumbing).

Design points:
  * The epoch's seed permutation is drawn ON DEVICE
    (`jax.random.permutation` over the input-seed array, reshaped to
    [steps, B] with a validity mask for the ragged tail). The host
    `SeedBatcher` remains the shuffle=False / mid-epoch-resume path; the
    device permutation is a different (but equally uniform) stream.
  * PRNG keys are derived INSIDE the scan body via
    `fold_in(base_key, count)` with the same host-counter discipline as
    `NeighborSampler._next_key` — global step g uses
    count = call_count_at_epoch_start + 1 + g, so a shuffle=False scanned
    epoch replays the per-step loader loop's draws EXACTLY (equivalence-
    tested), and the sampler's counter is advanced afterwards so later
    sampling continues the same stream.
  * Losses/accuracies come back as [K] scan outputs; the calibrated-caps
    overflow flag accumulates in the carry — zero host syncs inside the
    epoch. overflow_policy='recompute' is rejected exactly like
    `OverlappedTrainer` (it needs a per-batch host sync).
  * The train state is DONATED across chunk dispatches, so HBM stays
    flat at one state + one in-flight chunk. The state passed INTO
    run_epoch is consumed — use the returned state.

Dispatch budget per epoch: ceil(steps/K) chunk programs + 1 seed-matrix
program + 1 loss/acc concatenation = ceil(steps/K) + 2
(tests/test_scan_epoch.py asserts it via utils.count_dispatches).

Composes with every fused fast path (tree/block/padded sampling,
tree_dense / merge_dense models, seed_labels_only); the per-epoch
padded-table reseed runs between epochs like the plain loader
(`NodeLoader._begin_epoch`), and `_fused_args()` is re-fetched each epoch
so the chunks see the fresh table. On CPU the same programs run
unchanged (donation is a no-op there); only the dispatch-tax WIN
disappears, not correctness.

Usage:
    loader = NeighborLoader(ds, fanouts, idx, batch_size=B, shuffle=True,
                            drop_last=True, ...)
    trainer = ScanTrainer(loader, model, tx, num_classes, chunk_size=32)
    state, losses, accs = trainer.run_epoch(state)   # arrays stay on device

`DistScanTrainer` (below) is the DISTRIBUTED counterpart: the same
epoch-as-a-program contract over the collocated mesh loop, with the
scan body composing the sharded sampler's all_to_all hop engine, the
cached miss-only feature exchange, and the pmean'd data-parallel train
step inside ONE shard_map chunk program (PERF.md 'Scanned distributed
epoch'). The REMOTE (server-client) topology gets the same contract
from `distributed.RemoteScanTrainer` (docs/remote_scan.md): sampling
servers replay the counter-addressed stream into K-batch blocks and
the client scans a train-only chunk program over device-resident
blocks — same ceil(steps/K)+2 budget, same stage/ack hook seams, ack
and failover at chunk granularity.
"""
from typing import Optional

import numpy as np

from ..metrics import programs, spans
from ..utils.strict import strict_guards
from ..utils.trace import record_dispatch
from .node_loader import NodeLoader
from .pipeline import (_RECOMPUTE_MSG, DistFusedEpochTrainer,
                       FusedEpochTrainer)


def _resolve_tuned_config(trainer_name: str, dataset, chunk_size,
                          config, topology: str = 'local') -> int:
  """Resolve the chunk size from an explicit value or a tune-artifact
  ``config=`` (graphlearn_tpu/tune/, docs/tuning.md). An artifact is
  validated against the loader's dataset BY FINGERPRINT — a tuned
  config on a drifted graph refuses loudly, the recovery-snapshot
  refusal contract — and against the trainer's TOPOLOGY: a non-local
  artifact only fits the scenario it was tuned for (a remote
  block-stream assignment says nothing about a tiered exchange), while
  a local artifact's knobs (chunk K, kernel routing) stay generically
  acceptable everywhere. Duck-typed (validate_dataset +
  trainer_kwargs) so the loader package never imports tune/."""
  if config is not None:
    art_topo = getattr(config, 'topology', 'local') or 'local'
    if art_topo not in ('local', topology):
      raise ValueError(
          f'{trainer_name}: tune artifact was tuned for topology '
          f'{art_topo!r}, but this trainer runs the {topology!r} '
          'scenario — per-topology knobs do not transfer; re-run '
          f'graphlearn_tpu.tune(topology={topology!r}) '
          '(docs/tuning.md "Topology candidates")')
    config.validate_dataset(dataset, where=trainer_name)
    if chunk_size is None:
      chunk_size = config.trainer_kwargs()['chunk_size']
    if hasattr(config, 'apply_kernel_routing'):
      # kernel selection is an artifact choice, not an env var: stamp
      # the tuned gather-kernel routing onto the dataset's feature
      # store (tune/artifact.py; v1 artifacts carry kernels-off)
      config.apply_kernel_routing(dataset)
  return 32 if chunk_size is None else int(chunk_size)


def _recovery_config_for(trainer) -> dict:
  """The snapshot-fingerprint config (recovery/checkpoint.py): the
  flight grouping config PLUS every stream-determining knob it omits —
  sampler strategy/dedup/padded-window/weighting and a digest of the
  seed pool itself. The resume refusal must catch any drift that would
  change the replayed draws, not just the coarse shape (a
  padded_window added to an 'identical' loader samples a different
  stream at the same fanouts/batch/seed)."""
  import hashlib
  s = trainer._sampler
  cfg = trainer._flight_config()
  cfg.update(
      strategy=getattr(s, 'strategy', None),
      dedup=getattr(s, 'dedup', None),
      padded_window=getattr(s, 'padded_window', None),
      weighted=str(getattr(s, 'with_weight', None)),
      frontier_caps=str(getattr(s, 'frontier_caps', None)),
      seeds_sha=hashlib.sha1(
          np.ascontiguousarray(
              np.asarray(trainer.loader.input_seeds,
                         np.int64)).tobytes()).hexdigest()[:16])
  return cfg


class ScanTrainer(FusedEpochTrainer):
  """Executes an epoch as ~ceil(steps/K) scanned-chunk dispatches.

  Args:
    loader: a homogeneous NeighborLoader on the fused sampler path with
      device-resident features and labels (same scope as
      OverlappedTrainer).
    chunk_size: K, the static number of steps per scanned dispatch. The
      tail chunk (steps % K) compiles once more at its own length; pick
      K to divide the epoch when compile count matters.
    perm_seed: base seed for the ON-DEVICE epoch permutation (default:
      the loader's seed). Folded with the epoch index, so every epoch
      shuffles differently yet replayably.
  """

  _NAME = 'ScanTrainer'
  #: which tune() scenario this trainer runs — the config= topology
  #: compatibility check (_resolve_tuned_config; docs/tuning.md)
  _TOPOLOGY = 'local'

  # chunk-boundary staging hooks (storage/ subsystem, docs/storage.md;
  # recovery/ checkpointing, docs/recovery.md): ``stage_hook(
  # chunk_index, start, k)`` runs on the dispatch thread BEFORE each
  # chunk dispatch, ``ack_hook(chunk_index, start, k)`` right after it
  # — the seam the out-of-core pipeline and the ChunkCheckpointer
  # attach to without subclassing the epoch loop. Host-side only; the
  # loop runs under strict_guards, so a hook may fetch device arrays
  # EXPLICITLY (jax.device_get — the checkpointer's boundary capture)
  # but must never transfer implicitly or dispatch programs. Inside
  # ack_hook, ``self._chunk_carry`` exposes the boundary state.
  stage_hook = None
  ack_hook = None

  def __init__(self, loader: NodeLoader, model, tx, num_classes: int,
               chunk_size: Optional[int] = None,
               seed_labels_only: Optional[bool] = None,
               perm_seed: Optional[int] = None, config=None):
    import jax
    super().__init__(loader, model, tx, num_classes, seed_labels_only)
    # config= takes a tune artifact (graphlearn_tpu.tune(),
    # docs/tuning.md): dataset-fingerprint-validated, supplies the
    # tuned chunk K when chunk_size is not given explicitly
    chunk_size = _resolve_tuned_config(self._NAME, loader.data,
                                       chunk_size, config,
                                       topology=self._TOPOLOGY)
    if chunk_size < 1:
      raise ValueError(f'chunk_size must be >= 1, got {chunk_size}')
    self.chunk_size = int(chunk_size)
    self._shuffle = loader._batcher.shuffle
    self._drop_last = loader._batcher.drop_last
    if perm_seed is None:
      perm_seed = loader._batcher.seed or 0
    # tag the perm stream off fold_in(2**32 - 1): the sampler's step
    # keys are fold_in(PRNGKey(seed), count >= 1) on the SAME default
    # seed, and epoch e's permutation must not reuse step e's random
    # words; the tag sits where no host step counter can ever land
    self._perm_key = jax.random.fold_in(jax.random.PRNGKey(perm_seed),
                                        0xFFFFFFFF)
    self._epochs = 0        # folds into the perm key: fresh shuffle/epoch
    self._seeds_dev = None  # input seeds, uploaded once
    # program-observatory instrumentation under the record_dispatch
    # site names: compile/retrace detection (+ signature diffs) rides
    # every dispatch as one host-side cache-size read — the "ONE
    # executable per chunk length" contract becomes observable, and
    # retrace_budget can enforce it (metrics/programs.py)
    self._seed_fn = programs.instrument(self._build_seed_fn(),
                                        'epoch_seeds')
    self._chunk_fn = programs.instrument(self._build_chunk_fn(),
                                         'scan_chunk')
    self._concat_fn = programs.instrument(self._build_concat_fn(),
                                          'metrics_concat')

  # ------------------------------------------------------------- programs

  def _build_seed_fn(self):
    """ONE program for the epoch prologue: permutation draw + seed
    gather + [steps, B] reshape + ragged-tail validity mask."""
    import jax
    import jax.numpy as jnp
    batch = self._batch_size
    shuffle = self._shuffle

    def epoch_seeds(seeds, key, steps):
      n = seeds.shape[0]
      order = (jax.random.permutation(key, n) if shuffle
               else jnp.arange(n, dtype=jnp.int32))
      total = steps * batch
      if total <= n:       # drop_last: the permutation's prefix
        order = order[:total]
        mask = jnp.ones((total,), bool)
      else:                # ragged tail, masked invalid
        order = jnp.concatenate(
            [order, jnp.zeros((total - n,), order.dtype)])
        mask = jnp.arange(total) < n
      # pad slots carry node id 0 — the HOST loop's np.zeros padding —
      # so a scanned batch is byte-identical to sample_from_nodes' input
      seed_mat = jnp.where(mask, seeds[order], 0).reshape(steps, batch)
      return seed_mat, mask.reshape(steps, batch)

    return jax.jit(epoch_seeds, static_argnums=(2,))

  def _build_chunk_fn(self):
    """The scanned K-step program. Chunk position enters as a DEVICE
    scalar (dynamic_slice start), so every full chunk reuses one
    executable; only the tail length retraces. State and the overflow
    carry are donated — HBM stays flat across chunk dispatches."""
    import jax
    from jax import lax
    sample_collate = self._sample_collate
    train_step = self._train_step   # jit-of-jit: inlined into the scan

    def scan_epoch_chunk(state, ovf, fargs, feats, id2i, labels,
                         seed_mat, mask_mat, base_key, count0, start, k):
      seeds_k = lax.dynamic_slice_in_dim(seed_mat, start, k, axis=0)
      masks_k = lax.dynamic_slice_in_dim(mask_mat, start, k, axis=0)
      # the sampler's fold_in stream: global step g -> count0 + g
      counts_k = count0 + start + lax.iota(seed_mat.dtype, k)

      def body(carry, xs):
        state, ovf = carry
        seeds, smask, count = xs
        key = jax.random.fold_in(base_key, count)
        batch, overflow = sample_collate(fargs, feats, id2i, labels,
                                         seeds, smask, key)
        state, loss, acc = train_step(state, batch)
        return (state, ovf | overflow), (loss, acc)

      (state, ovf), (losses, accs) = lax.scan(
          body, (state, ovf), (seeds_k, masks_k, counts_k))
      return state, ovf, losses, accs

    return jax.jit(scan_epoch_chunk, static_argnums=(11,),
                   donate_argnums=(0, 1))

  def _build_concat_fn(self):
    """One program concatenating the per-chunk [K] loss/acc outputs."""
    import jax
    import jax.numpy as jnp

    def epoch_metrics_concat(losses, accs):
      return jnp.concatenate(losses), jnp.concatenate(accs)

    return jax.jit(epoch_metrics_concat)

  # ----------------------------------------------------------------- epoch

  def _epoch_steps(self) -> int:
    # the batcher owns the full-batch/ragged-tail arithmetic — one
    # source of truth keeps the scanned step count equal to the
    # per-step loop's by construction
    return len(self.loader._batcher)

  def run_epoch(self, state, max_steps: Optional[int] = None,
                start_step: int = 0, resume_overflow: bool = False):
    """One scanned epoch. Returns ``(state, losses, accs)`` with losses
    and accs [steps]-shaped device arrays — fetch once, after the epoch.

    The input ``state`` is DONATED to the first chunk dispatch and must
    not be reused; train on the returned state. ``max_steps`` truncates
    the epoch to exactly that many optimizer updates (the permutation is
    still drawn for the full epoch, so truncation never changes which
    seeds later steps would have seen).

    ``start_step`` (a chunk boundary — a multiple of ``chunk_size``)
    resumes THIS epoch mid-flight: the seed matrix is drawn for the
    full epoch as usual and the scan starts at that boundary, so with
    the sampler counter and epoch index restored the remaining chunks
    replay BIT-IDENTICALLY (the recovery/ resume path — callers should
    go through ``recovery.ChunkCheckpointer.resume_epoch``, which also
    restores the counters). ``resume_overflow`` seeds the overflow
    carry with the flag the interrupted prefix had accumulated.
    Returned losses/accs then cover only ``[start_step, steps)``."""
    import jax
    import jax.numpy as jnp

    from ..metrics import flight
    guarded, recompute = self.loader._overflow_epoch_start()
    if recompute:
      raise ValueError(_RECOMPUTE_MSG)
    self.loader._begin_epoch()
    epoch_no = self._epochs
    full_steps = self._epoch_steps()
    steps = full_steps
    truncated = False
    if max_steps is not None and max_steps < steps:
      steps, truncated = max_steps, True
    if start_step:
      if start_step % self.chunk_size != 0:
        raise ValueError(f'start_step={start_step} is not a chunk '
                         f'boundary (chunk_size={self.chunk_size}) — '
                         'resume only at the boundaries checkpoints '
                         'are taken at')
      if not 0 <= start_step < steps:
        raise ValueError(f'start_step={start_step} outside this '
                         f"epoch's {steps} steps")
    # the epoch span is current for the whole program region: chunk
    # spans (and any spans the model hooks open) parent under it. Both
    # brackets open AFTER the step arithmetic (and, on the zero-step
    # path, after the empty-result device work) so nothing between
    # open and close can raise — a flight record opened before the
    # resume-argument raises above would stay permanently open, and an
    # attached span leaked by a prologue exception would mis-parent
    # the thread's spans for the rest of the process
    if steps <= 0:
      # zero-batch epochs still record (the per-step loop writes a
      # steps=0 line) so flight epoch counts line up across drivers
      empty = jnp.zeros((0,), jnp.float32)
      flight_tok = flight.epoch_begin()
      epoch_span = spans.begin('epoch.run', emitter=self._NAME,
                               epoch=epoch_no)
      spans.end(epoch_span, steps=0, completed=True)
      flight.epoch_end(flight_tok, emitter=self._NAME, epoch=epoch_no,
                       steps=0, config=self._flight_config(),
                       extra={'chunk_size': self.chunk_size,
                              'truncated': truncated})
      return state, empty, empty

    flight_tok = flight.epoch_begin()
    epoch_span = spans.begin('epoch.run', emitter=self._NAME,
                             epoch=epoch_no)
    completed = False
    # reset BEFORE the body: a failure in its staging prologue (fused
    # args rebuild, carry device_puts) must read as the resume point,
    # not the previous epoch's stale count — a resume that fails still
    # records the chunk boundary it reached
    self._steps_dispatched = start_step
    try:
      state, losses, accs, ovf = self._run_epoch_body(
          state, steps, full_steps, start_step=start_step,
          resume_overflow=resume_overflow)
      completed = True
      if guarded:
        # same contract as OverlappedTrainer: natural epoch end applies
        # overflow_policy; a max_steps break leaves the
        # device-accumulated flag to loader.check_overflow()
        self.loader._ovf_accum = ovf
        if not truncated:
          self.loader._finish_epoch_overflow()
    finally:
      # one JSONL flight record per epoch (metrics/flight.py): pure
      # host counter deltas + wall — written OUTSIDE strict_guards,
      # zero extra dispatches, zero device fetches. A mid-scan failure
      # still records (completed=False), with the un-advanced epoch
      # number the re-run will redraw and the steps the scan actually
      # dispatched (chunk-granular), matching the per-step emitters'
      # delivered-batch semantics
      spans.end(epoch_span,
                steps=(steps if completed else
                       getattr(self, '_steps_dispatched', 0)),
                completed=completed)
      flight.epoch_end(flight_tok, emitter=self._NAME, epoch=epoch_no,
                       steps=(steps if completed else
                              getattr(self, '_steps_dispatched', 0)),
                       completed=completed,
                       config=self._flight_config(),
                       extra={'chunk_size': self.chunk_size,
                              'truncated': truncated,
                              'start_step': start_step})
    return state, losses, accs

  def _run_epoch_body(self, state, steps, full_steps, start_step=0,
                      resume_overflow=False):
    """The epoch program proper: seed draw + scanned chunks. Split out
    so run_epoch owns only the guard/flight bracketing."""
    import jax
    if self._seeds_dev is None:
      self._seeds_dev = jax.device_put(
          np.asarray(self.loader.input_seeds, dtype=np.int32))
    # _epochs advances only on SUCCESS (below, with _call_count): a
    # failed epoch's re-run must redraw the SAME permutation, matching
    # the un-advanced sampler key stream
    perm_key = jax.random.fold_in(self._perm_key, self._epochs)

    # graph arrays re-fetched each epoch: the padded-table reseed in
    # _begin_epoch must reach the chunks (lazy rebuild in _fused_args)
    fargs = self._sampler._fused_args()
    base_key = self._sampler._key
    # chunk-position scalars enter as EXPLICIT device_puts: inside the
    # strict_guards region (GLT_STRICT=1: transfer_guard('disallow') +
    # checking_leaks) every implicit host->device transfer — a stray
    # numpy arg, an eager op minting a constant — raises, so the epoch
    # region provably contains nothing but all-device program dispatches
    count0 = jax.device_put(np.int32(self._sampler._call_count + 1))
    # a resume seeds the carry with the interrupted prefix's flag — a
    # pre-crash overflow must still fire the epoch-end policy
    ovf = jax.device_put(np.asarray(bool(resume_overflow)))
    losses, accs = [], []
    start = start_step
    with strict_guards():
      record_dispatch('epoch_seeds')
      seed_mat, mask_mat = self._seed_fn(self._seeds_dev, perm_key,
                                         full_steps)
      while start < steps:
        k = min(self.chunk_size, steps - start)
        if self.stage_hook is not None:
          self.stage_hook(start // self.chunk_size, start, k)
        record_dispatch('scan_chunk')
        # chunk-level span: host clocks only (the dispatch is async, so
        # dur is dispatch wall, not device compute — PERF.md's point)
        with spans.span('epoch.chunk', start=start, k=k):
          state, ovf, loss_k, acc_k = self._chunk_fn(
              state, ovf, fargs, self._feats, self._id2i, self._labels,
              seed_mat, mask_mat, base_key, count0,
              jax.device_put(np.int32(start)), k)
        losses.append(loss_k)
        accs.append(acc_k)
        self._steps_dispatched = start + k
        if self.ack_hook is not None:
          # boundary carry for the recovery seam (recovery/checkpoint):
          # valid ONLY inside the hook call — the next chunk dispatch
          # donates state/ovf. Hooks may device_get it (explicit
          # fetches pass the strict transfer guard); they must never
          # fetch implicitly or dispatch programs.
          self._chunk_carry = dict(state=state, ovf=ovf, losses=losses,
                                   accs=accs, steps=steps,
                                   full_steps=full_steps,
                                   start_step=start_step)
          self.ack_hook(start // self.chunk_size, start, k)
        start += k
      if len(losses) > 1:
        record_dispatch('metrics_concat')
        losses, accs = self._concat_fn(losses, accs)
      else:
        losses, accs = losses[0], accs[0]
    # keep the host fold_in stream aligned with what the device consumed
    # (checkpoint/resume and any later per-step sampling continue it)
    self._sampler._call_count += steps
    self._epochs += 1
    return state, losses, accs, ovf

  def _flight_config(self) -> dict:
    """Static epoch-program configuration, fingerprinted into flight
    records so a postmortem can group epochs by config across runs."""
    return dict(trainer=self._NAME, batch_size=self._batch_size,
                chunk_size=self.chunk_size,
                fanouts=list(self._sampler.num_neighbors),
                shuffle=self._shuffle, drop_last=self._drop_last,
                num_classes=self.num_classes,
                seed=self.loader._batcher.seed)

  # -------------------------------------------------- recovery protocol
  # (recovery/checkpoint.py ChunkCheckpointer — docs/recovery.md)

  def _recovery_config(self) -> dict:
    return _recovery_config_for(self)

  def _recovery_capture(self, carry):
    """(meta_extra, device_arrays_extra) a boundary snapshot must
    carry beyond the train state: the sampler stream position (base
    key + counter — it still holds the EPOCH-START value while the
    epoch is in flight) and, for padded-window sampling, the
    padded-table reseed counters."""
    meta = {'sampler': self._sampler.state_dict()}
    s = self._sampler
    if getattr(s, 'padded_window', None) is not None:
      meta['padded'] = {
          'seed': int(s._padded_seed),
          'epochs_started': int(getattr(self.loader, '_epochs_started',
                                        0))}
    return meta, {}

  def _recovery_load(self, meta, arrays):
    """Rewind this (typically fresh) trainer to the snapshot's epoch:
    sampler stream, epoch index, and — for padded-window sampling —
    the padded-table reseed counters, positioned so run_epoch's own
    ``_begin_epoch`` lands the table on exactly the crashed epoch's
    seed (no refresh for a first epoch, one refresh otherwise)."""
    del arrays   # the local trainer carries no extra device state
    self._sampler.load_state_dict(meta['sampler'])
    self._epochs = int(meta['epoch'])
    pad = meta.get('padded')
    if pad:
      s = self._sampler
      es = int(pad['epochs_started'])
      if es <= 1:
        self.loader._epochs_started = 0
        s._padded_seed = int(pad['seed'])
      else:
        self.loader._epochs_started = es - 1
        s._padded_seed = int(pad['seed']) - 1
      # drop any cached padded table so the resumed epoch rebuilds it
      # from the restored seed
      s._garrs.pop(('padded', id(s._get_graph())), None)

  def _recovery_advance(self, meta):
    """A COMPLETED-epoch snapshot resumes as 'advance past it': the
    stream/epoch counters land where a normal epoch end would leave
    them, and the padded-table counters keep the values captured
    DURING that epoch (the next run_epoch's ``_begin_epoch`` then
    refreshes onto the FOLLOWING epoch's seed, matching the
    uninterrupted multi-epoch stream). No stats restore: a finished
    epoch already published its accumulators before the crash."""
    self._sampler.load_state_dict(meta['sampler'])
    self._sampler._call_count += int(meta['steps'])
    self._epochs = int(meta['epoch']) + 1
    pad = meta.get('padded')
    if pad:
      s = self._sampler
      self.loader._epochs_started = int(pad['epochs_started'])
      s._padded_seed = int(pad['seed'])
      s._garrs.pop(('padded', id(s._get_graph())), None)


class DistScanTrainer(DistFusedEpochTrainer):
  """Distributed epoch-as-a-program: one epoch of the COLLOCATED mesh
  loop as ``ceil(steps/K) + 2`` dispatches.

  The per-step distributed loop pays >= 2 program dispatches per batch
  (sample program + collate, plus the feature/label gathers and the
  train step) and a host numpy seed slice each step — on this rig's
  remote-dispatch runtime the dominant wall-clock tax (PERF.md). Here
  the scanned chunk is ONE jitted shard_map program whose ``lax.scan``
  body composes, per shard and per step:

    per-shard seed slice (dynamic_slice into the on-device [steps, B]
    seed matrix) -> fold_in key replay (``split(fold_in(base, count),
    P)[shard]`` — exactly DistNeighborSampler._keys_for, so a
    shuffle=False scanned epoch replays the per-step loop's draws
    BIT-IDENTICALLY) -> the sampler's multi-hop all_to_all exchange
    (_homo_hop_loop / _hetero_engine) -> DistFeature's cached miss-only
    lookup with the [4] stats row in the scan carry (publish_stats()
    still fetches once per epoch) -> label gather -> the pmean'd
    data-parallel train step. The calibrated-caps overflow flag
    (already psum-replicated by the engine) ORs into the carry.

  Collocated-mesh only: remote/server-client topologies run their own
  scanned path (``distributed.RemoteScanTrainer`` — the chunk-staged
  hybrid over server-produced K-batch blocks, docs/remote_scan.md;
  mp-worker loaders keep the per-step loop), and
  ``overflow_policy='recompute'`` is rejected (per-batch host sync).
  On failover/restart the scan carry and cache state are rebuilt —
  failover granularity is the CHUNK, not the batch.

  Args:
    loader: collocated DistNeighborLoader (homo or hetero) with
      feature collection and node labels.
    chunk_size: K steps per scanned dispatch (the tail chunk compiles
      once more at its own length).
    perm_seed: base seed for the ON-DEVICE epoch permutation (default:
      the loader's seed). The host loader's numpy shuffle stream is
      left untouched; shuffle=False epochs replay the host order
      exactly (arange + cyclic tail padding).

  Usage:
      trainer = DistScanTrainer(loader, model, tx, num_classes, K)
      state, losses, accs = trainer.run_epoch(state)
  """

  _NAME = 'DistScanTrainer'
  _TOPOLOGY = 'dist'

  # chunk-boundary staging hooks — same contract as ScanTrainer's:
  # host-side callables around each chunk dispatch, the attachment
  # point for per-shard staging pipelines (docs/storage.md documents
  # the distributed tier model and its current scope)
  stage_hook = None
  ack_hook = None

  def __init__(self, loader, model, tx, num_classes: int,
               chunk_size: Optional[int] = None,
               seed_labels_only: Optional[bool] = None,
               perm_seed: Optional[int] = None, config=None):
    import jax
    super().__init__(loader, model, tx, num_classes, seed_labels_only)
    # config= takes a tune artifact (docs/tuning.md): topology-checked
    # ('dist' or a generic local artifact) and validated against the
    # DistGraph's stacked-partition fingerprint (tune/artifact.py)
    chunk_size = _resolve_tuned_config(self._NAME, loader.data,
                                       chunk_size, config,
                                       topology=self._TOPOLOGY)
    if chunk_size < 1:
      raise ValueError(f'chunk_size must be >= 1, got {chunk_size}')
    self.chunk_size = int(chunk_size)
    if perm_seed is None:
      perm_seed = loader.seed or 0
    # tag the perm stream off fold_in(2**32 - 1): the sampler's step
    # keys are split(fold_in(PRNGKey(seed), count >= 1), P) on the SAME
    # default seed — the tag sits where no step counter can ever land
    self._perm_key = jax.random.fold_in(jax.random.PRNGKey(perm_seed),
                                        0xFFFFFFFF)
    self._epochs = 0        # folds into the perm key: fresh shuffle/epoch
    self._seeds_dev = None  # input seeds, uploaded once
    self._shard_tree, self._repl_tree, self._sc_body = \
        self._make_sample_collate()
    self._seed_fn = programs.instrument(self._build_seed_fn(),
                                        'dist_epoch_seeds')
    self._chunk_fns = {}    # k (static chunk length) -> program
    self._concat_fn = programs.instrument(self._build_concat_fn(),
                                          'dist_metrics_concat')

  # ------------------------------------------------------------- programs

  def _build_seed_fn(self):
    """ONE program for the epoch prologue: permutation draw + seed
    gather + [P, steps, B] reshape + ragged-tail validity mask.
    Replays DistLoader._index_blocks exactly for shuffle=False: blocks
    are row-major [steps, P, B] slices of the epoch order, and the
    short final block is padded by CYCLING the order (np.resize) with
    the pad slots masked invalid.

    Outputs are committed to the chunk program's [P, ...] mesh sharding
    HERE (out_shardings) — otherwise the matrices land on one device
    and the first chunk dispatch pays a hidden device-to-device
    reshard, which GLT_STRICT's transfer_guard('disallow') rejects."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    batch = self._batch_size
    nparts = self._nparts
    shuffle = self.loader.shuffle
    sharded = NamedSharding(self.mesh, P(self._axes))

    def epoch_seeds(seeds, key, steps):
      n = seeds.shape[0]
      order = (jax.random.permutation(key, n) if shuffle
               else jnp.arange(n, dtype=jnp.int32))
      total = steps * nparts * batch
      if total <= n:       # drop_last: the permutation's prefix
        ext = order[:total]
        maskf = jnp.ones((total,), bool)
      else:                # ragged tail: cyclic pad, masked invalid
        pad = order[jnp.arange(total - n, dtype=jnp.int32) % n]
        ext = jnp.concatenate([order, pad])
        maskf = jnp.arange(total) < n
      seed_mat = seeds[ext].reshape(steps, nparts, batch)
      mask_mat = maskf.reshape(steps, nparts, batch)
      # leading axis = partition: the chunk program shards on dim 0
      return (seed_mat.transpose(1, 0, 2),
              mask_mat.transpose(1, 0, 2))

    return jax.jit(epoch_seeds, static_argnums=(2,),
                   out_shardings=(sharded, sharded))

  def _chunk_fn_for(self, k: int):
    """The scanned K-step shard_map program (built per static chunk
    length; the chunk position enters as a DEVICE scalar so every full
    chunk reuses one executable). State and the overflow/stats carry
    are donated — HBM stays flat across chunk dispatches."""
    if k in self._chunk_fns:
      return self._chunk_fns[k]
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map
    ax = self._axes
    mesh = self.mesh
    nparts = self._nparts
    sc_body = self._sc_body
    dp = self._dp_step_body

    def body(shard_tree, repl_tree, stats, params, opt_state, stepc,
             ovf, seed_mat, mask_mat, base_key, count0, start):
      views = jax.tree.map(lambda a: a[0], shard_tree)
      stats_rows = jax.tree.map(lambda a: a[0], stats)
      seeds_k = lax.dynamic_slice_in_dim(seed_mat[0], start, k, 0)
      masks_k = lax.dynamic_slice_in_dim(mask_mat[0], start, k, 0)
      # the sampler's fold_in stream: global step g -> count0 + g
      counts_k = count0 + start + lax.iota(jnp.int32, k)
      # this shard's linear partition index, row-major over the axis
      # order — matches the [P, ...] leading-axis sharding and the
      # per-step path's keys[p] selection
      my = jnp.int32(0)
      for a in ax:
        my = my * mesh.shape[a] + lax.axis_index(a)

      def step(carry, xs):
        params, opt_state, stepc, ovf, srows = carry
        seeds, smask, count = xs
        keys = jax.random.split(jax.random.fold_in(base_key, count),
                                nparts)
        batch, overflow, srows = sc_body(views, repl_tree, srows, seeds,
                                         smask, keys[my])
        state, loss, acc = dp(
            self._train_state_cls(params, opt_state, stepc), batch)
        return (state.params, state.opt_state, state.step,
                ovf | overflow, srows), (loss, acc)

      (params, opt_state, stepc, ovf, srows), (losses, accs) = lax.scan(
          step, (params, opt_state, stepc, ovf, stats_rows),
          (seeds_k, masks_k, counts_k))
      return (params, opt_state, stepc, ovf,
              jax.tree.map(lambda a: a[None], srows), losses, accs)

    sh = jax.tree.map(lambda _: P(ax), self._shard_tree)
    rp = jax.tree.map(lambda _: P(), self._repl_tree)
    stats_spec = (P(ax) if not self.is_hetero
                  else {t: P(ax) for t in self._feat_types})
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(sh, rp, stats_spec, P(), P(), P(), P(), P(ax), P(ax),
                  P(), P(), P()),
        out_specs=(P(), P(), P(), P(), stats_spec, P(), P()),
        check_replication=False)
    # donate the train state + the overflow/stats carries (args 3-6 +
    # 2); the graph/feature tables and seed matrix are reused across
    # chunks and must NOT be donated
    jfn = programs.instrument(jax.jit(fn, donate_argnums=(2, 3, 4, 5, 6)),
                              'dist_scan_chunk')
    self._chunk_fns[k] = jfn
    return jfn

  def _build_concat_fn(self):
    """One program concatenating the per-chunk [K] loss/acc outputs."""
    import jax
    import jax.numpy as jnp

    def epoch_metrics_concat(losses, accs):
      return jnp.concatenate(losses), jnp.concatenate(accs)

    return jax.jit(epoch_metrics_concat)

  # ----------------------------------------------------------------- epoch

  def run_epoch(self, state, max_steps: Optional[int] = None,
                start_step: int = 0, resume_overflow: bool = False):
    """One scanned distributed epoch. Returns ``(state, losses, accs)``
    with losses/accs [steps]-shaped replicated device arrays — fetch
    once, after the epoch.

    The input ``state`` is DONATED to the first chunk dispatch; train
    on the returned state. ``max_steps`` truncates the epoch to exactly
    that many optimizer updates (the permutation is still drawn for the
    full epoch, so truncation never changes which seeds later steps
    would have seen). ``start_step``/``resume_overflow`` resume THIS
    epoch at a chunk boundary — the recovery seam (see
    ``ScanTrainer.run_epoch``; go through ``recovery.
    ChunkCheckpointer.resume_epoch``, which also restores the sampler
    counter, epoch index and feature-cache stats rows)."""
    import jax
    import jax.numpy as jnp

    from ..metrics import flight
    guarded, recompute = self.loader._overflow_epoch_start()
    if recompute:   # unreachable after __init__'s check; kept for parity
      raise ValueError(_RECOMPUTE_MSG)
    epoch_no = self._epochs
    full_steps = len(self.loader)
    steps = full_steps
    truncated = False
    if max_steps is not None and max_steps < steps:
      steps, truncated = max_steps, True
    if start_step:
      if start_step % self.chunk_size != 0:
        raise ValueError(f'start_step={start_step} is not a chunk '
                         f'boundary (chunk_size={self.chunk_size})')
      if not 0 <= start_step < steps:
        raise ValueError(f'start_step={start_step} outside this '
                         f"epoch's {steps} steps")
    # both brackets open after the step arithmetic (and the zero-step
    # path's empty-result device work): every statement between open
    # and close is a try/finally body or a bracket call, so every path
    # provably ends them — see ScanTrainer.run_epoch
    if steps <= 0:
      # mirror the per-step loop's zero-batch epoch (DistLoader.__iter__
      # closes the overflow guard and STILL publishes in its finally):
      # the feature-stats accumulators a prior template iteration left
      # on device must drain this epoch too, or they eventually wrap
      empty = jnp.zeros((0,), jnp.float32)
      flight_tok = flight.epoch_begin()
      epoch_span = spans.begin('epoch.run', emitter=self._NAME,
                               epoch=epoch_no)
      try:
        if guarded and not truncated:
          self.loader._finish_epoch_overflow()
      finally:
        # publish BEFORE the flight record (feature fields must
        # bit-match the freshly published counters) but never at the
        # cost of the record or the attached span: a raising fetch
        # must still end both (a leaked attached span mis-parents
        # every later span on this thread)
        try:
          self.loader._publish_feature_stats()
        finally:
          # zero-batch epochs still record, like the per-step loop's
          # steps=0 line, so flight epoch counts line up across drivers
          spans.end(epoch_span, steps=0, completed=True)
          flight.epoch_end(flight_tok, emitter=self._NAME,
                           epoch=epoch_no, steps=0,
                           config=self._flight_config(),
                           extra={'chunk_size': self.chunk_size,
                                  'truncated': truncated})
      return state, empty, empty

    flight_tok = flight.epoch_begin()
    epoch_span = spans.begin('epoch.run', emitter=self._NAME,
                             epoch=epoch_no)
    completed = False
    # reset BEFORE the body: a failure in its staging prologue (the
    # replicated-carry device_puts, program retraces) must read as the
    # resume point, not the previous epoch's stale count — a resume
    # that fails still records the chunk boundary it reached
    self._steps_dispatched = start_step
    try:
      state, losses, accs, ovf = self._run_epoch_body(
          state, steps, full_steps, start_step=start_step,
          resume_overflow=resume_overflow)
      completed = True
      if guarded:
        # same contract as the local trainers: natural epoch end
        # applies overflow_policy; a max_steps break leaves the flag to
        # loader.check_overflow()
        self.loader._ovf_accum = ovf
        if not truncated:
          self.loader._finish_epoch_overflow()
    finally:
      # also when the epoch fails mid-scan or the overflow guard raises
      # — the per-step loop's finally-publish contract (the accumulator
      # must drain per epoch; a dropped partial-epoch accumulator
      # publishes zeros). Flight record AFTER publish_stats: the
      # feature fields must bit-match the freshly published
      # dist_feature.* counters. Host deltas only — outside
      # strict_guards, zero extra dispatches; a failed epoch records
      # completed=False under the un-advanced epoch number its re-run
      # will redraw. The publish is itself a device fetch that can
      # raise against a broken device — the span and flight record
      # (the postmortem trail for exactly that failure) must still
      # close, so they sit in an inner finally
      try:
        self.loader._publish_feature_stats()
      finally:
        spans.end(epoch_span,
                  steps=(steps if completed else
                         getattr(self, '_steps_dispatched', 0)),
                  completed=completed)
        flight.epoch_end(flight_tok, emitter=self._NAME, epoch=epoch_no,
                         steps=(steps if completed else
                                getattr(self, '_steps_dispatched', 0)),
                         completed=completed,
                         config=self._flight_config(),
                         extra={'chunk_size': self.chunk_size,
                                'truncated': truncated,
                                'start_step': start_step})
    return state, losses, accs

  def _run_epoch_body(self, state, steps, full_steps, start_step=0,
                      resume_overflow=False):
    """The mesh epoch program proper: replicated carry staging + seed
    draw + scanned chunks. Split out so run_epoch owns only the
    guard/publish/flight bracketing."""
    import jax

    from jax.sharding import NamedSharding, PartitionSpec
    repl = NamedSharding(self.mesh, PartitionSpec())
    if self._seeds_dev is None:
      # committed to the mesh (replicated) at upload: the seed program
      # runs on the mesh, and an uncommitted single-device array would
      # be broadcast IMPLICITLY at its first dispatch — a hidden
      # device-to-device transfer GLT_STRICT's transfer guard rejects
      self._seeds_dev = jax.device_put(
          np.asarray(self.loader.input_seeds, dtype=np.int32), repl)
    # _epochs advances only on SUCCESS (below, with _call_count): a
    # failed epoch's re-run must redraw the SAME permutation or the
    # chunk-granularity failover story (docs/failure_model.md) can't
    # reproduce the completed chunks' seed matrix
    perm_key = jax.device_put(
        jax.random.fold_in(self._perm_key, self._epochs), repl)

    base_key = jax.device_put(self._sampler._key, repl)
    stats = ({t: self._feat[t]._stats_dev() for t in self._feat_types}
             if self.is_hetero else self._feat._stats_dev())
    # commit the replicated carry leaves explicitly: a fresh (host /
    # single-device) state and the chunk program's replicated outputs
    # must present the SAME sharding signature, or every epoch's first
    # chunk retraces (sharding is part of the jit cache key). The
    # chunk-position scalars are explicit device_puts too: inside the
    # strict_guards region (GLT_STRICT=1: transfer_guard('disallow') +
    # checking_leaks) any implicit host->device transfer raises, so the
    # epoch region provably dispatches only all-device program args
    count0 = jax.device_put(np.int32(self._sampler._call_count + 1),
                            repl)
    params, opt_state, stepc, ovf = jax.device_put(
        (state.params, state.opt_state, state.step,
         np.asarray(bool(resume_overflow))), repl)

    def stats_back(tree):
      # hand the carried accumulators back to the stores AFTER EVERY
      # chunk (not just at epoch end): each chunk DONATES its stats
      # input, so the store must never be left referencing a deleted
      # buffer — a mid-epoch stats() read, or a later publish after an
      # aborted epoch, would otherwise raise 'Array has been deleted'
      if self.is_hetero:
        for t in self._feat_types:
          self._feat[t]._stats = tree[t]
      else:
        self._feat._stats = tree

    losses, accs = [], []
    start = start_step
    try:
      with strict_guards():
        seed_mat, mask_mat = self._epoch_prologue(
            perm_key, full_steps, steps, start_step, base_key, count0)
        while start < steps:
          k = min(self.chunk_size, steps - start)
          if self.stage_hook is not None:
            self.stage_hook(start // self.chunk_size, start, k)
          with spans.span('epoch.chunk', start=start, k=k):
            params, opt_state, stepc, ovf, stats, loss_k, acc_k = \
                self._dispatch_chunk(
                    start // self.chunk_size, k, stats, params,
                    opt_state, stepc, ovf, seed_mat, mask_mat, base_key,
                    count0, jax.device_put(np.int32(start), repl))
          stats_back(stats)
          losses.append(loss_k)
          accs.append(acc_k)
          self._steps_dispatched = start + k
          if self.ack_hook is not None:
            # boundary carry for the recovery seam — valid only inside
            # the hook call (the next chunk dispatch donates the state
            # and stats buffers); see ScanTrainer
            self._chunk_carry = dict(
                state=self._train_state_cls(params, opt_state, stepc),
                ovf=ovf, stats=stats, losses=losses, accs=accs,
                steps=steps, full_steps=full_steps,
                start_step=start_step)
            self.ack_hook(start // self.chunk_size, start, k)
          start += k
        if len(losses) > 1:
          record_dispatch('dist_metrics_concat')
          losses, accs = self._concat_fn(losses, accs)
        else:
          losses, accs = losses[0], accs[0]
    except BaseException:
      # the in-flight chunk's donated stats input is gone; drop the
      # partial epoch's counts rather than leave a dead reference
      stats_back({t: None for t in self._feat_types}
                 if self.is_hetero else None)
      raise
    # keep the host fold_in stream aligned with what the device consumed
    # (checkpoint/resume and any later per-step sampling continue it)
    self._sampler._call_count += steps
    self._epochs += 1
    return (self._train_state_cls(params, opt_state, stepc),
            losses, accs, ovf)

  # ---------------------------------------------- exchange-aware seams
  # The two points where the epoch program touches the feature-storage
  # topology, split out so the OVERSUBSCRIBED distributed trainer
  # (storage/dist_scan.py TieredDistScanTrainer) can fold the
  # miss-exchange replay into the prologue and stage per-chunk slabs
  # without re-owning the guard/publish/flight bracketing above. Both
  # run INSIDE strict_guards: anything host-resident they feed the
  # programs must be an explicit device_put.

  def _epoch_prologue(self, perm_key, full_steps, steps, start_step,
                      base_key, count0):
    """ONE prologue dispatch -> (seed_mat, mask_mat) committed to the
    chunk program's mesh sharding. The base program is the seed
    permutation alone; the tiered override extends it with the id-only
    sampler replay whose fetched row matrix becomes the per-chunk
    miss-exchange program (same dispatch, same budget)."""
    del steps, start_step  # the base prologue needs no plan extent
    record_dispatch('dist_epoch_seeds')
    return self._seed_fn(self._seeds_dev, perm_key, full_steps)

  def _dispatch_chunk(self, c, k, stats, params, opt_state, stepc, ovf,
                      seed_mat, mask_mat, base_key, count0, start_dev):
    """Dispatch chunk ``c`` (k steps). The tiered override uploads the
    chunk's staged exchange slabs (explicit device_puts) and routes
    through its slab-aware program; the outputs contract is shared."""
    del c  # the all-HBM chunk program has no per-chunk staging
    record_dispatch('dist_scan_chunk')
    return self._chunk_fn_for(k)(
        self._shard_tree, self._repl_tree, stats, params, opt_state,
        stepc, ovf, seed_mat, mask_mat, base_key, count0, start_dev)

  def _flight_config(self) -> dict:
    """Static epoch-program configuration for flight-record grouping
    (mesh shape included: a resharded restart is a different config)."""
    return dict(trainer=self._NAME, batch_size=self._batch_size,
                chunk_size=self.chunk_size,
                fanouts=self._sampler.num_neighbors,
                shuffle=self.loader.shuffle,
                num_partitions=self._nparts,
                mesh={a: self.mesh.shape[a] for a in self._axes},
                hetero=self.is_hetero, num_classes=self.num_classes,
                seed=self.loader.seed)

  # -------------------------------------------------- recovery protocol
  # (recovery/checkpoint.py ChunkCheckpointer — docs/recovery.md)

  def _recovery_config(self) -> dict:
    return _recovery_config_for(self)

  def _recovery_capture(self, carry):
    """Beyond the train state: the sampler stream position and the
    feature-cache [P, 4] stats accumulators riding the scan carry —
    restoring them keeps the resumed epoch's ``publish_stats`` EXACT,
    not just its losses."""
    meta = {'sampler': self._sampler.state_dict()}
    stats = carry.get('stats')
    if self.is_hetero:
      dev = {f'stats:{t}': stats[t] for t in self._feat_types}
    else:
      dev = {'stats:': stats}
    return meta, dev

  def _recovery_load(self, meta, arrays):
    """Rewind a (typically fresh) trainer to the snapshot's epoch:
    sampler stream, epoch index, and the stores' stats accumulators
    (committed back to the mesh sharding ``_stats_dev`` uses)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils import global_device_put
    self._sampler.load_state_dict(meta['sampler'])
    self._epochs = int(meta['epoch'])
    if arrays:
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      if self.is_hetero:
        for t in self._feat_types:
          self._feat[t]._stats = global_device_put(
              np.asarray(arrays[f'stats:{t}'], np.int32), shard)
      else:
        self._feat._stats = global_device_put(
            np.asarray(arrays['stats:'], np.int32), shard)

  def _recovery_advance(self, meta):
    """Completed-epoch snapshot: advance the stream past the epoch.
    The stats accumulators are NOT restored — the finished epoch's
    publish already drained them pre-crash, and restoring would
    double-count them into the next epoch's publish."""
    self._sampler.load_state_dict(meta['sampler'])
    self._sampler._call_count += int(meta['steps'])
    self._epochs = int(meta['epoch']) + 1
