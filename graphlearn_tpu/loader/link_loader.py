"""Link (edge-seed) loader base.

TPU-native port of /root/reference/graphlearn_torch/python/loader/link_loader.py:
iterates seed edges, runs link sampling (negatives + node expansion), and
collates edge_label_index / edge_label (binary) or src/dst_pos/dst_neg
indices (triplet) into the batch metadata — same contract as the reference's
deduced edge_label_index (link_loader.py:100-229).
"""
from typing import Optional

import numpy as np

from ..data import Dataset
from ..sampler import BaseSampler, EdgeSamplerInput, NegativeSampling
from .node_loader import NodeLoader, SeedBatcher


class LinkLoader(NodeLoader):
  """Reference: loader/link_loader.py:35-229."""

  def __init__(self, data: Dataset, link_sampler: BaseSampler,
               edge_label_index, edge_label=None,
               neg_sampling: Optional[NegativeSampling] = None,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, to_device=None,
               seed: Optional[int] = None,
               overflow_policy: str = 'raise'):
    from ..typing import split_edge_type_seeds
    self.edge_type, edge_label_index = \
        split_edge_type_seeds(edge_label_index)
    eli = np.asarray(edge_label_index)
    self.rows, self.cols = eli[0].reshape(-1), eli[1].reshape(-1)
    self.edge_label = (np.asarray(edge_label).reshape(-1)
                       if edge_label is not None else None)
    self.neg_sampling = (NegativeSampling.cast(neg_sampling)
                         if neg_sampling is not None else None)
    self.data = data
    self.sampler = link_sampler
    self.batch_size = batch_size
    self.collect_features = collect_features
    self.to_device = to_device
    self.input_type = self.edge_type
    self._init_overflow_policy(overflow_policy)
    self._batcher = SeedBatcher(len(self.rows), batch_size, shuffle,
                                drop_last, seed)
    del with_edge

  def __iter__(self):
    guarded, recompute = self._overflow_epoch_start()
    for idx in self._batcher:
      inputs = EdgeSamplerInput(
          row=self.rows[idx], col=self.cols[idx],
          label=self.edge_label[idx] if self.edge_label is not None else
          None, input_type=self.edge_type, neg_sampling=self.neg_sampling)
      if recompute:
        key = self.sampler._next_key()
        out = self.sampler.sample_from_edges(inputs, key=key)
        if self._batch_overflowed(out):
          self.overflow_recomputes += 1
          out = self._replay_sampler().sample_from_edges(inputs, key=key)
      else:
        out = self.sampler.sample_from_edges(inputs)
        if guarded:
          self._accumulate_overflow(out)
      yield self._collate_fn(out)
    if guarded and not recompute:
      self._finish_epoch_overflow()
