"""Node-seed loader base.

TPU-native port of /root/reference/graphlearn_torch/python/loader/node_loader.py.
The reference wraps a torch DataLoader over seed ids and collates each index
batch through the sampler + feature stores. Here seed batching is plain
numpy (shuffle/drop_last), every batch is padded to the static
``batch_size`` so downstream jitted steps compile once, and collation is:
sample -> HBM/host feature gather -> label gather -> Data.
"""
from typing import Optional

import numpy as np

from ..data import Dataset
from ..sampler import BaseSampler, NodeSamplerInput
from .transform import to_data, to_hetero_data


class SeedBatcher:
  """Shuffled, batched iteration over seed indices (the torch DataLoader
  replacement; reference node_loader.py:76)."""

  def __init__(self, num_seeds: int, batch_size: int, shuffle: bool,
               drop_last: bool, seed: Optional[int] = None):
    self.num_seeds = num_seeds
    self.batch_size = batch_size
    self.shuffle = shuffle
    self.drop_last = drop_last
    self._rng = np.random.default_rng(seed)

  def __iter__(self):
    order = (self._rng.permutation(self.num_seeds) if self.shuffle
             else np.arange(self.num_seeds))
    n_full = self.num_seeds // self.batch_size
    for i in range(n_full):
      yield order[i * self.batch_size:(i + 1) * self.batch_size]
    rem = self.num_seeds - n_full * self.batch_size
    if rem and not self.drop_last:
      yield order[n_full * self.batch_size:]

  def __len__(self):
    n_full = self.num_seeds // self.batch_size
    rem = self.num_seeds - n_full * self.batch_size
    return n_full + (1 if rem and not self.drop_last else 0)


class NodeLoader:
  """Sample-and-collate loader over seed nodes
  (reference: loader/node_loader.py:27-113)."""

  def __init__(self, data: Dataset, node_sampler: BaseSampler,
               input_nodes, batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, to_device=None,
               seed: Optional[int] = None):
    self.data = data
    self.sampler = node_sampler
    if isinstance(input_nodes, tuple):
      self.input_type, self.input_seeds = input_nodes
    else:
      self.input_type, self.input_seeds = None, input_nodes
    self.input_seeds = np.asarray(self.input_seeds).reshape(-1)
    self.batch_size = batch_size
    self.collect_features = collect_features
    self.to_device = to_device
    self._batcher = SeedBatcher(len(self.input_seeds), batch_size, shuffle,
                                drop_last, seed)
    del with_edge  # carried by the sampler

  def __len__(self):
    return len(self._batcher)

  def __iter__(self):
    for idx in self._batcher:
      seeds = self.input_seeds[idx]
      out = self.sampler.sample_from_nodes(
          NodeSamplerInput(seeds, self.input_type),
          batch_cap=self.batch_size)
      yield self._collate_fn(out)

  # -- collate (reference: node_loader.py:85-113) --------------------------

  def _collate_fn(self, out):
    import jax.numpy as jnp
    if getattr(self.sampler, 'is_hetero', False):
      x = y = None
      if self.collect_features and self.data.node_features is not None:
        x = {}
        for t, buf in out.node.items():
          store = self.data.get_node_feature(t)
          if store is not None:
            safe = jnp.maximum(jnp.asarray(buf), 0)
            x[t] = store[safe]
      if self.data.node_labels is not None:
        y = {}
        for t, buf in out.node.items():
          labels = self.data.get_node_label(t)
          if labels is not None:
            safe = np.clip(np.asarray(buf), 0, len(labels) - 1)
            y[t] = jnp.asarray(np.asarray(labels)[safe])
      return to_hetero_data(out, x, y)

    x = y = None
    if self.collect_features and self.data.node_features is not None:
      safe = jnp.maximum(jnp.asarray(out.node), 0)
      x = self.data.node_features[safe]
    if self.data.node_labels is not None:
      labels = np.asarray(self.data.node_labels)
      safe = np.clip(np.asarray(out.node), 0, len(labels) - 1)
      y = jnp.asarray(labels[safe])
    ef = None
    if out.edge is not None and self.data.edge_features is not None:
      safe = jnp.maximum(jnp.asarray(out.edge), 0)
      ef = self.data.edge_features[safe]
    return to_data(out, x, y, ef)
