"""Node-seed loader base.

TPU-native port of /root/reference/graphlearn_torch/python/loader/node_loader.py.
The reference wraps a torch DataLoader over seed ids and collates each index
batch through the sampler + feature stores. Here seed batching is plain
numpy (shuffle/drop_last), every batch is padded to the static
``batch_size`` so downstream jitted steps compile once, and collation is:
sample -> HBM/host feature gather -> label gather -> Data.
"""
from typing import Optional

import numpy as np

from ..data import Dataset
from ..sampler import BaseSampler, NodeSamplerInput
from .transform import to_data, to_hetero_data


class SeedBatcher:
  """Shuffled, batched iteration over seed indices (the torch DataLoader
  replacement; reference node_loader.py:76)."""

  def __init__(self, num_seeds: int, batch_size: int, shuffle: bool,
               drop_last: bool, seed: Optional[int] = None):
    self.num_seeds = num_seeds
    self.batch_size = batch_size
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.seed = seed   # kept: ScanTrainer derives its device perm key
    self._rng = np.random.default_rng(seed)
    # mid-epoch resume bookkeeping (see state_dict below)
    self._epoch_start_state = self._rng.bit_generator.state
    self._consumed = 0
    self._pending_skip = 0

  def __iter__(self):
    # capture the stream position BEFORE the permutation draw: a
    # mid-epoch snapshot replays this epoch's permutation from here
    self._epoch_start_state = self._rng.bit_generator.state
    self._consumed = 0
    order = (self._rng.permutation(self.num_seeds) if self.shuffle
             else np.arange(self.num_seeds))
    skip, self._pending_skip = self._pending_skip, 0
    if skip >= len(self) > 0:
      # snapshot was taken at the epoch's end: the replayed epoch is
      # already complete — the permutation draw above advanced the
      # stream exactly as the original epoch did, so continue straight
      # into the next epoch. (len == 0 epochs yield nothing and must
      # not recurse.)
      yield from self.__iter__()
      return
    n_full = self.num_seeds // self.batch_size
    for i in range(n_full):
      if i < skip:
        self._consumed = i + 1
        continue
      # count BEFORE yielding: a snapshot taken while the consumer holds
      # batch i must record it as consumed (the trainer checkpoints
      # after finishing the step for the batch it was handed)
      self._consumed = i + 1
      yield order[i * self.batch_size:(i + 1) * self.batch_size]
    rem = self.num_seeds - n_full * self.batch_size
    if rem and not self.drop_last:
      self._consumed = n_full + 1
      yield order[n_full * self.batch_size:]

  def __len__(self):
    n_full = self.num_seeds // self.batch_size
    rem = self.num_seeds - n_full * self.batch_size
    return n_full + (1 if rem and not self.drop_last else 0)

  # -- checkpoint/resume (utils.checkpoint) --------------------------------
  # Mid-epoch granularity: the snapshot carries the PRNG state captured
  # at the CURRENT epoch's start plus how many batches were already
  # yielded. A restored batcher regenerates the identical permutation
  # and fast-forwards past the consumed batches, so training resumes at
  # the exact next batch (not the epoch start); subsequent epochs
  # continue the original stream. (The reference has no checkpointing
  # at all — SURVEY §5.)

  def state_dict(self):
    return {'rng_state': self._epoch_start_state,
            'consumed': int(self._consumed)}

  def load_state_dict(self, state):
    self._rng.bit_generator.state = state['rng_state']
    self._epoch_start_state = state['rng_state']
    self._pending_skip = int(state.get('consumed', 0))
    self._consumed = self._pending_skip


class OverflowGuardMixin:
  """Calibrated-caps overflow guard shared by the local and distributed
  loaders.

  Calibrated frontier_caps (sampler.calibrate) keep exact-dedup batches
  ~5x smaller than worst case, but a batch whose unique frontier exceeds
  a cap is TRUNCATED — quietly biased if nobody looks. The reference can
  never truncate (dynamic shapes), so silent truncation must not be
  reachable here by default either. Every sampled batch carries an
  on-device metadata['overflow'] flag; the loader applies
  ``overflow_policy``:

    'raise' (default) — accumulate the flag ON DEVICE (no host sync in
        the hot loop) and fetch it ONCE at epoch end; raise if any batch
        truncated. Loud, zero dispatch-pipeline cost.
    'warn'      — same, warnings.warn instead of raising.
    'recompute' — check each batch's flag on the host and recompute
        offenders at FULL capacities with the SAME PRNG key (the
        untruncated version of the identical draw — exact by
        construction). Costs one device->host sync per batch: correct
        unconditionally, so benchmarks opt into 'raise'/'off'
        explicitly.
    'off'       — round-3 behavior (truncation only visible via
        calibrate.check_no_overflow).
  """

  # defaults for subclasses that skip __init__ (guard inactive)
  overflow_policy = 'off'
  overflow_recomputes = 0
  _ovf_accum = None
  _full_sampler = None

  _OVERFLOW_POLICIES = ('raise', 'warn', 'recompute', 'off')

  def _init_overflow_policy(self, policy: str):
    if policy not in self._OVERFLOW_POLICIES:
      raise ValueError(f'overflow_policy {policy!r} not in '
                       f'{self._OVERFLOW_POLICIES}')
    if policy == 'recompute' and \
        getattr(getattr(self, 'sampler', None), 'is_hetero', False):
      # hetero sampling draws (hop, etype) keys from the sampler's
      # internal stream — no replayable per-batch key exists, so a
      # full-caps recompute could not reproduce the truncated draw
      # graftlint: allow[hetero-gate] no replayable hetero batch key
      raise ValueError(
          "overflow_policy='recompute' is homogeneous-only (hetero "
          'batches have no replayable per-batch key); use '
          "'raise'/'warn', or recalibrate with more slack")
    self.overflow_policy = policy
    self.overflow_recomputes = 0   # total full-caps replays ('recompute')
    self._ovf_accum = None         # on-device accumulated flag
    self._full_sampler = None      # lazy uncapped clone

  def _overflow_guarded(self) -> bool:
    return getattr(self.sampler, 'clamped_exact', False) and \
        self.overflow_policy != 'off'

  def _overflow_epoch_start(self):
    """(guarded, recompute) for this epoch. Also DROPS any flag
    accumulated by a previous, early-exited epoch — a stale flag would
    otherwise make the next clean epoch raise (an early break already
    forfeited that epoch's verdict; it must not taint this one)."""
    self._ovf_accum = None
    guarded = self._overflow_guarded()
    return guarded, guarded and self.overflow_policy == 'recompute'

  def _accumulate_overflow(self, out):
    import jax.numpy as jnp
    flag = out.metadata.get('overflow')
    if flag is None:
      return
    flag = jnp.any(flag)
    self._ovf_accum = (flag if self._ovf_accum is None
                       else jnp.logical_or(self._ovf_accum, flag))

  def _batch_overflowed(self, out) -> bool:
    flag = out.metadata.get('overflow')
    return flag is not None and bool(np.any(np.asarray(flag)))

  def _replay_sampler(self):
    if self._full_sampler is None:
      self._full_sampler = self.sampler.uncapped_clone()
    return self._full_sampler

  def check_overflow(self) -> bool:
    """True iff any batch sampled SINCE the current epoch started has
    tripped the calibrated-caps overflow flag (one device fetch). For
    consumers that exit an epoch early (eval loops with a batch cap,
    early stopping): the automatic epoch-end check only runs when the
    iterator is exhausted, so call this after an early break to keep the
    no-truncation claim honest."""
    if self._ovf_accum is None:
      return False
    return bool(np.asarray(self._ovf_accum))

  def _finish_epoch_overflow(self):
    if self._ovf_accum is None:
      return
    flag, self._ovf_accum = self._ovf_accum, None
    if bool(np.asarray(flag)):
      msg = (
          'calibrated frontier_caps overflowed this epoch: at least one '
          'batch was truncated (quietly biased). Re-calibrate with more '
          'slack (sampler.calibrate.estimate_frontier_caps), or pass '
          "overflow_policy='recompute' to replay offending batches at "
          'full capacities (exact, one host sync per batch).')
      if self.overflow_policy == 'warn':
        import warnings
        warnings.warn(msg, stacklevel=2)
      else:
        raise RuntimeError(msg)


class NodeLoader(OverflowGuardMixin):
  """Sample-and-collate loader over seed nodes
  (reference: loader/node_loader.py:27-113)."""

  seed_labels_only = False   # subclasses that skip __init__ inherit this

  def __init__(self, data: Dataset, node_sampler: BaseSampler,
               input_nodes, batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, to_device=None,
               seed: Optional[int] = None,
               seed_labels_only: bool = False,
               overflow_policy: str = 'raise'):
    self.data = data
    self.sampler = node_sampler
    # seed_labels_only: gather y for the seed block only (supervision
    # uses seed slots; skips a full-capacity random gather — PERF.md)
    self.seed_labels_only = seed_labels_only
    if isinstance(input_nodes, tuple):
      self.input_type, self.input_seeds = input_nodes
    else:
      self.input_type, self.input_seeds = None, input_nodes
    self.input_seeds = np.asarray(self.input_seeds).reshape(-1)
    self.batch_size = batch_size
    self.collect_features = collect_features
    self.to_device = to_device
    self._init_overflow_policy(overflow_policy)
    self._batcher = SeedBatcher(len(self.input_seeds), batch_size, shuffle,
                                drop_last, seed)
    del with_edge  # carried by the sampler

  def __len__(self):
    return len(self._batcher)

  def state_dict(self):
    """Resumable iteration state (MID-EPOCH granularity): the seed
    shuffle stream + position within the current epoch's permutation,
    plus the sampler's PRNG state — a restored run resumes at the exact
    next batch and replays precisely what the uninterrupted run would
    have produced (SeedBatcher.state_dict)."""
    state = self._batcher.state_dict()
    state['sampler'] = self.sampler.state_dict()
    return state

  def load_state_dict(self, state):
    self._batcher.load_state_dict(state)
    if 'sampler' in state:
      self.sampler.load_state_dict(state['sampler'])

  def _begin_epoch(self):
    """Per-epoch padded-table reseed: rows with deg > window expose a
    fresh random window-subset each epoch, de-biasing the truncation
    (ops.build_padded_adjacency; no-op for non-padded samplers). The
    single counter lives here so every epoch driver — __iter__ and
    OverlappedTrainer.run_epoch — shares one view of how many epochs
    this loader has run."""
    if getattr(self.sampler, 'padded_window', None) is not None:
      if getattr(self, '_epochs_started', 0) > 0:
        self.sampler.refresh_padded_table()
      self._epochs_started = getattr(self, '_epochs_started', 0) + 1

  def __iter__(self):
    from ..metrics import flight
    from ..utils import step_annotation
    self._begin_epoch()
    # overflow-policy resolve BEFORE the flight bracket opens: a config
    # error raising here must not leave a permanently-open record
    guarded, recompute = self._overflow_epoch_start()
    tok = flight.epoch_begin()
    steps, completed = 0, False
    try:
      for i, idx in enumerate(self._batcher):
        with step_annotation('glt_batch', i):
          seeds = self.input_seeds[idx]
          inp = NodeSamplerInput(seeds, self.input_type)
          if recompute:
            key = self.sampler._next_key()
            out = self.sampler.sample_from_nodes(
                inp, batch_cap=self.batch_size, key=key)
            if self._batch_overflowed(out):
              self.overflow_recomputes += 1
              out = self._replay_sampler().sample_from_nodes(
                  inp, batch_cap=self.batch_size, key=key)
          else:
            out = self.sampler.sample_from_nodes(
                inp, batch_cap=self.batch_size)
            if guarded:
              self._accumulate_overflow(out)
          yield self._collate_fn(out)
          steps += 1
      completed = True
      if guarded and not recompute:
        self._finish_epoch_overflow()
    finally:
      # one flight record per per-step epoch (metrics/flight.py) —
      # host-side counter deltas only, nothing dispatched or fetched
      flight.end_for(
          self, tok, steps=steps, completed=completed,
          config=dict(loader=type(self).__name__,
                      batch_size=self.batch_size,
                      shuffle=self._batcher.shuffle,
                      drop_last=self._batcher.drop_last,
                      seed=self._batcher.seed,
                      num_neighbors=getattr(self.sampler,
                                            'num_neighbors', None)))

  # -- collate (reference: node_loader.py:85-113) --------------------------
  #
  # Collation runs as ONE jitted dispatch (ops.collate_batch) whose array
  # inputs are all arguments: the loader must never run eager ops on the
  # sampler's still-pending outputs, and never fetch them to host
  # (PERF.md dispatch rules). The reference gathers on the host driver
  # instead (node_loader.py:85-113) — that shape would serialize here.

  def _label_table(self, ntype=None):
    """Device-resident label table, cached (host labels uploaded once)."""
    import jax.numpy as jnp
    if not hasattr(self, '_labels_dev'):
      self._labels_dev = {}
    key = ntype
    if key not in self._labels_dev:
      labels = (self.data.get_node_label(ntype) if ntype is not None
                else self.data.node_labels)
      self._labels_dev[key] = (None if labels is None
                               else jnp.asarray(np.asarray(labels)))
    return self._labels_dev[key]

  def _collate_fn(self, out):
    from .. import ops
    if getattr(self.sampler, 'is_hetero', False):
      x = y = None
      if self.collect_features and self.data.node_features is not None:
        x = {}
        for t, buf in out.node.items():
          store = self.data.get_node_feature(t)
          if store is not None:
            dt = store.device_table()
            if dt is not None:
              x[t] = ops.gather_rows(dt[0], dt[1], buf)
            else:  # host/mixed store: UnifiedTensor mixed path
              x[t] = store[buf]
      if self.data.node_labels is not None:
        y = {}
        for t, buf in out.node.items():
          labels = self._label_table(t)
          if labels is None:
            continue
          if self.seed_labels_only:
            # supervision reads seed slots only, and seeds lead the
            # INPUT type's buffer; other types carry no seed block.
            # Slice by the ENGINE's actual seed cap (out.batch carries
            # the padded seed block) — the hetero engine rounds seed
            # caps up, so batch_size alone could misalign labels
            if t != out.input_type:
              continue
            cap = (out.batch[t].shape[0]
                   if out.batch is not None and t in out.batch
                   else self.batch_size)
            buf = buf[:cap]
          y[t] = ops.gather_rows(labels, None, buf)
      return to_hetero_data(out, x, y)

    feats = id2i = None
    if self.collect_features and self.data.node_features is not None:
      dt = self.data.node_features.device_table()
      if dt is not None:
        feats, id2i = dt
    efeats = None
    if out.edge is not None and self.data.edge_features is not None:
      edt = self.data.edge_features.device_table()
      if edt is not None:
        efeats = edt[0]
    from ..utils.trace import record_dispatch
    record_dispatch('collate')
    res = ops.collate_batch(out.node, out.num_nodes, out.row, out.col,
                            feats, id2i, self._label_table(), efeats,
                            out.edge,
                            label_cap=(self.batch_size
                                       if self.seed_labels_only else None))
    x = res['x']
    if x is None and self.collect_features and \
        self.data.node_features is not None:
      # host/mixed feature store: fall back to the UnifiedTensor path
      x = self.data.node_features[out.node]
    ef = res['edge_attr']
    if ef is None and out.edge is not None and \
        self.data.edge_features is not None:
      ef = self.data.edge_features[out.edge]
    data = to_data(out, x, res['y'], ef,
                   node_mask=res['node_mask'],
                   edge_index=res['edge_index'])
    return data
