from .link_loader import LinkLoader
from .link_neighbor_loader import LinkNeighborLoader
from .neighbor_loader import NeighborLoader
from .node_loader import NodeLoader, SeedBatcher
from .pipeline import (DistFusedEpochTrainer, FusedEpochTrainer,
                       OverlappedTrainer)
from .run_epoch import RunTrainer
from .scan_epoch import DistScanTrainer, ScanTrainer
from .subgraph_loader import SubGraphLoader
from .transform import Data, HeteroData, to_data, to_hetero_data
