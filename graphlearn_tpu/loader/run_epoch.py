"""Run-as-a-program: the whole multi-epoch training RUN as one scanned
program stream.

Epoch-as-a-program (scan_epoch.ScanTrainer) collapsed an epoch to
``ceil(steps/K) + 2`` dispatches, but a RUN of E epochs still pays that
per epoch — ``E * (ceil(steps/K) + 2)`` dispatches plus per-epoch host
Python (seed redraw, counter bookkeeping). On the remote-dispatch
runtime PERF.md profiles, those per-epoch prologues are pure dispatch
tax. :class:`RunTrainer` extends the contract one level up: the E-epoch
run executes as

    ``ceil(E * steps / K) + 2`` dispatches
    (1 run-seed program + chunks + 1 metrics concat)

with the per-epoch reseed FOLDED INTO the seed program (epoch ``e``'s
permutation key is ``fold_in(perm_key, epoch0 + e)`` — exactly the key
ScanTrainer would fold on the host — drawn for all E epochs in one
dispatch) and chunks crossing epoch boundaries freely.

The scan carry additionally threads:

* **on-device eval counts** (``models.train.make_eval_counts``): exact
  per-epoch correct/total over the training stream's seed slots,
  accumulated in-carry and converted to the epoch metric at each
  boundary — zero host fetches;
* **an early-stop flag**: patience on the eval metric, checked
  IN-CARRY at epoch boundaries. Once set, every later step runs the
  no-op branch of a ``lax.cond`` — stopped epochs become no-op chunks
  (the dispatches still land, the device work doesn't) with no host
  round-trip anywhere.

Bit-identity contract: with early-stop never firing, losses and final
params are BIT-IDENTICAL to E sequential ``ScanTrainer.run_epoch``
calls over the same loader (ragged tail, tail chunk, shuffle on or
off) — the eval forward is a pure read of the pre-update params and
perturbs nothing (tests/test_run_epoch.py pins the matrix). The
``stage_hook``/``ack_hook`` chunk-boundary seams carry the standard
contract, so ``recovery.ChunkCheckpointer`` attaches unchanged and a
mid-run crash resumes BIT-IDENTICALLY at the last chunk boundary of
the right epoch (the eval carry rides the snapshot's extra arrays).

Scope: the ScanTrainer scope MINUS padded-window sampling — the
padded table's per-epoch reseed is a host-side table rebuild that
cannot fold into one program stream (use per-epoch ScanTrainer there).
The run's overflow flag accumulates across ALL epochs and the loader's
overflow policy fires once, at run end.

Usage::

    trainer = RunTrainer(loader, model, tx, num_classes, chunk_size=32,
                         epochs=20, patience=3)
    state, losses, accs = trainer.run(state)
    report = trainer.last_run_report   # device arrays: fetch once
"""
from typing import Optional

import numpy as np

from .. import metrics
from ..metrics import programs, spans
from ..utils.strict import strict_guards
from ..utils.trace import record_dispatch
from .node_loader import NodeLoader
from .scan_epoch import ScanTrainer


class RunTrainer(ScanTrainer):
  """Executes an E-epoch run as ``ceil(E * steps / K) + 2`` dispatches
  (module docstring).

  Args (beyond ScanTrainer's):
    epochs: E, the number of epochs the run program covers.
    patience: early-stop patience — stop after this many consecutive
      epochs whose eval metric failed to improve ``best + min_delta``
      (None disables early stop; the bit-identity contract's mode).
    min_delta: minimum improvement that resets the patience counter.
    track_eval: compute the in-carry eval counts (one extra model
      FORWARD per step — a pure read, bit-identity preserved either
      way). ``False`` drops that forward for runs that want the pure
      dispatch-tax win and no report metrics (``last_run_report``'s
      eval_metric stays NaN); required True when ``patience`` is set.
  """

  _NAME = 'RunTrainer'

  def __init__(self, loader: NodeLoader, model, tx, num_classes: int,
               chunk_size: Optional[int] = None, epochs: int = 1,
               patience: Optional[int] = None, min_delta: float = 0.0,
               seed_labels_only: Optional[bool] = None,
               perm_seed: Optional[int] = None, config=None,
               track_eval: bool = True):
    super().__init__(loader, model, tx, num_classes,
                     chunk_size=chunk_size,
                     seed_labels_only=seed_labels_only,
                     perm_seed=perm_seed, config=config)
    if epochs < 1:
      raise ValueError(f'epochs must be >= 1, got {epochs}')
    if patience is not None and patience < 1:
      raise ValueError(f'patience must be >= 1 or None, got {patience}')
    if patience is not None and not track_eval:
      raise ValueError('patience requires track_eval=True — the '
                       'early-stop flag is a function of the in-carry '
                       'eval metric')
    if getattr(self._sampler, 'padded_window', None) is not None:
      raise ValueError(
          f'{self._NAME} cannot fold padded-window sampling into one '
          'run program: the per-epoch padded-table reseed is a '
          'host-side adjacency rebuild (NodeLoader._begin_epoch). Run '
          'per-epoch ScanTrainer there, or drop padded_window')
    self.epochs = int(epochs)
    self.patience = None if patience is None else int(patience)
    self.min_delta = float(min_delta)
    self.track_eval = bool(track_eval)
    from ..models import train as train_lib
    self._eval_counts = (train_lib.make_eval_counts(model)
                         if self.track_eval else None)
    self._run_seed_fn = programs.instrument(self._build_run_seed_fn(),
                                            'run_epoch_seeds')
    self._run_chunk_fn = programs.instrument(self._build_run_chunk_fn(),
                                             'run_scan_chunk')
    self._run_concat_fn = programs.instrument(self._build_concat_fn(),
                                              'run_metrics_concat')
    #: device arrays from the final carry after each run: per-epoch
    #: eval metric [E] (NaN for epochs never reached), epochs_run,
    #: stopped flag, best metric — fetch once, after the run
    self.last_run_report = None
    self._resume_eval = None   # recovery: eval carry at the boundary

  # ------------------------------------------------------------- programs

  def _build_run_seed_fn(self):
    """ONE program for the RUN prologue: all E epochs' permutations
    (epoch ``e`` drawn under ``fold_in(perm_base, epoch0 + e)`` — the
    exact key ScanTrainer folds per epoch, so the flattened
    [E * steps, B] matrices are row-identical to E sequential epoch
    prologues), ragged tails masked per epoch."""
    import jax
    import jax.numpy as jnp
    batch = self._batch_size
    shuffle = self._shuffle

    def run_epoch_seeds(seeds, perm_base, epoch0, num_epochs, steps):
      n = seeds.shape[0]

      def one_epoch(e):
        key = jax.random.fold_in(perm_base, e)
        order = (jax.random.permutation(key, n) if shuffle
                 else jnp.arange(n, dtype=jnp.int32))
        total = steps * batch
        if total <= n:       # drop_last: the permutation's prefix
          order = order[:total]
          mask = jnp.ones((total,), bool)
        else:                # ragged tail, masked invalid
          order = jnp.concatenate(
              [order, jnp.zeros((total - n,), order.dtype)])
          mask = jnp.arange(total) < n
        seed_mat = jnp.where(mask, seeds[order], 0).reshape(steps,
                                                            batch)
        return seed_mat, mask.reshape(steps, batch)

      mats, masks = jax.vmap(one_epoch)(
          epoch0 + jnp.arange(num_epochs, dtype=jnp.int32))
      return (mats.reshape(num_epochs * steps, batch),
              masks.reshape(num_epochs * steps, batch))

    return jax.jit(run_epoch_seeds, static_argnums=(3, 4))

  def _build_run_chunk_fn(self):
    """The scanned K-step RUN program: the ScanTrainer chunk body plus
    the eval/early-stop carry. Global step ``g`` derives its epoch as
    ``g // S`` and its sampler count as ``count0 + g`` (the exact
    continuation of E sequential epochs' fold_in streams). The whole
    step body sits under a ``lax.cond`` on the stop flag: a stopped
    run's remaining chunks execute the no-op branch only."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    sample_collate = self._sample_collate
    train_step = self._train_step   # jit-of-jit: inlined into the scan
    eval_counts = self._eval_counts
    track_eval = self.track_eval
    patience = self.patience
    min_delta = self.min_delta

    def run_scan_chunk(state, ovf, ev, fargs, feats, id2i, labels,
                       seed_mat, mask_mat, base_key, count0, start, k,
                       steps_per_epoch):
      seeds_k = lax.dynamic_slice_in_dim(seed_mat, start, k, axis=0)
      masks_k = lax.dynamic_slice_in_dim(mask_mat, start, k, axis=0)
      gsteps = start + lax.iota(jnp.int32, k)

      def active(op, seeds, smask, g):
        state, ovf, ev = op
        key = jax.random.fold_in(base_key, count0 + g)
        batch, overflow = sample_collate(fargs, feats, id2i, labels,
                                         seeds, smask, key)
        is_end = (g + 1) % steps_per_epoch == 0
        if track_eval:
          # exact eval counts of the CURRENT params over this batch's
          # seed slots — a pure read; the train step below consumes
          # the same batch unperturbed (the bit-identity contract)
          correct, total = eval_counts(state.params, batch)
          state, loss, acc = train_step(state, batch)
          correct = ev['correct'] + correct.astype(jnp.int32)
          total = ev['total'] + total.astype(jnp.int32)
          e_idx = g // steps_per_epoch
          metric = correct.astype(jnp.float32) / \
              jnp.maximum(total, 1).astype(jnp.float32)
          improved = metric > ev['best'] + min_delta
          best = jnp.where(is_end & improved, metric, ev['best'])
          bad = jnp.where(is_end,
                          jnp.where(improved, jnp.int32(0),
                                    ev['bad'] + 1),
                          ev['bad'])
          stop = ev['stop']
          if patience is not None:
            stop = stop | (is_end & (bad >= patience))
          mets = jnp.where(
              is_end,
              lax.dynamic_update_index_in_dim(ev['metrics'], metric,
                                              e_idx, 0),
              ev['metrics'])
          ev = dict(correct=jnp.where(is_end, jnp.int32(0), correct),
                    total=jnp.where(is_end, jnp.int32(0), total),
                    best=best, bad=bad, stop=stop,
                    edone=ev['edone'] + is_end.astype(jnp.int32),
                    metrics=mets)
        else:
          # track_eval=False drops the per-step eval forward (the pure
          # dispatch-tax mode); the carry keeps its full structure so
          # recovery snapshots and the report shape stay uniform —
          # eval_metric remains NaN, epochs_run still counts
          state, loss, acc = train_step(state, batch)
          ev = dict(ev, edone=ev['edone'] + is_end.astype(jnp.int32))
        return ((state, ovf | overflow, ev),
                (loss.astype(jnp.float32), acc.astype(jnp.float32)))

      def stopped(op, seeds, smask, g):
        del seeds, smask, g
        return op, (jnp.float32(0), jnp.float32(0))

      def body(carry, xs):
        seeds, smask, g = xs
        _, _, ev = carry
        # once stopped, the whole sample+eval+train body is skipped —
        # the chunk dispatch lands but the device executes the no-op
        # branch (no host round-trip decides this, ever)
        return lax.cond(ev['stop'], stopped, active, carry, seeds,
                        smask, g)

      (state, ovf, ev), (losses, accs) = lax.scan(
          body, (state, ovf, ev), (seeds_k, masks_k, gsteps))
      return state, ovf, ev, losses, accs

    return jax.jit(run_scan_chunk, static_argnums=(12, 13),
                   donate_argnums=(0, 1, 2))

  # ----------------------------------------------------------------- run

  def _epoch_steps(self) -> int:
    # the RUN is the unit: the inherited run_epoch bracket sees
    # E * steps as "the epoch's" step count (budget, spans, flight)
    return len(self.loader._batcher) * self.epochs

  def run(self, state, max_steps: Optional[int] = None,
          start_step: int = 0, resume_overflow: bool = False):
    """The whole-run entry point (an alias of :meth:`run_epoch` — the
    checkpointer seam requires the standard name). Returns
    ``(state, losses, accs)`` with losses/accs [E * steps]-shaped
    device arrays; after an early stop the stopped tail is zeros and
    ``last_run_report`` carries the per-epoch metrics + stop point."""
    return self.run_epoch(state, max_steps=max_steps,
                          start_step=start_step,
                          resume_overflow=resume_overflow)

  def run_epoch(self, state, max_steps: Optional[int] = None,
                start_step: int = 0, resume_overflow: bool = False):
    metrics.inc('run.runs')
    metrics.inc('run.epochs_scheduled', self.epochs)
    # a zero-step run returns from the inherited early path before
    # _run_epoch_body assigns the report — None there, never a stale
    # report attributed to this run
    self.last_run_report = None
    with spans.span('run.train', emitter=self._NAME,
                    epochs=self.epochs, epoch0=self._epochs):
      return super().run_epoch(state, max_steps=max_steps,
                               start_step=start_step,
                               resume_overflow=resume_overflow)

  def _initial_eval_carry(self, num_epochs: int):
    import jax
    if self._resume_eval is not None:
      ev = {k: np.asarray(v) for k, v in self._resume_eval.items()}
      self._resume_eval = None
      return jax.device_put(ev)
    return jax.device_put(dict(
        correct=np.int32(0), total=np.int32(0),
        best=np.float32(-np.inf), bad=np.int32(0),
        stop=np.asarray(False), edone=np.int32(0),
        metrics=np.full((num_epochs,), np.nan, np.float32)))

  def _run_epoch_body(self, state, steps, full_steps, start_step=0,
                      resume_overflow=False):
    """The run program proper: one all-epochs seed draw + scanned
    chunks over the flattened step stream. Mirrors ScanTrainer's body;
    the inherited run_epoch owns the guard/flight bracketing."""
    import jax
    num_epochs = self.epochs
    steps_per_epoch = full_steps // num_epochs
    if self._seeds_dev is None:
      self._seeds_dev = jax.device_put(
          np.asarray(self.loader.input_seeds, dtype=np.int32))
    fargs = self._sampler._fused_args()
    base_key = self._sampler._key
    epoch0 = jax.device_put(np.int32(self._epochs))
    count0 = jax.device_put(np.int32(self._sampler._call_count + 1))
    ovf = jax.device_put(np.asarray(bool(resume_overflow)))
    ev = self._initial_eval_carry(num_epochs)
    losses, accs = [], []
    start = start_step
    with strict_guards():
      record_dispatch('run_epoch_seeds')
      seed_mat, mask_mat = self._run_seed_fn(
          self._seeds_dev, self._perm_key, epoch0, num_epochs,
          steps_per_epoch)
      while start < steps:
        k = min(self.chunk_size, steps - start)
        if self.stage_hook is not None:
          self.stage_hook(start // self.chunk_size, start, k)
        record_dispatch('run_scan_chunk')
        with spans.span('epoch.chunk', start=start, k=k):
          state, ovf, ev, loss_k, acc_k = self._run_chunk_fn(
              state, ovf, ev, fargs, self._feats, self._id2i,
              self._labels, seed_mat, mask_mat, base_key, count0,
              jax.device_put(np.int32(start)), k, steps_per_epoch)
        losses.append(loss_k)
        accs.append(acc_k)
        self._steps_dispatched = start + k
        if self.ack_hook is not None:
          # boundary carry for the recovery seam — valid only inside
          # the hook call (the next chunk donates state/ovf/eval)
          self._chunk_carry = dict(state=state, ovf=ovf, eval=ev,
                                   losses=losses, accs=accs,
                                   steps=steps, full_steps=full_steps,
                                   start_step=start_step)
          self.ack_hook(start // self.chunk_size, start, k)
        start += k
      if len(losses) > 1:
        record_dispatch('run_metrics_concat')
        losses, accs = self._run_concat_fn(losses, accs)
      else:
        losses, accs = losses[0], accs[0]
    self.last_run_report = dict(eval_metric=ev['metrics'],
                                best_metric=ev['best'],
                                epochs_run=ev['edone'],
                                stopped=ev['stop'])
    # keep the host fold_in stream aligned with the RUN's consumption:
    # counter addressing is positional, so the host position advances
    # by the scheduled steps whether or not early-stop no-op'd a tail
    # (later sampling continues the same deterministic stream)
    self._sampler._call_count += steps
    self._epochs += num_epochs
    return state, losses, accs, ovf

  def _flight_config(self) -> dict:
    cfg = super()._flight_config()
    cfg.update(epochs=self.epochs, patience=self.patience,
               min_delta=self.min_delta, track_eval=self.track_eval)
    return cfg

  # -------------------------------------------------- recovery protocol
  # (recovery/checkpoint.py ChunkCheckpointer rides the inherited
  # stage/ack seams unchanged; the run adds only the eval carry to the
  # boundary snapshot)

  def _recovery_capture(self, carry):
    meta, dev = super()._recovery_capture(carry)
    meta['epochs_total'] = self.epochs
    ev = carry.get('eval')
    if ev is not None:
      for key, val in ev.items():
        dev[f'eval:{key}'] = val
    return meta, dev

  def _recovery_load(self, meta, arrays):
    ev = {k[len('eval:'):]: np.asarray(v)
          for k, v in (arrays or {}).items() if k.startswith('eval:')}
    rest = {k: v for k, v in (arrays or {}).items()
            if not k.startswith('eval:')}
    super()._recovery_load(meta, rest)
    self._resume_eval = ev or None

  def _recovery_advance(self, meta):
    """A completed-RUN snapshot advances past all E epochs."""
    self._sampler.load_state_dict(meta['sampler'])
    self._sampler._call_count += int(meta['steps'])
    self._epochs = int(meta['epoch']) + int(meta.get('epochs_total', 1))
