"""LinkNeighborLoader: fanout link loader.

TPU-native port of
/root/reference/graphlearn_torch/python/loader/link_neighbor_loader.py.
"""
from typing import Optional

from ..data import Dataset
from ..sampler import NegativeSampling, NeighborSampler
from .link_loader import LinkLoader


class LinkNeighborLoader(LinkLoader):
  """Reference: loader/link_neighbor_loader.py."""

  def __init__(self, data: Dataset, num_neighbors, edge_label_index,
               edge_label=None,
               neg_sampling: Optional[NegativeSampling] = None,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               with_weight: bool = False, strategy: str = 'random',
               collect_features: bool = True, to_device=None,
               seed: Optional[int] = None,
               node_budget: Optional[int] = None, dedup: str = 'auto',
               frontier_caps=None, overflow_policy: str = 'raise'):
    # Link batches seed src+dst(+negatives), so the calibration width is
    # NOT batch_size — it is calibrate.link_seed_width(batch_size,
    # neg_sampling). frontier_caps='auto' computes that width and
    # calibrates against this loader's own endpoint pool, so callers
    # never hand-derive it. (Explicit caps lists are taken as-is — they
    # must have been estimated at the same effective width.)
    if isinstance(frontier_caps, str):
      if frontier_caps != 'auto':
        raise ValueError(f'frontier_caps={frontier_caps!r}: pass a list '
                         "of per-hop caps or 'auto'")
      from ..typing import split_edge_type_seeds
      if isinstance(data.graph, dict) or \
          split_edge_type_seeds(edge_label_index)[0] is not None:
        # hetero dataset, or an (etype, index) pair on LinkLoader's own
        # tuple convention — fail clearly, not with an AttributeError
        # inside estimate_frontier_caps
        raise ValueError(
            "frontier_caps='auto' is homogeneous-only; on hetero "
            'datasets pass the {edge_type: [per-hop caps]} dict from '
            'calibrate.estimate_hetero_frontier_caps')
      import numpy as np
      from ..sampler.calibrate import (estimate_frontier_caps,
                                       link_seed_width)
      ns = (NegativeSampling.cast(neg_sampling)
            if neg_sampling is not None else None)
      eli = (edge_label_index[1]
             if isinstance(edge_label_index, tuple) and
             len(edge_label_index) == 2 and
             isinstance(edge_label_index[0], (tuple, list))
             else edge_label_index)
      eli = np.asarray(eli)
      # probe pool: the positive endpoints. Negative seeds are uniform
      # nodes — endpoint neighborhoods are at least as dense, so probing
      # the full width from the endpoint pool stays an upper bound.
      pool = np.concatenate([eli[0].reshape(-1), eli[1].reshape(-1)])
      frontier_caps = estimate_frontier_caps(
          data.graph, list(num_neighbors),
          link_seed_width(batch_size, ns), input_nodes=pool,
          seed=seed or 0)
    sampler = NeighborSampler(
        data.graph, num_neighbors, device=to_device, with_edge=with_edge,
        with_weight=with_weight, strategy=strategy, edge_dir=data.edge_dir,
        seed=seed, node_budget=node_budget, dedup=dedup,
        frontier_caps=frontier_caps)
    super().__init__(data, sampler, edge_label_index, edge_label,
                     neg_sampling, batch_size, shuffle, drop_last,
                     with_edge, collect_features, to_device, seed,
                     overflow_policy=overflow_policy)
