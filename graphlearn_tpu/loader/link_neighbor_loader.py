"""LinkNeighborLoader: fanout link loader.

TPU-native port of
/root/reference/graphlearn_torch/python/loader/link_neighbor_loader.py.
"""
from typing import Optional

from ..data import Dataset
from ..sampler import NegativeSampling, NeighborSampler
from .link_loader import LinkLoader


class LinkNeighborLoader(LinkLoader):
  """Reference: loader/link_neighbor_loader.py."""

  def __init__(self, data: Dataset, num_neighbors, edge_label_index,
               edge_label=None,
               neg_sampling: Optional[NegativeSampling] = None,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               with_weight: bool = False, strategy: str = 'random',
               collect_features: bool = True, to_device=None,
               seed: Optional[int] = None,
               node_budget: Optional[int] = None, dedup: str = 'auto',
               frontier_caps=None):
    # frontier_caps note: link batches seed src+dst(+negatives) — the
    # effective seed width is 2*batch_size (binary: +2*num_neg,
    # triplet: +num_neg), NOT batch_size. Calibrate with
    # estimate_frontier_caps(graph, fanouts, batch_size=<that width>)
    # or every batch overflows into (clean, but silent) truncation.
    sampler = NeighborSampler(
        data.graph, num_neighbors, device=to_device, with_edge=with_edge,
        with_weight=with_weight, strategy=strategy, edge_dir=data.edge_dir,
        seed=seed, node_budget=node_budget, dedup=dedup,
        frontier_caps=frontier_caps)
    super().__init__(data, sampler, edge_label_index, edge_label,
                     neg_sampling, batch_size, shuffle, drop_last,
                     with_edge, collect_features, to_device, seed)
