"""Overlapped (double-buffered) sample -> collate -> train pipeline.

The reference's defining architecture is the asynchronous producer-consumer
pipeline: sampling runs decoupled from training and its latency hides
behind the train step
(/root/reference/graphlearn_torch/python/distributed/dist_sampling_producer.py:53-151;
docs/get_started/dist_train.md:3-8 "asynchronous producer consumer model",
via CUDA streams / separate processes).

A single TPU core has no concurrent streams — XLA programs execute one at
a time — so the TPU-native equivalent is PROGRAM FUSION with software
double-buffering: batch n's train step and batch n+1's sample+collate are
traced into ONE XLA program with no data dependency between the two
subgraphs. XLA's scheduler is then free to interleave the sampler/collate
work (DMA-latency/HBM-bound gathers) with the train step's MXU-bound
matmul pipeline, which is exactly the resource overlap the reference gets
from its producer streams. Whether the scheduler exploits it is an
empirical question — bench.py measures the fused step against the serial
sum with device-trace truth (PERF.md reports the measured overlap).

Usage:
    loader = NeighborLoader(ds, fanouts, idx, batch_size=B, ...)
    trainer = OverlappedTrainer(loader, model, tx, num_classes)
    state, losses = trainer.run_epoch(state)   # losses stay on device

The host loop stays dispatch-only (no device->host fetches, PERF.md
rules); fetch the returned loss array once per epoch if needed.
"""
from typing import Optional

import numpy as np

from .. import ops
from .node_loader import NodeLoader

_RECOMPUTE_MSG = (
    "overflow_policy='recompute' needs a device->host sync per batch, "
    'which defeats the overlapped pipeline. Use the plain loader loop '
    "for recompute, or overflow_policy='raise'/'warn' here (the flag "
    'accumulates on device and is checked once at epoch end).')


class FusedEpochTrainer:
  """Shared plumbing for the fused epoch executors (OverlappedTrainer,
  scan_epoch.ScanTrainer): scope validation, the device feature/label
  tables, and the pure sample+collate body both trainers trace into
  their programs.

  Requirements: homogeneous graph, fused sampler, device-resident
  feature/label tables, no edge features (the fused programs keep the
  reference fast path's scope: supervised node classification).
  """

  _NAME = 'FusedEpochTrainer'

  def __init__(self, loader: NodeLoader, model, tx, num_classes: int,
               seed_labels_only: Optional[bool] = None):
    sampler = loader.sampler
    if getattr(sampler, 'is_hetero', False):
      raise ValueError(f'{self._NAME} is homogeneous-only')
    if not sampler.fused:
      raise ValueError(f'{self._NAME} needs the fused sampler path')
    if sampler.with_edge:
      raise ValueError('with_edge batches are not supported in the '
                       'fused epoch programs')
    if getattr(sampler, 'clamped_exact', False) and \
        loader.overflow_policy == 'recompute':
      raise ValueError(_RECOMPUTE_MSG)
    self.loader = loader
    self.model = model
    self.num_classes = num_classes
    self._sampler = sampler
    self._batch_size = loader.batch_size
    fanouts = tuple(sampler.num_neighbors)
    self._sample_fn = sampler._homo_fn(self._batch_size, fanouts)
    if seed_labels_only is None:
      seed_labels_only = loader.seed_labels_only
    self._label_cap = self._batch_size if seed_labels_only else None

    dt = loader.data.node_features.device_table() \
        if loader.data.node_features is not None else None
    if dt is None:
      raise ValueError(f'{self._NAME} needs a device-resident '
                       'feature table (Feature on HBM)')
    self._feats, self._id2i = dt
    self._labels = loader._label_table()
    if self._labels is None:
      raise ValueError(f'{self._NAME} needs node labels')

    from ..models import train as train_lib
    self._train_step, _ = train_lib.make_train_step(model, tx, num_classes)

    sample_fn, label_cap = self._sample_fn, self._label_cap

    def _sample_collate(fargs, feats, id2i, labels, seeds, smask, key):
      res = sample_fn(*fargs, seeds, smask, key)
      col = ops.collate_batch(res['node'], res['num_nodes'], res['row'],
                              res['col'], feats, id2i, labels, None, None,
                              label_cap=label_cap)
      batch = dict(x=col['x'], edge_index=col['edge_index'],
                   edge_mask=res['edge_mask'], y=col['y'],
                   num_seed_nodes=res['num_sampled_nodes'][0])
      # the calibrated-caps truncation flag rides OUTSIDE the batch dict
      # (train_step must not see it; the batch buffers are donated)
      return batch, res['overflow']

    self._sample_collate = _sample_collate


class OverlappedTrainer(FusedEpochTrainer):
  """Fuses batch n's train step with batch n+1's sample+collate."""

  _NAME = 'OverlappedTrainer'

  def __init__(self, loader: NodeLoader, model, tx, num_classes: int,
               seed_labels_only: Optional[bool] = None):
    import jax
    super().__init__(loader, model, tx, num_classes, seed_labels_only)

    _sample_collate = self._sample_collate
    train_step = self._train_step

    def _fused(state, batch, ovf, pending, fargs, feats, id2i, labels,
               seeds, smask, key):
      # two independent subgraphs in one program: XLA may interleave
      new_state, loss, acc = train_step(state, batch)
      next_batch, next_pending = _sample_collate(fargs, feats, id2i,
                                                 labels, seeds, smask, key)
      # overflow accumulates on device — zero host syncs in the hot
      # loop. ``pending`` is the flag of the batch being trained NOW;
      # next_pending stays out of the accumulator until its batch is
      # actually consumed (a dropped prefetch must not taint the epoch)
      return new_state, loss, acc, next_batch, ovf | pending, next_pending

    # donate the consumed batch buffers (state update buffers are small
    # relative to the 938k-slot batch; donation keeps HBM flat at two
    # batches in flight)
    self._prime_fn = jax.jit(_sample_collate)
    self._fused_fn = jax.jit(_fused, donate_argnums=(1,))

  # ---------------------------------------------------------------- loop

  def _seed_batches(self):
    for idx in self.loader._batcher:
      seeds = self.loader.input_seeds[idx]
      n = seeds.shape[0]
      padded = np.zeros((self._batch_size,), np.int32)
      padded[:n] = seeds
      yield padded, np.arange(self._batch_size) < n

  def _dispatch_prime(self, padded, mask):
    import jax.numpy as jnp
    from ..utils.trace import record_dispatch
    record_dispatch('prime')
    return self._prime_fn(self._sampler._fused_args(), self._feats,
                          self._id2i, self._labels, jnp.asarray(padded),
                          jnp.asarray(mask), self._sampler._next_key())

  def run_epoch(self, state, max_steps: Optional[int] = None):
    """One epoch of overlapped steps. Returns (state, losses) with
    ``losses`` a list of device scalars (one per step) — fetch once,
    after the epoch, to keep the hot loop pipelined."""
    import jax.numpy as jnp
    from ..utils.trace import record_dispatch
    # _seed_batches walks loader._batcher directly (bypassing
    # NodeLoader.__iter__), so the per-epoch padded-table reseed must be
    # driven explicitly — same counter as plain iteration
    # re-evaluate the guard each epoch (a post-construction policy
    # change must take effect, like the plain loader's epoch start) —
    # BEFORE _begin_epoch, so a refused epoch doesn't consume a
    # padded-table reseed and drift later epochs' windows
    guarded, recompute = self.loader._overflow_epoch_start()
    if recompute:
      raise ValueError(_RECOMPUTE_MSG)
    self.loader._begin_epoch()
    losses = []
    batch = None
    ovf = jnp.zeros((), bool)   # flags of batches actually trained
    pending = None              # flag of the in-flight (sampled) batch
    truncated = False
    for padded, mask in self._seed_batches():
      if batch is None:
        batch, pending = self._dispatch_prime(padded, mask)
        continue
      record_dispatch('fused_step')
      state, loss, _, batch, ovf, pending = self._fused_fn(
          state, batch, ovf, pending, self._sampler._fused_args(),
          self._feats, self._id2i, self._labels, jnp.asarray(padded),
          jnp.asarray(mask), self._sampler._next_key())
      losses.append(loss)
      if max_steps is not None and len(losses) >= max_steps:
        truncated = True
        break
    if batch is not None and not truncated:
      # natural epoch end: flush the last sampled batch with a plain
      # train step. A max_steps break drops the pending batch instead —
      # exactly max_steps optimizer updates, step-exact for benchmarks
      # and LR schedules.
      record_dispatch('train_step')
      state, loss, _ = self._train_step(state, batch)
      losses.append(loss)
      ovf = jnp.logical_or(ovf, pending)
    if guarded:
      # hand the device-accumulated flag to the loader's guard: natural
      # epoch end applies overflow_policy ('raise'/'warn'); a max_steps
      # break leaves it for loader.check_overflow(). Only trained
      # batches count — a dropped prefetch's flag is discarded with it.
      self.loader._ovf_accum = ovf
      if not truncated:
        self.loader._finish_epoch_overflow()
    return state, losses
