"""Overlapped (double-buffered) sample -> collate -> train pipeline.

The reference's defining architecture is the asynchronous producer-consumer
pipeline: sampling runs decoupled from training and its latency hides
behind the train step
(/root/reference/graphlearn_torch/python/distributed/dist_sampling_producer.py:53-151;
docs/get_started/dist_train.md:3-8 "asynchronous producer consumer model",
via CUDA streams / separate processes).

A single TPU core has no concurrent streams — XLA programs execute one at
a time — so the TPU-native equivalent is PROGRAM FUSION with software
double-buffering: batch n's train step and batch n+1's sample+collate are
traced into ONE XLA program with no data dependency between the two
subgraphs. XLA's scheduler is then free to interleave the sampler/collate
work (DMA-latency/HBM-bound gathers) with the train step's MXU-bound
matmul pipeline, which is exactly the resource overlap the reference gets
from its producer streams. Whether the scheduler exploits it is an
empirical question — bench.py measures the fused step against the serial
sum with device-trace truth (PERF.md reports the measured overlap).

Usage:
    loader = NeighborLoader(ds, fanouts, idx, batch_size=B, ...)
    trainer = OverlappedTrainer(loader, model, tx, num_classes)
    state, losses = trainer.run_epoch(state)   # losses stay on device

The host loop stays dispatch-only (no device->host fetches, PERF.md
rules); fetch the returned loss array once per epoch if needed.
"""
from typing import Optional

import numpy as np

from .. import ops
from .node_loader import NodeLoader

_RECOMPUTE_MSG = (
    "overflow_policy='recompute' needs a device->host sync per batch, "
    'which defeats the overlapped pipeline. Use the plain loader loop '
    "for recompute, or overflow_policy='raise'/'warn' here (the flag "
    'accumulates on device and is checked once at epoch end).')

_DIST_REMOTE_MSG = (
    'scanned/fused distributed epochs are COLLOCATED-MESH only: pass a '
    'DistNeighborLoader over the training mesh. Remote (server-client) '
    'loaders have their own scanned path — distributed.'
    'RemoteScanTrainer, the chunk-staged hybrid (docs/remote_scan.md): '
    'sampling servers replay the counter-addressed stream into K-batch '
    'blocks, the client double-buffers block c+1 over RPC while chunk '
    'c trains, and acks/failover run at CHUNK granularity — exact '
    'even under shuffle=True, whose epoch permutation is a pure '
    'function of (seed, epoch) that survivors replay identically. '
    'Mp-worker loaders keep the '
    'per-step host loop: their worker-restart replay acks batches one '
    'by one (docs/failure_model.md).')


class FusedEpochTrainer:
  """Shared plumbing for the fused epoch executors (OverlappedTrainer,
  scan_epoch.ScanTrainer): scope validation, the device feature/label
  tables, and the pure sample+collate body both trainers trace into
  their programs.

  Requirements: homogeneous graph, fused sampler, device-resident
  feature/label tables, no edge features (the fused programs keep the
  reference fast path's scope: supervised node classification).
  """

  _NAME = 'FusedEpochTrainer'

  def __init__(self, loader: NodeLoader, model, tx, num_classes: int,
               seed_labels_only: Optional[bool] = None):
    sampler = loader.sampler
    if getattr(sampler, 'is_hetero', False):
      # the LOCAL fused trainer is the homo degenerate by design —
      # typed datasets ride the dist/remote/tiered scan trainers whose
      # CapacityPlans close the per-ntype shapes
      # graftlint: allow[hetero-gate] local trainer is homo by design
      raise ValueError(f'{self._NAME} is homogeneous-only')
    if not sampler.fused:
      raise ValueError(f'{self._NAME} needs the fused sampler path')
    if sampler.with_edge:
      raise ValueError('with_edge batches are not supported in the '
                       'fused epoch programs')
    if getattr(sampler, 'clamped_exact', False) and \
        loader.overflow_policy == 'recompute':
      raise ValueError(_RECOMPUTE_MSG)
    self.loader = loader
    self.model = model
    self.num_classes = num_classes
    self._sampler = sampler
    self._batch_size = loader.batch_size
    fanouts = tuple(sampler.num_neighbors)
    self._sample_fn = sampler._homo_fn(self._batch_size, fanouts)
    if seed_labels_only is None:
      seed_labels_only = loader.seed_labels_only
    self._label_cap = self._batch_size if seed_labels_only else None

    self._feats, self._id2i = self._resolve_feature_tables(loader)
    self._labels = loader._label_table()
    if self._labels is None:
      raise ValueError(f'{self._NAME} needs node labels')

    from ..models import train as train_lib
    self._train_step, _ = train_lib.make_train_step(model, tx, num_classes)
    self._sample_collate = self._make_sample_collate_body()

  def _resolve_feature_tables(self, loader):
    """(feats, id2index) device tables the traced programs gather from.
    The base contract is an ALL-HBM table; the out-of-core trainer
    (storage/scan.py TieredScanTrainer) overrides this to accept a
    TieredFeature's hot prefix + per-chunk staged slabs."""
    dt = loader.data.node_features.device_table() \
        if loader.data.node_features is not None else None
    if dt is None:
      raise ValueError(f'{self._NAME} needs a device-resident '
                       'feature table (Feature on HBM), or the tiered '
                       'trainer (storage.TieredScanTrainer) for an '
                       'out-of-core TieredFeature')
    return dt

  def _make_sample_collate_body(self):
    """The pure traced sample+collate body. ``feats`` is whatever
    pytree :meth:`_resolve_feature_tables` produced — here a plain
    [N, F] table fed straight to the fused collate gather."""
    sample_fn, label_cap = self._sample_fn, self._label_cap

    def _sample_collate(fargs, feats, id2i, labels, seeds, smask, key):
      res = sample_fn(*fargs, seeds, smask, key)
      col = ops.collate_batch(res['node'], res['num_nodes'], res['row'],
                              res['col'], feats, id2i, labels, None, None,
                              label_cap=label_cap)
      batch = dict(x=col['x'], edge_index=col['edge_index'],
                   edge_mask=res['edge_mask'], y=col['y'],
                   num_seed_nodes=res['num_sampled_nodes'][0])
      # the calibrated-caps truncation flag rides OUTSIDE the batch dict
      # (train_step must not see it; the batch buffers are donated)
      return batch, res['overflow']

    return _sample_collate


class OverlappedTrainer(FusedEpochTrainer):
  """Fuses batch n's train step with batch n+1's sample+collate."""

  _NAME = 'OverlappedTrainer'

  def __init__(self, loader: NodeLoader, model, tx, num_classes: int,
               seed_labels_only: Optional[bool] = None):
    import jax
    super().__init__(loader, model, tx, num_classes, seed_labels_only)

    _sample_collate = self._sample_collate
    train_step = self._train_step

    def _fused(state, batch, ovf, pending, fargs, feats, id2i, labels,
               seeds, smask, key):
      # two independent subgraphs in one program: XLA may interleave
      new_state, loss, acc = train_step(state, batch)
      next_batch, next_pending = _sample_collate(fargs, feats, id2i,
                                                 labels, seeds, smask, key)
      # overflow accumulates on device — zero host syncs in the hot
      # loop. ``pending`` is the flag of the batch being trained NOW;
      # next_pending stays out of the accumulator until its batch is
      # actually consumed (a dropped prefetch must not taint the epoch)
      return new_state, loss, acc, next_batch, ovf | pending, next_pending

    # donate the consumed batch buffers (state update buffers are small
    # relative to the 938k-slot batch; donation keeps HBM flat at two
    # batches in flight)
    from ..metrics import programs
    self._prime_fn = programs.instrument(jax.jit(_sample_collate),
                                         'prime')
    self._fused_fn = programs.instrument(
        jax.jit(_fused, donate_argnums=(1,)), 'fused_step')

  # ---------------------------------------------------------------- loop

  def _seed_batches(self):
    for idx in self.loader._batcher:
      seeds = self.loader.input_seeds[idx]
      n = seeds.shape[0]
      padded = np.zeros((self._batch_size,), np.int32)
      padded[:n] = seeds
      yield padded, np.arange(self._batch_size) < n

  def _dispatch_prime(self, padded, mask):
    import jax.numpy as jnp
    from ..utils.trace import record_dispatch
    record_dispatch('prime')
    return self._prime_fn(self._sampler._fused_args(), self._feats,
                          self._id2i, self._labels, jnp.asarray(padded),
                          jnp.asarray(mask), self._sampler._next_key())

  def run_epoch(self, state, max_steps: Optional[int] = None):
    """One epoch of overlapped steps. Returns (state, losses) with
    ``losses`` a list of device scalars (one per step) — fetch once,
    after the epoch, to keep the hot loop pipelined."""
    import jax.numpy as jnp

    from ..metrics import flight
    from ..utils.trace import record_dispatch
    # _seed_batches walks loader._batcher directly (bypassing
    # NodeLoader.__iter__), so the per-epoch padded-table reseed must be
    # driven explicitly — same counter as plain iteration
    # re-evaluate the guard each epoch (a post-construction policy
    # change must take effect, like the plain loader's epoch start) —
    # BEFORE _begin_epoch, so a refused epoch doesn't consume a
    # padded-table reseed and drift later epochs' windows
    guarded, recompute = self.loader._overflow_epoch_start()
    if recompute:
      raise ValueError(_RECOMPUTE_MSG)
    self.loader._begin_epoch()
    flight_tok = flight.epoch_begin()
    losses = []
    completed = False
    truncated = False
    try:
      batch = None
      ovf = jnp.zeros((), bool)   # flags of batches actually trained
      pending = None              # flag of the in-flight (sampled) batch
      for padded, mask in self._seed_batches():
        if batch is None:
          batch, pending = self._dispatch_prime(padded, mask)
          continue
        record_dispatch('fused_step')
        state, loss, _, batch, ovf, pending = self._fused_fn(
            state, batch, ovf, pending, self._sampler._fused_args(),
            self._feats, self._id2i, self._labels, jnp.asarray(padded),
            jnp.asarray(mask), self._sampler._next_key())
        losses.append(loss)
        if max_steps is not None and len(losses) >= max_steps:
          truncated = True
          break
      if batch is not None and not truncated:
        # natural epoch end: flush the last sampled batch with a plain
        # train step. A max_steps break drops the pending batch instead
        # — exactly max_steps optimizer updates, step-exact for
        # benchmarks and LR schedules.
        record_dispatch('train_step')
        state, loss, _ = self._train_step(state, batch)
        losses.append(loss)
        ovf = jnp.logical_or(ovf, pending)
      completed = True
      if guarded:
        # hand the device-accumulated flag to the loader's guard:
        # natural epoch end applies overflow_policy ('raise'/'warn'); a
        # max_steps break leaves it for loader.check_overflow(). Only
        # trained batches count — a dropped prefetch's flag is
        # discarded with it.
        self.loader._ovf_accum = ovf
        if not truncated:
          self.loader._finish_epoch_overflow()
    finally:
      # per-epoch flight record (metrics/flight.py) — host deltas only;
      # a mid-epoch failure still records, with completed=False
      flight.end_for(
          self, flight_tok, emitter=self._NAME, steps=len(losses),
          completed=completed,
          config=dict(trainer=self._NAME, batch_size=self._batch_size,
                      fanouts=list(self._sampler.num_neighbors),
                      num_classes=self.num_classes,
                      seed=self.loader._batcher.seed),
          extra={'truncated': truncated})
    return state, losses


class DistFusedEpochTrainer:
  """Shared plumbing for the DISTRIBUTED fused-epoch executors
  (scan_epoch.DistScanTrainer and its per-step reference loop): scope
  validation, the data-parallel train-step body (per-shard grads ->
  pmean over every mesh axis -> optax update), and the traced
  sample+collate body both the scanned chunks and the per-step program
  compose.

  Scope: a COLLOCATED homogeneous or heterogeneous DistNeighborLoader
  with feature collection and node labels (supervised node
  classification on the mesh — the distributed counterpart of
  FusedEpochTrainer's scope). Remote/mp loaders are rejected
  (``_DIST_REMOTE_MSG``): their failover contract needs per-batch host
  visibility. ``overflow_policy='recompute'`` is rejected exactly like
  the local trainers (per-batch host sync).
  """

  _NAME = 'DistFusedEpochTrainer'

  def __init__(self, loader, model, tx, num_classes: int,
               seed_labels_only: Optional[bool] = None):
    from ..distributed.dist_loader import (DistLinkNeighborLoader,
                                           DistLoader, DistSubGraphLoader)
    from ..models import train as train_lib
    if not isinstance(loader, DistLoader):
      raise ValueError(f'{self._NAME}: {type(loader).__name__} is not a '
                       f'collocated DistLoader. {_DIST_REMOTE_MSG}')
    if isinstance(loader, DistLinkNeighborLoader):
      raise ValueError(
          f'{self._NAME} covers supervised NODE classification; link '
          'loaders keep the per-step loop — link batches train on '
          'edge_label metadata the fused chunk program does not '
          'collate, and they carry no per-seed ack provenance for any '
          'chunk- or batch-granular failover (docs/failure_model.md '
          "'Limits'; the chunk-staged remote path, "
          'distributed.RemoteScanTrainer, is node-only for the same '
          'reason)')
    if isinstance(loader, DistSubGraphLoader):
      raise ValueError(
          f'{self._NAME} covers supervised NODE classification; '
          'subgraph loaders yield induced subgraphs with no '
          'train-step contract to fuse into a scanned chunk — '
          'iterate them per step')
    if loader.overflow_policy == 'recompute':
      raise ValueError(_RECOMPUTE_MSG)
    sampler = loader.sampler
    if sampler.with_edge:
      raise ValueError('with_edge batches are not supported in the '
                       'fused distributed epoch programs')
    if getattr(loader.data, 'edge_features', None):
      raise ValueError(f'{self._NAME} does not collate edge features; '
                       'use the per-step loader loop')
    if not loader.collect_features or sampler.dist_feature is None:
      raise ValueError(f'{self._NAME} needs collect_features=True and a '
                       'DistFeature store (the fused program inlines the '
                       'cached miss-only lookup)')
    if loader.data.node_labels is None:
      raise ValueError(f'{self._NAME} needs node labels')
    self.loader = loader
    self.model = model
    self.tx = tx
    self.num_classes = num_classes
    self._sampler = sampler
    self.is_hetero = sampler.is_hetero
    self.mesh = sampler.mesh
    self._axes = sampler._axes
    self._axis_sizes = sampler._axis_sizes
    self._nparts = loader.num_partitions
    self._batch_size = loader.batch_size    # per shard
    if seed_labels_only is None:
      seed_labels_only = loader.seed_labels_only
    self._label_cap = self._batch_size if seed_labels_only else None
    if self.is_hetero:
      self._input_type = loader.input_type
      assert self._input_type is not None, \
          'hetero distributed training requires typed seeds'
      labels = loader.data.node_labels
      if not isinstance(labels, dict) or self._input_type not in labels:
        raise ValueError(f'{self._NAME} needs labels for the seed type '
                         f'{self._input_type!r}')
      self._label_store = sampler._label_dist(labels[self._input_type],
                                              self._input_type)
      self._feat = dict(sampler.dist_feature)
    else:
      self._input_type = None
      self._label_store = sampler._label_dist(loader.data.node_labels)
      self._feat = sampler.dist_feature
    self._loss_fn = train_lib.make_loss_fn(model, num_classes)
    self._train_state_cls = train_lib.TrainState
    self._step_fn = None   # built lazily (first per-step train_step)

  # -------------------------------------------------------- traced bodies

  def _dp_step_body(self, state, batch):
    """Per-shard data-parallel update (traced): grads/loss/acc pmean'd
    over EVERY mesh axis — the SPMD analog of the reference's DDP
    allreduce — then one optax update of the replicated state."""
    import jax
    (loss, acc), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
        state.params, batch)
    grads = jax.lax.pmean(grads, self._axes)
    loss = jax.lax.pmean(loss, self._axes)
    acc = jax.lax.pmean(acc, self._axes)
    updates, opt_state = self.tx.update(grads, state.opt_state,
                                        state.params)
    import optax
    params = optax.apply_updates(state.params, updates)
    return self._train_state_cls(params, opt_state, state.step + 1), \
        loss, acc

  def _make_sample_collate(self):
    """Traced per-shard sample -> feature/label collate body shared by
    the scanned chunks (scan_epoch.DistScanTrainer) — the in-program
    equivalent of loader.__iter__'s sample_from_nodes + _collate_fn
    path, threading the feature-cache stats rows instead of the
    device-resident accumulator.

    Returns ``(shard_tree, repl_tree, body)`` where ``body(views, repl,
    stats_rows, seeds, smask, key) -> (batch, overflow,
    new_stats_rows)``; ``views`` is the per-shard ([0]-indexed) view of
    ``shard_tree`` and the trees are the device arrays to feed the
    enclosing shard_map (every ``shard_tree`` leaf takes spec P(axes),
    every ``repl_tree`` leaf P())."""
    import jax.numpy as jnp
    sampler = self._sampler
    b = self._batch_size
    label_cap = self._label_cap
    if self.is_hetero:
      return self._make_hetero_sample_collate()
    from ..distributed.dist_neighbor_sampler import _homo_hop_loop
    fanouts = tuple(sampler.num_neighbors)
    caps = sampler._capacities(b)
    node_cap = sampler._node_cap(caps)
    dedup = sampler.dedup
    weighted = sampler._weighted_for()
    bucket_frac = sampler.bucket_frac
    ax, sizes, nparts = self._axes, self._axis_sizes, self._nparts
    feat_body = self._feat._shard_body(node_cap)
    lab_body = self._label_store._shard_body(
        label_cap if label_cap is not None else node_cap)
    d = sampler._dev
    gsh = {k: d[k] for k in ('row_ids', 'indptr', 'indices', 'eids')}
    if weighted:
      gsh['wcum'] = d['wcum']
    fdev = self._feat.device_arrays()
    ldev = self._label_store.device_arrays()
    shard_tree = dict(
        g=gsh,
        f={k: fdev[k] for k in ('feat_ids', 'feats')},
        l={k: ldev[k] for k in ('feat_ids', 'feats')})
    repl_tree = dict(
        pb=d['node_pb'],
        f={k: fdev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')},
        l={k: ldev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')})

    def body(views, repl, stats_rows, seeds, smask, key):
      res = _homo_hop_loop(views['g'], repl['pb'], seeds, smask, key,
                           fanouts, caps, node_cap, nparts, False,
                           weighted, dedup=dedup,
                           bucket_frac=bucket_frac, axes=ax,
                           axis_sizes=sizes)
      ids = res['node']
      fv, frep = views['f'], repl['f']
      x, srow = feat_body(fv['feat_ids'], fv['feats'],
                          frep['feature_pb'], frep['cache_ids'],
                          frep['cache_feats'], stats_rows, ids, ids >= 0)
      lab_ids = ids[:label_cap] if label_cap is not None else ids
      lv, lrep = views['l'], repl['l']
      y, _ = lab_body(lv['feat_ids'], lv['feats'], lrep['feature_pb'],
                      lrep['cache_ids'], lrep['cache_feats'],
                      jnp.zeros((4,), jnp.int32), lab_ids, lab_ids >= 0)
      batch = dict(x=x,
                   edge_index=jnp.stack([res['row'], res['col']]),
                   edge_mask=res['edge_mask'], y=y[:, 0],
                   num_seed_nodes=res['num_sampled_nodes'][0])
      return batch, res['overflow'], srow

    return shard_tree, repl_tree, body

  def _make_hetero_sample_collate(self):
    """Typed counterpart of _make_sample_collate: the engine's typed
    hop loop + per-ntype cached feature lookups (stats row per store) +
    the seed type's label gather."""
    import jax.numpy as jnp
    sampler = self._sampler
    b = self._batch_size
    label_cap = self._label_cap
    t_in = self._input_type
    plan = sampler._hetero_plan({t_in: b})
    _, _, node_caps = plan
    feat_types = [t for t in sampler.graph.ntypes
                  if node_caps.get(t, 0) > 0 and t in self._feat]
    # the stores whose [4] stats rows thread the scan carry (one per
    # sampled, feature-bearing ntype) — DistScanTrainer reads this to
    # shape the carry and write the accumulators back per epoch
    self._feat_types = feat_types
    feat_bodies = {t: self._feat[t]._shard_body(node_caps[t])
                   for t in feat_types}
    lab_body = self._label_store._shard_body(
        label_cap if label_cap is not None else node_caps[t_in])
    d = sampler._dev
    gsh = {}
    for et in sampler.graph.etypes:
      ga = d[et]
      gsh[et] = {k: ga[k] for k in ('row_ids', 'indptr', 'indices',
                                    'eids')}
      if sampler._weighted_for(et):
        gsh[et]['wcum'] = ga['wcum']
    fdevs = {t: self._feat[t].device_arrays() for t in feat_types}
    ldev = self._label_store.device_arrays()
    shard_tree = dict(
        g=gsh,
        f={t: {k: fdevs[t][k] for k in ('feat_ids', 'feats')}
           for t in feat_types},
        l={k: ldev[k] for k in ('feat_ids', 'feats')})
    repl_tree = dict(
        pb=dict(d['#pb']),
        f={t: {k: fdevs[t][k] for k in ('feature_pb', 'cache_ids',
                                        'cache_feats')}
           for t in feat_types},
        l={k: ldev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')})

    def body(views, repl, stats_rows, seeds, smask, key):
      res, _ = sampler._hetero_engine(views['g'], repl['pb'],
                                      {t_in: (seeds, smask)}, key, plan)
      x, new_rows = {}, {}
      for t in feat_types:
        ids = res['node'][t]
        fv, frep = views['f'][t], repl['f'][t]
        x[t], new_rows[t] = feat_bodies[t](
            fv['feat_ids'], fv['feats'], frep['feature_pb'],
            frep['cache_ids'], frep['cache_feats'], stats_rows[t], ids,
            ids >= 0)
      ids = res['node'][t_in]
      lab_ids = ids[:label_cap] if label_cap is not None else ids
      lv, lrep = views['l'], repl['l']
      y, _ = lab_body(lv['feat_ids'], lv['feats'], lrep['feature_pb'],
                      lrep['cache_ids'], lrep['cache_feats'],
                      jnp.zeros((4,), jnp.int32), lab_ids, lab_ids >= 0)
      ei = {et: jnp.stack([res['row'][et], res['col'][et]])
            for et in res['row']}
      batch = dict(x=x, edge_index=ei, edge_mask=res['edge_mask'],
                   y=y[:, 0],
                   num_seed_nodes=res['num_sampled_nodes'][t_in][0])
      return batch, res['overflow'], new_rows

    return shard_tree, repl_tree, body

  # ------------------------------------------------- per-step reference

  def _build_step_fn(self):
    """The per-step data-parallel train program (ONE dispatch per
    optimizer update): shard_map over the mesh, per-shard batch views,
    pmean'd grads, replicated state in/out."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map
    ax = self._axes
    dp = self._dp_step_body

    def body(state, x, ei, em, y, nseed):
      view = lambda t: jax.tree.map(lambda a: a[0], t)
      batch = dict(x=view(x), edge_index=view(ei), edge_mask=view(em),
                   y=y[0], num_seed_nodes=nseed[0])
      return dp(state, batch)

    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P(), P(ax), P(ax), P(ax), P(ax), P(ax)),
        out_specs=(P(), P(), P()), check_replication=False)
    return jax.jit(fn)

  def train_step(self, state, batch):
    """One data-parallel optimizer update from a collocated dist batch
    (the loader's stacked Data/HeteroData). Returns
    ``(state, loss, acc)`` — loss/acc replicated device scalars."""
    import jax.numpy as jnp

    from ..metrics import programs
    from ..utils.trace import record_dispatch
    if self._step_fn is None:
      self._step_fn = programs.instrument(self._build_step_fn(),
                                          'dist_train_step')
    if self.is_hetero:
      y = batch.y[self._input_type]
      nseed = jnp.asarray(batch.num_sampled_nodes[self._input_type])[:, 0]
    else:
      y = batch.y
      nseed = jnp.asarray(batch.num_sampled_nodes)[:, 0]
    record_dispatch('dist_train_step')
    return self._step_fn(state, batch.x, batch.edge_index,
                         batch.edge_mask, y, nseed)

  def run_epoch_steps(self, state, max_steps: Optional[int] = None):
    """The PER-STEP reference epoch: iterate the collocated loader
    (sample + collate dispatches per batch) and apply the data-parallel
    step per batch — the loop the scanned epoch must replay
    bit-identically (shuffle=False) and the A/B baseline for the
    dispatch-count story. Returns (state, losses) with ``losses`` a
    list of replicated device scalars."""
    losses = []
    for batch in self.loader:
      state, loss, _ = self.train_step(state, batch)
      losses.append(loss)
      if max_steps is not None and len(losses) >= max_steps:
        break
    return state, losses
