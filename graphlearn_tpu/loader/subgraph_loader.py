"""SubGraphLoader: k-hop induced-subgraph batches.

TPU-native port of
/root/reference/graphlearn_torch/python/loader/subgraph_loader.py: each
batch is the full induced subgraph over the k-hop expansion of the seeds,
with ``mapping`` metadata locating each seed in the node list.
"""
from typing import Optional

from ..data import Dataset
from ..sampler import NeighborSampler, NodeSamplerInput
from .node_loader import NodeLoader


class SubGraphLoader(NodeLoader):
  """Reference: loader/subgraph_loader.py:27-98."""

  def __init__(self, data: Dataset, num_neighbors, input_nodes,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, to_device=None,
               seed: Optional[int] = None,
               max_degree: Optional[int] = None, bucketed: bool = False,
               cap_large: Optional[int] = None):
    sampler = NeighborSampler(
        data.graph, num_neighbors, device=to_device, with_edge=with_edge,
        edge_dir=data.edge_dir, seed=seed)
    super().__init__(data, sampler, input_nodes, batch_size, shuffle,
                     drop_last, with_edge, collect_features, to_device,
                     seed)
    self.max_degree = max_degree
    self.bucketed = bucketed
    self.cap_large = cap_large

  def __iter__(self):
    for idx in self._batcher:
      seeds = self.input_seeds[idx]
      out = self.sampler.subgraph(
          NodeSamplerInput(seeds, self.input_type),
          max_degree=self.max_degree, bucketed=self.bucketed,
          cap_large=self.cap_large)
      yield self._collate_fn(out)
