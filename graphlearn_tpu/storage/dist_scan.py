"""TieredDistScanTrainer: device oversubscription THROUGH the shard
exchange.

``DistScanTrainer`` runs a collocated-mesh epoch as ceil(steps/K)+2
dispatches, but every shard's HBM still holds its FULL feature
partition — the in-program all_to_all must be able to answer any
remote request. This trainer erases that boundary (ROADMAP item 2;
PyTorch-Direct, arxiv 2101.07956, and GPU-initiated direct storage,
arxiv 2306.16384, are the GPU-world exemplars — this is the multi-host
TPU instance):

* **Hot prefix per shard.** Each shard's HBM holds only positions
  ``[0, H)`` of its sorted partition table
  (``TieredDistFeature.dist_scan_tables``) plus a double-buffered
  pow2-padded exchange slab; the rest of the partition lives in the
  store's host/disk tiers.
* **Miss-exchange program.** The epoch prologue extends the seed
  program with an id-only replay of the distributed sampler over every
  step — the SAME ``split(fold_in(base_key, count), P)`` keys the chunk
  programs derive, so the draws are bit-identical by the PR 4 replay
  contract — still ONE ``dist_epoch_seeds`` dispatch. The fetched
  [P, steps, node_cap] request matrix is the prologue's one explicit
  ``jax.device_get``; ``planner.plan_exchange`` turns it into the exact
  per-chunk program: which POSITIONS of each shard's table its peers
  will request during each chunk, beyond the replicated hot cache and
  the HBM hot prefix.
* **Chunk-boundary slab staging.** While chunk ``c`` trains, a
  ``DistChunkStager`` worker gathers chunk ``c+1``'s planned positions
  from the per-partition tiers into a [P, cap] / [P, cap, F] host slab
  (pow2 ``cap`` = the chunk's max per-shard count — one executable per
  (chunk length, slab cap)); the dispatch thread device_puts it sharded
  over the mesh and dispatches the chunk.
* **In-program slab-backed exchange.** The chunk program's feature
  lookup is ``DistFeature._shard_body(slab=True)``: a remote request
  resolves its position exactly as before, then gathers ``hot[pos]``
  for positions < H and ``slab[searchsorted(slab_pos, pos)]`` for the
  rest — under the exact plan every staged bytes equals the all-HBM
  row, so LOSSES AND PARAMS ARE BIT-IDENTICAL to ``DistScanTrainer``
  at the unchanged ceil(steps/K)+2 dispatch budget.
* **Degradation, never corruption.** A failed/slow staging worker
  degrades to a synchronous gather of the same planned positions
  (``storage.prefetch_miss``); the chaos suite completes the epoch
  bit-identically with a ``storage.dist_stage`` fault armed
  (docs/failure_model.md).

Scope: collocated meshes (flat or 2-axis hierarchical — the
slab-backed lookup rides both exchange forms), homogeneous or
heterogeneous. Hetero stores are a ``{ntype: TieredDistFeature}``
dict whose per-ntype closed shapes come from the stream's CapacityPlan
(docs/capacity_plans.md): the prologue replays the typed engine
id-only, plans ONE exchange per feature-bearing ntype, and each chunk
stages one slab per ntype — the homo path is the single-ntype
degenerate case of the same machinery. Labels stay a full (small)
DistFeature. Single-process meshes: the prologue fetch and the stager
read the whole [P, ...] request matrix / tier set locally.
"""
from typing import Optional

import numpy as np

from .. import metrics
from ..loader.scan_epoch import DistScanTrainer
from ..sampler import CapacityPlanError
from ..utils.faults import fault_point
from ..utils.trace import record_dispatch
from . import planner
from .dist import TieredDistFeature
from .staging import INT32_MAX, ChunkStager, pow2_slab_cap


class DistChunkStager(ChunkStager):
  """ChunkStager whose plan rows are ENCODED ``p * n_max + position``
  addresses (planner.ExchangePlan) and whose slabs come back in the
  [P, cap] per-shard layout the shard_map chunk program consumes.
  Pad slots carry INT32_MAX positions (never match a searchsorted);
  per-shard position lists stay sorted because the encoded plan is."""

  def _stage_fault(self):
    # the dist pipeline's own registered chaos site — worker-only, so
    # take()'s synchronous fallback still gathers cleanly
    fault_point('storage.dist_stage')

  def _gather(self, enc: np.ndarray):
    store = self.store
    nparts, n_max = store.num_partitions, store.n_max
    enc = np.asarray(enc, np.int64)
    owners = enc // n_max
    pos = enc % n_max
    counts = (np.bincount(owners, minlength=nparts) if enc.size
              else np.zeros((nparts,), np.int64))
    cap = pow2_slab_cap(int(counts.max()) if enc.size else 1)
    ids = np.full((nparts, cap), INT32_MAX, np.int32)
    rows = np.zeros((nparts, cap, store.feature_dim),
                    store.storage_dtype)
    for p in range(nparts):
      kp = int(counts[p])
      if kp:
        m = owners == p
        ids[p, :kp] = pos[m].astype(np.int32)
        rows[p, :kp] = store.gather_positions(p, pos[m])
    metrics.inc('storage.dist_staged_rows', int(enc.shape[0]))
    return ids, rows


class TieredDistScanTrainer(DistScanTrainer):
  """DistScanTrainer over a ``TieredDistFeature`` whose HBM holds only
  each shard's hot prefix + the in-flight exchange slabs (module
  docstring).

  Args (beyond DistScanTrainer's):
    max_ahead: staged chunks in flight (2 = double buffer).
    stage_timeout_s: how long a chunk boundary waits for its slab
      before degrading to a synchronous gather.
  """

  _NAME = 'TieredDistScanTrainer'
  _TOPOLOGY = 'tiered_dist'

  def __init__(self, loader, model, tx, num_classes: int,
               chunk_size: Optional[int] = None,
               seed_labels_only: Optional[bool] = None,
               perm_seed: Optional[int] = None, max_ahead: int = 2,
               stage_timeout_s: float = 30.0, config=None):
    sampler = getattr(loader, 'sampler', None)
    store = getattr(sampler, 'dist_feature', None)
    # homo or hetero, ONE store contract: every feature store the chunk
    # program reads must be a TieredDistFeature with a hot prefix — the
    # per-ntype slab capacities of the hetero exchange (and the single
    # slab of the homo degenerate plan) come from these stores' sorted
    # row tables (docs/capacity_plans.md)
    stores = store if isinstance(store, dict) else \
        ({None: store} if store is not None else {})
    bad = sorted(f'{t}:{type(s).__name__}' for t, s in stores.items()
                 if not isinstance(s, TieredDistFeature))
    if not stores or bad:
      raise CapacityPlanError(
          self._NAME,
          'the feature store set carries no per-ntype slab capacities '
          f'(non-tiered stores: {bad or "<empty>"})',
          hint='build every feature store as storage.TieredDistFeature('
               'hot_prefix_rows >= 1) so the exchange planner can close '
               "each ntype's slab shapes; all-HBM DistFeature "
               'partitions keep loader.DistScanTrainer')
    low = sorted(str(t) for t, s in stores.items()
                 if s.hot_prefix_rows < 1)
    if low:
      raise CapacityPlanError(
          self._NAME,
          f'stores {low} declare no hot prefix (hot_prefix_rows < 1)',
          hint='the chunk program clamps pad positions into the hot '
               'prefix — pass hot_prefix_rows >= 1 at store '
               'construction')
    # spilled partitions are named part_NNN inside spill_dir: two
    # per-ntype stores sharing a directory overwrite each other's rows
    # at construction and every later gather silently reads the LAST
    # writer's features — a corruption, not a crash, so refuse loudly
    import os as _os
    dirs = {}
    for t, s in stores.items():
      d = getattr(s, '_spill_dir', None)
      if d is not None:
        dirs.setdefault(_os.path.realpath(d), []).append(str(t))
    clash = sorted((d, sorted(ts)) for d, ts in dirs.items()
                   if len(ts) > 1)
    if clash:
      raise CapacityPlanError(
          self._NAME,
          'per-ntype stores share a spill_dir — their part_NNN spill '
          f'files overwrite each other ({clash})',
          hint='give every ntype its own spill_dir (e.g. '
               'os.path.join(root, ntype)) so each store keeps its own '
               'sorted-row tables')
    if config is not None:
      # config= takes a tune artifact (docs/tuning.md 'Topology
      # candidates'). hot_prefix_rows is a STORE-construction knob —
      # the trainer cannot apply it after the fact, so a tuned value
      # that disagrees with the store it is handed is a loud error,
      # not a silent acceptance of untuned capacity
      tuned_hot = (config.choices or {}).get('hot_prefix_rows') \
          if hasattr(config, 'choices') else None
      for t, s in stores.items():
        want = (tuned_hot.get(t) if isinstance(tuned_hot, dict)
                else tuned_hot)
        if want is not None and int(want) != int(s.hot_prefix_rows):
          raise ValueError(
              f'{self._NAME}: tune artifact pins hot_prefix_rows='
              f'{int(want)} but the TieredDistFeature store'
              f'{"" if t is None else f" for ntype {t!r}"} was '
              f'built with hot_prefix_rows={int(s.hot_prefix_rows)} '
              '— rebuild the store with the tuned value (the knob is '
              'storage layout, not a trainer kwarg; docs/tuning.md)')
    self._store = store
    super().__init__(loader, model, tx, num_classes, chunk_size,
                     seed_labels_only, perm_seed, config=config)
    if self.is_hetero:
      # one staging pipeline per sampled feature-bearing ntype — the
      # CapacityPlan's node_caps pick the set; each ntype's slab caps
      # close independently over its own plan
      self._stagers = {t: DistChunkStager(self._feat[t],
                                          max_ahead=max_ahead,
                                          timeout_s=stage_timeout_s)
                       for t in self._feat_types}
      self._stager = None
    else:
      self._stager = DistChunkStager(store, max_ahead=max_ahead,
                                     timeout_s=stage_timeout_s)
      self._stagers = None
    self.last_plan = None   # ExchangePlan(s) of the most recent epoch

  # ------------------------------------------------------------- programs

  def _make_sample_collate(self):
    """The base sample+collate body with the SLAB-BACKED feature
    lookup: ``views['f']`` carries (feat_ids, hot) instead of the full
    partition, and the body takes the chunk's per-shard slab views as
    two extra trailing arguments (per-ntype dicts on hetero meshes).
    The label store stays a full (small) DistFeature."""
    import jax.numpy as jnp
    if self.is_hetero:
      return self._make_hetero_sample_collate()
    sampler = self._sampler
    b = self._batch_size
    label_cap = self._label_cap

    from ..distributed.dist_neighbor_sampler import _homo_hop_loop
    fanouts = tuple(sampler.num_neighbors)
    caps = sampler._capacities(b)
    node_cap = sampler._node_cap(caps)
    dedup = sampler.dedup
    weighted = sampler._weighted_for()
    bucket_frac = sampler.bucket_frac
    ax, sizes, nparts = self._axes, self._axis_sizes, self._nparts
    feat_body = self._feat._shard_body(node_cap, slab=True)
    lab_body = self._label_store._shard_body(
        label_cap if label_cap is not None else node_cap)
    d = sampler._dev
    gsh = {k: d[k] for k in ('row_ids', 'indptr', 'indices', 'eids')}
    if weighted:
      gsh['wcum'] = d['wcum']
    # hot-prefix tables only — the full [P, n_max, F] partition table is
    # never uploaded on this path (device_arrays stays the per-step
    # loaders' contract)
    fdev = self._store.dist_scan_tables()
    ldev = self._label_store.device_arrays()
    shard_tree = dict(
        g=gsh,
        f={k: fdev[k] for k in ('feat_ids', 'hot')},
        l={k: ldev[k] for k in ('feat_ids', 'feats')})
    repl_tree = dict(
        pb=d['node_pb'],
        f={k: fdev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')},
        l={k: ldev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')})

    def body(views, repl, stats_rows, seeds, smask, key, slab_pos,
             slab_rows):
      res = _homo_hop_loop(views['g'], repl['pb'], seeds, smask, key,
                           fanouts, caps, node_cap, nparts, False,
                           weighted, dedup=dedup,
                           bucket_frac=bucket_frac, axes=ax,
                           axis_sizes=sizes)
      ids = res['node']
      fv, frep = views['f'], repl['f']
      x, srow = feat_body(fv['feat_ids'],
                          (fv['hot'], slab_pos, slab_rows),
                          frep['feature_pb'], frep['cache_ids'],
                          frep['cache_feats'], stats_rows, ids, ids >= 0)
      lab_ids = ids[:label_cap] if label_cap is not None else ids
      lv, lrep = views['l'], repl['l']
      y, _ = lab_body(lv['feat_ids'], lv['feats'], lrep['feature_pb'],
                      lrep['cache_ids'], lrep['cache_feats'],
                      jnp.zeros((4,), jnp.int32), lab_ids, lab_ids >= 0)
      batch = dict(x=x,
                   edge_index=jnp.stack([res['row'], res['col']]),
                   edge_mask=res['edge_mask'], y=y[:, 0],
                   num_seed_nodes=res['num_sampled_nodes'][0])
      return batch, res['overflow'], srow

    return shard_tree, repl_tree, body

  def _make_hetero_sample_collate(self):
    """Typed slab-backed collate: the base hetero body
    (loader/pipeline.py _make_hetero_sample_collate) with every
    per-ntype feature lookup resolved against (hot prefix + that
    ntype's staged slab) instead of the full partition table. The
    CapacityPlan's per-ntype ``node_caps`` size both the lookup bodies
    and the prologue's replayed request matrices, so planned and
    served can never disagree per type."""
    import jax.numpy as jnp
    sampler = self._sampler
    b = self._batch_size
    label_cap = self._label_cap
    t_in = self._input_type
    plan = sampler._hetero_plan({t_in: b})
    _, _, node_caps = plan
    feat_types = [t for t in sampler.graph.ntypes
                  if node_caps.get(t, 0) > 0 and t in self._feat]
    self._feat_types = feat_types
    self._h_plan = plan     # the typed engine plan the seed fn replays
    feat_bodies = {t: self._feat[t]._shard_body(node_caps[t], slab=True)
                   for t in feat_types}
    lab_body = self._label_store._shard_body(
        label_cap if label_cap is not None else node_caps[t_in])
    d = sampler._dev
    gsh = {}
    for et in sampler.graph.etypes:
      ga = d[et]
      gsh[et] = {k: ga[k] for k in ('row_ids', 'indptr', 'indices',
                                    'eids')}
      if sampler._weighted_for(et):
        gsh[et]['wcum'] = ga['wcum']
    # hot-prefix tables only, per ntype — no full [P, n_max, F] uploads
    fdevs = {t: self._feat[t].dist_scan_tables() for t in feat_types}
    ldev = self._label_store.device_arrays()
    shard_tree = dict(
        g=gsh,
        f={t: {k: fdevs[t][k] for k in ('feat_ids', 'hot')}
           for t in feat_types},
        l={k: ldev[k] for k in ('feat_ids', 'feats')})
    repl_tree = dict(
        pb=dict(d['#pb']),
        f={t: {k: fdevs[t][k] for k in ('feature_pb', 'cache_ids',
                                        'cache_feats')}
           for t in feat_types},
        l={k: ldev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')})

    def body(views, repl, stats_rows, seeds, smask, key, slab_pos,
             slab_rows):
      res, _ = sampler._hetero_engine(views['g'], repl['pb'],
                                      {t_in: (seeds, smask)}, key, plan)
      x, new_rows = {}, {}
      for t in feat_types:
        ids = res['node'][t]
        fv, frep = views['f'][t], repl['f'][t]
        x[t], new_rows[t] = feat_bodies[t](
            fv['feat_ids'], (fv['hot'], slab_pos[t], slab_rows[t]),
            frep['feature_pb'], frep['cache_ids'], frep['cache_feats'],
            stats_rows[t], ids, ids >= 0)
      ids = res['node'][t_in]
      lab_ids = ids[:label_cap] if label_cap is not None else ids
      lv, lrep = views['l'], repl['l']
      y, _ = lab_body(lv['feat_ids'], lv['feats'], lrep['feature_pb'],
                      lrep['cache_ids'], lrep['cache_feats'],
                      jnp.zeros((4,), jnp.int32), lab_ids, lab_ids >= 0)
      ei = {et: jnp.stack([res['row'][et], res['col'][et]])
            for et in res['row']}
      batch = dict(x=x, edge_index=ei, edge_mask=res['edge_mask'],
                   y=y[:, 0],
                   num_seed_nodes=res['num_sampled_nodes'][t_in][0])
      return batch, res['overflow'], new_rows

    return shard_tree, repl_tree, body

  def _build_seed_fn(self):
    """The prologue PLAN program: the base seed/permutation math PLUS
    an id-only replay of the distributed sampler over every step inside
    one shard_map — emitting the [P, steps, node_cap] request matrix
    alongside the sharded seed matrices. One dispatch, fetched once;
    the keys are exactly the chunk programs'
    ``split(fold_in(base_key, count), P)[shard]`` stream, so the
    replayed requests ARE the chunk requests, bit for bit."""
    if self.is_hetero:
      return self._build_hetero_seed_fn()
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..distributed.dist_neighbor_sampler import _homo_hop_loop
    from ..utils.compat import shard_map
    sampler = self._sampler
    batch = self._batch_size
    nparts = self._nparts
    shuffle = self.loader.shuffle
    fanouts = tuple(sampler.num_neighbors)
    caps = sampler._capacities(batch)
    node_cap = sampler._node_cap(caps)
    dedup = sampler.dedup
    weighted = sampler._weighted_for()
    bucket_frac = sampler.bucket_frac
    ax, sizes = self._axes, self._axis_sizes
    mesh = self.mesh
    gspec = jax.tree.map(lambda _: P(ax), self._shard_tree['g'])

    def plan(gsh, pb, seeds, key, base_key, count0, steps):
      def body(gsh_s, pb_s, seeds_s, key_s, base_key_s, count0_s):
        gviews = jax.tree.map(lambda a: a[0], gsh_s)
        my = jnp.int32(0)
        for a in ax:
          my = my * mesh.shape[a] + lax.axis_index(a)
        n = seeds_s.shape[0]
        # the SAME permutation math as DistScanTrainer._build_seed_fn
        # (replicated computation per shard): arange + cyclic ragged
        # tail, or the on-device epoch permutation
        order = (jax.random.permutation(key_s, n) if shuffle
                 else jnp.arange(n, dtype=jnp.int32))
        total = steps * nparts * batch
        if total <= n:
          ext = order[:total]
          maskf = jnp.ones((total,), bool)
        else:
          pad = order[jnp.arange(total - n, dtype=jnp.int32) % n]
          ext = jnp.concatenate([order, pad])
          maskf = jnp.arange(total) < n
        seed_all = seeds_s[ext].reshape(steps, nparts, batch)
        mask_all = maskf.reshape(steps, nparts, batch)
        seeds_my = jnp.take(seed_all, my, axis=1)    # [steps, B]
        mask_my = jnp.take(mask_all, my, axis=1)
        counts = count0_s + lax.iota(jnp.int32, steps)

        def step(carry, xs):
          s, m, cnt = xs
          keys = jax.random.split(
              jax.random.fold_in(base_key_s, cnt), nparts)
          res = _homo_hop_loop(gviews, pb_s, s, m, keys[my], fanouts,
                               caps, node_cap, nparts, False, weighted,
                               dedup=dedup, bucket_frac=bucket_frac,
                               axes=ax, axis_sizes=sizes)
          return carry, res['node']

        _, rows = lax.scan(step, 0, (seeds_my, mask_my, counts))
        return seeds_my[None], mask_my[None], rows[None]

      fn = shard_map(body, mesh=mesh,
                     in_specs=(gspec, P(), P(), P(), P(), P()),
                     out_specs=(P(ax), P(ax), P(ax)),
                     check_replication=False)
      return fn(gsh, pb, seeds, key, base_key, count0)

    return jax.jit(plan, static_argnums=(6,))

  def _build_hetero_seed_fn(self):
    """Typed prologue PLAN program: the same permutation math plus an
    id-only replay of ``_hetero_engine`` over every step, emitting ONE
    per-ntype request matrix dict ``{ntype: [P, steps, node_caps[t]]}``
    — the CapacityPlan's per-ntype shapes, closed at trace time. Still
    one ``dist_epoch_seeds`` dispatch; the keys are exactly the typed
    chunk programs' ``split(fold_in(base_key, count), P)[shard]``
    stream."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map
    sampler = self._sampler
    batch = self._batch_size
    nparts = self._nparts
    shuffle = self.loader.shuffle
    t_in = self._input_type
    eplan = self._h_plan          # set by _make_hetero_sample_collate
    feat_types = list(self._feat_types)
    ax = self._axes
    mesh = self.mesh
    gspec = jax.tree.map(lambda _: P(ax), self._shard_tree['g'])

    def plan(gsh, pb, seeds, key, base_key, count0, steps):
      def body(gsh_s, pb_s, seeds_s, key_s, base_key_s, count0_s):
        gviews = jax.tree.map(lambda a: a[0], gsh_s)
        my = jnp.int32(0)
        for a in ax:
          my = my * mesh.shape[a] + lax.axis_index(a)
        n = seeds_s.shape[0]
        order = (jax.random.permutation(key_s, n) if shuffle
                 else jnp.arange(n, dtype=jnp.int32))
        total = steps * nparts * batch
        if total <= n:
          ext = order[:total]
          maskf = jnp.ones((total,), bool)
        else:
          pad = order[jnp.arange(total - n, dtype=jnp.int32) % n]
          ext = jnp.concatenate([order, pad])
          maskf = jnp.arange(total) < n
        seed_all = seeds_s[ext].reshape(steps, nparts, batch)
        mask_all = maskf.reshape(steps, nparts, batch)
        seeds_my = jnp.take(seed_all, my, axis=1)    # [steps, B]
        mask_my = jnp.take(mask_all, my, axis=1)
        counts = count0_s + lax.iota(jnp.int32, steps)

        def step(carry, xs):
          s, m, cnt = xs
          keys = jax.random.split(
              jax.random.fold_in(base_key_s, cnt), nparts)
          res, _ = sampler._hetero_engine(gviews, pb_s,
                                          {t_in: (s, m)}, keys[my],
                                          eplan)
          return carry, {t: res['node'][t] for t in feat_types}

        _, rows = lax.scan(step, 0, (seeds_my, mask_my, counts))
        return (seeds_my[None], mask_my[None],
                {t: rows[t][None] for t in feat_types})

      fn = shard_map(body, mesh=mesh,
                     in_specs=(gspec, P(), P(), P(), P(), P()),
                     out_specs=(P(ax), P(ax),
                                {t: P(ax) for t in feat_types}),
                     check_replication=False)
      return fn(gsh, pb, seeds, key, base_key, count0)

    return jax.jit(plan, static_argnums=(6,))

  def _chunk_fn_for(self, k: int, cap: Optional[int] = None):
    """The slab-aware scanned K-step shard_map program, keyed by
    (chunk length, slab cap) — pow2 caps keep the executable set
    closed. Arg order extends the base program's with the two slab
    arrays at the END, so the donation set (stats + train state +
    overflow) is unchanged; slabs are fresh per chunk and never
    donated."""
    if cap is None:   # the base signature — unreachable via our seam
      raise TypeError(f'{self._NAME}._chunk_fn_for needs the slab cap')
    ck = (k, cap)
    if ck in self._chunk_fns:
      return self._chunk_fns[ck]
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..metrics import programs
    from ..utils.compat import shard_map
    ax = self._axes
    mesh = self.mesh
    nparts = self._nparts
    sc_body = self._sc_body
    dp = self._dp_step_body

    def body(shard_tree, repl_tree, stats, params, opt_state, stepc,
             ovf, seed_mat, mask_mat, base_key, count0, start, slab_pos,
             slab_rows):
      views = jax.tree.map(lambda a: a[0], shard_tree)
      stats_rows = jax.tree.map(lambda a: a[0], stats)
      sp_v = jax.tree.map(lambda a: a[0], slab_pos)
      sr_v = jax.tree.map(lambda a: a[0], slab_rows)
      seeds_k = lax.dynamic_slice_in_dim(seed_mat[0], start, k, 0)
      masks_k = lax.dynamic_slice_in_dim(mask_mat[0], start, k, 0)
      counts_k = count0 + start + lax.iota(jnp.int32, k)
      my = jnp.int32(0)
      for a in ax:
        my = my * mesh.shape[a] + lax.axis_index(a)

      def step(carry, xs):
        params, opt_state, stepc, ovf, srows = carry
        seeds, smask, count = xs
        keys = jax.random.split(jax.random.fold_in(base_key, count),
                                nparts)
        batch, overflow, srows = sc_body(views, repl_tree, srows, seeds,
                                         smask, keys[my], sp_v, sr_v)
        state, loss, acc = dp(
            self._train_state_cls(params, opt_state, stepc), batch)
        return (state.params, state.opt_state, state.step,
                ovf | overflow, srows), (loss, acc)

      (params, opt_state, stepc, ovf, srows), (losses, accs) = lax.scan(
          step, (params, opt_state, stepc, ovf, stats_rows),
          (seeds_k, masks_k, counts_k))
      return (params, opt_state, stepc, ovf,
              jax.tree.map(lambda a: a[None], srows), losses, accs)

    sh = jax.tree.map(lambda _: P(ax), self._shard_tree)
    rp = jax.tree.map(lambda _: P(), self._repl_tree)
    stats_spec = (P(ax) if not self.is_hetero
                  else {t: P(ax) for t in self._feat_types})
    slab_spec = (P(ax) if not self.is_hetero
                 else {t: P(ax) for t in self._feat_types})
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(sh, rp, stats_spec, P(), P(), P(), P(), P(ax), P(ax),
                  P(), P(), P(), slab_spec, slab_spec),
        out_specs=(P(), P(), P(), P(), stats_spec, P(), P()),
        check_replication=False)
    jfn = programs.instrument(
        jax.jit(fn, donate_argnums=(2, 3, 4, 5, 6)), 'dist_scan_chunk')
    self._chunk_fns[ck] = jfn
    return jfn

  # ------------------------------------------------ exchange-aware seams

  def _epoch_prologue(self, perm_key, full_steps, steps, start_step,
                      base_key, count0):
    """One plan dispatch + the prologue's ONE explicit fetch: the
    replayed request matrix (per-ntype matrices on hetero meshes)
    becomes the per-chunk miss-exchange program — one ExchangePlan per
    feature-bearing ntype — and staging starts at the resume chunk
    (consumed chunks never stage again)."""
    import jax
    record_dispatch('dist_epoch_seeds')
    seed_mat, mask_mat, rows_mat = self._seed_fn(
        self._shard_tree['g'], self._repl_tree['pb'], self._seeds_dev,
        perm_key, base_key, count0, full_steps)
    # explicit device_get — strict_guards rejects implicit transfers only
    rows_host = jax.device_get(rows_mat)
    start_chunk = start_step // self.chunk_size
    if self.is_hetero:
      plans = {}
      for t in self._feat_types:
        st = self._feat[t]
        plans[t] = planner.plan_exchange(
            np.asarray(rows_host[t])[:, :steps], self.chunk_size,
            st.feature_pb, st.feat_ids, st.hot_prefix_rows,
            cache_ids=st.cache_ids)
        self._stagers[t].begin_epoch(plans[t].chunk_rows,
                                     start_chunk=start_chunk)
      self.last_plan = plans
    else:
      plan = planner.plan_exchange(
          np.asarray(rows_host)[:, :steps], self.chunk_size,
          self._store.feature_pb, self._store.feat_ids,
          self._store.hot_prefix_rows, cache_ids=self._store.cache_ids)
      self.last_plan = plan
      self._stager.begin_epoch(plan.chunk_rows, start_chunk=start_chunk)
    return seed_mat, mask_mat

  def _dispatch_chunk(self, c, k, stats, params, opt_state, stepc, ovf,
                      seed_mat, mask_mat, base_key, count0, start_dev):
    """Take chunk ``c``'s staged slab(s) (or degrade to a synchronous
    gather of the same planned positions), upload them sharded over the
    mesh (explicit device_puts — the strict region stays clean), and
    dispatch the (k, caps) program. Hetero chunks stage one slab per
    feature-bearing ntype; the executable is keyed by the per-ntype
    pow2 cap tuple so the compiled set stays closed. The ack frees the
    host ring slots; the device copies belong to the in-flight
    program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils import global_device_put
    sharded = NamedSharding(self.mesh, P(self._axes))
    if self.is_hetero:
      slab_np = {t: self._stagers[t].take(c) for t in self._feat_types}
      slab_pos = {t: global_device_put(v[0], sharded)
                  for t, v in slab_np.items()}
      slab_rows = {t: global_device_put(v[1], sharded)
                   for t, v in slab_np.items()}
      cap = tuple(int(slab_np[t][0].shape[1]) for t in self._feat_types)
    else:
      slab_pos_np, slab_rows_np = self._stager.take(c)
      slab_pos = global_device_put(slab_pos_np, sharded)
      slab_rows = global_device_put(slab_rows_np, sharded)
      cap = int(slab_pos_np.shape[1])
    record_dispatch('dist_scan_chunk')
    out = self._chunk_fn_for(k, cap)(
        self._shard_tree, self._repl_tree, stats, params, opt_state,
        stepc, ovf, seed_mat, mask_mat, base_key, count0, start_dev,
        slab_pos, slab_rows)
    if self.is_hetero:
      for t in self._feat_types:
        self._stagers[t].ack(c)
    else:
      self._stager.ack(c)
    return out

  # ---------------------------------------------------------- lifecycle

  def _flight_config(self) -> dict:
    cfg = super()._flight_config()
    if self.is_hetero:
      cfg.update(
          hot_prefix_rows={t: self._feat[t].hot_prefix_rows
                           for t in self._feat_types},
          n_max={t: self._feat[t].n_max for t in self._feat_types})
    else:
      cfg.update(hot_prefix_rows=self._store.hot_prefix_rows,
                 n_max=self._store.n_max)
    return cfg

  def _recovery_capture(self, carry):
    """DistScanTrainer's capture plus the staging-ring watermarks
    (diagnostic — a resume re-plans and re-stages)."""
    meta, dev = super()._recovery_capture(carry)
    meta['staging'] = ({t: self._stagers[t].watermarks()
                        for t in self._feat_types}
                       if self.is_hetero else self._stager.watermarks())
    return meta, dev

  def close(self):
    """Stop the staging worker thread(s)."""
    if self._stagers is not None:
      for st in self._stagers.values():
        st.close()
    if self._stager is not None:
      self._stager.close()
