"""TieredDistScanTrainer: device oversubscription THROUGH the shard
exchange.

``DistScanTrainer`` runs a collocated-mesh epoch as ceil(steps/K)+2
dispatches, but every shard's HBM still holds its FULL feature
partition — the in-program all_to_all must be able to answer any
remote request. This trainer erases that boundary (ROADMAP item 2;
PyTorch-Direct, arxiv 2101.07956, and GPU-initiated direct storage,
arxiv 2306.16384, are the GPU-world exemplars — this is the multi-host
TPU instance):

* **Hot prefix per shard.** Each shard's HBM holds only positions
  ``[0, H)`` of its sorted partition table
  (``TieredDistFeature.dist_scan_tables``) plus a double-buffered
  pow2-padded exchange slab; the rest of the partition lives in the
  store's host/disk tiers.
* **Miss-exchange program.** The epoch prologue extends the seed
  program with an id-only replay of the distributed sampler over every
  step — the SAME ``split(fold_in(base_key, count), P)`` keys the chunk
  programs derive, so the draws are bit-identical by the PR 4 replay
  contract — still ONE ``dist_epoch_seeds`` dispatch. The fetched
  [P, steps, node_cap] request matrix is the prologue's one explicit
  ``jax.device_get``; ``planner.plan_exchange`` turns it into the exact
  per-chunk program: which POSITIONS of each shard's table its peers
  will request during each chunk, beyond the replicated hot cache and
  the HBM hot prefix.
* **Chunk-boundary slab staging.** While chunk ``c`` trains, a
  ``DistChunkStager`` worker gathers chunk ``c+1``'s planned positions
  from the per-partition tiers into a [P, cap] / [P, cap, F] host slab
  (pow2 ``cap`` = the chunk's max per-shard count — one executable per
  (chunk length, slab cap)); the dispatch thread device_puts it sharded
  over the mesh and dispatches the chunk.
* **In-program slab-backed exchange.** The chunk program's feature
  lookup is ``DistFeature._shard_body(slab=True)``: a remote request
  resolves its position exactly as before, then gathers ``hot[pos]``
  for positions < H and ``slab[searchsorted(slab_pos, pos)]`` for the
  rest — under the exact plan every staged bytes equals the all-HBM
  row, so LOSSES AND PARAMS ARE BIT-IDENTICAL to ``DistScanTrainer``
  at the unchanged ceil(steps/K)+2 dispatch budget.
* **Degradation, never corruption.** A failed/slow staging worker
  degrades to a synchronous gather of the same planned positions
  (``storage.prefetch_miss``); the chaos suite completes the epoch
  bit-identically with a ``storage.dist_stage`` fault armed
  (docs/failure_model.md).

Scope: homogeneous collocated meshes (flat or 2-axis hierarchical —
the slab-backed lookup rides both exchange forms). Hetero dist stores
keep the all-HBM ``DistScanTrainer``. Labels stay a full (small)
DistFeature. Single-process meshes: the prologue fetch and the stager
read the whole [P, ...] request matrix / tier set locally.
"""
from typing import Optional

import numpy as np

from .. import metrics
from ..loader.scan_epoch import DistScanTrainer
from ..utils.faults import fault_point
from ..utils.trace import record_dispatch
from . import planner
from .dist import TieredDistFeature
from .staging import INT32_MAX, ChunkStager, pow2_slab_cap


class DistChunkStager(ChunkStager):
  """ChunkStager whose plan rows are ENCODED ``p * n_max + position``
  addresses (planner.ExchangePlan) and whose slabs come back in the
  [P, cap] per-shard layout the shard_map chunk program consumes.
  Pad slots carry INT32_MAX positions (never match a searchsorted);
  per-shard position lists stay sorted because the encoded plan is."""

  def _stage_fault(self):
    # the dist pipeline's own registered chaos site — worker-only, so
    # take()'s synchronous fallback still gathers cleanly
    fault_point('storage.dist_stage')

  def _gather(self, enc: np.ndarray):
    store = self.store
    nparts, n_max = store.num_partitions, store.n_max
    enc = np.asarray(enc, np.int64)
    owners = enc // n_max
    pos = enc % n_max
    counts = (np.bincount(owners, minlength=nparts) if enc.size
              else np.zeros((nparts,), np.int64))
    cap = pow2_slab_cap(int(counts.max()) if enc.size else 1)
    ids = np.full((nparts, cap), INT32_MAX, np.int32)
    rows = np.zeros((nparts, cap, store.feature_dim),
                    store.storage_dtype)
    for p in range(nparts):
      kp = int(counts[p])
      if kp:
        m = owners == p
        ids[p, :kp] = pos[m].astype(np.int32)
        rows[p, :kp] = store.gather_positions(p, pos[m])
    metrics.inc('storage.dist_staged_rows', int(enc.shape[0]))
    return ids, rows


class TieredDistScanTrainer(DistScanTrainer):
  """DistScanTrainer over a ``TieredDistFeature`` whose HBM holds only
  each shard's hot prefix + the in-flight exchange slabs (module
  docstring).

  Args (beyond DistScanTrainer's):
    max_ahead: staged chunks in flight (2 = double buffer).
    stage_timeout_s: how long a chunk boundary waits for its slab
      before degrading to a synchronous gather.
  """

  _NAME = 'TieredDistScanTrainer'
  _TOPOLOGY = 'tiered_dist'

  def __init__(self, loader, model, tx, num_classes: int,
               chunk_size: Optional[int] = None,
               seed_labels_only: Optional[bool] = None,
               perm_seed: Optional[int] = None, max_ahead: int = 2,
               stage_timeout_s: float = 30.0, config=None):
    sampler = getattr(loader, 'sampler', None)
    if sampler is not None and getattr(sampler, 'is_hetero', False):
      raise ValueError(
          f'{self._NAME} is homogeneous-only — hetero dist stores keep '
          'the all-HBM loader.DistScanTrainer (per-ntype slab staging '
          'is tracked in ROADMAP)')
    store = getattr(sampler, 'dist_feature', None)
    if not isinstance(store, TieredDistFeature):
      raise ValueError(
          f'{self._NAME} drives a storage.TieredDistFeature store '
          f'(got {type(store).__name__}); use loader.DistScanTrainer '
          'for all-HBM DistFeature partitions')
    if store.hot_prefix_rows < 1:
      raise ValueError(
          f'{self._NAME} needs TieredDistFeature(hot_prefix_rows >= 1) '
          '— the chunk program clamps pad positions into the hot '
          'prefix')
    if config is not None:
      # config= takes a tune artifact (docs/tuning.md 'Topology
      # candidates'). hot_prefix_rows is a STORE-construction knob —
      # the trainer cannot apply it after the fact, so a tuned value
      # that disagrees with the store it is handed is a loud error,
      # not a silent acceptance of untuned capacity
      tuned_hot = (config.choices or {}).get('hot_prefix_rows') \
          if hasattr(config, 'choices') else None
      if tuned_hot is not None and \
          int(tuned_hot) != int(store.hot_prefix_rows):
        raise ValueError(
            f'{self._NAME}: tune artifact pins hot_prefix_rows='
            f'{int(tuned_hot)} but the TieredDistFeature store was '
            f'built with hot_prefix_rows={int(store.hot_prefix_rows)} '
            '— rebuild the store with the tuned value (the knob is '
            'storage layout, not a trainer kwarg; docs/tuning.md)')
    self._store = store
    super().__init__(loader, model, tx, num_classes, chunk_size,
                     seed_labels_only, perm_seed, config=config)
    self._stager = DistChunkStager(store, max_ahead=max_ahead,
                                   timeout_s=stage_timeout_s)
    self.last_plan = None   # ExchangePlan of the most recent epoch

  # ------------------------------------------------------------- programs

  def _make_sample_collate(self):
    """The base homo sample+collate body with the SLAB-BACKED feature
    lookup: ``views['f']`` carries (feat_ids, hot) instead of the full
    partition, and the body takes the chunk's per-shard slab views as
    two extra arguments. The label store stays a full (small)
    DistFeature."""
    import jax.numpy as jnp
    sampler = self._sampler
    b = self._batch_size
    label_cap = self._label_cap

    from ..distributed.dist_neighbor_sampler import _homo_hop_loop
    fanouts = tuple(sampler.num_neighbors)
    caps = sampler._capacities(b)
    node_cap = sampler._node_cap(caps)
    dedup = sampler.dedup
    weighted = sampler._weighted_for()
    bucket_frac = sampler.bucket_frac
    ax, sizes, nparts = self._axes, self._axis_sizes, self._nparts
    feat_body = self._feat._shard_body(node_cap, slab=True)
    lab_body = self._label_store._shard_body(
        label_cap if label_cap is not None else node_cap)
    d = sampler._dev
    gsh = {k: d[k] for k in ('row_ids', 'indptr', 'indices', 'eids')}
    if weighted:
      gsh['wcum'] = d['wcum']
    # hot-prefix tables only — the full [P, n_max, F] partition table is
    # never uploaded on this path (device_arrays stays the per-step
    # loaders' contract)
    fdev = self._store.dist_scan_tables()
    ldev = self._label_store.device_arrays()
    shard_tree = dict(
        g=gsh,
        f={k: fdev[k] for k in ('feat_ids', 'hot')},
        l={k: ldev[k] for k in ('feat_ids', 'feats')})
    repl_tree = dict(
        pb=d['node_pb'],
        f={k: fdev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')},
        l={k: ldev[k] for k in ('feature_pb', 'cache_ids',
                                'cache_feats')})

    def body(views, repl, stats_rows, seeds, smask, key, slab_pos,
             slab_rows):
      res = _homo_hop_loop(views['g'], repl['pb'], seeds, smask, key,
                           fanouts, caps, node_cap, nparts, False,
                           weighted, dedup=dedup,
                           bucket_frac=bucket_frac, axes=ax,
                           axis_sizes=sizes)
      ids = res['node']
      fv, frep = views['f'], repl['f']
      x, srow = feat_body(fv['feat_ids'],
                          (fv['hot'], slab_pos, slab_rows),
                          frep['feature_pb'], frep['cache_ids'],
                          frep['cache_feats'], stats_rows, ids, ids >= 0)
      lab_ids = ids[:label_cap] if label_cap is not None else ids
      lv, lrep = views['l'], repl['l']
      y, _ = lab_body(lv['feat_ids'], lv['feats'], lrep['feature_pb'],
                      lrep['cache_ids'], lrep['cache_feats'],
                      jnp.zeros((4,), jnp.int32), lab_ids, lab_ids >= 0)
      batch = dict(x=x,
                   edge_index=jnp.stack([res['row'], res['col']]),
                   edge_mask=res['edge_mask'], y=y[:, 0],
                   num_seed_nodes=res['num_sampled_nodes'][0])
      return batch, res['overflow'], srow

    return shard_tree, repl_tree, body

  def _build_seed_fn(self):
    """The prologue PLAN program: the base seed/permutation math PLUS
    an id-only replay of the distributed sampler over every step inside
    one shard_map — emitting the [P, steps, node_cap] request matrix
    alongside the sharded seed matrices. One dispatch, fetched once;
    the keys are exactly the chunk programs'
    ``split(fold_in(base_key, count), P)[shard]`` stream, so the
    replayed requests ARE the chunk requests, bit for bit."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..distributed.dist_neighbor_sampler import _homo_hop_loop
    from ..utils.compat import shard_map
    sampler = self._sampler
    batch = self._batch_size
    nparts = self._nparts
    shuffle = self.loader.shuffle
    fanouts = tuple(sampler.num_neighbors)
    caps = sampler._capacities(batch)
    node_cap = sampler._node_cap(caps)
    dedup = sampler.dedup
    weighted = sampler._weighted_for()
    bucket_frac = sampler.bucket_frac
    ax, sizes = self._axes, self._axis_sizes
    mesh = self.mesh
    gspec = jax.tree.map(lambda _: P(ax), self._shard_tree['g'])

    def plan(gsh, pb, seeds, key, base_key, count0, steps):
      def body(gsh_s, pb_s, seeds_s, key_s, base_key_s, count0_s):
        gviews = jax.tree.map(lambda a: a[0], gsh_s)
        my = jnp.int32(0)
        for a in ax:
          my = my * mesh.shape[a] + lax.axis_index(a)
        n = seeds_s.shape[0]
        # the SAME permutation math as DistScanTrainer._build_seed_fn
        # (replicated computation per shard): arange + cyclic ragged
        # tail, or the on-device epoch permutation
        order = (jax.random.permutation(key_s, n) if shuffle
                 else jnp.arange(n, dtype=jnp.int32))
        total = steps * nparts * batch
        if total <= n:
          ext = order[:total]
          maskf = jnp.ones((total,), bool)
        else:
          pad = order[jnp.arange(total - n, dtype=jnp.int32) % n]
          ext = jnp.concatenate([order, pad])
          maskf = jnp.arange(total) < n
        seed_all = seeds_s[ext].reshape(steps, nparts, batch)
        mask_all = maskf.reshape(steps, nparts, batch)
        seeds_my = jnp.take(seed_all, my, axis=1)    # [steps, B]
        mask_my = jnp.take(mask_all, my, axis=1)
        counts = count0_s + lax.iota(jnp.int32, steps)

        def step(carry, xs):
          s, m, cnt = xs
          keys = jax.random.split(
              jax.random.fold_in(base_key_s, cnt), nparts)
          res = _homo_hop_loop(gviews, pb_s, s, m, keys[my], fanouts,
                               caps, node_cap, nparts, False, weighted,
                               dedup=dedup, bucket_frac=bucket_frac,
                               axes=ax, axis_sizes=sizes)
          return carry, res['node']

        _, rows = lax.scan(step, 0, (seeds_my, mask_my, counts))
        return seeds_my[None], mask_my[None], rows[None]

      fn = shard_map(body, mesh=mesh,
                     in_specs=(gspec, P(), P(), P(), P(), P()),
                     out_specs=(P(ax), P(ax), P(ax)),
                     check_replication=False)
      return fn(gsh, pb, seeds, key, base_key, count0)

    return jax.jit(plan, static_argnums=(6,))

  def _chunk_fn_for(self, k: int, cap: Optional[int] = None):
    """The slab-aware scanned K-step shard_map program, keyed by
    (chunk length, slab cap) — pow2 caps keep the executable set
    closed. Arg order extends the base program's with the two slab
    arrays at the END, so the donation set (stats + train state +
    overflow) is unchanged; slabs are fresh per chunk and never
    donated."""
    if cap is None:   # the base signature — unreachable via our seam
      raise TypeError(f'{self._NAME}._chunk_fn_for needs the slab cap')
    ck = (k, cap)
    if ck in self._chunk_fns:
      return self._chunk_fns[ck]
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..metrics import programs
    from ..utils.compat import shard_map
    ax = self._axes
    mesh = self.mesh
    nparts = self._nparts
    sc_body = self._sc_body
    dp = self._dp_step_body

    def body(shard_tree, repl_tree, stats, params, opt_state, stepc,
             ovf, seed_mat, mask_mat, base_key, count0, start, slab_pos,
             slab_rows):
      views = jax.tree.map(lambda a: a[0], shard_tree)
      stats_rows = stats[0]
      sp_v, sr_v = slab_pos[0], slab_rows[0]
      seeds_k = lax.dynamic_slice_in_dim(seed_mat[0], start, k, 0)
      masks_k = lax.dynamic_slice_in_dim(mask_mat[0], start, k, 0)
      counts_k = count0 + start + lax.iota(jnp.int32, k)
      my = jnp.int32(0)
      for a in ax:
        my = my * mesh.shape[a] + lax.axis_index(a)

      def step(carry, xs):
        params, opt_state, stepc, ovf, srows = carry
        seeds, smask, count = xs
        keys = jax.random.split(jax.random.fold_in(base_key, count),
                                nparts)
        batch, overflow, srows = sc_body(views, repl_tree, srows, seeds,
                                         smask, keys[my], sp_v, sr_v)
        state, loss, acc = dp(
            self._train_state_cls(params, opt_state, stepc), batch)
        return (state.params, state.opt_state, state.step,
                ovf | overflow, srows), (loss, acc)

      (params, opt_state, stepc, ovf, srows), (losses, accs) = lax.scan(
          step, (params, opt_state, stepc, ovf, stats_rows),
          (seeds_k, masks_k, counts_k))
      return (params, opt_state, stepc, ovf, srows[None], losses, accs)

    sh = jax.tree.map(lambda _: P(ax), self._shard_tree)
    rp = jax.tree.map(lambda _: P(), self._repl_tree)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(sh, rp, P(ax), P(), P(), P(), P(), P(ax), P(ax),
                  P(), P(), P(), P(ax), P(ax)),
        out_specs=(P(), P(), P(), P(), P(ax), P(), P()),
        check_replication=False)
    jfn = programs.instrument(
        jax.jit(fn, donate_argnums=(2, 3, 4, 5, 6)), 'dist_scan_chunk')
    self._chunk_fns[ck] = jfn
    return jfn

  # ------------------------------------------------ exchange-aware seams

  def _epoch_prologue(self, perm_key, full_steps, steps, start_step,
                      base_key, count0):
    """One plan dispatch + the prologue's ONE explicit fetch: the
    replayed request matrix becomes the per-chunk miss-exchange
    program, and staging starts at the resume chunk (consumed chunks
    never stage again)."""
    import jax
    record_dispatch('dist_epoch_seeds')
    seed_mat, mask_mat, rows_mat = self._seed_fn(
        self._shard_tree['g'], self._repl_tree['pb'], self._seeds_dev,
        perm_key, base_key, count0, full_steps)
    # explicit device_get — strict_guards rejects implicit transfers only
    rows_host = np.asarray(jax.device_get(rows_mat))[:, :steps]
    plan = planner.plan_exchange(
        rows_host, self.chunk_size, self._store.feature_pb,
        self._store.feat_ids, self._store.hot_prefix_rows,
        cache_ids=self._store.cache_ids)
    self.last_plan = plan
    self._stager.begin_epoch(plan.chunk_rows,
                             start_chunk=start_step // self.chunk_size)
    return seed_mat, mask_mat

  def _dispatch_chunk(self, c, k, stats, params, opt_state, stepc, ovf,
                      seed_mat, mask_mat, base_key, count0, start_dev):
    """Take chunk ``c``'s staged slab (or degrade to a synchronous
    gather of the same planned positions), upload it sharded over the
    mesh (explicit device_puts — the strict region stays clean), and
    dispatch the (k, cap) program. The ack frees the host ring slot;
    the device copies belong to the in-flight program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils import global_device_put
    slab_pos_np, slab_rows_np = self._stager.take(c)
    sharded = NamedSharding(self.mesh, P(self._axes))
    slab_pos = global_device_put(slab_pos_np, sharded)
    slab_rows = global_device_put(slab_rows_np, sharded)
    record_dispatch('dist_scan_chunk')
    out = self._chunk_fn_for(k, int(slab_pos_np.shape[1]))(
        self._shard_tree, self._repl_tree, stats, params, opt_state,
        stepc, ovf, seed_mat, mask_mat, base_key, count0, start_dev,
        slab_pos, slab_rows)
    self._stager.ack(c)
    return out

  # ---------------------------------------------------------- lifecycle

  def _flight_config(self) -> dict:
    cfg = super()._flight_config()
    cfg.update(hot_prefix_rows=self._store.hot_prefix_rows,
               n_max=self._store.n_max)
    return cfg

  def _recovery_capture(self, carry):
    """DistScanTrainer's capture plus the staging-ring watermarks
    (diagnostic — a resume re-plans and re-stages)."""
    meta, dev = super()._recovery_capture(carry)
    meta['staging'] = self._stager.watermarks()
    return meta, dev

  def close(self):
    """Stop the staging worker thread."""
    self._stager.close()
