"""Chunk-boundary staging pipeline: disk -> pinned host ring -> warm tier.

The reference hides feature-fetch latency behind CUDA streams and UVA
zero-copy (PAPER.md, unified_tensor.cu); PyTorch-Direct (arxiv
2101.07956) and GPU-initiated storage access (arxiv 2306.16384) are the
GPU-world exemplars. The TPU analog is *double-buffered host staging
fused to the scanned epoch's chunk cadence*: the whole epoch's miss set
is computable at the prologue (storage/planner.py), so while chunk ``c``
trains on device, a single bounded worker thread gathers chunk
``c+1``'s warm/disk rows into a host ring slab (pow2-padded — the
chunk program's staging shapes form a closed set) and hands it to the
dispatch thread at the chunk boundary.

Failure semantics (docs/failure_model.md): a failed or slow staging
worker NEVER yields a wrong batch — :meth:`ChunkStager.take` falls back
to a synchronous on-demand gather of the SAME planned row set (counted
by ``storage.prefetch_miss``), so the degraded epoch is bit-identical
to the healthy one, just slower. Fault sites ``storage.stage`` (the
worker's gather) and ``storage.promote`` (handing the slab to the
ring) are registered in utils/faults.py for the chaos suite.

Observability: ``storage.staged_rows`` / ``storage.staged_bytes``
counters, ``storage.stage_ms`` / ``storage.promote_ms`` histograms, a
``storage.ring_rows`` gauge, and one ``storage.stage`` span per staged
chunk (docs/observability.md).
"""
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import metrics
from ..metrics import spans
from ..utils.faults import fault_point

INT32_MAX = np.iinfo(np.int32).max


def pow2_slab_cap(n: int) -> int:
  """Padded slab capacity: next power of two, floor 1 — the staging
  analog of UnifiedTensor's pow2 cold caps (one executable per shape)."""
  if n <= 1:
    return 1
  return 1 << int(n - 1).bit_length()


def pad_slab(row_ids: np.ndarray, rows: np.ndarray):
  """(ids [cap], rows [cap, F]) pow2-padded; pad id slots carry
  INT32_MAX so an in-program searchsorted can never match them."""
  n = int(row_ids.shape[0])
  cap = pow2_slab_cap(n)
  ids = np.full((cap,), INT32_MAX, np.int32)
  ids[:n] = row_ids
  out = np.zeros((cap,) + rows.shape[1:], rows.dtype)
  out[:n] = rows
  return ids, out


class _Slab:
  __slots__ = ('ids', 'rows', 'ready', 'error', 'staged_async', 't_done')

  def __init__(self):
    self.ids = None
    self.rows = None
    self.ready = threading.Event()
    self.error: Optional[BaseException] = None
    self.staged_async = False
    self.t_done: Optional[float] = None


class ChunkStager:
  """One background worker staging planned chunk slabs ahead of the
  dispatch loop.

  Args:
    store: the TieredFeature whose warm/disk tiers to read
      (``store.stage_gather(abs_rows)``).
    max_ahead: outstanding staged chunks (2 = classic double buffer:
      slab c+1 fills while chunk c trains).
    timeout_s: how long :meth:`take` waits for the worker before
      degrading to a synchronous gather.
  """

  def __init__(self, store, max_ahead: int = 2, timeout_s: float = 30.0):
    if max_ahead < 1:
      raise ValueError('max_ahead must be >= 1')
    self.store = store
    self.max_ahead = int(max_ahead)
    self.timeout_s = float(timeout_s)
    self._lock = threading.Lock()
    # ring state shared between the dispatch thread (begin_epoch/take/
    # ack) and the stager worker (_loop) — every access holds _lock
    # graftlint: shared[_lock]
    self._plan: List[np.ndarray] = []
    # graftlint: shared[_lock]
    self._slabs: Dict[int, _Slab] = {}
    self._q: 'queue.Queue' = queue.Queue()
    self._worker: Optional[threading.Thread] = None
    self._stop = False
    # graftlint: shared[_lock]
    self._next_submit = 0
    self.degraded = False   # a worker gather failed this epoch
    # perf_counter marks per chunk, kept for the whole epoch — the
    # chunk-boundary-overlap contract ("stage of c+1 completes before
    # chunk c's ack") is asserted from these
    self.stage_done_t: Dict[int, float] = {}
    self.ack_t: Dict[int, float] = {}

  # ------------------------------------------------------------ lifecycle

  def begin_epoch(self, chunk_rows: List[np.ndarray],
                  start_chunk: int = 0):
    """Install this epoch's plan (per-chunk sorted absolute storage
    rows beyond the hot tier) and prime the first ``max_ahead`` slabs.
    Any previous epoch's outstanding slabs are dropped. A mid-epoch
    RESUME (recovery/checkpoint.py) passes ``start_chunk``: the plan
    keeps its absolute chunk indexing and staging starts at that
    chunk — earlier chunks were consumed before the crash and are
    never staged again."""
    if not 0 <= start_chunk <= len(chunk_rows):
      raise ValueError(f'start_chunk={start_chunk} outside the '
                       f'{len(chunk_rows)}-chunk plan')
    with self._lock:
      self._plan = list(chunk_rows)
      self._slabs = {}
      self._next_submit = int(start_chunk)
      self.degraded = False
      self.stage_done_t = {}
      self.ack_t = {}
    self._ensure_worker()
    # sized from the argument, not self._plan — the worker owns the
    # ring state once _ensure_worker starts it, so reads go through
    # the lock (or, like here, never touch the shared field at all)
    for _ in range(min(self.max_ahead,
                       len(chunk_rows) - int(start_chunk))):
      self._submit_next()

  def watermarks(self) -> Dict[str, int]:
    """Ring position snapshot for checkpoint metadata: the next chunk
    the worker will be asked to stage and the slabs currently held."""
    with self._lock:
      return dict(next_submit=int(self._next_submit),
                  held=len(self._slabs), planned=len(self._plan))

  def close(self):
    self._stop = True
    self._q.put(None)
    w = self._worker
    if w is not None:
      w.join(timeout=5.0)
    self._worker = None
    self._stop = False
    # drain whatever the dead worker left behind (queued chunk ids, the
    # None sentinel itself when the worker exited on a chunk id + _stop
    # instead): a stale None would kill the NEXT epoch's fresh worker on
    # its first pop, silently degrading every take() to the timeout path
    try:
      while True:
        self._q.get_nowait()
    except queue.Empty:
      pass

  def _ensure_worker(self):
    if self._worker is not None and self._worker.is_alive():
      return
    self._worker = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-storage-stager')
    self._worker.start()

  def _submit_next(self):
    with self._lock:
      c = self._next_submit
      if c >= len(self._plan):
        return
      self._next_submit = c + 1
      self._slabs[c] = _Slab()
    self._q.put(c)

  # --------------------------------------------------------------- worker

  def _loop(self):
    while True:
      c = self._q.get()
      if c is None or self._stop:
        return
      with self._lock:
        slab = self._slabs.get(c)
        rows_abs = self._plan[c] if c < len(self._plan) else None
      if slab is None or rows_abs is None:
        continue   # epoch moved on under us
      try:
        with spans.span('storage.stage', chunk=int(c),
                        rows=int(rows_abs.shape[0])):
          t0 = time.perf_counter()
          # worker-only fault seam: armed faults fire HERE, never in
          # take()'s synchronous fallback — the degraded path must be
          # able to gather the same planned rows cleanly
          self._stage_fault()
          ids, rows = self._gather(rows_abs)
          metrics.observe('storage.stage_ms',
                          (time.perf_counter() - t0) * 1e3)
          t1 = time.perf_counter()
          fault_point('storage.promote')
          slab.ids, slab.rows = ids, rows
          slab.staged_async = True
          metrics.inc('storage.staged_rows', int(rows_abs.shape[0]))
          metrics.inc('storage.staged_bytes', int(rows.nbytes))
          metrics.observe('storage.promote_ms',
                          (time.perf_counter() - t1) * 1e3)
          metrics.set_gauge('storage.ring_rows', self._ring_rows())
      except BaseException as e:   # a chaos 'raise' must not kill later chunks
        slab.error = e
        self.degraded = True
      finally:
        slab.t_done = time.perf_counter()
        with self._lock:
          self.stage_done_t[c] = slab.t_done
        slab.ready.set()

  def _stage_fault(self):
    """The worker-thread fault site (chaos suite). Subclasses override
    with their own registered literal name (the dist staging pipeline's
    ``storage.dist_stage``, storage/dist_scan.py)."""
    fault_point('storage.stage')

  def _gather(self, rows_abs: np.ndarray):
    rows = self.store.stage_gather(rows_abs)
    return pad_slab(rows_abs.astype(np.int32), rows)

  def _ring_rows(self) -> int:
    with self._lock:
      return sum(s.rows.shape[0] for s in self._slabs.values()
                 if s.rows is not None)

  # ------------------------------------------------------------- consumer

  def take(self, c: int):
    """Slab for chunk ``c``: ``(ids [cap] int32 sorted+INT32_MAX pads,
    rows [cap, F])``. Blocks up to ``timeout_s`` for the worker, then
    degrades to a synchronous gather of the same planned rows (counted
    in ``storage.prefetch_miss``) — identical bytes either way. Also
    submits the next chunk so the pipeline stays ``max_ahead`` deep."""
    with self._lock:
      slab = self._slabs.get(c)
      rows_abs = self._plan[c]
    ok = slab is not None and slab.ready.wait(self.timeout_s)
    self._submit_next()
    if ok and slab.error is None and slab.ids is not None:
      return slab.ids, slab.rows
    # degraded path: the worker died, faulted, or is too slow — gather
    # the SAME planned rows on the dispatch thread. Never a wrong
    # batch, only a slower one.
    self.degraded = True
    metrics.inc('storage.prefetch_miss', int(rows_abs.shape[0]))
    return self._gather(rows_abs)

  def ack(self, c: int):
    """Chunk ``c``'s program has consumed its slab (the device_put
    copied it): free the ring slot."""
    with self._lock:
      self._slabs.pop(c, None)
      self.ack_t[c] = time.perf_counter()
    metrics.set_gauge('storage.ring_rows', self._ring_rows())
