"""TieredScanTrainer: the scanned epoch over an out-of-core feature store.

``loader.ScanTrainer`` requires the whole [N, F] feature table in HBM;
this trainer runs the SAME epoch-as-a-program over a
``storage.TieredFeature`` whose table spans HBM -> host RAM -> disk:

* **Prologue plan, one dispatch.** The epoch-seeds program is extended
  with an id-only replay of the sampler over every step (same
  ``fold_in(base_key, count)`` keys the chunk programs will derive, so
  the draws are bit-identical by the PR 1/4 replay contracts) and emits
  the [steps, node_cap] STORAGE-ROW matrix alongside the seed matrix —
  still ONE ``epoch_seeds`` dispatch, so the epoch budget stays
  ``ceil(steps/K) + 2``. The row matrix is fetched once (the prologue's
  one explicit ``jax.device_get``) and ``planner.plan_from_rows`` turns
  it into per-chunk sorted miss sets.
* **Chunk-boundary staging.** While chunk ``c`` trains on device, the
  bounded staging worker (storage/staging.py) gathers chunk ``c+1``'s
  warm/disk rows into a pow2-padded host slab; at the boundary the
  dispatch thread device_puts the slab (explicit — the strict_guards
  region stays transfer-clean) and dispatches the chunk. Slabs are
  acked (freed) as soon as their chunk is dispatched.
* **In-program tiered gather.** The chunk program's feature gather is
  hot-prefix ``take`` + slab ``searchsorted`` — every non-hot row a
  chunk touches is in its slab by construction (the plan is exact), so
  losses are BIT-IDENTICAL to the all-HBM ScanTrainer. Staging shapes
  are pow2-capped: one executable per (chunk length, slab cap) pair.
* **Degradation, never corruption.** A failed/slow staging worker
  degrades to a synchronous gather of the same planned rows
  (``storage.prefetch_miss``); the chaos suite completes the epoch
  bit-identically with a ``storage.stage`` fault armed.

Sampling runs twice per epoch (once id-only in the plan, once in the
chunks) — the price of an exact plan with zero extra dispatches; the
oversubscription gate (bench.py 'oversub' section, ROADMAP item 2)
bounds the total at ~1.5x the all-HBM epoch wall.
"""
from typing import Optional

import numpy as np

from ..loader.node_loader import NodeLoader
from ..loader.scan_epoch import ScanTrainer
from ..metrics import spans
from ..utils.strict import strict_guards
from ..utils.trace import record_dispatch
from . import planner
from .staging import INT32_MAX, ChunkStager
from .tiered import TieredFeature


def tiered_gather(hot, slab_ids, slab, id2i, node):
  """Traced three-way feature gather: node-id buffer -> rows from the
  HBM hot prefix or the chunk's staged slab. Mirrors
  ``ops.collate_batch``'s clamp exactly (pad slots -> node id 0), so a
  tiered batch is byte-identical to the all-HBM gather. Rows in neither
  (an impossible case under an exact plan) read as zeros rather than
  garbage."""
  import jax.numpy as jnp
  safe = jnp.maximum(node, 0)
  ridx = id2i[safe] if id2i is not None else safe
  h = hot.shape[0]
  hot_rows = hot[jnp.clip(ridx, 0, h - 1)]
  pos = jnp.clip(jnp.searchsorted(slab_ids, ridx.astype(jnp.int32)), 0,
                 slab_ids.shape[0] - 1)
  in_slab = slab_ids[pos] == ridx.astype(jnp.int32)
  return jnp.where((ridx < h)[:, None], hot_rows,
                   jnp.where(in_slab[:, None], slab[pos], 0))


class TieredScanTrainer(ScanTrainer):
  """ScanTrainer over a TieredFeature (HBM hot prefix + host warm tier
  + disk cold tier), with the epoch prefetch plan fused into the
  prologue and chunk-boundary staging (module docstring).

  Args (beyond ScanTrainer's):
    max_ahead: staged chunks in flight (2 = double buffer).
    stage_timeout_s: how long a chunk boundary waits for its slab
      before degrading to a synchronous read.
  """

  _NAME = 'TieredScanTrainer'

  def __init__(self, loader: NodeLoader, model, tx, num_classes: int,
               chunk_size: Optional[int] = None,
               seed_labels_only: Optional[bool] = None,
               perm_seed: Optional[int] = None, max_ahead: int = 2,
               stage_timeout_s: float = 30.0, config=None):
    store = loader.data.node_features
    if not isinstance(store, TieredFeature):
      raise ValueError(
          f'{self._NAME} drives a storage.TieredFeature store, got '
          f'{type(store).__name__}; use loader.ScanTrainer for all-HBM '
          'Feature tables')
    self._store = store
    # config= takes a tune artifact (docs/tuning.md): fingerprint-
    # validated in ScanTrainer.__init__, supplies the tuned chunk K
    super().__init__(loader, model, tx, num_classes, chunk_size,
                     seed_labels_only, perm_seed, config=config)
    self._stager = ChunkStager(store, max_ahead=max_ahead,
                               timeout_s=stage_timeout_s)
    self.last_plan = None   # EpochPlan of the most recent epoch

  # ------------------------------------------------------ trainer hooks

  def _resolve_feature_tables(self, loader):
    # the device table is the HOT PREFIX only; the id2index remap is
    # shared with the all-HBM path (scan_tables validates hot_rows >= 1
    # so the collate clamp lands on resident rows)
    return self._store.scan_tables()

  def _make_sample_collate_body(self):
    from .. import ops
    sample_fn, label_cap = self._sample_fn, self._label_cap

    def _sample_collate(fargs, feats, id2i, labels, seeds, smask, key):
      hot, slab_ids, slab = feats
      res = sample_fn(*fargs, seeds, smask, key)
      col = ops.collate_batch(res['node'], res['num_nodes'], res['row'],
                              res['col'], None, None, labels, None,
                              None, label_cap=label_cap)
      x = tiered_gather(hot, slab_ids, slab, id2i, res['node'])
      batch = dict(x=x, edge_index=col['edge_index'],
                   edge_mask=res['edge_mask'], y=col['y'],
                   num_seed_nodes=res['num_sampled_nodes'][0])
      return batch, res['overflow']

    return _sample_collate

  def _build_seed_fn(self):
    """The prologue PLAN program: the base seed/permutation math plus
    an id-only sampler replay over every step, emitting the epoch's
    [steps, node_cap] storage-row matrix — one dispatch, fetched once.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    batch = self._batch_size
    shuffle = self._shuffle
    sample_fn = self._sample_fn
    has_id2i = self._id2i is not None

    def epoch_seeds(fargs, id2i, seeds, key, base_key, count0, steps):
      n = seeds.shape[0]
      order = (jax.random.permutation(key, n) if shuffle
               else jnp.arange(n, dtype=jnp.int32))
      total = steps * batch
      if total <= n:
        order = order[:total]
        mask = jnp.ones((total,), bool)
      else:
        order = jnp.concatenate(
            [order, jnp.zeros((total - n,), order.dtype)])
        mask = jnp.arange(total) < n
      seed_mat = jnp.where(mask, seeds[order], 0).reshape(steps, batch)
      mask_mat = mask.reshape(steps, batch)
      counts = count0 + lax.iota(jnp.int32, steps)

      def step_rows(carry, xs):
        seeds_s, mask_s, count = xs
        k = jax.random.fold_in(base_key, count)
        res = sample_fn(*fargs, seeds_s, mask_s, k)
        safe = jnp.maximum(res['node'], 0)
        ridx = id2i[safe] if has_id2i else safe
        return carry, ridx.astype(jnp.int32)

      _, rows_mat = lax.scan(step_rows, 0, (seed_mat, mask_mat, counts))
      return seed_mat, mask_mat, rows_mat

    return jax.jit(epoch_seeds, static_argnums=(6,))

  # ------------------------------------------------------------- epoch

  def _run_epoch_body(self, state, steps, full_steps, start_step=0,
                      resume_overflow=False):
    """The tiered epoch program: fused plan prologue (one dispatch, one
    explicit fetch) + staged chunk loop. Budget: 1 epoch_seeds +
    ceil(steps/K) scan_chunk + 1 metrics_concat = ceil(steps/K) + 2 —
    unchanged from the all-HBM trainer. A mid-epoch resume
    (``start_step`` — recovery/checkpoint.py) re-runs the SAME plan
    prologue (the permutation and sampler streams replay exactly) and
    begins staging at the resume chunk; consumed chunks never stage
    again."""
    import jax
    if self._seeds_dev is None:
      self._seeds_dev = jax.device_put(
          np.asarray(self.loader.input_seeds, dtype=np.int32))
    perm_key = jax.random.fold_in(self._perm_key, self._epochs)
    fargs = self._sampler._fused_args()
    base_key = self._sampler._key
    count0 = jax.device_put(np.int32(self._sampler._call_count + 1))
    ovf = jax.device_put(np.asarray(bool(resume_overflow)))
    losses, accs = [], []
    start = start_step
    hot = self._feats
    with strict_guards():
      record_dispatch('epoch_seeds')
      seed_mat, mask_mat, rows_mat = self._seed_fn(
          fargs, self._id2i, self._seeds_dev, perm_key, base_key,
          count0, full_steps)
      # the prologue's ONE fetch: the planned storage rows (explicit
      # device_get — strict_guards rejects implicit transfers only)
      rows_host = jax.device_get(rows_mat)[:steps]
      plan = planner.plan_from_rows(rows_host, self.chunk_size,
                                    self._store.hot_rows,
                                    self._store.warm_rows)
      self.last_plan = plan
      self._stager.begin_epoch(plan.chunk_rows,
                               start_chunk=start // self.chunk_size)
      while start < steps:
        k = min(self.chunk_size, steps - start)
        c = start // self.chunk_size
        if self.stage_hook is not None:
          self.stage_hook(c, start, k)
        slab_ids_np, slab_np = self._stager.take(c)
        slab_ids = jax.device_put(slab_ids_np)
        slab = jax.device_put(slab_np)
        record_dispatch('scan_chunk')
        with spans.span('epoch.chunk', start=start, k=k):
          state, ovf, loss_k, acc_k = self._chunk_fn(
              state, ovf, fargs, (hot, slab_ids, slab), self._id2i,
              self._labels, seed_mat, mask_mat, base_key, count0,
              jax.device_put(np.int32(start)), k)
        # the device_put above copied the slab: free its ring slot and
        # let the worker pull the next chunk forward
        self._stager.ack(c)
        losses.append(loss_k)
        accs.append(acc_k)
        self._steps_dispatched = start + k
        if self.ack_hook is not None:
          # the generic chunk-boundary seam (recovery/checkpoint.py
          # rides it) — same carry contract as ScanTrainer
          self._chunk_carry = dict(state=state, ovf=ovf, losses=losses,
                                   accs=accs, steps=steps,
                                   full_steps=full_steps,
                                   start_step=start_step)
          self.ack_hook(c, start, k)
        start += k
      if len(losses) > 1:
        record_dispatch('metrics_concat')
        losses, accs = self._concat_fn(losses, accs)
      else:
        losses, accs = losses[0], accs[0]
    self._sampler._call_count += steps
    self._epochs += 1
    return state, losses, accs, ovf

  def _flight_config(self) -> dict:
    cfg = super()._flight_config()
    cfg.update(hot_rows=self._store.hot_rows,
               warm_rows=self._store.warm_rows,
               disk_rows=self._store.disk_rows)
    return cfg

  def _recovery_capture(self, carry):
    """ScanTrainer's capture plus the staging-ring watermarks — a
    postmortem can see how deep the prefetch pipeline was at the
    boundary (resume re-plans and re-stages; the watermarks are
    diagnostic, not replayed state)."""
    meta, dev = super()._recovery_capture(carry)
    meta['staging'] = self._stager.watermarks()
    return meta, dev

  def close(self):
    """Stop the staging worker thread."""
    self._stager.close()


# keep the module's int sentinel importable next to the trainer (the
# slab pad id tests assert against)
__all__ = ['TieredScanTrainer', 'tiered_gather', 'INT32_MAX']
