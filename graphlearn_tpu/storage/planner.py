"""Epoch prefetch planner: the scanned epoch's miss set, known up front.

The scanned epoch draws its whole seed permutation at the prologue
(loader/scan_epoch.py) and the samplers derive every per-step key from
a ``fold_in`` counter stream that is bit-reproducible (the PR 1/4
replay contracts). Together those make the epoch's ENTIRE feature
access set a pure function of (seeds, perm key, epoch index, sampler
state) — so the out-of-core store never has to guess what to prefetch:
the plan is exact, per chunk, per tier.

Two routes produce the same plan:

* **Fused (production)** — ``TieredScanTrainer`` folds an id-only
  replay of the sampler into its epoch-prologue seed program (the same
  ``epoch_seeds`` dispatch: budget stays ceil(steps/K)+2) and fetches
  the [steps, node_cap] storage-row matrix once. ``plan_from_rows``
  turns it into per-chunk sorted miss sets.
* **Host replay (verification / standalone)** — :func:`replay_seed_matrix`
  mirrors the seed program's permutation math in eager jax on the host
  CPU backend (threefry is bit-identical across backends), and
  :func:`plan_epoch_host` walks the sampler's fused program step by
  step. tests/test_storage.py pins host-planned == device-observed
  under shuffle=True and False.

The plan's unit is the STORAGE ROW (post-``id2index`` hotness remap),
clamped exactly like the collate gather (pad slots -> node id 0), so
"planned" and "gathered" can never disagree on padding.
"""
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .staging import pow2_slab_cap


@dataclass
class EpochPlan:
  """Per-chunk staging plan for one scanned epoch."""
  chunk_size: int
  hot_rows: int
  warm_rows: int
  # per chunk: sorted unique absolute storage rows >= hot_rows
  chunk_rows: List[np.ndarray] = field(default_factory=list)

  @property
  def num_chunks(self) -> int:
    return len(self.chunk_rows)

  def slab_caps(self) -> List[int]:
    """The pow2 staging-shape set this plan compiles against."""
    return [pow2_slab_cap(int(r.shape[0])) for r in self.chunk_rows]

  def stats(self) -> dict:
    rows = [int(r.shape[0]) for r in self.chunk_rows]
    warm_edge = self.hot_rows + self.warm_rows
    disk = [int(np.sum(r >= warm_edge)) for r in self.chunk_rows]
    return dict(chunks=self.num_chunks, planned_rows=int(sum(rows)),
                planned_disk_rows=int(sum(disk)),
                max_chunk_rows=int(max(rows)) if rows else 0,
                slab_caps=sorted(set(self.slab_caps())))


def rows_for_nodes(nodes: np.ndarray,
                   id2index: Optional[np.ndarray]) -> np.ndarray:
  """Node-id buffer -> storage rows, with the collate gather's exact
  clamp (FILL=-1 pads -> node id 0 -> that node's storage row)."""
  safe = np.maximum(np.asarray(nodes, np.int64), 0)
  return id2index[safe] if id2index is not None else safe


def plan_from_rows(rows_mat: np.ndarray, chunk_size: int, hot_rows: int,
                   warm_rows: int = 0) -> EpochPlan:
  """Per-chunk miss sets from a [steps, cap] storage-row matrix (the
  fused plan program's output, already clamped + remapped). Rows below
  ``hot_rows`` are HBM-resident and drop out; the rest dedup per chunk
  into one sorted staging list."""
  rows_mat = np.asarray(rows_mat)
  steps = rows_mat.shape[0]
  plan = EpochPlan(chunk_size=int(chunk_size), hot_rows=int(hot_rows),
                   warm_rows=int(warm_rows))
  for start in range(0, steps, chunk_size):
    block = rows_mat[start:start + chunk_size].reshape(-1)
    uniq = np.unique(block)
    plan.chunk_rows.append(uniq[uniq >= hot_rows].astype(np.int64))
  return plan


@dataclass
class ExchangePlan:
  """Per-chunk MISS-EXCHANGE program for one scanned distributed epoch
  (storage/dist_scan.py): which positions of each shard's sorted row
  table its peers (or the shard itself) will request during each chunk,
  beyond the replicated hot cache and the per-partition HBM hot prefix.

  ``chunk_rows[c]`` holds ENCODED sorted staging rows
  ``p * n_max + position`` — the flat address space the dist stager
  decodes back into per-shard slabs. The unit is the POSITION in the
  owning partition's sorted id table (what ``_shard_body`` resolves
  requests to in-program), so "planned" and "served" can never disagree
  on routing."""
  chunk_size: int
  n_max: int
  hot_prefix_rows: int
  num_partitions: int
  chunk_rows: List[np.ndarray] = field(default_factory=list)

  @property
  def num_chunks(self) -> int:
    return len(self.chunk_rows)

  def slab_caps(self) -> List[int]:
    """Per-chunk pow2 PER-SHARD slab capacities (the closed staging
    shape set the chunk programs compile against): the max per-shard
    staged count of the chunk, padded to a power of two."""
    caps = []
    for enc in self.chunk_rows:
      if enc.size:
        per = np.bincount(enc // self.n_max,
                          minlength=self.num_partitions)
        caps.append(pow2_slab_cap(int(per.max())))
      else:
        caps.append(1)
    return caps

  def stats(self) -> dict:
    rows = [int(r.shape[0]) for r in self.chunk_rows]
    return dict(chunks=self.num_chunks, planned_rows=int(sum(rows)),
                max_chunk_rows=int(max(rows)) if rows else 0,
                slab_caps=sorted(set(self.slab_caps())))


def plan_exchange(rows_mat: np.ndarray, chunk_size: int,
                  feature_pb: np.ndarray, feat_ids: np.ndarray,
                  hot_prefix_rows: int,
                  cache_ids: Optional[np.ndarray] = None) -> ExchangePlan:
  """The exact miss-exchange program from the prologue's replayed
  [P, steps, node_cap] node-id matrix (FILL pads < 0).

  Mirrors the in-program lookup exactly: ids hitting the REPLICATED hot
  cache never enter the exchange (the cache split happens before the
  all_to_all), every other requested id routes to its owning partition
  (``feature_pb``) and resolves to a position in that partition's
  sorted id table; positions below the HBM ``hot_prefix_rows`` are
  device-resident and drop out, the rest dedup per chunk into the
  encoded staging list."""
  rows_mat = np.asarray(rows_mat)
  nparts, steps = rows_mat.shape[0], rows_mat.shape[1]
  n_max = feat_ids.shape[1]
  plan = ExchangePlan(chunk_size=int(chunk_size), n_max=int(n_max),
                      hot_prefix_rows=int(hot_prefix_rows),
                      num_partitions=int(nparts))
  feature_pb = np.asarray(feature_pb)
  for start in range(0, steps, chunk_size):
    blk = rows_mat[:, start:start + chunk_size].reshape(-1)
    blk = np.unique(blk[blk >= 0]).astype(np.int64)
    if cache_ids is not None and cache_ids.size:
      cpos = np.clip(np.searchsorted(cache_ids, blk), 0,
                     cache_ids.shape[0] - 1)
      blk = blk[cache_ids[cpos] != blk]
    owners = feature_pb[blk]
    enc = []
    for p in range(nparts):
      ids_p = blk[owners == p]
      pos = np.clip(np.searchsorted(feat_ids[p], ids_p), 0, n_max - 1)
      found = feat_ids[p][pos] == ids_p
      stage = pos[found & (pos >= hot_prefix_rows)].astype(np.int64)
      if stage.size:
        enc.append(p * n_max + stage)
    plan.chunk_rows.append(
        np.sort(np.concatenate(enc)) if enc else
        np.zeros((0,), np.int64))
  return plan


def replay_seed_matrix(seeds: np.ndarray, perm_key, steps: int,
                       batch: int, shuffle: bool,
                       nparts: int = 1) -> tuple:
  """Host replay of the scanned trainers' seed programs: returns
  (seed_mat, mask_mat) exactly as ``ScanTrainer._build_seed_fn``
  (nparts == 1; [steps, batch], zero-padded ragged tail) or
  ``DistScanTrainer._build_seed_fn`` (nparts > 1; [P, steps, batch],
  cyclic-padded tail) computes them on device. Runs in eager jax ON THE
  HOST CPU backend — jax's threefry PRNG is bit-identical across
  backends, which is the whole reason the plan can be trusted."""
  import jax
  seeds = np.asarray(seeds, np.int32)
  n = seeds.shape[0]
  with jax.default_device(jax.local_devices(backend='cpu')[0]):
    order = (np.asarray(jax.random.permutation(perm_key, n))
             if shuffle else np.arange(n, dtype=np.int32))
  total = steps * nparts * batch
  if total <= n:
    ext = order[:total]
    maskf = np.ones((total,), bool)
  elif nparts == 1:
    ext = np.concatenate(
        [order, np.zeros((total - n,), order.dtype)])
    maskf = np.arange(total) < n
  else:
    pad = order[np.arange(total - n, dtype=np.int64) % n]
    ext = np.concatenate([order, pad])
    maskf = np.arange(total) < n
  if nparts == 1:
    seed_mat = np.where(maskf, seeds[ext], 0).reshape(steps, batch)
    return seed_mat, maskf.reshape(steps, batch)
  seed_mat = seeds[ext].reshape(steps, nparts, batch).transpose(1, 0, 2)
  mask_mat = maskf.reshape(steps, nparts, batch).transpose(1, 0, 2)
  return seed_mat, mask_mat


def plan_epoch_host(sampler, seeds: np.ndarray, perm_key, steps: int,
                    batch: int, shuffle: bool, chunk_size: int,
                    hot_rows: int, warm_rows: int = 0,
                    id2index: Optional[np.ndarray] = None,
                    count0: Optional[int] = None) -> EpochPlan:
  """The verification route: replay the permutation AND the sampler's
  per-step draws on the host, step by step, and build the plan the
  fused route must match. O(steps) eager program calls — test/debug
  tooling, not the production prologue (that is the fused plan program,
  one dispatch)."""
  import jax
  seed_mat, mask_mat = replay_seed_matrix(seeds, perm_key, steps, batch,
                                          shuffle)
  fanouts = tuple(sampler.num_neighbors)
  fn = sampler._build_homo_fn(batch, fanouts)
  fargs = sampler._fused_args()
  base_key = sampler._key
  if count0 is None:
    count0 = sampler._call_count + 1
  rows = []
  for g in range(steps):
    key = jax.random.fold_in(base_key, count0 + g)
    res = fn(*fargs, np.asarray(seed_mat[g]), np.asarray(mask_mat[g]),
             key)
    rows.append(rows_for_nodes(np.asarray(res['node']), id2index))
  return plan_from_rows(np.stack(rows), chunk_size, hot_rows, warm_rows)
