"""Disk (cold) tier: memory-mapped row-chunk files.

The bottom tier of the out-of-core feature store (docs/storage.md). A
DiskTier is a directory of fixed-height row-chunk files plus a
``meta.json``; rows are addressed by their tier-relative index and
gathered through ``np.memmap`` / ``np.load(mmap_mode='r')`` views, so
the host working set is the OS page cache, not a resident copy — the
property that lets a 100M–1B-node feature table (ROADMAP item 2) back a
store whose RAM tiers hold only the hot/warm prefix.

Two on-disk layouts:

* ``npy``  — one ``chunk_NNNNN.npy`` per row block (np.save /
  np.load(mmap_mode='r')): self-describing, interoperable with plain
  numpy tooling, the default.
* ``raw``  — one ``chunk_NNNNN.raw`` per row block (bare np.memmap):
  supports :meth:`create_empty` + :meth:`write_rows`, the streaming
  spill path (serving materialization writes layer stores block by
  block without ever holding the table in RAM).

Chunk files bound two things: the mmap handle working set (handles open
lazily, per chunk) and the unit of sequential disk IO the staging
pipeline (storage/staging.py) issues. ``rows_per_chunk`` is a layout
knob, not a correctness one — gathers span chunk boundaries freely.
"""
import json
import os

import numpy as np

_META = 'meta.json'


def _chunk_name(i: int, fmt: str) -> str:
  return f'chunk_{i:05d}.{fmt}'


class DiskTier:
  """A [rows, dim] on-disk row table, gathered via memory maps.

  Open an existing tier with ``DiskTier(dir_path)``; create one from an
  in-RAM array with :meth:`write`, or streamed with
  :meth:`create_empty` + :meth:`write_rows` (raw layout only).
  """

  def __init__(self, dir_path: str):
    self.dir = str(dir_path)
    with open(os.path.join(self.dir, _META), encoding='utf-8') as fh:
      meta = json.load(fh)
    self.rows = int(meta['rows'])
    self.dim = int(meta['dim'])
    self.dtype = np.dtype(meta['dtype'])
    self.rows_per_chunk = int(meta['rows_per_chunk'])
    self.fmt = meta['fmt']
    self.num_chunks = int(meta['num_chunks'])
    self._maps = {}   # chunk index -> lazily opened mmap view

  # ------------------------------------------------------------ creation

  @classmethod
  def write(cls, dir_path: str, array, rows_per_chunk: int = 65536,
            fmt: str = 'npy') -> 'DiskTier':
    """Write ``array`` ([rows, dim]) as a chunked tier and open it."""
    array = np.asarray(array)
    if array.ndim != 2:
      raise ValueError(f'DiskTier stores [rows, dim] tables, got shape '
                       f'{array.shape}')
    tier = cls.create_empty(dir_path, array.shape[0], array.shape[1],
                            array.dtype, rows_per_chunk=rows_per_chunk,
                            fmt=fmt)
    for start in range(0, array.shape[0], rows_per_chunk):
      tier.write_rows(start, array[start:start + rows_per_chunk])
    return tier

  @classmethod
  def create_empty(cls, dir_path: str, rows: int, dim: int, dtype,
                   rows_per_chunk: int = 65536,
                   fmt: str = 'npy') -> 'DiskTier':
    """Allocate an all-zeros tier to be filled with :meth:`write_rows`
    (the streaming spill path). Both layouts allocate their chunk files
    up front so partial writes never leave a short file behind."""
    if fmt not in ('npy', 'raw'):
      raise ValueError(f"fmt must be 'npy' or 'raw', got {fmt!r}")
    if rows_per_chunk < 1:
      raise ValueError('rows_per_chunk must be >= 1')
    rows, dim = int(rows), int(dim)
    dtype = np.dtype(dtype)
    os.makedirs(dir_path, exist_ok=True)
    num_chunks = max(1, -(-rows // rows_per_chunk))
    for i in range(num_chunks):
      h = min(rows_per_chunk, rows - i * rows_per_chunk)
      h = max(h, 0)
      path = os.path.join(dir_path, _chunk_name(i, fmt))
      if fmt == 'npy':
        np.save(path, np.zeros((h, dim), dtype))
      else:
        mm = np.memmap(path, dtype=dtype, mode='w+', shape=(h, dim))
        mm.flush()
        del mm
    meta = dict(rows=rows, dim=dim, dtype=dtype.name,
                rows_per_chunk=int(rows_per_chunk), fmt=fmt,
                num_chunks=num_chunks)
    with open(os.path.join(dir_path, _META), 'w', encoding='utf-8') as fh:
      json.dump(meta, fh)
    return cls(dir_path)

  def write_rows(self, start: int, block):
    """Write ``block`` at tier rows [start, start+len) (spanning chunk
    boundaries). npy chunks are rewritten via a writable mmap of the
    saved file; raw chunks through np.memmap 'r+'."""
    block = np.asarray(block, self.dtype)
    done = 0
    while done < block.shape[0]:
      row = start + done
      c, off = divmod(row, self.rows_per_chunk)
      mm = self._open(c, mode='r+')
      n = min(mm.shape[0] - off, block.shape[0] - done)
      if n <= 0:
        raise IndexError(f'write_rows past tier end (row {row} of '
                         f'{self.rows})')
      mm[off:off + n] = block[done:done + n]
      if hasattr(mm, 'flush'):
        mm.flush()
      done += n
    # drop cached read-only views so later gathers see the write
    self._maps.clear()

  # ------------------------------------------------------------- access

  def _open(self, c: int, mode: str = 'r'):
    if mode == 'r' and c in self._maps:
      return self._maps[c]
    path = os.path.join(self.dir, _chunk_name(c, self.fmt))
    h = min(self.rows_per_chunk, self.rows - c * self.rows_per_chunk)
    if self.fmt == 'npy':
      mm = np.load(path, mmap_mode=mode)
    else:
      mm = np.memmap(path, dtype=self.dtype, mode=mode, shape=(h, self.dim))
    if mode == 'r':
      self._maps[c] = mm
    return mm

  def gather(self, rel_ids) -> np.ndarray:
    """Rows for tier-relative indices (any order, duplicates fine).
    Reads group by chunk so each touched chunk is one strided mmap
    take, not a per-row seek storm."""
    rel_ids = np.asarray(rel_ids, np.int64).reshape(-1)
    if rel_ids.size == 0:
      return np.zeros((0, self.dim), self.dtype)
    if rel_ids.min() < 0 or rel_ids.max() >= self.rows:
      raise IndexError(f'tier row out of range [0, {self.rows}): '
                       f'[{rel_ids.min()}, {rel_ids.max()}]')
    out = np.empty((rel_ids.shape[0], self.dim), self.dtype)
    chunks = rel_ids // self.rows_per_chunk
    order = np.argsort(chunks, kind='stable')
    sorted_chunks = chunks[order]
    bounds = np.flatnonzero(np.diff(sorted_chunks)) + 1
    for grp in np.split(order, bounds):
      c = int(chunks[grp[0]])
      mm = self._open(c)
      out[grp] = mm[rel_ids[grp] - c * self.rows_per_chunk]
    return out

  @property
  def shape(self):
    return (self.rows, self.dim)

  @property
  def nbytes(self) -> int:
    return self.rows * self.dim * self.dtype.itemsize

  def close(self):
    """Drop cached mmap views (handles close with the views)."""
    self._maps.clear()


def spill_array(dir_path: str, array, rows_per_chunk: int = 65536,
                fmt: str = 'npy') -> DiskTier:
  """Write ``array`` to a DiskTier at ``dir_path`` — the one-call spill
  used by TieredFeature / the serving materializer."""
  return DiskTier.write(dir_path, array, rows_per_chunk=rows_per_chunk,
                        fmt=fmt)
