"""TieredFeature: the three-tier HBM -> host-RAM -> disk feature store.

GLT's UnifiedTensor spans GPU HBM plus a pinned-CPU zero-copy shard so
only misses cross the bus (PAPER.md, unified_tensor.cu); our two-tier
``data.Feature`` port still required every row in host RAM. This store
adds the third tier: storage rows ``[0, H)`` are HBM-resident (the hot
prefix, after the hotness reorder), ``[H, H+W)`` live in host RAM (the
warm tier), and ``[H+W, N)`` live on disk as memory-mapped chunk files
(storage/disk.py) — a products-scale (2.45M-node) or papers-scale
feature table fits on a machine whose RAM holds only the warm prefix.

``TieredFeature`` plugs in wherever ``data.Feature`` is accepted (the
loaders' mixed-gather path, ``cpu_get`` serving, ``Dataset`` stores):
it subclasses Feature and routes host-row resolution through
``UnifiedTensor._host_resolve`` — warm rows read RAM, cold rows first
consult the staging ring of promoted blocks (rows the chunk-boundary
prefetcher, storage/staging.py, already pulled), then fall back to a
synchronous mmap gather counted in ``storage.prefetch_miss``.
Synchronously-read cold rows are promoted into a bounded warm cache so
reactive (per-batch) workloads self-warm.

The scanned-epoch integration — where the epoch's whole miss set is
planned up front and staged ahead of each chunk — lives in
storage/scan.py (``TieredScanTrainer``).
"""
import threading
from typing import List, Optional, Tuple

import numpy as np

from .. import metrics
from ..data.feature import Feature
from ..data.unified_tensor import UnifiedTensor
from .disk import DiskTier, spill_array


class _PromotedCache:
  """Bounded FIFO of promoted cold-row blocks, searched newest-first by
  sorted absolute storage row — the reactive half of the warm tier
  (the planned half is the staging ring, storage/staging.py)."""

  def __init__(self, capacity_rows: int):
    self.capacity_rows = int(capacity_rows)
    self._blocks: List[Tuple[np.ndarray, np.ndarray]] = []
    self._rows = 0
    self._lock = threading.Lock()

  def put(self, abs_rows_sorted: np.ndarray, rows: np.ndarray):
    if self.capacity_rows <= 0 or abs_rows_sorted.size == 0:
      return
    with self._lock:
      self._blocks.append((abs_rows_sorted, rows))
      self._rows += int(abs_rows_sorted.shape[0])
      while self._rows > self.capacity_rows and len(self._blocks) > 1:
        old_ids, _ = self._blocks.pop(0)
        self._rows -= int(old_ids.shape[0])

  def lookup(self, abs_rows: np.ndarray, out: np.ndarray,
             missing: np.ndarray) -> np.ndarray:
    """Fill ``out`` rows found in the cache; returns the updated
    ``missing`` bool mask (True = still unresolved)."""
    with self._lock:
      blocks = list(self._blocks)
    for ids, rows in reversed(blocks):
      if not missing.any():
        break
      pos = np.searchsorted(ids, abs_rows)
      pos = np.clip(pos, 0, ids.shape[0] - 1)
      hit = missing & (ids[pos] == abs_rows)
      if hit.any():
        out[hit] = rows[pos[hit]]
        missing = missing & ~hit
    return missing

  @property
  def rows(self) -> int:
    with self._lock:
      return self._rows


class _TieredTensor(UnifiedTensor):
  """UnifiedTensor whose host span stacks a warm-RAM block over a disk
  tier. The device part and the pow2 cold-block shipping machinery are
  inherited unchanged — only ``_host_resolve`` learns tiers."""

  def __init__(self, warm: Optional[np.ndarray], disk: Optional[DiskTier],
               disk_base: int, promoted: _PromotedCache,
               device=None, dtype=None):
    super().__init__(device=device, dtype=dtype)
    self._warm = warm
    self._disk = disk
    # tier-relative offset of host row (H+W) inside the DiskTier: 0 when
    # the tier holds only the cold tail, H+W when it holds all N rows
    self._disk_base = int(disk_base)
    self._promoted = promoted
    warm_n = int(warm.shape[0]) if warm is not None else 0
    disk_n = int(disk.rows - disk_base) if disk is not None else 0
    self._warm_n = warm_n
    self._host_rows_n = warm_n + disk_n

  @property
  def host_part(self):
    # the warm block is the RAM-resident host part; disk rows resolve
    # through _host_resolve (consumers must use host_rows for spans)
    return self._warm

  def _host_resolve(self, rel_ids: np.ndarray) -> np.ndarray:
    rel_ids = np.asarray(rel_ids, np.int64).reshape(-1)
    dim = (self._warm.shape[1] if self._warm is not None
           else self._disk.dim)
    dt = (self._warm.dtype if self._warm is not None else self._disk.dtype)
    out = np.zeros((rel_ids.shape[0], dim), dt)
    is_warm = rel_ids < self._warm_n
    if is_warm.any():
      out[is_warm] = self._warm[rel_ids[is_warm]]
    cold = ~is_warm
    if cold.any():
      # absolute storage rows key the promoted cache (the staging ring
      # promotes by storage row, which callers everywhere share)
      abs_rows = rel_ids[cold] + self._device_rows
      block = np.zeros((int(cold.sum()), dim), dt)
      missing = np.ones((block.shape[0],), bool)
      missing = self._promoted.lookup(abs_rows, block, missing)
      if missing.any():
        n_miss = int(missing.sum())
        metrics.inc('storage.prefetch_miss', n_miss)
        disk_rel = (rel_ids[cold][missing] - self._warm_n
                    + self._disk_base)
        read = self._disk.gather(disk_rel)
        block[missing] = read
        # promote: repeated reactive access to the same cold rows warms
        order = np.argsort(abs_rows[missing], kind='stable')
        self._promoted.put(abs_rows[missing][order], read[order])
      out[cold] = block
    return out


class TieredFeature(Feature):
  """Three-tier drop-in for ``data.Feature``.

  Args:
    source: the full [N, F] table — an in-RAM np.ndarray (its cold tail
      is spilled to ``spill_dir``), OR a ``DiskTier`` holding all N
      rows (the already-on-disk case: hot/warm prefixes are read from
      it once at init), OR a path to such a tier.
    hot_rows: H — rows [0, H) resident in HBM.
    warm_rows: W — rows [H, H+W) resident in host RAM. None with an
      array source means "everything not hot stays warm" (no disk
      tier); None with a disk source means W = 0.
    id2index: optional [N] node-id -> storage-row map from the hotness
      reorder, exactly as ``data.Feature`` (row 0 = hottest).
    dtype: optional storage dtype for the HBM tier.
    device: explicit device for the hot tier.
    spill_dir: where to write the cold tail when ``source`` is an
      array and cold rows exist (required in that case).
    rows_per_chunk / fmt: DiskTier layout knobs for the spill.
    promoted_rows: capacity of the bounded promoted-row cache reactive
      cold reads warm into (0 disables promotion).
  """

  def __init__(self, source, hot_rows: int = 0,
               warm_rows: Optional[int] = None,
               id2index: Optional[np.ndarray] = None, dtype=None,
               device=None, spill_dir: Optional[str] = None,
               rows_per_chunk: int = 65536, fmt: str = 'npy',
               promoted_rows: int = 65536):
    if isinstance(source, str):
      source = DiskTier(source)
    self._disk: Optional[DiskTier] = None
    self._warm_np: Optional[np.ndarray] = None
    self._hot_np: Optional[np.ndarray] = None
    if isinstance(source, DiskTier):
      n = source.rows
      self.hot_rows = max(0, min(int(hot_rows), n))
      w = 0 if warm_rows is None else int(warm_rows)
      self.warm_rows = max(0, min(w, n - self.hot_rows))
      self._disk = source
      self._disk_base = self.hot_rows + self.warm_rows
      if self.hot_rows:
        self._hot_np = source.gather(np.arange(self.hot_rows))
      if self.warm_rows:
        self._warm_np = source.gather(
            np.arange(self.hot_rows, self._disk_base))
      self._n, self._f = n, source.dim
      self._np_dtype = source.dtype
    else:
      arr = np.asarray(source)
      n = arr.shape[0]
      self.hot_rows = max(0, min(int(hot_rows), n))
      w = (n - self.hot_rows) if warm_rows is None else int(warm_rows)
      self.warm_rows = max(0, min(w, n - self.hot_rows))
      cold = n - self.hot_rows - self.warm_rows
      # COPIES, not views: a slice view pins the whole source array
      # (its .base) in host RAM for the store's lifetime — the caller
      # must be able to `del arr` after construction and keep only
      # hot+warm resident, or the out-of-core point is lost
      self._hot_np = (arr[:self.hot_rows].copy() if self.hot_rows
                      else None)
      self._warm_np = (arr[self.hot_rows:self.hot_rows + self.warm_rows]
                       .copy() if self.warm_rows else None)
      if cold:
        if spill_dir is None:
          raise ValueError(
              f'{cold} rows fall in the disk tier but no spill_dir was '
              'given — pass spill_dir=... (the cold tail is written as '
              'memory-mapped chunk files), or widen hot/warm to cover '
              'the table')
        self._disk = spill_array(spill_dir,
                                 arr[self.hot_rows + self.warm_rows:],
                                 rows_per_chunk=rows_per_chunk, fmt=fmt)
        self._disk_base = 0
      else:
        self._disk_base = 0
      self._n, self._f = n, int(arr.shape[1])
      self._np_dtype = arr.dtype
    self.disk_rows = self._n - self.hot_rows - self.warm_rows
    # Feature surface (no super().__init__: the base stores the full
    # array; the whole point here is NOT holding one)
    self.split_ratio = self.hot_rows / self._n if self._n else 0.0
    self.cache_rows = self.hot_rows
    self.device_group_list = None
    self.device = device
    self.with_device = self.hot_rows > 0
    self._id2index = (np.asarray(id2index) if id2index is not None
                      else None)
    self.dtype = dtype
    self._unified = None
    self._id2index_dev = None
    self._promoted = _PromotedCache(promoted_rows)

  # ------------------------------------------------------------ lifecycle

  def lazy_init(self):
    if self._unified is not None:
      return
    ut = _TieredTensor(self._warm_np, self._disk, self._disk_base,
                       self._promoted, device=self.device,
                       dtype=self.dtype)
    ut.init_from(self._hot_np, None)
    # init_from only sees the hot block; stamp the tiered host span
    ut._host_rows_n = self.warm_rows + self.disk_rows
    self._unified = ut
    self._stamp_kernel_routing()
    if self._id2index is not None:
      import jax
      self._id2index_dev = jax.device_put(self._id2index, self.device)
    metrics.set_gauge('storage.hot_rows', self.hot_rows)
    metrics.set_gauge('storage.warm_rows', self.warm_rows)
    metrics.set_gauge('storage.disk_rows', self.disk_rows)

  # ------------------------------------------------------- Feature surface

  @property
  def feature_array(self):
    raise AttributeError(
        'TieredFeature holds no resident full table — use cpu_get / '
        '__getitem__ (tiers resolve per request), or stage_gather for '
        'planned blocks')

  @property
  def shape(self):
    return (self._n, self._f)

  @property
  def size(self) -> int:
    return self._n

  def cpu_get(self, ids) -> np.ndarray:
    """Pure-host gather across all three tiers (hot rows come from the
    host copy kept for IPC/rebuild, not from HBM)."""
    ids = np.asarray(ids).reshape(-1)
    if self._id2index is not None:
      rows = self._id2index[ids]
    else:
      rows = ids
    return self._rows_host(np.asarray(rows, np.int64))

  def _rows_host(self, rows: np.ndarray) -> np.ndarray:
    out = np.zeros((rows.shape[0], self._f), self._np_dtype)
    is_hot = rows < self.hot_rows
    if is_hot.any():
      out[is_hot] = self._hot_np[rows[is_hot]]
    rest = ~is_hot
    if rest.any():
      self.lazy_init()
      out[rest] = self._unified._host_resolve(rows[rest] - self.hot_rows)
    return out

  def stage_gather(self, abs_rows: np.ndarray) -> np.ndarray:
    """Warm/disk rows for ABSOLUTE storage rows >= hot_rows, straight
    from the tiers (no promoted-cache consult, no miss accounting) —
    the staging worker's read path (storage/staging.py)."""
    abs_rows = np.asarray(abs_rows, np.int64).reshape(-1)
    if abs_rows.size and abs_rows.min() < self.hot_rows:
      raise IndexError('stage_gather serves the host tiers: rows must '
                       f'be >= hot_rows ({self.hot_rows})')
    out = np.zeros((abs_rows.shape[0], self._f), self._np_dtype)
    rel = abs_rows - self.hot_rows
    is_warm = rel < self.warm_rows
    if is_warm.any():
      out[is_warm] = self._warm_np[rel[is_warm]]
    cold = ~is_warm
    if cold.any():
      out[cold] = self._disk.gather(rel[cold] - self.warm_rows
                                    + self._disk_base)
    return out

  def promote(self, abs_rows_sorted: np.ndarray, rows: np.ndarray):
    """Install already-gathered cold rows into the promoted cache (the
    staging pipeline's hand-off into the reactive warm path)."""
    self._promoted.put(np.asarray(abs_rows_sorted, np.int64),
                       np.asarray(rows))

  def scan_tables(self):
    """(hot_table_dev [H, F], id2index_dev) — the device-resident
    prefix the tiered scanned trainer (storage/scan.py) gathers hot
    rows from. Requires hot_rows >= 1 (pad slots clamp into the hot
    prefix)."""
    self.lazy_init()
    if self._unified.device_part is None:
      raise ValueError('TieredFeature.scan_tables needs hot_rows >= 1 '
                       '(the scanned chunk program clamps pad slots '
                       'into the hot prefix)')
    return self._unified.device_part, self._id2index_dev

  def tier_occupancy(self) -> dict:
    """Row counts per tier plus the promoted-cache fill — the
    ``storage.*`` gauge payload."""
    return dict(hot=self.hot_rows, warm=self.warm_rows,
                disk=self.disk_rows, promoted=self._promoted.rows)

  # ----------------------------------------------------------------- IPC

  def share_ipc(self):
    """Hand the tier layout to another consumer: the disk tier travels
    as its directory path (mmaps reopen on the other side), hot/warm
    blocks as host arrays (reference feature.py:240-257 — CUDA-IPC
    re-init collapses to host-array handoff on TPU)."""
    return ('tiered', self._disk.dir if self._disk is not None else None,
            self._disk_base, self._hot_np, self._warm_np,
            self._id2index, self.dtype)

  @classmethod
  def from_ipc_handle(cls, handle):
    tag, disk_dir, disk_base, hot_np, warm_np, id2index, dtype = handle
    assert tag == 'tiered', tag
    obj = cls.__new__(cls)
    obj._disk = DiskTier(disk_dir) if disk_dir is not None else None
    obj._disk_base = int(disk_base)
    obj._hot_np, obj._warm_np = hot_np, warm_np
    obj.hot_rows = int(hot_np.shape[0]) if hot_np is not None else 0
    obj.warm_rows = int(warm_np.shape[0]) if warm_np is not None else 0
    obj.disk_rows = (int(obj._disk.rows - disk_base)
                     if obj._disk is not None else 0)
    obj._n = obj.hot_rows + obj.warm_rows + obj.disk_rows
    ref = hot_np if hot_np is not None else warm_np
    obj._f = (int(ref.shape[1]) if ref is not None else obj._disk.dim)
    obj._np_dtype = (ref.dtype if ref is not None else obj._disk.dtype)
    obj.split_ratio = obj.hot_rows / obj._n if obj._n else 0.0
    obj.cache_rows = obj.hot_rows
    obj.device_group_list = None
    obj.device = None
    obj.with_device = obj.hot_rows > 0
    obj._id2index = id2index
    obj.dtype = dtype
    obj._unified = None
    obj._id2index_dev = None
    obj._promoted = _PromotedCache(65536)
    return obj
