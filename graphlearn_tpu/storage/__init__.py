"""graphlearn_tpu.storage: out-of-core tiered feature storage.

The subsystem that turns "fits in HBM" into "fits on disk" (ROADMAP
item 2; docs/storage.md): a three-tier feature store — HBM hot prefix,
host-RAM warm tier, memory-mapped disk cold tier — plus the epoch
prefetch planner and the chunk-boundary staging pipeline that fuse
disk reads to the scanned epoch's dispatch cadence.

* ``DiskTier`` / ``spill_array`` — the chunked mmap bottom tier.
* ``TieredFeature`` — drop-in for ``data.Feature`` across all three
  tiers (reactive per-batch path + ``cpu_get`` serving).
* ``ChunkStager`` — the bounded staging worker (double-buffered disk ->
  host ring, degrade-to-sync failure semantics).
* ``planner`` — exact per-chunk / per-tier miss sets, computable at the
  epoch prologue from the replayable seed + fold_in PRNG streams.
* ``TieredScanTrainer`` — the scanned epoch over a TieredFeature at the
  unchanged ceil(steps/K)+2 dispatch budget.
* ``TieredDistFeature`` — per-shard disk-backed rows behind the PR 3
  hot-cache / miss-exchange machinery.
* ``TieredDistScanTrainer`` — device oversubscription THROUGH the
  shard exchange: per-shard HBM hot prefixes + chunk-staged exchange
  slabs against the prologue's exact miss-exchange program, bit
  -identical to the all-HBM ``DistScanTrainer`` at the same
  ceil(steps/K)+2 budget.
"""
from . import planner
from .disk import DiskTier, spill_array
from .dist import TieredDistFeature, spill_partitions
from .dist_scan import DistChunkStager, TieredDistScanTrainer
from .scan import TieredScanTrainer, tiered_gather
from .staging import ChunkStager, pad_slab, pow2_slab_cap
from .tiered import TieredFeature

__all__ = [
    'DiskTier', 'spill_array', 'TieredDistFeature', 'spill_partitions',
    'DistChunkStager', 'TieredDistScanTrainer',
    'TieredScanTrainer', 'tiered_gather', 'ChunkStager', 'pad_slab',
    'pow2_slab_cap', 'TieredFeature', 'planner',
]
