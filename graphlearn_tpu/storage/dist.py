"""TieredDistFeature: per-shard out-of-core storage behind the PR 3
hot-cache / miss-exchange machinery.

``DistFeature`` keeps every partition's rows resident in host RAM (the
[P, n_max, F] block it uploads and serves ``cpu_get`` from) — at
products scale that is already ~GBs per host, and at the 100M–1B-node
production scale ROADMAP item 2 names it is impossible. This subclass
keeps only the ROUTING structures resident (the sorted [P, n_max] id
table, the partition book, the replicated hot cache) and moves the row
payload to per-partition memory-mapped disk tiers (storage/disk.py):

* ``cpu_get`` (the server-side remote serving path, cache
  construction) gathers through the mmaps — the OS page cache is the
  warm tier;
* ``device_arrays`` uploads the HBM shard table straight from disk via
  ``jax.make_array_from_callback``: each addressable shard's block is
  read transiently, so peak host RAM during upload is ONE shard's
  block, never the whole table;
* everything else — the one-dispatch cached miss-only exchange, the
  [P, 4] on-device stats, ``publish_stats`` — is inherited unchanged:
  the device-side lookup semantics are bit-identical to DistFeature
  built from the same rows (tests/test_storage.py pins it).

Device oversubscription THROUGH the shard exchange (docs/storage.md):
by default the HBM tier still holds each shard's full partition — the
exchange program must answer arbitrary remote requests in-program. With
``hot_prefix_rows=H`` set, :meth:`dist_scan_tables` uploads only the
first H positions of each partition (plus the small routing
structures), and ``storage.TieredDistScanTrainer`` answers the
remaining positions from per-chunk staged slabs computed by the epoch
prologue's exact miss-exchange program — the
``DistFeature._shard_body(slab=True)`` lookup path.
"""
import os
from typing import Optional

import numpy as np

from ..distributed.dist_feature import INT32_MAX, DistFeature
from .disk import DiskTier, spill_array


class TieredDistFeature(DistFeature):
  """DistFeature whose per-partition row payloads live on disk.

  Args (beyond DistFeature's): ``spill_dir`` — where the per-partition
  tiers are written when ``feat_parts`` carries in-RAM arrays. A part's
  rows may also be given as a ``DiskTier`` directly (ids then must
  already be in sorted-id order, the layout :func:`spill_partitions`
  writes).
  """

  def __init__(self, num_partitions: int, feat_parts, feature_pb,
               mesh=None, dtype=None, spill_dir: Optional[str] = None,
               rows_per_chunk: int = 65536, fmt: str = 'npy',
               hot_prefix_rows: int = 0, **kwargs):
    self._spill_dir = spill_dir
    self._rows_per_chunk = int(rows_per_chunk)
    self._fmt = fmt
    # per-partition HBM hot prefix for the oversubscribed scanned path
    # (storage/dist_scan.py): positions [0, H) of each partition's
    # sorted row table stay device-resident; the rest stage per chunk
    self.hot_prefix_rows = int(hot_prefix_rows)
    self._scan_dev = None
    super().__init__(num_partitions, feat_parts, feature_pb, mesh=mesh,
                     dtype=dtype, **kwargs)

  # ------------------------------------------------------------- storage

  def _init_storage(self, feat_parts, dtype):
    first_rows = feat_parts[0][1]
    f = (first_rows.dim if isinstance(first_rows, DiskTier)
         else np.asarray(first_rows).shape[1])
    dt = np.dtype(dtype) if dtype is not None else (
        first_rows.dtype if isinstance(first_rows, DiskTier)
        else np.asarray(first_rows).dtype)
    p = len(feat_parts)
    n_max = max((ids.shape[0] for ids, _ in feat_parts), default=1)
    self.n_max = int(n_max)
    self._fdim = int(f)
    self.storage_dtype = dt
    self.feat_ids = np.full((p, n_max), INT32_MAX, np.int32)
    self._tiers = []
    for i, (ids, rows) in enumerate(feat_parts):
      ids = np.asarray(ids)
      if isinstance(rows, DiskTier):
        if rows.rows != ids.shape[0]:
          raise ValueError(f'partition {i}: tier holds {rows.rows} '
                           f'rows for {ids.shape[0]} ids')
        if ids.size > 1 and np.any(np.diff(ids) < 0):
          raise ValueError(f'partition {i}: a DiskTier part must carry '
                           'rows in sorted-id order (spill_partitions '
                           'writes that layout)')
        self.feat_ids[i, :ids.shape[0]] = ids
        self._tiers.append(rows)
      else:
        if self._spill_dir is None:
          raise ValueError('TieredDistFeature needs spill_dir=... when '
                           'feat_parts carry in-RAM arrays (rows are '
                           'written as memory-mapped chunk files)')
        order = np.argsort(ids)
        rows = np.asarray(rows)
        if dtype is not None:
          rows = rows.astype(dt)
        self.feat_ids[i, :ids.shape[0]] = ids[order]
        self._tiers.append(spill_array(
            os.path.join(self._spill_dir, f'part_{i:03d}'), rows[order],
            rows_per_chunk=self._rows_per_chunk, fmt=self._fmt))

  def _part_rows(self, p: int) -> int:
    return self._tiers[p].rows

  # -------------------------------------------------------------- access

  def cpu_get(self, ids) -> np.ndarray:
    """Host-side exact gather via the per-partition mmaps — semantics
    identical to DistFeature.cpu_get over the same rows."""
    ids = np.asarray(ids)
    out = np.zeros((ids.shape[0], self.feature_dim), self.storage_dtype)
    for p in range(self.num_partitions):
      m = self.feature_pb[np.clip(ids, 0, None)] == p
      if not m.any():
        continue
      pos = np.searchsorted(self.feat_ids[p], ids[m])
      pos = np.clip(pos, 0, self.feat_ids.shape[1] - 1)
      n_p = self._part_rows(p)
      real = pos < n_p        # pad slots read as zero rows, like the
      vals = np.zeros((int(m.sum()), self.feature_dim),  # base's zero
                      self.storage_dtype)                # padding
      if real.any():
        vals[real] = self._tiers[p].gather(pos[real])
      out[m] = vals
    return out

  def device_arrays(self):
    """Upload the shard table straight from the disk tiers: each
    addressable shard's [n_max, F] block is assembled transiently in
    the make_array_from_callback callback — whole-table host RAM is
    never allocated.

    OVERSUBSCRIBED stores refuse this path: with ``hot_prefix_rows``
    set, the operator declared that a shard's full partition does NOT
    fit in HBM — uploading the full [P, n_max, F] table anyway (which
    is what every per-step consumer of device_arrays does) would
    silently defeat the oversubscription, or OOM on a real topology.
    The scanned path (``storage.TieredDistScanTrainer`` over
    ``dist_scan_tables()``) is the supported consumer; the loud error
    here is ROADMAP 2b's per-step scope gap made explicit."""
    if self.hot_prefix_rows > 0:
      raise RuntimeError(
          f'TieredDistFeature(hot_prefix_rows={self.hot_prefix_rows}) '
          'is OVERSUBSCRIBED: device_arrays() would upload the full '
          f'[{self.num_partitions}, {self.n_max}, {self.feature_dim}] '
          'partition table to HBM, silently defeating the declared '
          'oversubscription (or OOMing at real scale). The per-step '
          'distributed loader path has no slab-staging story — drive '
          'this store through storage.TieredDistScanTrainer (the '
          'scanned exchange over dist_scan_tables(), docs/storage.md '
          "'Device oversubscription through the shard exchange'), or "
          'construct it with hot_prefix_rows=0 to accept the full '
          'upload')
    if self._dev is None:
      import jax
      from jax.sharding import NamedSharding, PartitionSpec as P

      from ..utils import global_device_put
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      repl = NamedSharding(self.mesh, P())
      h = self.cache_rows
      cache_ids = (self.cache_ids if h else
                   np.full((1,), INT32_MAX, np.int32))
      cache_feats = (self.cache_feats if h else
                     np.zeros((1, self.feature_dim), self.storage_dtype))
      shape = (self.num_partitions, self.n_max, self.feature_dim)

      def part_block(p: int) -> np.ndarray:
        block = np.zeros((self.n_max, self.feature_dim),
                         self.storage_dtype)
        n_p = self._part_rows(p)
        if n_p:
          block[:n_p] = self._tiers[p].gather(np.arange(n_p))
        return block

      def cb(index):
        ps = range(*index[0].indices(self.num_partitions))
        block = np.stack([part_block(p) for p in ps]) if ps else \
            np.zeros((0,) + shape[1:], self.storage_dtype)
        return block[(slice(None),) + tuple(index[1:])]

      self._dev = dict(
          feat_ids=global_device_put(self.feat_ids, shard),
          feats=jax.make_array_from_callback(shape, shard, cb),
          feature_pb=global_device_put(self.feature_pb.astype(np.int32),
                                       repl),
          cache_ids=global_device_put(cache_ids, repl),
          cache_feats=global_device_put(cache_feats, repl))
    return self._dev

  def gather_positions(self, p: int, positions: np.ndarray) -> np.ndarray:
    """Partition-``p`` rows by POSITION in its sorted row table (the
    staging pipeline's read path — positions are what the miss-exchange
    program stages and what ``_shard_body(slab=True)`` resolves)."""
    return self._tiers[p].gather(np.asarray(positions, np.int64))

  def dist_scan_tables(self):
    """Device arrays for the OVERSUBSCRIBED scanned exchange
    (storage.TieredDistScanTrainer): the [P, H, F] hot-prefix blocks —
    positions [0, H) of each partition, bit-identical to the full
    upload's leading rows — plus the small routing structures
    (sorted id table, partition book, replicated hot cache). The full
    [P, n_max, F] table is NEVER uploaded on this path; the remaining
    positions arrive per chunk as staged slabs."""
    if self._scan_dev is None:
      import jax
      from jax.sharding import NamedSharding, PartitionSpec as P

      from ..utils import global_device_put
      h = self.hot_prefix_rows
      if h < 1:
        raise ValueError(
            'dist_scan_tables needs hot_prefix_rows >= 1 (the scanned '
            'chunk program clamps pad positions into the hot prefix) — '
            'pass hot_prefix_rows=... to TieredDistFeature')
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      repl = NamedSharding(self.mesh, P())
      c = self.cache_rows
      cache_ids = (self.cache_ids if c else
                   np.full((1,), INT32_MAX, np.int32))
      cache_feats = (self.cache_feats if c else
                     np.zeros((1, self.feature_dim), self.storage_dtype))
      hot = np.zeros((self.num_partitions, h, self.feature_dim),
                     self.storage_dtype)
      for p in range(self.num_partitions):
        n_p = min(h, self._part_rows(p))
        if n_p:
          hot[p, :n_p] = self._tiers[p].gather(np.arange(n_p))
      self._scan_dev = dict(
          feat_ids=global_device_put(self.feat_ids, shard),
          hot=global_device_put(hot, shard),
          feature_pb=global_device_put(self.feature_pb.astype(np.int32),
                                       repl),
          cache_ids=global_device_put(cache_ids, repl),
          cache_feats=global_device_put(cache_feats, repl))
    return self._scan_dev

  def tier_bytes(self) -> dict:
    """Resident vs on-disk byte accounting (sizing guidance,
    docs/storage.md)."""
    disk = sum(t.nbytes for t in self._tiers)
    resident = self.feat_ids.nbytes + self.feature_pb.nbytes
    if self.cache_feats is not None:
      resident += self.cache_feats.nbytes + self.cache_ids.nbytes
    return dict(disk_bytes=int(disk), resident_bytes=int(resident))


def spill_partitions(spill_dir: str, feat_parts, rows_per_chunk: int =
                     65536, fmt: str = 'npy'):
  """Write per-partition (ids, rows) blocks as sorted-id disk tiers and
  return ``[(sorted_ids, DiskTier), ...]`` — the layout
  TieredDistFeature consumes directly (and the offline step a
  partitioner can run once so later runs never touch the raw arrays)."""
  out = []
  for i, (ids, rows) in enumerate(feat_parts):
    ids = np.asarray(ids)
    order = np.argsort(ids)
    tier = spill_array(os.path.join(spill_dir, f'part_{i:03d}'),
                       np.asarray(rows)[order],
                       rows_per_chunk=rows_per_chunk, fmt=fmt)
    out.append((ids[order], tier))
  return out
