"""TieredDistFeature: per-shard out-of-core storage behind the PR 3
hot-cache / miss-exchange machinery.

``DistFeature`` keeps every partition's rows resident in host RAM (the
[P, n_max, F] block it uploads and serves ``cpu_get`` from) — at
products scale that is already ~GBs per host, and at the 100M–1B-node
production scale ROADMAP item 2 names it is impossible. This subclass
keeps only the ROUTING structures resident (the sorted [P, n_max] id
table, the partition book, the replicated hot cache) and moves the row
payload to per-partition memory-mapped disk tiers (storage/disk.py):

* ``cpu_get`` (the server-side remote serving path, cache
  construction) gathers through the mmaps — the OS page cache is the
  warm tier;
* ``device_arrays`` uploads the HBM shard table straight from disk via
  ``jax.make_array_from_callback``: each addressable shard's block is
  read transiently, so peak host RAM during upload is ONE shard's
  block, never the whole table;
* everything else — the one-dispatch cached miss-only exchange, the
  [P, 4] on-device stats, ``publish_stats`` — is inherited unchanged:
  the device-side lookup semantics are bit-identical to DistFeature
  built from the same rows (tests/test_storage.py pins it).

Device oversubscription THROUGH the shard exchange (docs/storage.md):
by default the HBM tier still holds each shard's full partition — the
exchange program must answer arbitrary remote requests in-program. With
``hot_prefix_rows=H`` set, :meth:`dist_scan_tables` uploads only the
first H positions of each partition (plus the small routing
structures), and ``storage.TieredDistScanTrainer`` answers the
remaining positions from per-chunk staged slabs computed by the epoch
prologue's exact miss-exchange program — the
``DistFeature._shard_body(slab=True)`` lookup path.

PER-STEP demand paging (PR 16): the per-step loader path
(``DistFeature.get`` on arbitrary [P, b] request blocks) rides the
SAME slab-backed lookup. An oversubscribed store overrides
``_build_fn`` so each ``get`` step routes its own miss set on the host
— ``planner.plan_exchange`` over the step's ids as a one-chunk plan,
the exact searchsorted-position routing the scanned prologue uses —
gathers those positions from the disk tiers into a pow2-padded
[P, cap] slab (``DistChunkStager._gather``'s layout), and dispatches
the ``_shard_body(slab=True)`` program over the hot prefix + slab.
Under the exact per-step plan every requested position >= H is in the
slab, so the returned rows are bit-identical to the all-HBM program
(tests/test_dist_oversub.py pins it). Per-step staging is inherently
synchronous — the request set only exists at step time — i.e. the
demand-paged path IS the ChunkStager degrade-to-sync contract applied
every step: each page counts into ``storage.prefetch_miss`` alongside
the new ``storage.demand_pages`` / ``storage.demand_paged_rows`` /
``storage.demand_page_ms`` series under a ``storage.demand_page`` span
(docs/observability.md).
"""
import os
from typing import Optional

import numpy as np

from ..distributed.dist_feature import INT32_MAX, DistFeature
from .disk import DiskTier, spill_array


class TieredDistFeature(DistFeature):
  """DistFeature whose per-partition row payloads live on disk.

  Args (beyond DistFeature's): ``spill_dir`` — where the per-partition
  tiers are written when ``feat_parts`` carries in-RAM arrays. A part's
  rows may also be given as a ``DiskTier`` directly (ids then must
  already be in sorted-id order, the layout :func:`spill_partitions`
  writes).
  """

  def __init__(self, num_partitions: int, feat_parts, feature_pb,
               mesh=None, dtype=None, spill_dir: Optional[str] = None,
               rows_per_chunk: int = 65536, fmt: str = 'npy',
               hot_prefix_rows: int = 0, **kwargs):
    self._spill_dir = spill_dir
    self._rows_per_chunk = int(rows_per_chunk)
    self._fmt = fmt
    # per-partition HBM hot prefix for the oversubscribed scanned path
    # (storage/dist_scan.py): positions [0, H) of each partition's
    # sorted row table stay device-resident; the rest stage per chunk
    self.hot_prefix_rows = int(hot_prefix_rows)
    self._scan_dev = None
    # demand-paged per-step programs, keyed b -> {slab cap -> jitted fn}
    self._slab_fns = {}
    super().__init__(num_partitions, feat_parts, feature_pb, mesh=mesh,
                     dtype=dtype, **kwargs)

  # ------------------------------------------------------------- storage

  def _init_storage(self, feat_parts, dtype):
    first_rows = feat_parts[0][1]
    f = (first_rows.dim if isinstance(first_rows, DiskTier)
         else np.asarray(first_rows).shape[1])
    dt = np.dtype(dtype) if dtype is not None else (
        first_rows.dtype if isinstance(first_rows, DiskTier)
        else np.asarray(first_rows).dtype)
    p = len(feat_parts)
    n_max = max((ids.shape[0] for ids, _ in feat_parts), default=1)
    self.n_max = int(n_max)
    self._fdim = int(f)
    self.storage_dtype = dt
    self.feat_ids = np.full((p, n_max), INT32_MAX, np.int32)
    self._tiers = []
    for i, (ids, rows) in enumerate(feat_parts):
      ids = np.asarray(ids)
      if isinstance(rows, DiskTier):
        if rows.rows != ids.shape[0]:
          raise ValueError(f'partition {i}: tier holds {rows.rows} '
                           f'rows for {ids.shape[0]} ids')
        if ids.size > 1 and np.any(np.diff(ids) < 0):
          raise ValueError(f'partition {i}: a DiskTier part must carry '
                           'rows in sorted-id order (spill_partitions '
                           'writes that layout)')
        self.feat_ids[i, :ids.shape[0]] = ids
        self._tiers.append(rows)
      else:
        if self._spill_dir is None:
          raise ValueError('TieredDistFeature needs spill_dir=... when '
                           'feat_parts carry in-RAM arrays (rows are '
                           'written as memory-mapped chunk files)')
        order = np.argsort(ids)
        rows = np.asarray(rows)
        if dtype is not None:
          rows = rows.astype(dt)
        self.feat_ids[i, :ids.shape[0]] = ids[order]
        self._tiers.append(spill_array(
            os.path.join(self._spill_dir, f'part_{i:03d}'), rows[order],
            rows_per_chunk=self._rows_per_chunk, fmt=self._fmt))

  def _part_rows(self, p: int) -> int:
    return self._tiers[p].rows

  # -------------------------------------------------------------- access

  def cpu_get(self, ids) -> np.ndarray:
    """Host-side exact gather via the per-partition mmaps — semantics
    identical to DistFeature.cpu_get over the same rows."""
    ids = np.asarray(ids)
    out = np.zeros((ids.shape[0], self.feature_dim), self.storage_dtype)
    for p in range(self.num_partitions):
      m = self.feature_pb[np.clip(ids, 0, None)] == p
      if not m.any():
        continue
      pos = np.searchsorted(self.feat_ids[p], ids[m])
      pos = np.clip(pos, 0, self.feat_ids.shape[1] - 1)
      n_p = self._part_rows(p)
      real = pos < n_p        # pad slots read as zero rows, like the
      vals = np.zeros((int(m.sum()), self.feature_dim),  # base's zero
                      self.storage_dtype)                # padding
      if real.any():
        vals[real] = self._tiers[p].gather(pos[real])
      out[m] = vals
    return out

  def device_arrays(self):
    """Upload the shard table straight from the disk tiers: each
    addressable shard's [n_max, F] block is assembled transiently in
    the make_array_from_callback callback — whole-table host RAM is
    never allocated.

    OVERSUBSCRIBED stores refuse this path: with ``hot_prefix_rows``
    set, the operator declared that a shard's full partition does NOT
    fit in HBM — uploading the full [P, n_max, F] table anyway would
    silently defeat the oversubscription, or OOM on a real topology.
    The store's OWN per-step ``get`` never comes here any more (its
    ``_build_fn`` override demand-pages through ``dist_scan_tables``,
    module docstring); this error now guards only DIRECT external
    consumers of the full table."""
    if self.hot_prefix_rows > 0:
      raise RuntimeError(
          f'TieredDistFeature(hot_prefix_rows={self.hot_prefix_rows}) '
          'is OVERSUBSCRIBED: device_arrays() would upload the full '
          f'[{self.num_partitions}, {self.n_max}, {self.feature_dim}] '
          'partition table to HBM, silently defeating the declared '
          'oversubscription (or OOMing at real scale). Per-step get() '
          'demand-pages automatically (hot prefix + per-step slab, '
          "docs/storage.md 'Demand-paged per-step gather'), and the "
          'scanned path stages per chunk via '
          'storage.TieredDistScanTrainer; a consumer that really needs '
          'the full table must construct the store with '
          'hot_prefix_rows=0 to accept the full upload')
    if self._dev is None:
      import jax
      from jax.sharding import NamedSharding, PartitionSpec as P

      from ..utils import global_device_put
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      repl = NamedSharding(self.mesh, P())
      h = self.cache_rows
      cache_ids = (self.cache_ids if h else
                   np.full((1,), INT32_MAX, np.int32))
      cache_feats = (self.cache_feats if h else
                     np.zeros((1, self.feature_dim), self.storage_dtype))
      shape = (self.num_partitions, self.n_max, self.feature_dim)

      def part_block(p: int) -> np.ndarray:
        block = np.zeros((self.n_max, self.feature_dim),
                         self.storage_dtype)
        n_p = self._part_rows(p)
        if n_p:
          block[:n_p] = self._tiers[p].gather(np.arange(n_p))
        return block

      def cb(index):
        ps = range(*index[0].indices(self.num_partitions))
        block = np.stack([part_block(p) for p in ps]) if ps else \
            np.zeros((0,) + shape[1:], self.storage_dtype)
        return block[(slice(None),) + tuple(index[1:])]

      self._dev = dict(
          feat_ids=global_device_put(self.feat_ids, shard),
          feats=jax.make_array_from_callback(shape, shard, cb),
          feature_pb=global_device_put(self.feature_pb.astype(np.int32),
                                       repl),
          cache_ids=global_device_put(cache_ids, repl),
          cache_feats=global_device_put(cache_feats, repl))
    return self._dev

  def gather_positions(self, p: int, positions: np.ndarray) -> np.ndarray:
    """Partition-``p`` rows by POSITION in its sorted row table (the
    staging pipeline's read path — positions are what the miss-exchange
    program stages and what ``_shard_body(slab=True)`` resolves)."""
    return self._tiers[p].gather(np.asarray(positions, np.int64))

  def dist_scan_tables(self):
    """Device arrays for the OVERSUBSCRIBED scanned exchange
    (storage.TieredDistScanTrainer): the [P, H, F] hot-prefix blocks —
    positions [0, H) of each partition, bit-identical to the full
    upload's leading rows — plus the small routing structures
    (sorted id table, partition book, replicated hot cache). The full
    [P, n_max, F] table is NEVER uploaded on this path; the remaining
    positions arrive per chunk as staged slabs."""
    if self._scan_dev is None:
      import jax
      from jax.sharding import NamedSharding, PartitionSpec as P

      from ..utils import global_device_put
      h = self.hot_prefix_rows
      if h < 1:
        raise ValueError(
            'dist_scan_tables needs hot_prefix_rows >= 1 (the scanned '
            'chunk program clamps pad positions into the hot prefix) — '
            'pass hot_prefix_rows=... to TieredDistFeature')
      shard = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
      repl = NamedSharding(self.mesh, P())
      c = self.cache_rows
      cache_ids = (self.cache_ids if c else
                   np.full((1,), INT32_MAX, np.int32))
      cache_feats = (self.cache_feats if c else
                     np.zeros((1, self.feature_dim), self.storage_dtype))
      hot = np.zeros((self.num_partitions, h, self.feature_dim),
                     self.storage_dtype)
      for p in range(self.num_partitions):
        n_p = min(h, self._part_rows(p))
        if n_p:
          hot[p, :n_p] = self._tiers[p].gather(np.arange(n_p))
      self._scan_dev = dict(
          feat_ids=global_device_put(self.feat_ids, shard),
          hot=global_device_put(hot, shard),
          feature_pb=global_device_put(self.feature_pb.astype(np.int32),
                                       repl),
          cache_ids=global_device_put(cache_ids, repl),
          cache_feats=global_device_put(cache_feats, repl))
    return self._scan_dev

  # ---------------------------------------------- per-step demand paging

  def _demand_slab(self, ids_host: np.ndarray, mask_host: np.ndarray):
    """Host miss routing + tier gather for ONE step's [P, b] request
    block: ``planner.plan_exchange`` over the masked ids as a
    single-chunk plan (the scanned prologue's exact position routing —
    replicated-cache hits drop before routing, owners come from the
    partition book, positions from searchsorted over the sorted id
    table, positions < H are HBM-resident and drop out), then the
    staged positions gather from the disk tiers into the
    ``DistChunkStager._gather`` slab layout. Returns ``(slab_pos
    [P, cap] int32 sorted + INT32_MAX pads, slab_rows [P, cap, F],
    staged_row_count)``."""
    from . import planner
    nparts, n_max = self.num_partitions, self.n_max
    masked = np.where(mask_host, ids_host, -1)
    plan = planner.plan_exchange(
        masked, masked.shape[1], self.feature_pb, self.feat_ids,
        self.hot_prefix_rows, cache_ids=self.cache_ids)
    enc = plan.chunk_rows[0]
    cap = plan.slab_caps()[0]
    owners = enc // n_max
    pos = enc % n_max
    counts = (np.bincount(owners, minlength=nparts) if enc.size
              else np.zeros((nparts,), np.int64))
    slab_pos = np.full((nparts, cap), INT32_MAX, np.int32)
    slab_rows = np.zeros((nparts, cap, self.feature_dim),
                         self.storage_dtype)
    for p in range(nparts):
      kp = int(counts[p])
      if kp:
        m = owners == p
        slab_pos[p, :kp] = pos[m].astype(np.int32)
        slab_rows[p, :kp] = self.gather_positions(p, pos[m])
    return slab_pos, slab_rows, int(enc.shape[0])

  def _build_slab_fn(self, b: int, cap: int):
    """The slab-backed per-step lookup program, keyed (b, cap): the
    base ``_build_fn`` shard_map shape with ``_shard_body(slab=True)``
    as the core — feats is the (hot, slab_pos, slab_rows) pytree
    instead of the full [n, F] partition view."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map
    ax = tuple(self.mesh.axis_names)
    core = self._shard_body(b, slab=True)

    def body(feat_ids, hot, slab_pos, slab_rows, pb, cache_ids,
             cache_feats, stats, ids, mask):
      out, new_stats = core(
          feat_ids[0], (hot[0], slab_pos[0], slab_rows[0]), pb,
          cache_ids, cache_feats, stats[0], ids[0], mask[0])
      return out[None], new_stats[None]

    fn = shard_map(
        body, mesh=self.mesh,
        in_specs=(P(ax), P(ax), P(ax), P(ax), P(), P(), P(), P(ax),
                  P(ax), P(ax)),
        out_specs=(P(ax), P(ax)))
    return jax.jit(fn)

  def _build_fn(self, b: int):
    """Per-step lookup program. All-HBM stores (hot_prefix_rows == 0)
    keep DistFeature's one-dispatch program over the full partition
    table; OVERSUBSCRIBED stores get the demand-paged path (module
    docstring): per-step host miss routing + tier gather into a pow2
    slab, then the ``_shard_body(slab=True)`` program over the hot
    prefix — bit-identical rows, one extra host round trip per step."""
    if self.hot_prefix_rows <= 0:
      return super()._build_fn(b)
    import functools
    return functools.partial(self._demand_run, b)

  def _demand_run(self, b: int, ids, mask):
    """One demand-paged per-step dispatch: host miss routing + tier
    gather into the step's slab, sharded upload, and the (b, cap)
    slab-backed program. Host-side by design — the per-step request
    set only exists at step time, so the page is the explicit host
    round trip the ChunkStager's degrade-to-sync path makes at a chunk
    boundary, taken every step."""
    import time as _time

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .. import metrics
    from ..metrics import spans
    from ..utils import global_device_put
    scan = self.dist_scan_tables()
    # explicit fetch — the strict guards reject implicit transfers only
    ids_host = np.asarray(jax.device_get(ids))
    mask_host = np.asarray(jax.device_get(mask))
    with spans.span('storage.demand_page', b=int(ids_host.shape[1])):
      t0 = _time.perf_counter()
      slab_pos_np, slab_rows_np, staged = self._demand_slab(
          ids_host, mask_host)
      metrics.observe('storage.demand_page_ms',
                      (_time.perf_counter() - t0) * 1e3)
      metrics.inc('storage.demand_pages')
      if staged:
        metrics.inc('storage.demand_paged_rows', staged)
        # every demand page is, definitionally, a prefetch miss: the
        # sync-stage counter keeps the degrade-to-sync accounting
        # comparable across the scanned and per-step paths
        metrics.inc('storage.prefetch_miss', staged)
    sharded = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
    slab_pos = global_device_put(slab_pos_np, sharded)
    slab_rows = global_device_put(slab_rows_np, sharded)
    cap = int(slab_pos_np.shape[1])
    fns = self._slab_fns.setdefault(b, {})
    jfn = fns.get(cap)
    if jfn is None:
      jfn = fns[cap] = self._build_slab_fn(b, cap)
    out, self._stats = jfn(
        scan['feat_ids'], scan['hot'], slab_pos, slab_rows,
        scan['feature_pb'], scan['cache_ids'], scan['cache_feats'],
        self._stats_dev(), ids, mask)
    return out

  def tier_bytes(self) -> dict:
    """Resident vs on-disk byte accounting (sizing guidance,
    docs/storage.md)."""
    disk = sum(t.nbytes for t in self._tiers)
    resident = self.feat_ids.nbytes + self.feature_pb.nbytes
    if self.cache_feats is not None:
      resident += self.cache_feats.nbytes + self.cache_ids.nbytes
    return dict(disk_bytes=int(disk), resident_bytes=int(resident))


def spill_partitions(spill_dir: str, feat_parts, rows_per_chunk: int =
                     65536, fmt: str = 'npy'):
  """Write per-partition (ids, rows) blocks as sorted-id disk tiers and
  return ``[(sorted_ids, DiskTier), ...]`` — the layout
  TieredDistFeature consumes directly (and the offline step a
  partitioner can run once so later runs never touch the raw arrays)."""
  out = []
  for i, (ids, rows) in enumerate(feat_parts):
    ids = np.asarray(ids)
    order = np.argsort(ids)
    tier = spill_array(os.path.join(spill_dir, f'part_{i:03d}'),
                       np.asarray(rows)[order],
                       rows_per_chunk=rows_per_chunk, fmt=fmt)
    out.append((ids[order], tier))
  return out
