"""Frontier-capacity calibration for exact-dedup sampling.

Why: XLA programs have static shapes, so every buffer in an exact-dedup
sample is sized for the WORST case (`caps[i+1] = caps[i] * k`: every
sampled neighbor distinct and never seen before). On real graphs the
deduped frontier runs far below that — products-scale measurement puts
actual unique counts ~5x under the static plan — so the sampler, inducer
and collate all pay ~5x more slots than they use. The reference's CUDA
kernels never pay this (dynamic shapes); calibrated static caps are the
TPU answer.

`estimate_frontier_caps` simulates the sampler's per-hop dedup in plain
numpy (no device work, no jit, no device->host transfers — safe to run
in-process on remote-dispatch runtimes) over a few probe batches and
returns per-hop caps with slack, rounded up for XLA-friendly shapes.
Pass them to ``NeighborSampler(frontier_caps=...)`` /
``NeighborLoader(frontier_caps=...)``. Sampling stays EXACT as long as
no batch overflows a cap; overflow is detectable per batch as
``out.num_sampled_nodes[i+1] > sampler.hop_caps(batch)[i+1]`` (fetch the
counts once per epoch, not per batch).

The simulation mirrors ops.uniform_sample: k draws with replacement for
rows with degree > k, keep-all below (keep-all yields MORE distinct
neighbors, so simulating it matters for an upper bound).
"""
from typing import List, Optional, Sequence

import numpy as np


def _round_up(n: int, m: int) -> int:
  return max(m, ((n + m - 1) // m) * m)


def _sim_expand(indptr, indices, frontier, k, rng):
  """Numpy mirror of ops.uniform_sample over ``frontier``: k draws with
  replacement for rows with degree > k, keep-all below (keep-all yields
  MORE distinct neighbors, so simulating it matters for an upper
  bound). Returns the (non-unique) candidate array."""
  deg = indptr[frontier + 1] - indptr[frontier]
  cand = []
  hi = frontier[deg > k]
  if hi.size:
    off = (rng.random((hi.size, k))
           * (indptr[hi + 1] - indptr[hi])[:, None]).astype(np.int64)
    cand.append(indices[indptr[hi][:, None] + off].ravel())
  lo = frontier[(deg > 0) & (deg <= k)]
  if lo.size:
    dlo = indptr[lo + 1] - indptr[lo]
    j = np.arange(k)[None, :]
    take = j < dlo[:, None]
    idx = indptr[lo][:, None] + np.minimum(j, np.maximum(
        dlo[:, None] - 1, 0))
    cand.append(indices[idx][take])
  if not cand:
    return np.empty((0,), np.int64)
  return np.concatenate(cand)


def estimate_frontier_caps(graph, fanouts: Sequence[int], batch_size: int,
                           input_nodes=None, num_probes: int = 8,
                           slack: float = 1.5, seed: int = 0,
                           multiple: int = 128) -> List[int]:
  """Estimate per-hop post-dedup frontier capacities.

  Args:
    graph: data.Graph (or any object with numpy-convertible
      ``indptr``/``indices``).
    fanouts: the sampler's fanout list.
    batch_size: seed batch capacity. For LINK loaders pass the
      effective seed width (2*batch_size for positives, plus the
      negatives: binary adds 2*num_neg, triplet adds num_neg) — link
      batches seed src+dst(+negatives), not batch_size nodes.
    input_nodes: optional seed pool to draw probe seeds from (defaults
      to all nodes — match the loader's seed distribution when you can).
    num_probes: probe batches to simulate.
    slack: multiplier over the observed per-hop maximum.
    multiple: round each cap up to this multiple (XLA-friendly shapes).

  Returns per-hop caps (len == len(fanouts)) for
  ``NeighborSampler(frontier_caps=...)``.
  """
  # prefer the host-side Topology CSR: Graph.indptr is a DEVICE array in
  # HBM mode, and a device->host fetch would both waste the transfer and
  # degrade remote-dispatch runtimes (PERF.md)
  src = getattr(graph, 'topo', graph)
  indptr = np.asarray(src.indptr)
  indices = np.asarray(src.indices)
  n = indptr.shape[0] - 1
  pool = (np.asarray(input_nodes).reshape(-1)
          if input_nodes is not None else None)
  rng = np.random.default_rng(seed)
  maxima = np.zeros(len(fanouts), np.int64)
  for _ in range(num_probes):
    seeds = (rng.choice(pool, batch_size)
             if pool is not None else rng.integers(0, n, batch_size))
    frontier = np.unique(seeds)
    seen = frontier
    for i, k in enumerate(fanouts):
      cand = _sim_expand(indptr, indices, frontier, k, rng)
      if cand.size == 0:
        break
      uniq = np.unique(cand)
      new = uniq[~np.isin(uniq, seen, assume_unique=True)]
      maxima[i] = max(maxima[i], new.size)
      seen = np.union1d(seen, new)
      frontier = new
      if frontier.size == 0:
        break
  return [_round_up(int(m * slack), multiple) for m in maxima]


def estimate_hetero_frontier_caps(graph, num_neighbors, seed_caps,
                                  edge_dir: str = 'out', input_nodes=None,
                                  num_probes: int = 8, slack: float = 1.5,
                                  seed: int = 0,
                                  multiple: int = 128) -> dict:
  """Per-(hop, edge-type) post-dedup calibration for the typed engine.

  The hetero worst-case plan compounds per hop ACROSS edge types
  (``hetero_capacity_plan``: each hop's frontier is the sum of every
  contributing etype's full ``fcap * k``), so a reference-shaped config
  (batch 5120 x 3 typed hops, examples/igbh/train_rgnn.py defaults)
  statically exceeds the graph itself. Real typed frontiers saturate at
  the type's population long before that — this probe measures them.

  The simulation mirrors ``_hetero_sample_from_nodes`` exactly:
  canonical (sorted) intra-hop edge-type order, sequential per-type
  dedup within a hop (a later etype's candidates dedup against an
  earlier etype's additions), per-type ``seen`` sets across hops.

  Args:
    graph: ``{edge_type: data.Graph}`` (the sampler's hetero dict).
    num_neighbors: per-etype fanout dict or shared list.
    seed_caps: ``{ntype: batch_cap}`` — the loader's seed widths.
    edge_dir: 'out' (CSR by src) or 'in' (CSC by dst), as the dataset.
    input_nodes: optional ``{ntype: seed pool}`` to draw probe seeds
      from (defaults to each type's full id range).
    num_probes / slack / seed / multiple: as estimate_frontier_caps.

  Returns ``{edge_type: [per-hop caps]}`` for
  ``NeighborSampler(frontier_caps=...)`` on a hetero graph — hop h's
  entry clamps the NEW unique nodes etype ``et`` may add to its result
  type at hop h (the engine's ``max_new``).
  """
  etypes = sorted(tuple(et) for et in graph)
  fanouts_of = ((lambda et: list(num_neighbors[et]))
                if isinstance(num_neighbors, dict)
                else (lambda et: list(num_neighbors)))
  num_hops = max(len(fanouts_of(et)) for et in etypes)
  csr = {}
  for et, g in graph.items():
    src = getattr(g, 'topo', g)
    csr[tuple(et)] = (np.asarray(src.indptr), np.asarray(src.indices))
  rng = np.random.default_rng(seed)
  maxima = {et: np.zeros(num_hops, np.int64) for et in etypes}
  for _ in range(num_probes):
    frontier = {}
    seen = {}
    for t, cap in seed_caps.items():
      pool = None if input_nodes is None else input_nodes.get(t)
      n_t = None
      if pool is not None:
        pool = np.asarray(pool).reshape(-1)
        seeds = rng.choice(pool, cap)
      else:
        # seed id range: the src dimension of any etype keyed by t
        for et in etypes:
          key_t = et[0] if edge_dir == 'out' else et[2]
          if key_t == t:
            n_t = csr[et][0].shape[0] - 1
            break
        if n_t is None:
          continue
        seeds = rng.integers(0, n_t, cap)
      frontier[t] = np.unique(seeds)
      seen[t] = frontier[t]
    for hop in range(num_hops):
      parts = {}
      for et in etypes:
        fo = fanouts_of(et)
        if hop >= len(fo) or fo[hop] == 0:
          continue
        key_t = et[0] if edge_dir == 'out' else et[2]
        res_t = et[2] if edge_dir == 'out' else et[0]
        f = frontier.get(key_t)
        if f is None or f.size == 0:
          continue
        indptr, indices = csr[et]
        cand = _sim_expand(indptr, indices, f, fo[hop], rng)
        if cand.size == 0:
          continue
        uniq = np.unique(cand)
        prev = seen.get(res_t)
        new = (uniq if prev is None
               else uniq[~np.isin(uniq, prev, assume_unique=True)])
        maxima[et][hop] = max(maxima[et][hop], new.size)
        seen[res_t] = new if prev is None else np.union1d(prev, new)
        parts.setdefault(res_t, []).append(new)
      frontier = {t: np.concatenate(v) for t, v in parts.items()}
  return {et: [_round_up(int(m * slack), multiple) for m in maxima[et]]
          for et in etypes}


def normalize_hetero_frontier_caps(frontier_caps, known_etypes) -> dict:
  """Validate + normalize dict-form hetero caps to
  ``{tuple(etype): tuple(int|None per hop)}`` — the ONE contract shared
  by the local and distributed samplers (None = no clamp at that hop).
  Raises the shared error messages on list-form caps or unknown edge
  types."""
  if not isinstance(frontier_caps, dict):
    raise ValueError(
        'list-form frontier_caps is homogeneous-only; hetero graphs '
        'take a {edge_type: [per-hop caps]} dict '
        '(calibrate.estimate_hetero_frontier_caps)')
  known = {tuple(et) for et in known_etypes}
  fc = {}
  for et, caps in frontier_caps.items():
    et = tuple(et)
    if et not in known:
      raise ValueError(f'frontier_caps edge type {et!r} is not in '
                       'the graph')
    fc[et] = tuple(None if c is None else int(c) for c in caps)
  return fc


def clamp_etype_cap(etype_caps, et, hop: int, worst: int) -> int:
  """The per-(hop, edge-type) clamp rule shared by
  ``hetero_capacity_plan`` and the distributed ``_hetero_plan`` — ONE
  implementation so the engines' buffer plans can never desynchronize
  from the layout helpers' offsets."""
  if etype_caps is None:
    return worst
  ec = etype_caps.get(et)
  if ec is not None and hop < len(ec) and ec[hop] is not None:
    return min(worst, int(ec[hop]))
  return worst


def link_seed_width(batch_size: int, neg_sampling=None) -> int:
  """EFFECTIVE seed width of one link-loader batch: src + dst positives
  (2*batch_size) plus the negatives the sampler seeds alongside them
  (binary adds both endpoints of each negative, triplet only the dst
  candidate). This is the ``batch_size`` to calibrate frontier caps
  against for link loaders — the loaders compute it themselves
  (``frontier_caps='auto'``), so no caller has to hand-derive it."""
  if neg_sampling is None:
    return 2 * batch_size
  num_neg = neg_sampling.num_negatives(batch_size)
  return 2 * batch_size + \
      (2 * num_neg if neg_sampling.is_binary() else num_neg)


def check_no_overflow(sampler, out, batch_cap: Optional[int] = None):
  """True iff no hop of ``out`` exceeded the sampler's frontier caps
  (host fetch — call at epoch end, not per batch)."""
  caps = sampler.hop_caps(batch_cap or out.batch.shape[0])
  counts = [int(c) for c in out.num_sampled_nodes]
  return all(c <= cap for c, cap in zip(counts[1:], caps[1:]))
