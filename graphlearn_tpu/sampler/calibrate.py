"""Frontier-capacity calibration for exact-dedup sampling.

Why: XLA programs have static shapes, so every buffer in an exact-dedup
sample is sized for the WORST case (`caps[i+1] = caps[i] * k`: every
sampled neighbor distinct and never seen before). On real graphs the
deduped frontier runs far below that — products-scale measurement puts
actual unique counts ~5x under the static plan — so the sampler, inducer
and collate all pay ~5x more slots than they use. The reference's CUDA
kernels never pay this (dynamic shapes); calibrated static caps are the
TPU answer.

`estimate_frontier_caps` simulates the sampler's per-hop dedup in plain
numpy (no device work, no jit, no device->host transfers — safe to run
in-process on remote-dispatch runtimes) over a few probe batches and
returns per-hop caps with slack, rounded up for XLA-friendly shapes.
Pass them to ``NeighborSampler(frontier_caps=...)`` /
``NeighborLoader(frontier_caps=...)``. Sampling stays EXACT as long as
no batch overflows a cap; overflow is detectable per batch as
``out.num_sampled_nodes[i+1] > sampler.hop_caps(batch)[i+1]`` (fetch the
counts once per epoch, not per batch).

The simulation mirrors ops.uniform_sample: k draws with replacement for
rows with degree > k, keep-all below (keep-all yields MORE distinct
neighbors, so simulating it matters for an upper bound).
"""
from typing import List, Optional, Sequence

import numpy as np


def _round_up(n: int, m: int) -> int:
  return max(m, ((n + m - 1) // m) * m)


def estimate_frontier_caps(graph, fanouts: Sequence[int], batch_size: int,
                           input_nodes=None, num_probes: int = 8,
                           slack: float = 1.5, seed: int = 0,
                           multiple: int = 128) -> List[int]:
  """Estimate per-hop post-dedup frontier capacities.

  Args:
    graph: data.Graph (or any object with numpy-convertible
      ``indptr``/``indices``).
    fanouts: the sampler's fanout list.
    batch_size: seed batch capacity. For LINK loaders pass the
      effective seed width (2*batch_size for positives, plus the
      negatives: binary adds 2*num_neg, triplet adds num_neg) — link
      batches seed src+dst(+negatives), not batch_size nodes.
    input_nodes: optional seed pool to draw probe seeds from (defaults
      to all nodes — match the loader's seed distribution when you can).
    num_probes: probe batches to simulate.
    slack: multiplier over the observed per-hop maximum.
    multiple: round each cap up to this multiple (XLA-friendly shapes).

  Returns per-hop caps (len == len(fanouts)) for
  ``NeighborSampler(frontier_caps=...)``.
  """
  # prefer the host-side Topology CSR: Graph.indptr is a DEVICE array in
  # HBM mode, and a device->host fetch would both waste the transfer and
  # degrade remote-dispatch runtimes (PERF.md)
  src = getattr(graph, 'topo', graph)
  indptr = np.asarray(src.indptr)
  indices = np.asarray(src.indices)
  n = indptr.shape[0] - 1
  pool = (np.asarray(input_nodes).reshape(-1)
          if input_nodes is not None else None)
  rng = np.random.default_rng(seed)
  maxima = np.zeros(len(fanouts), np.int64)
  for _ in range(num_probes):
    seeds = (rng.choice(pool, batch_size)
             if pool is not None else rng.integers(0, n, batch_size))
    frontier = np.unique(seeds)
    seen = frontier
    for i, k in enumerate(fanouts):
      deg = indptr[frontier + 1] - indptr[frontier]
      cand = []
      hi = frontier[deg > k]
      if hi.size:
        # k draws with replacement per high-degree row
        off = (rng.random((hi.size, k))
               * (indptr[hi + 1] - indptr[hi])[:, None]).astype(np.int64)
        cand.append(indices[indptr[hi][:, None] + off].ravel())
      lo = frontier[(deg > 0) & (deg <= k)]
      if lo.size:
        # keep-all rows: every neighbor, via a [rows, k] grid mask
        dlo = indptr[lo + 1] - indptr[lo]
        j = np.arange(k)[None, :]
        take = j < dlo[:, None]
        idx = indptr[lo][:, None] + np.minimum(j, np.maximum(
            dlo[:, None] - 1, 0))
        cand.append(indices[idx][take])
      if not cand:
        break
      uniq = np.unique(np.concatenate(cand))
      new = uniq[~np.isin(uniq, seen, assume_unique=True)]
      maxima[i] = max(maxima[i], new.size)
      seen = np.union1d(seen, new)
      frontier = new
      if frontier.size == 0:
        break
  return [_round_up(int(m * slack), multiple) for m in maxima]


def link_seed_width(batch_size: int, neg_sampling=None) -> int:
  """EFFECTIVE seed width of one link-loader batch: src + dst positives
  (2*batch_size) plus the negatives the sampler seeds alongside them
  (binary adds both endpoints of each negative, triplet only the dst
  candidate). This is the ``batch_size`` to calibrate frontier caps
  against for link loaders — the loaders compute it themselves
  (``frontier_caps='auto'``), so no caller has to hand-derive it."""
  if neg_sampling is None:
    return 2 * batch_size
  num_neg = neg_sampling.num_negatives(batch_size)
  return 2 * batch_size + \
      (2 * num_neg if neg_sampling.is_binary() else num_neg)


def check_no_overflow(sampler, out, batch_cap: Optional[int] = None):
  """True iff no hop of ``out`` exceeded the sampler's frontier caps
  (host fetch — call at epoch end, not per batch)."""
  caps = sampler.hop_caps(batch_cap or out.batch.shape[0])
  counts = [int(c) for c in out.num_sampled_nodes]
  return all(c <= cap for c, cap in zip(counts[1:], caps[1:]))
