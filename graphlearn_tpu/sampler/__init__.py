from .base import (BaseSampler, EdgeSamplerInput, HeteroSamplerOutput,
                   NegativeSampling, NeighborOutput, NodeSamplerInput,
                   RemoteNodePathSamplerInput, RemoteSamplerInput,
                   SamplerOutput, SamplingConfig, SamplingType)
from .calibrate import (check_no_overflow, estimate_frontier_caps,
                        estimate_hetero_frontier_caps, link_seed_width)
from .capacity import (DEFAULT_ETYPE, DEFAULT_NTYPE, CapacityPlan,
                       CapacityPlanError, ack_edge_ids)
from .negative_sampler import RandomNegativeSampler
from .neighbor_sampler import (NeighborSampler, hetero_tree_blocks,
                               hetero_tree_layout, tree_layout)
