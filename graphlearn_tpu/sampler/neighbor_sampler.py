"""Multi-hop neighbor sampler (homogeneous + heterogeneous).

TPU-native re-design of
/root/reference/graphlearn_torch/python/sampler/neighbor_sampler.py. The
reference drives CUDA kernels hop by hop with exact-size outputs and a D2H
sync per hop (random_sampler.cu:288-300); here the whole multi-hop sample is
ONE jitted function over fixed-shape buffers: per-hop fanout sampling
(ops.neighbor), incremental dedup/relabel (ops.induce), masked outputs.
Capacities are static — hop i's frontier capacity is
``batch_cap * prod(fanouts[:i])`` (optionally clamped by ``node_budget``) —
so XLA compiles once per (batch_cap, fanouts) signature and never again.

Edge-direction convention (matches the reference's transposed emit,
neighbor_sampler.py:168-212): output ``row`` is the *neighbor* (message
source) local index and ``col`` the *seed* (message target) local index, so
``row->col`` is the message-passing direction for PyG-style convs.
"""
import functools
from typing import Dict, List, Optional, Union

import numpy as np

from .. import ops
from ..data import Graph
from ..typing import EdgeType, NodeType, reverse_edge_type
from .base import (BaseSampler, EdgeSamplerInput, HeteroSamplerOutput,
                   NeighborOutput, NodeSamplerInput, SamplerOutput)


def _round_up(n: int, multiple: int = 8) -> int:
  return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def _inducer_for(mode: str, num_graph_nodes: int = 0):
  """(init_seed, init_empty, induce_fn(state, fidx, nbrs, m, offset)) per
  dedup mode — the single source of truth for inducer dispatch across the
  local homo/hetero and distributed engines. ``offset`` (static
  positional slot base / prefix cap) is consumed by 'tree' and the merge
  engine. ``final=True`` marks the last hop induced on a state (lets the
  merge engine skip its sorted-view rebuild)."""
  if mode in ('map', 'sort', 'merge'):
    # exact dedup: all three names run the merge-sort engine — the
    # fastest exact engine on TPU (sorts beat random scatters ~3x,
    # ops/induce_merge.py) and the only one whose memory scales with the
    # batch rather than the graph. The historical engines stay available
    # for parity/bisection: 'map_table' = direct-address [N] table
    # (ops/induce_map.py), 'sort_legacy' = searchsorted engine
    # (ops/induce.py).
    return ops.init_node_merge, ops.init_empty_merge, \
        lambda st, fi, nb, m, off, compact=True, final=False, \
        max_new=None: \
        ops.induce_next_merge(st, fi, nb, m, prefix_cap=off,
                              max_new=max_new, update_view=not final)
  if mode == 'map_table':
    init = functools.partial(ops.init_node_map,
                             num_graph_nodes=num_graph_nodes)

    def _no_empty_map(capacity):
      raise NotImplementedError(
          'map-table lazy (empty) inducer states are not implemented — '
          'the hetero engines use merge/tree modes; add an '
          'ops.init_empty_map before wiring map_table into a typed path')

    return init, _no_empty_map, \
        lambda st, fi, nb, m, off, compact=True, final=False, \
        max_new=None: \
        ops.induce_next_map(st, fi, nb, m, compact_frontier=compact)
  if mode == 'sort_legacy':
    return ops.init_node, ops.init_empty, \
        lambda st, fi, nb, m, off, compact=True, final=False, \
        max_new=None: \
        ops.induce_next(st, fi, nb, m)
  assert mode == 'tree', f'unknown dedup mode {mode!r}'
  return ops.init_node_tree, ops.init_empty_tree, \
      lambda st, fi, nb, m, off, compact=True, final=False, \
      max_new=None: \
      ops.induce_next_tree(st, fi, nb, m, offset=off)


def _final_touch_map(items, edge_dir):
  """{result node type -> index of its LAST induce within a hop's
  (edge_type, caps) items} — used by both hetero engines to pass
  final=True on the last hop so the merge engine skips its sorted-view
  rebuild (only nodes/num_nodes are read afterwards)."""
  last = {}
  for j, (et, _) in enumerate(items):
    last[et[2] if edge_dir == 'out' else et[0]] = j
  return last


def capacity_plan(batch_cap: int, fanouts, node_budget=None,
                  frontier_caps=None):
  """Per-hop frontier capacities [b, c1, ...] with the node_budget and
  per-hop frontier_caps clamps — the shared base of every buffer/offset
  computation below.

  ``frontier_caps[i]`` clamps hop i's post-dedup frontier (and therefore
  every downstream buffer: the next hop's candidate width, the node
  buffer, the collate gather). Worst-case static capacities are the
  single biggest cost of exact-dedup sampling on TPU — real unique
  counts run ~5x below ``caps[i] * k`` on products-like graphs — so
  calibrated caps (sampler.calibrate.estimate_frontier_caps) recover
  most of that factor while staying exact as long as no batch exceeds
  them; overflow is detectable per batch as
  ``num_sampled_nodes[i+1] > caps[i+1]``."""
  caps = [batch_cap]
  for i, k in enumerate(fanouts):
    nxt = caps[-1] * k
    if node_budget is not None:
      nxt = min(nxt, node_budget)
    if frontier_caps is not None and i < len(frontier_caps) and \
        frontier_caps[i] is not None:
      nxt = min(nxt, frontier_caps[i])
    caps.append(nxt)
  return caps


def tree_layout_from_caps(caps, fanouts):
  """(hop_node_offsets, hop_edge_offsets) of the tree-mode positional
  layout for an existing capacity plan."""
  node_offs = [caps[0]]
  edge_offs = []
  total_e = 0
  for i, k in enumerate(fanouts):
    seg = caps[i] * k
    total_e += seg
    edge_offs.append(total_e)
    node_offs.append(node_offs[-1] + seg)
  return tuple(node_offs), tuple(edge_offs)


def merge_layout_from_caps(caps, fanouts):
  """(prefix_offsets, edge_offsets) of the merge-engine layout for a
  capacity plan: ``prefix_offsets[i]`` is the CLAMPED max occupancy
  before hop i (what the inducer needs as ``prefix_cap`` to keep its
  contiguous append statically safe — the clamped-growth invariant),
  with the node capacity as the last entry; edge block i is
  ``caps[i] * k`` wide. The single source of truth for every
  merge-engine consumer (fused/chained/distributed samplers and
  models.train.merge_hop_offsets)."""
  node_offs = [caps[0]]
  edge_offs = []
  tot_e = 0
  for i, k in enumerate(fanouts):
    tot_e += caps[i] * k
    edge_offs.append(tot_e)
    node_offs.append(node_offs[-1] + caps[i + 1])
  return tuple(node_offs), tuple(edge_offs)


def tree_layout(batch_cap: int, fanouts, node_budget=None):
  """(hop_node_offsets, hop_edge_offsets) of the tree-mode positional
  layout — THE source of truth shared by the sampler's buffer plan
  (_homo_capacities/_node_cap/_fused_homo_fn all derive from it) and the
  layered model forward (models.train.tree_hop_offsets)."""
  return tree_layout_from_caps(capacity_plan(batch_cap, fanouts,
                                             node_budget), fanouts)


def _tree_node_cap(caps, fanouts) -> int:
  """Positional layout size: seeds block + one full block per hop."""
  return tree_layout_from_caps(caps, fanouts)[0][-1]


def hetero_capacity_plan(etypes, fanouts_of, seed_caps, edge_dir,
                         etype_caps=None):
  """Static hetero buffer plan shared by the typed engine and the
  hierarchical model layout.

  Returns ``(ntypes, hop_caps, node_caps)``: ``hop_caps[h]`` maps each
  edge type active at hop ``h`` to ``(source-frontier capacity, fanout,
  new-node cap)``; ``node_caps[t]`` is node type ``t``'s total buffer
  size.

  ``etype_caps`` (``{etype: [per-hop caps]}``,
  calibrate.estimate_hetero_frontier_caps) clamps the NEW unique nodes
  each (hop, etype) may contribute — without it the plan compounds
  worst case across etypes every hop (new-node cap == fcap * k) and a
  reference-shaped 3-hop config statically exceeds the graph itself.
  Calibrated plans stay exact while no batch overflows a cap (the typed
  engine raises the on-device overflow flag when one does).
  """
  # CANONICAL intra-hop order: every consumer of this plan — the typed
  # engines' per-hop expansion loops, hetero_tree_layout, and
  # hetero_tree_blocks — derives its (hop, etype) ordering from
  # hop_caps's dict order, so building it SORTED makes the positional
  # layout independent of the caller's etypes ordering (a mismatch
  # between a graph-dict order and a layout call would otherwise
  # silently mis-base intra-hop child blocks)
  etypes = sorted(tuple(et) for et in etypes)
  num_hops = max(len(fanouts_of(et)) for et in etypes)
  ntypes = set()
  for (u, _, v) in etypes:
    ntypes.update((u, v))
  frontier_cap = {t: seed_caps.get(t, 0) for t in ntypes}
  node_caps = dict(frontier_cap)
  hop_caps = []
  for hop in range(num_hops):
    adds = {t: 0 for t in ntypes}
    per_et = {}
    for et in etypes:
      fo = fanouts_of(et)
      if hop >= len(fo):
        continue
      k = fo[hop]
      key_t = et[0] if edge_dir == 'out' else et[2]
      res_t = et[2] if edge_dir == 'out' else et[0]
      fcap = frontier_cap.get(key_t, 0)
      if fcap == 0 or k == 0:
        continue
      from .calibrate import clamp_etype_cap
      cap = clamp_etype_cap(etype_caps, et, hop, fcap * k)
      per_et[et] = (fcap, k, cap)
      adds[res_t] += cap
    hop_caps.append(per_et)
    for t in ntypes:
      frontier_cap[t] = adds[t]
      node_caps[t] += adds[t]
  return ntypes, hop_caps, node_caps


def hetero_tree_layout(seed_caps: Dict[NodeType, int], etypes,
                       num_neighbors, edge_dir: str = 'out',
                       etype_caps=None):
  """(hop_node_offsets, hop_edge_offsets) of the hetero tree-mode
  positional layout — the typed counterpart of ``tree_layout`` consumed
  by the hierarchical (trim-per-layer) hetero model forward.

  ``seed_caps`` must match the engine's seed buffer sizes: for
  single-type seeds that is the loader's ``batch_size`` (its
  ``batch_cap``); multi-type (link) seeds round up to 8.

  Returns ``({ntype: (o_0, ..., o_H)}, {out_etype: (e_1, ..., e_H)})``
  where ``o_h`` is the node-buffer prefix holding every node of depth
  <= h and ``e_h`` the edge-buffer prefix holding hops 1..h; output edge
  types are reversed from the stored etypes when ``edge_dir='out'``
  (the engine emits message-flow orientation).

  ``etype_caps`` (calibrate.estimate_hetero_frontier_caps) gives the
  CALIBRATED layout: node prefixes grow by each (hop, etype)'s clamped
  new-node cap while edge segments keep their ``fcap * k`` emission
  width — matching the clamped typed engine exactly (fcap itself
  shrinks because the previous hop's frontier was clamped).
  """
  etypes = [tuple(et) for et in etypes]
  fanouts_of = ((lambda et: list(num_neighbors[et]))
                if isinstance(num_neighbors, dict)
                else (lambda et: list(num_neighbors)))
  ntypes, hop_caps, _ = hetero_capacity_plan(etypes, fanouts_of,
                                             seed_caps, edge_dir,
                                             etype_caps=etype_caps)
  node_offs = {t: [seed_caps.get(t, 0)] for t in ntypes}
  out_ets = [reverse_edge_type(et) if edge_dir == 'out' else et
             for et in etypes]
  edge_tot = {et: 0 for et in out_ets}
  edge_offs = {et: [] for et in out_ets}
  for per_et in hop_caps:
    adds = {t: 0 for t in ntypes}
    seg = {et: 0 for et in out_ets}
    for et, (fcap, k, cap) in per_et.items():
      res_t = et[2] if edge_dir == 'out' else et[0]
      out_et = reverse_edge_type(et) if edge_dir == 'out' else et
      adds[res_t] += cap          # == fcap * k on unclamped plans
      seg[out_et] += fcap * k     # emission width is never clamped
    for t in ntypes:
      node_offs[t].append(node_offs[t][-1] + adds[t])
    for et in out_ets:
      edge_tot[et] += seg[et]
      edge_offs[et].append(edge_tot[et])
  return ({t: tuple(v) for t, v in node_offs.items()},
          {et: tuple(v) for et, v in edge_offs.items()})


def hetero_tree_blocks(seed_caps: Dict[NodeType, int], etypes,
                       num_neighbors, edge_dir: str = 'out',
                       etype_caps=None):
  """Per-(hop, edge-type) dense-aggregation records for typed tree
  batches — the typed counterpart of the homo dense-run layout
  (models.TreeSAGEConv): within hop ``h``, each edge type's children
  occupy a CONTIGUOUS ``fcap*k`` block of the result type's buffer (the
  engine appends per (hop, etype) in ``hop_caps`` order), their
  targets are the key type's contiguous frontier block, and the edge
  block is the out-etype's hop-``h`` segment. Consumed by
  ``models.TreeHeteroConv``.

  Returns ``(records, node_offs, edge_offs)`` with ``records[h]`` a
  tuple of dicts ``{et, out_et, key_t, res_t, fcap, k, cap, child_base,
  parent_base, edge_base}`` and node_offs/edge_offs the
  hetero_tree_layout offsets (returned so one call serves both the
  records and the hierarchical model layout — paired calls with
  diverging arguments would silently mis-base the layout).

  With ``etype_caps`` (the calibrated merge layout), ``cap`` is the
  clamped new-node cap and ``child_base`` is NOT meaningful — clamped
  merge states pack kept nodes by dynamic valid counts, so the dense
  merge conv gathers children through the edge rows instead of a
  positional slice (models.TreeHeteroConv mode='merge').
  """
  etypes = [tuple(et) for et in etypes]
  fanouts_of = ((lambda et: list(num_neighbors[et]))
                if isinstance(num_neighbors, dict)
                else (lambda et: list(num_neighbors)))
  ntypes, hop_caps, _ = hetero_capacity_plan(etypes, fanouts_of,
                                             seed_caps, edge_dir,
                                             etype_caps=etype_caps)
  node_offs, edge_offs = hetero_tree_layout(seed_caps, etypes,
                                            num_neighbors, edge_dir,
                                            etype_caps=etype_caps)
  records = []
  for h, per_et in enumerate(hop_caps):
    recs = []
    child_off = {t: node_offs[t][h] for t in ntypes}   # hop-h block start
    for et, (fcap, k, cap) in per_et.items():
      key_t = et[0] if edge_dir == 'out' else et[2]
      res_t = et[2] if edge_dir == 'out' else et[0]
      out_et = reverse_edge_type(et) if edge_dir == 'out' else et
      recs.append(dict(
          et=et, out_et=out_et, key_t=key_t, res_t=res_t, fcap=fcap,
          k=k, cap=cap, child_base=child_off[res_t],
          parent_base=0 if h == 0 else node_offs[key_t][h - 1],
          edge_base=(0 if h == 0 else edge_offs[out_et][h - 1])))
      child_off[res_t] += cap
    records.append(tuple(recs))
  return tuple(records), node_offs, edge_offs


@functools.lru_cache(maxsize=None)
def _fused_homo_fn(fanouts, caps, node_cap, with_edge, weighted, mode,
                   num_graph_nodes, padded=False, block_num_edges=0,
                   fused_hop=False, fused_hop_window=512):
  """Jitted whole-multi-hop sample program, cached at MODULE level on its
  static signature: every sampler instance with the same config (e.g. the
  train and eval loaders of one run) shares one traced/compiled
  executable instead of paying the ~60s XLA compile per instance.

  All device arrays enter as ARGUMENTS, never closure constants — an
  executable with captured constants pays a flat ~5ms per call on
  remote-dispatch runtimes (PERF.md).
  """
  import jax

  init_fn, _, induce_fn = _inducer_for(mode, num_graph_nodes)

  def fn(indptr, indices, eids, cum, tab, deg, eptab, seeds, seed_mask,
         key):
    import jax.numpy as jnp
    batch_cap = seeds.shape[0]
    state, uniq, umask, inv = init_fn(seeds, seed_mask, capacity=node_cap)
    frontier, fidx, fmask = uniq, jnp.arange(batch_cap, dtype=jnp.int32), \
        umask
    rows, cols, edges, emasks = [], [], [], []
    nodes_per_hop = [state.num_nodes]
    edges_per_hop = []
    # on-device truncation flag: True iff ANY clamped hop produced more
    # new uniques than its cap kept (the merge engine reports the RAW
    # count). Constant False on unclamped plans — XLA folds it away.
    overflow = jnp.zeros((), bool)
    keys = jax.random.split(key, len(fanouts))
    if mode == 'tree':
      node_offs, _ = tree_layout_from_caps(caps, fanouts)
    else:
      # merge engine: prefix = CLAMPED occupancy bound before hop i —
      # smaller sorts under calibrated plans, and keeps the contiguous
      # node append statically safe
      node_offs, _ = merge_layout_from_caps(caps, fanouts)
    # fused LEVEL routing: under the merge engine the whole level —
    # sample + gather + exact dedup — runs as ONE kernel pass
    # (ops.sample_level_fused, the dedup map resolved in-kernel); tree
    # mode keeps the hop kernel + its positional inducer (the tree
    # layout needs no cross-hop dedup, so there is no map to fuse)
    fused_level = fused_hop and mode == 'merge'
    for i, k in enumerate(fanouts):
      if fused_level:
        state, out, epos, m = ops.sample_level_fused(
            indptr, indices, tab, frontier, fmask, k, keys[i], state,
            fidx, meta=deg, prefix_cap=node_offs[i], max_new=caps[i + 1],
            final=(i + 1 == len(fanouts)), window=fused_hop_window,
            interpret=(fused_hop == 'interpret'))
      else:
        if padded:
          nbrs, epos, m = ops.uniform_sample_padded(
              tab, deg, frontier, fmask, k, keys[i], epos_table=eptab)
        elif block_num_edges:
          # deg is the metadata row gather; tab = (csr_meta,
          # indices_blocks)
          nbrs, epos, m = ops.uniform_sample_block(
              deg, tab, block_num_edges, frontier, fmask, k, keys[i])
        elif weighted:
          nbrs, epos, m = ops.weighted_sample(indptr, indices, cum,
                                              frontier, fmask, k, keys[i])
        elif fused_hop:
          # fused sample+gather Pallas hop (ops/sample_fused.py): same
          # fold_in stream as uniform_sample bit for bit — tab carries
          # the [E/128, 128] aligned indices view, deg the csr_meta row
          # table. Off-TPU the op routes its own XLA fallback, so the
          # flag is safe to leave on in CPU tests ('interpret' forces
          # the kernel through the Pallas interpreter for parity
          # coverage).
          nbrs, epos, m = ops.sample_hop_fused(
              indptr, indices, tab, frontier, fmask, k, keys[i], meta=deg,
              window=fused_hop_window,
              interpret=(fused_hop == 'interpret'))
        else:
          # deg slot carries the [N, 2] csr_meta row table for plain
          # uniform sampling (see _fused_args / ops.uniform_sample)
          nbrs, epos, m = ops.uniform_sample(indptr, indices, frontier,
                                             fmask, k, keys[i], meta=deg)
        # the frontier feeds the next hop at caps[i+1] width; when
        # nothing truncates it (no node_budget clamp) the map inducer can
        # emit it positionally and skip two S-element compaction scatters
        compact = (i + 1 < len(caps)) and caps[i + 1] < caps[i] * k
        state, out = induce_fn(state, fidx, nbrs, m, node_offs[i],
                               compact, final=(i + 1 == len(fanouts)),
                               max_new=caps[i + 1])
      # message direction: neighbor -> seed
      rows.append(out['cols'])
      cols.append(out['rows'])
      emasks.append(out['edge_mask'])
      if with_edge:
        flat_epos = epos.reshape(-1)
        e = (eids[flat_epos] if eids is not None else flat_epos)
        edges.append(jnp.where(out['edge_mask'], e, -1))
      nodes_per_hop.append(out['num_new'])
      edges_per_hop.append(out['edge_mask'].sum())
      if mode == 'merge' and caps[i + 1] < caps[i] * k:
        overflow = overflow | (out['num_new'] > caps[i + 1])
      nxt = caps[i + 1]
      frontier = out['frontier'][:nxt]
      fidx = out['frontier_idx'][:nxt]
      fmask = out['frontier_mask'][:nxt]
    return dict(
        node=state.nodes, num_nodes=state.num_nodes,
        row=jnp.concatenate(rows), col=jnp.concatenate(cols),
        edge=jnp.concatenate(edges) if with_edge else None,
        edge_mask=jnp.concatenate(emasks),
        num_sampled_nodes=nodes_per_hop, num_sampled_edges=edges_per_hop,
        seed_inverse=inv, overflow=overflow)

  # distinguishable per-mode trace name (bench.py keys device-trace
  # events by the jitted program name); '_capped' marks a clamped
  # (budget/frontier_caps) capacity plan
  full = True
  for i, k in enumerate(fanouts):
    full = full and caps[i + 1] == caps[i] * k
  fn.__name__ = f'sample_{mode}' + ('_padded' if padded else '') + \
      ('_block' if block_num_edges else '') + \
      ('_fusedhop' if fused_hop else '') + \
      ('' if full else '_capped')
  fn.__qualname__ = fn.__name__
  return jax.jit(fn)


class NeighborSampler(BaseSampler):
  """Fanout neighbor sampling over device-resident CSR
  (reference: sampler/neighbor_sampler.py:37-674).

  Args:
    graph: `Graph` or Dict[EdgeType, Graph] (hetero).
    num_neighbors: per-hop fanouts, list or Dict[EdgeType, list].
    device: jax device for sampling.
    with_edge: also emit global edge ids per sampled edge.
    with_weight: weighted (edge-weight-biased) sampling.
    strategy: 'random' (uniform) — 'weighted' selected via with_weight.
    edge_dir: 'out' (CSR: neighbors = out-edges) or 'in' (CSC).
    seed: PRNG seed.
    node_budget: optional clamp on any hop's frontier capacity (controls
      the worst-case padded size). Under the exact-dedup merge engine,
      overflow new nodes are truncated cleanly: not stored, not
      expanded, and edges targeting them are masked out (the legacy
      engines kept them half-alive past capacity).
    frontier_caps: per-hop post-dedup frontier capacity clamps — the
      calibrated-capacity mechanism (capacity_plan /
      sampler.calibrate.estimate_frontier_caps). Homogeneous only.
  """

  def __init__(self, graph: Union[Graph, Dict[EdgeType, Graph]],
               num_neighbors=None, device=None, with_edge: bool = False,
               with_weight: bool = False, strategy: str = 'random',
               edge_dir: str = 'out', seed: Optional[int] = None,
               node_budget: Optional[int] = None, fused: bool = True,
               dedup: str = 'auto',
               padded_window: Optional[int] = None,
               frontier_caps=None, use_fused_hop=False,
               fused_hop_window: int = 512):
    import jax
    self.graph = graph
    self.num_neighbors = num_neighbors
    self.device = device
    self.with_edge = with_edge
    self.with_weight = with_weight
    self.strategy = strategy
    self.edge_dir = edge_dir
    self.node_budget = node_budget
    # frontier_caps: calibrated capacity clamps — per-hop post-dedup
    # frontier caps on homo graphs (list), per-(hop, edge-type) new-node
    # caps on hetero graphs (dict, calibrate.estimate_hetero_frontier_
    # caps). Exact while no batch overflows them; every result carries
    # an on-device metadata['overflow'] flag (see capacity_plan /
    # hetero_capacity_plan / sampler.calibrate).
    if frontier_caps is not None and dedup in ('tree', 'none'):
      # tree frontiers are un-deduped (positional, ~fanout-product
      # wide): clamping them with POST-dedup calibrated caps would
      # silently truncate most samples. Budget-style truncation on tree
      # batches is node_budget's job.
      raise ValueError('frontier_caps requires an exact-dedup mode '
                       "(map/sort/merge); use node_budget with "
                       "dedup='tree'")
    if frontier_caps is not None and dedup in ('map_table',
                                               'sort_legacy'):
      # the legacy engines have no clean-truncation contract and no
      # overflow flag — clamping them would reintroduce exactly the
      # silent bias the merge engine's guard exists to prevent
      raise ValueError(f'frontier_caps is not supported with the legacy '
                       f'{dedup!r} engine (no overflow detection); use '
                       "dedup='merge'")
    if frontier_caps is None:
      self.frontier_caps = None
    elif isinstance(graph, dict):
      from .calibrate import normalize_hetero_frontier_caps
      self.frontier_caps = normalize_hetero_frontier_caps(
          frontier_caps, graph)
    else:
      if isinstance(frontier_caps, dict):
        raise ValueError('dict-form frontier_caps is hetero-only; pass '
                         'a per-hop list on homogeneous graphs')
      self.frontier_caps = tuple(frontier_caps)
    # fused=True (default) compiles the whole multi-hop sample into one
    # XLA program — one dispatch per batch, and in-program op fusion. The
    # chained path (fused=False) dispatches each per-op kernel from the
    # host; it exists for debugging/bisection. (An earlier version
    # defaulted to chained because the fused program was slow through the
    # remote-dispatch runtime; that was the closure-captured-constant
    # penalty, since fixed — see _build_homo_fn.)
    self.fused = fused
    # dedup strategy: 'map' = direct-address table over node ids (no
    # sorts; 4 bytes/node HBM — the TPU hash-table analog), 'sort' =
    # sort-based masked unique (memory scales with the batch, not the
    # graph). 'auto' picks map below 64M nodes (256MB table).
    self.dedup = dedup
    # padded_window: sample hops from a dense pre-shuffled [N, W]
    # adjacency table instead of the CSR — one ROW gather per hop rather
    # than per-edge ELEMENT gathers (~5x faster on TPU, PERF.md). Rows
    # with degree > W sample from a uniformly random W-subset (the
    # loaders reseed the table each epoch to de-bias the truncation;
    # ops.padded_table_stats quantifies the recall). 'auto' picks the
    # fastest sufficient window, dodging the measured W=32 cliff
    # (ops.choose_padded_window). Homo + uniform only.
    fo = (list(num_neighbors)
          if num_neighbors is not None and
          not isinstance(num_neighbors, dict) else [])
    if padded_window == 'auto':
      if not fo:
        raise ValueError("padded_window='auto' needs a fanout list")
      padded_window = ops.choose_padded_window(fo)
    self.padded_window = padded_window
    # strategy='block': cluster sampling over aligned 16-wide CSR blocks
    # (row-gather speed on the raw CSR, exact uniform marginals,
    # correlated within a row per hop — ops.uniform_sample_block)
    if strategy == 'block':
      if with_weight:
        raise ValueError('block sampling does not support weights')
      if not fused and not isinstance(graph, dict):
        raise ValueError('block sampling requires the fused path')
      if padded_window is not None:
        raise ValueError("strategy='block' and padded_window are "
                         'mutually exclusive sampling backends')
      if fo and max(fo) > ops.BLOCK:
        raise ValueError(f'block sampling caps fanouts at {ops.BLOCK}')
    if padded_window is not None:
      if with_weight:
        raise ValueError('padded_window does not support weighted '
                         'sampling')
      if not fused:
        raise ValueError('padded_window requires the fused path')
      if isinstance(graph, dict):
        raise ValueError('padded_window is homogeneous-only (the typed '
                         'engine samples the CSR directly)')
      if fo and padded_window < max(fo):
        raise ValueError(
            f'padded_window={padded_window} < max fanout {max(fo)}: '
            'rows with degree > window would silently under-sample '
            '(the table caps per-row candidates at the window)')
    # use_fused_hop: route uniform CSR hops through the fused
    # sample+gather Pallas kernel (ops.sample_hop_fused — one staged
    # segment DMA per seed instead of k element gathers). MEASURED-WIN
    # flag, default False (the repo's evidence-gated routing pattern,
    # like UnifiedTensor.use_pallas): the XLA path is bit-identical —
    # same counter-addressed fold_in stream — so flipping it never
    # changes samples. 'interpret' runs the kernel through the Pallas
    # interpreter (CPU parity tests). fused_hop_window is the staged
    # segment span per seed (multiple of 128; deg > window seeds take
    # the per-sample row-DMA path inside the kernel).
    if use_fused_hop:
      if isinstance(graph, dict):
        raise ValueError('use_fused_hop is homogeneous-only (the typed '
                         'engine samples per etype; fuse there once the '
                         'homo kernel has a measured win)')
      if with_weight:
        raise ValueError('use_fused_hop supports uniform sampling only '
                         '(the weighted CDF bisection has no fused '
                         'kernel)')
      if padded_window is not None or strategy == 'block':
        raise ValueError('use_fused_hop replaces the CSR hop itself — '
                         'padded_window/block are alternative sampling '
                         'backends, pick one')
      if not fused:
        raise ValueError('use_fused_hop requires the fused '
                         'multi-hop program (fused=True)')
      if fused_hop_window % 128 != 0 or fused_hop_window <= 0:
        raise ValueError('fused_hop_window must be a positive multiple '
                         'of 128 (aligned row DMAs)')
    self.use_fused_hop = use_fused_hop
    self.fused_hop_window = fused_hop_window
    self._padded_seed = 0 if seed is None else seed
    self._key = jax.random.PRNGKey(0 if seed is None else seed)
    self._call_count = 0    # host-side PRNG stream position
    self._row_cumsum = {}   # per-graph CDF cache for weighted sampling
    self._fns = {}          # compiled fn cache keyed by static signature
    self._garrs = {}        # per-graph device arrays (id -> dict)

  @property
  def is_hetero(self) -> bool:
    return isinstance(self.graph, dict)

  def _next_key(self):
    """Per-call key via fold_in of a HOST counter: unlike split-and-carry,
    consecutive batches share no device-side dependency, so their sampling
    programs pipeline freely (important under remote-dispatch runtimes
    where dependent dispatches serialize)."""
    import jax
    self._call_count += 1
    return jax.random.fold_in(self._key, self._call_count)

  def state_dict(self):
    """fold_in counter + the base key itself. Serializing the key (not
    just the counter) makes restores exact even when the sampler was
    constructed with seed=None (random base key) — a counter-only
    restore would silently replay a different sampling stream."""
    return {'call_count': int(self._call_count),
            'base_key': np.asarray(self._key).tolist()}

  def load_state_dict(self, state):
    import jax.numpy as jnp
    if 'call_count' not in state:
      raise ValueError(
          f'checkpoint sampler state {sorted(state)} was written by a '
          'different sampler type; resuming would diverge')
    self._call_count = int(state['call_count'])
    if 'base_key' in state:
      self._key = jnp.asarray(np.asarray(state['base_key'],
                                         dtype=np.uint32))

  def _get_graph(self, etype: Optional[EdgeType] = None) -> Graph:
    return self.graph[etype] if self.is_hetero else self.graph

  def _cumsum_for(self, etype=None):
    g = self._get_graph(etype)
    if id(g) not in self._row_cumsum:
      if g.edge_weights is None:
        raise ValueError('with_weight=True requires edge_weights')
      self._row_cumsum[id(g)] = ops.build_row_cumsum(g.indptr,
                                                     g.edge_weights)
    return self._row_cumsum[id(g)]

  # ------------------------------------------------------------------ hops

  def _dedup_mode(self) -> str:
    """Resolved engine name ('none' aliases 'tree').

    'map' / 'sort' / 'merge' / 'auto' all run the merge-sort exact-dedup
    engine (ops/induce_merge.py — the fastest exact engine on TPU, and
    memory scales with the batch, not the graph, so it also covers
    billion-node graphs). 'map_table' forces the direct-address [N]
    table (ops/induce_map.py, the literal GPU-hash-table analog),
    'sort_legacy' the searchsorted engine (ops/induce.py) — both kept
    for parity/bisection. 'tree' is the computation-tree relaxation
    (positional relabeling, zero random access — PERF.md).
    """
    if self.dedup in ('tree', 'none'):
      return 'tree'
    if self.dedup in ('map_table', 'sort_legacy'):
      return self.dedup
    if self.dedup in ('map', 'sort', 'merge', 'auto'):
      return 'merge'
    raise ValueError(f'unknown dedup mode {self.dedup!r}')

  def _inducer_fns(self):
    """(init_fn(seeds, mask, capacity), induce_fn(..., offset)) for the
    chained path."""
    return _inducer_for(self._dedup_mode(), self._get_graph().num_nodes)

  def sample_one_hop(self, srcs, src_mask, k: int, key=None,
                     etype: Optional[EdgeType] = None) -> NeighborOutput:
    """One fanout hop; [B] seeds -> dense [B, K] + mask
    (reference: neighbor_sampler.py:128-166)."""
    g = self._get_graph(etype)
    if key is None:
      key = self._next_key()
    if self.with_weight and g.edge_weights is not None:
      nbrs, epos, mask = ops.weighted_sample(
          g.indptr, g.indices, self._cumsum_for(etype), srcs, src_mask, k,
          key)
    elif self.strategy == 'block':
      blocks, meta = self._block_arrays(etype)
      nbrs, epos, mask = ops.uniform_sample_block(
          meta, blocks, int(g.indices.shape[0]), srcs, src_mask, k, key)
    else:
      nbrs, epos, mask = ops.uniform_sample(g.indptr, g.indices, srcs,
                                            src_mask, k, key)
    edges = None
    if self.with_edge:
      import jax.numpy as jnp
      eids = g.edge_ids
      edges = (jnp.where(mask, eids[epos], -1) if eids is not None
               else jnp.where(mask, epos, -1))
    return NeighborOutput(nbrs=nbrs, mask=mask, edges=edges)

  # -------------------------------------------------------------- homo path

  def _homo_capacities(self, batch_cap: int, fanouts) -> List[int]:
    """Frontier capacity per hop (hop 0 = seeds)."""
    return capacity_plan(batch_cap, fanouts, self.node_budget,
                         self.frontier_caps)

  def hop_caps(self, batch_cap: int) -> List[int]:
    """Public view of the resolved per-hop frontier capacities — compare
    ``out.num_sampled_nodes[i+1] > hop_caps[i+1]`` to detect truncation
    under calibrated frontier_caps (fetch once per epoch, not per
    batch)."""
    if self.is_hetero:
      # homo accessor by contract: the typed engine's capacities
      # live in its per-etype CapacityPlan
      # graftlint: allow[hetero-gate] homo accessor by contract
      raise ValueError('hop_caps is homogeneous-only (the typed engine '
                       'plans capacities per edge type)')
    return self._homo_capacities(batch_cap, tuple(self.num_neighbors))

  @property
  def clamped_exact(self) -> bool:
    """True when this sampler runs an exact-dedup engine under
    calibrated frontier_caps — the configuration whose batches can be
    silently truncated on overflow, and therefore the one the loaders'
    overflow_policy machinery guards (every result carries an on-device
    ``metadata['overflow']`` flag)."""
    return self.frontier_caps is not None and \
        self._dedup_mode() == 'merge'

  def uncapped_clone(self) -> 'NeighborSampler':
    """A sampler sharing this one's graph, device arrays and PRNG base
    but with NO frontier_caps — the full-capacity replay target for
    overflow recovery. Compiled programs are NOT shared (capacity plans
    differ) but the module-level program cache dedups the full-caps
    trace across clones."""
    import copy
    clone = copy.copy(self)
    clone.frontier_caps = None
    clone._fns = {}
    return clone

  def _node_cap(self, caps, fanouts) -> int:
    if self._dedup_mode() == 'tree':
      return _tree_node_cap(caps, list(fanouts))
    return sum(caps)

  def _build_homo_fn(self, batch_cap: int, fanouts):
    """Resolve the shared jitted multi-hop program for this config."""
    g = self._get_graph()
    caps = self._homo_capacities(batch_cap, fanouts)
    mode = self._dedup_mode()
    nblk_edges = 0
    if self.strategy == 'block':
      nblk_edges = int(g.indices.shape[0])   # no D2H: shape is metadata
    return _fused_homo_fn(
        tuple(fanouts), tuple(caps), self._node_cap(caps, fanouts),
        self.with_edge,
        self.with_weight and g.edge_weights is not None,
        mode, g.num_nodes if mode == 'map_table' else 0,
        padded=self.padded_window is not None,
        block_num_edges=nblk_edges,
        fused_hop=self.use_fused_hop,
        fused_hop_window=self.fused_hop_window)

  def _padded_arrays(self):
    """Lazily built device-resident padded adjacency (homo).

    EVERY graph mode rebuilds ON DEVICE (one edge-list sort + scatter
    over the already-uploaded CSR, ~0.5 s at products scale): the host
    builder cost ~90-101 s/epoch of numpy lexsort + [N, W] upload under
    the per-epoch reseed at 2.45M nodes (round-4 matrix finding) —
    which would dominate any SCANNED epoch using padded_window (the
    whole epoch is ~ceil(steps/K) dispatches, so a 90 s host prologue
    is the epoch). CPU-mode graphs upload indptr/indices through
    _graph_arrays anyway, so the device path costs no extra transfer;
    ops.build_padded_adjacency (host) remains for direct callers.
    """
    import jax
    g = self._get_graph()
    key = ('padded', id(g))
    if key not in self._garrs:
      ga = self._graph_arrays()
      tab, deg, epos = ops.build_padded_adjacency_device(
          ga['indptr'], ga['indices'], self.padded_window,
          jax.random.PRNGKey(self._padded_seed),
          edge_pos=self.with_edge)
      self._garrs[key] = dict(tab=tab, deg=deg, eptab=epos)
    return self._garrs[key]

  def _block_arrays(self, etype=None):
    """(aligned [E/16, 16] view of the CSR indices, packed [N, 2]
    (start, deg) metadata). Built device-side — a host round-trip here
    would both copy ~E bytes and flip the remote-dispatch runtime into
    its degraded mode (PERF.md)."""
    import jax.numpy as jnp
    g = self._get_graph(etype)
    key = ('blocks', id(g))
    if key not in self._garrs:
      ind = jnp.asarray(g.indices)
      pad = (-int(ind.shape[0])) % ops.BLOCK
      if pad:
        ind = jnp.concatenate([ind, jnp.full((pad,), -1, ind.dtype)])
      ptr = jnp.asarray(g.indptr)
      meta = jnp.stack([ptr[:-1], ptr[1:] - ptr[:-1]],
                       axis=1).astype(jnp.int32)
      self._garrs[key] = (ind.reshape(-1, ops.BLOCK), meta)
    return self._garrs[key]

  def _indices128(self, etype=None):
    """Lazily built FILL-padded [ceil(E/128), 128] aligned view of the
    CSR indices for the fused hop kernel (ops.build_indices128; the
    128-lane cousin of _block_arrays' [E/16, 16] view). min_rows keeps
    the kernel's staged window slice in bounds on tiny graphs."""
    g = self._get_graph(etype)
    key = ('indices128', id(g), self.fused_hop_window)
    if key not in self._garrs:
      from ..ops.sample_fused import LANES
      ga = self._graph_arrays(etype)
      self._garrs[key] = ops.build_indices128(
          ga['indices'], min_rows=self.fused_hop_window // LANES + 1)
    return self._garrs[key]

  def _csr_meta(self, etype=None):
    """Packed [N, 2] (start, degree) row table for uniform sampling —
    one ROW gather replaces two indptr ELEMENT gathers per frontier
    (both ~1 HBM transaction/seed on TPU; see ops.uniform_sample)."""
    import jax.numpy as jnp
    g = self._get_graph(etype)
    key = ('csr_meta', id(g))
    if key not in self._garrs:
      # int32 everywhere: jnp arrays are 32-bit in this stack anyway
      # (x64 disabled), which bounds single-shard graphs at 2^31 edges —
      # beyond that, shard via the distributed engine
      ptr = jnp.asarray(g.indptr)
      self._garrs[key] = jnp.stack([ptr[:-1], ptr[1:] - ptr[:-1]],
                                   axis=1).astype(jnp.int32)
    return self._garrs[key]

  def refresh_padded_table(self, seed: Optional[int] = None):
    """Rebuild the padded adjacency with a fresh shuffle so truncated
    rows (deg > window) sample a NEW random window-subset — call between
    epochs to de-bias the truncation (PERF.md)."""
    if self.padded_window is None:
      return
    self._padded_seed = (self._padded_seed + 1 if seed is None else seed)
    self._garrs.pop(('padded', id(self._get_graph())), None)

  def _fused_args(self):
    """Graph device arrays passed (not captured) into the fused program."""
    import jax.numpy as jnp
    ga = self._graph_arrays()
    weighted = self.with_weight and \
        self._get_graph().edge_weights is not None
    cum = jnp.asarray(self._cumsum_for()) if weighted else None
    if self.padded_window is not None:
      pa = self._padded_arrays()
      return (ga['indptr'], ga['indices'], ga['eids'], cum, pa['tab'],
              pa['deg'], pa['eptab'])
    if self.strategy == 'block':
      blocks, meta = self._block_arrays()
      return (ga['indptr'], ga['indices'], ga['eids'], cum, blocks,
              meta, None)
    if self.use_fused_hop:
      return (ga['indptr'], ga['indices'], ga['eids'], cum,
              self._indices128(), self._csr_meta(), None)
    return (ga['indptr'], ga['indices'], ga['eids'], cum, None,
            None if weighted else self._csr_meta(), None)

  def _homo_fn(self, batch_cap: int, fanouts):
    sig = ('homo', batch_cap, tuple(fanouts), self.with_edge,
           self.with_weight, self.padded_window, self.strategy,
           self.use_fused_hop, self.fused_hop_window)
    if sig not in self._fns:
      from ..metrics import programs
      self._fns[sig] = programs.instrument(
          self._build_homo_fn(batch_cap, tuple(fanouts)), 'sample')
    return self._fns[sig]

  def _graph_arrays(self, etype=None):
    import jax.numpy as jnp
    g = self._get_graph(etype)
    if id(g) not in self._garrs:
      self._garrs[id(g)] = dict(
          indptr=jnp.asarray(g.indptr), indices=jnp.asarray(g.indices),
          eids=(jnp.asarray(g.edge_ids) if g.edge_ids is not None
                else None))
    return self._garrs[id(g)]

  def _run_homo_chain(self, batch_cap: int, fanouts, seeds, seed_mask,
                      key):
    """Same computation as _build_homo_fn but dispatched as the per-op
    jitted kernels (default path; see `fused` note in __init__)."""
    import jax
    import jax.numpy as jnp
    ga = self._graph_arrays()
    indptr, indices, eids = ga['indptr'], ga['indices'], ga['eids']
    weighted = self.with_weight and \
        self._get_graph().edge_weights is not None
    cum = jnp.asarray(self._cumsum_for()) if weighted else None
    caps = self._homo_capacities(batch_cap, fanouts)
    node_cap = self._node_cap(caps, fanouts)
    init_fn, _, induce_fn = self._inducer_fns()
    state, uniq, umask, inv = init_fn(seeds, seed_mask, capacity=node_cap)
    frontier = uniq
    fidx = jnp.arange(batch_cap, dtype=jnp.int32)
    fmask = umask
    rows, cols, edges, emasks = [], [], [], []
    nodes_per_hop = [state.num_nodes]
    edges_per_hop = []
    overflow = jnp.zeros((), bool)   # see _fused_homo_fn
    keys = jax.random.split(key, len(fanouts))
    offset = caps[0]
    for i, k in enumerate(fanouts):
      if weighted:
        nbrs, epos, m = ops.weighted_sample(indptr, indices, cum, frontier,
                                            fmask, k, keys[i])
      else:
        nbrs, epos, m = ops.uniform_sample(indptr, indices, frontier,
                                           fmask, k, keys[i])
      compact = caps[i + 1] < caps[i] * k   # see _fused_homo_fn note
      state, out = induce_fn(state, fidx, nbrs, m, offset, compact,
                             final=(i + 1 == len(fanouts)),
                             max_new=caps[i + 1])
      # tree consumes slot bases (full hop widths); merge consumes the
      # clamped occupancy bound (merge_layout_from_caps)
      offset += (caps[i] * k if self._dedup_mode() == 'tree'
                 else caps[i + 1])
      rows.append(out['cols'])
      cols.append(out['rows'])
      emasks.append(out['edge_mask'])
      if self.with_edge:
        flat_epos = epos.reshape(-1)
        e = (eids[flat_epos] if eids is not None else flat_epos)
        edges.append(jnp.where(out['edge_mask'], e, -1))
      nodes_per_hop.append(out['num_new'])
      edges_per_hop.append(out['edge_mask'].sum())
      if self._dedup_mode() == 'merge' and caps[i + 1] < caps[i] * k:
        overflow = overflow | (out['num_new'] > caps[i + 1])
      nxt = caps[i + 1]
      frontier = out['frontier'][:nxt]
      fidx = out['frontier_idx'][:nxt]
      fmask = out['frontier_mask'][:nxt]
    return dict(
        node=state.nodes, num_nodes=state.num_nodes,
        row=jnp.concatenate(rows), col=jnp.concatenate(cols),
        edge=jnp.concatenate(edges) if self.with_edge else None,
        edge_mask=jnp.concatenate(emasks),
        num_sampled_nodes=nodes_per_hop, num_sampled_edges=edges_per_hop,
        seed_inverse=inv, overflow=overflow)

  def sample_from_nodes(self, inputs: NodeSamplerInput,
                        batch_cap: Optional[int] = None, key=None,
                        **kwargs):
    """Multi-hop sample from seed nodes
    (reference: neighbor_sampler.py:168-299).

    ``key``: explicit per-batch PRNG key (default: the sampler's own
    fold_in stream). Loaders replay a batch at full capacities with the
    SAME key on calibration overflow — the recomputed batch is the
    untruncated version of the identical draw, so exactness needs no
    distributional argument.
    """
    if self.is_hetero:
      if key is not None:
        # hetero paths draw from the sampler's internal stream; silently
        # ignoring an explicit key would let the exact-replay contract
        # degrade unnoticed if hetero calibration lands later
        raise NotImplementedError(
            'explicit key is homogeneous-only; hetero sampling uses the '
            "sampler's internal PRNG stream")
      return self._hetero_sample_from_nodes(inputs, batch_cap)
    import jax.numpy as jnp
    seeds = np.asarray(inputs.node).reshape(-1)
    n = seeds.shape[0]
    cap = batch_cap or _round_up(n)
    padded = np.zeros((cap,), dtype=np.int32)
    padded[:n] = seeds
    mask = np.arange(cap) < n
    fanouts = tuple(self.num_neighbors)
    if key is None:
      key = self._next_key()
    if self.fused:
      from ..utils.trace import record_dispatch
      fn = self._homo_fn(cap, fanouts)
      if self.use_fused_hop:
        # kernel-path observability: batches whose hop program routed
        # through the fused Pallas kernel (len(fanouts) hops per call).
        # Under the merge engine the whole LEVEL fuses (sample + gather
        # + in-kernel dedup, ops.sample_level_fused); other engines fuse
        # the sample+gather hop only.
        from .. import metrics
        if self._dedup_mode() == 'merge':
          metrics.inc('ops.fused_level_calls')
        else:
          metrics.inc('ops.fused_hop_calls')
      record_dispatch('sample')
      res = fn(*self._fused_args(), jnp.asarray(padded), jnp.asarray(mask),
               key)
    else:
      res = self._run_homo_chain(cap, fanouts, jnp.asarray(padded),
                                 jnp.asarray(mask), key)
    return SamplerOutput(
        node=res['node'], num_nodes=res['num_nodes'], row=res['row'],
        col=res['col'], edge=res['edge'], edge_mask=res['edge_mask'],
        batch=jnp.asarray(padded), batch_size=n,
        num_sampled_nodes=res['num_sampled_nodes'],
        num_sampled_edges=res['num_sampled_edges'],
        input_type=inputs.input_type,
        metadata={'seed_inverse': res['seed_inverse'], 'seed_mask': mask,
                  'overflow': res['overflow']})

  # ------------------------------------------------------------ hetero path

  def _etype_fanouts(self, etype: EdgeType) -> List[int]:
    nn = self.num_neighbors
    return list(nn[etype]) if isinstance(nn, dict) else list(nn)

  def _hetero_sample_from_nodes(self, inputs: NodeSamplerInput,
                                batch_cap: Optional[int] = None):
    """Per-etype hop loop with per-node-type inducers
    (reference: neighbor_sampler.py:214-299).

    edge_dir='out': etype (u, r, v) stores u's out-edges (CSR by src);
      sampling expands u-frontier to v neighbors; emitted under
      reverse_edge_type (v, rev_r, u) so row=v (source), col=u (target).
    edge_dir='in': etype stores CSC by dst; expands v-frontier to u
      in-neighbors; emitted under the original etype, row=u, col=v.
    """
    import jax
    import jax.numpy as jnp
    if isinstance(inputs, dict):
      # multi-type seeds (link sampling): {ntype: seed array}
      seeds_dict = {t: np.asarray(v).reshape(-1)
                    for t, v in inputs.items()}
      ntype = next(iter(seeds_dict))
    else:
      ntype = inputs.input_type
      assert ntype is not None, 'hetero sampling requires input_type'
      seeds_dict = {ntype: np.asarray(inputs.node).reshape(-1)}
    caps_in, padded_d, smask_d = {}, {}, {}
    for t, s in seeds_dict.items():
      n_t = s.shape[0]
      c = (batch_cap if batch_cap and len(seeds_dict) == 1
           else _round_up(n_t))
      caps_in[t] = c
      buf = np.zeros((c,), np.int32)
      buf[:n_t] = s
      padded_d[t] = buf
      smask_d[t] = np.arange(c) < n_t
    n = seeds_dict[ntype].shape[0]
    cap = caps_in[ntype]
    padded, smask = padded_d[ntype], smask_d[ntype]

    etypes = list(self.graph.keys())

    # Static per-hop/per-ntype buffer plan — shared with
    # hetero_tree_layout so the hierarchical model forward can never
    # disagree with the engine's positional layout. Calibrated
    # per-(hop, etype) caps (dict-form frontier_caps) clamp the plan;
    # 'clamped' gates the max_new threading + overflow flag below.
    clamped = self.frontier_caps is not None
    ntypes, hop_caps, node_caps = hetero_capacity_plan(
        etypes, self._etype_fanouts, caps_in, self.edge_dir,
        etype_caps=self.frontier_caps if clamped else None)
    num_hops = len(hop_caps)

    states = {}
    frontier = {}
    with_edge = self.with_edge
    rows: Dict[EdgeType, list] = {}
    cols: Dict[EdgeType, list] = {}
    edges: Dict[EdgeType, list] = {}
    emasks: Dict[EdgeType, list] = {}
    nodes_per_hop: Dict[NodeType, list] = {t: [] for t in ntypes}
    edges_per_hop: Dict[EdgeType, list] = {}

    mode = self._dedup_mode()
    if mode == 'map_table':
      raise ValueError("dedup='map_table' is homogeneous-only (no lazy "
                       "empty inducer state); use 'map'/'sort'/'merge' "
                       'or tree for hetero graphs')
    init_seed, init_empty, induce = _inducer_for(mode)
    offsets = {t: caps_in.get(t, 0) for t in ntypes}  # positional layout
    inv_d = {}
    for t in seeds_dict:
      st, uniq, umask, inv_t = init_seed(
          jnp.asarray(padded_d[t]), jnp.asarray(smask_d[t]),
          capacity=node_caps[t])
      states[t] = st
      frontier[t] = (uniq, jnp.arange(caps_in[t], dtype=jnp.int32), umask)
      inv_d[t] = inv_t
    inv = inv_d[ntype]
    for t in ntypes:
      nodes_per_hop[t].append(states[t].num_nodes if t in states
                              else jnp.asarray(0, jnp.int32))

    overflow = jnp.zeros((), bool)
    for hop in range(num_hops):
      new_parts: Dict[NodeType, list] = {t: [] for t in ntypes}
      items = list(hop_caps[hop].items())
      last_touch = (_final_touch_map(items, self.edge_dir)
                    if hop + 1 == num_hops else {})
      for j, (et, (fcap, k, ecap)) in enumerate(items):
        key_t = et[0] if self.edge_dir == 'out' else et[2]
        res_t = et[2] if self.edge_dir == 'out' else et[0]
        out_et = reverse_edge_type(et) if self.edge_dir == 'out' else et
        f, fidx, fmask = frontier[key_t]
        f, fidx, fmask = f[:fcap], fidx[:fcap], fmask[:fcap]
        hop_out = self.sample_one_hop(f, fmask, k, etype=et)
        if res_t not in states:
          states[res_t] = init_empty(node_caps[res_t])
        states[res_t], iout = induce(states[res_t], fidx, hop_out.nbrs,
                                     hop_out.mask, offsets[res_t],
                                     final=last_touch.get(res_t) == j,
                                     max_new=ecap if clamped else None)
        # occupancy bound advances by the CLAMPED contribution (== the
        # full fcap*k width on unclamped plans)
        offsets[res_t] += ecap
        rows.setdefault(out_et, []).append(iout['cols'])
        cols.setdefault(out_et, []).append(iout['rows'])
        emasks.setdefault(out_et, []).append(iout['edge_mask'])
        if with_edge:
          edges.setdefault(out_et, []).append(
              hop_out.edges.reshape(-1) if hop_out.edges is not None
              else jnp.full_like(iout['rows'], -1))
        edges_per_hop.setdefault(out_et, []).append(
            iout['edge_mask'].sum())
        if clamped and ecap < fcap * k:
          overflow = overflow | (iout['num_new'] > ecap)
        new_parts[res_t].append((iout['frontier'][:ecap],
                                 iout['frontier_idx'][:ecap],
                                 iout['frontier_mask'][:ecap]))
      # Merge per-type new frontiers; each part is compact (valid
      # leading, merge engine contract).
      for t in ntypes:
        parts = new_parts[t]
        if not parts:
          frontier[t] = (jnp.zeros((0,), jnp.int32),
                         jnp.zeros((0,), jnp.int32), jnp.zeros((0,), bool))
          nodes_per_hop[t].append(jnp.asarray(0, jnp.int32))
          continue
        fr = jnp.concatenate([p[0] for p in parts])
        fi = jnp.concatenate([p[1] for p in parts])
        fm = jnp.concatenate([p[2] for p in parts])
        if mode == 'merge' and len(parts) > 1:
          # cross-part compaction: each part may end in invalid slots;
          # a stable valid-first sort restores the arithmetic
          # frontier_idx prefix the dense (k-run) hetero aggregation
          # relies on (models.TreeHeteroConv mode='merge' computes run
          # bases as min(tgt - j)). Unconditional for merge batches so
          # merge_dense is safe with or without calibrated caps. Tiny
          # sort (frontier width); the valid fi of consecutive parts
          # are consecutive appends.
          order = jnp.argsort(~fm, stable=True)
          fr, fi, fm = fr[order], fi[order], fm[order]
        frontier[t] = (fr, fi, fm)
        nodes_per_hop[t].append(fm.sum().astype(jnp.int32))

    out = HeteroSamplerOutput(
        node={t: s.nodes for t, s in states.items()},
        num_nodes={t: s.num_nodes for t, s in states.items()},
        row={et: jnp.concatenate(v) for et, v in rows.items()},
        col={et: jnp.concatenate(v) for et, v in cols.items()},
        edge=({et: jnp.concatenate(v) for et, v in edges.items()}
              if with_edge else None),
        edge_mask={et: jnp.concatenate(v) for et, v in emasks.items()},
        batch={t: jnp.asarray(padded_d[t]) for t in seeds_dict},
        batch_size=n,
        num_sampled_nodes=nodes_per_hop, num_sampled_edges=edges_per_hop,
        input_type=ntype,
        metadata={'seed_inverse': inv, 'seed_inverse_dict': inv_d,
                  'seed_mask': smask, 'overflow': overflow})
    return out

  # ------------------------------------------------------------- link path

  def sample_from_edges(self, inputs: EdgeSamplerInput, key=None,
                        **kwargs):
    """Link sampling: negatives + seed union + node sampling + metadata
    (reference: neighbor_sampler.py:301-428).

    ``key``: explicit per-batch PRNG key (split across the negative draw
    and the node expansion); loaders replay overflowed batches at full
    capacities with the same key (see sample_from_nodes).
    """
    import jax
    import jax.numpy as jnp
    if self.is_hetero:
      if key is not None:
        raise NotImplementedError(
            'explicit key is homogeneous-only; hetero sampling uses the '
            "sampler's internal PRNG stream")
      return self._hetero_sample_from_edges(inputs, **kwargs)
    # ONE key split across the negative draw and the node expansion —
    # identical whether the key comes from the caller (overflow replay)
    # or the sampler's own stream, so replayed batches match exactly
    if key is None:
      key = self._next_key()
    kneg, knode = jax.random.split(key)
    rows = np.asarray(inputs.row).reshape(-1)
    cols = np.asarray(inputs.col).reshape(-1)
    b = rows.shape[0]
    neg = inputs.neg_sampling
    g = self._get_graph()

    neg_rows = neg_cols = None
    if neg is not None:
      num_neg = neg.num_negatives(b)
      sorted_idx, _ = self._neg_sorted()
      # num_neg is exact by contract (the label layout below indexes by
      # it), so it cannot be pow2-clamped without changing the drawn
      # negatives; batch shape is held constant by the producers'
      # cyclic padding, and retrace_budget guards ragged ad-hoc callers
      # graftlint: allow[retrace-hazard] num_samples is an exact contract; producer-side padding keeps b constant
      nr, nc, nmask = ops.random_negative_sample(
          g.indptr, sorted_idx, g.num_nodes, g.num_nodes, num_neg,
          kneg, padding=True)
      neg_rows, neg_cols = np.asarray(nr), np.asarray(nc)
      if self.edge_dir == 'in':
        # CSC stores (dst, src); emit user-facing (src, dst) pairs
        # (reference: sampler/negative_sampler.py:21-57 row/col flip).
        neg_rows, neg_cols = neg_cols, neg_rows
      del nmask  # padding=True: all slots filled (non-strict mode)

    if neg is None:
      seeds = np.concatenate([rows, cols])
    elif neg.is_binary():
      seeds = np.concatenate([rows, cols, neg_rows, neg_cols])
    else:  # triplet: negatives are dst candidates only
      seeds = np.concatenate([rows, cols, neg_cols])

    out = self.sample_from_nodes(NodeSamplerInput(seeds), key=knode)
    inv = out.metadata['seed_inverse']  # local idx of each seed position
    inv = jnp.asarray(inv)

    if neg is None:
      md = dict(edge_label_index=jnp.stack([inv[:b], inv[b:2 * b]]),
                edge_label=jnp.asarray(inputs.label) if inputs.label is not
                None else jnp.ones((b,), jnp.int32))
    elif neg.is_binary():
      num_neg = neg_rows.shape[0]
      src = jnp.concatenate([inv[:b], inv[2 * b:2 * b + num_neg]])
      dst = jnp.concatenate([inv[b:2 * b],
                             inv[2 * b + num_neg:2 * b + 2 * num_neg]])
      pos_label = (jnp.asarray(inputs.label) if inputs.label is not None
                   else jnp.ones((b,), jnp.int32))
      label = jnp.concatenate([pos_label, jnp.zeros((num_neg,),
                                                    pos_label.dtype)])
      md = dict(edge_label_index=jnp.stack([src, dst]), edge_label=label)
    else:
      num_neg = neg_cols.shape[0]
      md = dict(src_index=inv[:b], dst_pos_index=inv[b:2 * b],
                dst_neg_index=inv[2 * b:2 * b + num_neg])
    out.metadata.update(md)
    out.batch_size = b
    return out

  def _hetero_sample_from_edges(self, inputs: EdgeSamplerInput,
                                num_dst_nodes: Optional[int] = None,
                                **kwargs):
    """Hetero link sampling (reference: neighbor_sampler.py:301-428 hetero
    branch): typed seed edges (src_t, rel, dst_t); negatives are drawn
    against the seed edge type's CSR; src/dst seed sets go into their
    node-type frontiers and metadata indices reference each type's local
    node buffers."""
    import jax.numpy as jnp
    etype = inputs.input_type
    assert etype is not None, 'hetero link sampling requires input_type'
    src_t, _, dst_t = etype
    rows = np.asarray(inputs.row).reshape(-1)
    cols = np.asarray(inputs.col).reshape(-1)
    b = rows.shape[0]
    neg = inputs.neg_sampling
    g = self._get_graph(etype)
    # id ranges: key-type rows come from indptr length, other side from the
    # neighbor ids present (caller may pass num_dst_nodes for exactness)
    num_key = int(np.asarray(g.indptr).shape[0]) - 1
    num_other = num_dst_nodes or int(np.asarray(g.indices).max()) + 1

    neg_rows = neg_cols = None
    if neg is not None:
      num_neg = neg.num_negatives(b)
      sorted_idx, _ = self._neg_sorted(etype)
      # same contract as the homogeneous branch: num_neg is exact
      # graftlint: allow[retrace-hazard] num_samples is an exact contract; producer-side padding keeps b constant
      nr, nc, _ = ops.random_negative_sample(
          g.indptr, jnp.asarray(sorted_idx), num_key, num_other, num_neg,
          self._next_key(), padding=True)
      neg_rows, neg_cols = np.asarray(nr), np.asarray(nc)
      if self.edge_dir == 'in':
        neg_rows, neg_cols = neg_cols, neg_rows

    # typed seed sets with positional bookkeeping
    if neg is None:
      src_seeds, dst_seeds = rows, cols
    elif neg.is_binary():
      src_seeds = np.concatenate([rows, neg_rows])
      dst_seeds = np.concatenate([cols, neg_cols])
    else:  # triplet: negatives are dst candidates
      src_seeds = rows
      dst_seeds = np.concatenate([cols, neg_cols])

    if src_t == dst_t:
      seeds = {src_t: np.concatenate([src_seeds, dst_seeds])}
      off = src_seeds.shape[0]
    else:
      seeds = {src_t: src_seeds, dst_t: dst_seeds}
      off = 0

    out = self._hetero_sample_from_nodes(seeds)
    inv_d = out.metadata['seed_inverse_dict']
    if src_t == dst_t:
      inv_src = jnp.asarray(inv_d[src_t])[:src_seeds.shape[0]]
      inv_dst = jnp.asarray(inv_d[src_t])[off:off + dst_seeds.shape[0]]
    else:
      inv_src = jnp.asarray(inv_d[src_t])[:src_seeds.shape[0]]
      inv_dst = jnp.asarray(inv_d[dst_t])[:dst_seeds.shape[0]]

    if neg is None:
      md = dict(edge_label_index=jnp.stack([inv_src[:b], inv_dst[:b]]),
                edge_label=(jnp.asarray(inputs.label)
                            if inputs.label is not None
                            else jnp.ones((b,), jnp.int32)))
    elif neg.is_binary():
      num_neg = neg_rows.shape[0]
      src = jnp.concatenate([inv_src[:b], inv_src[b:b + num_neg]])
      dst = jnp.concatenate([inv_dst[:b], inv_dst[b:b + num_neg]])
      pos_label = (jnp.asarray(inputs.label) if inputs.label is not None
                   else jnp.ones((b,), jnp.int32))
      label = jnp.concatenate([pos_label,
                               jnp.zeros((num_neg,), pos_label.dtype)])
      md = dict(edge_label_index=jnp.stack([src, dst]), edge_label=label)
    else:
      num_neg = neg_cols.shape[0]
      md = dict(src_index=inv_src[:b], dst_pos_index=inv_dst[:b],
                dst_neg_index=inv_dst[b:b + num_neg])
    out.metadata.update(md)
    out.input_type = etype
    out.batch_size = b
    return out

  @functools.lru_cache(maxsize=None)
  def _neg_sorted(self, etype=None):
    """Per-(edge type) sorted CSR view for negative membership checks —
    cached: the graph is static across batches, and the mp hetero link
    hot loop would otherwise re-sort the whole CSR every batch."""
    g = self._get_graph(etype)
    return ops.sort_csr_segments(np.asarray(g.indptr), np.asarray(g.indices))

  def __hash__(self):
    return id(self)

  def sample_pyg_v1(self, seeds, batch_cap: Optional[int] = None):
    """PyG-v1 style sampling: (batch_size, n_id, adjs)
    (reference: neighbor_sampler.py:430-454).

    adjs is per-layer [(edge_index [2, cap_e_i], edge_mask, e_id, size)]
    in REVERSE hop order (deepest hop first), the layout SAGE-style models
    consume layer by layer. Arrays stay padded.
    """
    import jax.numpy as jnp
    seeds = np.asarray(seeds).reshape(-1)
    out = self.sample_from_nodes(NodeSamplerInput(seeds),
                                 batch_cap=batch_cap)
    cap = out.batch.shape[0]
    fanouts = list(self.num_neighbors)
    caps = self._homo_capacities(cap, fanouts)
    adjs = []
    offset = 0
    nodes_so_far = caps[0]
    for i, k in enumerate(fanouts):
      seg = caps[i] * k
      ei = jnp.stack([out.row[offset:offset + seg],
                      out.col[offset:offset + seg]])
      em = out.edge_mask[offset:offset + seg]
      eid = (out.edge[offset:offset + seg] if out.edge is not None
             else None)
      nodes_so_far += caps[i + 1]
      size = (nodes_so_far, caps[i])
      adjs.append((ei, em, eid, size))
      offset += seg
    return out.batch_size, out.node, list(reversed(adjs))

  # --------------------------------------------------------------- subgraph

  def subgraph(self, inputs: NodeSamplerInput,
               max_degree: Optional[int] = None, bucketed: bool = False,
               cap_large: Optional[int] = None, **kwargs):
    """k-hop induced subgraph (reference: neighbor_sampler.py:456-480):
    expand seeds by the fanouts, then keep ALL edges among collected nodes.

    The default is EXACT (every row scanned to ``max_degree``, defaulting
    to the graph's global max — lossless but ``[B, max_deg]``-sized, so
    one celebrity vertex inflates every batch). ``bucketed=True`` trades
    bounded loss for memory: most rows scan only the graph's ~p90 degree
    and up to ``cap_large`` high-degree rows (default B//8) scan the max;
    high-degree rows beyond the cap LOSE their out-edges, with the count
    reported in ``metadata['num_dropped_rows']`` — size ``cap_large`` from
    that signal.
    """
    import jax.numpy as jnp
    g = self._get_graph()
    nodes_out = self.sample_from_nodes(inputs)
    node_buf = nodes_out.node
    nmask = jnp.arange(node_buf.shape[0]) < nodes_out.num_nodes
    if bucketed:
      deg_small, dmax = self._degree_buckets()
      cap = cap_large or max(8, node_buf.shape[0] // 8)
      # node_buf is the padded node buffer: its shape is a closed
      # capacity-plan value (pow2-capped upstream), so cap takes one
      # value per compiled configuration — not a fresh-executable mint
      # graftlint: allow[retrace-hazard] node_buf.shape is a closed capacity-plan shape, constant per config
      sub = ops.node_subgraph_bucketed(
          g.indptr, g.indices, node_buf, nmask, deg_small=deg_small,
          cap_large=cap, max_degree=max_degree or dmax)
    else:
      sub = ops.node_subgraph(
          g.indptr, g.indices, node_buf, nmask,
          max_degree=max_degree or int(g.topo.max_degree))
    eids = None
    if self.with_edge:
      e = g.edge_ids
      pos = sub['epos']
      eids = jnp.where(sub['edge_mask'], e[pos] if e is not None else pos,
                       -1)
    # note: subgraph row/col are (src=row, dst=col) in the induced graph;
    # mapping metadata = position of each original seed in `nodes`.
    seeds = jnp.asarray(np.asarray(inputs.node).reshape(-1))
    skeys = jnp.where(jnp.arange(sub['nodes'].shape[0]) < sub['num_nodes'],
                      sub['nodes'], jnp.iinfo(jnp.int32).max)
    pos = jnp.clip(jnp.searchsorted(skeys, seeds), 0, skeys.shape[0] - 1)
    mapping = jnp.where(skeys[pos] == seeds, pos, -1)
    md = {'mapping': mapping}
    if 'num_dropped_rows' in sub:
      md['num_dropped_rows'] = sub['num_dropped_rows']
    return SamplerOutput(
        node=sub['nodes'], num_nodes=sub['num_nodes'], row=sub['rows'],
        col=sub['cols'], edge=eids, edge_mask=sub['edge_mask'],
        batch=seeds, batch_size=int(seeds.shape[0]),
        input_type=inputs.input_type, metadata=md)

  def _degree_buckets(self):
    """(p90 degree rounded up to a multiple of 8, max degree) — the
    static bucket plan for ops.node_subgraph_bucketed."""
    if not hasattr(self, '_deg_buckets'):
      g = self._get_graph()
      deg = np.diff(np.asarray(g.indptr))
      dmax = max(1, int(deg.max())) if deg.size else 1
      p90 = int(np.quantile(deg, 0.9)) if deg.size else 1
      small = min(dmax, max(8, -(-p90 // 8) * 8))
      self._deg_buckets = (small, dmax)
    return self._deg_buckets

  # ----------------------------------------------- pre-sampling probability

  def sample_prob(self, seeds: np.ndarray, num_nodes: Optional[int] = None):
    """Per-node probability of being touched by a multi-hop sample starting
    at ``seeds`` (reference: neighbor_sampler.py:482-609 + CalNbrProbKernel,
    random_sampler.cu:354-372). Used by FrequencyPartitioner.

    TPU form: instead of Monte-Carlo device kernels, one exact dense
    propagation per hop — p_v += sum_{u->v} p_u * min(1, k/deg(u)) — i.e. a
    sparse matvec via segment_sum over the CSR, clipped to [0, 1].
    """
    import jax.numpy as jnp
    g = self._get_graph()
    n = num_nodes or g.num_nodes
    indptr = jnp.asarray(g.indptr)
    indices = jnp.asarray(g.indices)
    deg = (indptr[1:] - indptr[:-1]).astype(jnp.float32)
    edge_src = jnp.asarray(ops_ptr2ind(np.asarray(g.indptr)))
    prob = jnp.zeros((n,), jnp.float32).at[jnp.asarray(seeds)].set(1.0)
    total = prob
    for k in self.num_neighbors:
      rate = jnp.minimum(1.0, k / jnp.maximum(deg, 1.0))
      contrib = (prob * rate)[edge_src]
      nxt = jnp.zeros((n,), jnp.float32).at[indices].add(contrib)
      prob = jnp.clip(nxt, 0.0, 1.0)
      total = jnp.clip(total + prob, 0.0, 1.0)
    return total


def ops_ptr2ind(indptr: np.ndarray) -> np.ndarray:
  return np.repeat(np.arange(indptr.shape[0] - 1), np.diff(indptr))
