"""CapacityPlan: the explicit per-ntype/etype closed-shape artifact
(docs/capacity_plans.md).

Every marquee fast path in this repo is a closed-shape contract — the
scanned trainers compile one executable per chunk length, the block
producers ship frames whose arrays stack, the tiered exchange stages
slabs whose capacities are known at plan time, and tune() fingerprints
the dataset + choices that produced those shapes. Until this module,
that contract lived implicitly in a chain of homogeneous helpers
(``capacity_plan`` -> wire frames -> slab caps -> tune fingerprint),
and every hetero workload fell off it into dispatch-per-batch paths.

``CapacityPlan`` reifies the chain once: per node type and edge type,
the frontier caps per hop, the padded row counts, the wire key set, the
PRNG draw count per batch, and the analytic byte budgets — computed
from sampler config + dataset stats and then CONSUMED (never recomputed
ad hoc) by

* the hetero sampler engines (``hetero_capacity_plan`` is the kernel
  this artifact wraps; homo is the single-ntype degenerate plan),
* ``distributed.block_producer`` (typed multi-ntype block frames for
  ``RemoteScanTrainer``),
* the exchange planner + ``storage.dist_scan`` stagers (per-ntype
  exchange slabs for ``TieredDistScanTrainer``),
* ``tune()`` / ``tune(topology=...)`` (typed dataset fingerprints and
  per-etype fanout candidates).

A consumer that cannot build a plan raises :class:`CapacityPlanError`
naming the missing input and this doc anchor — the graftlint
``hetero-gate`` rule keeps new ``is_hetero``-gated refusals from
growing anywhere else.
"""
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..typing import as_str, reverse_edge_type

#: the degenerate node type homo plans use — one ntype, one implicit
#: etype; every typed consumer treats homo as this single-entry plan
DEFAULT_NTYPE = '_N'
DEFAULT_ETYPE = (DEFAULT_NTYPE, '_E', DEFAULT_NTYPE)

DOC_ANCHOR = 'docs/capacity_plans.md'


class CapacityPlanError(ValueError):
  """A consumer needed a CapacityPlan it could not build.

  Always names the consumer, the missing input, and the doc anchor —
  replacing the bare ``ValueError`` homo-only guards this repo used to
  scatter (storage/dist_scan.py, distributed/block_producer.py).
  """

  def __init__(self, consumer: str, missing: str, hint: str = ''):
    self.consumer = consumer
    self.missing = missing
    msg = (f'{consumer} needs a CapacityPlan but {missing}'
           f'{" — " + hint if hint else ""} (see {DOC_ANCHOR})')
    super().__init__(msg)


def _et_str(et) -> str:
  return as_str(tuple(et))


@dataclass(frozen=True)
class CapacityPlan:
  """Per-ntype/etype closed shapes for one (sampler config, batch cap).

  ``hop_caps[h][et] = (fcap, k, ecap)``: at hop ``h``, edge type ``et``
  expands a source frontier of at most ``fcap`` nodes by fanout ``k``
  into at most ``ecap`` new unique nodes (the calibrated clamp; equals
  ``fcap * k`` unclamped). ``node_caps[t]`` is node type ``t``'s total
  padded row count — the feature-gather width, the per-ntype exchange
  slab request width, and the block frame's ``x.{t}`` leading axis.
  ``edge_caps[oet]`` is OUT-facing edge type ``oet``'s total padded
  edge rows (the ``row.{oet}``/``col.{oet}`` frame width).
  """
  ntypes: Tuple[str, ...]
  etypes: Tuple[Tuple[str, str, str], ...]   # canonical sorted input ets
  edge_dir: str
  seed_caps: Dict[str, int]
  hop_caps: Tuple[Dict[Tuple[str, str, str], Tuple[int, int, int]], ...]
  node_caps: Dict[str, int]
  input_type: Optional[str] = None
  wire_dtype: Optional[str] = None
  metadata: dict = field(default_factory=dict, compare=False)

  # ------------------------------------------------------------ derived

  @property
  def num_hops(self) -> int:
    return len(self.hop_caps)

  @property
  def is_hetero(self) -> bool:
    return self.ntypes != (DEFAULT_NTYPE,)

  @property
  def batch_cap(self) -> int:
    t = self.input_type or DEFAULT_NTYPE
    return int(self.seed_caps.get(t, 0))

  def out_etypes(self) -> List[Tuple[str, str, str]]:
    """OUT-facing edge types in first-touched order — the engines emit
    edge blocks under ``reverse_edge_type(et)`` when edge_dir='out'."""
    out = []
    for per_et in self.hop_caps:
      for et in per_et:
        oet = reverse_edge_type(et) if self.edge_dir == 'out' else et
        if oet not in out:
          out.append(oet)
    return out

  @property
  def edge_caps(self) -> Dict[Tuple[str, str, str], int]:
    """Total padded edge rows per OUT-facing edge type: the engines
    append one ``fcap * k`` block per (hop, etype) touch and
    concatenate, so the frame width is the sum over hops."""
    caps: Dict[Tuple[str, str, str], int] = {}
    for per_et in self.hop_caps:
      for et, (fcap, k, _ecap) in per_et.items():
        oet = reverse_edge_type(et) if self.edge_dir == 'out' else et
        caps[oet] = caps.get(oet, 0) + fcap * k
    return caps

  @property
  def key_draws_per_batch(self) -> int:
    """Host PRNG fold_in draws one batch consumes: the homo engine
    draws one key per batch; the hetero engine draws one per (hop,
    etype) touch. Counter-addressed replay (block producers, failover)
    multiplies batch indices by THIS, so random access lands on the
    same stream positions the sequential per-batch loaders use."""
    if not self.is_hetero:
      return 1
    return sum(len(per_et) for per_et in self.hop_caps)

  def feat_types(self, available=None) -> List[str]:
    """Node types carrying rows (node_caps > 0), intersected with the
    store keys when given — the deterministic per-ntype order every
    consumer (frame keys, slab threading, collate bodies) shares."""
    ts = [t for t in sorted(self.ntypes) if self.node_caps.get(t, 0) > 0]
    if available is not None:
      ts = [t for t in ts if t in available]
    return ts

  # ---------------------------------------------------------- wire view

  def frame_keys(self, feat_types=None) -> List[str]:
    """The closed key set of one block frame under this plan (the
    typed-flat SampleMessage convention, distributed/message.py)."""
    if not self.is_hetero:
      keys = ['node', 'num_nodes', 'row', 'col', 'edge_mask', 'batch',
              'num_sampled_nodes', 'num_sampled_edges', 'x', 'y']
      return keys
    keys = ['#META.hetero', '#META.batch_size', '#META.input_type']
    for t in self.feat_types():
      keys += [f'node.{t}', f'num_nodes.{t}', f'num_sampled_nodes.{t}']
    for oet in self.out_etypes():
      s = _et_str(oet)
      keys += [f'row.{s}', f'col.{s}', f'edge_mask.{s}',
               f'num_sampled_edges.{s}']
    for t in (feat_types if feat_types is not None else self.feat_types()):
      keys.append(f'x.{t}')
    if self.input_type is not None:
      keys += [f'batch.{self.input_type}', f'y.{self.input_type}']
    return keys

  def block_mb_per_chunk(self, k: int, feat_dims: Dict[str, int],
                         edge_id_bytes: int = 4) -> float:
    """Analytic wire size of one K-batch block frame under this plan —
    the typed generalization of ``block_mb_per_chunk`` the topology
    tuner screens candidates with."""
    feat_bytes = 2 if self.wire_dtype in ('bf16', 'bfloat16') else 4
    total = 0
    for t, cap in self.node_caps.items():
      f = feat_dims.get(t, 0)
      total += cap * f * feat_bytes      # x rows
      total += cap * edge_id_bytes      # node ids
    for _oet, ecap in self.edge_caps.items():
      total += ecap * 3 * edge_id_bytes  # row + col + mask
    b = self.batch_cap
    total += b * 2 * edge_id_bytes       # batch ids + labels
    return k * total / 1e6

  def slab_caps_upper(self, hot_prefix_rows: Dict[str, int],
                      chunk_size: int) -> Dict[str, int]:
    """Per-ntype upper bound on a chunk's staged-slab capacity: at most
    ``chunk_size * node_caps[t]`` distinct rows can miss the hot prefix
    in one chunk (the planner pads the actual miss count to pow2 and
    never exceeds this)."""
    out = {}
    for t in self.feat_types():
      h = int(hot_prefix_rows.get(t, 0))
      cap = chunk_size * int(self.node_caps[t])
      out[t] = 0 if h <= 0 else cap
    return out

  # --------------------------------------------------------- tune view

  def fingerprint_payload(self) -> dict:
    """Canonical JSON-able view for tune artifacts: the shapes a tuned
    choice set was measured under. Etype keys are stringified so the
    payload round-trips through JSON unchanged."""
    return {
        'ntypes': list(self.ntypes),
        'etypes': [_et_str(et) for et in self.etypes],
        'edge_dir': self.edge_dir,
        'input_type': self.input_type,
        'seed_caps': {t: int(v) for t, v in sorted(self.seed_caps.items())},
        'node_caps': {t: int(v) for t, v in sorted(self.node_caps.items())},
        'hop_caps': [
            {_et_str(et): [int(x) for x in caps]
             for et, caps in sorted(per_et.items())}
            for per_et in self.hop_caps],
        'key_draws_per_batch': int(self.key_draws_per_batch),
        'wire_dtype': self.wire_dtype,
    }

  # ------------------------------------------------------- constructors

  @classmethod
  def homo(cls, batch_cap: int, fanouts, node_budget=None,
           frontier_caps=None, wire_dtype=None) -> 'CapacityPlan':
    """The single-ntype degenerate plan: the homogeneous
    ``capacity_plan`` chain re-expressed as a one-ntype, one-etype
    CapacityPlan so typed consumers need no homo special case."""
    from .neighbor_sampler import capacity_plan
    caps = capacity_plan(int(batch_cap), tuple(fanouts),
                         node_budget=node_budget,
                         frontier_caps=frontier_caps)
    hop_caps = []
    for i, k in enumerate(fanouts):
      hop_caps.append({DEFAULT_ETYPE: (int(caps[i]), int(k),
                                       int(caps[i + 1]))})
    # merge-style occupancy (clamped contributions accumulate), matching
    # hetero_capacity_plan's node_caps arithmetic exactly
    node_cap = int(sum(caps))
    return cls(ntypes=(DEFAULT_NTYPE,), etypes=(DEFAULT_ETYPE,),
               edge_dir='out', seed_caps={DEFAULT_NTYPE: int(batch_cap)},
               hop_caps=tuple(hop_caps),
               node_caps={DEFAULT_NTYPE: node_cap},
               input_type=None, wire_dtype=wire_dtype,
               metadata={'caps': [int(c) for c in caps]})

  @classmethod
  def hetero(cls, etypes, fanouts_of, seed_caps, edge_dir,
             etype_caps=None, input_type=None,
             wire_dtype=None) -> 'CapacityPlan':
    """Typed plan over ``hetero_capacity_plan`` — the same kernel the
    engines trace, reified with its inputs. ``fanouts_of`` is either
    the engines' accessor (etype -> per-hop fanouts) or a plain
    per-etype dict."""
    from .neighbor_sampler import hetero_capacity_plan
    if not callable(fanouts_of):
      fans = {tuple(et): [int(k) for k in v]
              for et, v in fanouts_of.items()}
      fanouts_of = lambda et: fans[tuple(et)]  # noqa: E731
    ets = tuple(sorted(tuple(et) for et in etypes))
    ntypes, hop_caps, node_caps = hetero_capacity_plan(
        ets, fanouts_of, dict(seed_caps), edge_dir,
        etype_caps=etype_caps)
    return cls(ntypes=tuple(sorted(ntypes)), etypes=ets,
               edge_dir=edge_dir,
               seed_caps={t: int(v) for t, v in seed_caps.items()},
               hop_caps=tuple(hop_caps),
               node_caps={t: int(v) for t, v in node_caps.items()},
               input_type=input_type, wire_dtype=wire_dtype)

  @classmethod
  def from_sampler(cls, sampler, batch_cap: int, input_type=None,
                   wire_dtype=None) -> 'CapacityPlan':
    """Plan for one sampler + seed batch — hetero when the sampler is,
    else the degenerate homo plan. The one constructor consumers call
    (block producers, tiered stagers, tune probes)."""
    if getattr(sampler, 'is_hetero', False):
      if input_type is None:
        raise CapacityPlanError(
            'CapacityPlan.from_sampler', 'typed seeds carry no '
            'input_type', 'pass input_type (the seed node type)')
      g = sampler.graph
      etypes = list(g.etypes) if hasattr(g, 'etypes') else list(g.keys())
      return cls.hetero(
          etypes, sampler._etype_fanouts, {input_type: int(batch_cap)},
          sampler.edge_dir, etype_caps=sampler.frontier_caps,
          input_type=input_type, wire_dtype=wire_dtype)
    return cls.homo(batch_cap, tuple(sampler.num_neighbors),
                    node_budget=getattr(sampler, 'node_budget', None),
                    frontier_caps=getattr(sampler, 'frontier_caps', None),
                    wire_dtype=wire_dtype)

  # -------------------------------------------------------- engine view

  def engine_plan(self):
    """The raw ``(num_hops, hop_caps, node_caps)`` triple the typed
    engines consume (``_hetero_engine`` / ``_hetero_plan`` shape)."""
    return (self.num_hops,
            [dict(per_et) for per_et in self.hop_caps],
            dict(self.node_caps))


def ack_edge_ids(frame: dict, step: int) -> Optional[np.ndarray]:
  """Chunk-granular LINK ack provenance: the seed edge (src, dst) pairs
  batch ``step`` of a block frame covered — ``None`` on node frames.
  Edge frames carry ``#META.edge_batch`` [k, 2, b] and
  ``#META.edge_batch_size`` [k] (block_producer link frames), so a
  failover replay can account every seed EDGE exactly once, the same
  record node epochs get from ``batch``."""
  if '#META.edge_batch' not in frame:
    return None
  eb = np.asarray(frame['#META.edge_batch'][step])
  n = int(np.asarray(frame['#META.edge_batch_size'][step]).reshape(-1)[0])
  return eb[:, :n]
