"""Random negative edge sampler wrapper.

TPU-native port of
/root/reference/graphlearn_torch/python/sampler/negative_sampler.py: thin
object API over the fixed-shape negative-sampling op (ops/negative.py), with
the edge_dir='in' row/col flip (CSC stores (dst, src) pairs).
"""
from typing import Optional

import numpy as np

from .. import ops
from ..data import Graph


class RandomNegativeSampler:
  """Sample (src, dst) pairs absent from the graph
  (reference: negative_sampler.py:21-57)."""

  def __init__(self, graph: Graph, mode: str = 'binary',
               edge_dir: str = 'out', seed: Optional[int] = None):
    import jax
    self.graph = graph
    self.mode = mode
    self.edge_dir = edge_dir
    # counter-addressed PRNG (never split-and-carry): call N's key is
    # fold_in(base, N), so any stream position is reachable from
    # (base_key, integer) alone — the replay discipline every sampler
    # in this package follows (docs/failure_model.md)
    self._key = jax.random.PRNGKey(0 if seed is None else seed)
    self._call_count = 0
    self._sorted_indices, _ = ops.sort_csr_segments(
        np.asarray(graph.indptr), np.asarray(graph.indices))

  def sample(self, num_samples: int, trials: int = 5,
             padding: bool = False):
    """Returns (rows, cols, mask); with ``padding`` the output is always
    full (non-strict mode, reference random_negative_sampler.cu)."""
    import jax
    g = self.graph
    self._call_count += 1
    sub = jax.random.fold_in(self._key, self._call_count)
    rows, cols, mask = ops.random_negative_sample(
        g.indptr, self._sorted_indices, g.num_nodes, g.num_nodes,
        num_samples, sub, trials=trials, padding=padding)
    if self.edge_dir == 'in':
      rows, cols = cols, rows
    return rows, cols, mask
