"""Sampler input/output dataclasses and the abstract sampler interface.

TPU-native port of /root/reference/graphlearn_torch/python/sampler/base.py.
API surface is kept (NodeSamplerInput, EdgeSamplerInput, NegativeSampling,
SamplerOutput, HeteroSamplerOutput, NeighborOutput, SamplingType,
SamplingConfig, BaseSampler), with one deliberate semantic change: outputs
are **fixed-shape and mask-padded**. The reference's CUDA samplers emit
exact-size tensors (requiring a D2H sync per hop); on TPU exact sizes would
retrigger XLA compilation every batch, so `node`/`row`/`col` are padded to
static capacities and validity is carried in `node_mask`/`edge_mask` plus
traced counts. Conversion to exact-size (PyG-style) arrays happens only at
the host boundary via `.trim()`.
"""
import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..typing import EdgeType, NodeType
from ..utils import CastMixin


class SamplingType(enum.Enum):
  """Reference: sampler/base.py:329-335."""
  NODE = 0
  LINK = 1
  SUBGRAPH = 2
  RANDOM_WALK = 3


@dataclass
class SamplingConfig:
  """Bundle of sampling options (reference: sampler/base.py:338-351)."""
  sampling_type: SamplingType
  num_neighbors: Optional[Union[List[int], Dict[EdgeType, List[int]]]]
  batch_size: int
  shuffle: bool = False
  drop_last: bool = False
  with_edge: bool = False
  collect_features: bool = False
  with_neg: bool = False
  with_weight: bool = False
  edge_dir: str = 'out'
  seed: Optional[int] = None


@dataclass
class NodeSamplerInput(CastMixin):
  """Seed nodes for node-based sampling (reference: sampler/base.py:44-82)."""
  node: np.ndarray
  input_type: Optional[NodeType] = None

  def __len__(self):
    return int(np.asarray(self.node).shape[0])

  def __getitem__(self, index) -> 'NodeSamplerInput':
    return NodeSamplerInput(np.asarray(self.node)[index], self.input_type)

  def share_memory(self):
    return self


@dataclass
class NegativeSampling(CastMixin):
  """Negative sampling config (reference: sampler/base.py:85-145).

  mode: 'binary' (negatives become extra supervision edges with label 0) or
  'triplet' (per-positive dst negatives for margin losses).
  amount: ratio of negatives per positive edge.
  """
  mode: str = 'binary'
  amount: Union[int, float] = 1

  def __post_init__(self):
    if self.mode not in ('binary', 'triplet'):
      raise ValueError(f'unknown negative sampling mode {self.mode!r}')
    if self.amount <= 0:
      raise ValueError('negative sampling amount must be positive')

  def is_binary(self) -> bool:
    return self.mode == 'binary'

  def is_triplet(self) -> bool:
    return self.mode == 'triplet'

  def num_negatives(self, num_pos: int) -> int:
    return int(np.ceil(self.amount * num_pos))


@dataclass
class EdgeSamplerInput(CastMixin):
  """Seed edges for link-based sampling (reference: sampler/base.py:149-204)."""
  row: np.ndarray
  col: np.ndarray
  label: Optional[np.ndarray] = None
  input_type: Optional[EdgeType] = None
  neg_sampling: Optional[NegativeSampling] = None

  def __len__(self):
    return int(np.asarray(self.row).shape[0])

  def __getitem__(self, index) -> 'EdgeSamplerInput':
    return EdgeSamplerInput(
        np.asarray(self.row)[index],
        np.asarray(self.col)[index],
        np.asarray(self.label)[index] if self.label is not None else None,
        self.input_type, self.neg_sampling)

  def share_memory(self):
    return self


@dataclass
class NeighborOutput(CastMixin):
  """One hop's raw sampling result (reference: sampler/base.py:305-326).

  The reference packs (nbrs [sum(nbrs_num)], nbrs_num [B], edges); the
  TPU shape-stable form is dense [B, K] + mask.
  """
  nbrs: Any               # [B, K] neighbor ids (FILL-padded)
  mask: Any               # [B, K] validity
  edges: Optional[Any] = None   # [B, K] global edge ids

  @property
  def nbrs_num(self):
    return self.mask.sum(axis=1)


@dataclass
class SamplerOutput(CastMixin):
  """Multi-hop subgraph sample (reference: sampler/base.py:207-243).

  node: [cap_n] global node ids, position == local index, FILL-padded.
  num_nodes: valid prefix length of `node`.
  row/col: [cap_e] relabeled COO (into `node`), -1 where invalid.
  edge: optional [cap_e] global edge ids.
  edge_mask: [cap_e] validity.
  batch: optional [B] seed ids (link sampling: the per-seed origin).
  num_sampled_nodes/num_sampled_edges: per-hop counts (traced or numpy).
  metadata: extra payloads (edge_label_index, labels, features...).
  """
  node: Any
  num_nodes: Any = None
  row: Any = None
  col: Any = None
  edge: Optional[Any] = None
  edge_mask: Any = None
  batch: Optional[Any] = None
  batch_size: Optional[int] = None
  num_sampled_nodes: Optional[List[Any]] = None
  num_sampled_edges: Optional[List[Any]] = None
  input_type: Optional[Union[NodeType, EdgeType]] = None
  metadata: Dict[str, Any] = field(default_factory=dict)
  device: Any = None

  def trim(self) -> 'SamplerOutput':
    """Host-boundary conversion to exact-size numpy arrays (drops padding).
    Local indices stay valid because padding occupies the tail."""
    node = np.asarray(self.node)
    n = int(self.num_nodes) if self.num_nodes is not None else node.shape[0]
    out = SamplerOutput(node=node[:n], num_nodes=n,
                        input_type=self.input_type,
                        batch_size=self.batch_size, metadata=self.metadata)
    if self.row is not None:
      row = np.asarray(self.row)
      col = np.asarray(self.col)
      mask = (np.asarray(self.edge_mask) if self.edge_mask is not None
              else (row >= 0))
      mask = mask & (row >= 0) & (col >= 0)
      out.row, out.col = row[mask], col[mask]
      if self.edge is not None:
        out.edge = np.asarray(self.edge)[mask]
      out.edge_mask = None
    if self.batch is not None:
      out.batch = np.asarray(self.batch)
    if self.num_sampled_nodes is not None:
      out.num_sampled_nodes = [int(x) for x in self.num_sampled_nodes]
    if self.num_sampled_edges is not None:
      out.num_sampled_edges = [int(x) for x in self.num_sampled_edges]
    return out


@dataclass
class HeteroSamplerOutput(CastMixin):
  """Hetero multi-hop sample (reference: sampler/base.py:245-302):
  per-node-type node buffers and per-edge-type relabeled COO."""
  node: Dict[NodeType, Any]
  num_nodes: Dict[NodeType, Any] = None
  row: Dict[EdgeType, Any] = None
  col: Dict[EdgeType, Any] = None
  edge: Optional[Dict[EdgeType, Any]] = None
  edge_mask: Dict[EdgeType, Any] = None
  batch: Optional[Dict[NodeType, Any]] = None
  batch_size: Optional[int] = None
  num_sampled_nodes: Optional[Dict[NodeType, List[Any]]] = None
  num_sampled_edges: Optional[Dict[EdgeType, List[Any]]] = None
  input_type: Optional[Union[NodeType, EdgeType]] = None
  metadata: Dict[str, Any] = field(default_factory=dict)
  device: Any = None

  def trim(self) -> 'HeteroSamplerOutput':
    node, num_nodes = {}, {}
    for t, buf in self.node.items():
      buf = np.asarray(buf)
      n = (int(self.num_nodes[t]) if self.num_nodes is not None
           else buf.shape[0])
      node[t], num_nodes[t] = buf[:n], n
    out = HeteroSamplerOutput(node=node, num_nodes=num_nodes,
                              input_type=self.input_type,
                              batch_size=self.batch_size,
                              metadata=self.metadata)
    if self.row is not None:
      out.row, out.col, out.edge = {}, {}, ({} if self.edge else None)
      for et, row in self.row.items():
        row = np.asarray(row)
        col = np.asarray(self.col[et])
        mask = (np.asarray(self.edge_mask[et]) if self.edge_mask is not None
                else np.ones_like(row, bool))
        mask = mask & (row >= 0) & (col >= 0)
        out.row[et], out.col[et] = row[mask], col[mask]
        if self.edge is not None and self.edge.get(et) is not None:
          out.edge[et] = np.asarray(self.edge[et])[mask]
      out.edge_mask = None
    if self.batch is not None:
      out.batch = {t: np.asarray(v) for t, v in self.batch.items()}
    return out


class RemoteSamplerInput(CastMixin):
  """Server-resident seed source (reference: sampler/base.py:408-420)."""

  def to_input(self):
    raise NotImplementedError


class RemoteNodePathSamplerInput(RemoteSamplerInput):
  """Seeds loaded from a file path on the server
  (reference: sampler/base.py:423-435)."""

  def __init__(self, node_path: str, input_type: Optional[NodeType] = None):
    self.node_path = node_path
    self.input_type = input_type

  def to_input(self) -> NodeSamplerInput:
    seeds = np.load(self.node_path)
    return NodeSamplerInput(seeds, self.input_type)


class BaseSampler:
  """Abstract sampler (reference: sampler/base.py:354-406)."""

  def sample_from_nodes(self, inputs: NodeSamplerInput, **kwargs):
    raise NotImplementedError

  def sample_from_edges(self, inputs: EdgeSamplerInput, **kwargs):
    raise NotImplementedError

  def subgraph(self, inputs: NodeSamplerInput, **kwargs):
    raise NotImplementedError

  # -- checkpoint/resume (utils.checkpoint; loaders delegate here) ---------

  def state_dict(self):
    """PRNG/iteration state for checkpoint-resume. Default: stateless."""
    return {}

  def load_state_dict(self, state):
    if state:
      raise ValueError(
          f'{type(self).__name__} has no state to restore, but the '
          f'checkpoint carries sampler state {sorted(state)} — it was '
          'written by a different sampler type; resuming would diverge')
