"""Device assignment helpers.

TPU-native port of /root/reference/graphlearn_torch/python/utils/device.py:
the reference rotates sampling workers across CUDA devices; here devices are
jax devices and the default policy is round-robin over local chips.
"""
from typing import Optional, Sequence


def get_available_device(index: int = 0, devices: Optional[Sequence] = None):
  """Round-robin device pick (reference: device.py:22-40)."""
  import jax
  devs = list(devices) if devices is not None else jax.local_devices()
  if not devs:
    return None
  return devs[index % len(devs)]


def ensure_device(device=None):
  """Default device when none given (reference: device.py:42-54)."""
  import jax
  if device is not None:
    return device
  devs = jax.local_devices()
  return devs[0] if devs else None


def global_device_put(arr, sharding):
  """device_put that also works on multi-host meshes.

  On a single-host mesh this is `jax.device_put`. When ``sharding`` spans
  devices this process cannot address (a multi-host mesh from
  dist_context.init_multihost), the array is assembled from the locally
  addressable shards via `make_array_from_callback` — every process passes
  the same full host array (the "each host loads what it serves" model;
  the callback touches only this process's shard slices).
  """
  import jax
  if getattr(sharding, 'is_fully_addressable', True):
    return jax.device_put(arr, sharding)
  import numpy as np
  arr = np.asarray(arr)
  return jax.make_array_from_callback(arr.shape, sharding,
                                      lambda idx: arr[idx])


def enable_compilation_cache(path: Optional[str] = None,
                             min_compile_secs: float = 1.0):
  """Persist XLA executables to disk so repeated process runs warm-start.

  The fused multi-hop sampler compiles in ~60s on TPU the first time; with
  this cache a fresh process (bench run, example, driver re-run) loads the
  binary instead of recompiling. No reference counterpart (CUDA kernels
  are AOT-built wheels); this is the JIT-world equivalent.
  """
  import os
  import jax
  path = path or os.environ.get(
      'GLT_XLA_CACHE', os.path.expanduser('~/.cache/graphlearn_tpu_xla'))
  os.makedirs(path, exist_ok=True)
  jax.config.update('jax_compilation_cache_dir', path)
  jax.config.update('jax_persistent_cache_min_compile_time_secs',
                    min_compile_secs)
  return path
