"""Device assignment helpers.

TPU-native port of /root/reference/graphlearn_torch/python/utils/device.py:
the reference rotates sampling workers across CUDA devices; here devices are
jax devices and the default policy is round-robin over local chips.
"""
from typing import Optional, Sequence


def get_available_device(index: int = 0, devices: Optional[Sequence] = None):
  """Round-robin device pick (reference: device.py:22-40)."""
  import jax
  devs = list(devices) if devices is not None else jax.local_devices()
  if not devs:
    return None
  return devs[index % len(devs)]


def ensure_device(device=None):
  """Default device when none given (reference: device.py:42-54)."""
  import jax
  if device is not None:
    return device
  devs = jax.local_devices()
  return devs[0] if devs else None
