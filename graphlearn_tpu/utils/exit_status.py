"""Interpreter-exit guard for __del__-time cleanup.

Port of /root/reference/graphlearn_torch/python/utils/exit_status.py:19-33:
destructors that talk to channels/processes must not run during interpreter
teardown.
"""
import atexit

_python_exit_status = False


def _set_python_exit():
  global _python_exit_status
  _python_exit_status = True


atexit.register(_set_python_exit)


def python_exit_status() -> bool:
  return _python_exit_status
