"""Profiling/tracing hooks over jax.profiler.

The reference leans on torch.profiler + nvtx ranges in its benchmarks; the
TPU equivalents are XLA's profiler traces (viewable in TensorBoard /
Perfetto). These helpers are no-ops when no trace is active, so loaders
annotate unconditionally.

Usage:
    with glt.utils.profile_trace('/tmp/glt_trace'):
      for batch in loader:   # each batch shows up as a named step
        train_step(batch)

or env-driven: set GLT_PROFILE_DIR and call maybe_start_trace() /
stop_trace() around the region of interest (bench.py honors it).
"""
import contextlib
import functools
import os
from typing import Callable, Iterator, Optional


@contextlib.contextmanager
def profile_trace(logdir: str) -> Iterator[None]:
  """Capture a jax.profiler trace for the enclosed region."""
  import jax
  jax.profiler.start_trace(logdir)
  try:
    yield
  finally:
    jax.profiler.stop_trace()


def annotate(name: str, **kwargs):
  """Named range inside an active trace (no-op otherwise)."""
  import jax
  return jax.profiler.TraceAnnotation(name, **kwargs)


def step_annotation(name: str, step: int):
  """Step-numbered range (loader batches, train steps)."""
  import jax
  return jax.profiler.StepTraceAnnotation(name, step_num=step)


_trace_cache = {}   # (path, mtime) -> parsed events (newest entry only)


def _tpu_trace_events(trace_dir: str):
  """Duration ('X') events on TPU lanes from the NEWEST trace under
  ``trace_dir`` — the shared loader behind device_program_ms /
  device_op_ms (one place owns trace discovery + pid mapping). The
  parsed result is memoized on (path, mtime) so program- and op-level
  views of the same trace parse it once."""
  import glob
  import gzip
  import json
  paths = sorted(glob.glob(trace_dir + '/**/*.trace.json.gz',
                           recursive=True))
  if not paths:
    return []
  key = (paths[-1], os.path.getmtime(paths[-1]))
  if key in _trace_cache:
    return _trace_cache[key]
  with gzip.open(paths[-1]) as f:
    t = json.load(f)
  pids = {}
  for e in t.get('traceEvents', []):
    if e.get('ph') == 'M' and e.get('name') == 'process_name':
      pids[e['pid']] = e['args'].get('name', '')
  events = [e for e in t.get('traceEvents', [])
            if e.get('ph') == 'X' and 'dur' in e and
            'TPU' in pids.get(e.get('pid'), '')]
  _trace_cache.clear()            # keep only the newest trace in memory
  _trace_cache[key] = events
  return events


def device_program_ms(trace_dir: str):
  """Per-program average device ms from the newest trace under
  ``trace_dir``, keyed by jitted program name, TPU lane only — the
  device-trace clock every benchmark uses (PERF.md 'Timing on the axon
  tunnel': wall clocks are untrustworthy on remote-dispatch runtimes).

  Returns {name: (avg_ms, call_count)}.
  """
  import collections
  durs = collections.defaultdict(lambda: [0.0, 0])
  for e in _tpu_trace_events(trace_dir):
    n = e.get('name', '')
    if n.startswith('jit_'):
      d = durs[n]
      d[0] += e['dur']
      d[1] += 1
  return {n: (tot / cnt / 1000.0, cnt) for n, (tot, cnt) in durs.items()}


def device_op_ms(trace_dir: str, top: int = 0, steps: int = 1,
                 strip_ids: bool = True):
  """Per-OP device ms from the newest trace under ``trace_dir`` (TPU
  lanes, non-program events) — the op-level companion of
  device_program_ms for kernel-attribution work (PERF.md byte audits).

  ``steps`` divides totals so units match device_program_ms's per-call
  averages (pass the traced step count). ``strip_ids`` groups op
  instances by XLA name with the trailing ``.NNN`` suffix removed
  (``fusion.123`` -> ``fusion``; bare-digit names like ``layer1`` are
  left intact) for op-class totals; pass False to keep instance names
  (for HLO correlation). Returns {name: (ms, count)}, sorted desc and
  truncated when ``top`` > 0.
  """
  import collections
  import re
  durs = collections.defaultdict(lambda: [0.0, 0])
  suffix = re.compile(r'\.\d+$')
  for e in _tpu_trace_events(trace_dir):
    n = e.get('name', '')
    if n.startswith('jit_'):
      continue
    if strip_ids:
      n = suffix.sub('', n)
    d = durs[n]
    d[0] += e['dur']
    d[1] += 1
  out = {n: (tot / 1000.0 / steps, cnt)
         for n, (tot, cnt) in durs.items()}
  if top:
    out = dict(sorted(out.items(), key=lambda kv: -kv[1][0])[:top])
  return out


# ---------------------------------------------------------------- dispatch
# Dispatch counting: on this rig wall-clock epoch time scales with the
# NUMBER of program dispatches, not device ms (PERF.md 'Timing on the
# axon tunnel'), so the loaders/trainers instrument their dispatch sites
# and tests/bench.py assert & report dispatches/epoch. The counter is a
# host-side convention — every hot-path program launch in this package
# calls record_dispatch() right before dispatching — which makes it
# exact for the instrumented paths and free (one None check) otherwise.


class DispatchCounter:
  """Per-site XLA program launch counts (see count_dispatches)."""

  def __init__(self):
    self.counts = {}

  @property
  def total(self) -> int:
    return sum(self.counts.values())

  def record(self, name: str = 'program'):
    self.counts[name] = self.counts.get(name, 0) + 1

  def subtotal(self, prefix: str) -> int:
    """Dispatches whose site name starts with ``prefix`` — the
    dispatch-budget tests assert per-subsystem slices ('dist_' for the
    distributed hot path) without being brittle to unrelated sites."""
    return sum(v for k, v in self.counts.items() if k.startswith(prefix))

  def __repr__(self):
    return f'DispatchCounter(total={self.total}, counts={self.counts})'


_dispatch_counter: Optional[DispatchCounter] = None


@contextlib.contextmanager
def count_dispatches(propagate: bool = False) -> Iterator[DispatchCounter]:
  """Count instrumented program dispatches in the enclosed region.

  Yields the active DispatchCounter; read ``.total`` / ``.counts`` after
  the block. Nesting restores the outer counter on exit; by default the
  inner region's dispatches are NOT added to the outer count (each
  counter owns its own region), which makes a nested bench region a
  silent blind spot in the outer budget — pass ``propagate=True`` to
  fold the inner region's per-site counts into the enclosing counter on
  exit (a no-op at top level)."""
  global _dispatch_counter
  prev, _dispatch_counter = _dispatch_counter, DispatchCounter()
  try:
    yield _dispatch_counter
  finally:
    inner, _dispatch_counter = _dispatch_counter, prev
    if propagate and prev is not None:
      for name, n in inner.counts.items():
        prev.counts[name] = prev.counts.get(name, 0) + n


def dispatch_snapshot() -> Optional[dict]:
  """Copy of the active count_dispatches region's per-site counts, or
  None when no region is active — the flight recorder's read hook
  (metrics/flight.py diffs two snapshots into per-epoch deltas without
  ever owning the region)."""
  return dict(_dispatch_counter.counts) \
      if _dispatch_counter is not None else None


def record_dispatch(name: str = 'program'):
  """Count one program dispatch under ``name`` (no-op when no
  count_dispatches() region is active). Call at the dispatch SITE, just
  before launching a jitted program — never inside traced code, where it
  would fire once per trace instead of once per call."""
  if _dispatch_counter is not None:
    _dispatch_counter.record(name)


def wrap_dispatch(fn: Callable, name: Optional[str] = None) -> Callable:
  """Counting wrapper for a jitted callable: each call records one
  dispatch under ``name`` (default: the function's name). For code
  outside this package (bench loops, tests) whose dispatch sites the
  built-in instrumentation doesn't cover."""
  label = name or getattr(fn, '__name__', 'program')

  @functools.wraps(fn)
  def wrapper(*args, **kwargs):
    record_dispatch(label)
    return fn(*args, **kwargs)

  return wrapper


# ---------------------------------------------------------------- counters
# Named event counters: the resilience layer (distributed/resilience.py)
# reports degradation events here — retries, failovers, worker restarts,
# injected faults — so a degraded-but-completed epoch is visible without
# log scraping. The distributed feature store publishes its ON-DEVICE
# hit/miss/overflow accumulator here too ('dist_feature.*'), via
# DistFeature.publish_stats() at EPOCH granularity — the counters ride
# the lookup program between publishes, so the hot loop never pays a
# device->host fetch for observability (PERF.md rules).
#
# These four are COMPATIBILITY SHIMS over the typed metric registry
# (graphlearn_tpu/metrics/registry.py, which subsumed the dict that
# used to live here): every call site keeps working, and the counters
# now appear in metrics.snapshot() / scrape_all() / the epoch flight
# recorder alongside gauges and histograms. Thread-safety moved with
# the store (the registry locks every mutation). Lazy import: metrics
# is a sibling package and utils must stay importable first.

_metric_registry = None


def _registry():
  global _metric_registry
  if _metric_registry is None:
    from ..metrics.registry import default_registry
    _metric_registry = default_registry()
  return _metric_registry


def counter_inc(name: str, n: int = 1):
  """Add ``n`` to the named event counter (creating it at 0)."""
  _registry().inc(name, n)


def counter_get(name: str) -> int:
  return _registry().counter_value(name)


def counters(prefix: str = '') -> dict:
  """Snapshot of counters, optionally filtered by name prefix."""
  return _registry().counters(prefix)


def reset_counters(prefix: str = ''):
  """Drop counters matching ``prefix`` (all by default). Shim note:
  this clears COUNTERS only, exactly the old dict semantics — gauges
  and histograms are reset through metrics.reset()."""
  _registry().reset_counters(prefix)


_active = False
_active_dir: Optional[str] = None


def active_profile_dir() -> Optional[str]:
  """The live maybe_start_trace() session's log dir, or None. Spans
  opened while a profiler session is live stamp this key
  (metrics/spans.py ``profile_key``), so device traces and host span
  trees correlate — previously the key only reached flight records."""
  return _active_dir if _active else None


def maybe_start_trace(env_var: str = 'GLT_PROFILE_DIR') -> Optional[str]:
  """Start a trace if ``env_var`` names a directory; returns the dir.

  Exception-safe: a ``start_trace`` that raises (unwritable dir, a
  profiler session another tool left open) must leave ``_active``
  False AND best-effort-close any half-opened profiler session —
  otherwise the next maybe_start_trace either silently no-ops for the
  rest of the run or trips over the orphaned session."""
  global _active, _active_dir
  logdir = os.environ.get(env_var)
  if logdir and not _active:
    import jax
    try:
      jax.profiler.start_trace(logdir)
    except BaseException:
      _active = False
      _active_dir = None
      try:       # close a partially-started session so a later start
        jax.profiler.stop_trace()   # isn't wedged by the orphan
      except Exception:  # noqa: BLE001 - cleanup of a failed start
        pass
      raise
    _active = True
    _active_dir = logdir
    return logdir
  return None


def stop_trace():
  """Stop the maybe_start_trace() session. Exception-safe: ``_active``
  is cleared FIRST — a stop_trace that raises (trace-write failure)
  must not leave the flag stuck True, where every later
  maybe_start_trace would silently no-op and the run would quietly
  produce no traces at all."""
  global _active, _active_dir
  if _active:
    import jax
    _active = False
    _active_dir = None
    jax.profiler.stop_trace()
