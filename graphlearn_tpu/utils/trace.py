"""Profiling/tracing hooks over jax.profiler.

The reference leans on torch.profiler + nvtx ranges in its benchmarks; the
TPU equivalents are XLA's profiler traces (viewable in TensorBoard /
Perfetto). These helpers are no-ops when no trace is active, so loaders
annotate unconditionally.

Usage:
    with glt.utils.profile_trace('/tmp/glt_trace'):
      for batch in loader:   # each batch shows up as a named step
        train_step(batch)

or env-driven: set GLT_PROFILE_DIR and call maybe_start_trace() /
stop_trace() around the region of interest (bench.py honors it).
"""
import contextlib
import os
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_trace(logdir: str) -> Iterator[None]:
  """Capture a jax.profiler trace for the enclosed region."""
  import jax
  jax.profiler.start_trace(logdir)
  try:
    yield
  finally:
    jax.profiler.stop_trace()


def annotate(name: str, **kwargs):
  """Named range inside an active trace (no-op otherwise)."""
  import jax
  return jax.profiler.TraceAnnotation(name, **kwargs)


def step_annotation(name: str, step: int):
  """Step-numbered range (loader batches, train steps)."""
  import jax
  return jax.profiler.StepTraceAnnotation(name, step_num=step)


_active = False


def maybe_start_trace(env_var: str = 'GLT_PROFILE_DIR') -> Optional[str]:
  """Start a trace if ``env_var`` names a directory; returns the dir."""
  global _active
  logdir = os.environ.get(env_var)
  if logdir and not _active:
    import jax
    jax.profiler.start_trace(logdir)
    _active = True
    return logdir
  return None


def stop_trace():
  global _active
  if _active:
    import jax
    jax.profiler.stop_trace()
    _active = False
