"""Profiling/tracing hooks over jax.profiler.

The reference leans on torch.profiler + nvtx ranges in its benchmarks; the
TPU equivalents are XLA's profiler traces (viewable in TensorBoard /
Perfetto). These helpers are no-ops when no trace is active, so loaders
annotate unconditionally.

Usage:
    with glt.utils.profile_trace('/tmp/glt_trace'):
      for batch in loader:   # each batch shows up as a named step
        train_step(batch)

or env-driven: set GLT_PROFILE_DIR and call maybe_start_trace() /
stop_trace() around the region of interest (bench.py honors it).
"""
import contextlib
import os
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_trace(logdir: str) -> Iterator[None]:
  """Capture a jax.profiler trace for the enclosed region."""
  import jax
  jax.profiler.start_trace(logdir)
  try:
    yield
  finally:
    jax.profiler.stop_trace()


def annotate(name: str, **kwargs):
  """Named range inside an active trace (no-op otherwise)."""
  import jax
  return jax.profiler.TraceAnnotation(name, **kwargs)


def step_annotation(name: str, step: int):
  """Step-numbered range (loader batches, train steps)."""
  import jax
  return jax.profiler.StepTraceAnnotation(name, step_num=step)


def device_program_ms(trace_dir: str):
  """Per-program average device ms from the newest trace under
  ``trace_dir``, keyed by jitted program name, TPU lane only — the
  device-trace clock every benchmark uses (PERF.md 'Timing on the axon
  tunnel': wall clocks are untrustworthy on remote-dispatch runtimes).

  Returns {name: (avg_ms, call_count)}.
  """
  import collections
  import glob
  import gzip
  import json
  paths = sorted(glob.glob(trace_dir + '/**/*.trace.json.gz',
                           recursive=True))
  if not paths:
    return {}
  with gzip.open(paths[-1]) as f:
    t = json.load(f)
  pids = {}
  for e in t.get('traceEvents', []):
    if e.get('ph') == 'M' and e.get('name') == 'process_name':
      pids[e['pid']] = e['args'].get('name', '')
  durs = collections.defaultdict(lambda: [0.0, 0])
  for e in t.get('traceEvents', []):
    if e.get('ph') == 'X' and 'dur' in e and \
        'TPU' in pids.get(e.get('pid'), ''):
      n = e.get('name', '')
      if n.startswith('jit_'):
        d = durs[n]
        d[0] += e['dur']
        d[1] += 1
  return {n: (tot / cnt / 1000.0, cnt) for n, (tot, cnt) in durs.items()}


_active = False


def maybe_start_trace(env_var: str = 'GLT_PROFILE_DIR') -> Optional[str]:
  """Start a trace if ``env_var`` names a directory; returns the dir."""
  global _active
  logdir = os.environ.get(env_var)
  if logdir and not _active:
    import jax
    jax.profiler.start_trace(logdir)
    _active = True
    return logdir
  return None


def stop_trace():
  global _active
  if _active:
    import jax
    jax.profiler.stop_trace()
    _active = False
