"""Small tensor helpers shared across the framework.

TPU-native counterpart of /root/reference/graphlearn_torch/python/utils/tensor.py.
"""
from typing import Any, Dict, Optional, Union

import numpy as np


def id2idx(ids: np.ndarray, max_id: Optional[int] = None) -> np.ndarray:
  """Dense inverse map: out[ids[i]] = i (reference: utils/tensor.py:30-39).

  Positions not present in ``ids`` map to 0; callers mask by membership.
  """
  ids = np.asarray(ids)
  if max_id is None:
    max_id = int(ids.max(initial=-1)) + 1
  out = np.zeros(max_id, dtype=np.int64)
  out[ids] = np.arange(ids.shape[0], dtype=np.int64)
  return out


def convert_to_array(data: Any, dtype=None) -> Any:
  """Recursively convert python/list/torch data to numpy arrays."""
  if data is None:
    return None
  if isinstance(data, dict):
    return {k: convert_to_array(v, dtype) for k, v in data.items()}
  if hasattr(data, 'detach'):  # torch.Tensor without importing torch
    data = data.detach().cpu().numpy()
  arr = np.asarray(data)
  if dtype is not None:
    arr = arr.astype(dtype, copy=False)
  return arr


def squeeze_dict(data: Union[Dict, Any]) -> Any:
  """Unwrap single-entry dicts (mirrors reference utils squeeze semantics)."""
  if isinstance(data, dict) and len(data) == 1:
    return next(iter(data.values()))
  return data
