from .checkpoint import CheckpointManager
from .common import (count_dict, get_free_port, load_module,
                     merge_dict)
from .compat import shard_map
from .device import (enable_compilation_cache, ensure_device,
                     get_available_device, global_device_put)
from .exit_status import python_exit_status
from .faults import FaultError, fault_point
from .mixin import CastMixin
from .singleton import Singleton
from .strict import strict_enabled, strict_guards
from .tensor import convert_to_array, id2idx, squeeze_dict
from .topo import (coo_to_csc, coo_to_csr, csr_to_coo, csr_to_csc, ind2ptr,
                   ptr2ind)
from .trace import (DispatchCounter, annotate, count_dispatches,
                    counter_get, counter_inc, counters, device_op_ms,
                    device_program_ms, dispatch_snapshot,
                    maybe_start_trace, profile_trace, record_dispatch,
                    reset_counters, step_annotation, stop_trace,
                    wrap_dispatch)
from .units import format_size, parse_size
