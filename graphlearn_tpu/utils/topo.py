"""Host-side graph layout conversions (COO/CSR/CSC).

TPU-native counterpart of the reference's conversion helpers
(/root/reference/graphlearn_torch/python/utils/topo.py). Pure numpy — graph
construction happens on host; device transfer is owned by data.graph.Graph.
"""
from typing import Optional, Tuple

import numpy as np


def ptr2ind(indptr: np.ndarray) -> np.ndarray:
  """Expand a CSR row-pointer into per-edge row ids."""
  n = indptr.shape[0] - 1
  counts = np.diff(indptr)
  return np.repeat(np.arange(n, dtype=indptr.dtype), counts)


def ind2ptr(rows: np.ndarray, num_rows: int) -> np.ndarray:
  """Build a CSR row-pointer from *sorted* per-edge row ids."""
  counts = np.bincount(rows, minlength=num_rows)
  indptr = np.zeros(num_rows + 1, dtype=np.int64)
  np.cumsum(counts, out=indptr[1:])
  return indptr


def coo_to_csr(
    row: np.ndarray,
    col: np.ndarray,
    num_nodes: Optional[int] = None,
    edge_ids: Optional[np.ndarray] = None,
    edge_weights: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Optional[np.ndarray]]:
  """COO -> CSR. Returns (indptr, indices, edge_ids, edge_weights).

  If ``edge_ids`` is None, it is assigned as the original COO position so that
  edge features/weights indexed by input order remain addressable.
  """
  row = np.asarray(row)
  col = np.asarray(col)
  if num_nodes is None:
    num_nodes = int(max(row.max(initial=-1), col.max(initial=-1))) + 1
  if edge_ids is None:
    edge_ids = np.arange(row.shape[0], dtype=np.int64)
  order = np.argsort(row, kind='stable')
  sorted_row = row[order]
  indices = col[order]
  eids = np.asarray(edge_ids)[order]
  weights = None if edge_weights is None else np.asarray(edge_weights)[order]
  indptr = ind2ptr(sorted_row, num_nodes)
  return indptr, indices, eids, weights


def coo_to_csc(
    row: np.ndarray,
    col: np.ndarray,
    num_nodes: Optional[int] = None,
    edge_ids: Optional[np.ndarray] = None,
    edge_weights: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Optional[np.ndarray]]:
  """COO -> CSC (i.e. CSR over the transposed graph)."""
  return coo_to_csr(col, row, num_nodes, edge_ids, edge_weights)


def csr_to_coo(
    indptr: np.ndarray, indices: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
  return ptr2ind(indptr), indices


def csr_to_csc(
    indptr: np.ndarray,
    indices: np.ndarray,
    edge_ids: Optional[np.ndarray] = None,
    edge_weights: Optional[np.ndarray] = None,
):
  row, col = csr_to_coo(indptr, indices)
  return coo_to_csr(col, row, indptr.shape[0] - 1, edge_ids, edge_weights)
