"""Singleton metaclass (port of
/root/reference/graphlearn_torch/python/utils/singleton.py)."""
import threading


class Singleton(type):
  _instances = {}
  _lock = threading.Lock()

  def __call__(cls, *args, **kwargs):
    if cls not in cls._instances:
      with cls._lock:
        if cls not in cls._instances:
          cls._instances[cls] = super().__call__(*args, **kwargs)
    return cls._instances[cls]
