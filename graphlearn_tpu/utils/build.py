"""On-demand native build of the C++ runtime pieces.

Counterpart of the reference's build utilities
(/root/reference/graphlearn_torch/python/utils/build.py + setup.py): the
reference ships a pybind11 extension; here the native runtime (csrc/) is a
plain shared library compiled with g++ on first use and bound via ctypes
(pybind11 is not available in this image).
"""
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_CSRC = os.path.join(_REPO_ROOT, 'csrc')
_BUILD = os.path.join(_REPO_ROOT, 'build')


def native_lib_path() -> str:
  return os.path.join(_BUILD, 'libglt_c.so')


def build_native(force: bool = False) -> str:
  """Compile csrc/*.cc into build/libglt_c.so (cached by mtime)."""
  srcs = sorted(
      os.path.join(_CSRC, f) for f in os.listdir(_CSRC)
      if f.endswith('.cc'))
  out = native_lib_path()
  if not force and os.path.exists(out):
    newest = max(os.path.getmtime(s) for s in srcs)
    if os.path.getmtime(out) >= newest:
      return out
  os.makedirs(_BUILD, exist_ok=True)
  cmd = ['g++', '-O2', '-fPIC', '-shared', '-std=c++17', '-pthread',
         '-o', out] + srcs
  subprocess.run(cmd, check=True, capture_output=True, text=True)
  return out


def load_native():
  """ctypes handle to the native runtime, building it if needed."""
  global _lib
  with _lock:
    if _lib is None:
      import ctypes
      path = build_native()
      lib = ctypes.CDLL(path)
      lib.shmq_create.restype = ctypes.c_void_p
      lib.shmq_create.argtypes = [ctypes.c_uint64]
      lib.shmq_attach.restype = ctypes.c_void_p
      lib.shmq_attach.argtypes = [ctypes.c_int]
      lib.shmq_id.restype = ctypes.c_int
      lib.shmq_id.argtypes = [ctypes.c_void_p]
      lib.shmq_enqueue.restype = ctypes.c_int
      lib.shmq_enqueue.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_uint64]
      lib.shmq_next_size.restype = ctypes.c_int64
      lib.shmq_next_size.argtypes = [ctypes.c_void_p, ctypes.c_long]
      lib.shmq_dequeue.restype = ctypes.c_int64
      lib.shmq_dequeue.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_uint64, ctypes.c_long]
      lib.shmq_count.restype = ctypes.c_uint64
      lib.shmq_count.argtypes = [ctypes.c_void_p]
      lib.shmq_finish.argtypes = [ctypes.c_void_p]
      lib.shmq_reset_finished.argtypes = [ctypes.c_void_p]
      lib.shmq_close.argtypes = [ctypes.c_void_p]
      _lib = lib
  return _lib
