"""Byte-size string parsing (counterpart of reference utils/units.py)."""

_UNITS = {
    'b': 1,
    'k': 1024, 'kb': 1024,
    'm': 1024 ** 2, 'mb': 1024 ** 2,
    'g': 1024 ** 3, 'gb': 1024 ** 3,
    't': 1024 ** 4, 'tb': 1024 ** 4,
}


def parse_size(size) -> int:
  """Parse '10GB' / '512M' / 1024 into a byte count."""
  if isinstance(size, (int, float)):
    return int(size)
  s = str(size).strip().lower()
  num_end = len(s)
  for i, ch in enumerate(s):
    if not (ch.isdigit() or ch == '.'):
      num_end = i
      break
  num = float(s[:num_end])
  unit = s[num_end:].strip() or 'b'
  if unit not in _UNITS:
    raise ValueError(f'unknown size unit {unit!r} in {size!r}')
  return int(num * _UNITS[unit])


def format_size(num_bytes: int) -> str:
  for unit in ('B', 'KB', 'MB', 'GB', 'TB'):
    if abs(num_bytes) < 1024 or unit == 'TB':
      return f'{num_bytes:.1f}{unit}' if unit != 'B' else f'{num_bytes}B'
    num_bytes /= 1024
  return f'{num_bytes}B'
