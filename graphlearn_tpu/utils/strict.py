"""Strict runtime guard rails for the scanned-epoch hot paths.

``GLT_STRICT=1`` turns the hot-path contracts graftlint checks
statically (graphlearn_tpu/analysis/) into RUNTIME tripwires: the
scanned epoch programs (``loader.ScanTrainer`` /
``loader.DistScanTrainer``) execute under

  * ``jax.transfer_guard('disallow')`` — any IMPLICIT device<->host
    transfer inside the epoch region raises instead of silently
    reintroducing the per-step sync the scan exists to remove
    (PERF.md: on this rig wall clock scales with dispatches + fetches,
    not device ms). Explicit ``jax.device_put`` / ``jax.device_get``
    still work — the epoch boundary uses them deliberately.
  * ``jax.checking_leaks()`` — a traced value escaping its trace
    (captured by a host closure, stored on ``self``) raises at the
    leak, not at some later use.

The guard is scoped to the epoch program region — seed-matrix build,
chunk dispatch loop, metrics concat — NOT the epoch-boundary
bookkeeping (overflow-policy fetch, stats publish), which fetches
per-epoch by design. tests/conftest.py enables strict mode for the
scanned-epoch test modules, so the equivalence suites double as
guard-rail regression tests; see docs/static_analysis.md.
"""
import contextlib
import os

ENV_VAR = 'GLT_STRICT'


def strict_enabled() -> bool:
  """True when GLT_STRICT is set to anything but '' / '0'."""
  return os.environ.get(ENV_VAR, '') not in ('', '0')


@contextlib.contextmanager
def strict_guards():
  """Transfer-guard('disallow') + checking_leaks when GLT_STRICT is on;
  a no-op otherwise (zero overhead in production: one env check at
  entry). Reads the env var per call so tests can toggle it with
  monkeypatch.setenv without re-importing anything."""
  if not strict_enabled():
    yield
    return
  import jax
  with jax.transfer_guard('disallow'), jax.checking_leaks():
    yield
