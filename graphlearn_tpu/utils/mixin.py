"""CastMixin: permissive construction from tuples/dicts (reference utils/mixin.py)."""
from typing import Any


class CastMixin:
  @classmethod
  def cast(cls, *args, **kwargs) -> Any:
    if len(args) == 1 and len(kwargs) == 0:
      elem = args[0]
      if elem is None:
        return None
      if isinstance(elem, CastMixin):
        return elem
      if isinstance(elem, (tuple, list)):
        return cls(*elem)
      if isinstance(elem, dict):
        return cls(**elem)
    return cls(*args, **kwargs)
