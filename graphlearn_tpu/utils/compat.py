"""Version shims over moving jax APIs.

One place resolves symbols whose home changed across the jax versions this
package must run on (the TPU rig's pinned jax vs the 0.4.x CI images), so
call sites never need try/except imports.

``shard_map``: top-level ``jax.shard_map`` exists only on newer jax; on
0.4.x the implementation lives in ``jax.experimental.shard_map``. Both
accept the keyword form used throughout this package
(``shard_map(f, mesh=..., in_specs=..., out_specs=...)``). Resolution is
deferred to the first call so importing this package never forces jax in
(the package-wide convention: jax config keys must stay settable before
first backend use).
"""

_shard_map_impl = None


def _resolve_shard_map():
  global _shard_map_impl
  if _shard_map_impl is None:
    try:
      from jax import shard_map as sm  # jax >= 0.6 top-level export
    except ImportError:
      from jax.experimental.shard_map import shard_map as sm
    _shard_map_impl = sm
  return _shard_map_impl


def shard_map(*args, check_replication=None, **kwargs):
  """jax.shard_map on jax versions that export it, else the
  jax.experimental.shard_map implementation (jax 0.4.x).

  ``check_replication`` (optional bool) resolves to the version's
  replication-check keyword — ``check_vma`` on new jax, ``check_rep``
  on 0.4.x. Programs whose replicated outputs come from collectives
  inside ``lax.scan`` (the scanned-epoch trainers) pass False: the
  static replication checker cannot see through the scan carry, while
  the values are replicated by construction (every shard computes the
  same pmean)."""
  impl = _resolve_shard_map()
  if check_replication is not None:
    import inspect
    params = inspect.signature(impl).parameters
    key = 'check_vma' if 'check_vma' in params else 'check_rep'
    kwargs[key] = check_replication
  return impl(*args, **kwargs)
