"""Dict merge/count helpers used by hetero sampling and loaders.

Counterpart of /root/reference/graphlearn_torch/python/utils/common.py.
"""
import socket
from typing import Dict, List

import numpy as np


def merge_dict(in_dict: Dict, out_dict: Dict[object, List]) -> Dict[object, List]:
  """Append each value of ``in_dict`` onto the list at the same key."""
  for k, v in in_dict.items():
    out_dict.setdefault(k, []).append(v)
  return out_dict


def count_dict(in_dict: Dict, out_dict: Dict[object, List], expand: int) -> Dict:
  """Record per-key cumulative counts, padding absent keys with the last value."""
  for k, vals in out_dict.items():
    while len(vals) < expand - 1:
      vals.append(vals[-1] if vals else 0)
  for k, v in in_dict.items():
    n = int(np.asarray(v).shape[0]) if v is not None else 0
    out_dict.setdefault(k, [0] * (expand - 1))
    out_dict[k].append(n)
  for k, vals in out_dict.items():
    while len(vals) < expand:
      vals.append(vals[-1] if vals else 0)
  return out_dict


def get_free_port(host: str = 'localhost') -> int:
  s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
  try:
    s.bind((host, 0))
    return s.getsockname()[1]
  finally:
    s.close()


def load_module(path, name=None):
  """Import a source FILE as a module object (reference-free analog of
  torch.hub-style script reuse): examples and benchmarks share helpers
  from sibling scripts (e.g. the products gate's draw_class_targets /
  make_synthetic) without packaging example code into the library."""
  import importlib.util
  import os
  name = name or '_glt_mod_' + \
      os.path.splitext(os.path.basename(path))[0]
  spec = importlib.util.spec_from_file_location(name, path)
  mod = importlib.util.module_from_spec(spec)
  spec.loader.exec_module(mod)
  return mod
