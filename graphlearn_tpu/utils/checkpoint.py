"""Checkpoint / resume for training state + loader position (orbax).

The reference has NO checkpointing (SURVEY.md §5: "Checkpoint / resume:
none in the library ... TPU build: add orbax-style checkpoint for parity
with modern expectations"). This module goes beyond the reference:

- ``CheckpointManager.save(step, state, loader=..., extra=...)`` writes
  the train-state pytree (params/opt_state/...) plus a JSON item holding
  the loader's resumable iteration state (``loader.state_dict()`` — the
  shuffle PRNG stream + position within the current epoch + sampler
  PRNG base key/counter: MID-EPOCH granularity, a restore resumes at
  the exact next batch) and any user metadata.
- ``restore(state_template, loader=...)`` loads the newest (or a given)
  step back into arrays shaped like the template and replays the loader
  position, so training continues with the exact permutation sequence it
  would have seen.

Thin wrapper over ``orbax.checkpoint.CheckpointManager`` — step
indexing, retention (``max_to_keep``), and ATOMIC per-step commits
(tmp-dir + rename, so a crash mid-save can never leave a latest-looking
but unrestorable step) are orbax's; this adds only the loader-state JSON
item and numpy-safe serialization.

Works with any pytree state (models.train.TrainState, raw param dicts)
and any loader exposing state_dict/load_state_dict (NodeLoader family,
LinkLoader family, DistLoader family).
"""
from typing import Any, Optional

import numpy as np


def _jsonify(obj):
  """numpy scalars/arrays inside rng state dicts -> JSON-able."""
  if isinstance(obj, dict):
    return {k: _jsonify(v) for k, v in obj.items()}
  if isinstance(obj, (list, tuple)):
    return [_jsonify(v) for v in obj]
  if isinstance(obj, np.ndarray):
    return {'__ndarray__': obj.tolist(), 'dtype': str(obj.dtype)}
  if isinstance(obj, np.generic):
    return obj.item()
  return obj


def _dejsonify(obj):
  if isinstance(obj, dict):
    if '__ndarray__' in obj:
      return np.asarray(obj['__ndarray__'], dtype=obj['dtype'])
    return {k: _dejsonify(v) for k, v in obj.items()}
  if isinstance(obj, list):
    return [_dejsonify(v) for v in obj]
  return obj


class CheckpointManager:
  """Step-indexed checkpoints under one directory (orbax-backed)."""

  def __init__(self, directory: str, max_to_keep: Optional[int] = 3):
    import os
    import orbax.checkpoint as ocp
    self.directory = os.path.abspath(directory)
    self._mgr = ocp.CheckpointManager(
        self.directory,
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))
    self._args = ocp.args

  def save(self, step: int, state: Any, loader=None, extra: Any = None):
    """Write state (+ loader position + extra JSON metadata) at `step`."""
    meta = {'step': int(step), 'extra': _jsonify(extra)}
    if loader is not None:
      meta['loader'] = _jsonify(loader.state_dict())
    self._mgr.save(int(step), args=self._args.Composite(
        state=self._args.StandardSave(state),
        meta=self._args.JsonSave(meta)))
    self._mgr.wait_until_finished()

  def all_steps(self):
    return sorted(self._mgr.all_steps())

  def latest_step(self) -> Optional[int]:
    return self._mgr.latest_step()

  def restore(self, state_template: Any, step: Optional[int] = None,
              loader=None):
    """Load `step` (default: newest). Returns (state, extra); if
    `loader` is given its iteration position is restored in place."""
    if step is None:
      step = self.latest_step()
    if step is None:
      raise FileNotFoundError(f'no checkpoints in {self.directory}')
    out = self._mgr.restore(int(step), args=self._args.Composite(
        state=self._args.StandardRestore(state_template),
        meta=self._args.JsonRestore()))
    meta = out['meta']
    if loader is not None and 'loader' in meta:
      loader.load_state_dict(_dejsonify(meta['loader']))
    return out['state'], _dejsonify(meta.get('extra'))

  def close(self):
    self._mgr.close()
