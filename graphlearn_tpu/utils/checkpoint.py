"""Checkpoint / resume for training state + loader position (orbax).

The reference has NO checkpointing (SURVEY.md §5: "Checkpoint / resume:
none in the library ... TPU build: add orbax-style checkpoint for parity
with modern expectations"). This module goes beyond the reference:

- ``CheckpointManager.save(step, state, loader=..., extra=...)`` writes
  the train-state pytree (params/opt_state/...) via orbax, plus a JSON
  sidecar holding the loader's resumable iteration state
  (``loader.state_dict()`` — the shuffle PRNG stream, epoch-boundary
  granularity) and any user metadata.
- ``restore(state_template, loader=...)`` loads the newest (or a given)
  step back into arrays shaped like the template and replays the loader
  position, so training continues with the exact permutation sequence it
  would have seen.

Works with any pytree state (models.train.TrainState, raw param dicts)
and any loader exposing state_dict/load_state_dict (NodeLoader family,
LinkLoader family, DistLoader family).
"""
import json
import os
from typing import Any, Optional

import numpy as np


def _jsonify(obj):
  """numpy scalars/arrays inside rng state dicts -> JSON-able."""
  if isinstance(obj, dict):
    return {k: _jsonify(v) for k, v in obj.items()}
  if isinstance(obj, (list, tuple)):
    return [_jsonify(v) for v in obj]
  if isinstance(obj, np.ndarray):
    return {'__ndarray__': obj.tolist(), 'dtype': str(obj.dtype)}
  if isinstance(obj, np.generic):
    return obj.item()
  return obj


def _dejsonify(obj):
  if isinstance(obj, dict):
    if '__ndarray__' in obj:
      return np.asarray(obj['__ndarray__'], dtype=obj['dtype'])
    return {k: _dejsonify(v) for k, v in obj.items()}
  if isinstance(obj, list):
    return [_dejsonify(v) for v in obj]
  return obj


class CheckpointManager:
  """Step-indexed checkpoints under one directory.

  Layout: ``{directory}/{step}/state`` (orbax pytree) +
  ``{directory}/{step}/meta.json`` (loader state + extra metadata).
  """

  def __init__(self, directory: str, max_to_keep: Optional[int] = 3):
    self.directory = os.path.abspath(directory)
    os.makedirs(self.directory, exist_ok=True)
    self.max_to_keep = max_to_keep
    import orbax.checkpoint as ocp
    self._ckptr = ocp.StandardCheckpointer()

  # -- save ----------------------------------------------------------------

  def save(self, step: int, state: Any, loader=None, extra: Any = None):
    """Write state (+ loader position + extra JSON metadata) at `step`."""
    path = os.path.join(self.directory, str(int(step)))
    self._ckptr.save(os.path.join(path, 'state'), state)
    self._ckptr.wait_until_finished()
    meta = {'step': int(step), 'extra': extra}
    if loader is not None:
      meta['loader'] = _jsonify(loader.state_dict())
    with open(os.path.join(path, 'meta.json'), 'w') as f:
      json.dump(meta, f)
    self._gc()
    return path

  def _gc(self):
    if self.max_to_keep is None:
      return
    steps = self.all_steps()
    for s in steps[: max(0, len(steps) - self.max_to_keep)]:
      import shutil
      shutil.rmtree(os.path.join(self.directory, str(s)),
                    ignore_errors=True)

  # -- restore -------------------------------------------------------------

  def all_steps(self):
    steps = []
    for name in os.listdir(self.directory):
      full = os.path.join(self.directory, name, 'meta.json')
      if name.isdigit() and os.path.exists(full):
        steps.append(int(name))
    return sorted(steps)

  def latest_step(self) -> Optional[int]:
    steps = self.all_steps()
    return steps[-1] if steps else None

  def restore(self, state_template: Any, step: Optional[int] = None,
              loader=None):
    """Load `step` (default: newest). Returns (state, extra); if
    `loader` is given its iteration position is restored in place."""
    if step is None:
      step = self.latest_step()
    if step is None:
      raise FileNotFoundError(f'no checkpoints in {self.directory}')
    path = os.path.join(self.directory, str(int(step)))
    state = self._ckptr.restore(os.path.join(path, 'state'),
                                state_template)
    with open(os.path.join(path, 'meta.json')) as f:
      meta = json.load(f)
    if loader is not None and 'loader' in meta:
      loader.load_state_dict(_dejsonify(meta['loader']))
    return state, meta.get('extra')
