"""Deterministic fault injection for the distributed sampling path.

Named ``fault_point(name)`` sites are threaded through rpc / channel /
server / producer code. In production every site is a no-op: the fast
path is a single falsy check on the module-level registry, and nothing
else runs (tests/test_resilience.py verifies the disarmed path never
dispatches into the slow handler). In tests a site is armed either
in-process via :func:`arm` / :func:`injected`, or across process
boundaries via the ``GLT_FAULTS`` environment variable, which spawned
subprocesses (sampling workers, server processes) inherit and parse at
import.

``GLT_FAULTS`` grammar (';'-separated specs)::

    name:kind[:key=val[,key=val...]]

    kinds:  raise  — raise FaultError (or ``exc=ConnectionError`` etc.)
            delay  — sleep ``delay`` seconds (default 1.0)
            exit   — os._exit(``code``) (default 1): a hard crash, no
                     cleanup, the closest stand-in for SIGKILL that can
                     be armed from inside the victim
            drop   — fault_point returns 'drop'; the site decides what
                     dropping means (skip a send, discard a frame)

    keys:   times=N — fire at most N times (default: unlimited)
            after=K — skip the first K hits, then start firing (lets a
                      test kill a worker exactly at batch K)
            delay=S, code=N, exc=NAME (builtin exception name)

Example: kill a sampling worker at its 4th batch, once::

    GLT_FAULTS='producer.worker.batch:exit:after=3,times=1,code=17'

Every firing increments the ``fault.<name>`` trace counter
(utils/trace.py), so chaos tests can assert a fault actually fired.
"""
import builtins
import logging
import os
import time
from contextlib import contextmanager
from typing import Dict, Optional

logger = logging.getLogger('graphlearn_tpu.faults')

_ENV_VAR = 'GLT_FAULTS'

# The closed inventory of fault sites. graftlint's fault-point-coverage
# rule cross-checks every ``fault_point('<name>')`` call site against
# this frozenset AND the docs/failure_model.md fault-site table — adding
# a site means registering it here and documenting it there, in the same
# change. Names are '<layer>.<operation>', one name per code site.
REGISTERED_SITES = frozenset({
    'rpc.client.request',
    'rpc.client.response',
    'rpc.server.dispatch',
    'channel.remote.fetch',
    'channel.shm.send',
    'server.create_producer',
    'server.fetch',
    'producer.worker.batch',
    'heartbeat.probe',
    'storage.stage',
    'storage.promote',
    'storage.dist_stage',
    'serving.rotate',
    'remote.block_stage',
    'remote.block_fetch',
    'recovery.save',
    'recovery.restore',
    'recovery.roll_back',
    'tenant.admit',
    'tenant.throttle',
    'tenant.reap',
    'tune.shadow_retune',
})


class FaultError(RuntimeError):
  """Default exception raised by an armed 'raise' fault point."""


class _Fault:
  __slots__ = ('name', 'kind', 'exc', 'times', 'after', 'delay', 'code',
               'hits', 'fired')

  def __init__(self, name: str, kind: str = 'raise',
               exc: type = FaultError, times: Optional[int] = None,
               after: int = 0, delay: float = 1.0, code: int = 1):
    if kind not in ('raise', 'delay', 'exit', 'drop'):
      raise ValueError(f'unknown fault kind {kind!r}')
    self.name, self.kind, self.exc = name, kind, exc
    self.times, self.after = times, after
    self.delay, self.code = delay, code
    self.hits = 0    # site passages while armed
    self.fired = 0   # actual injections


# name -> _Fault. Empty (falsy) when disarmed — fault_point's fast path.
_active: Dict[str, _Fault] = {}


def fault_point(name: str):
  """Marks a named fault site. No-op unless armed; when armed, may
  raise / sleep / hard-exit, or return ``'drop'`` for the site to act
  on. Call sites pay one falsy check when the registry is empty."""
  if not _active:
    return None
  return _fire(name)


def _fire(name: str):
  """Slow path: only reached when at least one fault is armed."""
  f = _active.get(name)
  if f is None:
    return None
  f.hits += 1
  if f.hits <= f.after:
    return None
  if f.times is not None and f.fired >= f.times:
    return None
  f.fired += 1
  from . import trace
  trace.counter_inc(f'fault.{name}')
  if f.kind == 'raise':
    raise f.exc(f'injected fault at {name!r} '
                f'(hit {f.hits}, firing {f.fired})')
  if f.kind == 'delay':
    time.sleep(f.delay)
    return None
  if f.kind == 'exit':
    os._exit(f.code)
  return 'drop'


def arm(name: str, kind: str = 'raise', **kwargs):
  """Arm a fault site in this process (see module docstring for kinds
  and knobs). Re-arming a name replaces its previous fault."""
  _active[name] = _Fault(name, kind, **kwargs)


def disarm(name: Optional[str] = None):
  """Disarm one site, or everything when ``name`` is None."""
  if name is None:
    _active.clear()
  else:
    _active.pop(name, None)


def armed() -> Dict[str, _Fault]:
  """Snapshot of currently armed faults (for assertions)."""
  return dict(_active)


def stats(name: str):
  """(hits, fired) for an armed site — (0, 0) if not armed."""
  f = _active.get(name)
  return (f.hits, f.fired) if f is not None else (0, 0)


@contextmanager
def injected(name: str, kind: str = 'raise', **kwargs):
  """Scoped arm/disarm for tests."""
  arm(name, kind, **kwargs)
  try:
    yield _active[name]
  finally:
    disarm(name)


def env_spec(*specs: str) -> Dict[str, str]:
  """{'GLT_FAULTS': joined spec} — merge into a subprocess env."""
  return {_ENV_VAR: ';'.join(specs)}


def _parse_env(spec: str):
  """Parse a GLT_FAULTS spec into faults, then arm them all. The parse
  happens FIRST: a malformed later item must not leave a partial
  arming behind (raises before any arm)."""
  parsed = []
  for item in spec.split(';'):
    item = item.strip()
    if not item:
      continue
    parts = item.split(':')
    name, kind = parts[0], (parts[1] if len(parts) > 1 else 'raise')
    kwargs = {}
    if len(parts) > 2 and parts[2]:
      for kv in parts[2].split(','):
        k, sep, v = kv.partition('=')
        if not sep:
          raise ValueError(f'GLT_FAULTS: malformed key=val {kv!r}')
        if k in ('times', 'after', 'code'):
          kwargs[k] = int(v)
        elif k == 'delay':
          kwargs[k] = float(v)
        elif k == 'exc':
          exc = getattr(builtins, v, None)
          if not (isinstance(exc, type) and
                  issubclass(exc, BaseException)):
            raise ValueError(f'GLT_FAULTS: unknown exception {v!r}')
          kwargs['exc'] = exc
        else:
          raise ValueError(f'GLT_FAULTS: unknown key {k!r}')
    parsed.append(_Fault(name, kind, **kwargs))   # validates kind
  for f in parsed:
    _active[f.name] = f


def load_env(spec: Optional[str]) -> bool:
  """Arm faults from a GLT_FAULTS-grammar spec, tolerating garbage: a
  malformed value WARNS and arms nothing (observability/chaos tooling
  must never crash the worker import it rides in on — the PR 8
  GLT_SPAN_BUFFER discipline). Returns True when the spec armed."""
  if not spec:
    return False
  try:
    _parse_env(spec)
    return True
  except (ValueError, TypeError) as e:
    logger.warning('%s=%r is malformed (%s) — no faults armed; see the '
                   'grammar in utils/faults.py', _ENV_VAR, spec, e)
    return False


load_env(os.environ.get(_ENV_VAR))
