"""graphlearn_tpu: a TPU-native graph learning framework.

Brand-new JAX/XLA/Pallas re-design with the capabilities of
GraphLearn-for-PyTorch (reference at /root/reference; see SURVEY.md):
accelerator-resident graph sampling, a sharded HBM feature store with
hot-vertex caching, graph partitioning, distributed sampling + feature
collection over ICI/DCN collectives, and PyG-compatible dataset/loader APIs.
"""
import os as _os

# Honor JAX_PLATFORMS even on runtimes whose PJRT plugin registration
# ignores the env var (the axon-tunnel rig): only the
# jax.config.update path reliably selects the backend there, and it
# must run BEFORE first backend use. Without this, subprocesses
# launched with JAX_PLATFORMS=cpu (tests, example smokes) silently
# attach to the accelerator — or hang when it is unreachable.
# NEVER override an EXPLICIT jax.config choice though: a caller that
# ran jax.config.update('jax_platforms', 'cpu') before importing this
# package chose deliberately, and resetting it from the env (= 'axon'
# on the rig) would re-point the next backend init at the tunnel —
# a hang when the relay is down (round-5 bench_dist_loader bug).
if _os.environ.get('JAX_PLATFORMS'):
  try:
    import jax as _jax
    # The axon plugin installs an axon-containing jax_platforms value
    # at interpreter start ('axon,cpu' today, register/pjrt.py), so an
    # unset or axon-containing value means "the tunnel is still the
    # default" — apply the env var (robust to the plugin renaming its
    # default, unlike an exact-string match). Any explicit NON-axon
    # value is a deliberate caller choice (e.g.
    # jax.config.update('jax_platforms', 'cpu') before importing this
    # package) and must never be clobbered back to the tunnel — a hang
    # when the relay is down.
    _cur = _jax.config.jax_platforms
    if _cur is None or 'axon' in _cur:
      _jax.config.update('jax_platforms', _os.environ['JAX_PLATFORMS'])
  except (ImportError, RuntimeError):
    pass   # backend already initialized (config then already applied)

from . import (channel, data, distributed, loader, models, ops, partition,
               sampler, typing, utils)

__version__ = '0.1.0'
