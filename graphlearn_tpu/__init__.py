"""graphlearn_tpu: a TPU-native graph learning framework.

Brand-new JAX/XLA/Pallas re-design with the capabilities of
GraphLearn-for-PyTorch (reference at /root/reference; see SURVEY.md):
accelerator-resident graph sampling, a sharded HBM feature store with
hot-vertex caching, graph partitioning, distributed sampling + feature
collection over ICI/DCN collectives, and PyG-compatible dataset/loader APIs.
"""
import os as _os

# Honor JAX_PLATFORMS even on runtimes whose PJRT plugin registration
# ignores the env var (the axon-tunnel rig): only the
# jax.config.update path reliably selects the backend there, and it
# must run BEFORE first backend use. Without this, subprocesses
# launched with JAX_PLATFORMS=cpu (tests, example smokes) silently
# attach to the accelerator — or hang when it is unreachable.
# NEVER override an EXPLICIT jax.config choice though: a caller that
# ran jax.config.update('jax_platforms', 'cpu') before importing this
# package chose deliberately, and resetting it from the env (= 'axon'
# on the rig) would re-point the next backend init at the tunnel —
# a hang when the relay is down (round-5 bench_dist_loader bug).
if _os.environ.get('JAX_PLATFORMS'):
  try:
    import jax as _jax
    # Apply the env var ONLY over the axon plugin's own installed
    # default (exactly 'axon,cpu' today — register/pjrt.py:86) or an
    # unset config. Anything else is an explicit caller choice —
    # including an explicit 'axon' — and is preserved: clobbering an
    # explicit CPU selection back to the tunnel hangs when the relay
    # is down, and clobbering an explicit axon selection to CPU
    # silently drops the accelerator. If the plugin ever renames its
    # installed default, update the literal below (symptom: env
    # JAX_PLATFORMS stops applying and CPU subprocesses dial the
    # tunnel — conftest's direct jax.config path stays unaffected).
    if _jax.config.jax_platforms in (None, 'axon,cpu'):
      _jax.config.update('jax_platforms', _os.environ['JAX_PLATFORMS'])
  except (ImportError, RuntimeError):
    pass   # backend already initialized (config then already applied)

from . import (channel, data, distributed, loader, metrics, models, ops,
               partition, recovery, sampler, serving, storage, tune,
               typing, utils)
# the epoch executors are the package's training entry points — exported
# at the root alongside their loader-submodule homes. `tune` is the
# one-call autotuner (a CALLABLE subpackage: graphlearn_tpu.tune(ds,
# cfg) emits the fast-path config artifact — docs/tuning.md); RunTrainer
# is the whole-run-as-a-program executor (loader/run_epoch.py).
from .loader import OverlappedTrainer, RunTrainer, ScanTrainer

__version__ = '0.1.0'
