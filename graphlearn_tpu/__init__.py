"""graphlearn_tpu: a TPU-native graph learning framework.

Brand-new JAX/XLA/Pallas re-design with the capabilities of
GraphLearn-for-PyTorch (reference at /root/reference; see SURVEY.md):
accelerator-resident graph sampling, a sharded HBM feature store with
hot-vertex caching, graph partitioning, distributed sampling + feature
collection over ICI/DCN collectives, and PyG-compatible dataset/loader APIs.
"""
from . import (channel, data, distributed, loader, models, ops, partition,
               sampler, typing, utils)

__version__ = '0.1.0'
