"""graphlearn_tpu: a TPU-native graph learning framework.

Brand-new JAX/XLA/Pallas re-design with the capabilities of
GraphLearn-for-PyTorch (reference at /root/reference; see SURVEY.md):
accelerator-resident graph sampling, a sharded HBM feature store with
hot-vertex caching, graph partitioning, distributed sampling + feature
collection over ICI/DCN collectives, and PyG-compatible dataset/loader APIs.
"""
import os as _os

# Honor JAX_PLATFORMS even on runtimes whose PJRT plugin registration
# ignores the env var (the axon-tunnel rig): only the
# jax.config.update path reliably selects the backend there, and it
# must run BEFORE first backend use. Without this, subprocesses
# launched with JAX_PLATFORMS=cpu (tests, example smokes) silently
# attach to the accelerator — or hang when it is unreachable.
if _os.environ.get('JAX_PLATFORMS'):
  try:
    import jax as _jax
    _jax.config.update('jax_platforms', _os.environ['JAX_PLATFORMS'])
  except (ImportError, RuntimeError):
    pass   # backend already initialized (config then already applied)

from . import (channel, data, distributed, loader, models, ops, partition,
               sampler, typing, utils)

__version__ = '0.1.0'
