"""Training utilities: jitted supervised train/eval steps.

The reference leaves the training loop to user code
(/root/reference/examples/train_sage_ogbn_products.py:120-150: DDP +
cross-entropy on the seed slots). Here the step is a single jitted function
over the padded batch: loss is masked cross-entropy on the seed-node slots
(local indices [0, num_seed_nodes)), so the same compiled step serves every
batch of an epoch.
"""
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class TrainState(NamedTuple):
  params: Any
  opt_state: Any
  step: jnp.ndarray


def create_train_state(model, rng, sample_batch, lr: float = 3e-3,
                       optimizer=None):
  params = model.init(rng, sample_batch['x'], sample_batch['edge_index'],
                      sample_batch['edge_mask'])
  tx = optimizer or optax.adam(lr)
  return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32)), tx


def make_forward_fn(model):
  """THE forward definition: ``(params, batch) -> model output`` over
  the flat batch dict (homo arrays or hetero per-type dicts — the model
  owns the signature). Training loss (:func:`make_loss_fn`), evaluation
  (:func:`make_eval_counts`), link prediction, the serving tier's
  full-graph layer materialization and its final-layer refresh
  (graphlearn_tpu/serving/) ALL resolve through this one function, so a
  trained checkpoint and the embeddings served from it can never drift.
  Extra keyword arguments pass through to ``model.apply`` (the layer
  slice below uses this)."""

  def forward(params, batch, **kwargs):
    return model.apply(params, batch['x'], batch['edge_index'],
                       batch['edge_mask'], **kwargs)

  return forward


def make_layer_slice_fn(model, lo: int, hi: int, **fixed):
  """Layer-slice view of :func:`make_forward_fn`: run only conv layers
  ``[lo, hi)`` of the SAME forward definition (``layers=(lo, hi)`` on
  the model call — models supporting it: GraphSAGE/GCN/GAT/RGNN).
  ``fixed`` forwards extra static call kwargs (RGNN's ``embed``/
  ``head``). This is the serving tier's materialization/refresh hook:
  layer l of the offline embedding program and the online final-layer
  refresh are slices of the training forward, not re-implementations."""
  fwd = make_forward_fn(model)

  def slice_fwd(params, batch):
    return fwd(params, batch, layers=(lo, hi), **fixed)

  return slice_fwd


def make_loss_fn(model, num_classes: int):
  """Masked seed-slot cross-entropy ``(params, batch) -> (loss, acc)``
  — ONE definition shared by the local jitted step and the distributed
  per-step/scanned epoch programs (loader/pipeline.py), so the
  scanned-vs-per-step bit-equivalence bar can never drift on the loss.
  Works for homo batches (array x/edge_index/edge_mask) and hetero
  batches (per-type dicts, seed-type logits/y) alike — the model owns
  the signature (the forward resolves through make_forward_fn, the same
  definition the serving tier materializes from)."""
  forward = make_forward_fn(model)

  def loss_fn(params, batch):
    logits = forward(params, batch)
    logits = logits.astype(jnp.float32)  # loss in f32 under bf16 compute
    # seed slots lead both buffers; y may be seed-block-sized
    # (seed_labels_only loaders) or full-buffer-sized — either way only
    # the common prefix carries supervision
    n = min(logits.shape[0], batch['y'].shape[0])
    logits = logits[:n]
    y = batch['y'][:n]
    seed_mask = jnp.arange(n) < batch['num_seed_nodes']
    labels = jax.nn.one_hot(y, num_classes)
    ce = optax.softmax_cross_entropy(logits, labels)
    ce = jnp.where(seed_mask, ce, 0.0)
    loss = ce.sum() / jnp.maximum(seed_mask.sum(), 1)
    correct = (logits.argmax(-1) == y) & seed_mask
    acc = correct.sum() / jnp.maximum(seed_mask.sum(), 1)
    return loss, acc

  return loss_fn


def make_train_step(model, tx, num_classes: int):
  """Build the jitted supervised step. The batch dict carries padded
  x/edge_index/edge_mask/y plus num_seed_nodes (seed slots lead the node
  list by inducer construction)."""

  loss_fn = make_loss_fn(model, num_classes)

  @jax.jit
  def train_step(state: TrainState, batch):
    (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params, batch)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss, acc

  @jax.jit
  def eval_step(state: TrainState, batch):
    return loss_fn(state.params, batch)[1]

  return train_step, eval_step


def make_eval_counts(model):
  """Jitted exact-count evaluation: (params, batch) -> (correct, total)
  over the batch's seed slots. Counts stay on device so epoch-level
  accuracy can be accumulated without host fetches (PERF.md rules) and
  aggregated exactly across uneven batches."""

  forward = make_forward_fn(model)

  @jax.jit
  def eval_counts(params, batch):
    logits = forward(params, batch)
    # common prefix (see make_train_step loss_fn)
    n = min(logits.shape[0], batch['y'].shape[0])
    seed_mask = jnp.arange(n) < batch['num_seed_nodes']
    correct = (logits[:n].argmax(-1) == batch['y'][:n]) & seed_mask
    return correct.sum(), seed_mask.sum()

  return eval_counts


def tree_hop_offsets(batch_cap: int, fanouts, node_budget=None):
  """(hop_node_offsets, hop_edge_offsets) for the layered forward over
  dedup='tree' batches — delegates to the sampler's layout plan so the
  two can never diverge."""
  from ..sampler.neighbor_sampler import tree_layout
  return tree_layout(batch_cap, list(fanouts), node_budget)  # shared plan


def merge_hop_offsets(batch_cap: int, fanouts, node_budget=None,
                      frontier_caps=None):
  """(hop_node_offsets, hop_edge_offsets) for the layered forward over
  exact-dedup ('map'/'sort'/'merge') batches.

  The merge inducer appends each hop's new unique nodes as a contiguous
  block (prefix widths = cumulative clamped frontier caps) and emits
  each hop's edges as a contiguous ``caps[i] * k`` block, so the same
  layer-trimming the tree layout enables applies: layer ``l`` only needs
  the node prefix reachable in ``L - l`` hops and the edge blocks of
  hops ``<= L - l``. Exactness holds because dedup expands every node at
  most once — each target's in-edges live entirely in the single hop
  block that expanded it (equivalence-tested against the full forward).
  Delegates to the sampler's capacity plan so the two can never diverge.
  """
  from ..sampler.neighbor_sampler import (capacity_plan,
                                          merge_layout_from_caps)
  caps = capacity_plan(batch_cap, list(fanouts), node_budget,
                       frontier_caps)
  return merge_layout_from_caps(caps, list(fanouts))


def make_link_train_step(model, tx):
  """Jitted unsupervised/link-prediction step: dot-product scores on the
  batch's ``edge_label_index`` pairs, sigmoid BCE against ``edge_label``
  (1 for positives, 0 for the sampled negatives — the reference's
  unsupervised SAGE objective, examples/graph_sage_unsup_ppi.py loss).
  Pairs with -1 indices (masked negatives / pad seeds) are excluded."""
  forward = make_forward_fn(model)

  def loss_fn(params, batch):
    h = forward(params, batch).astype(jnp.float32)
    eli = batch['edge_label_index']
    lab = batch['edge_label'].astype(jnp.float32)
    valid = (eli[0] >= 0) & (eli[1] >= 0)
    src = h[jnp.maximum(eli[0], 0)]
    dst = h[jnp.maximum(eli[1], 0)]
    score = (src * dst).sum(-1)
    bce = optax.sigmoid_binary_cross_entropy(score, lab)
    bce = jnp.where(valid, bce, 0.0)
    loss = bce.sum() / jnp.maximum(valid.sum(), 1)
    hit = ((score > 0) == (lab > 0.5)) & valid
    acc = hit.sum() / jnp.maximum(valid.sum(), 1)
    return loss, acc

  @jax.jit
  def train_step(state: TrainState, batch):
    (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params, batch)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss, acc

  @jax.jit
  def eval_step(state: TrainState, batch):
    return loss_fn(state.params, batch)[1]

  return train_step, eval_step


def link_batch_to_dict(batch):
  """`loader.Data` from a Link(Neighbor)Loader -> jitted-step dict."""
  return dict(x=batch.x, edge_index=batch.edge_index,
              edge_mask=batch.edge_mask,
              edge_label_index=batch.metadata['edge_label_index'],
              edge_label=batch.metadata['edge_label'])


def batch_to_dict(batch):
  """`loader.Data` -> the flat dict the jitted step consumes."""
  num_seed = (batch.num_sampled_nodes[0]
              if batch.num_sampled_nodes is not None else batch.batch_size)
  return dict(x=batch.x, edge_index=batch.edge_index,
              edge_mask=batch.edge_mask, y=batch.y,
              num_seed_nodes=num_seed)
